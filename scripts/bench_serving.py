"""Closed-loop multi-tenant TPC-H serving bench (exec/scheduler).

The ROADMAP's "millions of users" rung measured: N tenants share ONE
mesh, each running a closed loop over its own TPC-H query mix (a new
query is issued the moment the previous one finishes), multiplexed by
the admission-controlled scheduler — tenants interleave at piece-loop /
shuffle boundaries, the HBM ledger is the admission controller, cold
tenants' packed sources spill under pressure, and every tenant's result
must stay BIT-EQUAL to its solo (single-session) run.

What one run produces (``SERVING_r01.json`` alongside the BENCH_r0x
series):

* per-tenant p50/p99 query latency, queries and rows/s served;
* aggregate rows/s across the mix;
* admission waits (count + seconds) and cross-tenant eviction / spill /
  recovery event counts — was the number achieved on the happy path or
  under managed pressure?
* a ``bit_equal`` verdict: sha256 over every query result vs the solo
  pass (the acceptance criterion; a serving tier that changes answers
  under load is not a serving tier).

The default budget ("auto") is sized to ~2.2 tenants' footprints so a
4-tenant run exercises BOTH acceptance events: later tenants wait at
admission until earlier ones drain, and concurrent packers evict each
other's cold sources through the consensus'd admission path.

``--families`` switches to the SHAPE-FAMILY compile-cost round
(SERVING_r03, docs/serving.md "Compile-cost contract"): N single-
controller tenants whose ingest row counts are near-misses inside ONE
pow2 shape family run the same join+groupby mix, and the facade's
compiled-program count must stay FLAT as the tenant count grows 4×
(tenants 2..N ride tenant 1's executables).  The report carries cold
(first-iteration, compiles included) vs warm p50/p99 and their gap, the
compiled-program trajectory, the ``CYLON_TPU_SHAPE_FAMILIES=0`` contrast
run (per-shape recompiles — the cost the canonicalization removes), and
a ``bit_equal`` verdict of every canonicalized result against its
exact-shape families-off oracle.

Usage::

    python scripts/bench_serving.py                    # 4 tenants
    python scripts/bench_serving.py --tenants 6 --queries 4 \
        --policy fair --budget-mb 24 --out SERVING_rNN.json
    python scripts/bench_serving.py --tenants 64 --smoke --preempt 8 \
        --slo-ms 2000 --out SERVING_r02.json   # preemptive serving round
    python scripts/bench_serving.py --families \
        --out SERVING_r03.json                 # shape-family round

Exit status 0 = completed and bit-equal; 1 otherwise.  A trimmed run is
wired as a slow-marked test (tests/test_scheduler.py).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import struct
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

#: per-tenant query mixes, cycled tenant i -> MIXES[i % len(MIXES)].
#: ``qpipe`` is the pipelined join+sink workload (piece-loop interleave
#: + spillable PieceSource registrations — the tenants that exercise
#: admission pressure); the rest are tpch.py queries (monolithic plans,
#: interleaving at shuffle boundaries).
MIXES = [
    ("qpipe", "q6", "q1"),
    ("qpipe", "q12"),
    ("q3", "q14", "q15"),
    ("q5", "q17"),
    ("q1", "q19", "q11"),
    ("qpipe", "q22"),
]

#: tables each query reads — the rows/s numerator and the footprint
#: estimate's input set
QUERY_TABLES = {
    "q1": ("lineitem",), "q3": ("customer", "orders", "lineitem"),
    "q5": ("customer", "orders", "lineitem", "supplier", "nation",
           "region"),
    "q6": ("lineitem",), "q11": ("partsupp", "supplier", "nation"),
    "q12": ("orders", "lineitem"), "q14": ("lineitem", "part"),
    "q15": ("lineitem", "supplier"), "q17": ("lineitem", "part"),
    "q19": ("lineitem", "part"), "q22": ("customer", "orders"),
    "qpipe": ("orders", "lineitem"),
}


def _result_sha(out) -> str:
    """sha256 over a query result's raw bytes (frames sorted by their
    columns first so row order is canonical).  Deliberately NOT shared
    with chaos_soak's hash helper: that one hashes pre-sorted frames of
    one fixed schema, this one must canonicalize arbitrary query
    outputs (row order, column names, float scalars) — the digests are
    only ever compared within this script."""
    import numpy as np
    h = hashlib.sha256()
    if isinstance(out, float):
        h.update(struct.pack("<d", out))
        return h.hexdigest()
    df = out.to_pandas() if hasattr(out, "to_pandas") else out
    # object-dtype columns (e.g. a groupby max that surfaced through
    # python scalars) hash their POINTER bytes — coerce to concrete
    # dtypes first or the digest is a fresh random per materialization
    df = df.infer_objects()
    df = df.sort_values(list(df.columns)).reset_index(drop=True)
    for col in df.columns:
        h.update(str(col).encode())
        h.update(np.ascontiguousarray(df[col].to_numpy()).tobytes())
    return h.hexdigest()


def _make_qpipe(env, dfs):
    """The pipelined sink workload: orders ⋈ lineitem per order key,
    quantity/price sums — runs through pipelined_join's range loop, so
    the tenant yields per piece and its PieceSource registrations are
    the spillable state the admission controller manages."""
    from cylon_tpu.exec import GroupBySink, pipelined_join

    def qpipe(dfs_, env_=None):
        sink = GroupBySink("l_orderkey", [("l_quantity", "sum"),
                                          ("l_extendedprice", "sum")])
        pipelined_join(dfs_["lineitem"]._table, dfs_["orders"]._table,
                       "l_orderkey", "o_orderkey", how="inner",
                       n_chunks=4, sink=sink)
        return sink.finalize()
    return qpipe


def _tenant_fn(name, mix, queries, dfs, env, qfuncs, record, hist=None,
               on_start=None):
    """Closed loop: cycle the mix for ``queries`` iterations, recording
    (query, latency, sha) into ``record`` as each completes.  ``hist``
    (concurrent pass only) is the tenant's streaming latency histogram
    in the metrics registry (cylon_tpu.obs) — the SLO-attainment
    source, bit-consistent with the sorted-list quantiles by the
    histogram's exact-sample contract.

    The fn RESETS its record and histogram on entry: a preempted tenant
    is requeued and its fn replayed from the top (committed qpipe
    pieces fast-forward), so stale partial observations from the
    drained attempt must not double-count — bit-equality compares the
    LAST full replay against the solo oracle."""
    def fn():
        if on_start is not None:
            on_start()
        record.clear()
        if hist is not None:
            hist.reset()
        for k in range(queries):
            qname = mix[k % len(mix)]
            t0 = time.perf_counter()
            out = qfuncs[qname](dfs, env_=env) if qname == "qpipe" \
                else qfuncs[qname](dfs, env=env)
            if hasattr(out, "to_pandas"):
                out = out.to_pandas()
            lat = time.perf_counter() - t0
            if hist is not None:
                hist.observe(lat)
            record.append({"q": qname, "latency_s": lat,
                           "sha": _result_sha(out)})
        return len(record)
    return fn


def _percentile(xs, p):
    import numpy as np
    # empty -> nan, matching the histogram edge contract
    # (obs/metrics.Histogram.percentile; docs/observability.md)
    return float(np.percentile(np.asarray(xs, float), p)) if xs \
        else float("nan")


def run_serving(tenants: int = 4, queries: int = 4, scale: float = 0.01,
                policy: str = "fair", budget_mb=None, world: int = 4,
                seed: int = 0, slo_ms: float | None = None,
                preempt_tenants: int = 0,
                ckpt_dir: str | None = None) -> dict:
    """Drive the bench in-process and return the report dict (the CLI
    wraps this; tests call it directly with trimmed parameters).
    ``budget_mb``: None = unlimited (no pressure), "auto" = ~2.2 tenant
    footprints (the acceptance configuration), or explicit MiB.
    ``slo_ms``: per-query latency SLO target — each tenant's report
    then carries its attainment fraction from the latency histogram.

    ``preempt_tenants``: hold back the LAST N tenants and submit them
    from inside the first tenant's closed loop at priority 5 — a
    high-priority arrival against an already-running fleet, which is
    the preemptive-scheduling trigger (docs/serving.md).  Requires a
    preemptive policy and ``ckpt_dir`` (victims drain at checkpoint
    boundaries and requeue; without durable stages preemption is
    flag-only best-effort).  ``ckpt_dir`` is armed for the CONCURRENT
    pass only — the solo oracle stays unarmed so the bit-equality
    baseline carries zero checkpoint machinery."""
    import jax
    import cylon_tpu as ct
    from cylon_tpu import config, obs, tpch
    from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
    from cylon_tpu.exec import checkpoint, memory, recovery
    from cylon_tpu.exec.scheduler import (QueryScheduler,
                                          estimate_footprint)

    on_accel = jax.devices()[0].platform != "cpu"
    env = ct.CylonEnv(config=TPUConfig() if on_accel
                      else CPUMeshConfig(world_size=world))
    dfs = tpch.generate_tables(scale=scale, env=env, seed=seed)
    row_counts = {k: int(v._table.row_count) for k, v in dfs.items()}

    qfuncs = {k: getattr(tpch, k) for k in
              {q for mix in MIXES for q in mix} - {"qpipe"}}
    qfuncs["qpipe"] = _make_qpipe(env, dfs)

    plans = []
    for i in range(int(tenants)):
        mix = MIXES[i % len(MIXES)]
        foot = estimate_footprint(
            *[dfs[t] for t in sorted({t for q in mix
                                      for t in QUERY_TABLES[q]})])
        plans.append({"name": f"t{i}", "mix": mix, "footprint": foot})

    # ---- solo pass: the bit-equality oracle -----------------------------
    solo = {}
    for p in plans:
        rec: list = []
        _tenant_fn(p["name"], p["mix"], queries, dfs, env, qfuncs, rec)()
        solo[p["name"]] = rec

    # ---- concurrent pass ------------------------------------------------
    # Budgets under "auto" (the acceptance configuration): the SCHEDULER
    # budget admits the two smallest tenants together and makes the
    # third wait (admission gates on declared footprints); the LEDGER
    # budget is 1.6x one measured qpipe resident peak, so two
    # concurrently packing tenants must evict each other's cold sources
    # through the consensus'd admission path.
    ledger_budget = 0
    if budget_mb == "auto":
        foots = sorted(p["footprint"] for p in plans)
        budget = int(1.05 * (foots[0] + foots[1])) if len(foots) > 2 \
            else int(2.2 * foots[-1])
        memory.reset_stats()
        qfuncs["qpipe"](dfs, env_=env)
        peak = memory.stats()["peak_ledger_bytes"]
        ledger_budget = int(1.6 * peak) if peak else 0
    elif budget_mb is None:
        budget = 0
    else:
        budget = int(float(budget_mb) * (1 << 20))
        ledger_budget = budget
    preempt_tenants = min(int(preempt_tenants), max(tenants - 1, 0))
    if preempt_tenants and policy not in QueryScheduler.PREEMPTIVE_POLICIES:
        raise ValueError(f"preempt_tenants requires a preemptive policy "
                         f"({QueryScheduler.PREEMPTIVE_POLICIES}), "
                         f"got {policy!r}")
    prev_budget = config.HBM_BUDGET_BYTES
    prev_ckpt = os.environ.get("CYLON_TPU_CKPT_DIR")
    memory.reset_stats()
    recovery.reset_events()
    checkpoint.reset_stats()
    checkpoint.reset_stages()
    records: dict[str, list] = {p["name"]: [] for p in plans}
    sched = QueryScheduler(env, policy=policy,
                           budget_bytes=budget or None)
    if ledger_budget:
        # the ledger's own allocation-time admission (PieceSource pack)
        # gates on the config budget
        config.HBM_BUDGET_BYTES = ledger_budget
    obs.metrics.reset("serving_latency")   # fresh histograms per round

    early = plans[:tenants - preempt_tenants]
    late = plans[tenants - preempt_tenants:]

    def _submit(p, priority=0, on_start=None):
        sched.submit(p["name"],
                     _tenant_fn(p["name"], p["mix"], queries, dfs,
                                env, qfuncs, records[p["name"]],
                                hist=obs.histogram(
                                    f"serving_latency_{p['name']}"),
                                on_start=on_start),
                     footprint_bytes=p["footprint"], priority=priority)

    fired = []

    def _submit_late():
        # runs on the first tenant's thread (under the baton); guarded
        # so a requeued replay of that tenant does not resubmit
        if fired or not late:
            return
        fired.append(True)
        for p in late:
            _submit(p, priority=5)

    try:
        if ckpt_dir is not None:
            os.environ["CYLON_TPU_CKPT_DIR"] = ckpt_dir
        for i, p in enumerate(early):
            _submit(p, on_start=_submit_late if i == 0 else None)
        t0 = time.perf_counter()
        sessions = sched.run()
        elapsed = time.perf_counter() - t0
    finally:
        config.HBM_BUDGET_BYTES = prev_budget
        if ckpt_dir is not None:
            if prev_ckpt is None:
                os.environ.pop("CYLON_TPU_CKPT_DIR", None)
            else:
                os.environ["CYLON_TPU_CKPT_DIR"] = prev_ckpt

    # ---- verdicts + metrics ---------------------------------------------
    failures = []
    for s in sessions:
        if s.error is not None:
            failures.append(f"{s.name}: {type(s.error).__name__}: "
                            f"{s.error}")
    bit_equal = True
    for p in plans:
        got = records[p["name"]]
        want = solo[p["name"]]
        if len(got) != len(want) or any(
                g["sha"] != w["sha"] or g["q"] != w["q"]
                for g, w in zip(got, want)):
            bit_equal = False
            failures.append(f"{p['name']}: concurrent results diverged "
                            "from the solo run")

    per_tenant = {}
    total_rows = 0
    for s in sessions:
        rec = records[s.name]
        lats = [r["latency_s"] for r in rec]
        rows = sum(sum(row_counts[t] for t in QUERY_TABLES[r["q"]])
                   for r in rec)
        total_rows += rows
        # SLO quantiles come from the streaming histogram registry
        # (obs.metrics) — the exact-sample contract makes them
        # BIT-CONSISTENT with the sorted-list np.percentile this script
        # used to compute, which the assert pins (acceptance criterion)
        hist = obs.histogram(f"serving_latency_{s.name}")
        p50, p99 = hist.percentile(50), hist.percentile(99)
        def _same(a, b):
            import math
            return a == b or (math.isnan(a) and math.isnan(b))
        assert _same(p50, _percentile(lats, 50)) and \
            _same(p99, _percentile(lats, 99)), \
            (s.name, p50, p99, _percentile(lats, 50), _percentile(lats, 99))
        per_tenant[s.name] = {
            "mix": list(next(p["mix"] for p in plans
                             if p["name"] == s.name)),
            "queries": len(rec),
            # NaN (no completed queries) reports as 0 like the old
            # None did — `or 0` no longer works because NaN is truthy
            "p50_latency_s": 0.0 if p50 != p50 else round(p50, 4),
            "p99_latency_s": 0.0 if p99 != p99 else round(p99, 4),
            **({"slo_target_s": slo_ms / 1e3,
                "slo_attainment": round(
                    hist.attainment(slo_ms / 1e3) or 0.0, 4)}
               if slo_ms is not None else {}),
            **{k: v for k, v in s.summary().items()
               if k not in ("name", "tenant", "state")},
        }

    # recovery events + spill counters through the shared collector
    # (cylon_tpu.obs.bench_detail — same keys the report always carried)
    bd = obs.bench_detail(
        spill_keys=("spill_events", "bytes_spilled", "readmit_events",
                    "cross_session_evictions", "peak_ledger_bytes"),
        ckpt_keys=())
    report = {
        "metric": f"TPC-H SF{scale:g} serving mix, {tenants} tenants "
                  f"x {queries} queries ({policy})",
        "value": round(total_rows / elapsed, 1) if elapsed else 0.0,
        "unit": "rows/s aggregate",
        "vs_baseline": 0.0,
        "detail": {
            "world": env.world_size,
            "platform": jax.devices()[0].platform,
            "scale": scale, "policy": policy,
            "budget_bytes": budget,
            "ledger_budget_bytes": ledger_budget,
            "elapsed_s": round(elapsed, 4),
            "queries_total": sum(len(r) for r in records.values()),
            "queries_per_s": round(
                sum(len(r) for r in records.values()) / elapsed, 3)
            if elapsed else 0.0,
            "bit_equal": bit_equal,
            "failures": failures,
            "scheduler": sched.stats(),
            "spill": {k: v for k, v in bd.items()
                      if k != "recovery_events"},
            "recovery_events": bd["recovery_events"],
            "tenants": per_tenant,
        },
    }
    return report


def run_families(tenants: int = 16, queries: int = 3,
                 family: int = 1024, seed: int = 0) -> dict:
    """The shape-family compile-cost round (docs/serving.md,
    "Compile-cost contract").  ``tenants`` single-controller tenants —
    ingest row counts spread across ONE pow2 family ``(family/2,
    family]`` — each run ``queries`` closed-loop join+groupby queries.
    Phase 1 (families on) measures the compiled-program trajectory:
    after tenant 1, after the first quarter of the fleet, and after the
    full 4× fleet — the contract is FLAT (misses_after_all ==
    misses_after_first).  Cold is each tenant's first iteration (tenant
    1's includes every real compile; later tenants' measure the family
    hit), warm is every subsequent iteration.  Phase 2 re-runs every
    tenant once with ``SHAPE_FAMILIES`` off — the exact-shape oracle for
    ``bit_equal`` AND the per-shape recompile contrast (its miss count
    must GROW with tenant count)."""
    import numpy as np
    import pandas as pd

    import cylon_tpu as ct
    from cylon_tpu import config
    from cylon_tpu.exec import compiler
    from cylon_tpu.relational import groupby_aggregate, join_tables

    env = ct.CylonEnv(config=ct.LocalConfig())
    n_keys = 64

    # distinct near-miss row counts inside one pow2 family: every
    # tenant canonicalizes onto the same padded ingest (and, with
    # unique build keys, the same data-independent join output cap)
    lo, hi = family // 2 + 8, family - 4
    sizes = sorted({int(x) for x in np.linspace(lo, hi, tenants)})
    while len(sizes) < tenants:     # collisions only at tiny counts
        sizes.append(sizes[-1] - 1)
    sizes = sorted(sizes)[:tenants]

    def make_inputs(i: int, n: int):
        r = np.random.default_rng(seed * 7919 + 1000 + i)
        ldf = pd.DataFrame({"k": r.integers(0, n_keys, n).astype(np.int32),
                            "v": r.integers(0, 10_000, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": np.arange(n_keys, dtype=np.int32),
                            "w": r.integers(0, 10_000,
                                            n_keys).astype(np.int64)})
        return ldf, rdf

    def run_query(ldf, rdf):
        lt = ct.Table.from_pandas(ldf, env)
        rt = ct.Table.from_pandas(rdf, env)
        j = join_tables(lt, rt, "k", "k", how="inner")
        out = groupby_aggregate(j, "k", [("v", "sum"), ("w", "max")])
        return out.to_pandas()

    inputs = [make_inputs(i, n) for i, n in enumerate(sizes)]

    # ---- phase 1: families on — the compile-cost trajectory ------------
    prev = config.SHAPE_FAMILIES
    config.SHAPE_FAMILIES = True
    compiler.reset_stats()
    cold, warm, fam_shas = [], [], []
    misses_after_first = misses_after_quarter = 0
    quarter = max(tenants // 4, 1)
    try:
        for i, (ldf, rdf) in enumerate(inputs):
            lats = []
            for it in range(queries):
                t0 = time.perf_counter()
                df = run_query(ldf, rdf)
                lats.append(time.perf_counter() - t0)
                if it == 0:
                    fam_shas.append(_result_sha(df))
            cold.append(lats[0])
            warm.extend(lats[1:])
            if i == 0:
                misses_after_first = compiler.stats()["cache_misses"]
            if i == quarter - 1:
                misses_after_quarter = compiler.stats()["cache_misses"]
        st = compiler.stats()
        misses_after_all = st["cache_misses"]
        programs_live = st["programs_live"]
        family_hits = st["cache_hits"]

        # ---- phase 2: families off — exact-shape oracle + contrast -----
        config.SHAPE_FAMILIES = False
        compiler.reset_stats()
        off_shas, off_first = [], 0
        for i, (ldf, rdf) in enumerate(inputs):
            off_shas.append(_result_sha(run_query(ldf, rdf)))
            if i == 0:
                off_first = compiler.stats()["cache_misses"]
        off_all = compiler.stats()["cache_misses"]
    finally:
        config.SHAPE_FAMILIES = prev

    flat = misses_after_all == misses_after_first
    bit_equal = fam_shas == off_shas
    failures = []
    if not flat:
        failures.append(f"compiled programs grew with tenant count: "
                        f"{misses_after_first} -> {misses_after_all}")
    if not bit_equal:
        bad = [i for i, (a, b) in enumerate(zip(fam_shas, off_shas))
               if a != b]
        failures.append(f"canonicalized results diverged from the "
                        f"exact-shape oracle for tenants {bad}")
    if off_all <= off_first:
        failures.append(f"families-off contrast did not recompile per "
                        f"shape: {off_first} -> {off_all}")

    cold_p50, warm_p50 = _percentile(cold, 50), _percentile(warm, 50)
    return {
        "metric": f"shape-family serving, {tenants} tenants x {queries} "
                  f"queries (single-controller, family {family})",
        "value": misses_after_all,
        "unit": "compiled programs at 4x tenant count",
        "vs_baseline": 0.0,
        "detail": {
            "tenants": tenants, "queries": queries,
            "family": family, "ingest_rows": sizes,
            "compiled_programs": {
                "after_first_tenant": misses_after_first,
                "after_quarter_fleet": misses_after_quarter,
                "after_full_fleet": misses_after_all,
                "flat": flat,
                "programs_live": programs_live,
                "family_cache_hits": family_hits,
            },
            "families_off_contrast": {
                "after_first_tenant": off_first,
                "after_full_fleet": off_all,
                "recompiles_added": off_all - off_first,
            },
            # tenant 1's first iteration is the only TRUE cold query
            # (every real compile happens there); tenants 2.. first
            # iterations measure the family hit — the contract is that
            # they land near warm, nowhere near cold
            "cold_first_tenant_s": round(cold[0], 4),
            "family_first_iters": {
                "p50_s": round(_percentile(cold[1:], 50), 4),
                "p99_s": round(_percentile(cold[1:], 99), 4),
                "n": len(cold) - 1},
            "cold": {"p50_s": round(cold_p50, 4),
                     "p99_s": round(_percentile(cold, 99), 4),
                     "n": len(cold)},
            "warm": {"p50_s": round(warm_p50, 4),
                     "p99_s": round(_percentile(warm, 99), 4),
                     "n": len(warm)},
            "cold_warm_gap": (round(cold[0] / warm_p50, 2)
                              if warm_p50 else 0.0),
            "bit_equal": bit_equal,
            "failures": failures,
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--queries", type=int, default=4,
                    help="closed-loop queries per tenant")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--policy", default="fair",
                    choices=["fifo", "priority", "fair"])
    ap.add_argument("--budget-mb", default="auto",
                    help='"auto" (acceptance pressure), "none", or MiB')
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="per-query latency SLO target (ms): per-tenant "
                         "attainment is reported from the latency "
                         "histogram registry (docs/observability.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="trimmed acceptance smoke: caps queries/tenant "
                         "at 2 and scale at SF0.004 (tenant count is NOT "
                         "trimmed — the slow-lane test runs 64)")
    ap.add_argument("--preempt", type=int, default=0, metavar="N",
                    help="hold back the last N tenants and submit them "
                         "mid-run at priority 5 (forces --policy "
                         "priority and arms --ckpt-dir so victims "
                         "drain at boundaries and requeue)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint root for the concurrent pass "
                         "(default with --preempt: a fresh temp dir)")
    ap.add_argument("--families", action="store_true",
                    help="run the shape-family compile-cost round "
                         "(single-controller: 4x tenant count at a FLAT "
                         "compiled-program count, cold vs warm latency, "
                         "bit-equality vs the SHAPE_FAMILIES=0 exact-"
                         "shape oracle); --tenants defaults to 16 here")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.families:
        tenants = args.tenants if args.tenants != 4 else 16
        report = run_families(tenants=tenants,
                              queries=max(args.queries, 2),
                              seed=args.seed)
        out = args.out or os.path.join(REPO, "SERVING_r03.json")
        with open(out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        d = report["detail"]
        cp = d["compiled_programs"]
        print(f"# {report['metric']}: {report['value']} {report['unit']}")
        print(f"# flat={cp['flat']} "
              f"({cp['after_first_tenant']} -> {cp['after_full_fleet']} "
              f"misses; families-off contrast adds "
              f"{d['families_off_contrast']['recompiles_added']})")
        print(f"# cold={d['cold_first_tenant_s']}s "
              f"warm_p50={d['warm']['p50_s']}s "
              f"gap={d['cold_warm_gap']}x "
              f"bit_equal={d['bit_equal']}")
        print(f"# wrote {out}")
        return 0 if (d["bit_equal"] and cp["flat"]
                     and not d["failures"]) else 1

    args.out = args.out or os.path.join(REPO, "SERVING_r01.json")

    if args.smoke:
        args.queries = min(args.queries, 2)
        args.scale = min(args.scale, 0.004)
    ckpt_dir = args.ckpt_dir
    if args.preempt:
        args.policy = "priority"
        if ckpt_dir is None:
            import tempfile
            ckpt_dir = tempfile.mkdtemp(prefix="cylon_serving_ckpt_")

    budget = None if args.budget_mb in ("none", "0") else args.budget_mb
    report = run_serving(tenants=args.tenants, queries=args.queries,
                         scale=args.scale, policy=args.policy,
                         budget_mb=budget, world=args.world,
                         seed=args.seed, slo_ms=args.slo_ms,
                         preempt_tenants=args.preempt, ckpt_dir=ckpt_dir)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    d = report["detail"]
    print(f"# {report['metric']}: {report['value']} {report['unit']}")
    print(f"# bit_equal={d['bit_equal']} "
          f"admission_waits={d['scheduler']['admission_waits']} "
          f"cross_session_evictions="
          f"{d['spill']['cross_session_evictions']} "
          f"spill_events={d['spill']['spill_events']}")
    print(f"# preemptions={d['scheduler']['preemptions']} "
          f"requeues={d['scheduler']['requeues']} "
          f"outcomes={d['scheduler']['outcomes']}")
    print(f"# wrote {args.out}")
    return 0 if (d["bit_equal"] and not d["failures"]) else 1


if __name__ == "__main__":
    sys.exit(main())
