"""EXPLAIN / ANALYZE plan inspector — print and diff saved plan trees.

Usage:
    python scripts/explain.py PLAN.json            # annotated tree
    python scripts/explain.py A.json B.json        # diff two runs

Accepts either a raw ``QueryPlan.to_dict()`` payload (what
``obs.explain_analyze(...).to_dict()`` serializes) or a bench JSON that
carries one — ``detail.plan`` (bench.py), ``detail.plans.<q>``
(scripts/bench_tpch_q3q5.py: the first query is shown; name one with
``A.json:q5``) or ``detail.q13_plan`` (the tpch driver).

The diff aligns the two trees positionally, flags structural divergence
(a different op or child count means the engine CHOSE a different plan
— route flips, chunk-count changes), and reports per-node deltas of
self seconds, rows and exchanged bytes for structurally matching nodes
— how "the same query got slower" decomposes into "which operator".
Runs whose comm matrix carries the multi-slice TIER split
(cylon_tpu/topo, docs/topology.md) additionally render/diff the
ICI/DCN payload, padded wire and message totals — the flat ↔ two-hop
route comparison instrument.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cylon_tpu.obs.plan import render_tree  # noqa: E402


def load_plan(spec: str) -> dict:
    """Load a plan payload from ``path`` or ``path:query``."""
    path, _, qname = spec.partition(":")
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if "roots" in doc:
        return doc
    det = doc.get("detail", doc)
    if qname:
        plans = det.get("plans", {})
        if qname in plans:
            return plans[qname]
        if f"{qname}_plan" in det:
            return det[f"{qname}_plan"]
        raise SystemExit(f"no plan for query {qname!r} in {path}")
    for key in ("plan", "q13_plan"):
        if key in det:
            return det[key]
    plans = det.get("plans")
    if plans:
        return plans[sorted(plans)[0]]
    raise SystemExit(f"no plan payload found in {path}")


def _flatten(d: dict, path: str = "") -> list[tuple[str, dict]]:
    me = f"{path}/{d['op']}"
    out = [(me, d)]
    for i, c in enumerate(d.get("children", ())):
        out.extend(_flatten(c, f"{me}[{i}]"))
    return out


def _why_skew(path: str, hh: dict | None, plan: dict | None) -> str:
    """The "why this plan" line for a hash↔skew_split route flip
    (docs/skew.md): the heavy-hitter profile's ``est_rows_per_rank``
    names the concentration the CURRENT partitioner would produce —
    the number a split plan's balanced layout is judged against — and
    the voted plan's key count + fan-out says what the split bought."""
    bits = [f"? why: {path}"]
    if hh and hh.get("est_rows_per_rank"):
        per = hh["est_rows_per_rank"]
        tot = sum(per) or 1
        hot_r = max(range(len(per)), key=per.__getitem__)
        even = tot / max(len(per), 1)
        bits.append(f"hash plan would land ≈{per[hot_r]:,} rows "
                    f"({per[hot_r] / tot:.1%}) on rank {hot_r} "
                    f"(even share ≈{even:,.0f})")
    if hh and hh.get("est_max_rank_share") is not None:
        bits.append(f"est_max_rank_share={hh['est_max_rank_share']:.3f}")
    if plan:
        bits.append(f"split plan: {plan.get('keys')} key(s), "
                    f"fanout={plan.get('fanout')}, "
                    f"hash={plan.get('plan_hash')}")
    return "\n    ".join(bits)


def _tier_lines(plan: dict, prefix: str = "") -> list[str]:
    """The comm matrix's ICI/DCN tier split (cylon_tpu/topo — armed
    multi-slice runs embed it at comm_matrix.tiers), rendered as the
    per-tier payload/wire/message summary docs/topology.md reads."""
    t = (plan.get("comm_matrix") or {}).get("tiers")
    if not t:
        return []
    return [f"{prefix}tiers ({t['n_slices']} slices, routes "
            f"{t.get('routes')}):",
            f"{prefix}  ici: rows={t['ici_rows']:,} "
            f"bytes={t['ici_bytes']:,} wire={t['ici_wire_bytes']:,} "
            f"messages={t['ici_messages']:,}",
            f"{prefix}  dcn: rows={t['dcn_rows']:,} "
            f"bytes={t['dcn_bytes']:,} wire={t['dcn_wire_bytes']:,} "
            f"messages={t['dcn_messages']:,}"]


def _diff_tiers(a: dict, b: dict) -> list[str]:
    """Tier-split delta between two runs — how a route change (flat ↔
    two-hop) moved the cross-slice traffic: payload rows are
    route-invariant, so the load-bearing deltas are the DCN message
    count (~1/R under the two-hop route) and the padded wire bytes."""
    ta = (a.get("comm_matrix") or {}).get("tiers")
    tb = (b.get("comm_matrix") or {}).get("tiers")
    if not ta and not tb:
        return []
    if not ta or not tb:
        have = "B" if tb else "A"
        return [f"! comm tier split present only in {have} "
                "(single-slice vs multi-slice topology)"]
    lines = []
    for k, label in (("dcn_messages", "DCN messages"),
                     ("dcn_wire_bytes", "DCN wire bytes"),
                     ("dcn_rows", "DCN payload rows"),
                     ("ici_wire_bytes", "ICI wire bytes")):
        va, vb = ta.get(k, 0), tb.get(k, 0)
        if va != vb:
            ratio = f" ({vb / va:.3f}x)" if va else ""
            lines.append(f"! tier {label}: {va:,} -> {vb:,}{ratio}")
    if ta.get("routes") != tb.get("routes"):
        lines.append(f"! tier routes: {ta.get('routes')} -> "
                     f"{tb.get('routes')}")
    return lines


def diff_plans(a: dict, b: dict) -> str:
    """Human-readable diff of two plan payloads (see module docstring)."""
    fa = [p for r in a.get("roots", ()) for p in _flatten(r)]
    fb = [p for r in b.get("roots", ()) for p in _flatten(r)]
    lines = []
    n = max(len(fa), len(fb))
    for i in range(n):
        if i >= len(fa):
            lines.append(f"+ only in B: {fb[i][0]}")
            continue
        if i >= len(fb):
            lines.append(f"- only in A: {fa[i][0]}")
            continue
        pa, da = fa[i]
        pb, db = fb[i]
        if pa != pb or da["op"] != db["op"]:
            lines.append(f"! structure diverges at #{i}: A={pa} B={pb}")
            continue
        attrs_a, attrs_b = da.get("attrs", {}), db.get("attrs", {})
        for k in sorted(set(attrs_a) | set(attrs_b)):
            if attrs_a.get(k) != attrs_b.get(k):
                lines.append(f"! {pa} attr {k}: "
                             f"{attrs_a.get(k)!r} -> {attrs_b.get(k)!r}")
        route_a, route_b = attrs_a.get("route"), attrs_b.get("route")
        if route_a != route_b and "skew_split" in (route_a, route_b):
            # hash ↔ skew_split flip: explain WHY from the profile of
            # whichever run carries one (analyze-mode key profiles) and
            # from the split side's voted plan summary
            hh = da.get("heavy_hitters") or db.get("heavy_hitters")
            split_attrs = attrs_a if route_a == "skew_split" else attrs_b
            lines.append(_why_skew(pa, hh, split_attrs.get("skew_plan")))
        deltas = []
        for k, fmt in (("self_s", "{:+.4f}s"), ("rows_out", "{:+d}"),
                       ("bytes_exchanged", "{:+d}B")):
            va, vb = da.get(k), db.get(k)
            if va is not None and vb is not None and va != vb:
                deltas.append(f"{k} " + fmt.format(
                    (vb - va) if isinstance(va, (int, float)) else 0))
        if deltas:
            lines.append(f"  {pa}: " + ", ".join(deltas))
    lines.extend(_diff_tiers(a, b))
    ra, rb = a.get("reconcile"), b.get("reconcile")
    if ra and rb:
        lines.append(f"total: {ra['phase_s']}s -> {rb['phase_s']}s "
                     f"({rb['phase_s'] - ra['phase_s']:+.4f}s)")
    return "\n".join(lines) if lines else "plans are identical"


def main(argv: list[str]) -> int:
    if len(argv) not in (2, 3) or argv[1] in ("-h", "--help"):
        print(__doc__)
        return 2
    a = load_plan(argv[1])
    if len(argv) == 2:
        print(render_tree(a))
        for line in _tier_lines(a):
            print(line)
        return 0
    b = load_plan(argv[2])
    print(diff_plans(a, b))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
