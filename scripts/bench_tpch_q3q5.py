"""BASELINE config 4 as written: TPC-H SF10 Q3/Q5 (one chip or CPU mesh).

The full 10-query suite keeps every base table and every query's
intermediates resident, which exceeds one v5e's 16 GB past SF5.  Config 4
names exactly two queries, so this driver ingests only the columns Q3/Q5
touch (the reference's scaling drivers do the same: cylon_scaling.py
materializes just the workload columns) — at SF10 that is ~3 GB of base
tables, leaving HBM for the join intermediates; joins that still exceed
memory fall back to the range-partitioned pipeline automatically
(relational/join.py OOM fallback).

Usage: python scripts/bench_tpch_q3q5.py [scale] [iters]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

Q3_COLS = {
    "customer": ["c_custkey", "c_mktsegment", "c_nationkey"],
    "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"],
    "lineitem": ["l_orderkey", "l_suppkey", "l_extendedprice", "l_discount",
                 "l_shipdate"],
    "supplier": ["s_suppkey", "s_nationkey"],
    "nation": ["n_nationkey", "n_name", "n_regionkey"],
    "region": ["r_regionkey", "r_name"],
}


def main():
    scale = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 2

    import jax
    import cylon_tpu as ct
    from cylon_tpu import obs, tpch
    from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
    from cylon_tpu.exec import checkpoint, memory, recovery

    recovery.reset_events()
    memory.reset_stats()
    checkpoint.reset_stats()

    devs = jax.devices()
    on_accel = devs[0].platform != "cpu"
    env = ct.CylonEnv(config=TPUConfig() if on_accel else CPUMeshConfig())

    pdfs = tpch.generate_pandas(scale=scale)
    dfs = {name: ct.DataFrame(pdfs.pop(name)[cols], env=env)
           for name, cols in Q3_COLS.items()}
    del pdfs

    times = {}
    plans = {}
    for name, fn in (("q3", tpch.q3), ("q5", tpch.q5)):
        def step():
            out = fn(dfs, env=env)
            out.to_pandas()
            return out
        step()  # warmup/compile
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            step()
            ts.append(time.perf_counter() - t0)
        times[name] = min(ts)
        # one extra ANALYZE-profiled run per query: the emitted JSON
        # carries the plan tree (per-node rows/bytes/seconds + the
        # phase-table reconcile block) alongside the wall times
        plans[name] = obs.explain_analyze(step).to_dict()
        print(f"# {name}: {times[name]:.3f}s", flush=True)

    print(json.dumps({
        "metric": f"TPC-H SF{scale:g} Q3+Q5 wall time (BASELINE config 4)",
        "value": round(sum(times.values()), 4),
        "unit": "seconds",
        "detail": {"world": env.world_size, "platform": devs[0].platform,
                   "scale": scale,
                   # recovery + spill + checkpoint counters through the
                   # shared collector (cylon_tpu.obs.bench_detail):
                   # happy path vs post-degradation, resident vs
                   # host-spilled, re-shard vs thrown-away checkpoint
                   **obs.bench_detail(spill_keys=(
                       "spill_events", "bytes_spilled",
                       "peak_ledger_bytes")),
                   # EXPLAIN ANALYZE trees, one per query (obs/plan)
                   "plans": plans,
                   **{f"{n}_s": round(t, 4) for n, t in times.items()}},
    }))


if __name__ == "__main__":
    main()
