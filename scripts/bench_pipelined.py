"""Out-of-HBM-scale pipelined join+groupby on ONE chip.

The monolithic join+groupby OOMs at ~64M rows/chip on v5e (16 GB HBM);
the range-partitioned pipeline (exec/pipeline.py — the reference's
operator-DAG slot) sorts the build side once, tiles the join over key
ranges, and aggregates each piece in a key-disjoint groupby sink — peak
join scratch and output are 1/R-sized.  Measured round 4: 18.6M
rows/s/chip at 96M rows/chip (chunks=6), 17.4M at 125M rows/chip (the
1B-row/v5e-8 per-chip share).  Usage:
python scripts/bench_pipelined.py [rows] [chunks]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import cylon_tpu as ct
from cylon_tpu.exec import pipelined_join
from cylon_tpu.utils.host import sync_pull


def sync(t):
    sync_pull(next(iter(t.columns.values())).data)


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 128_000_000
    chunks = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    w = len(jax.devices())
    unique = 0.9
    rng = np.random.default_rng(42)
    max_val = max(int(rows * unique), 1)
    lt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, rows).astype(np.int64),
         "a": rng.integers(0, max_val, rows).astype(np.int64)})
    rt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, rows).astype(np.int64),
         "b": rng.integers(0, max_val, rows).astype(np.int64)})

    from cylon_tpu.exec import GroupBySink

    def step():
        # per-chunk partial aggregation (the sink releases each join chunk
        # — and each chunk's join+groupby rides the FUSED pushdown since
        # chunk joins defer), then one combine over the partials
        sink = GroupBySink("k", [("a", "sum"), ("b", "sum")])
        pipelined_join(lt, rt, "k", "k", n_chunks=chunks, sink=sink)
        out = sink.finalize()
        sync(out)
        return out

    step()  # compile
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        out = step()
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({
        "metric": "pipelined join+groupby (out-of-HBM scale)",
        "rows_per_chip": rows // w, "world": w, "chunks": chunks,
        "best_iter_s": round(best, 3),
        "rows_per_sec_per_chip": round(2 * rows / best / w, 1),
        "groups": int(out.row_count)}))


if __name__ == "__main__":
    main()
