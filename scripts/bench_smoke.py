#!/usr/bin/env python
"""Tiny-shape smoke run of the pipelined join+groupby dispatch path.

``bench.py``'s north-star configuration (125M rows/chip through the
range-partitioned pipeline with a fused GroupBySink) only runs on
accelerator rigs — a dispatch-path regression there (a phase silently
dropped, the sink no longer engaging, the packed-piece path bailing to
materialize) would otherwise surface first in a slow TPU bench round.
This script runs the SAME code path at <= 64k rows on whatever devices
exist (CPU mesh included), asserts the expected phase markers were
recorded, and checks the streamed result equals the monolithic
join+groupby bit-for-bit on the integer sums.

Usage:
    JAX_PLATFORMS=cpu python scripts/bench_smoke.py [--rows=N]

Exit status 0 and one JSON line on success; wired as a ``slow``-marked
tier-1 test in tests/test_pipeline.py (TestBenchSmoke).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: phase keys the pipelined sink path must record (dispatch markers)
EXPECTED_PHASES = (
    "pipe.build_sort", "pipe.bounds", "pipe.targets", "pipe.probe_sort",
    "pipe.pack", "pipe.piece_join", "pipe.consume",
)


def run_smoke(env=None, rows: int = 65536, n_chunks: int = 4) -> dict:
    """Run the pipelined join+groupby at a tiny shape and verify the
    dispatch path: phase keys present, sink result == monolith.  Returns
    the phase snapshot dict.  Raises AssertionError on any regression."""
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu import config
    from cylon_tpu.exec import GroupBySink, pipelined_join
    from cylon_tpu.relational import groupby_aggregate, join_tables
    from cylon_tpu.utils import timing

    assert rows <= 65536, "smoke stays tiny: <= 64k rows"
    if env is None:
        from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
        import jax
        cfg = TPUConfig() if jax.devices()[0].platform != "cpu" \
            else CPUMeshConfig()
        env = ct.CylonEnv(config=cfg)

    rng = np.random.default_rng(7)
    max_val = max(int(rows * 0.9), 1)
    lt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, rows).astype(np.int64),
         "a": rng.integers(0, 1000, rows).astype(np.int64)}, env)
    rt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, rows).astype(np.int64),
         "b": rng.integers(0, 1000, rows).astype(np.int64)}, env)

    prev_bench, prev_async = config.BENCH_TIMINGS, config.TIMING_ASYNC
    try:
        config.BENCH_TIMINGS = True
        config.TIMING_ASYNC = True      # dispatch-only markers (bench mode)
        timing.reset()
        sink = GroupBySink("k", [("a", "sum"), ("b", "sum")])
        pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=n_chunks,
                       sink=sink)
        got = sink.finalize()
        snap = timing.snapshot()
    finally:
        config.BENCH_TIMINGS = prev_bench
        config.TIMING_ASYNC = prev_async
        timing.reset()

    missing = [p for p in EXPECTED_PHASES if p not in snap]
    assert not missing, f"pipelined phases missing from profile: {missing}"

    mono = groupby_aggregate(join_tables(lt, rt, "k", "k", how="inner"),
                             "k", [("a", "sum"), ("b", "sum")])
    gp = got.to_pandas().sort_values("k").reset_index(drop=True)
    mp = mono.to_pandas().sort_values("k").reset_index(drop=True)
    assert len(gp) == len(mp), (len(gp), len(mp))
    for col in ("k", "a_sum", "b_sum"):
        # integer sums: the streamed decomposition must be EXACT
        assert (gp[col].to_numpy() == mp[col].to_numpy()).all(), col
    return snap


def main() -> int:
    rows = 65536
    for a in sys.argv[1:]:
        if a.startswith("--rows="):
            rows = int(a.split("=", 1)[1])
    snap = run_smoke(rows=rows)
    print(json.dumps({"metric": "pipelined smoke", "rows": rows,
                      "ok": True, "phases_s":
                      {k: v["s"] for k, v in snap.items()}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
