#!/usr/bin/env python
"""Tiny-shape smoke run of the pipelined join+groupby dispatch path.

``bench.py``'s north-star configuration (125M rows/chip through the
range-partitioned pipeline with a fused GroupBySink) only runs on
accelerator rigs — a dispatch-path regression there (a phase silently
dropped, the sink no longer engaging, the packed-piece path bailing to
materialize) would otherwise surface first in a slow TPU bench round.
This script runs the SAME code path at <= 64k rows on whatever devices
exist (CPU mesh included), asserts the expected phase markers were
recorded, and checks the streamed result equals the monolithic
join+groupby bit-for-bit on the integer sums.

Usage:
    JAX_PLATFORMS=cpu python scripts/bench_smoke.py [--rows=N]

Exit status 0 and one JSON line on success; wired as a ``slow``-marked
tier-1 test in tests/test_pipeline.py (TestBenchSmoke).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: phase keys the pipelined sink path must record (dispatch markers)
EXPECTED_PHASES = (
    "pipe.build_sort", "pipe.bounds", "pipe.targets", "pipe.probe_sort",
    "pipe.pack", "pipe.piece_join", "pipe.consume",
)


def run_smoke(env=None, rows: int = 65536, n_chunks: int = 4,
              overlap: bool | None = None, donate: bool | None = None,
              pallas: bool | None = None) -> dict:
    """Run the pipelined join+groupby at a tiny shape and verify the
    dispatch path: phase keys present, sink result == monolith.  Returns
    the phase snapshot dict.  Raises AssertionError on any regression.

    ``overlap``/``donate``/``pallas`` pin the ISSUE-6 dispatch rungs
    (CYLON_TPU_PACKED_OVERLAP / CYLON_TPU_DONATE / CYLON_TPU_PALLAS_PROBE)
    for the run; ``None`` keeps the session config.  With overlap ON the
    pre-loop batched sync marker (``pipe.phase_sync.block``) must appear;
    with the Pallas probe requested, the eligibility gate must actually
    route the kernel (no silent fallback at this tile-aligned shape)."""
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu import config
    from cylon_tpu.exec import GroupBySink, pipelined_join
    from cylon_tpu.ops import pallas_probe
    from cylon_tpu.relational import groupby_aggregate, join_tables
    from cylon_tpu.utils import timing

    assert rows <= 65536, "smoke stays tiny: <= 64k rows"
    if env is None:
        from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
        import jax
        cfg = TPUConfig() if jax.devices()[0].platform != "cpu" \
            else CPUMeshConfig()
        env = ct.CylonEnv(config=cfg)

    rng = np.random.default_rng(7)
    max_val = max(int(rows * 0.9), 1)
    lt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, rows).astype(np.int64),
         "a": rng.integers(0, 1000, rows).astype(np.int64)}, env)
    rt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, rows).astype(np.int64),
         "b": rng.integers(0, 1000, rows).astype(np.int64)}, env)

    prev = (config.BENCH_TIMINGS, config.TIMING_ASYNC,
            config.PACKED_OVERLAP, config.DONATE_BUFFERS,
            config.PALLAS_PROBE)
    probed = []
    orig_supported = pallas_probe.supported

    def spy(cap, n_split, kinds):
        ok = orig_supported(cap, n_split, kinds)
        probed.append(ok)
        return ok

    try:
        config.BENCH_TIMINGS = True
        config.TIMING_ASYNC = True      # dispatch-only markers (bench mode)
        if overlap is not None:
            config.PACKED_OVERLAP = overlap
        if donate is not None:
            config.DONATE_BUFFERS = donate
        if pallas is not None:
            config.PALLAS_PROBE = pallas
            pallas_probe.supported = spy
        timing.reset()
        sink = GroupBySink("k", [("a", "sum"), ("b", "sum")])
        pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=n_chunks,
                       sink=sink)
        got = sink.finalize()
        snap = timing.snapshot()
        overlap_on = config.PACKED_OVERLAP
    finally:
        pallas_probe.supported = orig_supported
        (config.BENCH_TIMINGS, config.TIMING_ASYNC, config.PACKED_OVERLAP,
         config.DONATE_BUFFERS, config.PALLAS_PROBE) = prev
        timing.reset()

    missing = [p for p in EXPECTED_PHASES if p not in snap]
    assert not missing, f"pipelined phases missing from profile: {missing}"
    if overlap_on:
        assert "pipe.phase_sync" + timing.BLOCK_SUFFIX in snap, \
            "overlap on but the pre-loop batched sync marker is missing"
    if pallas:
        assert probed == [True], \
            f"Pallas probe requested but the gate saw {probed}"

    mono = groupby_aggregate(join_tables(lt, rt, "k", "k", how="inner"),
                             "k", [("a", "sum"), ("b", "sum")])
    gp = got.to_pandas().sort_values("k").reset_index(drop=True)
    mp = mono.to_pandas().sort_values("k").reset_index(drop=True)
    assert len(gp) == len(mp), (len(gp), len(mp))
    for col in ("k", "a_sum", "b_sum"):
        # integer sums: the streamed decomposition must be EXACT
        assert (gp[col].to_numpy() == mp[col].to_numpy()).all(), col
    return snap


def main() -> int:
    rows = 65536
    all_rungs = "--all-rungs" in sys.argv
    for a in sys.argv[1:]:
        if a.startswith("--rows="):
            rows = int(a.split("=", 1)[1])
    kw = {"overlap": True, "donate": True, "pallas": True} if all_rungs \
        else {}
    snap = run_smoke(rows=rows, **kw)
    print(json.dumps({"metric": "pipelined smoke", "rows": rows,
                      "ok": True, "all_rungs": all_rungs, "phases_s":
                      {k: v["s"] for k, v in snap.items()}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
