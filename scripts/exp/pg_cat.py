"""Isolate: bf16 sublane concatenate of byte planes in Mosaic."""
import sys
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
import cylon_tpu
from jax.experimental import pallas as pl

L, W = 8, 1024

def kern(x_ref, o_ref):
    w32 = x_ref[...]
    parts = [((w32 >> jnp.uint32(8 * k)) & jnp.uint32(0xFF))
             .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
             for k in range(4)]
    wb = jnp.concatenate(parts, axis=0)        # (4L, W) bf16
    back = [wb[k * L:(k + 1) * L].astype(jnp.float32).astype(jnp.int32)
            .astype(jnp.uint32) for k in range(4)]
    o_ref[...] = (back[0] | back[1] << jnp.uint32(8)
                  | back[2] << jnp.uint32(16) | back[3] << jnp.uint32(24))

rng = np.random.default_rng(1)
x = jnp.asarray(rng.integers(0, 1 << 32, (L, W), dtype=np.uint32))
out = pl.pallas_call(kern, out_shape=jax.ShapeDtypeStruct((L, W), jnp.uint32))(x)
got, exp = np.asarray(out), np.asarray(x)
eq = got == exp
print("concat exact:", bool(eq.all()), "bad:", int((~eq).sum()))
if not eq.all():
    r, c = np.argwhere(~eq)[0]
    print(hex(got[r, c]), "vs", hex(exp[r, c]))
