"""EXPERIMENT (not wired into the package): Pallas streaming multi-scan.

Verdict: measured 1.7x over XLA's co-resident scans (100 ms vs 176 ms
for 4 forward scans at 67M i32; 96 vs 148 ms for the reverse pair) —
XLA already amortizes co-resident scans well (~35 ms/scan), and the
block-scan's shifted-combine relayouts dominate the Pallas version, so
per-scan costs land within ~1.5x of each other.  Not enough to clear
the integration risk on the fused hot path; kept here with the working
grid/SMEM-carry/reverse-scan patterns (mirrored shift directions — no
Mosaic `rev`).  Run scripts/exp/pallas_scan_bench.py for the numbers.

The fused kernel (relational/fused.py) derives its per-position group
geometry from seven full-length scans (cumsum / cummax forward, cummin
reverse).  XLA:TPU runs them at ~0.5 ns/element even co-resident
(measured: 248 ms for the 7-scan block at 67M positions) — each lowers
to its own multi-pass loop.  A sequential-grid Pallas kernel streams the
arrays ONCE: per block, lane scans are log2(128) shifted combines on the
VPU, sublane offsets a tiny axis-0 scan, and the running carry lives in
SMEM across grid steps (TPU grids are sequential).  All forward scans of
the algebra ride ONE pass; the reverse pair rides a second pass with a
REVERSED grid and in-block flips.

Cost: ~2 passes of memory traffic over the operand set vs one XLA loop
per scan — ~5-10x on the boundary block.

Reference slot: this feeds the same geometry the C++ sort-join derives
with per-row comparator loops (sort_join.cpp:66 ``advance()``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: block shape (rows, lanes): 64 KiB of i32 per array per step
BLOCK_R, LANES = 128, 128
_IMAX = np.int32(2**31 - 1)
_IMIN = np.int32(-(2**31 - 1) - 1)

_IDENT = {"sum": np.int32(0), "max": _IMIN, "min": _IMAX}
_COMBINE = {"sum": jnp.add, "max": jnp.maximum, "min": jnp.minimum}


def _block_scan(x, kind: str):
    """Inclusive scan of an (R, LANES) block in LINEAR (row-major) order.
    Returns (scanned block, block total scalar)."""
    comb = _COMBINE[kind]
    ident = _IDENT[kind]
    R = x.shape[0]
    # lane scan within rows: log2(LANES) shifted combines
    k = 1
    while k < LANES:
        shifted = jnp.concatenate(
            [jnp.full((R, k), ident, x.dtype), x[:, :-k]], axis=1)
        x = comb(x, shifted)
        k *= 2
    # row offsets: exclusive scan of row totals down the sublanes.
    # Full-width blocks throughout — narrow (R,1) vectors trip Mosaic's
    # offset-layout concatenate.
    tot = jnp.broadcast_to(x[:, LANES - 1:LANES], (R, LANES))
    off = jnp.concatenate([jnp.full((1, LANES), ident, x.dtype),
                           tot[:-1]], axis=0)
    k = 1
    while k < R:
        shifted = jnp.concatenate(
            [jnp.full((k, LANES), ident, x.dtype), off[:-k]], axis=0)
        off = comb(off, shifted)
        k *= 2
    x = comb(x, off)
    return x, x[R - 1, LANES - 1]


def _block_scan_rev(x, kind: str):
    """Reverse (back-to-front) inclusive scan of an (R, LANES) block in
    linear order — MIRRORED shift directions instead of flips (Mosaic has
    no `rev` lowering): lanes pull from the right, row offsets propagate
    upward from the bottom rows."""
    comb = _COMBINE[kind]
    ident = _IDENT[kind]
    R = x.shape[0]
    k = 1
    while k < LANES:
        shifted = jnp.concatenate(
            [x[:, k:], jnp.full((R, k), ident, x.dtype)], axis=1)
        x = comb(x, shifted)
        k *= 2
    tot = jnp.broadcast_to(x[:, 0:1], (R, LANES))   # reverse row totals
    off = jnp.concatenate([tot[1:],
                           jnp.full((1, LANES), ident, x.dtype)], axis=0)
    k = 1
    while k < R:
        shifted = jnp.concatenate(
            [off[k:], jnp.full((k, LANES), ident, x.dtype)], axis=0)
        off = comb(off, shifted)
        k *= 2
    x = comb(x, off)
    return x, x[0, 0]


def _kernel(*refs, kinds: tuple, reverse: bool):
    n = len(kinds)
    in_refs = refs[:n]
    out_refs = refs[n:2 * n]
    carry = refs[2 * n]                              # SMEM (n,)
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        for i, kind in enumerate(kinds):
            carry[i] = _IDENT[kind]

    scan = _block_scan_rev if reverse else _block_scan
    for i, kind in enumerate(kinds):
        y, tot = scan(in_refs[i][...], kind)
        y = _COMBINE[kind](y, carry[i])
        out_refs[i][...] = y
        carry[i] = _COMBINE[kind](carry[i], tot)


def multi_scan(arrays, kinds, reverse: bool = False,
               interpret: bool | None = None):
    """Inclusive scans of equal-length 1-D int32 arrays in ONE streaming
    pass.  ``kinds[i]`` in {'sum','max','min'}; ``reverse=True`` scans
    back-to-front (the grid walks blocks in reverse and blocks flip
    in-VMEM — no XLA flip passes).  Returns a tuple of scanned arrays."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n0 = arrays[0].shape[0]
    blk = BLOCK_R * LANES
    npad = -(-n0 // blk) * blk
    G = npad // blk
    kinds = tuple(kinds)
    ins = []
    for a, kind in zip(arrays, kinds):
        a = a.astype(jnp.int32)
        if npad != n0:
            a = jnp.concatenate(
                [a, jnp.full(npad - n0, _IDENT[kind], jnp.int32)])
        ins.append(a.reshape(G * BLOCK_R, LANES))

    if reverse:
        def imap(j):
            return (G - 1 - j, jnp.int32(0))
    else:
        def imap(j):
            return (j, jnp.int32(0))

    spec = pl.BlockSpec((BLOCK_R, LANES), imap)
    # under shard_map (check_vma) outputs must declare their mesh axes
    vma = frozenset()
    for a in ins:
        vma = vma | getattr(a.aval, "vma", frozenset())
    outs = pl.pallas_call(
        partial(_kernel, kinds=kinds, reverse=reverse),
        grid=(G,),
        in_specs=[spec] * len(ins),
        out_specs=[spec] * len(ins),
        out_shape=[jax.ShapeDtypeStruct((G * BLOCK_R, LANES), jnp.int32,
                                        vma=vma)
                   for _ in ins],
        scratch_shapes=[pltpu.SMEM((len(ins),), jnp.int32)],
        interpret=interpret,
    )(*ins)
    res = []
    for o in outs:
        o = o.reshape(npad)
        res.append(o[:n0] if npad != n0 else o)
    return tuple(res)
