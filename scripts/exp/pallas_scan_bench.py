"""Bench for the pallas_scan experiment (see its docstring verdict)."""
import sys, time, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
import cylon_tpu
import pallas_scan as ps

_pull = jax.jit(lambda x: x.reshape(-1)[:2].astype(jnp.float32).sum())
def sync(out): np.asarray(_pull(jax.tree.leaves(out)[0]))
def timed(label, fn, *args):
    f = jax.jit(fn); sync(f(*args)); best = 1e9
    for _ in range(3):
        t0 = time.perf_counter(); sync(f(*args)); best = min(best, time.perf_counter()-t0)
    print(f"{label:44s} {best*1e3:8.1f} ms")

N = 67_108_864
rng = np.random.default_rng(0)
arrs = [jnp.asarray(rng.integers(0, 3, N, dtype=np.int32)) for _ in range(4)]
timed("pallas 4 fwd (sum,sum,max,max)",
      lambda *xs: ps.multi_scan(list(xs), ["sum", "sum", "max", "max"]), *arrs)
timed("pallas 2 rev (min,min)",
      lambda *xs: ps.multi_scan(list(xs), ["min", "min"], reverse=True),
      *arrs[:2])
timed("XLA 4 fwd", lambda a, b, c, d: (jnp.cumsum(a), jnp.cumsum(b),
      jax.lax.cummax(c), jax.lax.cummax(d)), *arrs)
timed("XLA 2 rev", lambda a, b: (jax.lax.cummin(a, reverse=True),
      jax.lax.cummin(b, reverse=True)), *arrs[:2])
