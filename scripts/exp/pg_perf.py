"""Find the per-step overhead: strip kernel stages at full scale."""
import sys
sys.path.insert(0, "/root/repo")
from functools import partial
import time
import jax, jax.numpy as jnp, numpy as np
import cylon_tpu
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MODE = sys.argv[1]
TILE = int(sys.argv[2]) if len(sys.argv) > 2 else 256
W = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
L = 8
N = 64_000_000; SEG = 33_554_432

_pull = jax.jit(lambda x: x.reshape(-1)[:2].astype(jnp.float32).sum())
def sync(out): np.asarray(_pull(jax.tree.leaves(out)[0]))

def kern(ws_ref, idx_ref, mat_ref, out_ref, win_ref, wb_ref, sem_ref):
    j = pl.program_id(0)
    nt = pl.num_programs(0)
    def dma(slot, t):
        slot = jnp.asarray(slot, jnp.int32)
        start = pl.multiple_of(ws_ref[t], 128)
        return pltpu.make_async_copy(
            mat_ref.at[:, pl.ds(start, W)],
            win_ref.at[slot], sem_ref.at[slot])
    if MODE != "nodma":
        @pl.when(j == 0)
        def _():
            dma(0, jnp.int32(0)).start()
        @pl.when(j + 1 < nt)
        def _():
            dma(jax.lax.rem(j + 1, jnp.int32(2)), j + 1).start()
        slot = jax.lax.rem(j, jnp.int32(2))
        dma(slot, j).wait()
    else:
        slot = jnp.int32(0)
    if MODE in ("full", "nohot"):
        w32 = win_ref[slot]
        for k in range(4):
            wb_ref[pl.ds(k * L, L), :] = ((w32 >> jnp.uint32(8 * k))
                                          & jnp.uint32(0xFF)) \
                .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
    if MODE == "full":
        lidx = idx_ref[0] - ws_ref[j]
        iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, W), 2)
        oh = (iota == lidx[:, :, None]).astype(jnp.bfloat16)
        oh = oh.reshape(TILE, W)
    elif MODE == "nohot":
        oh = jnp.zeros((TILE, W), jnp.bfloat16)
    if MODE in ("full", "nohot"):
        accT = jax.lax.dot_general(wb_ref[...], oh, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        u = accT.astype(jnp.int32).astype(jnp.uint32)
        out_ref[...] = (u[0:L] | u[L:2*L] << jnp.uint32(8)
                        | u[2*L:3*L] << jnp.uint32(16)
                        | u[3*L:4*L] << jnp.uint32(24))
    else:
        out_ref[...] = jnp.zeros((L, TILE), jnp.uint32)

rng = np.random.default_rng(0)
sn = np.sort(rng.choice(N, 29_000_000, replace=False)).astype(np.int32)
idx = np.full(SEG, N, np.int32); idx[:len(sn)] = sn
idx = jnp.asarray(idx)
mat_t = jnp.asarray(rng.integers(0, 1 << 32, (L, N + 128), dtype=np.uint32))
G = SEG // TILE
heads = idx[::TILE]
ws = jnp.minimum((heads // 128) * 128, jnp.int32(((N + 128 - W) // 128) * 128))
idx2 = idx.reshape(G, 8, TILE // 8)

def run(ws, idx2, mat_t):
    return pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(G,),
            in_specs=[pl.BlockSpec((1, 8, TILE // 8),
                                   lambda j, ws: (j, jnp.int32(0), jnp.int32(0))),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((L, TILE), lambda j, ws: (jnp.int32(0), j)),
            scratch_shapes=[pltpu.VMEM((2, L, W), jnp.uint32),
                            pltpu.VMEM((4 * L, W), jnp.bfloat16),
                            pltpu.SemaphoreType.DMA((2,))]),
        out_shape=jax.ShapeDtypeStruct((L, SEG), jnp.uint32),
    )(ws, idx2, mat_t)

f = jax.jit(run)
sync(f(ws, idx2, mat_t))
best = 1e9
for _ in range(3):
    t0 = time.perf_counter(); sync(f(ws, idx2, mat_t)); best = min(best, time.perf_counter() - t0)
print(f"{MODE} TILE={TILE} W={W}: {best*1e3:.1f} ms")
