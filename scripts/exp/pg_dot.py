"""Isolate dot_general corruption: select columns of B via one-hot A."""
import sys
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
import cylon_tpu
from jax.experimental import pallas as pl

MODE = sys.argv[1]
TILE, W, R = 256, 1024, 32
rng = np.random.default_rng(2)
bn = rng.integers(0, 256, (R, W)).astype(np.float32)   # like u8 planes
idxn = np.sort(rng.choice(W, TILE, replace=False)).astype(np.int32)
ohn = np.zeros((TILE, W), np.float32); ohn[np.arange(TILE), idxn] = 1.0

def kern_t(a_ref, b_ref, o_ref):   # contract dim1 of both (A @ B^T)
    o_ref[...] = jax.lax.dot_general(a_ref[...], b_ref[...],
                                     (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)

def kern_n(a_ref, b_ref, o_ref):   # standard A @ B
    o_ref[...] = jax.lax.dot_general(a_ref[...], b_ref[...],
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

if MODE == "t":
    out = pl.pallas_call(kern_t,
        out_shape=jax.ShapeDtypeStruct((TILE, R), jnp.float32))(
        jnp.asarray(ohn), jnp.asarray(bn))
    exp = bn.T[idxn]
elif MODE == "n":
    out = pl.pallas_call(kern_n,
        out_shape=jax.ShapeDtypeStruct((TILE, R), jnp.float32))(
        jnp.asarray(ohn), jnp.asarray(bn.T.copy()))
    exp = bn.T[idxn]
elif MODE == "tbig":
    bn2 = rng.integers(0, 65536, (R, W)).astype(np.float32)
    out = pl.pallas_call(kern_t,
        out_shape=jax.ShapeDtypeStruct((TILE, R), jnp.float32))(
        jnp.asarray(ohn), jnp.asarray(bn2))
    exp = bn2.T[idxn]
got = np.asarray(out)
eq = got == exp
print(MODE, "exact:", bool(eq.all()), "bad:", int((~eq).sum()))
if not eq.all():
    bi = np.argwhere(~eq)[:4]
    for r, c in bi:
        print("row", r, "col", c, "got", got[r, c], "exp", exp[r, c])
