"""Bisect Mosaic legalization failure in the windowed gather kernel."""
import sys
from functools import partial
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
import cylon_tpu  # x64 on
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 256
STAGE = int(sys.argv[1]) if len(sys.argv) > 1 else 1
FULL = {5, 6}

def kern(ws_ref, idx_ref, mat_ref, out_ref, win_ref, sem_ref, *, window, L):
    j = pl.program_id(0)
    nt = pl.num_programs(0)
    def dma(slot, t):
        slot = jnp.asarray(slot, jnp.int32)
        start = pl.multiple_of(ws_ref[t], 128)
        return pltpu.make_async_copy(
            mat_ref.at[:, pl.ds(start, window)],
            win_ref.at[slot], sem_ref.at[slot])
    if STAGE == 0:
        pass
    elif STAGE >= 2 and STAGE != 6:
        @pl.when(j == 0)
        def _():
            dma(0, jnp.int32(0)).start()
        @pl.when(j + 1 < nt)
        def _():
            dma(jax.lax.rem(j + 1, jnp.int32(2)), j + 1).start()
        slot = jax.lax.rem(j, jnp.int32(2))
        dma(slot, j).wait()
    else:
        slot = jnp.int32(0)
        d = dma(slot, j)
        d.start(); d.wait()
    if STAGE == 0:
        slot = jnp.int32(0)
    if STAGE >= 3 or STAGE in FULL:
        lidx = idx_ref[0] - ws_ref[j]
        iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, window), 2)
        oh = (iota == lidx[:, :, None]).astype(jnp.bfloat16)
        oh = oh.reshape(TILE, window)
    if STAGE >= 4 or STAGE in FULL:
        w32 = win_ref[slot]
        parts = [((w32 >> jnp.uint32(8 * k)) & jnp.uint32(0xFF))
                 .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
                 for k in range(4)]
        wb = jnp.concatenate(parts, axis=0)
    if STAGE in FULL:
        acc = jax.lax.dot_general(oh, wb, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        u = acc.astype(jnp.int32).astype(jnp.uint32)
        out_ref[...] = (u[:, :L] | (u[:, L:2*L] << jnp.uint32(8))
                        | (u[:, 2*L:3*L] << jnp.uint32(16))
                        | (u[:, 3*L:4*L] << jnp.uint32(24)))
    else:
        out_ref[...] = jnp.zeros((TILE, L), jnp.uint32)

def run():
    N = 1_048_576; SEG = 262_144; L = 8; window = 1024
    rng = np.random.default_rng(0)
    sn = np.sort(rng.choice(N, SEG // 2, replace=False)).astype(np.int32)
    idx = np.full(SEG, N, np.int32); idx[:len(sn)] = sn
    idx = jnp.asarray(idx)
    mat_t = jnp.asarray(rng.integers(0, 1 << 32, (L, N + 1), dtype=np.uint32))
    G = SEG // TILE
    heads = idx[::TILE]
    wsb = jnp.minimum((heads // 128) * 128, jnp.int32(((N + 1 - window) // 128) * 128))
    idx2 = idx.reshape(G, 8, TILE // 8)
    out = pl.pallas_call(
        partial(kern, window=window, L=L),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(G,),
            in_specs=[pl.BlockSpec((1, 8, TILE // 8), lambda j, ws: (j, jnp.int32(0), jnp.int32(0))),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((TILE, L), lambda j, ws: (j, jnp.int32(0))),
            scratch_shapes=[pltpu.VMEM((2, L, window), jnp.uint32),
                            pltpu.SemaphoreType.DMA((2,))]),
        out_shape=jax.ShapeDtypeStruct((SEG, L), jnp.uint32),
    )(wsb, idx2, mat_t)
    r = np.asarray(out)
    if STAGE in FULL:
        exp = np.asarray(mat_t).T[np.asarray(idx)]
        eq = (r == exp).all(axis=1)
        k = (~eq).sum()
        first_bad = int(np.argmin(eq)) if k else -1
        print("STAGE", STAGE, "equal:", bool(eq.all()), "bad rows:", int(k),
              "first bad:", first_bad, "n_real:", len(sn))
        if k:
            i = first_bad
            print("idx[i]:", int(np.asarray(idx)[i]))
            print("got:", [hex(v) for v in r[i]])
            print("exp:", [hex(v) for v in exp[i]])
            # which source row does 'got' correspond to?
            mt = np.asarray(mat_t)
            for cand in range(max(0, int(np.asarray(idx)[i])-3), int(np.asarray(idx)[i])+4):
                if (mt[:, cand] == r[i]).all():
                    print("got == mat row", cand)
    else:
        print("STAGE", STAGE, "compiled+ran")

run()
