"""Isolate: (a) u32 byte split/recombine in Mosaic; (b) one-hot matmul."""
import sys
sys.path.insert(0, "/root/repo")
from functools import partial
import jax, jax.numpy as jnp, numpy as np
import cylon_tpu
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MODE = sys.argv[1] if len(sys.argv) > 1 else "bytes"
L, W, TILE = 8, 1024, 256

def kern_bytes(x_ref, o_ref):
    w32 = x_ref[...]                       # (L, W) u32
    parts = [((w32 >> jnp.uint32(8 * k)) & jnp.uint32(0xFF))
             .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
             for k in range(4)]
    back = [p.astype(jnp.float32).astype(jnp.int32).astype(jnp.uint32)
            for p in parts]
    o_ref[...] = (back[0] | back[1] << jnp.uint32(8)
                  | back[2] << jnp.uint32(16) | back[3] << jnp.uint32(24))

def kern_mm(x_ref, idx_ref, o_ref):
    w32 = x_ref[...]
    parts = [((w32 >> jnp.uint32(8 * k)) & jnp.uint32(0xFF))
             .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
             for k in range(4)]
    wb = jnp.concatenate(parts, axis=0)    # (4L, W)
    lidx = idx_ref[0]                      # (8, 32)
    iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, W), 2)
    oh = (iota == lidx[:, :, None]).astype(jnp.bfloat16)
    oh = oh.reshape(TILE, W)
    acc = jax.lax.dot_general(oh, wb, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    u = acc.astype(jnp.int32).astype(jnp.uint32)
    o_ref[...] = (u[:, :L] | (u[:, L:2*L] << jnp.uint32(8))
                  | (u[:, 2*L:3*L] << jnp.uint32(16))
                  | (u[:, 3*L:4*L] << jnp.uint32(24)))

rng = np.random.default_rng(1)
x = jnp.asarray(rng.integers(0, 1 << 32, (L, W), dtype=np.uint32))
if MODE == "bytes":
    out = pl.pallas_call(kern_bytes,
                         out_shape=jax.ShapeDtypeStruct((L, W), jnp.uint32),
                         )(x)
    print("bytes exact:", bool((np.asarray(out) == np.asarray(x)).all()))
else:
    idxn = np.sort(rng.choice(W, TILE, replace=False)).astype(np.int32)
    idx2 = jnp.asarray(idxn.reshape(1, 8, TILE // 8))
    out = pl.pallas_call(kern_mm,
                         grid=(1,),
                         in_specs=[pl.BlockSpec((L, W), lambda j: (jnp.int32(0), jnp.int32(0))),
                                   pl.BlockSpec((1, 8, TILE // 8), lambda j: (j, jnp.int32(0), jnp.int32(0)))],
                         out_specs=pl.BlockSpec((TILE, L), lambda j: (j, jnp.int32(0))),
                         out_shape=jax.ShapeDtypeStruct((TILE, L), jnp.uint32),
                         )(x, idx2)
    exp = np.asarray(x).T[idxn]
    got = np.asarray(out)
    eq = (got == exp)
    print("mm exact:", bool(eq.all()), "bad:", int((~eq.all(axis=1)).sum()))
    if not eq.all():
        i = int(np.argmin(eq.all(axis=1)))
        print("got:", [hex(v) for v in got[i]]); print("exp:", [hex(v) for v in exp[i]])

# mode mm16: u16 split in f32 matmul
def kern_mm16(x_ref, idx_ref, o_ref):
    w32 = x_ref[...]
    hi = (w32 >> jnp.uint32(16)).astype(jnp.int32).astype(jnp.float32)
    lo = (w32 & jnp.uint32(0xFFFF)).astype(jnp.int32).astype(jnp.float32)
    wb = jnp.concatenate([hi, lo], axis=0)   # (2L, W) f32
    lidx = idx_ref[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, W), 2)
    oh = (iota == lidx[:, :, None]).astype(jnp.float32)
    oh = oh.reshape(TILE, W)
    acc = jax.lax.dot_general(oh, wb, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    u = acc.astype(jnp.int32).astype(jnp.uint32)
    o_ref[...] = (u[:, :L] << jnp.uint32(16)) | u[:, L:2*L]

if MODE == "mm16":
    idxn = np.sort(rng.choice(W, TILE, replace=False)).astype(np.int32)
    idx2 = jnp.asarray(idxn.reshape(1, 8, TILE // 8))
    out = pl.pallas_call(kern_mm16,
                         grid=(1,),
                         in_specs=[pl.BlockSpec((L, W), lambda j: (jnp.int32(0), jnp.int32(0))),
                                   pl.BlockSpec((1, 8, TILE // 8), lambda j: (j, jnp.int32(0), jnp.int32(0)))],
                         out_specs=pl.BlockSpec((TILE, L), lambda j: (j, jnp.int32(0))),
                         out_shape=jax.ShapeDtypeStruct((TILE, L), jnp.uint32),
                         )(x, idx2)
    exp = np.asarray(x).T[idxn]
    got = np.asarray(out)
    eq = got == exp
    print("mm16 exact:", bool(eq.all()), "bad rows:", int((~eq.all(axis=1)).sum()))
    if not eq.all():
        i = int(np.argmin(eq.all(axis=1)))
        print("got:", [hex(v) for v in got[i]])
        print("exp:", [hex(v) for v in exp[i]])

# mode mm4: four per-plane dots, no concat
def kern_mm4(x_ref, idx_ref, o_ref):
    w32 = x_ref[...]
    lidx = idx_ref[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, W), 2)
    oh = (iota == lidx[:, :, None]).astype(jnp.bfloat16)
    oh = oh.reshape(TILE, W)
    accs = []
    for k in range(4):
        pk = ((w32 >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)) \
            .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
        a = jax.lax.dot_general(oh, pk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        accs.append(a.astype(jnp.int32).astype(jnp.uint32))
    o_ref[...] = (accs[0] | accs[1] << jnp.uint32(8)
                  | accs[2] << jnp.uint32(16) | accs[3] << jnp.uint32(24))

if MODE == "mm4":
    idxn = np.sort(rng.choice(W, TILE, replace=False)).astype(np.int32)
    idx2 = jnp.asarray(idxn.reshape(1, 8, TILE // 8))
    out = pl.pallas_call(kern_mm4,
                         grid=(1,),
                         in_specs=[pl.BlockSpec((L, W), lambda j: (jnp.int32(0), jnp.int32(0))),
                                   pl.BlockSpec((1, 8, TILE // 8), lambda j: (j, jnp.int32(0), jnp.int32(0)))],
                         out_specs=pl.BlockSpec((TILE, L), lambda j: (j, jnp.int32(0))),
                         out_shape=jax.ShapeDtypeStruct((TILE, L), jnp.uint32),
                         )(x, idx2)
    exp = np.asarray(x).T[idxn]
    got = np.asarray(out)
    eq = got == exp
    print("mm4 exact:", bool(eq.all()), "bad:", int((~eq.all(axis=1)).sum()))

# mode mm5: wb assembled in VMEM scratch via slice writes, one 32-row dot
from jax.experimental.pallas import tpu as pltpu
def kern_mm5(x_ref, idx_ref, o_ref, wb_ref):
    w32 = x_ref[...]
    for k in range(4):
        wb_ref[pl.ds(k * L, L), :] = ((w32 >> jnp.uint32(8 * k))
                                      & jnp.uint32(0xFF)) \
            .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
    lidx = idx_ref[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, W), 2)
    oh = (iota == lidx[:, :, None]).astype(jnp.bfloat16)
    oh = oh.reshape(TILE, W)
    acc = jax.lax.dot_general(oh, wb_ref[...], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    u = acc.astype(jnp.int32).astype(jnp.uint32)
    o_ref[...] = (u[:, :L] | (u[:, L:2*L] << jnp.uint32(8))
                  | (u[:, 2*L:3*L] << jnp.uint32(16))
                  | (u[:, 3*L:4*L] << jnp.uint32(24)))

if MODE == "mm5":
    idxn = np.sort(rng.choice(W, TILE, replace=False)).astype(np.int32)
    idx2 = jnp.asarray(idxn.reshape(1, 8, TILE // 8))
    out = pl.pallas_call(kern_mm5,
                         grid=(1,),
                         in_specs=[pl.BlockSpec((L, W), lambda j: (jnp.int32(0), jnp.int32(0))),
                                   pl.BlockSpec((1, 8, TILE // 8), lambda j: (j, jnp.int32(0), jnp.int32(0)))],
                         out_specs=pl.BlockSpec((TILE, L), lambda j: (j, jnp.int32(0))),
                         out_shape=jax.ShapeDtypeStruct((TILE, L), jnp.uint32),
                         scratch_shapes=[pltpu.VMEM((4 * L, W), jnp.bfloat16)],
                         )(x, idx2)
    exp = np.asarray(x).T[idxn]
    got = np.asarray(out)
    eq = got == exp
    print("mm5 exact:", bool(eq.all()), "bad:", int((~eq.all(axis=1)).sum()))

# mode mm6: all-f32 operands (internal demotion exact for u8 values)
def kern_mm6(x_ref, idx_ref, o_ref, wb_ref):
    w32 = x_ref[...]
    for k in range(4):
        wb_ref[pl.ds(k * L, L), :] = ((w32 >> jnp.uint32(8 * k))
                                      & jnp.uint32(0xFF)) \
            .astype(jnp.int32).astype(jnp.float32)
    lidx = idx_ref[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, W), 2)
    oh = (iota == lidx[:, :, None]).astype(jnp.float32)
    oh = oh.reshape(TILE, W)
    acc = jax.lax.dot_general(oh, wb_ref[...], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    u = acc.astype(jnp.int32).astype(jnp.uint32)
    o_ref[...] = (u[:, :L] | (u[:, L:2*L] << jnp.uint32(8))
                  | (u[:, 2*L:3*L] << jnp.uint32(16))
                  | (u[:, 3*L:4*L] << jnp.uint32(24)))

if MODE == "mm6":
    idxn = np.sort(rng.choice(W, TILE, replace=False)).astype(np.int32)
    idx2 = jnp.asarray(idxn.reshape(1, 8, TILE // 8))
    out = pl.pallas_call(kern_mm6,
                         grid=(1,),
                         in_specs=[pl.BlockSpec((L, W), lambda j: (jnp.int32(0), jnp.int32(0))),
                                   pl.BlockSpec((1, 8, TILE // 8), lambda j: (j, jnp.int32(0), jnp.int32(0)))],
                         out_specs=pl.BlockSpec((TILE, L), lambda j: (j, jnp.int32(0))),
                         out_shape=jax.ShapeDtypeStruct((TILE, L), jnp.uint32),
                         scratch_shapes=[pltpu.VMEM((4 * L, W), jnp.float32)],
                         )(x, idx2)
    exp = np.asarray(x).T[idxn]
    got = np.asarray(out)
    eq = got == exp
    print("mm6 exact:", bool(eq.all()), "bad:", int((~eq.all(axis=1)).sum()))

# mode mm7: transposed acc (4L, TILE): planes are SUBLANE slices; output (L, TILE)
def kern_mm7(x_ref, idx_ref, o_ref, wb_ref):
    w32 = x_ref[...]
    for k in range(4):
        wb_ref[pl.ds(k * L, L), :] = ((w32 >> jnp.uint32(8 * k))
                                      & jnp.uint32(0xFF)) \
            .astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
    lidx = idx_ref[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (8, TILE // 8, W), 2)
    oh = (iota == lidx[:, :, None]).astype(jnp.bfloat16)
    oh = oh.reshape(TILE, W)
    accT = jax.lax.dot_general(wb_ref[...], oh, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)  # (4L, TILE)
    u = accT.astype(jnp.int32).astype(jnp.uint32)
    o_ref[...] = (u[0:L] | u[L:2*L] << jnp.uint32(8)
                  | u[2*L:3*L] << jnp.uint32(16)
                  | u[3*L:4*L] << jnp.uint32(24))

if MODE == "mm7":
    idxn = np.sort(rng.choice(W, TILE, replace=False)).astype(np.int32)
    idx2 = jnp.asarray(idxn.reshape(1, 8, TILE // 8))
    out = pl.pallas_call(kern_mm7,
                         grid=(1,),
                         in_specs=[pl.BlockSpec((L, W), lambda j: (jnp.int32(0), jnp.int32(0))),
                                   pl.BlockSpec((1, 8, TILE // 8), lambda j: (j, jnp.int32(0), jnp.int32(0)))],
                         out_specs=pl.BlockSpec((L, TILE), lambda j: (jnp.int32(0), j)),
                         out_shape=jax.ShapeDtypeStruct((L, TILE), jnp.uint32),
                         scratch_shapes=[pltpu.VMEM((4 * L, W), jnp.bfloat16)],
                         )(x, idx2)
    exp = np.asarray(x)[:, idxn]          # (L, TILE)
    got = np.asarray(out)
    eq = got == exp
    print("mm7 exact:", bool(eq.all()), "bad:", int((~eq).sum()))
