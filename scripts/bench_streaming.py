"""Closed-loop streaming ingest bench (cylon_tpu/stream).

The ROADMAP's "incremental & streaming workloads" rung measured: a
micro-batch stream is appended into a :class:`StreamTable` (hash-shuffle
on arrival), absorbed by an :class:`IncrementalView` (long-lived
GroupBySink — sum/count/min/max/mean/var/std over integer-valued
fixed-point amounts, so the exactness contract holds) and buffered into
a :class:`TumblingWindowJoin` (event-time windows against a small
broadcast build side, watermark-driven close + spill-tier eviction) —
while, by default, a TPC-H query tenant runs CONCURRENTLY on the same
mesh under the serving scheduler (the ingest loop is a ``stream``-kind
session; docs/serving.md), so the numbers describe ingest under mixed
traffic, not a quiet machine.

What one run produces (``STREAM_r01.json`` alongside BENCH_r0x /
SERVING_r01):

* sustained ingest rows/s over the whole loop;
* p50/p99 append-to-visible staleness — the wall time from an append's
  start to a finalized ``view.read()`` snapshot that includes it;
* watermark lag (max event time seen − agreed watermark) per vote;
* windows closed + ``window_evictions`` and the ledger-byte delta the
  close lifecycle (device → host → released) drained;
* a ``bit_equal`` verdict: the final incremental view vs a from-scratch
  batch groupby over every appended row, checked bitwise, and every
  closed window's join vs its batch recompute.

Usage::

    python scripts/bench_streaming.py                  # default config
    python scripts/bench_streaming.py --smoke          # tiny CI shape
    python scripts/bench_streaming.py --batches 60 --rows 4000 \
        --no-serve --out STREAM_r02.json

Exit status 0 = completed, bit-equal, >= 1 window closed+evicted (the
acceptance criteria); 1 otherwise.  ``--smoke`` runs as a slow-marked
tier-1 test (tests/test_stream.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4"
                               ).strip()

AGGS = [("amount", "sum"), ("amount", "count"), ("amount", "min"),
        ("amount", "max"), ("amount", "mean"), ("amount", "var"),
        ("amount", "std"), ("qty", "sum")]


def _quantile(xs, frac):
    """Nearest-rank quantile at FRACTION ``frac`` in [0, 1] (sibling
    bench_serving.py's private helper takes a 0-100 percent — the name
    difference keeps the two conventions from being confused)."""
    xs = sorted(xs)
    if not xs:
        return 0.0
    i = min(int(round(frac * (len(xs) - 1))), len(xs) - 1)
    return xs[i]


def make_batches(args):
    """Seeded micro-batch stream: keys uniform, amounts integer cents
    (f64 — exact sums, the bit-equality representation), event times
    advancing ~args.stride per batch with in-batch jitter and ~5% late
    stragglers (3 windows back — past the lateness allowance, so the
    late policy engages)."""
    import numpy as np
    rng = np.random.default_rng(args.seed)
    out = []
    for b in range(args.batches):
        n = args.rows
        t = (b * args.stride
             + rng.integers(0, args.stride, n)).astype(np.int64)
        late = rng.random(n) < 0.05
        t = np.where(late & (t >= 3 * args.window),
                     t - 3 * args.window, t)
        out.append({
            "k": rng.integers(0, args.keys, n).astype(np.int64),
            "qty": rng.integers(1, 51, n).astype(np.int64),
            "amount": rng.integers(100, 100_000, n).astype(np.float64),
            "t": t,
        })
    return out


def run(args) -> dict:
    import hashlib

    import numpy as np
    import pandas as pd

    import cylon_tpu as ct
    from cylon_tpu import obs, tpch
    from cylon_tpu.ctx.context import CPUMeshConfig
    from cylon_tpu.exec import memory
    from cylon_tpu.exec.scheduler import QueryScheduler
    from cylon_tpu.relational.groupby import groupby_aggregate
    from cylon_tpu.stream import (IncrementalView, StreamTable,
                                  TumblingWindowJoin)

    env = ct.CylonEnv(config=CPUMeshConfig(world_size=args.world))
    dims = ct.Table.from_pydict(
        {"k": np.arange(args.keys, dtype=np.int64),
         "dim": (np.arange(args.keys, dtype=np.int64) * 7 + 3)}, env)

    st = StreamTable(env, key="k", name="bench")
    view = IncrementalView(st, "k", AGGS, name="bench_view", env=env)
    wj = TumblingWindowJoin(env, key="k", time_col="t",
                            window=args.window, build=dims, build_on="k",
                            lateness=args.lateness, late_policy="drop",
                            name="bench_wjoin")
    batches = make_batches(args)
    memory.reset_stats()
    ledger_before = memory.balance()

    staleness: list[float] = []
    wm_lag: list[int] = []
    max_event = [np.int64(-1)]
    metrics: dict = {}

    closed_at: list[int] = []   # closed_through at each batch's arrival
    #                             (the late-policy replay oracle input)

    def ingest():
        t_loop = time.perf_counter()
        for b in batches:
            t0 = time.perf_counter()
            st.append(dict(b))
            closed_at.append(wj._closed_through)
            wj.append(dict(b))
            wj.watermark()
            # append-to-visible: the snapshot INCLUDING this batch is
            # finalized and host-materialized before the clock stops
            view.read().to_pandas()
            staleness.append(time.perf_counter() - t0)
            max_event[0] = max(max_event[0], int(b["t"].max()))
            wm = wj._closed_through * args.window
            wm_lag.append(int(max_event[0]) - wm)
        # drain: vote the final watermark (closes every ripe window)
        wj.watermark()
        metrics["ingest_wall_s"] = time.perf_counter() - t_loop
        return True

    def query_tenant():
        pdfs = tpch.generate_pandas(scale=args.tpch_scale, seed=6)
        dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
        outs = []
        for _ in range(args.tpch_iters):
            outs.append(float(tpch.q6(dfs, env=env)))
            outs.append(len(tpch.q1(dfs, env=env).to_pandas()))
        return outs

    if args.serve:
        sched = QueryScheduler(env, policy="fair")
        sched.submit("ingest", ingest, kind="stream")
        sched.submit("tpch", query_tenant)
        sessions = sched.run(raise_errors=True)
        serving = {s.name: {"kind": s.kind, "slices": s.slices,
                            "latency_s": round(s.latency_s or 0.0, 4)}
                   for s in sessions}
        sched_stats = sched.stats()
    else:
        ingest()
        serving, sched_stats = {}, {}

    # ---- verdicts --------------------------------------------------------
    def sha(df) -> str:
        h = hashlib.sha256()
        for col in df.columns:
            h.update(str(col).encode())
            h.update(np.ascontiguousarray(df[col].to_numpy()).tobytes())
        return h.hexdigest()

    got = view.read().to_pandas().sort_values("k").reset_index(drop=True)
    exp = groupby_aggregate(st.snapshot(), "k", AGGS).to_pandas() \
        .sort_values("k").reset_index(drop=True)
    bit_equal = sha(got[exp.columns]) == sha(exp)

    # every closed window's join vs its batch recompute: the oracle
    # replays the drop policy against ARRIVAL order — a batch's rows
    # survive only if their window was still open when the batch landed
    # (closed_at[i] = windows already closed at batch i's arrival)
    frames = []
    for i, b in enumerate(batches):
        f = pd.DataFrame(b)
        frames.append(f[(f.t // args.window) >= closed_at[i]]
                      if i < len(closed_at) else f)
    full = pd.concat(frames)
    dims_pd = dims.to_pandas()
    windows_equal = True
    for wid, out in wj.closed:
        if out is None:
            continue
        g = out.to_pandas().sort_values(["k", "t", "qty", "amount"]) \
            .reset_index(drop=True)
        w = full[(full.t >= wid * args.window)
                 & (full.t < (wid + 1) * args.window)]
        e = w.merge(dims_pd, on="k").sort_values(
            ["k", "t", "qty", "amount"]).reset_index(drop=True)
        if len(g) != len(e) or sha(g[e.columns].astype(e.dtypes)) != sha(e):
            windows_equal = False

    total_rows = sum(len(b["k"]) for b in batches)
    wall = metrics.get("ingest_wall_s", 1e-9)
    detail = {
        "world": env.world_size,
        "batches": args.batches, "rows_per_batch": args.rows,
        "keys": args.keys, "window": args.window,
        "lateness": args.lateness,
        "serve_concurrent": bool(args.serve),
        "rows_ingested": total_rows,
        "ingest_wall_s": round(wall, 4),
        "staleness_p50_s": round(_quantile(staleness, 0.50), 4),
        "staleness_p99_s": round(_quantile(staleness, 0.99), 4),
        "watermark_lag_p50": _quantile(wm_lag, 0.50),
        "watermark_lag_max": max(wm_lag) if wm_lag else 0,
        "windows_closed": wj.windows_closed,
        "late_dropped": wj.late_dropped,
        # spill-tier counters through the shared collector
        # (cylon_tpu.obs.bench_detail — same keys as the hand-rolled
        # block it replaces)
        **obs.bench_detail(spill_keys=("window_evictions",
                                       "bytes_spilled"),
                           ckpt_keys=(), events=None),
        "ledger_delta_bytes": memory.balance() - ledger_before,
        "bit_equal": bool(bit_equal),
        "windows_bit_equal": bool(windows_equal),
        "view_stats": view.stats(),
        "stream_stats": st.stats(),
        "window_stats": wj.stats(),
        "serving": serving, "scheduler": sched_stats,
    }
    return {
        "metric": "sustained streaming ingest (view + windowed join, "
                  + ("concurrent TPC-H tenant" if args.serve
                     else "solo") + ")",
        "value": round(total_rows / wall, 1),
        "unit": "rows/s",
        "detail": detail,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--rows", type=int, default=2500)
    ap.add_argument("--keys", type=int, default=64)
    ap.add_argument("--window", type=int, default=100)
    ap.add_argument("--stride", type=int, default=60)
    ap.add_argument("--lateness", type=int, default=30)
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tpch-scale", type=float, default=0.002)
    ap.add_argument("--tpch-iters", type=int, default=2)
    ap.add_argument("--no-serve", dest="serve", action="store_false",
                    help="run the ingest loop solo (no concurrent "
                         "TPC-H tenant / serving scheduler)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI shape; assert the acceptance criteria")
    ap.add_argument("--out", default=os.path.join(REPO, "STREAM_r01.json"))
    args = ap.parse_args()
    if args.smoke:
        args.batches, args.rows, args.keys = 6, 250, 16
        args.tpch_scale, args.tpch_iters = 0.001, 1

    res = run(args)
    d = res["detail"]
    print(json.dumps(res, indent=2))
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(res, f, indent=2)
        f.write("\n")
    ok = (res["value"] > 0 and d["bit_equal"] and d["windows_bit_equal"]
          and d["windows_closed"] >= 1 and d["window_evictions"] >= 1)
    print(f"# {'OK' if ok else 'FAIL'}: {res['value']} rows/s, "
          f"p99 staleness {d['staleness_p99_s']}s, "
          f"{d['windows_closed']} windows closed, "
          f"{d['window_evictions']} evicted, bit_equal={d['bit_equal']}",
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
