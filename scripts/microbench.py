"""Primitive microbenchmarks at bench scale — refreshes docs/DESIGN.md's
measured cost model on the current chip. Not part of the suite.

block_until_ready is unreliable over the axon tunnel; a tiny host pull is
the only real barrier (same trick as bench.py)."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

_pull = jax.jit(lambda x: x.reshape(-1)[:2].astype(jnp.float32).sum())


def sync(out):
    leaves = jax.tree.leaves(out)
    np.asarray(_pull(leaves[0]))


def timed(label, fn, *args, iters=3):
    f = jax.jit(fn)
    sync(f(*args))
    best = 1e9
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(f(*args))
        best = min(best, time.perf_counter() - t0)
    n = args[0].shape[0]
    print(f"{label:44s} {best*1e3:9.1f} ms  {best/n*1e9:6.2f} ns/row",
          flush=True)


def main():
    n = 128_000_000
    m = 80_000_000
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 1 << 31, n, dtype=np.int32))
    x2 = jnp.asarray(rng.integers(0, 1 << 31, n, dtype=np.int32))
    x64 = jnp.asarray(rng.integers(0, 1 << 62, n, dtype=np.int64))
    idx_r = jnp.asarray(rng.integers(0, n, m, dtype=np.int32))
    idx_m = jnp.asarray(np.sort(rng.integers(0, n, m, dtype=np.int32)))
    idx_n = jnp.asarray(rng.integers(0, m, n, dtype=np.int32))
    pos = jnp.arange(n, dtype=jnp.int32)

    timed("sort 1op i32", lambda a: jax.lax.sort((a,), num_keys=1), x)
    timed("sort 1key+1payload", lambda a, b: jax.lax.sort(
        (a, b), num_keys=1, is_stable=True), x, x2)
    timed("sort 1key+3payload", lambda a, b, c, d: jax.lax.sort(
        (a, b, c, d), num_keys=1, is_stable=True), x, x2, pos, pos)
    # distinct payload arrays per operand — XLA CSEs identical operands,
    # which would understate the per-lane payload cost
    timed("sort 1key+5payload", lambda a, b, c, d: jax.lax.sort(
        (a, b, c, d, b + 1, c + 1), num_keys=1, is_stable=True),
        x, x2, pos, pos)
    timed("sort 2key+2payload", lambda a, b, c, d: jax.lax.sort(
        (a, b, c, d), num_keys=2, is_stable=True), x, x2, pos, pos)
    timed("sort i64 key + payload", lambda a, b: jax.lax.sort(
        (a, b), num_keys=1, is_stable=True), x64, pos)
    timed("cumsum i32", jnp.cumsum, x)
    timed("cummax i32", jax.lax.cummax, x)
    timed("gather 1-D rand (m from n)", lambda i, a: a[i], idx_r, x)
    timed("gather 1-D monotone", lambda i, a: a[i], idx_m, x)
    timed("gather (n,2) rand", lambda i, a, b: jnp.stack([a, b], 1)[i],
          idx_r, x, x2)
    timed("gather (n,4) rand",
          lambda i, a, b: jnp.stack([a, b, a, b], 1)[i], idx_r, x, x2)
    timed("gather (n,6) rand",
          lambda i, a, b: jnp.stack([a, b, a, b, a, b], 1)[i], idx_r, x, x2)
    timed("gather (n,6) monotone",
          lambda i, a, b: jnp.stack([a, b, a, b, a, b], 1)[i], idx_m, x, x2)
    timed("stack (n,6) only",
          lambda a, b: jnp.stack([a, b, a, b, a, b], 1), x, x2)
    timed("gather 6 separate 1-D rand",
          lambda i, a, b: (a[i], b[i], a[i] + 1, b[i] + 1, a[i] + 2,
                           b[i] + 2), idx_r, x, x2)
    timed("scatter-max n->m slots",
          lambda i, p: jnp.zeros(m, jnp.int32).at[i].max(p, mode="drop"),
          idx_n, pos)
    timed("scatter-set m->n slots",
          lambda i, p: jnp.zeros(n, jnp.int32).at[i].set(p[:m], mode="drop"),
          idx_m, pos)
    timed("scatter-add m->n slots",
          lambda i, p: jnp.zeros(n, jnp.int32).at[i].add(p[:m], mode="drop"),
          idx_m, pos)
    timed("cumsum i64", jnp.cumsum, x64)
    timed("elementwise 3-op", lambda a, b: a * 2 + b, x, x2)
    timed("searchsorted m in n-sorted",
          lambda a, v: jnp.searchsorted(a, v, method="compare_all"),
          jnp.sort(x)[:n], idx_r)


if __name__ == "__main__":
    main()
