"""Per-phase profiling of the bench workload with explicit device blocking.

Runs the join+groupby pipeline's compiled phases one at a time, blocking
after each, so costs attribute to the phase that incurs them (the bench's
async regions smear attribution).  Not part of the test suite — a
measurement tool for kernel work.

Usage: python scripts/profile_join.py [--rows=N] [--unique=F]
"""

from __future__ import annotations

import sys
import time

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
from cylon_tpu.relational import groupby_aggregate, join_tables


def timed(label, fn, *args, iters=3):
    fn(*args)  # warm
    jax.block_until_ready(fn(*args))
    best = 1e9
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{label:40s} {best*1e3:10.1f} ms")
    return out


def main():
    rows = 64_000_000
    unique = 0.9
    for a in sys.argv[1:]:
        if a.startswith("--rows="):
            rows = int(a.split("=", 1)[1])
        if a.startswith("--unique="):
            unique = float(a.split("=", 1)[1])

    devs = jax.devices()
    on_accel = devs[0].platform != "cpu"
    cfg = TPUConfig() if on_accel else CPUMeshConfig()
    env = ct.CylonEnv(config=cfg)
    w = env.world_size
    n = rows * w
    max_val = max(int(n * unique), 1)
    rng = np.random.default_rng(42)
    lt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, n).astype(np.int64),
         "a": rng.integers(0, max_val, n).astype(np.int64)}, env)
    rt = ct.Table.from_pydict(
        {"k": rng.integers(0, max_val, n).astype(np.int64),
         "b": rng.integers(0, max_val, n).astype(np.int64)}, env)

    # ---- end-to-end first --------------------------------------------------
    def full():
        j = join_tables(lt, rt, "k", "k", how="inner")
        return groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])

    g = full()
    jax.block_until_ready([c.data for c in g.columns.values()])
    t0 = time.perf_counter()
    g = full()
    jax.block_until_ready([c.data for c in g.columns.values()])
    print(f"{'TOTAL join+groupby':40s} {(time.perf_counter()-t0)*1e3:10.1f} ms")

    # ---- join phases -------------------------------------------------------
    from cylon_tpu.ops import lanes
    from cylon_tpu.relational import join as rj
    from cylon_tpu.relational.common import (col_arrays, narrow32_flags)

    lwork, rwork = lt, rt
    l_key = [lwork.column("k")]
    r_key = [rwork.column("k")]
    l_datas, l_valids = col_arrays(l_key)
    r_datas, r_valids = col_arrays(r_key)
    narrow = narrow32_flags(l_key, r_key)
    print("narrow32 flags:", narrow)
    vcl = np.asarray(lwork.valid_counts, np.int32)
    vcr = np.asarray(rwork.valid_counts, np.int32)

    r_cols_list = [rwork.column("b")]
    l_cols_list = [lwork.column("k"), lwork.column("a")]
    rspec = lanes.plan_lanes(tuple(str(c.data.dtype) for c in r_cols_list),
                             tuple(c.validity is not None for c in r_cols_list),
                             narrow32_flags(r_cols_list))
    lspec = lanes.plan_lanes(tuple(str(c.data.dtype) for c in l_cols_list),
                             tuple(c.validity is not None for c in l_cols_list),
                             narrow32_flags(l_cols_list))
    print("lspec lanes:", lspec.n_lanes, "rspec lanes:", rspec.n_lanes)
    r_gather_args = (tuple(c.data for c in r_cols_list),
                     tuple(c.validity for c in r_cols_list))

    l_gather_args = (tuple(c.data for c in l_cols_list),
                     tuple(c.validity for c in l_cols_list))
    fn1 = rj._count_fn(env.mesh, "inner", narrow, lspec, rspec,
                       all_live=True)
    res = timed("join phase1 (sort+carry+count)", fn1, vcl, vcr, l_datas,
                l_valids, r_datas, r_valids, *l_gather_args, *r_gather_args)
    counts_dev, carry = res[0], res[1:7]
    pl_s = tuple(res[7:])
    counts = np.asarray(counts_dev).astype(np.int64)
    out_cap = config.pow2ceil(int(counts.max()))
    print("join out rows:", counts.sum(), "cap:", out_cap)

    plan = (("l", 0, False), ("l", 1, False), ("r", 0, False))
    fn2 = rj._materialize_fn(env.mesh, "inner", out_cap, lwork.capacity,
                             plan, lspec, rspec, True, True)
    mat_args = (carry, pl_s, *l_gather_args, *r_gather_args)
    timed("join phase2 (materialize)", fn2, *mat_args)

    # ---- groupby on grouped join output ------------------------------------
    j = join_tables(lt, rt, "k", "k", how="inner")
    jax.block_until_ready([c.data for c in j.columns.values()])

    def gb():
        return groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])

    g = gb()
    jax.block_until_ready([c.data for c in g.columns.values()])
    for _ in range(2):
        t0 = time.perf_counter()
        g = gb()
        jax.block_until_ready([c.data for c in g.columns.values()])
        print(f"{'groupby (grouped fast path)':40s} "
              f"{(time.perf_counter()-t0)*1e3:10.1f} ms")


if __name__ == "__main__":
    main()
