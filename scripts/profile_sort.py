"""Sort throughput spot check (local multi-key sort with lane carriage).
Not part of the suite."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import cylon_tpu as ct
from cylon_tpu.relational import sort_table

_pull = jax.jit(lambda x: x.reshape(-1)[:2].astype(jnp.float32).sum())


def sync(t):
    np.asarray(_pull(next(iter(t.columns.values())).data))


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 64_000_000
    rng = np.random.default_rng(0)
    t = ct.Table.from_pydict(
        {"k": rng.integers(0, rows, rows).astype(np.int64),
         "a": rng.integers(0, rows, rows).astype(np.int64),
         "b": rng.random(rows).astype(np.float32)})
    sync(sort_table(t, "k"))  # compile
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        sync(sort_table(t, "k"))
        best = min(best, time.perf_counter() - t0)
    print(f"sort_table {rows} rows, 3 cols: {best*1e3:.0f} ms "
          f"= {rows/best/1e6:.1f}M rows/s")


if __name__ == "__main__":
    main()
