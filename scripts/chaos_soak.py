"""Chaos-soak harness for the recovery ladder + durable checkpoint rung.

Seeded randomized fault schedules over every injector site
(``CYLON_TPU_FAULTS`` grammar, docs/robustness.md) driven against a
TPC-H-shaped pipelined join+groupby workload running in a CHILD
subprocess, so the ``kill`` fault kind (SIGKILL mid-range-loop) and the
``ResumableAbort`` path can actually be survived and resumed:

* every schedule must end in a BIT-EQUAL result (sha over the sorted
  result columns' raw bytes vs an un-injected baseline), possibly after
  the consensus retry ladder degraded the run in-process;
* or in a hard crash / typed ``ResumableAbort`` — then the harness
  reruns the child with ``CYLON_TPU_RESUME=1`` against the surviving
  checkpoint directory and THAT run must be bit-equal, fast-forwarding
  past committed pieces (``resume_fast_forwarded_pieces``) where any
  were committed;
* recovery-event counts stay bounded (the ladder's escalation is finite
  by construction — an unbounded count means a retry loop escaped it).

The first four schedules are pinned (kill-and-resume, corrupt-on-write
then kill, corrupt-on-load during resume, and kill-and-resume with the
phase-overlap escape hatch OFF — ``CYLON_TPU_PACKED_OVERLAP=0`` must
stay bit-equal to the overlap-on baseline even through a crash+resume)
so the acceptance paths run on every seed; the rest are drawn from
``--seed``.  Randomized draws run under the DEFAULT dispatch config,
which has the overlapped scheduler on — every drawn schedule therefore
also soaks deferred-fault re-raising (exec/pipeline._PieceFuture).

Usage::

    python scripts/chaos_soak.py --seed 7                 # 20 schedules
    python scripts/chaos_soak.py --seed 7 --schedules 4 --rows 1500

Exit status 0 = every schedule converged; 1 otherwise.  A trimmed soak
runs in CI as a slow-marked test (tests/test_checkpoint.py).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

#: (site, eligible kinds) for the randomized draws — `stall`/`desync`
#: are excluded: a desync is terminal by design (never retried), so a
#: schedule containing one cannot converge and would only test the
#: harness, not the ladder
SITE_KINDS = [
    ("shuffle.recv_guard", ["predicted", "device_oom", "capacity"]),
    ("join.piece_cap", ["capacity"]),
    ("groupby.device_oom", ["device_oom", "predicted"]),
    ("spill.evict", ["predicted"]),
    ("ckpt.write", ["corrupt", "device_oom", "kill"]),
    ("ckpt.load", ["corrupt"]),
]

#: per-run ceiling on logged recovery events: the ladder's schedule is
#: spill + 2 chunk rungs (+1 cap rung) per operator — a soak workload
#: crossing this is looping, not recovering
MAX_RECOVERY_EVENTS = 8

RESUMABLE_EXIT = 17


# ---------------------------------------------------------------------------
# worker: one workload run in this process (spawned by the parent)
# ---------------------------------------------------------------------------

def worker(args) -> int:
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig
    from cylon_tpu.exec import GroupBySink, checkpoint, pipelined_join, \
        recovery
    from cylon_tpu.status import ResumableAbort

    recovery.install_faults(None)   # validate the env grammar up front
    env = ct.CylonEnv(config=CPUMeshConfig(world_size=4))

    # TPC-H-shaped: orders ⋈ lineitem on the order key, aggregated per
    # order — integer "money" so every retry/restore path is exactly
    # bit-comparable.  Seeded: the resumed process rebuilds the
    # identical inputs, which is what makes the stage plan tokens match.
    rng = np.random.default_rng(20260803)
    n_ord = max(args.rows // 4, 64)
    n_line = args.rows
    orders = ct.Table.from_pydict(
        {"o_orderkey": np.arange(n_ord, dtype=np.int64),
         "o_shippriority": rng.integers(0, 5, n_ord).astype(np.int64)}, env)
    lineitem = ct.Table.from_pydict(
        {"l_orderkey": rng.integers(0, n_ord, n_line).astype(np.int64),
         "l_quantity": rng.integers(1, 51, n_line).astype(np.int64),
         "l_extendedprice": rng.integers(900_00, 10_500_00,
                                         n_line).astype(np.int64)}, env)

    def attempt(nc):
        sink = GroupBySink("l_orderkey", [("l_quantity", "sum"),
                                          ("l_extendedprice", "sum")])
        pipelined_join(lineitem, orders, "l_orderkey", "o_orderkey",
                       how="inner", n_chunks=nc, sink=sink)
        return sink.finalize()

    try:
        out = recovery.run_with_recovery(
            lambda: attempt(args.chunks), True, attempt, "soak", env=env)
    except ResumableAbort as e:
        print(json.dumps({"resumable": True, "token": e.token,
                          "events": len(recovery.recovery_events())}),
              flush=True)
        return RESUMABLE_EXIT

    df = out.to_pandas().sort_values("l_orderkey").reset_index(drop=True)
    h = hashlib.sha256()
    for col in sorted(df.columns):
        h.update(np.ascontiguousarray(df[col].to_numpy()).tobytes())
    print(json.dumps({
        "ok": True, "sha": h.hexdigest(), "rows": int(len(df)),
        "events": len(recovery.recovery_events()),
        "event_list": recovery.recovery_events(),
        **checkpoint.stats(),
    }), flush=True)
    return 0


# ---------------------------------------------------------------------------
# parent: schedule generation + child supervision
# ---------------------------------------------------------------------------

def _draw_schedule(rng) -> dict:
    n = 1 + int(rng.random() < 0.4)
    entries, resume_entries = [], []
    have_capacity = False
    for _ in range(n):
        site, kinds = SITE_KINDS[int(rng.integers(0, len(SITE_KINDS)))]
        kind = kinds[int(rng.integers(0, len(kinds)))]
        if kind == "capacity" and have_capacity:
            # the capacity ladder is ONE rung by design (bounded
            # escalation, docs/robustness.md) and a capacity abort is
            # not resumable — a schedule with two capacity faults is
            # unconvergeable by construction, like the excluded
            # stall/desync kinds; redraw the kind (or drop the entry
            # where capacity is the site's only kind)
            others = [k for k in kinds if k != "capacity"]
            if not others:
                continue
            kind = others[int(rng.integers(0, len(others)))]
        have_capacity = have_capacity or kind == "capacity"
        nth = int(rng.integers(1, 3))
        entry = f"{site}::{nth}={kind}"
        if site == "ckpt.load":
            # ckpt.load only fires while RESUMING (Stage.load_piece) —
            # armed in the primary run it would never trigger and the
            # schedule would silently degenerate to a happy-path run;
            # arm it in the resume leg instead
            resume_entries.append(entry)
        else:
            entries.append(entry)
    if resume_entries and not any(e.endswith("=kill") for e in entries):
        # the resume leg only runs after a hard crash — force one
        entries.append("ckpt.write::2=kill")
    return {"faults": ",".join(entries),
            "resume_faults": ",".join(resume_entries)}


def _pinned_schedules() -> list[dict]:
    return [
        # the acceptance path: SIGKILL mid-range-loop after one piece
        # committed, resume must fast-forward (ffwd > 0, no recompute of
        # the committed piece)
        {"faults": "ckpt.write::2=kill", "resume_faults": "",
         "expect_ffwd": True},
        # a corrupted page among the committed pieces: resume detects
        # the hash mismatch and degrades to recompute — still bit-equal
        {"faults": "ckpt.write::1=corrupt,ckpt.write::3=kill",
         "resume_faults": ""},
        # corruption injected on the LOAD side of the resume itself
        {"faults": "ckpt.write::3=kill",
         "resume_faults": "ckpt.load::1=corrupt"},
        # the overlap escape hatch: kill-and-resume with the
        # phase-overlapped scheduler DISABLED — both dispatch modes must
        # hash-equal the overlap-on baseline, crash and resume included
        {"faults": "ckpt.write::2=kill", "resume_faults": "",
         "expect_ffwd": True,
         "env": {"CYLON_TPU_PACKED_OVERLAP": "0"}},
    ]


def _spawn(args, workdir: str, faults: str, resume: bool,
           extra_env: dict | None = None) -> tuple:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never touch a TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["CYLON_TPU_FAULTS"] = faults
    env["CYLON_TPU_CKPT_DIR"] = workdir
    env.update(extra_env or {})
    if resume:
        env["CYLON_TPU_RESUME"] = "1"
    else:
        env.pop("CYLON_TPU_RESUME", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           f"--rows={args.rows}", f"--chunks={args.chunks}"]
    p = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=600)
    info = None
    for line in reversed(p.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                info = json.loads(line)
            except ValueError:
                pass
            break
    return p, info


def _run_schedule(args, idx: int, sched: dict, baseline_sha: str,
                  failures: list) -> None:
    workdir = tempfile.mkdtemp(prefix=f"soak{idx:02d}_", dir=args.workdir)

    def fail(msg, proc=None):
        tail = ("\n" + (proc.stdout + proc.stderr)[-2000:]) if proc else ""
        failures.append(f"schedule {idx} ({sched['faults']!r}): {msg}{tail}")

    p, info = _spawn(args, workdir, sched["faults"], resume=False,
                     extra_env=sched.get("env"))
    outcome = "ok"
    if p.returncode == 0:
        if not info or info.get("sha") != baseline_sha:
            fail(f"completed but result diverged: {info}", p)
        elif info["events"] > MAX_RECOVERY_EVENTS:
            fail(f"unbounded retries: {info['events']} recovery events", p)
    elif p.returncode == -9 or p.returncode == RESUMABLE_EXIT:
        outcome = "killed" if p.returncode == -9 else "resumable"
        p2, info2 = _spawn(args, workdir, sched.get("resume_faults", ""),
                           resume=True, extra_env=sched.get("env"))
        if p2.returncode != 0:
            fail(f"resume run failed rc={p2.returncode}", p2)
        elif not info2 or info2.get("sha") != baseline_sha:
            fail(f"resumed result diverged: {info2}", p2)
        elif info2["events"] > MAX_RECOVERY_EVENTS:
            fail(f"unbounded retries on resume: {info2['events']}", p2)
        elif sched.get("expect_ffwd") \
                and not info2.get("resume_fast_forwarded_pieces"):
            fail(f"resume recomputed committed pieces: {info2}", p2)
        else:
            outcome += (f"+resumed(ffwd="
                        f"{info2.get('resume_fast_forwarded_pieces')})")
    else:
        fail(f"unexpected exit rc={p.returncode}", p)
    rf = sched.get("resume_faults", "")
    print(f"# schedule {idx:02d} faults={sched['faults']!r}"
          + (f" resume_faults={rf!r}" if rf else "")
          + f" -> {outcome}", flush=True)
    shutil.rmtree(workdir, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--schedules", type=int, default=20)
    ap.add_argument("--rows", type=int, default=3000)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--worker", action="store_true")
    args = ap.parse_args()

    if args.worker:
        sys.path.insert(0, REPO)
        return worker(args)

    import numpy as np
    rng = np.random.default_rng(args.seed)
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_soak_")

    schedules = _pinned_schedules()
    while len(schedules) < args.schedules:
        schedules.append(_draw_schedule(rng))
    schedules = schedules[:args.schedules]

    # un-injected, un-checkpointed baseline: the bit-equality oracle
    p, info = _spawn(args, os.path.join(args.workdir, "baseline"), "",
                     resume=False)
    if p.returncode != 0 or not info or not info.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: baseline run failed", file=sys.stderr)
        return 1
    baseline_sha = info["sha"]
    print(f"# baseline sha={baseline_sha[:16]} rows={info['rows']}",
          flush=True)

    failures: list = []
    for i, sched in enumerate(schedules):
        _run_schedule(args, i, sched, baseline_sha, failures)

    print(json.dumps({"schedules": len(schedules),
                      "failures": len(failures), "seed": args.seed,
                      "detail": failures[:10]}))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
