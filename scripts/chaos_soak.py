"""Chaos-soak harness for the recovery ladder + durable checkpoint rung.

Seeded randomized fault schedules over every injector site
(``CYLON_TPU_FAULTS`` grammar, docs/robustness.md) driven against a
TPC-H-shaped pipelined join+groupby workload running in a CHILD
subprocess, so the ``kill`` fault kind (SIGKILL mid-range-loop) and the
``ResumableAbort`` path can actually be survived and resumed:

* every schedule must end in a BIT-EQUAL result (sha over the sorted
  result columns' raw bytes vs an un-injected baseline), possibly after
  the consensus retry ladder degraded the run in-process;
* or in a hard crash / typed ``ResumableAbort`` — then the harness
  reruns the child with ``CYLON_TPU_RESUME=1`` against the surviving
  checkpoint directory and THAT run must be bit-equal, fast-forwarding
  past committed pieces (``resume_fast_forwarded_pieces``) where any
  were committed;
* recovery-event counts stay bounded (the ladder's escalation is finite
  by construction — an unbounded count means a retry loop escaped it).

The first four schedules are pinned (kill-and-resume, corrupt-on-write
then kill, corrupt-on-load during resume, and kill-and-resume with the
phase-overlap escape hatch OFF — ``CYLON_TPU_PACKED_OVERLAP=0`` must
stay bit-equal to the overlap-on baseline even through a crash+resume)
so the acceptance paths run on every seed; the rest are drawn from
``--seed``.  Randomized draws run under the DEFAULT dispatch config,
which has the overlapped scheduler on — every drawn schedule therefore
also soaks deferred-fault re-raising (exec/pipeline._PieceFuture).

``--stream`` switches to the STREAMING-INGEST acceptance flow
(cylon_tpu/stream): a seeded micro-batch stream feeds a StreamTable +
IncrementalView whose absorbed partials commit durably per batch; the
pinned schedules SIGKILL the process mid-ingest (``stream.append::3=
kill`` and a kill during the view's ckpt.write) and the resumed rerun
must fast-forward the committed stream-view state (ffwd > 0; the
per-batch partials are the durable unit — windowed-join buffers replay
from upstream) with the final view bit-equal to the baseline.

``--concurrent K`` switches to the MULTI-TENANT acceptance flow
(exec/scheduler): K differently-seeded serving sessions interleave on
one mesh; the pinned schedule SIGKILLs the process mid-query in tenant
t0 only (the ``@session`` injector grammar, per-session occurrence
counting), and the resumed rerun must fast-forward t0's committed
pieces while EVERY tenant's answer stays bit-equal to its solo
(single-session) run — crash isolation under multi-tenancy.

``--oocore`` switches to the OUT-OF-CORE acceptance flow (the disk
tier, docs/robustness.md "Disk tier & scan pushdown"): the standard
join+sink workload runs under ``CYLON_TPU_HBM_BUDGET`` +
``CYLON_TPU_HOST_BUDGET`` caps sized below its working set, so packed
sources evict to host AND demote to per-rank spill files.  Pinned
schedules: a capped happy-path run (bit-equal with ``disk_events > 0``
and ``bytes_to_disk > 0``), ENOSPC mid-demote (typed degrade to
in-memory — no crash, bit-equal), corrupt-on-promote (the ladder
recomputes the owner — bit-equal, never a wrong answer), SIGKILL
mid-demote then resume (bit-equal), and the UNARMED contract leg (no
host budget ⇒ zero disk events and zero spill-file writes, asserted).

``--elastic`` switches to the ELASTIC-RESUME acceptance flow
(docs/robustness.md "Elastic resume & preemption grace"): a TWO-stage
workload (sinkless pipelined join feeding a join+sink) checkpoints at
world=2 in a subprocess; pinned schedules SIGKILL it mid-stage-2 and
resume at world=1 (the completed stage 1 must RE-SHARD and
fast-forward — ``resume_resharded_pieces > 0`` — while the interrupted
stage 2 recomputes, counted in ``resume_world_mismatch``), resume at
world=2 plain (no reshard, ordinary fast-forward), kill the world=1
resume AGAIN and resume at world=2-after-reshard (the rewritten
world=1 manifests re-shard back up), inject ``ckpt.reshard`` corruption
during a reshard (degrades to recompute, never a wrong answer), and
deliver SIGTERM with the preemption grace armed (the child must exit
via typed ResumableAbort — exit 17, not a signal death — within the
grace budget).  Every schedule must end bit-equal to the uninterrupted
world=2 baseline.

``--multislice`` switches to the MULTI-SLICE TOPOLOGY acceptance flow
(cylon_tpu/topo, docs/topology.md): a join+groupby workload on a
simulated two-tier grid (``CYLON_TPU_SLICES=2`` over a world-4 CPU
mesh) whose FLAT-routed run (``CYLON_TPU_TOPO_SHUFFLE=0``) is the
bit-equality oracle.  Pinned schedules: the armed happy path (a voted
topology plan, bit-equal, cross-slice DCN messages at ~1/R of the flat
plan's); a capacity fault inside the hierarchical exchange (the ladder
retries and must re-adopt the IDENTICAL voted plan hash — topology
derivation is deterministic); SIGKILL of one WHOLE SLICE mid-run
(simulated as a hard kill of the checkpointed two-stage elastic
workload at world=4/slices=2, resumed on the surviving world=2 single
slice — the PR 9 elastic re-shard must fast-forward stage 1 bit-equal
and the resumed topology re-votes); and the unarmed single-slice
contract leg: with no slice declaration the ARMED route must vote
nothing and move exactly the flat run's exchange rows and exchange
count — zero extra collectives, zero host syncs.

``--compile`` switches to the COMPILE-LIFECYCLE acceptance flow
(cylon_tpu/exec/compiler, docs/robustness.md "Compile lifecycle"): the
standard join+sink workload with the facade's persistent compile cache
armed per-leg (``CYLON_TPU_COMPILE_CACHE_DIR``).  Pinned legs: SIGKILL
*inside* a guarded ``.lower()/.compile()`` (the ``compile.build``
injector site) — the crash leaves the rank's intent journal on disk,
and the rerun against the same dir must ADOPT the orphan into the
crash quarantine (``quarantine_adoptions > 0``, the poisoned program
surfaces as typed ``CompileQuarantinedError``) and still complete
bit-equal via the ladder's capacity rung (a re-planned chunk count
compiles DIFFERENT shapes, skirting the quarantined signature);
corrupt-on-build (the manifest entry is poisoned, the relaunch's
arm-time hash validation drops it — ``manifest_drops > 0`` — and the
recompile is bit-equal); an injected compile stall with the watchdog
budget armed (typed ``CompileTimeoutError``, never a hang, and the
SAME dir reruns clean — a timeout does not poison the cache); and the
unarmed contract leg (no compile env vars ⇒ the facade never arms,
never creates its dir, and writes nothing).

``--audit`` switches to the DATA-INTEGRITY AUDIT acceptance flow
(cylon_tpu/exec/integrity, docs/robustness.md "Integrity audit tier"):
a monolithic join+groupby whose unarmed run is the bit-equality oracle.
Pinned legs: the armed clean run (``CYLON_TPU_AUDIT=1`` — bit-equal,
fingerprint checks > 0, zero violations, and exactly the unarmed run's
exchange rows/count: the audit adds no exchange traffic); an injected
silent corruption (``exchange.corrupt=corrupt`` flips one exchanged
byte) which the armed fingerprint must catch as a typed
``DataIntegrityError`` the ladder converts into ONE recompute —
bit-equal, with the ``integrity`` recovery event on the record;
PERSISTENT corruption (``exchange.corrupt::*=corrupt``) which must end
in a typed abort, never a silent wrong answer; the same one-shot
corruption under the skew-split route (``CYLON_TPU_SKEW_SPLIT=1``) and
under the two-tier topology route (``CYLON_TPU_SLICES=2`` +
``CYLON_TPU_TOPO_SHUFFLE=1``) — caught at the post-exchange stage
either way, recovered onto the same voted plan, bit-equal; and the
UNARMED contract leg: zero fingerprint checks, zero fingerprint votes
(the conservation laws still run — they are free host math).

``--skew`` switches to the ADAPTIVE-SKEW-SPLIT acceptance flow
(docs/skew.md): a monolithic skewed-key join+groupby (one hot key on
~80% of probe rows) whose unsplit run (``CYLON_TPU_SKEW_SPLIT=0``) is
the bit-equality oracle.  Pinned schedules: the armed happy path (a
non-empty voted plan, bit-equal), an exchange capacity fault INSIDE the
split (the ladder's retry must re-detect and re-vote the IDENTICAL plan
hash — determinism of the detection inputs), a spill fault under an
HBM budget cap (same contract), SIGKILL mid-workload then a fresh rerun
(same plan hash, bit-equal), and the unarmed-at-skew-0 contract leg: at
skew 0 the ARMED run must vote nothing, split nothing and move exactly
the exchange rows the unsplit run moves — zero extra collectives.

Usage::

    python scripts/chaos_soak.py --seed 7                 # 20 schedules
    python scripts/chaos_soak.py --seed 7 --schedules 4 --rows 1500
    python scripts/chaos_soak.py --concurrent 3 --rows 2000
    python scripts/chaos_soak.py --elastic --rows 1500 --chunks 3
    python scripts/chaos_soak.py --oocore --rows 2000 --chunks 3
    python scripts/chaos_soak.py --skew --rows 4000
    python scripts/chaos_soak.py --compile --rows 3000
    python scripts/chaos_soak.py --multislice --rows 3000
    python scripts/chaos_soak.py --audit --rows 3000

Exit status 0 = every schedule converged; 1 otherwise.  A trimmed soak
runs in CI as a slow-marked test (tests/test_checkpoint.py); the
concurrent flow as a slow-marked test in tests/test_scheduler.py.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

#: (site, eligible kinds) for the randomized draws — `stall`/`desync`
#: are excluded: a desync is terminal by design (never retried), so a
#: schedule containing one cannot converge and would only test the
#: harness, not the ladder
SITE_KINDS = [
    ("shuffle.recv_guard", ["predicted", "device_oom", "capacity"]),
    ("join.piece_cap", ["capacity"]),
    ("groupby.device_oom", ["device_oom", "predicted"]),
    ("spill.evict", ["predicted"]),
    ("ckpt.write", ["corrupt", "device_oom", "kill"]),
    ("ckpt.load", ["corrupt"]),
]

#: per-run ceiling on logged recovery events: the ladder's schedule is
#: spill + 2 chunk rungs (+1 cap rung) per operator — a soak workload
#: crossing this is looping, not recovering
MAX_RECOVERY_EVENTS = 8

RESUMABLE_EXIT = 17


# ---------------------------------------------------------------------------
# worker: one workload run in this process (spawned by the parent)
# ---------------------------------------------------------------------------

def _result_sha(df) -> str:
    import numpy as np
    h = hashlib.sha256()
    for col in sorted(df.columns):
        h.update(np.ascontiguousarray(df[col].to_numpy()).tobytes())
    return h.hexdigest()


def worker(args) -> int:
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig
    from cylon_tpu.exec import GroupBySink, checkpoint, pipelined_join, \
        recovery
    from cylon_tpu.status import ResumableAbort

    recovery.install_faults(None)   # validate the env grammar up front
    env = ct.CylonEnv(config=CPUMeshConfig(world_size=args.world))

    # TPC-H-shaped: orders ⋈ lineitem on the order key, aggregated per
    # order — integer "money" so every retry/restore path is exactly
    # bit-comparable.  Seeded (per tenant): the resumed process rebuilds
    # the identical inputs, which is what makes the stage plan tokens
    # match.
    def make_workload(seed: int, rows: int):
        def attempt(nc):
            rng = np.random.default_rng(seed)
            n_ord = max(rows // 4, 64)
            orders = ct.Table.from_pydict(
                {"o_orderkey": np.arange(n_ord, dtype=np.int64),
                 "o_shippriority": rng.integers(0, 5,
                                                n_ord).astype(np.int64)},
                env)
            lineitem = ct.Table.from_pydict(
                {"l_orderkey": rng.integers(0, n_ord,
                                            rows).astype(np.int64),
                 "l_quantity": rng.integers(1, 51, rows).astype(np.int64),
                 "l_extendedprice": rng.integers(900_00, 10_500_00,
                                                 rows).astype(np.int64)},
                env)
            sink = GroupBySink("l_orderkey", [("l_quantity", "sum"),
                                              ("l_extendedprice", "sum")])
            pipelined_join(lineitem, orders, "l_orderkey", "o_orderkey",
                           how="inner", n_chunks=nc, sink=sink)
            return sink.finalize()
        return attempt

    if args.stream:
        return _worker_stream(args, env)

    if args.elastic:
        return _worker_elastic(args, env)

    if args.skew:
        return _worker_skew(args, env)

    if args.multislice:
        return _worker_topo(args, env)

    if args.compile_flow:
        return _worker_compile(args, env, make_workload)

    if args.fleet:
        return _worker_fleet(args, env, make_workload)

    if args.concurrent > 1:
        return _worker_concurrent(args, env, make_workload)

    attempt = make_workload(20260803, args.rows)
    try:
        out = recovery.run_with_recovery(
            lambda: attempt(args.chunks), True, attempt, "soak", env=env)
    except ResumableAbort as e:
        print(json.dumps({"resumable": True, "token": e.token,
                          "events": len(recovery.recovery_events())}),
              flush=True)
        return RESUMABLE_EXIT

    from cylon_tpu.exec import memory
    df = out.to_pandas().sort_values("l_orderkey").reset_index(drop=True)
    print(json.dumps({
        "ok": True, "sha": _result_sha(df), "rows": int(len(df)),
        "events": len(recovery.recovery_events()),
        "event_list": recovery.recovery_events(),
        # disk-tier counters: the --oocore flow asserts these
        **{k: v for k, v in memory.stats().items()
           if k.startswith(("disk_", "bytes_to_disk", "bytes_from_disk"))},
        **checkpoint.stats(),
    }), flush=True)
    return 0


def _worker_stream(args, env) -> int:
    """The streaming-ingest acceptance workload (cylon_tpu/stream): a
    seeded micro-batch stream appended into a StreamTable + an
    IncrementalView whose absorbed partials commit durably per batch
    (one checkpoint piece per append with CYLON_TPU_CKPT_DIR armed).  A
    SIGKILL mid-ingest (``stream.append::N=kill`` or a kill during the
    view's ckpt.write) crashes the process between commits; the resumed
    rerun replays the SAME seeded stream, fast-forwards the committed
    stream-view state — the durable per-batch partials, the only
    checkpointed streaming state (windowed-join buffers replay from
    upstream; docs/streaming.md) — with ffwd > 0, and the final view
    must be bit-equal to the uninterrupted run."""
    import numpy as np

    from cylon_tpu.exec import checkpoint, recovery
    from cylon_tpu.stream import IncrementalView, StreamTable

    rng = np.random.default_rng(20260804)
    st = StreamTable(env, key="k", name="soak")
    view = IncrementalView(
        st, "k", [("v", "sum"), ("v", "mean"), ("v", "var")],
        name="soak_view", env=env)
    n_batches = max(args.rows // 500, 6)
    for _ in range(n_batches):
        st.append({"k": rng.integers(0, 64, 500).astype(np.int64),
                   "v": rng.integers(-100, 100, 500).astype(np.float64)})
    df = view.read().to_pandas().sort_values("k").reset_index(drop=True)
    print(json.dumps({
        "ok": True, "sha": _result_sha(df), "rows": int(len(df)),
        "batches": n_batches, "ffwd": view.fast_forwarded,
        "events": len(recovery.recovery_events()),
        **checkpoint.stats(),
    }), flush=True)
    return 0


def run_stream(args) -> int:
    """The ``--stream`` acceptance flow (pinned, not drawn): baseline →
    SIGKILL mid-ingest with checkpointing armed → resume.  The resume
    must fast-forward the committed stream-view state (ffwd > 0 —
    restored per-batch partials, not recomputed appends; windowed-join
    buffers are not checkpointed and replay from upstream) and end
    bit-equal to the uninterrupted baseline."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_stream_")
    failures: list = []

    base_p, base = _spawn(args, os.path.join(args.workdir, "base"), "",
                          resume=False, stream=True)
    if base_p.returncode != 0 or not base or not base.get("sha"):
        print((base_p.stdout + base_p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: stream baseline failed", file=sys.stderr)
        return 1
    print(f"# stream baseline sha={base['sha'][:16]} "
          f"batches={base['batches']}", flush=True)

    # pinned schedules: a hard kill at the Nth append, and one during
    # the view's checkpoint write — both mid-ingest, both must resume
    for faults in ("stream.append::3=kill", "ckpt.write::2=kill"):
        killdir = os.path.join(args.workdir,
                               faults.split("=")[0].replace(":", "_"))
        p, info = _spawn(args, killdir, faults, resume=False, stream=True)
        if p.returncode != -9:
            failures.append(
                f"stream kill ({faults!r}) did not crash the process "
                f"(rc={p.returncode})")
            continue
        if not os.path.exists(os.path.join(killdir,
                                           "TRACE_POSTMORTEM.json")):
            failures.append(f"stream kill ({faults!r}) left no "
                            "TRACE_POSTMORTEM.json breadcrumb")
        p2, info2 = _spawn(args, killdir, "", resume=True, stream=True)
        if p2.returncode != 0 or not info2:
            failures.append(f"stream resume ({faults!r}) failed "
                            f"rc={p2.returncode}: "
                            f"{(p2.stdout + p2.stderr)[-2000:]}")
        elif info2.get("sha") != base["sha"]:
            failures.append(
                f"stream resume ({faults!r}) diverged: {info2}")
        elif not info2.get("ffwd"):
            failures.append(
                f"stream resume ({faults!r}) recomputed committed "
                f"window state: {info2}")
        else:
            print(f"# stream {faults!r} + resume -> ok "
                  f"(ffwd={info2['ffwd']})", flush=True)

    # injection sanity: a predicted fault at the append site surfaces
    # TYPED — stream.append has no retry rung (an append is not a
    # guarded operator with a fallback), so the contract is a loud
    # typed abort, never a silent wrong answer
    p, info = _spawn(args, os.path.join(args.workdir, "pred"),
                     "stream.append::2=predicted", resume=False,
                     stream=True)
    if p.returncode == 0:
        failures.append(
            f"stream predicted fault was swallowed (rc=0): {info}")
    elif "PredictedResourceExhausted" not in (p.stdout + p.stderr):
        failures.append(
            f"stream predicted fault did not surface typed "
            f"(rc={p.returncode})")
    else:
        print("# stream predicted-fault schedule -> ok (typed abort)",
              flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"stream": True, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def _worker_compile(args, env, make_workload) -> int:
    """The compile-lifecycle acceptance workload (docs/robustness.md,
    "Compile lifecycle"): the standard join+sink workload under the
    consensus ladder, with the compile facade armed per-leg through the
    environment (CYLON_TPU_COMPILE_CACHE_DIR / _COMPILE_TIMEOUT_S /
    CYLON_TPU_FAULTS at the ``compile.build`` site).  The JSON line
    reports the result sha plus the facade's full counter set and the
    persistent dir's file listing — the parent's evidence for quarantine
    adoption, manifest poison drops, rewarm expectations and the
    unarmed zero-write contract.  A watchdog timeout the ladder cannot
    cure exits 3; an UNCURED quarantine (the ladder's re-planned shapes
    still hit the poisoned signature) exits 4 — both typed, never
    hangs."""
    from cylon_tpu.exec import compiler, recovery
    from cylon_tpu.status import (CompileQuarantinedError,
                                  CompileTimeoutError)

    attempt = make_workload(20260807, args.rows)
    try:
        out = recovery.run_with_recovery(
            lambda: attempt(args.chunks), True, attempt, "soak", env=env)
    except CompileTimeoutError as e:
        print(json.dumps({"timeout_typed": True, "site": e.site,
                          "signature": e.signature,
                          **compiler.stats()}), flush=True)
        return 3
    except CompileQuarantinedError as e:
        print(json.dumps({"quarantined_typed": True,
                          "signature": e.signature,
                          **compiler.stats()}), flush=True)
        return 4
    df = out.to_pandas().sort_values("l_orderkey").reset_index(drop=True)
    d = compiler.cache_dir()
    print(json.dumps({
        "ok": True, "sha": _result_sha(df), "rows": int(len(df)),
        "armed": bool(compiler.armed()),
        "cache_files": (sorted(os.listdir(d))
                        if d and os.path.isdir(d) else []),
        "events": len(recovery.recovery_events()),
        **compiler.stats(),
    }), flush=True)
    return 0


def run_compile(args) -> int:
    """The ``--compile`` acceptance flow (pinned, not drawn) — see the
    module docstring.  The kill leg's occurrence index targets a PIECE
    compile (chunk-shape-dependent), so the rerun's quarantine is
    curable by the ladder's capacity rung: re-planned chunk counts
    compile different shapes and skirt the poisoned signature."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_compile_")
    failures: list = []
    #: the first join _packed_count_fn compile in the pinned workload's
    #: deterministic fresh-compile order (rows=3000, chunks=4, world=4)
    #: — a per-piece program whose shapes change with the chunk count
    kill_nth = 21

    def spawn(tag, faults, cache_dir=None, extra=None):
        workdir = os.path.join(args.workdir, tag)
        env_extra = {}
        if cache_dir is not None:
            env_extra["CYLON_TPU_COMPILE_CACHE_DIR"] = cache_dir
        env_extra.update(extra or {})
        return _spawn(args, workdir, faults, resume=False,
                      extra_env=env_extra, compile_flow=True)

    # unarmed baseline: the bit-equality oracle AND the zero-write leg
    p, base = spawn("base", "")
    if p.returncode != 0 or not base or not base.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: compile baseline failed", file=sys.stderr)
        return 1
    print(f"# compile unarmed baseline sha={base['sha'][:16]}", flush=True)
    if base.get("armed"):
        failures.append(f"facade armed with no compile env vars: {base}")
    if base.get("quarantined") or base.get("watchdog_timeouts") \
            or base.get("expected_warm"):
        failures.append(f"unarmed run exercised armed-only state: {base}")

    # kill mid-compile → orphan intent → rerun adopts + quarantines +
    # completes bit-equal via the ladder's re-planned shapes
    kdir = os.path.join(args.workdir, "kill", "ccache")
    p, _ = spawn("kill", f"compile.build::{kill_nth}=kill",
                 cache_dir=kdir)
    if p.returncode != -9:
        failures.append(f"kill mid-compile did not crash the process "
                        f"(rc={p.returncode})")
    elif not os.path.exists(os.path.join(kdir, "intent.rank0.json")):
        failures.append("killed compile left no intent journal on disk")
    else:
        p2, info2 = spawn("kill_rerun", "", cache_dir=kdir)
        if p2.returncode != 0 or not info2 \
                or info2.get("sha") != base["sha"]:
            failures.append(f"rerun after kill mid-compile diverged "
                            f"(rc={p2.returncode}): {info2}\n"
                            f"{(p2.stdout + p2.stderr)[-2000:]}")
        elif not info2.get("quarantine_adoptions"):
            failures.append(f"rerun never adopted the orphan intent: "
                            f"{info2}")
        elif not info2.get("quarantined"):
            failures.append(f"adopted orphan not quarantined: {info2}")
        elif not info2.get("expected_warm"):
            failures.append(f"rerun saw no rewarm expectations from the "
                            f"killed run's manifest: {info2}")
        elif "quarantine.json" not in info2.get("cache_files", []):
            failures.append(f"quarantine not persisted: {info2}")
        else:
            print(f"# compile kill + rerun -> ok (adoptions="
                  f"{info2['quarantine_adoptions']} expected_warm="
                  f"{info2['expected_warm']})", flush=True)

    # corrupt-on-build: the poisoned manifest entry fails its content
    # hash at the relaunch's arm time — dropped to a clean recompile,
    # bit-equal, never wrong code
    cdir = os.path.join(args.workdir, "corrupt", "ccache")
    p, info = spawn("corrupt", "compile.build::1=corrupt",
                    cache_dir=cdir)
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"corrupt-on-build leg diverged "
                        f"(rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    else:
        p2, info2 = spawn("corrupt_rerun", "", cache_dir=cdir)
        if p2.returncode != 0 or not info2 \
                or info2.get("sha") != base["sha"]:
            failures.append(f"relaunch over poisoned manifest diverged "
                            f"(rc={p2.returncode}): {info2}")
        elif not info2.get("manifest_drops"):
            failures.append(f"poisoned manifest entry not dropped at "
                            f"arm time: {info2}")
        else:
            print(f"# compile corrupt + relaunch -> ok (drops="
                  f"{info2['manifest_drops']})", flush=True)

    # injected stall with the watchdog budget armed: typed
    # CompileTimeoutError (exit 3), never a hang — and the SAME dir
    # then reruns clean (a timeout does not poison the cache)
    sdir = os.path.join(args.workdir, "stall", "ccache")
    p, info = spawn("stall", "compile.build::1=stall", cache_dir=sdir,
                    extra={"CYLON_TPU_COMPILE_TIMEOUT_S": "0.5"})
    if p.returncode != 3 or not info or not info.get("timeout_typed"):
        failures.append(f"stall did not surface a typed compile timeout "
                        f"(rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    elif not info.get("watchdog_timeouts"):
        failures.append(f"watchdog timeout not counted: {info}")
    else:
        p2, info2 = spawn("stall_rerun", "", cache_dir=sdir)
        if p2.returncode != 0 or not info2 \
                or info2.get("sha") != base["sha"]:
            failures.append(f"rerun after stall diverged "
                            f"(rc={p2.returncode}): {info2}")
        elif info2.get("quarantine_adoptions"):
            failures.append(f"a watchdog timeout left an orphan intent "
                            f"(must clear in finally): {info2}")
        else:
            print("# compile stall -> ok (typed timeout, dir reruns "
                  "clean)", flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"compile": True, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def _worker_skew(args, env) -> int:
    """The adaptive-skew-split acceptance workload (docs/skew.md): a
    monolithic skewed-key inner join + groupby-sum on the DataFrame
    engine's default route.  ``--skew-frac`` shapes the probe key
    column (0.0 = the unarmed contract leg); CYLON_TPU_SKEW_SPLIT in
    the environment arms/disarms the route.  The JSON line reports the
    result sha, the voted plan hash (None when the join ran unsplit)
    and the always-on exchange row counter — the flow's zero-extra-
    collectives evidence."""
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu.exec import integrity, recovery
    from cylon_tpu.obs import metrics
    from cylon_tpu.relational import groupby_aggregate, join_tables
    from cylon_tpu.relational import skew as skew_facade

    rng = np.random.default_rng(20260805)
    n = max(args.rows, 2048)
    mv = max(int(n * 0.9), 8)
    hot = np.int64(mv // 2)
    lk = rng.integers(0, mv, n).astype(np.int64)
    if args.skew_frac > 0.0:
        lk = np.where(rng.random(n) < args.skew_frac, hot, lk)
    rk = rng.integers(0, mv, n).astype(np.int64)
    rk[rk == hot] = hot + 1
    rk[0] = hot
    lt = ct.Table.from_pydict(
        {"k": lk, "a": rng.integers(0, mv, n).astype(np.int64)}, env)
    rt = ct.Table.from_pydict(
        {"k": rk, "b": rng.integers(0, mv, n).astype(np.int64)}, env)

    # injected recoverable faults (capacity, spill, device_oom shapes)
    # are handled by the operators' own ladders inside these calls; a
    # `kill` kind SIGKILLs mid-flight and the parent reruns fresh
    j = join_tables(lt, rt, "k", "k", how="inner")
    out = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])
    plan = skew_facade.last_plan()
    df = out.to_pandas().sort_values("k").reset_index(drop=True)
    print(json.dumps({
        "ok": True, "sha": _result_sha(df), "rows": int(len(df)),
        "events": len(recovery.recovery_events()),
        "event_list": recovery.recovery_events(),
        "plan_hash": (format(plan.plan_hash(), "016x")
                      if plan is not None else None),
        "skew_split_joins": int(metrics.counter("skew_split_joins").value),
        "exchange_rows": int(metrics.counter("exchange_rows_total").value),
        # integrity-audit counters: the --audit flow asserts these
        **{f"audit_{k}": v for k, v in integrity.stats().items()
           if k in ("conservation_checks", "fingerprint_checks",
                    "fingerprint_votes", "violations",
                    "corruptions_injected")},
    }), flush=True)
    return 0


def _worker_topo(args, env) -> int:
    """The multi-slice topology acceptance workload (docs/topology.md):
    a monolithic join + groupby-sum whose route — flat vs hierarchical
    two-hop — is controlled by CYLON_TPU_SLICES / CYLON_TPU_TOPO_SHUFFLE
    in the environment.  The JSON line reports the result sha, the
    voted topology plan hash (None when every exchange routed flat),
    the always-on exchange counters (the zero-extra-collectives
    evidence) and the per-tier DCN message/wire counters (the ~1/R
    cross-slice instrument)."""
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu.exec import integrity, recovery
    from cylon_tpu.obs import metrics
    from cylon_tpu.relational import groupby_aggregate, join_tables
    from cylon_tpu.topo import model as topo_model

    rng = np.random.default_rng(20260806)
    n = max(args.rows, 2048)
    mv = max(int(n * 0.9), 8)
    lt = ct.Table.from_pydict(
        {"k": rng.integers(0, mv, n).astype(np.int64),
         "a": rng.integers(0, mv, n).astype(np.int64)}, env)
    rt = ct.Table.from_pydict(
        {"k": rng.integers(0, mv, n).astype(np.int64),
         "b": rng.integers(0, mv, n).astype(np.int64)}, env)
    j = join_tables(lt, rt, "k", "k", how="inner")
    out = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])
    plan = topo_model.last_plan()
    df = out.to_pandas().sort_values("k").reset_index(drop=True)
    print(json.dumps({
        "ok": True, "sha": _result_sha(df), "rows": int(len(df)),
        "events": len(recovery.recovery_events()),
        "event_list": recovery.recovery_events(),
        "topo_plan_hash": (format(plan.plan_hash(), "016x")
                           if plan is not None else None),
        "topo_plans_voted": int(
            metrics.counter("topo_plans_voted").value),
        "exchange_rows": int(metrics.counter("exchange_rows_total").value),
        "exchange_count": int(metrics.counter("exchange_count").value),
        "dcn_rows": int(metrics.counter("exchange_dcn_rows_total").value),
        "dcn_messages": int(
            metrics.counter("exchange_dcn_messages_total").value),
        "dcn_wire_bytes": int(
            metrics.counter("exchange_dcn_wire_bytes_total").value),
        # integrity-audit counters: the --audit flow asserts these
        **{f"audit_{k}": v for k, v in integrity.stats().items()
           if k in ("conservation_checks", "fingerprint_checks",
                    "fingerprint_votes", "violations",
                    "corruptions_injected")},
    }), flush=True)
    return 0


def run_multislice(args) -> int:
    """The ``--multislice`` acceptance flow (pinned, not drawn) — see
    the module docstring.  Simulated two-tier grid: world 4, 2 slices
    of 2 (``CYLON_TPU_SLICES=2``); R = ranks per slice = 2."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_topo_")
    failures: list = []
    r_per_slice = 2

    def spawn(tag, faults, slices=2, armed=True, extra=None):
        workdir = os.path.join(args.workdir, tag)
        env_extra = {"CYLON_TPU_TOPO_SHUFFLE": "1" if armed else "0"}
        if slices:
            env_extra["CYLON_TPU_SLICES"] = str(slices)
        env_extra.update(extra or {})
        return _spawn(args, workdir, faults, resume=False,
                      extra_env=env_extra, multislice=True, world=4)

    # flat-routed baseline on the two-tier grid: the bit-equality
    # oracle AND the cross-slice traffic yardstick
    p, base = spawn("base", "", armed=False)
    if p.returncode != 0 or not base or not base.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: multislice baseline failed", file=sys.stderr)
        return 1
    print(f"# topo flat baseline sha={base['sha'][:16]} "
          f"dcn_messages={base['dcn_messages']}", flush=True)
    if base.get("topo_plans_voted"):
        failures.append(f"flat-routed run voted a topology plan: {base}")

    # armed happy path: voted plan, bit-equal, DCN messages ~1/R
    p, info = spawn("hier", "")
    plan0 = (info or {}).get("topo_plan_hash")
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"hierarchical run diverged (rc={p.returncode}): "
                        f"{info}\n{(p.stdout + p.stderr)[-2000:]}")
    elif not plan0 or not info.get("topo_plans_voted"):
        failures.append(f"hierarchical run never voted a plan: {info}")
    elif info.get("dcn_rows") != base.get("dcn_rows"):
        failures.append(
            f"cross-slice PAYLOAD changed (must be route-invariant): "
            f"{info.get('dcn_rows')} != {base.get('dcn_rows')}")
    elif info["dcn_messages"] * r_per_slice > base["dcn_messages"] * 1.2:
        failures.append(
            f"DCN message count not reduced ~1/R: hier="
            f"{info['dcn_messages']} flat={base['dcn_messages']} R=2")
    else:
        print(f"# topo hier -> ok (plan={plan0} dcn_messages="
              f"{info['dcn_messages']} vs flat {base['dcn_messages']})",
              flush=True)

    # capacity fault INSIDE the hierarchical exchange (the receive
    # guard probes before phase B dispatch): the ladder's retry must
    # re-adopt the IDENTICAL voted topology plan before going bit-equal
    p, info = spawn("capacity", "shuffle.recv_guard::1=capacity",
                    extra={"CYLON_TPU_EXCHANGE_GUARD_CPU": "1"})
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"capacity-fault leg diverged (rc={p.returncode}):"
                        f" {info}\n{(p.stdout + p.stderr)[-2000:]}")
    elif info.get("topo_plan_hash") != plan0:
        failures.append(f"capacity-fault recovery adopted a DIFFERENT "
                        f"topology plan: {info.get('topo_plan_hash')} != "
                        f"{plan0}")
    elif not info.get("events") or info["events"] > MAX_RECOVERY_EVENTS:
        failures.append(f"capacity-fault leg events out of range: {info}")
    else:
        print("# topo capacity fault -> ok (same voted plan, bit-equal)",
              flush=True)

    # whole-slice loss → elastic resume: the checkpointed two-stage
    # elastic workload runs at world=4/slices=2, a SIGKILL mid-stage-2
    # takes the process (and with it both slices) down, and the resume
    # runs on the SURVIVING world=2 single slice — the PR 9 re-shard
    # must fast-forward stage 1 bit-equal while stage 2 recomputes
    k1 = args.chunks + 1
    two_tier = {"CYLON_TPU_SLICES": "2"}
    one_tier = {"CYLON_TPU_SLICES": "1"}
    p, ebase = _spawn(args, os.path.join(args.workdir, "ebase"), "",
                      resume=False, elastic=True, world=4,
                      extra_env=two_tier)
    if p.returncode != 0 or not ebase or not ebase.get("sha"):
        failures.append(f"elastic two-tier baseline failed "
                        f"(rc={p.returncode}): "
                        f"{(p.stdout + p.stderr)[-2000:]}")
    else:
        dK = os.path.join(args.workdir, "slicekill")
        p1, _ = _spawn(args, dK, f"ckpt.write::{k1}=kill", resume=False,
                       elastic=True, world=4, extra_env=two_tier)
        if p1.returncode != -9:
            failures.append(f"whole-slice kill did not crash "
                            f"(rc={p1.returncode})")
        else:
            p2, info2 = _spawn(args, dK, "", resume=True, elastic=True,
                               world=2, extra_env=one_tier)
            if p2.returncode != 0 or not info2 \
                    or info2.get("sha") != ebase["sha"]:
                failures.append(
                    f"slice-loss resume diverged (rc={p2.returncode}): "
                    f"{info2}\n{(p2.stdout + p2.stderr)[-2000:]}")
            elif not info2.get("resume_resharded_pieces") \
                    or not info2.get("resume_world_mismatch"):
                failures.append(f"slice loss did not re-shard: {info2}")
            else:
                print(f"# topo slice-kill + elastic resume -> ok "
                      f"(resharded={info2['resume_resharded_pieces']} "
                      f"ffwd={info2['resume_fast_forwarded_pieces']})",
                      flush=True)

    # unarmed single-slice contract: with NO slice declaration the
    # ARMED route must vote nothing and run the byte-identical flat
    # engine — same sha, same exchange rows, same exchange count (zero
    # extra collectives, zero host syncs)
    p, flat0 = spawn("single_unarmed", "", slices=0, armed=False)
    p2, flat1 = spawn("single_armed", "", slices=0, armed=True)
    if p.returncode != 0 or p2.returncode != 0 or not flat0 or not flat1:
        failures.append(f"single-slice legs failed (rc={p.returncode}/"
                        f"{p2.returncode}): {flat0} {flat1}")
    elif flat1.get("sha") != flat0.get("sha"):
        failures.append(f"armed-on-single-slice diverged: {flat1}")
    elif flat1.get("topo_plan_hash") is not None \
            or flat1.get("topo_plans_voted"):
        failures.append(f"armed-on-single-slice voted a plan: {flat1}")
    elif (flat1.get("exchange_rows") != flat0.get("exchange_rows")
          or flat1.get("exchange_count") != flat0.get("exchange_count")):
        failures.append(
            f"armed-on-single-slice moved different exchange traffic: "
            f"{flat1} != {flat0}")
    else:
        print("# topo unarmed single-slice -> ok (no vote, identical "
              "exchange counters)", flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"multislice": True, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def run_audit(args) -> int:
    """The ``--audit`` acceptance flow (pinned, not drawn) — see the
    module docstring.  Drives the integrity audit tier
    (cylon_tpu/exec/integrity) end to end: silent exchange corruption
    injected via ``exchange.corrupt`` must be CAUGHT by the armed
    fingerprint (typed, one recompute, bit-equal) on the flat, the
    skew-split and the two-tier topology routes; persistent corruption
    must end in a typed abort; and the unarmed path must do zero
    fingerprint work."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_audit_")
    failures: list = []

    def spawn(tag, faults, extra=None, skew=False):
        workdir = os.path.join(args.workdir, tag)
        return _spawn(args, workdir, faults, resume=False,
                      extra_env=extra or {}, world=4,
                      skew=skew, multislice=not skew)

    def integrity_event(info):
        # the ladder's recompute of a caught corruption records an
        # event with kind="integrity"
        return any(ev.get("kind") == "integrity"
                   for ev in (info or {}).get("event_list") or [])

    # unarmed baseline: the bit-equality oracle AND the zero-overhead
    # contract — no fingerprint checks, no fingerprint votes (the
    # conservation laws still run; they are free host math)
    p, base = spawn("base", "")
    if p.returncode != 0 or not base or not base.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: audit baseline failed", file=sys.stderr)
        return 1
    print(f"# audit unarmed baseline sha={base['sha'][:16]} "
          f"conservation_checks={base['audit_conservation_checks']}",
          flush=True)
    if base.get("audit_fingerprint_checks") \
            or base.get("audit_fingerprint_votes"):
        failures.append(f"UNARMED run did fingerprint work: {base}")
    if not base.get("audit_conservation_checks"):
        failures.append(f"conservation laws not always-on: {base}")

    # armed clean run: bit-equal, fingerprints checked, zero
    # violations, and exactly the unarmed run's exchange traffic (the
    # audit's all_gather is not an exchange — armed adds no exchange
    # collectives, and the checks are stage-boundary, not per-row)
    p, info = spawn("armed", "", extra={"CYLON_TPU_AUDIT": "1"})
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"armed clean run diverged (rc={p.returncode}): "
                        f"{info}\n{(p.stdout + p.stderr)[-2000:]}")
    elif not info.get("audit_fingerprint_checks") \
            or not info.get("audit_fingerprint_votes"):
        failures.append(f"armed run never fingerprinted: {info}")
    elif info.get("audit_violations"):
        failures.append(f"armed clean run reported violations: {info}")
    elif (info.get("exchange_rows") != base.get("exchange_rows")
          or info.get("exchange_count") != base.get("exchange_count")):
        failures.append(
            f"arming the audit changed exchange traffic: {info} != {base}")
    else:
        print(f"# audit armed clean -> ok (fp_checks="
              f"{info['audit_fingerprint_checks']})", flush=True)

    # one-shot silent corruption, armed: the flipped byte must surface
    # as a typed DataIntegrityError the ladder converts into ONE
    # recompute — bit-equal, with the integrity event on the record
    p, info = spawn("corrupt", "exchange.corrupt=corrupt",
                    extra={"CYLON_TPU_AUDIT": "1"})
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"caught-corruption leg diverged "
                        f"(rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    elif not info.get("audit_violations") \
            or not info.get("audit_corruptions_injected"):
        failures.append(f"corruption not injected/detected: {info}")
    elif not integrity_event(info):
        failures.append(f"no integrity recovery event recorded: {info}")
    elif info.get("events", 0) > MAX_RECOVERY_EVENTS:
        failures.append(f"corruption recovery events out of range: {info}")
    else:
        print("# audit one-shot corruption -> ok (caught, one recompute, "
              "bit-equal)", flush=True)

    # PERSISTENT corruption: every recompute re-flips, so the ladder
    # must exhaust its single rung and abort TYPED — a wrong answer or
    # a clean exit here is the silent-corruption disaster this tier
    # exists to prevent
    p, info = spawn("persist", "exchange.corrupt::*=corrupt",
                    extra={"CYLON_TPU_AUDIT": "1"})
    if p.returncode == 0:
        failures.append(f"persistent corruption returned a result: {info}")
    elif "DataIntegrityError" not in (p.stderr or ""):
        failures.append(f"persistent corruption died untyped "
                        f"(rc={p.returncode}): "
                        f"{(p.stdout + p.stderr)[-2000:]}")
    else:
        print("# audit persistent corruption -> typed abort (ok)",
              flush=True)

    # corruption under the SKEW-SPLIT route: the fingerprint must catch
    # it at the post-exchange stage inside the split join, and the
    # recompute must land on the same voted plan, bit-equal
    skew_env = {"CYLON_TPU_SKEW_SPLIT": "1", "CYLON_TPU_AUDIT": "1"}
    p, sbase = spawn("skew_base", "", extra=skew_env, skew=True)
    if p.returncode != 0 or not sbase or not sbase.get("sha") \
            or not sbase.get("skew_split_joins"):
        failures.append(f"audit skew baseline failed (rc={p.returncode}, "
                        f"did it split?): {sbase}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    else:
        p, info = spawn("skew_corrupt", "exchange.corrupt=corrupt",
                        extra=skew_env, skew=True)
        if p.returncode != 0 or not info \
                or info.get("sha") != sbase["sha"]:
            failures.append(f"skew-route corruption leg diverged "
                            f"(rc={p.returncode}): {info}\n"
                            f"{(p.stdout + p.stderr)[-2000:]}")
        elif not info.get("audit_violations") or not integrity_event(info):
            failures.append(f"skew-route corruption not caught: {info}")
        elif info.get("plan_hash") != sbase.get("plan_hash"):
            failures.append(f"skew-route recompute changed the voted "
                            f"plan: {info.get('plan_hash')} != "
                            f"{sbase.get('plan_hash')}")
        else:
            print("# audit corruption under skew-split -> ok (caught, "
                  "same plan, bit-equal)", flush=True)

    # corruption under the TWO-TIER topology route: the hierarchical
    # exchange's delivered bytes are fingerprint-verified exactly like
    # the flat route's — caught post-exchange, bit-equal to the flat
    # oracle (route bit-equality is the topo tier's own invariant)
    topo_env = {"CYLON_TPU_SLICES": "2", "CYLON_TPU_TOPO_SHUFFLE": "1",
                "CYLON_TPU_AUDIT": "1"}
    p, info = spawn("topo_corrupt", "exchange.corrupt=corrupt",
                    extra=topo_env)
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"topo-route corruption leg diverged "
                        f"(rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    elif not info.get("topo_plans_voted"):
        failures.append(f"topo-route leg never voted a plan: {info}")
    elif not info.get("audit_violations") or not integrity_event(info):
        failures.append(f"topo-route corruption not caught: {info}")
    else:
        print("# audit corruption under two-tier route -> ok (caught, "
              "bit-equal)", flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"audit": True, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def run_skew(args) -> int:
    """The ``--skew`` acceptance flow (pinned, not drawn) — see the
    module docstring.  Every schedule must end bit-equal to the UNSPLIT
    baseline, and every recovered schedule must land on the IDENTICAL
    voted plan hash."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_skew_")
    failures: list = []

    def spawn(tag, faults, armed=True, frac=0.8, extra=None):
        workdir = os.path.join(args.workdir, tag)
        env_extra = {"CYLON_TPU_SKEW_SPLIT": "1" if armed else "0"}
        env_extra.update(extra or {})
        return _spawn(args, workdir, faults, resume=False,
                      extra_env=env_extra, skew=True, skew_frac=frac)

    # unsplit baseline: the bit-equality oracle
    p, base = spawn("base", "", armed=False)
    if p.returncode != 0 or not base or not base.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: skew baseline failed", file=sys.stderr)
        return 1
    print(f"# skew unsplit baseline sha={base['sha'][:16]}", flush=True)

    # armed happy path: a non-empty voted plan, bit-equal
    p, info = spawn("split", "")
    plan0 = (info or {}).get("plan_hash")
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"armed split diverged (rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    elif not plan0 or not info.get("skew_split_joins"):
        failures.append(f"armed split never voted a plan: {info}")
    else:
        print(f"# skew split -> ok (plan={plan0})", flush=True)

    # exchange capacity fault INSIDE the split (the build-side hash
    # shuffle's receive guard): the ladder retries the join, which must
    # re-detect and re-vote the IDENTICAL plan before going bit-equal
    p, info = spawn("capacity", "shuffle.recv_guard::1=capacity",
                    extra={"CYLON_TPU_EXCHANGE_GUARD_CPU": "1"})
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"capacity-fault leg diverged (rc={p.returncode}): "
                        f"{info}\n{(p.stdout + p.stderr)[-2000:]}")
    elif info.get("plan_hash") != plan0:
        failures.append(f"capacity-fault recovery re-voted a DIFFERENT "
                        f"plan: {info.get('plan_hash')} != {plan0}")
    elif not info.get("events") or info["events"] > MAX_RECOVERY_EVENTS:
        failures.append(f"capacity-fault leg events out of range: {info}")
    else:
        print("# skew capacity fault -> ok (same plan, bit-equal)",
              flush=True)

    # spill fault under an HBM budget cap: same contract
    p, info = spawn("spill", "spill.evict::1=predicted",
                    extra={"CYLON_TPU_HBM_BUDGET": "4096"})
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"spill-fault leg diverged (rc={p.returncode}): "
                        f"{info}\n{(p.stdout + p.stderr)[-2000:]}")
    elif info.get("plan_hash") != plan0:
        failures.append(f"spill-fault recovery re-voted a DIFFERENT "
                        f"plan: {info.get('plan_hash')} != {plan0}")
    else:
        print("# skew spill fault -> ok (same plan, bit-equal)", flush=True)

    # SIGKILL mid-workload (at the groupby site, after the voted split
    # exchange ran), then a fresh rerun: same plan, bit-equal
    p, _ = spawn("kill", "groupby.device_oom::1=kill")
    if p.returncode != -9:
        failures.append(f"kill leg did not crash (rc={p.returncode})")
    else:
        p2, info2 = spawn("kill_rerun", "")
        if p2.returncode != 0 or not info2 \
                or info2.get("sha") != base["sha"]:
            failures.append(f"rerun after kill diverged "
                            f"(rc={p2.returncode}): {info2}\n"
                            f"{(p2.stdout + p2.stderr)[-2000:]}")
        elif info2.get("plan_hash") != plan0:
            failures.append(f"rerun after kill voted a DIFFERENT plan: "
                            f"{info2.get('plan_hash')} != {plan0}")
        else:
            print("# skew kill + rerun -> ok (same plan, bit-equal)",
                  flush=True)

    # unarmed-at-skew-0 contract: the ARMED run at skew 0 votes nothing,
    # splits nothing, and moves exactly the unsplit run's exchange rows
    p, flat0 = spawn("flat_unsplit", "", armed=False, frac=0.0)
    p2, flat1 = spawn("flat_armed", "", armed=True, frac=0.0)
    if p.returncode != 0 or p2.returncode != 0 or not flat0 or not flat1:
        failures.append(f"flat legs failed (rc={p.returncode}/"
                        f"{p2.returncode}): {flat0} {flat1}")
    elif flat1.get("sha") != flat0.get("sha"):
        failures.append(f"armed-at-skew-0 diverged: {flat1}")
    elif flat1.get("plan_hash") is not None \
            or flat1.get("skew_split_joins"):
        failures.append(f"armed-at-skew-0 voted a plan: {flat1}")
    elif flat1.get("exchange_rows") != flat0.get("exchange_rows"):
        failures.append(
            f"armed-at-skew-0 moved extra exchange rows: "
            f"{flat1.get('exchange_rows')} != {flat0.get('exchange_rows')}")
    else:
        print("# skew unarmed-at-0 -> ok (no vote, no extra exchange "
              "rows)", flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"skew": True, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def run_oocore(args) -> int:
    """The ``--oocore`` acceptance flow (pinned, not drawn): the disk
    tier's end-to-end contract.  Budget caps sized below the workload's
    working set force evict→demote; every schedule must end bit-equal
    to the uncapped baseline — degraded, resumed or recomputed, never
    wrong — and the unarmed leg must write NOTHING."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_oocore_")
    failures: list = []
    caps = {"CYLON_TPU_HBM_BUDGET": "4096",
            "CYLON_TPU_HOST_BUDGET": "4096"}

    def spawn(tag, faults, resume=False, capped=True, spill_sub="spill"):
        workdir = os.path.join(args.workdir, tag)
        extra = dict(caps) if capped else {}
        extra["CYLON_TPU_SPILL_DIR"] = os.path.join(workdir, spill_sub)
        return _spawn(args, workdir, faults, resume=resume,
                      extra_env=extra), os.path.join(workdir, spill_sub)

    # uncapped, un-injected baseline: the bit-equality oracle
    (p, base), _sd = spawn("base", "", capped=False)
    if p.returncode != 0 or not base or not base.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: oocore baseline failed", file=sys.stderr)
        return 1
    print(f"# oocore baseline sha={base['sha'][:16]}", flush=True)
    if base.get("disk_events"):
        failures.append(f"UNARMED baseline wrote to disk: {base}")
    if os.path.isdir(_sd):
        failures.append(f"unarmed run created the spill dir {_sd}")

    # capped happy path: bit-equal THROUGH the disk tier, traffic counted
    (p, info), _sd = spawn("capped", "")
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"capped run diverged (rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    elif not info.get("disk_events") or not info.get("bytes_to_disk"):
        failures.append(f"capped run never touched the disk tier: {info}")
    else:
        print(f"# oocore capped -> ok (disk_events={info['disk_events']} "
              f"bytes_to_disk={info['bytes_to_disk']})", flush=True)

    # ENOSPC mid-demote: typed degrade to in-memory — no crash, bit-equal
    (p, info), _sd = spawn("enospc", "disk.write::1=enospc")
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"enospc mid-demote crashed or diverged "
                        f"(rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    elif not info.get("disk_write_degrades"):
        failures.append(f"enospc degrade not counted: {info}")
    else:
        print("# oocore enospc -> ok (typed degrade, bit-equal)",
              flush=True)

    # corrupt-on-promote: the ladder recomputes the owner — bit-equal
    (p, info), _sd = spawn("corrupt", "disk.read::1=corrupt")
    if p.returncode != 0 or not info or info.get("sha") != base["sha"]:
        failures.append(f"corrupt-on-promote crashed or produced a WRONG "
                        f"answer (rc={p.returncode}): {info}\n"
                        f"{(p.stdout + p.stderr)[-2000:]}")
    elif not info.get("disk_corrupt_degrades"):
        failures.append(f"corrupt degrade not counted: {info}")
    elif info.get("events", 0) > MAX_RECOVERY_EVENTS:
        failures.append(f"unbounded retries after corruption: {info}")
    else:
        print("# oocore corrupt-on-promote -> ok (recompute, bit-equal)",
              flush=True)

    # SIGKILL mid-demote, then resume: bit-equal after the crash
    (p, _), _sd = spawn("kill", "disk.write::1=kill")
    if p.returncode != -9:
        failures.append(f"kill mid-demote did not crash "
                        f"(rc={p.returncode})")
    else:
        workdir = os.path.join(args.workdir, "kill")
        extra = dict(caps)
        extra["CYLON_TPU_SPILL_DIR"] = os.path.join(workdir, "spill2")
        p2, info2 = _spawn(args, workdir, "", resume=True, extra_env=extra)
        if p2.returncode != 0 or not info2 \
                or info2.get("sha") != base["sha"]:
            failures.append(f"resume after kill mid-demote diverged "
                            f"(rc={p2.returncode}): {info2}\n"
                            f"{(p2.stdout + p2.stderr)[-2000:]}")
        else:
            print(f"# oocore kill mid-demote + resume -> ok (ffwd="
                  f"{info2.get('resume_fast_forwarded_pieces')})",
                  flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"oocore": True, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def _worker_elastic(args, env) -> int:
    """The elastic-resume acceptance workload: TWO chained pipelined
    stages — a sinkless join (stage 1) feeding a join+GroupBySink
    (stage 2) — so a kill landing mid-stage-2 leaves a COMPLETE stage 1
    behind, which a resume at a different world must re-shard and
    fast-forward while stage 2 recomputes.  Integer "money" columns and
    a unique-key final groupby keep the sorted result sha world-
    invariant, which is what makes one uninterrupted world=2 baseline
    the oracle for every resume world.  A preemption-grace drain
    (SIGTERM via the ``term`` injector kind, grace budget in the env)
    exits via typed ResumableAbort → RESUMABLE_EXIT instead of a signal
    death."""
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu.exec import GroupBySink, checkpoint, pipelined_join, \
        recovery
    from cylon_tpu.status import ResumableAbort

    rng = np.random.default_rng(20260804)
    rows = args.rows
    n_ord = max(rows // 4, 64)
    n_cust = 16
    orders = ct.Table.from_pydict(
        {"o_orderkey": np.arange(n_ord, dtype=np.int64),
         "o_custkey": rng.integers(0, n_cust, n_ord).astype(np.int64)},
        env)
    lineitem = ct.Table.from_pydict(
        {"l_orderkey": rng.integers(0, n_ord, rows).astype(np.int64),
         "l_quantity": rng.integers(1, 51, rows).astype(np.int64),
         "l_extendedprice": rng.integers(900_00, 10_500_00,
                                         rows).astype(np.int64)},
        env)
    customers = ct.Table.from_pydict(
        {"c_custkey": np.arange(n_cust, dtype=np.int64),
         "c_nationkey": rng.integers(0, 5, n_cust).astype(np.int64)},
        env)
    try:
        # stage 1 (sinkless): its piece outputs are the checkpointed
        # state a world change must re-shard in global row order
        jt = pipelined_join(lineitem, orders, "l_orderkey", "o_orderkey",
                            how="inner", n_chunks=args.chunks)
        # stage 2 (sink): mergeable partial aggregates
        sink = GroupBySink("o_custkey", [("l_quantity", "sum"),
                                         ("l_extendedprice", "sum")])
        pipelined_join(jt, customers, "o_custkey", "c_custkey",
                       how="inner", n_chunks=args.chunks, sink=sink)
        out = sink.finalize()
    except ResumableAbort as e:
        print(json.dumps({"resumable": True, "token": e.token,
                          "events": len(recovery.recovery_events()),
                          **checkpoint.stats()}), flush=True)
        return RESUMABLE_EXIT
    df = out.to_pandas().sort_values("o_custkey").reset_index(drop=True)
    print(json.dumps({
        "ok": True, "sha": _result_sha(df), "rows": int(len(df)),
        "world": int(env.world_size),
        "events": len(recovery.recovery_events()),
        **checkpoint.stats(),
    }), flush=True)
    return 0


def run_elastic(args) -> int:
    """The ``--elastic`` acceptance flow (pinned, not drawn) — see the
    module docstring.  ``k1`` is stage 2's first checkpoint write (the
    stage-1 pieces occupy writes 1..chunks), so a fault there leaves
    stage 1 complete and stage 2 untouched or partial."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_elastic_")
    failures: list = []
    k1 = args.chunks + 1

    p, base = _spawn(args, os.path.join(args.workdir, "base"), "",
                     resume=False, elastic=True, world=2)
    if p.returncode != 0 or not base or not base.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: elastic baseline failed", file=sys.stderr)
        return 1
    print(f"# elastic baseline sha={base['sha'][:16]} world=2", flush=True)

    def resume_leg(tag, workdir, world, resume_faults="", extra=None,
                   want_reshard=True):
        p2, info = _spawn(args, workdir, resume_faults, resume=True,
                          elastic=True, world=world, extra_env=extra)
        if p2.returncode != 0 or not info:
            failures.append(f"{tag}: resume at world={world} failed "
                            f"rc={p2.returncode}: "
                            f"{(p2.stdout + p2.stderr)[-2000:]}")
            return None
        if info.get("sha") != base["sha"]:
            failures.append(f"{tag}: resume at world={world} diverged "
                            f"from the world=2 baseline: {info}")
        elif want_reshard and not info.get("resume_resharded_pieces"):
            failures.append(f"{tag}: world change did not re-shard "
                            f"(recomputed everything): {info}")
        elif want_reshard and not info.get("resume_world_mismatch"):
            failures.append(f"{tag}: world mismatch not counted: {info}")
        elif not want_reshard and info.get("resume_resharded_pieces"):
            failures.append(f"{tag}: same-world resume resharded: {info}")
        elif not info.get("resume_fast_forwarded_pieces"):
            failures.append(f"{tag}: resume recomputed every committed "
                            f"piece: {info}")
        else:
            print(f"# elastic {tag} -> ok (world={world} "
                  f"ffwd={info['resume_fast_forwarded_pieces']} "
                  f"resharded={info['resume_resharded_pieces']} "
                  f"mismatch={info['resume_world_mismatch']})", flush=True)
        return info

    def kill_leg(tag, workdir, faults, extra=None):
        p1, _ = _spawn(args, workdir, faults, resume=False, elastic=True,
                       world=2, extra_env=extra)
        if p1.returncode != -9:
            failures.append(f"{tag}: kill schedule did not crash "
                            f"(rc={p1.returncode})")
            return False
        if not os.path.exists(os.path.join(workdir,
                                           "TRACE_POSTMORTEM.json")):
            failures.append(f"{tag}: killed child left no "
                            "TRACE_POSTMORTEM.json breadcrumb")
        return True

    # A: ckpt at world=2, SIGKILL mid-stage-2 → resume at world=1:
    # stage 1 re-shards 2→1 and fast-forwards, stage 2 recomputes
    dA = os.path.join(args.workdir, "killA")
    if kill_leg("A", dA, f"ckpt.write::{k1}=kill"):
        resume_leg("A (2→1 reshard)", dA, 1)

    # B: same kill → plain resume at world=2 (fast-forward, no reshard)
    dB = os.path.join(args.workdir, "killB")
    if kill_leg("B", dB, f"ckpt.write::{k1}=kill"):
        resume_leg("B (2→2 plain)", dB, 2, want_reshard=False)

    # C: kill at world=2, resume at world=1 and kill THAT mid-stage-2
    # (stage 1 is now rewritten in the world=1 layout), then resume at
    # world=2-after-reshard: the gen-bumped world=1 manifests must
    # re-shard back up while the stale world=2 rank dirs read as stale
    dC = os.path.join(args.workdir, "killC")
    if kill_leg("C", dC, f"ckpt.write::{k1}=kill"):
        p2, _ = _spawn(args, dC, f"ckpt.write::{args.chunks + 2}=kill",
                       resume=True, elastic=True, world=1)
        if p2.returncode != -9:
            failures.append(f"C: second kill (world=1 resume) did not "
                            f"crash (rc={p2.returncode})")
        else:
            resume_leg("C (1→2 after-reshard)", dC, 2)

    # D: corruption injected DURING the re-shard read: the stage must
    # degrade to recompute — bit-equal, nothing resharded
    dD = os.path.join(args.workdir, "killD")
    if kill_leg("D", dD, f"ckpt.write::{k1}=kill"):
        p2, info = _spawn(args, dD, "ckpt.reshard::1=corrupt",
                          resume=True, elastic=True, world=1)
        if p2.returncode != 0 or not info:
            failures.append(f"D: corrupt-reshard resume failed "
                            f"rc={p2.returncode}")
        elif info.get("sha") != base["sha"]:
            failures.append(f"D: corrupt reshard produced a WRONG "
                            f"answer: {info}")
        elif info.get("resume_resharded_pieces"):
            failures.append(f"D: corrupt reshard still adopted pieces: "
                            f"{info}")
        else:
            print("# elastic D (corrupt reshard → recompute) -> ok",
                  flush=True)

    # F: SIGKILL DURING the re-shard itself (mid-adoption crash): the
    # checkpoint state is untouched (adoption commits nothing until the
    # rewrite), so resuming AGAIN must re-shard cleanly
    dF = os.path.join(args.workdir, "killF")
    if kill_leg("F", dF, f"ckpt.write::{k1}=kill"):
        p2, _ = _spawn(args, dF, "ckpt.reshard::1=kill", resume=True,
                       elastic=True, world=1)
        if p2.returncode != -9:
            failures.append(f"F: kill mid-reshard did not crash "
                            f"(rc={p2.returncode})")
        else:
            resume_leg("F (reshard after mid-reshard kill)", dF, 1)

    # E: preemption grace — SIGTERM (term kind) with the grace budget
    # armed must exit via typed ResumableAbort (RESUMABLE_EXIT), not a
    # signal death, with the current stage committed; the world=1
    # resume then rides the committed prefix
    dE = os.path.join(args.workdir, "termE")
    grace = {"CYLON_TPU_PREEMPT_GRACE_S": "30"}
    p1, info1 = _spawn(args, dE, f"ckpt.write::{k1}=term", resume=False,
                       elastic=True, world=2, extra_env=grace)
    if p1.returncode != RESUMABLE_EXIT:
        failures.append(f"E: SIGTERM with grace armed did not drain via "
                        f"ResumableAbort (rc={p1.returncode}): "
                        f"{(p1.stdout + p1.stderr)[-1500:]}")
    elif not info1 or not info1.get("checkpoint_events"):
        failures.append(f"E: grace drain committed nothing: {info1}")
    elif not os.path.exists(os.path.join(dE, "TRACE_POSTMORTEM.json")):
        failures.append("E: grace drain left no TRACE_POSTMORTEM.json "
                        "breadcrumb beside the manifests")
    else:
        print(f"# elastic E drain -> ok (committed="
              f"{info1['checkpoint_events']})", flush=True)
        p2, info2 = _spawn(args, dE, "", resume=True, elastic=True,
                           world=1, extra_env=grace)
        if p2.returncode != 0 or not info2 \
                or info2.get("sha") != base["sha"]:
            failures.append(f"E: resume after grace drain diverged "
                            f"(rc={p2.returncode}): {info2}")
        else:
            print(f"# elastic E resume -> ok (ffwd="
                  f"{info2['resume_fast_forwarded_pieces']})", flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"elastic": True, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def _worker_concurrent(args, env, make_workload) -> int:
    """K concurrent serving sessions over one mesh (exec/scheduler), each
    a differently-seeded pipelined join+sink tenant.  ``--only i``
    restricts to one tenant — the SOLO leg whose sha is the concurrent
    run's bit-equality oracle.  Faults target tenants with the
    ``@session`` grammar (``ckpt.write::2=kill@t0``); a kill takes the
    whole process down and the parent reruns with CYLON_TPU_RESUME=1 —
    the per-session checkpoint stage namespace then fast-forwards the
    killed tenant while every tenant's answer stays bit-equal to its
    solo run."""
    from cylon_tpu.exec import checkpoint, recovery
    from cylon_tpu.exec.scheduler import QueryScheduler
    from cylon_tpu.status import ResumableAbort

    def make_fn(i: int):
        attempt = make_workload(20260803 + 7919 * i, args.rows)

        def fn():
            out = recovery.run_with_recovery(
                lambda: attempt(args.chunks), True, attempt,
                f"soak.t{i}", env=env)
            return out.to_pandas().sort_values("l_orderkey") \
                .reset_index(drop=True)
        return fn

    sched = QueryScheduler(env, policy="fair")
    idxs = [i for i in range(args.concurrent)
            if args.only is None or i == args.only]
    for i in idxs:
        sched.submit(f"t{i}", make_fn(i))
    sessions = sched.run()
    shas, events = {}, {}
    for s in sessions:
        if isinstance(s.error, ResumableAbort):
            print(json.dumps({"resumable": True, "token": s.error.token,
                              "session": s.name}), flush=True)
            return RESUMABLE_EXIT
        if s.error is not None:
            raise s.error
        shas[s.name] = _result_sha(s.result)
        events[s.name] = s.recovery_events()
    print(json.dumps({
        "ok": True, "shas": shas, "session_events": events,
        "events": len(recovery.recovery_events()),
        **checkpoint.stats(),
    }), flush=True)
    return 0


def _worker_fleet(args, env, make_workload) -> int:
    """One ``--fleet`` worker process (docs/serving.md, "Preemption &
    elastic serving").  The case rides ``CYLON_TPU_FLEET_CASE``:

    * ``preempt`` — tA (long, low priority) submits tB (short, high
      priority) from inside its own first run; under
      ``policy=priority`` + ``max_concurrency=1`` the scheduler
      preempt-drains tA at its next checkpoint boundary, runs tB, then
      requeues tA which resumes in-process (fast-forward > 0).  Solo
      oracles are computed in-process with checkpointing popped, so
      ``bit_equal`` is decided right here.
    * ``resize`` — three tenants under a ResizeController
      (``CYLON_TPU_FLEET_TARGET`` armed): sustained queue depth
      engages the all-or-nothing fleet drain; the worker exits
      RESUMABLE_EXIT with zero failed_typed tenants, and the SAME case
      relaunched without the target (at the new ``--world``) resumes
      every tenant to a bit-equal finish.
    * ``deadline`` — ``CYLON_TPU_ADMISSION_TIMEOUT_S`` armed, fifo,
      one slot: the queued tenant must fail typed
      (AdmissionTimeoutError), never hang.
    """
    from cylon_tpu.exec import checkpoint
    from cylon_tpu.exec.fleet import ResizeController
    from cylon_tpu.exec.scheduler import QueryScheduler
    from cylon_tpu.status import AdmissionTimeoutError, ResumableAbort

    case = os.environ.get("CYLON_TPU_FLEET_CASE", "preempt")

    def df_of(seed, rows, nc):
        out = make_workload(seed, rows)(nc)
        return out.to_pandas().sort_values("l_orderkey") \
            .reset_index(drop=True)

    # tenant specs: (name, seed, rows, chunks) — tA long (many drain
    # boundaries), tB short (the high-priority arrival)
    specs = {
        "tA": (20260803, args.rows, args.chunks + 2),
        "tB": (20260810, max(args.rows // 3, 256), 2),
        "tC": (20260817, args.rows, args.chunks),
    }

    # solo oracles, computed in-process with durable checkpointing (and
    # any resume request) popped so they neither write stages nor
    # fast-forward from the scheduler runs' stages
    saved = {k: os.environ.pop(k, None)
             for k in ("CYLON_TPU_CKPT_DIR", "CYLON_TPU_RESUME")}
    solo = {name: _result_sha(df_of(*spec))
            for name, spec in specs.items()}
    for k, v in saved.items():
        if v is not None:
            os.environ[k] = v

    def finish(sched, extra) -> int:
        shas, outcomes = {}, sched.stats()["outcomes"]
        for s in sched.sessions:
            if isinstance(s.error, ResumableAbort):
                print(json.dumps({
                    "resumable": True, "token": s.error.token,
                    "session": s.name, "outcomes": outcomes,
                    "failed_typed": outcomes.get("failed_typed", 0),
                    "resize_target": sched.resize_target, **extra}),
                    flush=True)
                return RESUMABLE_EXIT
            if s.error is not None:
                raise s.error
            shas[s.name] = _result_sha(s.result)
        print(json.dumps({
            "ok": True, "shas": shas,
            "bit_equal": all(shas[n] == solo[n] for n in shas),
            "outcomes": outcomes,
            "failed_typed": outcomes.get("failed_typed", 0),
            "preemptions": sched.stats()["preemptions"],
            "requeues": sched.stats()["requeues"],
            "resize_target": sched.resize_target,
            **checkpoint.stats(), **extra}), flush=True)
        return 0

    if case == "preempt":
        sched = QueryScheduler(env, policy="priority", max_concurrency=1)
        runs = {"n": 0}
        fnA = lambda: df_of(*specs["tA"])  # noqa: E731

        def tA():
            runs["n"] += 1
            if runs["n"] == 1:
                # the high-priority arrival lands MID-TRAFFIC: tA's own
                # first slice submits it
                sched.submit("tB", lambda: df_of(*specs["tB"]),
                             priority=5)
            return fnA()

        sched.submit("tA", tA)
        sched.submit("tC", lambda: df_of(*specs["tC"]))
        sched.run()
        return finish(sched, {"case": case})

    if case == "resize":
        target = int(os.environ.get("CYLON_TPU_FLEET_TARGET", "0") or 0)
        fleet = (ResizeController(env, target_world=target,
                                  queue_depth_high=2)
                 if target > 0 else None)
        sched = QueryScheduler(env, policy="fair", max_concurrency=1,
                               fleet=fleet)
        for name in ("tA", "tB", "tC"):
            sched.submit(name, lambda n=name: df_of(*specs[n]))
        sched.run()
        return finish(sched, {"case": case, "world": args.world})

    if case == "deadline":
        sched = QueryScheduler(env, policy="fifo", max_concurrency=1)
        sched.submit("tA", lambda: df_of(*specs["tA"]))
        sched.submit("tB", lambda: df_of(*specs["tB"]))
        sched.run()
        a = sched.sessions[0]
        b = sched.sessions[1]
        outcomes = sched.stats()["outcomes"]
        print(json.dumps({
            "ok": a.state == "done" and b.state == "failed",
            "timeout_typed": isinstance(b.error, AdmissionTimeoutError),
            "tA_bit_equal": (a.result is not None
                             and _result_sha(a.result) == solo["tA"]),
            "outcomes": outcomes,
            "admission_timeouts": sched.stats()["admission_timeouts"],
            "case": case}), flush=True)
        return 0

    print(json.dumps({"ok": False,
                      "error": f"unknown fleet case {case!r}"}))
    return 1


# ---------------------------------------------------------------------------
# parent: schedule generation + child supervision
# ---------------------------------------------------------------------------

def _draw_schedule(rng) -> dict:
    n = 1 + int(rng.random() < 0.4)
    entries, resume_entries = [], []
    have_capacity = False
    for _ in range(n):
        site, kinds = SITE_KINDS[int(rng.integers(0, len(SITE_KINDS)))]
        kind = kinds[int(rng.integers(0, len(kinds)))]
        if kind == "capacity" and have_capacity:
            # the capacity ladder is ONE rung by design (bounded
            # escalation, docs/robustness.md) and a capacity abort is
            # not resumable — a schedule with two capacity faults is
            # unconvergeable by construction, like the excluded
            # stall/desync kinds; redraw the kind (or drop the entry
            # where capacity is the site's only kind)
            others = [k for k in kinds if k != "capacity"]
            if not others:
                continue
            kind = others[int(rng.integers(0, len(others)))]
        have_capacity = have_capacity or kind == "capacity"
        nth = int(rng.integers(1, 3))
        entry = f"{site}::{nth}={kind}"
        if site == "ckpt.load":
            # ckpt.load only fires while RESUMING (Stage.load_piece) —
            # armed in the primary run it would never trigger and the
            # schedule would silently degenerate to a happy-path run;
            # arm it in the resume leg instead
            resume_entries.append(entry)
        else:
            entries.append(entry)
    if resume_entries and not any(e.endswith("=kill") for e in entries):
        # the resume leg only runs after a hard crash — force one
        entries.append("ckpt.write::2=kill")
    return {"faults": ",".join(entries),
            "resume_faults": ",".join(resume_entries)}


def _pinned_schedules() -> list[dict]:
    return [
        # the acceptance path: SIGKILL mid-range-loop after one piece
        # committed, resume must fast-forward (ffwd > 0, no recompute of
        # the committed piece)
        {"faults": "ckpt.write::2=kill", "resume_faults": "",
         "expect_ffwd": True},
        # a corrupted page among the committed pieces: resume detects
        # the hash mismatch and degrades to recompute — still bit-equal
        {"faults": "ckpt.write::1=corrupt,ckpt.write::3=kill",
         "resume_faults": ""},
        # corruption injected on the LOAD side of the resume itself
        {"faults": "ckpt.write::3=kill",
         "resume_faults": "ckpt.load::1=corrupt"},
        # the overlap escape hatch: kill-and-resume with the
        # phase-overlapped scheduler DISABLED — both dispatch modes must
        # hash-equal the overlap-on baseline, crash and resume included
        {"faults": "ckpt.write::2=kill", "resume_faults": "",
         "expect_ffwd": True,
         "env": {"CYLON_TPU_PACKED_OVERLAP": "0"}},
    ]


def _spawn(args, workdir: str, faults: str, resume: bool,
           extra_env: dict | None = None, concurrent: int = 1,
           only: int | None = None, stream: bool = False,
           elastic: bool = False, world: int | None = None,
           skew: bool = False, skew_frac: float = 0.8,
           multislice: bool = False, fleet: bool = False,
           compile_flow: bool = False) -> tuple:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never touch a TPU tunnel
    env.pop("CYLON_TPU_PREEMPT_GRACE_S", None)  # armed per-leg only
    # the out-of-core caps and the topology declaration are armed
    # per-leg too (extra_env) — an inherited budget/slice map would
    # cap or re-route the baseline legs
    for k in ("CYLON_TPU_HBM_BUDGET", "CYLON_TPU_HOST_BUDGET",
              "CYLON_TPU_SPILL_DIR", "CYLON_TPU_SLICES",
              "CYLON_TPU_TOPO_SHUFFLE", "CYLON_TPU_FLEET_CASE",
              "CYLON_TPU_FLEET_TARGET", "CYLON_TPU_ADMISSION_TIMEOUT_S",
              "CYLON_TPU_COMPILE_CACHE_DIR", "CYLON_TPU_COMPILE_TIMEOUT_S",
              "CYLON_TPU_COMPILE_BUDGET", "CYLON_TPU_AUDIT"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["CYLON_TPU_FAULTS"] = faults
    env["CYLON_TPU_CKPT_DIR"] = workdir
    # arm the flight recorder (cylon_tpu/obs/trace): a killed or drained
    # child leaves TRACE_POSTMORTEM.json next to its manifests — the
    # crash breadcrumb the schedules assert below
    env["CYLON_TPU_TRACE"] = os.path.join(workdir, "trace.json")
    env.update(extra_env or {})
    if resume:
        env["CYLON_TPU_RESUME"] = "1"
    else:
        env.pop("CYLON_TPU_RESUME", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           f"--rows={args.rows}", f"--chunks={args.chunks}",
           f"--concurrent={concurrent}", f"--world={world or 4}"]
    if only is not None:
        cmd.append(f"--only={only}")
    if stream:
        cmd.append("--stream")
    if elastic:
        cmd.append("--elastic")
    if skew:
        cmd += ["--skew", f"--skew-frac={skew_frac}"]
    if multislice:
        cmd.append("--multislice")
    if fleet:
        cmd.append("--fleet")
    if compile_flow:
        cmd.append("--compile")
    p = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=600)
    info = None
    for line in reversed(p.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                info = json.loads(line)
            except ValueError:
                pass
            break
    return p, info


def _run_schedule(args, idx: int, sched: dict, baseline_sha: str,
                  failures: list) -> None:
    workdir = tempfile.mkdtemp(prefix=f"soak{idx:02d}_", dir=args.workdir)

    def fail(msg, proc=None):
        tail = ("\n" + (proc.stdout + proc.stderr)[-2000:]) if proc else ""
        failures.append(f"schedule {idx} ({sched['faults']!r}): {msg}{tail}")

    p, info = _spawn(args, workdir, sched["faults"], resume=False,
                     extra_env=sched.get("env"))
    outcome = "ok"
    if p.returncode == 0:
        if not info or info.get("sha") != baseline_sha:
            fail(f"completed but result diverged: {info}", p)
        elif info["events"] > MAX_RECOVERY_EVENTS:
            fail(f"unbounded retries: {info['events']} recovery events", p)
    elif p.returncode == -9 or p.returncode == RESUMABLE_EXIT:
        outcome = "killed" if p.returncode == -9 else "resumable"
        if not os.path.exists(os.path.join(workdir,
                                           "TRACE_POSTMORTEM.json")):
            # the injected kill dumps the flight recorder BEFORE the
            # SIGKILL; a ResumableAbort dumps at its flush — either way
            # the breadcrumb must land next to the manifests
            fail("no TRACE_POSTMORTEM.json breadcrumb after kill/abort", p)
        p2, info2 = _spawn(args, workdir, sched.get("resume_faults", ""),
                           resume=True, extra_env=sched.get("env"))
        if p2.returncode != 0:
            fail(f"resume run failed rc={p2.returncode}", p2)
        elif not info2 or info2.get("sha") != baseline_sha:
            fail(f"resumed result diverged: {info2}", p2)
        elif info2["events"] > MAX_RECOVERY_EVENTS:
            fail(f"unbounded retries on resume: {info2['events']}", p2)
        elif sched.get("expect_ffwd") \
                and not info2.get("resume_fast_forwarded_pieces"):
            fail(f"resume recomputed committed pieces: {info2}", p2)
        else:
            outcome += (f"+resumed(ffwd="
                        f"{info2.get('resume_fast_forwarded_pieces')})")
    else:
        fail(f"unexpected exit rc={p.returncode}", p)
    rf = sched.get("resume_faults", "")
    print(f"# schedule {idx:02d} faults={sched['faults']!r}"
          + (f" resume_faults={rf!r}" if rf else "")
          + f" -> {outcome}", flush=True)
    shutil.rmtree(workdir, ignore_errors=True)


def run_concurrent(args) -> int:
    """The ``--concurrent K`` acceptance flow: K serving sessions on one
    mesh, a mid-query SIGKILL targeted at tenant t0 (``@session``
    grammar), and a resumed rerun that must (a) fast-forward t0 past its
    committed pieces (ffwd > 0) and (b) leave EVERY tenant's answer
    bit-equal to its solo (single-session) run — crash isolation under
    multi-tenancy, not just under a single query."""
    K = args.concurrent
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_conc_")
    failures: list = []

    # solo legs: each tenant alone on the mesh — the bit-equality oracle
    solo_shas: dict = {}
    for i in range(K):
        p, info = _spawn(args, os.path.join(args.workdir, f"solo{i}"),
                         "", resume=False, concurrent=K, only=i)
        if p.returncode != 0 or not info or not info.get("shas"):
            print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
            print(f"chaos-soak: solo leg t{i} failed", file=sys.stderr)
            return 1
        solo_shas.update(info["shas"])
    print(f"# concurrent acceptance: {K} tenants, solo shas "
          f"{ {k: v[:12] for k, v in solo_shas.items()} }", flush=True)

    # un-injected concurrent run: interleaving alone must not change
    # any tenant's answer
    ckdir = os.path.join(args.workdir, "conc")
    p, info = _spawn(args, ckdir, "", resume=False, concurrent=K)
    if p.returncode != 0 or not info or info.get("shas") != solo_shas:
        failures.append(f"un-injected concurrent run diverged: {info}")

    # the pinned kill schedule: SIGKILL mid-query in tenant t0 after its
    # 2nd committed piece; every tenant dies with the process
    killdir = os.path.join(args.workdir, "kill")
    p, info = _spawn(args, killdir, "ckpt.write::2=kill@t0",
                     resume=False, concurrent=K)
    if p.returncode not in (-9, RESUMABLE_EXIT):
        failures.append(
            f"targeted kill did not crash the process (rc={p.returncode})")
    else:
        p2, info2 = _spawn(args, killdir, "", resume=True, concurrent=K)
        if p2.returncode != 0 or not info2:
            failures.append(f"concurrent resume failed rc={p2.returncode}:"
                            f" {(p2.stdout + p2.stderr)[-2000:]}")
        elif info2.get("shas") != solo_shas:
            failures.append(f"resumed concurrent result diverged: {info2}")
        elif not info2.get("resume_fast_forwarded_pieces"):
            failures.append(
                f"resume recomputed t0's committed pieces: {info2}")
        else:
            print(f"# kill@t0 + resume -> ok (ffwd="
                  f"{info2['resume_fast_forwarded_pieces']})", flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"concurrent": K, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def run_fleet(args) -> int:
    """The ``--fleet`` acceptance flow (docs/serving.md): four pinned
    legs proving fleet survival under live traffic — (1) a priority
    arrival preempts a running tenant which requeues and finishes
    bit-equal with ffwd > 0; (2) SIGKILL *during* the preemption drain
    (the new ``sched.preempt`` injector site) → relaunch resumes every
    tenant bit-equal; (3) elastic mesh resize world 4→2 mid-traffic
    with ZERO failed tenants (``failed_typed == 0``, every tenant
    bit-equal to its solo run after the cross-world resume); (4) the
    admission-deadline leg surfaces a typed AdmissionTimeoutError, not
    a hang."""
    own_workdir = args.workdir is None
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_fleet_")
    failures: list = []

    def fail(msg, p=None):
        if p is not None:
            print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        failures.append(msg)
        print(f"# FAIL: {msg}", flush=True)

    # -- leg 1: preempt -> requeue -> resume, bit-equal ------------------
    d1 = os.path.join(args.workdir, "preempt")
    p, info = _spawn(args, d1, "", resume=False, fleet=True,
                     extra_env={"CYLON_TPU_FLEET_CASE": "preempt"})
    if p.returncode != 0 or not info or not info.get("ok"):
        fail(f"preempt leg rc={p.returncode}: {info}", p)
    elif (not info.get("bit_equal")
          or info.get("preemptions", 0) < 1
          or not info.get("resume_fast_forwarded_pieces")):
        fail(f"preempt leg: expected bit-equal requeue with ffwd>0, "
             f"got {info}")
    else:
        print(f"# preempt leg -> ok (preemptions="
              f"{info['preemptions']}, ffwd="
              f"{info['resume_fast_forwarded_pieces']})", flush=True)

    # -- leg 2: SIGKILL during the preemption drain ----------------------
    d2 = os.path.join(args.workdir, "killdrain")
    p, info = _spawn(args, d2, "sched.preempt::1=kill@tA", resume=False,
                     fleet=True,
                     extra_env={"CYLON_TPU_FLEET_CASE": "preempt"})
    if p.returncode != -9:
        fail(f"kill during preemption drain did not crash the process "
             f"(rc={p.returncode})", p)
    else:
        p2, info2 = _spawn(args, d2, "", resume=True, fleet=True,
                           extra_env={"CYLON_TPU_FLEET_CASE": "preempt"})
        if p2.returncode != 0 or not info2 or not info2.get("ok"):
            fail(f"killdrain resume rc={p2.returncode}: {info2}", p2)
        elif (not info2.get("bit_equal")
              or not info2.get("resume_fast_forwarded_pieces")):
            fail(f"killdrain resume diverged or recomputed: {info2}")
        else:
            print(f"# kill@drain + resume -> ok (ffwd="
                  f"{info2['resume_fast_forwarded_pieces']})", flush=True)

    # -- leg 3: elastic mesh resize world 4 -> 2, zero failed tenants ----
    d3 = os.path.join(args.workdir, "resize")
    p, info = _spawn(args, d3, "", resume=False, fleet=True, world=4,
                     extra_env={"CYLON_TPU_FLEET_CASE": "resize",
                                "CYLON_TPU_FLEET_TARGET": "2"})
    if p.returncode != RESUMABLE_EXIT or not info \
            or not info.get("resumable"):
        fail(f"resize leg did not drain resumably rc={p.returncode}: "
             f"{info}", p)
    elif info.get("failed_typed"):
        fail(f"resize drain failed tenants typed: {info}")
    elif info.get("resize_target") != 2:
        fail(f"resize drain carried wrong target: {info}")
    else:
        p2, info2 = _spawn(args, d3, "", resume=True, fleet=True,
                           world=2,
                           extra_env={"CYLON_TPU_FLEET_CASE": "resize"})
        if p2.returncode != 0 or not info2 or not info2.get("ok"):
            fail(f"resize resume rc={p2.returncode}: {info2}", p2)
        elif not info2.get("bit_equal") or info2.get("failed_typed"):
            fail(f"resize resume diverged or failed tenants: {info2}")
        elif not info2.get("resume_world_mismatch"):
            fail(f"resize resume never took the cross-world reshard "
                 f"path: {info2}")
        else:
            print(f"# resize 4->2 + resume -> ok (world_mismatch="
                  f"{info2['resume_world_mismatch']}, ffwd="
                  f"{info2.get('resume_fast_forwarded_pieces', 0)})",
                  flush=True)

    # -- leg 4: admission deadline is typed, not a hang ------------------
    d4 = os.path.join(args.workdir, "deadline")
    p, info = _spawn(args, d4, "", resume=False, fleet=True,
                     extra_env={"CYLON_TPU_FLEET_CASE": "deadline",
                                "CYLON_TPU_ADMISSION_TIMEOUT_S": "0.3"})
    if p.returncode != 0 or not info or not info.get("ok"):
        fail(f"deadline leg rc={p.returncode}: {info}", p)
    elif not info.get("timeout_typed") or not info.get("tA_bit_equal"):
        fail(f"deadline leg: expected typed AdmissionTimeoutError with "
             f"tA unharmed, got {info}")
    else:
        print(f"# admission deadline -> ok (typed, "
              f"timeouts={info['admission_timeouts']})", flush=True)

    if own_workdir:
        shutil.rmtree(args.workdir, ignore_errors=True)
    print(json.dumps({"fleet_legs": 4, "failures": len(failures),
                      "detail": failures[:10]}))
    return 1 if failures else 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--schedules", type=int, default=20)
    ap.add_argument("--rows", type=int, default=3000)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--concurrent", type=int, default=1,
                    help="K>1: run the K-tenant concurrent acceptance "
                         "flow (kill one tenant mid-query, resume, "
                         "assert every tenant bit-equal to its solo run)")
    ap.add_argument("--only", type=int, default=None,
                    help="(worker) restrict the concurrent scheduler to "
                         "one tenant — the solo bit-equality leg")
    ap.add_argument("--oocore", action="store_true",
                    help="run the out-of-core acceptance flow (HBM+host "
                         "budget caps force the disk tier; enospc/"
                         "corrupt/kill schedules must end bit-equal, "
                         "and the unarmed leg must write nothing)")
    ap.add_argument("--stream", action="store_true",
                    help="run the streaming-ingest acceptance flow "
                         "(SIGKILL mid-ingest with checkpointing armed; "
                         "resume must fast-forward committed window "
                         "state and stay bit-equal)")
    ap.add_argument("--elastic", action="store_true",
                    help="run the elastic-resume acceptance flow "
                         "(checkpoint at world=2, SIGKILL/SIGTERM "
                         "mid-run, resume at world=1 and at world=2-"
                         "after-reshard; every schedule must end "
                         "bit-equal to the uninterrupted baseline)")
    ap.add_argument("--skew", action="store_true",
                    help="run the adaptive-skew-split acceptance flow "
                         "(faults inside a skew-split join must recover "
                         "onto the SAME voted plan, bit-equal to the "
                         "unsplit baseline; the armed-at-skew-0 leg "
                         "must add zero collectives)")
    ap.add_argument("--skew-frac", type=float, default=0.8,
                    help="(worker) fraction of probe rows on the hot key")
    ap.add_argument("--compile", dest="compile_flow",
                    action="store_true",
                    help="run the compile-lifecycle acceptance flow "
                         "(SIGKILL mid-compile leaves an intent journal "
                         "the rerun adopts into the crash quarantine; "
                         "poisoned manifest entries drop to a clean "
                         "recompile; stalls surface typed via the "
                         "watchdog; the unarmed leg writes nothing)")
    ap.add_argument("--multislice", action="store_true",
                    help="run the multi-slice topology acceptance flow "
                         "(simulated two-tier grid: hierarchical route "
                         "bit-equal to flat with a voted plan and ~1/R "
                         "DCN messages; whole-slice kill resumes via "
                         "elastic reshard; unarmed single-slice leg "
                         "adds zero collectives)")
    ap.add_argument("--audit", action="store_true",
                    help="run the data-integrity audit acceptance flow "
                         "(armed silent-corruption drill caught as a "
                         "typed DataIntegrityError and recomputed "
                         "bit-equal on the flat, skew-split and "
                         "two-tier routes; persistent corruption "
                         "aborts typed; the unarmed leg does zero "
                         "fingerprint work)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the fleet-survival acceptance flow "
                         "(preemptive drain/requeue with in-process "
                         "resume, SIGKILL during a preemption drain, "
                         "elastic mesh resize 4->2 mid-traffic with "
                         "zero failed tenants, typed admission "
                         "deadline)")
    ap.add_argument("--world", type=int, default=4,
                    help="(worker) mesh world size for this process")
    args = ap.parse_args()

    if args.worker:
        sys.path.insert(0, REPO)
        return worker(args)

    if args.oocore:
        return run_oocore(args)

    if args.skew:
        return run_skew(args)

    if args.audit:
        return run_audit(args)

    if args.multislice:
        return run_multislice(args)

    if args.stream:
        return run_stream(args)

    if args.compile_flow:
        return run_compile(args)

    if args.elastic:
        return run_elastic(args)

    if args.fleet:
        return run_fleet(args)

    if args.concurrent > 1:
        return run_concurrent(args)

    import numpy as np
    rng = np.random.default_rng(args.seed)
    args.workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_soak_")

    schedules = _pinned_schedules()
    while len(schedules) < args.schedules:
        schedules.append(_draw_schedule(rng))
    schedules = schedules[:args.schedules]

    # un-injected, un-checkpointed baseline: the bit-equality oracle
    p, info = _spawn(args, os.path.join(args.workdir, "baseline"), "",
                     resume=False)
    if p.returncode != 0 or not info or not info.get("sha"):
        print((p.stdout + p.stderr)[-3000:], file=sys.stderr)
        print("chaos-soak: baseline run failed", file=sys.stderr)
        return 1
    baseline_sha = info["sha"]
    print(f"# baseline sha={baseline_sha[:16]} rows={info['rows']}",
          flush=True)

    failures: list = []
    for i, sched in enumerate(schedules):
        _run_schedule(args, i, sched, baseline_sha, failures)

    print(json.dumps({"schedules": len(schedules),
                      "failures": len(failures), "seed": args.seed,
                      "detail": failures[:10]}))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
