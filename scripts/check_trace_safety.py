#!/usr/bin/env python
"""Trace-safety / SPMD-hazard lint gate (CI entry point).

Usage:
    python scripts/check_trace_safety.py [paths...]      # AST + CX passes
    python scripts/check_trace_safety.py --strict        # + jaxpr pass
    python scripts/check_trace_safety.py --list-rules
    python scripts/check_trace_safety.py --audit-suppressions
    python scripts/check_trace_safety.py --json out.json

Stages (see docs/trace_safety.md for the rule catalog):

1. **AST lint** (TS1xx) — per-file source hazards, jax-free.
2. **Collective coherence** (CX4xx) — interprocedural call-graph +
   taint/dominance pass over the whole tree: rank-local control flow
   between collectives, path-dependent collective sequences, plan-vote
   dominance, untyped post-collective raises.
3. **jaxpr verification** (JX2xx, ``--strict``/``--jaxpr`` only) —
   traces every registered builder over a virtual 8-device CPU mesh.
   Tracing only, nothing compiles.

The jax-free stages are cached under ``.tracecheck_cache/`` keyed on
content hashes of the analyzed files AND the analyzer modules, so a
warm re-run skips every unchanged file (``--no-cache`` bypasses).

``--audit-suppressions`` reports stale ``# tracecheck: off[...]``
comments whose rules no longer fire on the covered lines; ``--strict``
warns about them on stderr and ``--fail-stale-suppressions`` turns them
into a gate failure.  ``--json FILE`` emits every finding (suppressed
ones included, flagged) for CI diffing.

Exit status: 0 when no unsuppressed findings, 1 when any rule fires
(each printed as ``file:line: RULE message``), 2 on usage errors.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".tracecheck_cache")
CACHE_VERSION = 1


# --------------------------------------------------------------------------
# cache plumbing

def _analyzer_hash() -> str:
    """Content hash of the analyzer modules — any rule change invalidates
    every cache entry."""
    import cylon_tpu.analysis as pkg
    base = os.path.dirname(os.path.abspath(pkg.__file__))
    h = hashlib.sha256(str(CACHE_VERSION).encode())
    for name in ("rules.py", "ast_lint.py", "coherence.py"):
        try:
            with open(os.path.join(base, name), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"missing:" + name.encode())
    return h.hexdigest()


def _load_cache(name: str, analyzer_hash: str) -> dict:
    try:
        with open(os.path.join(CACHE_DIR, name), encoding="utf-8") as f:
            data = json.load(f)
        if data.get("analyzer") == analyzer_hash:
            return data
    except (OSError, ValueError):
        pass
    return {"analyzer": analyzer_hash}


def _store_cache(name: str, data: dict) -> None:
    try:
        os.makedirs(CACHE_DIR, exist_ok=True)
        tmp = os.path.join(CACHE_DIR, name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, os.path.join(CACHE_DIR, name))
    except OSError:
        pass          # cache is best-effort; the gate still ran


def _finding_to_list(f):
    return [f.rule, f.path, f.line, f.message]


def _finding_from_list(item):
    from cylon_tpu.analysis.rules import Finding
    return Finding(item[0], item[1], item[2], item[3])


# --------------------------------------------------------------------------
# stages

def _ast_stage(files: dict[str, str], analyzer_hash: str, use_cache: bool):
    """Per-file AST lint with content-hash skipping.  Returns
    ``(kept, raw, spans_by_file, n_cached)``."""
    from cylon_tpu.analysis import ast_lint
    from cylon_tpu.analysis.rules import (file_suppressed, is_suppressed,
                                          suppressions)
    cache = _load_cache("ast.json", analyzer_hash) if use_cache else {}
    entries = cache.setdefault("files", {})
    kept, raw, spans_by_file, n_cached = [], [], {}, 0
    for path, source in sorted(files.items()):
        sha = hashlib.sha256(source.encode()).hexdigest()
        ent = entries.get(path)
        if ent is not None and ent.get("sha") == sha:
            n_cached += 1
        else:
            file_raw, spans = ast_lint.lint_source_raw(path, source)
            if file_suppressed(source):
                file_kept = []
            else:
                sup = suppressions(source)
                file_kept = [
                    f for f in file_raw if not is_suppressed(
                        f, sup, ast_lint.enclosing_def_lines(spans, f.line))]
            ent = {"sha": sha,
                   "kept": [_finding_to_list(f) for f in file_kept],
                   "raw": [_finding_to_list(f) for f in file_raw],
                   "spans": spans}
            entries[path] = ent
        kept.extend(_finding_from_list(i) for i in ent["kept"])
        raw.extend(_finding_from_list(i) for i in ent["raw"])
        spans_by_file[path] = [tuple(s) for s in ent["spans"]]
    if use_cache:
        _store_cache("ast.json", cache)
    return kept, raw, spans_by_file, n_cached


def _cx_stage(files: dict[str, str], analyzer_hash: str, use_cache: bool):
    """Whole-tree coherence pass.  The call graph is interprocedural, so
    the cache key is the hash of EVERY analyzed file: any change reruns
    the pass, no change skips it entirely."""
    from cylon_tpu.analysis import coherence
    h = hashlib.sha256()
    for path, source in sorted(files.items()):
        h.update(path.encode())
        h.update(hashlib.sha256(source.encode()).digest())
    tree_sha = h.hexdigest()
    cache = _load_cache("cx.json", analyzer_hash) if use_cache else {}
    trees = cache.setdefault("trees", {})
    ent = trees.get(tree_sha)
    if ent is not None:
        return ([_finding_from_list(i) for i in ent["kept"]],
                [_finding_from_list(i) for i in ent["raw"]],
                ent["vote_summary"], True)
    report = coherence.analyze_files(files)
    if use_cache:
        # a handful of path-sets at most (default tree, fixture dirs)
        while len(trees) >= 8:
            trees.pop(next(iter(trees)))
        trees[tree_sha] = {
            "kept": [_finding_to_list(f) for f in report.findings],
            "raw": [_finding_to_list(f) for f in report.raw],
            "vote_summary": report.vote_summary}
        _store_cache("cx.json", cache)
    return report.findings, report.raw, report.vote_summary, False


def _audit_suppressions(files: dict[str, str], raw, spans_by_file):
    """Dead-suppression report: every ``# tracecheck: off[...]`` comment
    none of whose rules fires (pre-suppression) on the lines it covers.
    Returns ``[(path, line, rules-or-None), ...]``."""
    from cylon_tpu.analysis.rules import suppressions
    raw_by_file = {}
    for f in raw:
        raw_by_file.setdefault(f.path, []).append(f)
    dead = []
    for path, source in sorted(files.items()):
        sup = suppressions(source)
        if not sup:
            continue
        file_raw = raw_by_file.get(path, [])
        spans = spans_by_file.get(path, [])
        n_lines = source.count("\n") + 1
        for line, rules in sorted(sup.items()):
            covered = {line}
            for s, e in spans:
                if s == line:                 # comment on the def line
                    covered.update(range(s, e + 1))
            if rules is None and line <= 5:   # file-level off
                covered.update(range(1, n_lines + 1))
            live = any(f.line in covered
                       and (rules is None or f.rule in rules)
                       for f in file_raw)
            if not live:
                dead.append((path, line, rules))
    return dead


# --------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "cylon_tpu")],
                    help="files/directories to lint (default: cylon_tpu/)")
    ap.add_argument("--strict", action="store_true",
                    help="also run the jaxpr verification pass over every "
                         "registered builder")
    ap.add_argument("--jaxpr", action="store_true",
                    help="run only the jaxpr pass (skip the AST/CX stages)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write .tracecheck_cache/")
    ap.add_argument("--json", metavar="FILE", dest="json_out",
                    help="write every finding (suppressed included, "
                         "flagged) as JSON for CI diffing")
    ap.add_argument("--audit-suppressions", action="store_true",
                    help="report stale tracecheck suppression comments "
                         "and exit (no gate verdict)")
    ap.add_argument("--fail-stale-suppressions", action="store_true",
                    help="fail the gate when a stale suppression is found")
    args = ap.parse_args(argv)

    # rules import is jax-free; keep the lint-only path light
    from cylon_tpu.analysis.rules import RULES

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    findings, raw, dead = [], [], []
    use_cache = not args.no_cache
    if not args.jaxpr:
        from cylon_tpu.analysis.coherence import iter_py_files
        files = {}
        for path in iter_py_files(args.paths):
            with open(path, encoding="utf-8") as f:
                files[path] = f.read()
        ah = _analyzer_hash()

        ast_kept, ast_raw, spans_by_file, n_cached = _ast_stage(
            files, ah, use_cache)
        print(f"ast lint: {len(files)} files "
              f"({n_cached} cached)", file=sys.stderr)

        cx_kept, cx_raw, vote_summary, cx_cached = _cx_stage(
            files, ah, use_cache)
        votes = ", ".join(f"{k}={len(v)}"
                          for k, v in sorted(vote_summary.items()))
        print(f"coherence pass: {'cached' if cx_cached else 'ran'}; "
              f"dominating vote sites: {votes}", file=sys.stderr)

        findings += ast_kept + cx_kept
        raw += ast_raw + cx_raw
        dead = _audit_suppressions(files, raw, spans_by_file)
        if args.audit_suppressions:
            for path, line, rules in dead:
                what = "all rules" if rules is None \
                    else ",".join(sorted(rules))
                print(f"{path}:{line}: stale suppression ({what} — "
                      f"nothing fires on the covered lines)")
            print(f"suppression audit: {len(dead)} stale"
                  if dead else "suppression audit: clean",
                  file=sys.stderr)
            return 0
        for path, line, rules in dead:
            what = "all rules" if rules is None else ",".join(sorted(rules))
            print(f"warning: stale suppression at {path}:{line} ({what})",
                  file=sys.stderr)

    if args.strict or args.jaxpr:
        # the jaxpr pass needs a mesh: force the virtual 8-device CPU rig
        # BEFORE jax initializes a backend
        flag = "--xla_force_host_platform_device_count=8"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
        import cylon_tpu as ct
        from cylon_tpu.ctx.context import CPUMeshConfig
        from cylon_tpu.analysis import jaxpr_check, registry
        env = ct.CylonEnv(config=CPUMeshConfig())
        decls = registry.collect()
        if not decls:
            print("error: no builders registered for the jaxpr pass",
                  file=sys.stderr)
            return 2
        jx = jaxpr_check.verify_all(env.mesh, decls)
        findings.extend(jx)
        raw.extend(jx)
        checked = ", ".join(sorted({t for d in decls for t in d.tags}))
        print(f"jaxpr pass: {len(decls)} builders verified ({checked})",
              file=sys.stderr)

    if args.json_out:
        kept_keys = {(f.rule, f.path, f.line, f.message) for f in findings}
        payload = {
            "version": 1,
            "findings": [
                {"rule": f.rule, "file": f.path, "line": f.line,
                 "message": f.message,
                 "suppressed": (f.rule, f.path, f.line, f.message)
                 not in kept_keys}
                for f in raw],
            "stale_suppressions": [
                {"file": p, "line": ln,
                 "rules": sorted(r) if r is not None else None}
                for p, ln, r in dead],
            "counts": {},
        }
        for f in findings:
            payload["counts"][f.rule] = payload["counts"].get(f.rule, 0) + 1
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        print(f"wrote {args.json_out}", file=sys.stderr)

    for f in findings:
        print(f)
    if args.fail_stale_suppressions and dead:
        print(f"\n{len(dead)} stale suppression(s)", file=sys.stderr)
        return 1
    if findings:
        counts = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}x{n}" for r, n in sorted(counts.items()))
        print(f"\n{len(findings)} finding(s): {summary}", file=sys.stderr)
        return 1
    print("trace-safety: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
