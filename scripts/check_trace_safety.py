#!/usr/bin/env python
"""Trace-safety / SPMD-hazard lint gate (CI entry point).

Usage:
    python scripts/check_trace_safety.py [paths...]      # AST lint only
    python scripts/check_trace_safety.py --strict        # lint + jaxpr pass
    python scripts/check_trace_safety.py --list-rules

Exit status: 0 when no findings, 1 when any rule fires (each printed as
``file:line: RULE message``), 2 on usage errors.  ``--strict`` addition-
ally traces every registered program builder over a virtual 8-device CPU
mesh and verifies the jaxpr-level SPMD invariants (JX2xx) — tracing
only, nothing compiles, so the gate stays fast enough to run before
every test session (see ROADMAP.md tier-1 recipe).

Rule catalog + suppression syntax: docs/trace_safety.md.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "cylon_tpu")],
                    help="files/directories to lint (default: cylon_tpu/)")
    ap.add_argument("--strict", action="store_true",
                    help="also run the jaxpr verification pass over every "
                         "registered builder")
    ap.add_argument("--jaxpr", action="store_true",
                    help="run only the jaxpr pass (skip the AST lint)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    # rules import is jax-free; keep the lint-only path light
    from cylon_tpu.analysis.rules import RULES

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    findings = []
    if not args.jaxpr:
        from cylon_tpu.analysis.ast_lint import lint_paths
        findings.extend(lint_paths(args.paths))

    if args.strict or args.jaxpr:
        # the jaxpr pass needs a mesh: force the virtual 8-device CPU rig
        # BEFORE jax initializes a backend
        flag = "--xla_force_host_platform_device_count=8"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
        import cylon_tpu as ct
        from cylon_tpu.ctx.context import CPUMeshConfig
        from cylon_tpu.analysis import jaxpr_check, registry
        env = ct.CylonEnv(config=CPUMeshConfig())
        decls = registry.collect()
        if not decls:
            print("error: no builders registered for the jaxpr pass",
                  file=sys.stderr)
            return 2
        findings.extend(jaxpr_check.verify_all(env.mesh, decls))
        checked = ", ".join(sorted({t for d in decls for t in d.tags}))
        print(f"jaxpr pass: {len(decls)} builders verified ({checked})",
              file=sys.stderr)

    for f in findings:
        print(f)
    if findings:
        counts = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}x{n}" for r, n in sorted(counts.items()))
        print(f"\n{len(findings)} finding(s): {summary}", file=sys.stderr)
        return 1
    print("trace-safety: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
