"""Benchmark driver: distributed join + groupby throughput.

The BASELINE.json north-star workload: inner merge on random int64 keys
followed by groupby-sum, measured as rows/sec/chip.  Runs on every visible
accelerator chip (or a virtual CPU mesh when no accelerator is present).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/sec/chip", "vs_baseline": N}

vs_baseline anchors to the reference's published weak-scaling join number
(BASELINE.md: 1M rows/rank at 0.60 s/iter on Summit, 42 ranks/node =>
~1.67M rows/sec/rank for join alone; we use the same per-worker rows/sec
denominator for the join+groupby pipeline).
"""

from __future__ import annotations

import json
import os
import sys
import time

# allow virtual-device fallback before jax import
if "--cpu-mesh" in sys.argv:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

#: reference anchor: Summit weak scaling, 1M rows/rank/iter at 0.60 s
#: (BASELINE.md summit results-1000000) => rows/sec/worker
BASELINE_ROWS_PER_SEC_PER_WORKER = 1_000_000 / 0.60


def run(rows_per_chip: int = 2_000_000, n_keys_frac: float = 0.5,
        iters: int = 5) -> dict:
    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
    from cylon_tpu.relational import groupby_aggregate, join_tables

    devs = jax.devices()
    on_accel = devs[0].platform != "cpu"
    cfg = TPUConfig() if on_accel else CPUMeshConfig()
    env = ct.CylonEnv(config=cfg)
    w = env.world_size

    n = rows_per_chip * w
    n_keys = max(int(n * n_keys_frac), 1)
    rng = np.random.default_rng(42)
    lk = rng.integers(0, n_keys, n).astype(np.int64)
    rk = rng.integers(0, n_keys, n).astype(np.int64)
    lv = rng.random(n)
    rv = rng.random(n)

    lt = ct.Table.from_pydict({"k": lk, "a": lv}, env)
    rt = ct.Table.from_pydict({"k": rk, "b": rv}, env)

    def step():
        j = join_tables(lt, rt, "k", "k", how="inner")
        g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])
        # force completion
        jax.block_until_ready(next(iter(g.columns.values())).data)
        return g

    step()  # warmup + compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    best = min(times)
    # rows processed per iteration = left + right input rows
    rows_per_sec_per_chip = (2 * n) / best / w
    return {
        "metric": "dist join+groupby throughput (int64 keys)",
        "value": round(rows_per_sec_per_chip, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(rows_per_sec_per_chip
                             / BASELINE_ROWS_PER_SEC_PER_WORKER, 3),
        "detail": {
            "world": w,
            "platform": devs[0].platform,
            "rows_per_chip": rows_per_chip,
            "best_iter_s": round(best, 4),
            "all_iters_s": [round(t, 4) for t in times],
        },
    }


if __name__ == "__main__":
    rows = 2_000_000
    for a in sys.argv[1:]:
        if a.startswith("--rows="):
            rows = int(a.split("=", 1)[1])
    res = run(rows_per_chip=rows)
    print(json.dumps(res))
