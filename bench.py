"""Benchmark driver: distributed join + groupby throughput.

The BASELINE.json north-star workload: inner merge on random int64 keys
followed by groupby-sum, measured as rows/sec/chip.  The table shape follows
the reference's scaling driver (rivanna/scripts/cylon_scaling.py:31-37): two
int64 columns per side — a key column and a value column — with keys drawn
from [0, total_rows * 0.9) ("uniqueness factor" u = 0.9), per-rank rows =
rows_per_chip.  Our pipeline additionally groupby-sums the joined values
(BASELINE.json: join+groupby).

Runs on every visible accelerator chip (or a virtual CPU mesh when no
accelerator is present).  Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/sec/chip", "vs_baseline": N}

vs_baseline anchors to the reference's published weak-scaling join number
(BASELINE.md: 1M rows/rank at 0.60 s/iter on Summit, 42 ranks/node =>
~1.67M rows/sec/rank for join alone; we use the same per-worker rows/sec
denominator for the join+groupby pipeline).

Flags: --rows=N (per chip; default 125M on TPU — the BASELINE.json
north-star per-chip share, auto-routed through the range-partitioned
pipeline — 1M on CPU), --unique=F, --iters=K, --cpu-mesh, --tpch (TPC-H
instead, see cylon_tpu.tpch), --slices=S (declare an S-slice two-tier
fabric — exchanges route through the hierarchical two-hop engine and
the detail records per-tier rows/bytes/messages; cylon_tpu/topo,
docs/topology.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

# allow virtual-device fallback before jax import
if "--cpu-mesh" in sys.argv:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"  # hard set: axon presets the var

import jax  # noqa: E402
import numpy as np  # noqa: E402

if "--cpu-mesh" in sys.argv:
    # a TPU plugin may already be registered at interpreter start (axon
    # sitecustomize), which overrides the env var; the config knob still
    # wins while no backend has been initialized (same as tests/conftest)
    jax.config.update("jax_platforms", "cpu")

#: reference anchor: Summit weak scaling, 1M rows/rank/iter at 0.60 s
#: (BASELINE.md summit results-1000000) => rows/sec/worker
BASELINE_ROWS_PER_SEC_PER_WORKER = 1_000_000 / 0.60


def _sync(arr):
    """Force execution and wait (see cylon_tpu.utils.host.sync_pull).
    Under async profiling this is THE iteration-end block — its
    ``bench.output_sync.block`` entry absorbs all device time the
    dispatch-only phase markers enqueued and nothing else pulled."""
    from cylon_tpu.utils import timing
    from cylon_tpu.utils.host import sync_pull
    with timing.sync_region("bench.output_sync"):
        sync_pull(arr)


def run(rows_per_chip: int, unique: float = 0.9, iters: int = 4,
        skew: float = 0.0) -> dict:
    import cylon_tpu as ct
    from cylon_tpu import config, obs
    from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
    from cylon_tpu.exec import checkpoint, memory, recovery
    from cylon_tpu.relational import groupby_aggregate, join_tables
    from cylon_tpu.utils import timing

    if os.environ.get("CYLON_TPU_DISTRIBUTED", "0") == "1":
        # multi-host pod launch (deploy/): form the world first
        cfg = TPUConfig(distributed=True)
    else:
        cfg = TPUConfig() if jax.devices()[0].platform != "cpu" \
            else CPUMeshConfig()
    env = ct.CylonEnv(config=cfg)
    devs = jax.devices()
    w = env.world_size

    n = rows_per_chip * w
    max_val = max(int(n * unique), 1)
    rng = np.random.default_rng(42)
    lk = rng.integers(0, max_val, n).astype(np.int64)
    if skew > 0.0:
        # BASELINE.json config 5 (skewed-key join): a ``skew`` fraction of
        # probe rows share ONE hot key (tests/test_skew.py convention) —
        # exercises the heavy-hitter split path (probe hot keys spread
        # round-robin, build hot rows duplicate-broadcast).  The build side
        # stays uniform so the join output stays ~O(n).
        hot = np.int64(max_val // 2)
        lk = np.where(rng.random(n) < skew, hot, lk)
    lt = ct.Table.from_pydict(
        {"k": lk, "a": rng.integers(0, max_val, n).astype(np.int64)}, env)
    rk = rng.integers(0, max_val, n).astype(np.int64)
    if skew > 0.0:
        # apples-to-apples across skew levels: the hot key appears
        # EXACTLY once on the build side, so every probe row — hot or
        # not — joins ~1 build row and the output stays ~n rows at any
        # skew (a hot key that randomly drew 2+ build rows would double
        # the skewed config's output and poison the throughput ratio)
        rk[rk == hot] = hot + 1
        rk[0] = hot
    rt = ct.Table.from_pydict(
        {"k": rk, "b": rng.integers(0, max_val, n).astype(np.int64)}, env)

    # Route by size: the monolithic fused join+groupby OOMs past ~48M
    # rows/chip in 16 GB HBM; the north-star config (125M rows/chip = 1B
    # rows on v5e-8, BASELINE.json) runs through the range-partitioned
    # pipeline (exec/pipeline.py), whose per-piece working set is 1/R.
    # CYLON_TPU_BENCH_PIPELINE=1 forces the pipelined route at any size —
    # e.g. to demonstrate the HBM-budget spill tier on a CPU rig
    # (CYLON_TPU_HBM_BUDGET below the resident working set makes the
    # detail's spill_events go positive; docs/robustness.md).
    pipelined = (rows_per_chip > 48_000_000
                 or os.environ.get("CYLON_TPU_BENCH_PIPELINE") == "1")
    n_chunks = max(2, -(-rows_per_chip // 21_000_000)) if pipelined else 1

    if pipelined:
        from cylon_tpu.exec import GroupBySink, pipelined_join

        def step():
            sink = GroupBySink("k", [("a", "sum"), ("b", "sum")])
            pipelined_join(lt, rt, "k", "k", how="inner",
                           n_chunks=n_chunks, sink=sink)
            g = sink.finalize()
            _sync(next(iter(g.columns.values())).data)
            return g
    else:
        def step():
            j = join_tables(lt, rt, "k", "k", how="inner")
            g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])
            _sync(next(iter(g.columns.values())).data)
            return g

    # timed iterations run with region timings OFF: timing.maybe_block
    # inserts per-phase device syncs that serialize the pipelined sink's
    # dispatch/pull overlap — the phase profile comes from ONE extra
    # profiled iteration afterwards.  That iteration runs in ASYNC
    # attribution mode (CYLON_TPU_TIMING=async semantics): regions record
    # dispatch-only markers and the step's final output sync is the one
    # block — the phase numbers no longer serialize (or hide) the overlap
    # they are meant to expose.  Set CYLON_TPU_TIMING=block to profile
    # with per-phase device syncs instead (exact attribution, perturbed
    # overlap).
    timing_async = os.environ.get("CYLON_TPU_TIMING", "async") == "async"
    prev_flag = config.BENCH_TIMINGS
    prev_async = config.TIMING_ASYNC
    config.BENCH_TIMINGS = False
    recovery.reset_events()  # detail reports THIS workload's recoveries
    memory.reset_stats()     # ... and THIS workload's spill traffic
    checkpoint.reset_stats()  # ... and THIS workload's checkpoint traffic
    try:
        step()  # warmup + compile
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            step()
            times.append(time.perf_counter() - t0)
        config.BENCH_TIMINGS = True
        config.TIMING_ASYNC = timing_async
        timing.reset()
        t0 = time.perf_counter()
        # profiled (async mode: one block at the final sync), wrapped in
        # the query profiler: the bench JSON carries the EXPLAIN ANALYZE
        # plan tree alongside the phase table it reconciles with
        # (obs/plan, docs/observability.md).  profile_keys=False: the
        # key sampler would add device programs + mid-iteration host
        # pulls, breaking profiled_iter_s comparability with the
        # BENCH_rNN baselines and the one-designated-block async
        # contract above (the --skew heavy-hitter profile below runs
        # OUTSIDE the timed iteration instead)
        qplan = obs.explain_analyze(step, reset_timings=False,
                                    profile_keys=False)
        profiled_s = time.perf_counter() - t0
    finally:
        config.BENCH_TIMINGS = prev_flag
        config.TIMING_ASYNC = prev_async
    best = min(times)
    rows_per_sec_per_chip = (2 * n) / best / w
    # dispatch/block attribution split (utils/timing.split_snapshot):
    # under async profiling every plain region is host time to ENQUEUE
    # its work and every ".block" twin (sync_region — the pipelined
    # join's batched phase pull) is deliberate blocking time.  A phase
    # whose dispatch AND block are both near zero has left the critical
    # path — its device work hides under another phase's block point,
    # which is how piece r+1's overlap with piece r's consume shows up.
    snap = timing.snapshot()
    dispatch_s, block_s = timing.split_snapshot(snap)
    # --slices: the multi-slice topology decision + per-tier traffic
    # (cylon_tpu/topo, docs/topology.md).  The registry counters are
    # process-cumulative; this process ran only this workload, so the
    # snapshot IS the run's traffic.  dcn_rows/bytes are route-invariant
    # payload (each remote row crosses DCN once either way); the
    # two-hop win reads off dcn_messages (~1/R of the flat route's) and,
    # on concentrated count matrices, dcn_wire_bytes.
    topo_detail = {}
    topo_t = env.topology
    if topo_t.n_slices > 1:
        from cylon_tpu.topo import model as topo_model
        tplan = topo_model.last_plan()
        topo_detail = {
            "topology": {"n_slices": topo_t.n_slices,
                         "ranks_per_slice": topo_t.ranks_per_slice,
                         "source": topo_t.source},
            "topo_plan": tplan.summary() if tplan is not None else None,
            "tier_traffic": {
                name: int(obs.counter(f"exchange_{name}_total").value)
                for name in ("ici_rows", "dcn_rows", "ici_bytes",
                             "dcn_bytes", "ici_wire_bytes",
                             "dcn_wire_bytes", "ici_messages",
                             "dcn_messages")},
        }
    # capture the ARMED per-rank report of the (split-armed) profiled
    # iteration BEFORE the unsplit baseline leg below resets the timing
    # accumulators for its own "before" snapshot
    rank_rep = obs.rank_report.report() if obs.rank_report.armed() else None
    # ... and the recovery/spill/checkpoint counters: they were reset to
    # report THIS workload's events, and the unsplit audit leg below can
    # spill/degrade on its own (the hot key concentrates on one rank
    # there) — its events must not read as the measured run's
    bench_counters = obs.bench_detail(plan=qplan)

    # --skew: the adaptive skew-split decision + an UNSPLIT baseline leg
    # (CYLON_TPU_SKEW_SPLIT=0 semantics) on the same config, so the win —
    # and the plan that bought it — are auditable in one BENCH row
    # (docs/skew.md; ISSUE 14 acceptance: skew-0.9 throughput >= 80% of
    # skew-0.0 on the same config).
    skew_detail = {}
    if skew > 0.0:
        from cylon_tpu.relational import skew as skew_facade
        plan_rec = skew_facade.last_plan()
        skew_detail["skew_route"] = ("skew_split" if plan_rec is not None
                                     else "hash")
        skew_detail["skew_plan"] = (plan_rec.summary()
                                    if plan_rec is not None else None)
        if plan_rec is not None:
            skew_detail["skew_split_keys"] = int(len(plan_rec))
            skew_detail["skew_fanout"] = [int(f) for f in plan_rec.fanout]
    # the audit leg only means something when the profiled run actually
    # split — on the pipelined route (plain hashing, no plan) or a
    # detection decline the re-run would compare two identical unsplit
    # executions at full workload cost
    if skew > 0.0 and skew_detail.get("skew_route") == "skew_split":
        prev_split = config.SKEW_SPLIT
        prev_bench2 = config.BENCH_TIMINGS
        config.SKEW_SPLIT = False
        config.BENCH_TIMINGS = False
        try:
            step()  # warmup/compile the unsplit programs
            # min-of-N against min-of-N: `best` is the split run's best
            # of `iters` samples, so the unsplit leg gets the same
            # treatment — a one-shot sample would let ordinary
            # per-iteration jitter inflate the recorded speedup
            un_times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                step()
                un_times.append(time.perf_counter() - t0)
            skew_detail["unsplit_iter_s"] = round(min(un_times), 4)
            skew_detail["unsplit_all_iters_s"] = [round(t, 4)
                                                  for t in un_times]
            skew_detail["split_vs_unsplit_speedup"] = round(
                skew_detail["unsplit_iter_s"] / best, 3)
            if obs.rank_report.armed():
                # the "before" half of the before/after rank-skew pair
                # (the armed main report above is the "after")
                config.BENCH_TIMINGS = True
                config.TIMING_ASYNC = timing_async
                timing.reset()
                step()
                skew_detail["rank_phase_skew_unsplit"] = \
                    obs.rank_report.report()
        finally:
            config.SKEW_SPLIT = prev_split
            config.BENCH_TIMINGS = prev_bench2
            config.TIMING_ASYNC = prev_async
    return {
        "metric": ("dist join+groupby throughput (int64 keys"
                   + (f", skew={skew:g}" if skew else "") + ")"),
        "value": round(rows_per_sec_per_chip, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(rows_per_sec_per_chip
                             / BASELINE_ROWS_PER_SEC_PER_WORKER, 3),
        "detail": {
            "world": w,
            "platform": devs[0].platform,
            "rows_per_chip": rows_per_chip,
            "pipelined": pipelined,
            "n_chunks": n_chunks,
            "unique": unique,
            "skew": skew,
            "best_iter_s": round(best, 4),
            "all_iters_s": [round(t, 4) for t in times],
            "timing_mode": "async" if timing_async else "block",
            "profiled_iter_s": round(profiled_s, 4),
            # dispatch-path config: which of the three ISSUE-6 rungs were
            # active for this number (escape hatches: CYLON_TPU_PACKED_*,
            # CYLON_TPU_DONATE, CYLON_TPU_PALLAS_PROBE)
            "packed_pieces": config.PACKED_PIECES,
            "packed_overlap": config.PACKED_OVERLAP,
            "donate_buffers": config.DONATE_BUFFERS,
            "pallas_probe": config.PALLAS_PROBE,
            "phases_s": {k: v["s"] for k, v in snap.items()},
            "phases_dispatch_s": dispatch_s,
            "phases_block_s": block_s,
            # per-rank min/median/max phase skew (obs/rank_report,
            # CYLON_TPU_RANK_REPORT=1): the measurement rung the
            # heavy-hitter work stands on — one hot rank's piece_join
            # seconds towering over the median IS the skew signal.
            # Unarmed: not called, zero extra collectives.
            **({"rank_phase_skew": rank_rep}
               if rank_rep is not None else {}),
            # --skew: plan decision + unsplit-baseline audit leg
            **skew_detail,
            # --slices: topology decision + per-tier traffic
            **topo_detail,
            # heavy-hitter profile of the skewed key column (obs/plan
            # key_profile — Misra-Gries over shard-weighted samples):
            # names the hot keys and their estimated share, the ROADMAP
            # item 2 detection baseline.  Only computed when --skew
            # asked for a skewed workload (one small device sample).
            **({"heavy_hitters": obs.plan.key_profile(lt, "k")}
               if skew > 0.0 else {}),
            # armed comm matrix (CYLON_TPU_COMM_MATRIX=1): the
            # per-(src,dst) rows/bytes report rides the plan section
            # below (detail.plan.comm_matrix — QueryPlan.to_dict embeds
            # it; a second top-level copy would just be payload drift)
            # recovery events + spill-tier + durable-checkpoint counters
            # (cylon_tpu.obs.bench_detail — the collector every bench
            # script shares): recovery_events says whether the number
            # was achieved on the happy path or after degradation;
            # spill_events > 0 means PCIe-assisted, not HBM-resident;
            # checkpoint_events > 0 paid page writes in-loop, and
            # resume_world_mismatch vs resume_resharded_pieces tells
            # "resharded and fast-forwarded" apart from "threw the
            # checkpoint away" after a topology change (elastic resume);
            # plan= attaches the profiled iteration's EXPLAIN ANALYZE
            # tree as the "plan" section.  Snapshotted BEFORE the
            # unsplit audit leg so its events stay out of this run's
            # counters.
            **bench_counters,
        },
    }


def main() -> dict:
    rows = None
    unique = 0.9
    iters = 4
    scale = None
    skew = 0.0
    for a in sys.argv[1:]:
        if a.startswith("--rows="):
            rows = int(a.split("=", 1)[1])
        elif a.startswith("--scale="):
            scale = float(a.split("=", 1)[1])
        elif a.startswith("--unique="):
            unique = float(a.split("=", 1)[1])
        elif a.startswith("--iters="):
            iters = int(a.split("=", 1)[1])
        elif a.startswith("--skew="):
            skew = float(a.split("=", 1)[1])
        elif a.startswith("--slices="):
            # declare an n-slice two-tier fabric BEFORE the env (and
            # therefore the topology cache) exists — the hierarchical
            # two-hop route then carries every exchange and the bench
            # detail records per-tier bytes/messages (cylon_tpu/topo,
            # docs/topology.md)
            os.environ["CYLON_TPU_SLICES"] = a.split("=", 1)[1]

    if "--tpch" in sys.argv:
        from cylon_tpu.tpch import bench_tpch
        return bench_tpch(scale=scale if scale is not None else 0.1,
                          iters=iters)

    if rows is None:
        # 125M/chip: the north-star per-chip share (BASELINE.json: 1B rows
        # on v5e-8).  Out-of-HBM scale routes through the range-partitioned
        # pipeline automatically (see run()); --rows=32000000 measures the
        # monolithic in-HBM regime (36.5M rows/s/chip r5).
        rows = 125_000_000 if jax.devices()[0].platform != "cpu" \
            else 1_000_000
    # halve on device OOM so the driver always gets a number
    while True:
        try:
            return run(rows_per_chip=rows, unique=unique, iters=iters,
                       skew=skew)
        except Exception as e:  # noqa: BLE001
            from cylon_tpu.exec import recovery
            if recovery.is_oom(e) and rows > 1_000_000:
                rows //= 2
                continue
            raise


if __name__ == "__main__":
    print(json.dumps(main()))
