"""Distributed merge + groupby on a device mesh — the reference README's
`mpirun -np N` example (README.md:48-73) in the single-controller SPMD
model: the mesh is the world; pass `env=` to run an op distributed.

Run on a simulated 8-device CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/distributed_merge.py
On TPU hardware the same script uses every visible chip (TPUConfig).
"""

import numpy as np
import pandas as pd

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run without install
import cylon_tpu as ct
from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig

import jax

on_accel = jax.devices()[0].platform != "cpu"
env = ct.CylonEnv(config=TPUConfig() if on_accel else CPUMeshConfig())
print(env)

rng = np.random.default_rng(0)
n = 100_000
df1 = ct.DataFrame(pd.DataFrame({
    "key": rng.integers(0, n // 2, n), "a": rng.random(n)}), env=env)
df2 = ct.DataFrame(pd.DataFrame({
    "key": rng.integers(0, n // 2, n), "b": rng.random(n)}), env=env)

joined = df1.merge(df2, on="key", env=env)
agg = joined.groupby("key", env=env)[["a", "b"]].sum()
top = agg.sort_values("a", ascending=False, env=env).head(5)
print(top.to_pandas())
