"""Local (single-device) DataFrame merge — the reference README's first
example (README.md:34-45) in cylon_tpu.

Run: python examples/local_join.py
"""

import numpy as np
import pandas as pd

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run without install
from cylon_tpu import DataFrame

df1 = DataFrame(pd.DataFrame({"key": [1, 2, 3, 4], "a": [10., 20., 30., 40.]}))
df2 = DataFrame(pd.DataFrame({"key": [2, 3, 4, 5], "b": [2., 3., 4., 5.]}))

out = df1.merge(df2, on="key", how="inner")
print(out.to_pandas())
