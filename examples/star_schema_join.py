"""Star-schema join plan: broadcast joins for dimensions, semi/anti
filters, and the same-key N-way join (round 5).

A fact table joins several small dimensions: each dimension at or below
``config.BROADCAST_JOIN_ROWS`` replicates via AllGather and the fact
table NEVER shuffles (the broadcast-hash-join; reference analog
Bcast(Table) + local join).  Same-key chains co-partition once through
``join_tables_multi`` (reference join.hpp:29 multi-table overload).

Run on a simulated 8-device CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/star_schema_join.py
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import numpy as np
import pandas as pd

import jax
import cylon_tpu as ct
from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig
from cylon_tpu.relational import join_tables, join_tables_multi


def main():
    on_accel = jax.devices()[0].platform != "cpu"
    env = ct.CylonEnv(config=TPUConfig() if on_accel else CPUMeshConfig())
    rng = np.random.default_rng(7)

    n = 200_000
    fact = pd.DataFrame({
        "store_id": rng.integers(0, 200, n).astype(np.int64),
        "product_id": rng.integers(0, 1000, n).astype(np.int64),
        "units": rng.integers(1, 20, n).astype(np.int64),
    })
    stores = pd.DataFrame({
        "store_id": np.arange(200, dtype=np.int64),
        "region": np.asarray([f"R{i % 5}" for i in range(200)], object),
    })
    recalled = pd.DataFrame({
        "product_id": rng.choice(1000, 30, replace=False).astype(np.int64)})

    ft = ct.Table.from_pandas(fact, env)
    st = ct.Table.from_pandas(stores, env)
    rt = ct.Table.from_pandas(recalled, env)

    # dimension join: stores (200 rows) broadcasts, the 200K fact rows
    # stay in place — zero shuffles
    enriched = join_tables(ft, st, "store_id", "store_id", how="inner")
    # NOT EXISTS recall: anti join against the recalled product keys
    clean = join_tables(enriched, rt, "product_id", "product_id",
                        how="anti")
    got = clean.to_pandas()
    exp = fact.merge(stores, on="store_id")
    exp = exp[~exp["product_id"].isin(set(recalled["product_id"]))]
    assert len(got) == len(exp)
    print(f"broadcast dim join + anti recall filter: {len(got)} rows "
          f"(dropped {len(fact) - len(got)})")

    # same-key chain: three monthly per-store summaries co-partition
    # ONCE each (one row per store per month — the chain stays 1:1)
    slices = [ct.Table.from_pandas(pd.DataFrame({
        "store_id": np.sort(rng.choice(200, 180,
                                       replace=False)).astype(np.int64),
        f"month{i}_units": rng.integers(0, 5000, 180).astype(np.int64)}),
        env) for i in range(3)]
    chained = join_tables_multi(slices, ["store_id"] * 3)
    print(f"3-way same-key chain: {chained.row_count} stores with all "
          f"three months, one exchange per table")


if __name__ == "__main__":
    main()
