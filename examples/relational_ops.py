"""Tour of the relational operator surface: sort, set ops, dedup, slice,
collectives — each validated against pandas inline.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/relational_ops.py
"""

import numpy as np
import pandas as pd

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run without install
import cylon_tpu as ct
from cylon_tpu.ctx.context import CPUMeshConfig

env = ct.CylonEnv(config=CPUMeshConfig())
rng = np.random.default_rng(7)

pdf = pd.DataFrame({"k": rng.integers(0, 20, 200),
                    "v": rng.standard_normal(200)})
df = ct.DataFrame(pdf, env=env)

print("sorted head:\n", df.sort_values(["k", "v"], env=env).head(3).to_pandas())
print("dedup rows:", len(df.drop_duplicates(subset=["k"], env=env)))

other = ct.DataFrame(pdf.iloc[:50], env=env)
print("intersect rows:", len(df.intersect(other, env=env)))
print("subtract rows:", len(df.subtract(other, env=env)))

# collectives (reference net/communicator.hpp surface)
t = df.table
print("allgather rows per shard:", env.allgather(t).valid_counts)
print("gather(root=2) layout:", env.gather(t, root=2).valid_counts)
