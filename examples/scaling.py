"""Weak/strong scaling driver — the reference's
rivanna/scripts/cylon_scaling.py:14-62 re-expressed for the mesh model:
same workload (two int64 columns per side, keys in [0, max_val * unique)),
same -s w|s semantics, per-iteration timings printed as JSON lines.

Examples:
  # weak scaling, 1M rows per device on the 8-device CPU mesh
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/scaling.py -n 1000000 -s w -i 3
  # strong scaling on TPU chips
  python examples/scaling.py -n 8000000 -s s -i 5
"""

import argparse
import json
import time

import numpy as np

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run without install
import cylon_tpu as ct
from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--rows", type=int, default=1_000_000,
                    help="rows per device (weak) / total rows (strong)")
    ap.add_argument("-s", "--scaling", choices=["w", "s"], default="w")
    ap.add_argument("-i", "--iters", type=int, default=3)
    ap.add_argument("-u", "--unique", type=float, default=0.9)
    ap.add_argument("-w", "--world", type=int, default=None,
                    help="world size (CPU mesh only; default = all devices)")
    args = ap.parse_args()

    import jax
    on_accel = jax.devices()[0].platform != "cpu"
    cfg = TPUConfig() if on_accel else CPUMeshConfig(world_size=args.world)
    env = ct.CylonEnv(config=cfg)
    w = env.world_size

    if args.scaling == "w":
        num_rows = args.rows * w
        max_val = int(num_rows * args.unique)
    else:
        num_rows = args.rows
        max_val = int(args.rows * args.unique)

    rng = np.random.default_rng(0)
    mk = lambda: ct.Table.from_pydict(
        {"k": rng.integers(0, max(max_val, 1), num_rows).astype(np.int64),
         "v": rng.integers(0, max(max_val, 1), num_rows).astype(np.int64)},
        env)
    from cylon_tpu.relational import join_tables
    t1, t2 = mk(), mk()

    join_tables(t1, t2, "k", "k").row_count  # warmup/compile
    for i in range(args.iters):
        t0 = time.perf_counter()
        out = join_tables(t1, t2, "k", "k")
        n_out = out.row_count  # host sync
        dt = time.perf_counter() - t0
        print(json.dumps({"scaling": args.scaling, "world": w,
                          "rows": num_rows, "iter": i,
                          "join_s": round(dt, 4), "out_rows": int(n_out)}))


if __name__ == "__main__":
    main()
