"""Lexical sort_values on a high-cardinality string column (round 5).

The column never builds an n-entry dictionary (device codes are stable
64-bit value hashes); at sort time the values' first bytes expand into
value-stable big-endian order lanes and the numeric sample-sort machinery
delivers exact lexical order (relational/sort._expand_hashed_string_keys
— the type-dispatched string sort slot, reference arrow_kernels.hpp:53).

Run on a simulated 8-device CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/string_sort.py
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import numpy as np
import pandas as pd

import jax
import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.ctx.context import CPUMeshConfig, TPUConfig


def main():
    on_accel = jax.devices()[0].platform != "cpu"
    env = ct.CylonEnv(config=TPUConfig() if on_accel else CPUMeshConfig())
    # force the hashed-codes path at demo size (default crossover is 4M rows)
    config.STRING_HASH_MIN_ROWS = 1000
    config.STRING_HASH_RATIO = 0.1

    rng = np.random.default_rng(0)
    n = 200_000
    df = pd.DataFrame({
        "sku": np.asarray([f"item-{v:09d}" for v in
                           rng.integers(0, 10**9, n)], dtype=object),
        "qty": rng.integers(1, 100, n),
    })
    f = ct.DataFrame(df, env=env)
    from cylon_tpu.core.column import HashedStrings
    assert isinstance(f._table.column("sku").dictionary, HashedStrings)

    out = f.sort_values("sku", env=env).to_pandas()
    exp = df.sort_values("sku").reset_index(drop=True)
    assert out["sku"].tolist() == exp["sku"].tolist()
    print(f"sorted {n} rows on a ~{df['sku'].nunique()}-distinct string "
          f"key across {env.world_size} shards; head:")
    print(out.head(5).to_string(index=False))


if __name__ == "__main__":
    main()
