"""Regression tests for the XLA:TPU compiler-crash mitigation machinery.

On v5e libtpu (2026-07) specific groupby programs SIGSEGV the TPU compiler
subprocess (e.g. TPC-H Q1's exact 8-agg spec: 7xu32+6xf64 gather lanes),
while close variants compile.  ``relational.groupby._pad_ladder`` retries a
crashed compile with dummy gather lanes and finally the scatter fallback,
remembering the winning variant per program signature.  The crash itself
cannot reproduce on CPU; these tests pin the ladder mechanics and the
dense/scatter segment-reduction parity that makes the fallback fast.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cylon_tpu.ops import groupby as gbk
from cylon_tpu.relational import groupby as rel_gb


def _crash(msg="INTERNAL: http://127.0.0.1:1/remote_compile: HTTP 500: "
                "tpu_compile_helper subprocess exit signal SIGSEGV (11)"):
    raise RuntimeError(msg)


class TestPadLadder:
    def test_advances_past_compiler_crash_and_remembers(self):
        rel_gb._PAD_CACHE.clear()
        calls = []

        def make(tag, fail):
            def thunk():
                calls.append(tag)
                if fail:
                    _crash()
                return tag
            return (tag, thunk)

        attempts = [make("pad0", True), make("pad1", True),
                    make("scatter", False)]
        key = ("sig", 1)
        assert rel_gb._pad_ladder(key, attempts) == "scatter"
        assert calls == ["pad0", "pad1", "scatter"]
        # second run dispatches straight to the remembered variant
        calls.clear()
        assert rel_gb._pad_ladder(key, attempts) == "scatter"
        assert calls == ["scatter"]

    def test_non_crash_errors_propagate(self):
        rel_gb._PAD_CACHE.clear()

        def bad():
            raise ValueError("data error, not a compiler crash")

        with pytest.raises(ValueError):
            rel_gb._pad_ladder(("sig", 2), [("pad0", bad),
                                            ("scatter", lambda: "x")])

    def test_remembered_index_clamped_to_ladder_length(self):
        rel_gb._PAD_CACHE.clear()
        rel_gb._PAD_CACHE.put(("sig", 3), 5)
        assert rel_gb._pad_ladder(("sig", 3),
                                  [("only", lambda: "ok")]) == "ok"

    def test_crash_detector(self):
        e = RuntimeError("INTERNAL: http://x/remote_compile: HTTP 500: "
                         "tpu_compile_helper subprocess exit signal SIGSEGV")
        assert rel_gb._is_compiler_crash(e)
        assert not rel_gb._is_compiler_crash(RuntimeError("RESOURCE_EXHAUSTED"))

    def test_probe_classifies_once_per_process(self, env1):
        """The signature set comes from the per-process probe (primed at
        env creation), not an inline literal: the cache is populated and
        contains the platform-independent base shapes."""
        from cylon_tpu.exec import recovery
        sigs = recovery.compiler_crash_signatures()
        assert recovery._CRASH_SIG_CACHE, "env creation did not prime probe"
        assert set(recovery._BASE_CRASH_SIGS) <= set(sigs)
        # probed again: same (cached) classification
        assert recovery.compiler_crash_signatures() is \
            recovery._CRASH_SIG_CACHE[0]

    def test_ladder_engages_under_synthetic_signature_change(self,
                                                             monkeypatch):
        """VERDICT item 8: swap the platform's crash signature
        (CYLON_TPU_CRASH_SIGS override — the same lever a new libtpu
        wording would need) and prove the pad ladder STILL advances past
        crashes carrying the new signature, while the old wording is now
        correctly treated as a data error and propagates."""
        from cylon_tpu.exec import recovery
        monkeypatch.setenv("CYLON_TPU_CRASH_SIGS",
                           "FLUX_COMPILE_UNIT_FAULT|helper exited 139")
        assert recovery.is_compiler_crash(
            RuntimeError("backend: FLUX_COMPILE_UNIT_FAULT at lane 7"))
        assert not recovery.is_compiler_crash(
            RuntimeError("tpu_compile_helper subprocess exit signal "
                         "SIGSEGV"))
        rel_gb._PAD_CACHE.clear()
        calls = []

        def crash_new():
            calls.append("pad0")
            raise RuntimeError("helper exited 139 compiling fused kernel")

        assert rel_gb._pad_ladder(
            ("sig", "synthetic"),
            [("pad0", crash_new),
             ("scatter", lambda: calls.append("scatter") or "ok")]) == "ok"
        assert calls == ["pad0", "scatter"]
        # the OLD signature no longer advances the ladder — it propagates
        rel_gb._PAD_CACHE.clear()

        def crash_old():
            raise RuntimeError("tpu_compile_helper subprocess exit "
                               "signal SIGSEGV (11)")

        with pytest.raises(RuntimeError):
            rel_gb._pad_ladder(("sig", "synthetic2"),
                               [("pad0", crash_old),
                                ("scatter", lambda: "ok")])


class TestDenseSegmentParity:
    """The dense one-hot reduction (num_segments <= _DENSE_SEG_MAX) must
    agree exactly with the scatter path it replaces (measured v5e: scatter
    ~72 ns/row at small segment counts from collision serialization, dense
    ~9 ns/row)."""

    @pytest.mark.parametrize("kind", ["sum", "min", "max", "count"])
    @pytest.mark.parametrize("dtype", [np.int64, np.float64, np.int32])
    def test_parity(self, kind, dtype, monkeypatch):
        rng = np.random.default_rng(7)
        n, ns = 4096, 17
        gids = jnp.asarray(rng.integers(0, ns, n).astype(np.int32))
        vals = jnp.asarray(rng.integers(-50, 50, n).astype(dtype))
        mask = jnp.asarray(rng.integers(0, 2, n).astype(bool))
        fn = getattr(gbk, f"seg_{kind}")
        dense = fn(vals, gids, ns, mask)
        monkeypatch.setattr(gbk, "_DENSE_SEG_MAX", 0)
        scatter = fn(vals, gids, ns, mask)
        np.testing.assert_array_equal(np.asarray(dense), np.asarray(scatter))

    def test_empty_segment_identities(self):
        gids = jnp.asarray(np.array([0, 0, 2], np.int32))
        vals = jnp.asarray(np.array([5.0, 3.0, 1.0]))
        mn = np.asarray(gbk.seg_min(vals, gids, 4))
        mx = np.asarray(gbk.seg_max(vals, gids, 4))
        assert mn[1] == np.inf and mx[1] == -np.inf
        assert mn[0] == 3.0 and mx[0] == 5.0 and mn[2] == 1.0


def test_all_laneless_f64_key_and_value(env8):
    """Zero-lane vspec (every column laneless f64, none nullable): the sort
    path must ride the index lane alone, not crash in pack_lanes."""
    import pandas as pd
    import cylon_tpu as ct
    from cylon_tpu.relational import groupby_aggregate
    rng = np.random.default_rng(11)
    df = pd.DataFrame({"k": rng.integers(0, 5, 200).astype(np.float64),
                       "v": rng.random(200)})
    t = ct.Table.from_pandas(df, env8)
    g = groupby_aggregate(t, ["k"], [("v", "sum")]).to_pandas()
    exp = df.groupby("k", as_index=False).agg(v_sum=("v", "sum"))
    g = g.sort_values("k").reset_index(drop=True)
    np.testing.assert_allclose(g["v_sum"].to_numpy(),
                               exp["v_sum"].to_numpy(), rtol=1e-12)


def test_program_caches_bounded():
    """EVERY compiled-program factory in the package must be bounded at
    PROGRAM_CACHE_SIZE — a single reverted `lru_cache(maxsize=None)`
    anywhere fails this (round-2 VERDICT weak #6)."""
    import importlib
    from cylon_tpu import config
    mods = ["cylon_tpu.relational.join", "cylon_tpu.relational.groupby",
            "cylon_tpu.relational.fused", "cylon_tpu.relational.sort",
            "cylon_tpu.relational.setops", "cylon_tpu.relational.repart",
            "cylon_tpu.parallel.shuffle", "cylon_tpu.parallel.collectives",
            "cylon_tpu.exec.pipeline", "cylon_tpu.series"]
    checked = 0
    for mn in mods:
        mod = importlib.import_module(mn)
        for name, obj in vars(mod).items():
            if hasattr(obj, "cache_parameters"):
                ms = obj.cache_parameters()["maxsize"]
                assert ms == config.PROGRAM_CACHE_SIZE, \
                    f"{mn}.{name} cache maxsize={ms}"
                checked += 1
    assert checked >= 30  # the factories really were scanned


def test_program_cache_evicts(env1):
    """Eviction actually happens: more distinct static signatures than a
    (shrunken) cache bound leaves currsize == bound, and the operator
    still computes correctly after eviction."""
    import functools
    import pandas as pd
    import cylon_tpu as ct
    from cylon_tpu.relational import groupby as rg
    from cylon_tpu.relational import groupby_aggregate
    orig = rg._shrink_fn
    small = functools.lru_cache(maxsize=2)(
        orig.__wrapped__ if hasattr(orig, "__wrapped__") else orig)
    rg._shrink_fn = small
    try:
        for i in range(5):
            df = pd.DataFrame({"k": np.arange(3 + i, dtype=np.int64),
                               "v": np.arange(3 + i, dtype=np.int64)})
            t = ct.Table.from_pandas(df, env1)
            g = groupby_aggregate(t, "k", [("v", "sum")])
            assert g.row_count == 3 + i
        assert small.cache_info().currsize <= 2
    finally:
        rg._shrink_fn = orig
