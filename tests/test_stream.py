"""Streaming ingest subsystem (cylon_tpu/stream — ISSUE 9 acceptance):
incremental-view bit-equality vs full batch recompute after every
micro-batch (all agg kinds incl. var/std), window-close correctness +
watermark semantics, out-of-order/late-arrival policies, spill-tier
eviction of closed windows actually releasing ledger bytes, injector
sites, durable checkpoint fast-forward, and the bench/chaos acceptance
flows (slow-marked)."""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.exec import checkpoint, memory, recovery
from cylon_tpu.relational.groupby import groupby_aggregate
from cylon_tpu.status import (InvalidError, PredictedResourceExhausted,
                              RankDesyncError)
from cylon_tpu.stream import IncrementalView, StreamTable, TumblingWindowJoin

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_AGGS = [("v", "sum"), ("v", "count"), ("v", "min"), ("v", "max"),
            ("v", "mean"), ("v", "var"), ("v", "std"), ("q", "sum"),
            ("q", "mean")]


@pytest.fixture(autouse=True)
def _clean_state():
    recovery.install_faults("")
    recovery.reset_events()
    memory.reset_stats()
    yield
    recovery.install_faults("")
    recovery.reset_events()


def _batch(rng, n=200, keys=16):
    """Integer-valued payloads (f64 'money' + int64 qty): partial sums
    are exact, so the bit-equality contract holds for every agg kind."""
    return {"k": rng.integers(0, keys, n).astype(np.int64),
            "v": rng.integers(-500, 500, n).astype(np.float64),
            "q": rng.integers(1, 51, n).astype(np.int64)}


class TestIncrementalView:
    def test_bit_equal_vs_batch_recompute_every_batch(self, env4):
        """The acceptance contract: after EVERY micro-batch, read() is
        bitwise equal to a from-scratch batch groupby over all rows seen
        so far — all agg kinds, var/std included."""
        rng = np.random.default_rng(0)
        st = StreamTable(env4, key="k", name="t0")
        view = IncrementalView(st, "k", ALL_AGGS, env=env4)
        seen = []
        for i in range(3):
            b = _batch(rng)
            seen.append(b)
            st.append(dict(b))
            got = view.read().to_pandas().sort_values("k") \
                .reset_index(drop=True)
            full = ct.Table.from_pydict(
                {c: np.concatenate([bb[c] for bb in seen])
                 for c in ("k", "v", "q")}, env4)
            exp = groupby_aggregate(full, "k", ALL_AGGS).to_pandas() \
                .sort_values("k").reset_index(drop=True)
            pd.testing.assert_frame_equal(got[exp.columns], exp,
                                          check_exact=True)

    def test_read_is_nondestructive(self, env4):
        rng = np.random.default_rng(1)
        st = StreamTable(env4, key="k", name="t1")
        view = IncrementalView(st, "k", [("v", "sum")], env=env4)
        st.append(_batch(rng))
        first = view.read().to_pandas().sort_values("k") \
            .reset_index(drop=True)
        again = view.read().to_pandas().sort_values("k") \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(first, again, check_exact=True)
        n_parts = len(view.sink._parts)
        st.append(_batch(rng))
        assert len(view.sink._parts) == n_parts + 1
        assert view.read().to_pandas().v_sum.sum() != first.v_sum.sum() \
            or True  # values may coincide; the partial count is the claim

    def test_compaction_preserves_bit_equality(self, env4):
        """compact_every folds the sink's partials into one — state and
        read cost stay O(groups) for unbounded streams — and under the
        exactness contract the folded snapshot is bit-equal to both the
        uncompacted view and the batch recompute."""
        rng = np.random.default_rng(11)
        st = StreamTable(env4, key="k", name="tc")
        view = IncrementalView(st, "k", ALL_AGGS, env=env4,
                               compact_every=2)
        seen = []
        for _ in range(5):
            b = _batch(rng)
            seen.append(b)
            st.append(dict(b))
        assert len(view.sink._parts) <= 2   # folded, not one-per-batch
        got = view.read().to_pandas().sort_values("k") \
            .reset_index(drop=True)
        full = ct.Table.from_pydict(
            {c: np.concatenate([bb[c] for bb in seen])
             for c in ("k", "v", "q")}, env4)
        exp = groupby_aggregate(full, "k", ALL_AGGS).to_pandas() \
            .sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_exact=True)

    def test_stream_release_drains_ledger(self, env4):
        rng = np.random.default_rng(2)
        st = StreamTable(env4, key="k", name="t2")
        before = memory.balance()
        st.append(_batch(rng))
        assert memory.balance() > before
        st.release()
        assert memory.balance() <= before

    def test_empty_stream_raises(self, env4):
        st = StreamTable(env4, key="k", name="t3")
        with pytest.raises(InvalidError):
            st.snapshot()


def _dims(env, keys=16):
    return ct.Table.from_pydict(
        {"k": np.arange(keys, dtype=np.int64),
         "dim": np.arange(keys, dtype=np.int64) * 3 + 1}, env)


def _wbatch(rng, t_lo, t_hi, n=120, keys=16):
    return {"k": rng.integers(0, keys, n).astype(np.int64),
            "t": rng.integers(t_lo, t_hi, n).astype(np.int64),
            "v": rng.integers(0, 100, n).astype(np.int64)}


class TestWindowedJoin:
    def test_close_matches_batch_join_and_evicts(self, env4):
        """A closed window's join equals the batch recompute over that
        window's rows, and eviction actually releases ledger bytes
        (memory.stats() delta — the device→host→released lifecycle)."""
        rng = np.random.default_rng(3)
        dims = _dims(env4)
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=dims, build_on="k", lateness=0)
        rows = []
        for i in range(3):
            b = _wbatch(rng, i * 100, (i + 1) * 100)
            rows.append(pd.DataFrame(b))
            wj.append(b)
        held = memory.balance()
        assert wj.watermark() == 2     # windows 0 and 1 close; 2 open
        assert wj.windows_closed == 2
        assert memory.stats()["window_evictions"] >= 2
        assert memory.stats()["spill_events"] >= 2   # device→host first
        # the window BUFFERS drained (released); the emitted results are
        # themselves accounted — not ledger-invisible — until popped
        result_bytes = sum(r.nbytes for r in wj._closed_regs)
        assert result_bytes > 0
        assert memory.balance() - result_bytes < held
        full = pd.concat(rows)
        dpd = dims.to_pandas()
        for wid, out in wj.closed:
            assert out is not None
            got = out.to_pandas().sort_values(["k", "t", "v"]) \
                .reset_index(drop=True)
            w = full[(full.t >= wid * 100) & (full.t < (wid + 1) * 100)]
            exp = w.merge(dpd, on="k").sort_values(["k", "t", "v"]) \
                .reset_index(drop=True)
            pd.testing.assert_frame_equal(got[exp.columns], exp,
                                          check_dtype=False)

    def test_out_of_order_rows_land_in_correct_window(self, env4):
        """One batch spanning two windows out of order: every row lands
        in the window its EVENT time names, not its arrival order."""
        rng = np.random.default_rng(4)
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=_dims(env4), build_on="k")
        t = np.asarray([150, 20, 199, 0, 99, 100], np.int64)
        b = {"k": np.arange(6, dtype=np.int64) % 16, "t": t,
             "v": np.arange(6, dtype=np.int64)}
        wj.append(b)
        wj.append(_wbatch(rng, 200, 300, n=40))   # advances the watermark
        wj.watermark()
        by_wid = {wid: out for wid, out in wj.closed}
        t0 = sorted(by_wid[0].to_pandas().t.tolist())
        t1 = sorted(by_wid[1].to_pandas().t.tolist())
        assert t0 == [0, 20, 99]
        assert t1 == [100, 150, 199]

    def test_late_policy_drop(self, env4):
        rng = np.random.default_rng(5)
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=_dims(env4), build_on="k",
                                late_policy="drop")
        wj.append(_wbatch(rng, 0, 100, n=50))
        wj.append(_wbatch(rng, 200, 260, n=50))   # wm -> window 0 closed
        wj.watermark()
        assert wj.windows_closed >= 1
        before = wj.rows_buffered
        wj.append({"k": np.zeros(7, np.int64),
                   "t": np.full(7, 10, np.int64),
                   "v": np.zeros(7, np.int64)})   # 7 late rows
        assert wj.late_dropped == 7
        assert wj.rows_buffered == before

    def test_late_policy_clamp(self, env4):
        rng = np.random.default_rng(6)
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=_dims(env4), build_on="k",
                                late_policy="clamp")
        wj.append(_wbatch(rng, 0, 100, n=50))
        wj.append(_wbatch(rng, 210, 260, n=50))
        wj.watermark()                        # windows [0, 2) closed
        closed_through = wj._closed_through
        wj.append({"k": np.zeros(5, np.int64),
                   "t": np.full(5, 10, np.int64),
                   "v": np.zeros(5, np.int64)})   # late -> oldest open
        assert wj.late_clamped == 5
        assert closed_through in wj._open
        # the clamped rows close with (and appear in) the oldest open
        # window once the watermark passes it
        wj.append(_wbatch(rng, 300, 360, n=40))
        wj.watermark()
        by_wid = {wid: out for wid, out in wj.closed}
        t_closed = by_wid[closed_through].to_pandas().t.tolist()
        assert t_closed.count(10) == 5

    def test_open_window_spill_roundtrip(self, env4):
        """An OPEN window evicted under ledger pressure re-enters
        bit-exactly at close (the spill tier's window-lifetime class)."""
        rng = np.random.default_rng(7)
        dims = _dims(env4)
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=dims, build_on="k")
        b = _wbatch(rng, 0, 100, n=80)
        wj.append(b)
        # cold-window eviction (what the LRU would do under pressure)
        for buf in wj._open[0]:
            assert memory.evict(buf.reg) > 0
            assert buf.reg.spilled
        wj.append(_wbatch(rng, 150, 220, n=40))
        wj.watermark()
        wid, out = wj.closed[0]
        got = out.to_pandas().sort_values(["k", "t", "v"]) \
            .reset_index(drop=True)
        exp = pd.DataFrame(b).merge(dims.to_pandas(), on="k") \
            .sort_values(["k", "t", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)
        assert memory.stats()["readmit_events"] >= 1

    def test_epoch_scale_timestamps_fit_the_wire(self, env4):
        """Realistic epoch-scale event times with the default origin:
        the watermark vote carries the DELTA of newly-closable windows,
        so billions of window ordinals never touch the 2^20 consensus
        wire, and empty windows in the jumped-over range are skipped in
        O(open windows) — nothing recorded for them."""
        dims = _dims(env4)
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=60,
                                build=dims, build_on="k")
        t0 = 1_700_000_000            # epoch seconds, origin stays 0
        wj.append({"k": np.zeros(4, np.int64),
                   "t": np.asarray([t0, t0 + 10, t0 + 30, t0 + 50],
                                   np.int64),
                   "v": np.arange(4, dtype=np.int64)})
        wj.append({"k": np.ones(2, np.int64),
                   "t": np.asarray([t0 + 120, t0 + 130], np.int64),
                   "v": np.zeros(2, np.int64)})
        agreed = wj.watermark()
        assert agreed == (t0 + 130) // 60      # cumulative ordinal
        # only the buffered windows close (t0 is not window-aligned, so
        # the first batch spans two); the ~28M empty ordinals jumped
        # over from origin 0 are skipped, not recorded
        ripe = {t0 // 60, (t0 + 50) // 60}
        assert wj.windows_closed == len(ripe) == len(wj.closed)
        assert {wid for wid, _ in wj.closed} == ripe
        closed_ts = sorted(t for _, out in wj.closed
                           for t in out.to_pandas().t.tolist())
        assert closed_ts == [t0, t0 + 10, t0 + 30, t0 + 50]

    def test_pre_origin_events_raise(self, env4):
        """Events before the stream origin are invalid input (no window
        before the origin ever existed), never silently 'late'."""
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=_dims(env4), build_on="k",
                                origin=1000)
        with pytest.raises(InvalidError):
            wj.append({"k": np.zeros(3, np.int64),
                       "t": np.asarray([999, 1100, 1200], np.int64),
                       "v": np.zeros(3, np.int64)})

    def test_pop_closed_drains_results_and_ledger(self, env4):
        rng = np.random.default_rng(12)
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=_dims(env4), build_on="k")
        wj.append(_wbatch(rng, 0, 100, n=60))
        wj.append(_wbatch(rng, 150, 220, n=40))
        wj.watermark()
        assert wj.closed and wj._closed_regs
        held = memory.balance()
        popped = wj.pop_closed()
        assert len(popped) >= 1 and wj.closed == []
        del popped
        import gc
        gc.collect()
        assert memory.balance() < held   # emitted results drained

    def test_bad_late_policy_and_window(self, env4):
        with pytest.raises(InvalidError):
            TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                               build=_dims(env4), build_on="k",
                               late_policy="nope")
        with pytest.raises(InvalidError):
            TumblingWindowJoin(env4, key="k", time_col="t", window=0,
                               build=_dims(env4), build_on="k")


class TestStreamInjection:
    def test_append_site_raises_typed(self, env4):
        st = StreamTable(env4, key="k", name="inj")
        recovery.install_faults("stream.append::1=predicted")
        with pytest.raises(PredictedResourceExhausted):
            st.append(_batch(np.random.default_rng(8)))
        evs = recovery.recovery_events()
        assert evs and evs[0]["site"] == "stream.append"

    def test_watermark_site_raises_typed(self, env4):
        wj = TumblingWindowJoin(env4, key="k", time_col="t", window=100,
                                build=_dims(env4), build_on="k")
        recovery.install_faults("stream.watermark::1=desync")
        with pytest.raises(RankDesyncError):
            wj.watermark()


class TestViewCheckpointResume:
    def test_in_process_resume_fast_forwards(self, env4, tmp_path,
                                             monkeypatch):
        """Kill-free in-process replay of the resume path: absorb k
        batches with checkpointing armed, then rebuild the view under
        CYLON_TPU_RESUME=1 and replay the same stream — the committed
        partials fast-forward (ffwd == k) and the final read is
        bit-equal to the uninterrupted run."""
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        checkpoint.reset_stages()
        checkpoint.reset_stats()

        def run_stream():
            rng = np.random.default_rng(9)
            st = StreamTable(env4, key="k", name="ckpt")
            view = IncrementalView(st, "k", [("v", "sum"), ("v", "var")],
                                   name="ckpt_view", env=env4)
            for _ in range(3):
                st.append(_batch(rng))
            return view, view.read().to_pandas().sort_values("k") \
                .reset_index(drop=True)

        view1, base = run_stream()
        assert checkpoint.stats()["checkpoint_events"] == 3
        # fresh "process": replay the same workload under RESUME
        checkpoint.reset_stages()
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        view2, again = run_stream()
        assert view2.fast_forwarded == 3
        assert len(view2.sink._parts) == 3   # restored, not recomputed
        pd.testing.assert_frame_equal(again, base, check_exact=True)

    def test_world_change_reshards_committed_prefix(self, env4, tmp_path,
                                                    monkeypatch):
        """Elastic resume for streams (docs/robustness.md): a view's
        piece identity (the batch ordinal) is world-invariant and its
        partials are MERGEABLE, so a resume on a DIFFERENT mesh adopts
        the committed prefix — each partial's foreign pages stitched
        and re-blocked onto the live mesh, the replayed appends counted
        not re-absorbed — and the final read is bit-equal."""
        import cylon_tpu as ct
        from cylon_tpu.ctx.context import CPUMeshConfig
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        env2 = ct.CylonEnv(config=CPUMeshConfig(world_size=2))

        def run_stream(env):
            rng = np.random.default_rng(9)
            st = StreamTable(env, key="k", name="el")
            view = IncrementalView(st, "k", [("v", "sum"), ("q", "mean")],
                                   name="el_view", env=env)
            for _ in range(4):
                st.append(_batch(rng))
            return view, view.read().to_pandas().sort_values("k") \
                .reset_index(drop=True)

        _, base = run_stream(env4)
        assert checkpoint.stats()["checkpoint_events"] == 4
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        view2, again = run_stream(env2)
        assert view2.fast_forwarded == 4
        s = checkpoint.stats()
        assert s["resume_resharded_pieces"] == 4
        assert s["resume_world_mismatch"] == 1
        pd.testing.assert_frame_equal(again, base, check_exact=True)
        # the rewrite re-committed the adopted prefix in the new
        # layout: a THIRD run at world=2 is a plain fast-forward
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        view3, third = run_stream(env2)
        assert view3.fast_forwarded == 4
        assert checkpoint.stats()["resume_resharded_pieces"] == 0
        pd.testing.assert_frame_equal(third, base, check_exact=True)

    def test_world_change_corrupt_tail_trims_prefix(self, env4, tmp_path,
                                                    monkeypatch):
        """Review regression: one corrupt byte in the LAST committed
        batch's page must cost one batch, not the stream's whole
        history — the view's mergeable adoption trims to the verified
        prefix (load_foreign_pieces(prefix_ok=True))."""
        import cylon_tpu as ct
        from cylon_tpu.ctx.context import CPUMeshConfig
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        env2 = ct.CylonEnv(config=CPUMeshConfig(world_size=2))

        def run_stream(env):
            rng = np.random.default_rng(13)
            st = StreamTable(env, key="k", name="trim")
            view = IncrementalView(st, "k", [("v", "sum")],
                                   name="trim_view", env=env)
            for _ in range(4):
                st.append(_batch(rng))
            return view, view.read().to_pandas().sort_values("k") \
                .reset_index(drop=True)

        _, base = run_stream(env4)
        # flip a byte in the LAST batch's committed page
        import os
        stage_dir = os.path.join(str(tmp_path), "rank0",
                                 next(d for d in os.listdir(
                                     os.path.join(str(tmp_path), "rank0"))
                                     if "trim_view" in d))
        page = os.path.join(stage_dir, "piece_3.p0")
        raw = bytearray(open(page, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(page, "wb") as f:
            f.write(bytes(raw))
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        view2, again = run_stream(env2)
        assert view2.fast_forwarded == 3        # trimmed, not discarded
        s = checkpoint.stats()
        assert s["resume_resharded_pieces"] == 3
        assert s["corrupt_pages"] >= 1
        pd.testing.assert_frame_equal(again, base, check_exact=True)
        assert any(e["action"] == "prefix_trim"
                   for e in recovery.recovery_events())

    def test_no_ckpt_no_writes(self, env4, tmp_path, monkeypatch):
        monkeypatch.delenv("CYLON_TPU_CKPT_DIR", raising=False)
        rng = np.random.default_rng(10)
        st = StreamTable(env4, key="k", name="nockpt")
        view = IncrementalView(st, "k", [("v", "sum")], env=env4)
        st.append(_batch(rng))
        view.read()
        assert view.sink._ckpt is None
        assert list(tmp_path.iterdir()) == []


# ---------------------------------------------------------------------------
# acceptance flows (slow-marked: subprocess + compile-heavy)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_streaming_smoke():
    """The CI rung of the streaming bench: sustained ingest > 0 rows/s,
    bit_equal verdicts true, >= 1 window closed AND evicted — the
    script's own exit status asserts all of it."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "bench_streaming.py"),
         "--smoke", "--out", os.devnull],
        capture_output=True, text=True, timeout=560, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, (p.stdout + p.stderr)[-3000:]


@pytest.mark.slow
def test_chaos_stream_kill_and_resume(tmp_path):
    """SIGKILL mid-ingest with CYLON_TPU_CKPT_DIR armed: resume must
    fast-forward committed window state (ffwd > 0) and the final view
    must stay bit-equal to the uninterrupted run."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--stream", "--rows", "1500", "--workdir", str(tmp_path)],
        capture_output=True, text=True, timeout=560, cwd=REPO)
    assert p.returncode == 0, (p.stdout + p.stderr)[-3000:]
    assert "ffwd=" in p.stdout
