"""Groupby-aggregate tests against the pandas oracle.

Reference analog: cpp/test/groupby_test.cpp, aggregate_test.cpp,
python test_dist_aggregate.py.
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import groupby_aggregate

from utils import assert_frames_equal


def df(rng, n=200, nk=15):
    return pd.DataFrame({
        "k": rng.integers(0, nk, n),
        "k2": rng.choice(["x", "y", "z"], n),
        "v": rng.random(n),
        "w": rng.integers(-50, 50, n),
    })


@pytest.mark.parametrize("envname", ["env1", "env4", "env8"])
@pytest.mark.parametrize("op", ["sum", "count", "min", "max", "mean", "var",
                                "std"])
def test_associative_ops(request, rng, envname, op):
    env = request.getfixturevalue(envname)
    data = df(rng)
    t = ct.Table.from_pandas(data, env)
    got = groupby_aggregate(t, "k", [("v", op), ("w", op)]).to_pandas()
    exp = data.groupby("k", as_index=False).agg(
        **{f"v_{op}": ("v", op), f"w_{op}": ("w", op)})
    assert_frames_equal(got, exp, sort_by=["k"])


@pytest.mark.parametrize("envname", ["env1", "env8"])
def test_multi_key_groupby(request, rng, envname):
    env = request.getfixturevalue(envname)
    data = df(rng)
    t = ct.Table.from_pandas(data, env)
    got = groupby_aggregate(t, ["k", "k2"], [("v", "sum")]).to_pandas()
    exp = data.groupby(["k", "k2"], as_index=False).agg(v_sum=("v", "sum"))
    assert_frames_equal(got, exp, sort_by=["k", "k2"])


@pytest.mark.parametrize("envname", ["env1", "env8"])
def test_nunique(request, rng, envname):
    env = request.getfixturevalue(envname)
    data = df(rng)
    t = ct.Table.from_pandas(data, env)
    got = groupby_aggregate(t, "k", [("w", "nunique")]).to_pandas()
    exp = data.groupby("k", as_index=False).agg(w_nunique=("w", "nunique"))
    assert_frames_equal(got, exp, sort_by=["k"])


@pytest.mark.parametrize("envname", ["env1", "env8"])
def test_median_quantile(request, rng, envname):
    env = request.getfixturevalue(envname)
    data = df(rng)
    t = ct.Table.from_pandas(data, env)
    got = groupby_aggregate(t, "k", [("v", "median")]).to_pandas()
    exp = data.groupby("k", as_index=False).agg(v_median=("v", "median"))
    assert_frames_equal(got, exp, sort_by=["k"])


def test_string_key_groupby(env8, rng):
    data = df(rng)
    t = ct.Table.from_pandas(data, env8)
    got = groupby_aggregate(t, "k2", [("v", "sum"), ("v", "count")]).to_pandas()
    exp = data.groupby("k2", as_index=False).agg(v_sum=("v", "sum"),
                                                 v_count=("v", "count"))
    assert_frames_equal(got, exp, sort_by=["k2"])


def test_groupby_null_values(env4):
    data = pd.DataFrame({
        "k": [1, 1, 2, 2, 3, 3, 3, 1],
        "s": ["a", None, "b", None, None, "c", "c", "a"],
    })
    t = ct.Table.from_pandas(data, env4)
    got = groupby_aggregate(t, "k", [("s", "count"), ("s", "nunique")]
                            ).to_pandas()
    exp = data.groupby("k", as_index=False).agg(s_count=("s", "count"),
                                                s_nunique=("s", "nunique"))
    assert_frames_equal(got, exp, sort_by=["k"])


def test_mixed_assoc_nonassoc(env8, rng):
    data = df(rng)
    t = ct.Table.from_pandas(data, env8)
    got = groupby_aggregate(t, "k", [("v", "sum"), ("w", "nunique")]
                            ).to_pandas()
    exp = data.groupby("k", as_index=False).agg(v_sum=("v", "sum"),
                                                w_nunique=("w", "nunique"))
    assert_frames_equal(got, exp, sort_by=["k"])


def test_single_group(env8, rng):
    data = pd.DataFrame({"k": np.ones(64, np.int64), "v": rng.random(64)})
    t = ct.Table.from_pandas(data, env8)
    got = groupby_aggregate(t, "k", [("v", "sum")]).to_pandas()
    exp = data.groupby("k", as_index=False).agg(v_sum=("v", "sum"))
    assert_frames_equal(got, exp, sort_by=["k"])


def test_sortpath_laneable_dtypes(env4, rng):
    """The groupby SORT PATH (value/key columns riding the rank sort as u32
    lanes) requires laneable dtypes — f64 columns silently fall back, so
    the general float tests never exercise it.  This pins it with
    f32/int/string columns (eligibility asserted) against the pandas
    oracle, covering the two-phase distributed pre-combine."""
    import pandas as pd
    from cylon_tpu.relational import groupby as rg
    from cylon_tpu.relational.common import narrow32_flags

    n = 3000
    df = pd.DataFrame({
        "k": rng.integers(0, 150, n),
        "s": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        "v": np.where(rng.random(n) < 0.15, np.nan,
                      rng.random(n) * 100).astype(np.float32),
        "w": rng.integers(-1000, 1000, n),
    })
    t = ct.Table.from_pandas(df, env4)
    vcols = [t.column(c) for c in ("v", "w", "w", "v", "w")]
    bcols = [t.column("k"), t.column("s")]
    assert rg._plan_vspec(vcols, bcols, narrow32_flags(bcols)) is not None

    g = groupby_aggregate(t, ["k", "s"], [("v", "mean"), ("w", "min"),
                                          ("w", "max"), ("v", "std"),
                                          ("w", "sum")])
    exp = (df.groupby(["k", "s"], as_index=False)
           .agg(v_mean=("v", "mean"), w_min=("w", "min"),
                w_max=("w", "max"), v_std=("v", "std"), w_sum=("w", "sum")))
    got = g.to_pandas().sort_values(["k", "s"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, exp.sort_values(["k", "s"]).reset_index(drop=True),
        check_dtype=False, check_exact=False, rtol=1e-4)


def test_sortpath_f64_payload_riding(env4, rng):
    """f64 value/key columns DISQUALIFY the sort path (raw f64 sort
    payloads SIGSEGV the XLA:TPU compiler — see _plan_vspec) and must take
    the dense-rank fallback; mixed f64+laneable shapes must match pandas
    either way."""
    import pandas as pd
    n = 3000
    df = pd.DataFrame({"k": rng.integers(0, 150, n).astype(np.float64),
                       "v": rng.random(n),
                       "w": rng.integers(0, 100, n)})
    t = ct.Table.from_pandas(df, env4)
    g = groupby_aggregate(t, "k", [("v", "sum"), ("w", "mean"),
                                   ("v", "max"), ("w", "min")])
    exp = (df.groupby("k", as_index=False)
           .agg(v_sum=("v", "sum"), w_mean=("w", "mean"),
                v_max=("v", "max"), w_min=("w", "min")))
    got = g.to_pandas().sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, exp.sort_values("k").reset_index(drop=True),
        check_dtype=False, check_exact=False)


def test_sumsq_public_op(env4, rng):
    """sumsq (the reference VAR intermediate, aggregate_kernels.hpp:43)
    is a public op so streaming var/std decompositions close
    (exec/pipeline.GroupBySink)."""
    import pandas as pd
    n = 3000
    df = pd.DataFrame({"k": rng.integers(0, 80, n).astype(np.int64),
                       "v": rng.random(n),
                       "w": rng.integers(-30, 30, n).astype(np.int64)})
    df.loc[df.index % 7 == 0, "v"] = None
    t = ct.Table.from_pandas(df, env4)
    g = groupby_aggregate(t, "k", [("v", "sumsq"), ("w", "sumsq")])
    exp = (df.groupby("k", as_index=False)
           .agg(v_sumsq=("v", lambda s: (s.dropna() ** 2).sum()),
                w_sumsq=("w", lambda s: (s ** 2).sum())))
    got = g.to_pandas().sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, exp.sort_values("k").reset_index(drop=True),
        check_dtype=False, rtol=1e-9)
