"""Shuffle / repartition / slice / head / tail / concat tests
(reference cpp/test/repartition_test.cpp, slice_test.cpp analogs)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import (concat_tables, head, repartition,
                                  shuffle_table, slice_table, tail)

from utils import assert_frames_equal


def df(rng, n=120):
    return pd.DataFrame({"k": rng.integers(0, 20, n), "v": np.arange(n)})


@pytest.mark.parametrize("envname", ["env4", "env8"])
def test_shuffle_preserves_rows(request, rng, envname):
    env = request.getfixturevalue(envname)
    data = df(rng)
    t = ct.Table.from_pandas(data, env)
    s = shuffle_table(t, ["k"])
    assert s.row_count == len(data)
    assert_frames_equal(s.to_pandas(), data, sort_by=["v"])


def test_shuffle_colocates_keys(env8, rng):
    data = df(rng)
    t = ct.Table.from_pandas(data, env8)
    s = shuffle_table(t, ["k"])
    # each key must appear on exactly one shard
    w = env8.world_size
    cap = s.capacity
    kcol = np.asarray(s.column("k").data)
    owners = {}
    for i in range(w):
        ks = set(kcol[i * cap: i * cap + int(s.valid_counts[i])].tolist())
        for k in ks:
            assert k not in owners, f"key {k} on shards {owners[k]} and {i}"
            owners[k] = i


@pytest.mark.parametrize("envname", ["env4", "env8"])
def test_repartition_even(request, rng, envname):
    env = request.getfixturevalue(envname)
    data = df(rng, 100)
    t = ct.Table.from_pandas(data, env)
    # skew it first via a slice, then rebalance
    s = slice_table(t, 10, 77)
    r = repartition(s)
    w = env.world_size
    base = 77 // w
    assert all(c in (base, base + 1) for c in r.valid_counts)
    # global order preserved
    pd.testing.assert_frame_equal(
        r.to_pandas().reset_index(drop=True),
        data.iloc[10:87].reset_index(drop=True), check_dtype=False)


def test_repartition_specified(env4, rng):
    data = df(rng, 40)
    t = ct.Table.from_pandas(data, env4)
    r = repartition(t, (1, 2, 3, 34))
    assert r.valid_counts.tolist() == [1, 2, 3, 34]
    pd.testing.assert_frame_equal(r.to_pandas().reset_index(drop=True), data,
                                  check_dtype=False)


@pytest.mark.parametrize("off,length", [(0, 10), (5, 50), (95, 25), (0, 120),
                                        (119, 1)])
def test_slice(env8, rng, off, length):
    data = df(rng)
    t = ct.Table.from_pandas(data, env8)
    s = slice_table(t, off, length)
    exp = data.iloc[off:off + length].reset_index(drop=True)
    pd.testing.assert_frame_equal(s.to_pandas().reset_index(drop=True), exp,
                                  check_dtype=False)


def test_head_tail(env8, rng):
    data = df(rng)
    t = ct.Table.from_pandas(data, env8)
    pd.testing.assert_frame_equal(head(t, 7).to_pandas(),
                                  data.head(7).reset_index(drop=True),
                                  check_dtype=False)
    pd.testing.assert_frame_equal(tail(t, 7).to_pandas(),
                                  data.tail(7).reset_index(drop=True),
                                  check_dtype=False)


@pytest.mark.parametrize("envname", ["env1", "env8"])
def test_concat(request, rng, envname):
    env = request.getfixturevalue(envname)
    a = df(rng, 50)
    b = df(rng, 30)
    ta = ct.Table.from_pandas(a, env)
    tb = ct.Table.from_pandas(b, env)
    got = concat_tables([ta, tb])
    assert got.row_count == 80
    assert_frames_equal(got.to_pandas(), pd.concat([a, b], ignore_index=True),
                        sort_by=["v", "k"])


def test_concat_strings_and_nulls(env4):
    a = pd.DataFrame({"s": ["a", "b", None, "c"]})
    b = pd.DataFrame({"s": ["x", None]})
    ta = ct.Table.from_pandas(a, env4)
    tb = ct.Table.from_pandas(b, env4)
    got = concat_tables([ta, tb]).to_pandas()
    assert sorted([x for x in got["s"] if pd.notna(x)]) == ["a", "b", "c", "x"]
    assert int(got["s"].isna().sum()) == 2
