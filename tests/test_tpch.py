"""TPC-H Q3/Q5 against the pandas oracle (BASELINE.md config 4; reference
validated on TPC-xBB subsets, docs/docs/release/cylon_release_0.4.0.md)."""

import numpy as np
import pandas as pd
import pytest

from cylon_tpu import tpch


@pytest.fixture(params=["env1", "env4"])
def env(request):
    return request.getfixturevalue(request.param)


def test_q3_matches_pandas(env):
    pdfs = tpch.generate_pandas(scale=0.002, seed=3)
    dfs = {k: __import__("cylon_tpu").DataFrame(v, env=env)
           for k, v in pdfs.items()}
    got = tpch.q3(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q3_pandas(pdfs)
    assert len(got) == len(exp)
    # revenue descending with date tiebreak; float revenue ties are
    # possible in theory but measure-zero with these distributions
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q5_matches_pandas(env):
    pdfs = tpch.generate_pandas(scale=0.002, seed=4)
    dfs = {k: __import__("cylon_tpu").DataFrame(v, env=env)
           for k, v in pdfs.items()}
    got = tpch.q5(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q5_pandas(pdfs)
    assert len(got) == len(exp)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_generator_cardinalities():
    pdfs = tpch.generate_pandas(scale=0.01, seed=0)
    assert len(pdfs["customer"]) == 1500
    assert len(pdfs["orders"]) == 15000
    assert len(pdfs["nation"]) == 25 and len(pdfs["region"]) == 5
    assert pdfs["lineitem"].l_discount.between(0, 0.1).all()
    # shipdate strictly after orderdate
    li = pdfs["lineitem"]
    od = pdfs["orders"].set_index("o_orderkey").o_orderdate
    assert (li.l_shipdate.to_numpy()
            > od.loc[li.l_orderkey].to_numpy()).all()


def test_q1_matches_pandas(env):
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.002, seed=3)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q1(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q1_pandas(pdfs)
    pd.testing.assert_frame_equal(got, exp[got.columns], check_dtype=False,
                                  check_exact=False, rtol=1e-6)


def test_q6_matches_pandas(env):
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.002, seed=4)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q6(dfs, env=env)
    exp = tpch.q6_pandas(pdfs)
    assert abs(got - exp) <= 1e-6 * max(abs(exp), 1.0), (got, exp)


def test_q4_matches_pandas(env):
    pdfs = tpch.generate_pandas(scale=0.005, seed=7)
    dfs = {k: __import__("cylon_tpu").DataFrame(v, env=env)
           for k, v in pdfs.items()}
    got = tpch.q4(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q4_pandas(pdfs)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_q10_matches_pandas(env):
    pdfs = tpch.generate_pandas(scale=0.01, seed=8)
    dfs = {k: __import__("cylon_tpu").DataFrame(v, env=env)
           for k, v in pdfs.items()}
    got = tpch.q10(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q10_pandas(pdfs)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q12_matches_pandas(env):
    pdfs = tpch.generate_pandas(scale=0.01, seed=9)
    dfs = {k: __import__("cylon_tpu").DataFrame(v, env=env)
           for k, v in pdfs.items()}
    got = tpch.q12(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q12_pandas(pdfs)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_q14_matches_pandas(env):
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.004, seed=14)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q14(dfs, env=env)
    exp = tpch.q14_pandas(pdfs)
    assert got == pytest.approx(exp, rel=1e-9)


def test_q9_matches_pandas(env):
    """Q9 (round 13, the out-of-core tier's wide-join exerciser): six
    tables, five joins incl. the two-key partsupp edge, year-grouped
    profit — bit-checked against the pandas oracle at env1/env4."""
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.002, seed=9)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q9(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q9_pandas(pdfs)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp[got.columns], check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q9_generator_year_column_is_derived():
    """o_orderyear consumes no RNG draws: every pre-round-13 column
    stays byte-identical (the regression-baseline rule)."""
    pdfs = tpch.generate_pandas(scale=0.002, seed=9)
    o = pdfs["orders"]
    assert (o.o_orderyear.to_numpy()
            == o.o_orderdate.dt.year.to_numpy()).all()


def test_q18_matches_pandas(env):
    import cylon_tpu as ct
    # lower HAVING threshold so the tiny scale keeps qualifying orders
    pdfs = tpch.generate_pandas(scale=0.004, seed=18)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q18(dfs, env=env, quantity=150).to_pandas() \
        .reset_index(drop=True)
    exp = tpch.q18_pandas(pdfs, quantity=150)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q19_matches_pandas(env):
    import cylon_tpu as ct
    # Q19's conjunctions select ~1e-5 of lineitem; this scale keeps a
    # handful of qualifying rows so the assertion is non-vacuous
    pdfs = tpch.generate_pandas(scale=0.05, seed=19)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q19(dfs, env=env)
    exp = tpch.q19_pandas(pdfs)
    assert exp != 0.0
    assert got == pytest.approx(exp, rel=1e-9)


@pytest.mark.parametrize("qname", ["q16", "q21", "q22"])
def test_round5_queries_match_pandas(env, qname):
    """Q16/Q21/Q22 — the semi/anti-join query family (round 5)."""
    pdfs = tpch.generate_pandas(scale=0.004, seed=16)
    dfs = {k: __import__("cylon_tpu").DataFrame(v, env=env)
           for k, v in pdfs.items()}
    got = getattr(tpch, qname)(dfs, env=env).to_pandas() \
        .reset_index(drop=True)
    exp = getattr(tpch, f"{qname}_pandas")(pdfs)
    assert len(got) == len(exp)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q11_matches_pandas(env):
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.004, seed=11)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q11(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q11_pandas(pdfs)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q15_matches_pandas(env):
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.01, seed=15)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q15(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q15_pandas(pdfs)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q17_matches_pandas(env):
    import cylon_tpu as ct
    # brand x container selects ~1/1000 of parts; this scale keeps a
    # handful of qualifying parts so the assertion is non-vacuous
    pdfs = tpch.generate_pandas(scale=0.02, seed=17)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q17(dfs, env=env)
    exp = tpch.q17_pandas(pdfs)
    assert exp != 0.0
    assert got == pytest.approx(exp, rel=1e-9)


def test_q20_matches_pandas(env):
    import cylon_tpu as ct
    # ~1/6 of parts are forest-named; this scale keeps a non-vacuous
    # supplier set through the nested INs + correlated half-sum
    pdfs = tpch.generate_pandas(scale=0.01, seed=20)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q20(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q20_pandas(pdfs)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_q13_matches_pandas(env):
    """Q13 (round 12) — the LEFT-join count-distribution, bit-checked:
    integer counts compare exactly, including the c_count = 0 bucket the
    left join's null extension produces."""
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.004, seed=13)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q13(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q13_pandas(pdfs)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_q7_matches_pandas(env):
    """Q7 (round 14, the adaptive skew-split route's TPC-H exerciser):
    lineitem ⋈ supplier/customer ⋈ nation×2 on a 25-value nation key —
    every key a heavy hitter — bit-checked against the pandas oracle at
    env1/env4 with the skew route armed (its default)."""
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.004, seed=7)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q7(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q7_pandas(pdfs)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp[got.columns], check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q8_matches_pandas(env):
    """Q8 (round 15, the multi-slice topology tier's TPC-H exerciser):
    national market share — seven tables chained through six
    shuffle-backed joins, the suite's widest cross-slice working set —
    bit-checked against the pandas oracle at env1/env4 (docs/
    topology.md; the two-tier-route equality legs live in
    tests/test_topo.py)."""
    import cylon_tpu as ct
    pdfs = tpch.generate_pandas(scale=0.004, seed=8)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    got = tpch.q8(dfs, env=env).to_pandas().reset_index(drop=True)
    exp = tpch.q8_pandas(pdfs)
    assert len(got) == len(exp) > 0
    pd.testing.assert_frame_equal(got, exp[got.columns], check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_q7_generator_year_column_is_derived():
    """l_shipyear consumes no RNG draws: every pre-round-14 column
    stays byte-identical (the regression-baseline rule)."""
    pdfs = tpch.generate_pandas(scale=0.002, seed=7)
    li = pdfs["lineitem"]
    assert (li.l_shipyear.to_numpy()
            == li.l_shipdate.dt.year.to_numpy()).all()


def test_q18_explain_analyze_records_plan(env):
    """Round 14: the naturally skew-shaped Q18's ANALYZE tree (recorded
    as q18_plan in the tpch bench detail) carries its join route
    decisions — with the skew route armed, every distributed join node
    names a route and any skew_split node carries the voted plan
    summary."""
    import cylon_tpu as ct
    from cylon_tpu import obs
    pdfs = tpch.generate_pandas(scale=0.004, seed=18)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    qp = obs.explain_analyze(
        lambda: tpch.q18(dfs, env=env, quantity=150).to_pandas())
    d = qp.to_dict()
    assert d["roots"], "no plan nodes recorded"
    joins = []

    def walk(n):
        if n["op"] == "join":
            joins.append(n)
        for c in n.get("children", ()):
            walk(c)
    for r in d["roots"]:
        walk(r)
    assert joins, "Q18 recorded no join nodes"
    for n in joins:
        attrs = n.get("attrs", {})
        if attrs.get("route") == "skew_split":
            plan = attrs.get("skew_plan")
            assert plan and plan.get("plan_hash") and plan.get("fanout")


def test_q13_explain_analyze_records_plan(env):
    """The profiler's acceptance workload: EXPLAIN ANALYZE of Q13 at
    SF0.01 produces a plan tree whose per-node seconds reconcile with
    the global phase table (per-region equality up to fp summation) and
    whose exchange bytes equal the always-on exchange counters."""
    import cylon_tpu as ct
    from cylon_tpu import obs
    from cylon_tpu.obs import metrics
    pdfs = tpch.generate_pandas(scale=0.01, seed=13)
    dfs = {k: ct.DataFrame(v, env=env) for k, v in pdfs.items()}
    rows0 = metrics.counter("exchange_rows_total").value
    bytes0 = metrics.counter("exchange_bytes_total").value
    qp = obs.explain_analyze(lambda: tpch.q13(dfs, env=env).to_pandas())
    d = qp.to_dict()
    assert d["roots"], "no plan nodes recorded"
    ops = set()

    def walk(n):
        ops.add(n["op"])
        for c in n.get("children", ()):
            walk(c)
    for r in d["roots"]:
        walk(r)
    assert "join" in ops and "groupby" in ops and "sort" in ops
    rec = d["reconcile"]
    # per-node seconds reconcile with the global phase table: every
    # region second landed in exactly one node's self table
    assert rec["node_s"] <= rec["phase_s"] + 1e-6
    assert abs(rec["unattributed_s"]) <= max(0.05 * rec["phase_s"], 0.02)
    for name, s in rec["per_phase_node_s"].items():
        assert s == pytest.approx(d["global_phases"][name]["s"],
                                  rel=1e-4, abs=2e-3), name
    # exchange bytes attributed to nodes == the counter deltas
    def sum_xchg(n):
        return (n.get("bytes_exchanged", 0)
                + sum(sum_xchg(c) for c in n.get("children", ())))
    node_bytes = sum(sum_xchg(r) for r in d["roots"])
    assert node_bytes == metrics.counter("exchange_bytes_total").value \
        - bytes0
    if env.world_size == 1:
        assert metrics.counter("exchange_rows_total").value == rows0


def test_round12_generator_addition():
    pdfs = tpch.generate_pandas(scale=0.01, seed=0)
    o = pdfs["orders"]
    assert "o_comment" in o.columns
    assert set(o.o_comment.unique()) <= {"special requests", "ok"}
    assert (o.o_comment == "special requests").any()
    # the new column rides an independent stream: the previously
    # generated columns stay byte-identical (regression-baseline rule)
    assert o.o_totalprice.sum() == tpch.generate_pandas(
        scale=0.01, seed=0)["orders"].o_totalprice.sum()


def test_round9_generator_addition():
    pdfs = tpch.generate_pandas(scale=0.01, seed=0)
    p = pdfs["part"]
    assert "p_name" in p.columns
    assert p.p_name.str.startswith("forest").any()
    assert set(p.p_name.unique()) <= set(tpch.PNAMES.tolist())
    # the new column rides an independent stream: the previously
    # generated columns stay byte-identical (regression-baseline rule)
    assert p.p_size.sum() == tpch.generate_pandas(
        scale=0.01, seed=0)["part"].p_size.sum()


def test_round7_generator_addition():
    pdfs = tpch.generate_pandas(scale=0.01, seed=0)
    ps = pdfs["partsupp"]
    assert "ps_supplycost" in ps.columns
    assert ps.ps_supplycost.between(1.0, 1000.0).all()
    # the new column rides an independent stream: the previously
    # generated columns stay byte-identical (regression-baseline rule)
    assert ps.ps_availqty.sum() == tpch.generate_pandas(
        scale=0.01, seed=0)["partsupp"].ps_availqty.sum()


def test_round5_generator_additions():
    pdfs = tpch.generate_pandas(scale=0.01, seed=0)
    assert len(pdfs["partsupp"]) == 4 * len(pdfs["part"])
    assert set(pdfs["orders"].o_orderstatus) <= {"F", "O", "P"}
    s = pdfs["supplier"]
    assert {"s_name", "s_comment"} <= set(s.columns)
    c = pdfs["customer"]
    assert (c.c_cntrycode == c.c_nationkey + 10).all()
    assert (c.c_phone.str.split("-").str[0].astype(int)
            == c.c_nationkey + 10).all()


def test_tpch_out_of_core_disk_tier_bit_equal(env4, monkeypatch, tmp_path):
    """The ISSUE-13 acceptance shape at CI scale: a TPC-H-shaped
    pipelined join+groupby (lineitem ⋈ orders, the Q3/Q9 spine) under
    CYLON_TPU_HBM_BUDGET + CYLON_TPU_HOST_BUDGET caps sized below its
    working set completes BIT-EQUAL to the uncapped run, with
    disk_events > 0 and bytes_to_disk > 0 — the whole residency ladder
    (device → host → spill files → mmap windows) under a real TPC-H
    data distribution.  The full-scale run is `bench.py --tpch` under
    the same env caps; the subprocess legs live in
    `scripts/chaos_soak.py --oocore`."""
    import cylon_tpu as ct
    from cylon_tpu import config
    from cylon_tpu.exec import GroupBySink, memory, pipelined_join, recovery
    pdfs = tpch.generate_pandas(scale=0.002, seed=13)
    li = ct.Table.from_pandas(
        pdfs["lineitem"][["l_orderkey", "l_quantity"]], env4)
    o = ct.Table.from_pandas(
        pdfs["orders"][["o_orderkey", "o_orderyear"]], env4)

    def run():
        sink = GroupBySink("o_orderyear", [("l_quantity", "sum")])
        pipelined_join(li, o, "l_orderkey", "o_orderkey", how="inner",
                       n_chunks=4, sink=sink)
        return (sink.finalize().to_pandas().sort_values("o_orderyear")
                .reset_index(drop=True))

    base = run()
    import gc
    gc.collect()
    memory.reset_stats()
    recovery.reset_events()
    monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 4096)
    monkeypatch.setattr(config, "HOST_BUDGET_BYTES", 4096)
    monkeypatch.setattr(config, "SPILL_DIR", str(tmp_path / "spill"))
    capped = run()
    st = memory.stats()
    assert st["disk_events"] > 0 and st["bytes_to_disk"] > 0, st
    assert recovery.recovery_events() == []   # degraded, not escalated
    pd.testing.assert_frame_equal(capped, base)   # bit-equal
