"""Windowed Pallas gather (ops/pallas_gather) — interpret-mode checks on
the CPU rig; the real-TPU path is exercised by bench.py and the fused
groupby dispatch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cylon_tpu.ops import pallas_gather as pg


def _ref(mat, idx):
    return np.asarray(mat)[:, np.asarray(idx)]


def _mk(n_rows, n_lanes, seg, density_pattern, rng):
    # lane-major (L, M), as the API requires
    mat = jnp.asarray(
        rng.integers(0, 1 << 32, (n_lanes, n_rows), dtype=np.uint32))
    if density_pattern == "dense":
        k = min(int(n_rows * 0.45), seg)
        real = np.sort(rng.choice(n_rows - 1, k, replace=False))
    elif density_pattern == "skewed":
        # one huge group: a long index gap that overflows any window
        k = min(int(n_rows * 0.45), seg)
        real = np.sort(rng.choice(n_rows // 8, k - 1, replace=False))
        real = np.concatenate([real, [n_rows - 1]])
    else:  # tail sentinels only
        real = np.zeros(0, np.int64)
    idx = np.full(seg, n_rows - 1, np.int32)
    idx[:len(real)] = real
    return mat, jnp.asarray(idx)


class TestWindowedTake:
    @pytest.mark.parametrize("n_lanes", [1, 7, 8, 13])
    def test_matches_plain_gather(self, rng, n_lanes):
        n_rows, seg = 4096, 2048
        mat, idx = _mk(n_rows, n_lanes, seg, "dense", rng)
        out, ok = jax.jit(lambda m, i: pg.windowed_take_t(
            m, i, window=1024, interpret=True))(mat, idx)
        assert bool(np.asarray(ok))
        np.testing.assert_array_equal(np.asarray(out), _ref(mat, idx))

    def test_sentinel_tail(self, rng):
        # all-sentinel tail tiles (empty groups past n_groups)
        mat, idx = _mk(4096, 5, 1024, "tail", rng)
        out, ok = jax.jit(lambda m, i: pg.windowed_take_t(
            m, i, window=1024, interpret=True))(mat, idx)
        assert bool(np.asarray(ok))
        np.testing.assert_array_equal(np.asarray(out), _ref(mat, idx))

    def test_skewed_spans_flagged(self, rng):
        # a span overflow must be reported so the dispatch layer can
        # redispatch a plain-gather program
        mat, idx = _mk(1 << 15, 6, 4096, "skewed", rng)
        out, ok = jax.jit(lambda m, i: pg.windowed_take_t(
            m, i, window=1024, interpret=True))(mat, idx)
        assert not bool(np.asarray(ok))

    def test_supported_gate(self):
        assert pg.supported(1 << 20, 1 << 20, 8, 1024)
        assert not pg.supported(512, 1 << 20, 8, 1024)   # mat < window
        assert not pg.supported(1 << 20, 100, 8, 1024)   # seg not tiled

    def test_pick_window(self):
        assert pg.pick_window(0.45) == 1024
        assert pg.pick_window(0.25) == 2048
        assert pg.pick_window(0.05) == pg.MAX_WINDOW
