"""Durable checkpoint/resume rung (cylon_tpu.exec.checkpoint +
docs/robustness.md "Durable checkpoints & resume"): host-page round
trips, the two-phase manifest commit, resume fast-forward through the
pipelined range loop (sink and sinkless), corruption fallback, the
ladder's FINAL ResumableAbort rung, and the trimmed chaos soak.  The
cross-PROCESS kill-and-resume acceptance runs in scripts/chaos_soak.py
(pinned schedule 0) and in the slow-marked soak test here."""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.exec import GroupBySink, checkpoint, pipelined_join, preempt, \
    recovery
from cylon_tpu.status import (CheckpointCorruptError, DeviceOOMError,
                              InvalidError, ResumableAbort)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    """Every test runs with its own checkpoint root, a fresh stage
    sequence, zeroed counters, a disarmed injector and no pending
    preemption notice."""
    monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path / "ckpt"))
    monkeypatch.delenv("CYLON_TPU_RESUME", raising=False)
    monkeypatch.delenv("CYLON_TPU_PREEMPT_GRACE_S", raising=False)
    checkpoint.reset_stages()
    checkpoint.reset_stats()
    recovery.install_faults("")
    preempt.reset()
    yield
    checkpoint.reset_stages()
    checkpoint.reset_stats()
    recovery.install_faults("")
    preempt.reset(uninstall=True)


@pytest.fixture(scope="module")
def env2():
    """2-device env for the elastic (world-change) resume tests — the
    same virtual-device rig env4 uses, half the mesh."""
    from cylon_tpu.ctx.context import CPUMeshConfig
    return ct.CylonEnv(config=CPUMeshConfig(world_size=2))


@pytest.fixture()
def grace(monkeypatch):
    """Arm the preemption grace budget and install the SIGTERM
    handler (uninstalled by _clean's teardown)."""
    monkeypatch.setenv("CYLON_TPU_PREEMPT_GRACE_S", "30")
    assert preempt.install()
    return preempt


def _tables(env, rng, n=2500, card=250):
    ldf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                        "a": rng.integers(0, 50, n).astype(np.int64)})
    rdf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                        "b": rng.integers(0, 50, n).astype(np.int64)})
    return (ldf, rdf, ct.Table.from_pandas(ldf, env),
            ct.Table.from_pandas(rdf, env))


def _frames_bitequal(a: pd.DataFrame, b: pd.DataFrame) -> None:
    assert list(a.columns) == list(b.columns)
    for c in a.columns:
        np.testing.assert_array_equal(a[c].to_numpy(), b[c].to_numpy(), c)


def _run_join(lt, rt, n_chunks=4):
    return (pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=n_chunks)
            .to_pandas().sort_values(["k", "a", "b"])
            .reset_index(drop=True))


def _run_sink(lt, rt, n_chunks=4):
    sink = GroupBySink("k", [("a", "sum"), ("b", "sum")])
    pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=n_chunks,
                   sink=sink)
    return (sink.finalize().to_pandas().sort_values("k")
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# page round trip (Stage.save_piece / load_piece)
# ---------------------------------------------------------------------------

class TestPageRoundTrip:
    def test_bit_exact_all_column_classes(self, env4, rng):
        """Strings (dictionary), nullable ints, NaN-carrying f64 and
        plain int64 all survive the host-page round trip bit-exactly —
        the spill-tier transport persisted."""
        n = 400
        df = pd.DataFrame({
            "k": rng.integers(0, 50, n).astype(np.int64),
            "s": np.asarray([f"v{i % 7}" for i in range(n)], dtype=object),
            "f": np.where(rng.random(n) < 0.1, np.nan, rng.random(n)),
            "ni": pd.array(rng.integers(0, 9, n), dtype="Int64"),
        })
        df.loc[rng.integers(0, n, 20), "ni"] = pd.NA
        t = ct.Table.from_pandas(df, env4)
        stage = checkpoint.open_stage(env4, "unit", "tok")
        stage.save_piece(0, t)
        back = stage.load_piece(0)
        assert back.column_names == t.column_names
        for name in t.column_names:
            a, b = t.column(name), back.column(name)
            np.testing.assert_array_equal(np.asarray(a.data),
                                          np.asarray(b.data), name)
            assert (a.validity is None) == (b.validity is None)
            if a.validity is not None:
                np.testing.assert_array_equal(np.asarray(a.validity),
                                              np.asarray(b.validity))
            assert a.type == b.type
        np.testing.assert_array_equal(t.valid_counts, back.valid_counts)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 1

    def test_flaky_write_is_retried_not_aborted(self, env4, rng,
                                                monkeypatch):
        """The satellite regression (exec/recovery.retry_io adoption): a
        transient OSError on the manifest rename — an NFS blip during a
        GKE drain — used to abort the commit; the 3-attempt backoff now
        saves it and the piece round-trips bit-exactly."""
        import os as _os
        monkeypatch.setattr("time.sleep", lambda s: None)
        t = ct.Table.from_pandas(
            pd.DataFrame({"k": rng.integers(0, 9, 64).astype(np.int64)}),
            env4)
        stage = checkpoint.open_stage(env4, "flaky", "tok")
        real_replace = _os.replace
        fails = [1]

        def flaky_replace(src, dst):
            if fails[0]:
                fails[0] -= 1
                raise OSError(5, "transient EIO blip")
            return real_replace(src, dst)

        monkeypatch.setattr(_os, "replace", flaky_replace)
        stage.save_piece(0, t)          # survives the blip via retry_io
        monkeypatch.setattr(_os, "replace", real_replace)
        back = stage.load_piece(0)
        np.testing.assert_array_equal(np.asarray(t.column("k").data),
                                      np.asarray(back.column("k").data))
        from cylon_tpu.obs import metrics
        assert metrics.counter("recovery_io_retries").value >= 1

    def test_manifest_commits_identical_epoch_per_piece(self, env4, rng):
        import json
        _, _, lt, rt = _tables(env4, rng, n=800)
        stage = checkpoint.open_stage(env4, "unit", "tok")
        stage.save_piece(0, lt)
        stage.save_piece(1, rt)
        with open(stage._manifest_path, encoding="utf-8") as f:
            man = json.load(f)
        assert man["epoch"] == 2 and man["plan"] == "tok"
        assert set(man["pieces"]) == {"0", "1"}
        # no stray staged manifest survives a clean commit
        assert not os.path.exists(stage._manifest_path + ".staged")

    def test_hash_mismatch_raises_typed(self, env4, rng):
        _, _, lt, _ = _tables(env4, rng, n=800)
        stage = checkpoint.open_stage(env4, "unit", "tok")
        stage.save_piece(0, lt)
        page = os.path.join(stage.dir, stage.committed[0]["meta"])
        raw = bytearray(open(page, "rb").read())
        raw[0] ^= 0xFF
        with open(page, "wb") as f:
            f.write(bytes(raw))
        with pytest.raises(CheckpointCorruptError):
            stage.load_piece(0)
        assert checkpoint.stats()["corrupt_pages"] == 1


# ---------------------------------------------------------------------------
# resume fast-forward through the pipelined range loop
# ---------------------------------------------------------------------------

class TestResumeFastForward:
    def test_sinkless_resume_bit_equal_no_recompute(self, env4, rng,
                                                    monkeypatch):
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        s1 = checkpoint.stats()
        assert s1["checkpoint_events"] >= 2
        assert s1["bytes_checkpointed"] > 0
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        s2 = checkpoint.stats()
        # every piece fast-forwarded, none recomputed (no new commits)
        assert s2["resume_fast_forwarded_pieces"] == s1["checkpoint_events"]
        assert s2["checkpoint_events"] == 0

    def test_sink_partials_resume_bit_equal(self, env4, rng, monkeypatch):
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_sink(lt, rt)
        exp = (ldf.merge(rdf, on="k").groupby("k", as_index=False)
               .agg(a_sum=("a", "sum"), b_sum=("b", "sum"))
               .sort_values("k").reset_index(drop=True))
        pd.testing.assert_frame_equal(base, exp, check_dtype=False)
        n_committed = checkpoint.stats()["checkpoint_events"]
        assert n_committed >= 2
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_sink(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] \
            == n_committed

    def test_resume_bit_equal_across_overlap_modes(self, env4, rng,
                                                   monkeypatch):
        """Checkpoint state is dispatch-mode agnostic: pieces committed
        under the overlap scheduler resume bit-identically with overlap
        DISABLED (and the plan tokens match — the schedule is not part
        of the plan), so an operator can flip the escape hatch between
        a crash and its resume without losing the checkpoint."""
        from cylon_tpu import config
        ldf, rdf, lt, rt = _tables(env4, rng)
        monkeypatch.setattr(config, "PACKED_OVERLAP", True)
        base = _run_sink(lt, rt)
        n_committed = checkpoint.stats()["checkpoint_events"]
        assert n_committed >= 2
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        monkeypatch.setattr(config, "PACKED_OVERLAP", False)
        resumed = _run_sink(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] \
            == n_committed

    def test_partial_prefix_resume(self, env4, rng, monkeypatch):
        """Only a prefix committed (as after a mid-loop crash): resume
        restores the prefix and recomputes the rest — still bit-equal."""
        import json
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        # drop the last committed piece from the manifest, as if the
        # process died before its commit
        rank_dir = os.path.join(checkpoint.ckpt_dir(),
                                f"rank{0}")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        mpath = os.path.join(stage_dir, "MANIFEST.json")
        man = json.load(open(mpath, encoding="utf-8"))
        full = len(man["pieces"])
        assert full >= 2
        dropped = str(max(int(k) for k in man["pieces"]))
        del man["pieces"][dropped]
        man["epoch"] -= 1
        json.dump(man, open(mpath, "w", encoding="utf-8"))
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        s = checkpoint.stats()
        assert s["resume_fast_forwarded_pieces"] == full - 1
        assert s["checkpoint_events"] == 1   # only the dropped piece re-ran

    def test_corrupt_page_degrades_to_recompute(self, env4, rng,
                                                monkeypatch):
        """A flipped byte in a committed page: resume detects the hash
        mismatch, falls back to recomputing the stage's remaining
        pieces, and the result is STILL bit-equal — corruption never
        produces a wrong answer."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        rank_dir = os.path.join(checkpoint.ckpt_dir(), "rank0")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        page = next(p for p in sorted(os.listdir(stage_dir))
                    if p.startswith("piece_0.p"))
        path = os.path.join(stage_dir, page)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(raw))
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        s = checkpoint.stats()
        assert s["corrupt_pages"] >= 1
        assert s["resume_fast_forwarded_pieces"] == 0

    def test_injected_load_corruption(self, env4, rng, monkeypatch):
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        recovery.install_faults("ckpt.load::1=corrupt")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 0
        assert any(e["site"] == "ckpt.load" and e["action"] == "recompute"
                   for e in recovery.recovery_events())

    def test_plan_token_mismatch_starts_over(self, env4, rng, monkeypatch):
        """A stale checkpoint from a DIFFERENT plan (other chunk count)
        is never spliced in: the stage starts over and recomputes."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        _run_join(lt, rt, n_chunks=4)
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        out = _run_join(lt, rt, n_chunks=3)   # different plan, same stage id
        exp = (ldf.merge(rdf, on="k").sort_values(["k", "a", "b"])
               .reset_index(drop=True))
        pd.testing.assert_frame_equal(out[exp.columns], exp,
                                      check_dtype=False)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 0

    def test_injected_write_fault_records_event(self, env4, rng):
        """A non-corrupt/non-kill fault armed at ckpt.write is recorded
        like every other injection site (the soak's MAX_RECOVERY_EVENTS
        bound counts it) — and the ladder still converges."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        recovery.install_faults("ckpt.write::1=device_oom")

        def attempt(nc=4):
            return _run_join(lt, rt, n_chunks=nc)

        out = recovery.run_with_recovery(attempt, True, attempt, "test",
                                         env=env4)
        exp = (ldf.merge(rdf, on="k").sort_values(["k", "a", "b"])
               .reset_index(drop=True))
        pd.testing.assert_frame_equal(out[exp.columns], exp,
                                      check_dtype=False)
        assert any(e["site"] == "ckpt.write" and e["action"] == "injected"
                   for e in recovery.recovery_events())

    def test_resume_consensus_wire_math(self):
        """Single-controller identity + wire-range validation for the
        min-agree fast-forward vote, and unrestore() backs discarded
        restores out of the counter."""
        assert recovery.ckpt_resume_consensus(None, 0) == 0
        assert recovery.ckpt_resume_consensus(None, 7) == 7
        with pytest.raises(ValueError):
            recovery.ckpt_resume_consensus(None, -1)
        with pytest.raises(ValueError):
            recovery.ckpt_resume_consensus(None, 1 << 20)
        checkpoint._STATS["resume_fast_forwarded_pieces"] = 5
        checkpoint.unrestore(2)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 3
        checkpoint.reset_stats()

    def test_staged_only_manifest_is_ignored(self, env4, rng, monkeypatch):
        """Phase-2 atomicity: a manifest that was STAGED but never
        committed (crash between the write and the consensus rename)
        must not be restored from."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        rank_dir = os.path.join(checkpoint.ckpt_dir(), "rank0")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        mpath = os.path.join(stage_dir, "MANIFEST.json")
        os.replace(mpath, mpath + ".staged")   # un-commit it
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 0


# ---------------------------------------------------------------------------
# elastic resume: checkpoints survive topology changes (re-shard path)
# ---------------------------------------------------------------------------

def _join_on(env, ldf, rdf, n_chunks=3):
    """The sinkless workload rebuilt on ``env`` from the same frames —
    what a resumed process on a different topology actually does."""
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    return _run_join(lt, rt, n_chunks=n_chunks)


def _sink_on(env, ldf, rdf, n_chunks=3):
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    return _run_sink(lt, rt, n_chunks=n_chunks)


def _resume_mode(monkeypatch):
    monkeypatch.setenv("CYLON_TPU_RESUME", "1")
    checkpoint.reset_stages()
    checkpoint.reset_stats()


class TestElasticReshard:
    def _frames(self, rng, n=1800, card=200):
        ldf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                            "a": rng.integers(0, 50, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                            "b": rng.integers(0, 50, n).astype(np.int64)})
        return ldf, rdf

    def test_shrink_world_reshards_then_plain_fast_forward(
            self, env4, env2, rng, monkeypatch):
        """world=4 → world=2: the complete stage re-shards (every piece
        fast-forwarded AND counted as resharded, the mismatch counted
        once), and — because the first post-reshard commit rewrote the
        manifests in the new layout — a SECOND resume at world=2 is a
        plain fast-forward with zero reshard work."""
        ldf, rdf = self._frames(rng)
        base = _join_on(env4, ldf, rdf)
        n_pieces = checkpoint.stats()["checkpoint_events"]
        assert n_pieces >= 2
        _resume_mode(monkeypatch)
        resharded = _join_on(env2, ldf, rdf)
        _frames_bitequal(resharded, base)
        s = checkpoint.stats()
        assert s["resume_world_mismatch"] == 1
        assert s["resume_resharded_pieces"] == n_pieces
        assert s["resume_fast_forwarded_pieces"] == n_pieces
        # second resume at the new world: rewritten manifests match the
        # full layout token — ordinary fast-forward, nothing resharded
        _resume_mode(monkeypatch)
        again = _join_on(env2, ldf, rdf)
        _frames_bitequal(again, base)
        s2 = checkpoint.stats()
        assert s2["resume_world_mismatch"] == 0
        assert s2["resume_resharded_pieces"] == 0
        assert s2["resume_fast_forwarded_pieces"] == n_pieces
        assert s2["checkpoint_events"] == 0

    def test_grow_world_reshards(self, env4, env2, rng, monkeypatch):
        """world=2 → world=4 (M > N): ranks that never existed at
        checkpoint time adopt the stitched state too."""
        ldf, rdf = self._frames(rng)
        base = _join_on(env2, ldf, rdf)
        n_pieces = checkpoint.stats()["checkpoint_events"]
        _resume_mode(monkeypatch)
        out = _join_on(env4, ldf, rdf)
        _frames_bitequal(out, base)
        s = checkpoint.stats()
        assert s["resume_resharded_pieces"] == n_pieces > 0
        assert s["resume_world_mismatch"] == 1

    def test_reshard_to_single_device(self, env4, env1, rng, monkeypatch):
        """world=4 → world=1: the degenerate mesh still adopts."""
        ldf, rdf = self._frames(rng, n=1200)
        base = _join_on(env4, ldf, rdf)
        n_pieces = checkpoint.stats()["checkpoint_events"]
        _resume_mode(monkeypatch)
        out = _join_on(env1, ldf, rdf)
        _frames_bitequal(out, base)
        assert checkpoint.stats()["resume_resharded_pieces"] == n_pieces > 0

    def test_lane_classes_round_trip_bit_exact(self, env4, env2, rng,
                                               monkeypatch):
        """Strings (dictionary codes), nullable ints and NaN-carrying
        f64 side arrays survive the stitch + re-block bit-exactly —
        the reshard reuses the page transport, so every lane class the
        spill tier round-trips must round-trip here too."""
        n = 1600
        ldf = pd.DataFrame({
            "k": rng.integers(0, 120, n).astype(np.int64),
            "s": np.asarray([f"v{i % 11}" for i in range(n)], dtype=object),
            "f": np.where(rng.random(n) < 0.15, np.nan, rng.random(n)),
            "ni": pd.array(rng.integers(0, 9, n), dtype="Int64"),
        })
        ldf.loc[rng.integers(0, n, 40), "ni"] = pd.NA
        rdf = pd.DataFrame({"k": rng.integers(0, 120, n).astype(np.int64),
                            "b": rng.integers(0, 50, n).astype(np.int64)})

        def run(env):
            lt = ct.Table.from_pandas(ldf, env)
            rt = ct.Table.from_pandas(rdf, env)
            out = pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=3)
            df = out.to_pandas()
            return df.sort_values(["k", "b", "s", "f"],
                                  na_position="last").reset_index(drop=True)

        base = run(env4)
        assert checkpoint.stats()["checkpoint_events"] >= 2
        _resume_mode(monkeypatch)
        resharded = run(env2)
        assert checkpoint.stats()["resume_resharded_pieces"] > 0
        assert list(resharded.columns) == list(base.columns)
        for c in base.columns:
            a = base[c].to_numpy()
            b = resharded[c].to_numpy()
            if a.dtype.kind == "f":
                np.testing.assert_array_equal(a, b, c)   # NaN == NaN here
            else:
                np.testing.assert_array_equal(a, b, c)

    def test_corrupt_foreign_page_degrades_to_recompute(
            self, env4, env2, rng, monkeypatch):
        """A flipped byte in a foreign rank's committed page: the
        reshard detects the hash mismatch and the stage recomputes —
        bit-equal, never a wrong answer."""
        ldf, rdf = self._frames(rng, n=1200)
        base = _join_on(env4, ldf, rdf)
        rank_dir = os.path.join(checkpoint.ckpt_dir(), "rank0")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        page = next(p for p in sorted(os.listdir(stage_dir))
                    if p.startswith("piece_0.p"))
        path = os.path.join(stage_dir, page)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(raw))
        _resume_mode(monkeypatch)
        out = _join_on(env2, ldf, rdf)
        _frames_bitequal(out, base)
        s = checkpoint.stats()
        assert s["corrupt_pages"] >= 1
        assert s["resume_resharded_pieces"] == 0
        assert s["resume_world_mismatch"] == 1   # detected, then degraded

    def test_injected_reshard_corruption(self, env4, env2, rng,
                                         monkeypatch):
        ldf, rdf = self._frames(rng, n=1200)
        base = _join_on(env4, ldf, rdf)
        _resume_mode(monkeypatch)
        recovery.install_faults("ckpt.reshard::1=corrupt")
        out = _join_on(env2, ldf, rdf)
        _frames_bitequal(out, base)
        assert checkpoint.stats()["resume_resharded_pieces"] == 0
        assert any(e["site"] == "ckpt.reshard"
                   for e in recovery.recovery_events())

    def test_sink_partial_reshard_equals_batch_recompute(
            self, env4, env2, rng, monkeypatch):
        """GroupBySink partials re-shard as MERGEABLE state: the adopted
        (re-blocked) partials combine through combine_sink_partials to
        the exact batch answer."""
        ldf, rdf = self._frames(rng)
        base = _sink_on(env4, ldf, rdf)
        exp = (ldf.merge(rdf, on="k").groupby("k", as_index=False)
               .agg(a_sum=("a", "sum"), b_sum=("b", "sum"))
               .sort_values("k").reset_index(drop=True))
        pd.testing.assert_frame_equal(base, exp, check_dtype=False)
        n_pieces = checkpoint.stats()["checkpoint_events"]
        _resume_mode(monkeypatch)
        out = _sink_on(env2, ldf, rdf)
        _frames_bitequal(out, base)
        s = checkpoint.stats()
        assert s["resume_resharded_pieces"] == n_pieces > 0

    def test_incomplete_stage_recomputes_and_counts(self, env4, env2, rng,
                                                    monkeypatch):
        """A stage that never completed at the old topology (a crash
        prefix) is NOT adoptable across a world change: old-layout
        pieces have no complement in the new layout.  The mismatch is
        counted and the stage recomputes — the satellite contract that
        kills the silent-recompute behavior."""
        import json
        ldf, rdf = self._frames(rng, n=1200)
        base = _join_on(env4, ldf, rdf)
        rank_dir = os.path.join(checkpoint.ckpt_dir(), "rank0")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        mpath = os.path.join(stage_dir, "MANIFEST.json")
        man = json.load(open(mpath, encoding="utf-8"))
        man["complete"] = False   # as if the process died mid-stage
        json.dump(man, open(mpath, "w", encoding="utf-8"))
        _resume_mode(monkeypatch)
        out = _join_on(env2, ldf, rdf)
        _frames_bitequal(out, base)
        s = checkpoint.stats()
        assert s["resume_world_mismatch"] == 1
        assert s["resume_resharded_pieces"] == 0
        assert s["resume_fast_forwarded_pieces"] == 0
        assert any(e["site"] == "ckpt.reshard"
                   and e["kind"] == "world_mismatch"
                   for e in recovery.recovery_events())

    def test_truncated_complete_manifest_recomputes(self, env4, env2, rng,
                                                    monkeypatch):
        """A manifest still flagged complete but with a truncated piece
        table (torn edit, tampering) must NOT adopt the surviving
        prefix as the whole stage — the recorded completion count gates
        the adoption, and the stage recomputes bit-equal."""
        import json
        ldf, rdf = self._frames(rng, n=1200)
        base = _join_on(env4, ldf, rdf)
        rank_dir = os.path.join(checkpoint.ckpt_dir(), "rank0")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        mpath = os.path.join(stage_dir, "MANIFEST.json")
        man = json.load(open(mpath, encoding="utf-8"))
        assert man["complete"] and man["n_pieces"] >= 2
        dropped = str(max(int(k) for k in man["pieces"]))
        del man["pieces"][dropped]          # n_pieces left claiming more
        json.dump(man, open(mpath, "w", encoding="utf-8"))
        _resume_mode(monkeypatch)
        out = _join_on(env2, ldf, rdf)
        _frames_bitequal(out, base)
        s = checkpoint.stats()
        assert s["resume_resharded_pieces"] == 0
        assert s["resume_world_mismatch"] == 1

    def test_changed_data_never_adopts_across_worlds(self, env4, env2, rng,
                                                     monkeypatch):
        """Review regression: the world-invariant BASE token carries a
        data fingerprint (global live row totals), so an elastic resume
        over DIFFERENT inputs must not adopt the stale checkpoint — it
        recomputes the new data's answer, exactly like the same-world
        full-token guard."""
        ldf, rdf = self._frames(rng, n=1500)
        _join_on(env4, ldf, rdf)                      # checkpoint D1 @ 4
        ldf2, rdf2 = self._frames(rng, n=1100)        # a DIFFERENT dataset
        exp = (ldf2.merge(rdf2, on="k").sort_values(["k", "a", "b"])
               .reset_index(drop=True))
        _resume_mode(monkeypatch)
        out = _join_on(env2, ldf2, rdf2)              # resume D2 @ 2
        pd.testing.assert_frame_equal(out[exp.columns], exp,
                                      check_dtype=False)
        s = checkpoint.stats()
        assert s["resume_resharded_pieces"] == 0      # D1 never spliced in
        assert s["resume_fast_forwarded_pieces"] == 0

    def test_fresh_run_supersedes_older_generations(self, env4, env2, rng,
                                                    monkeypatch):
        """Review regression: generations must stay monotonic ACROSS
        sessions.  After a reshard rewrite (gen 1), a FRESH run of the
        same shape with DIFFERENT payload values must not be outranked
        by the stale rewrite at the next resume — same keys means the
        layout token matches, so only the generation can disambiguate,
        and losing would silently fast-forward the old run's data."""
        ldf, rdf = self._frames(rng, n=1200)
        _join_on(env4, ldf, rdf)                      # gen 0 @ world 4
        _resume_mode(monkeypatch)
        _join_on(env2, ldf, rdf)                      # reshard → gen 1 @ 2
        # fresh session, same keys (identical layout token), new values
        monkeypatch.delenv("CYLON_TPU_RESUME", raising=False)
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        ldf2 = ldf.copy()
        ldf2["a"] = ldf2["a"] + 1000
        base2 = _join_on(env2, ldf2, rdf)             # must write gen 2
        n_pieces = checkpoint.stats()["checkpoint_events"]
        _resume_mode(monkeypatch)
        out = _join_on(env2, ldf2, rdf)
        _frames_bitequal(out, base2)                  # the NEW data
        s = checkpoint.stats()
        assert s["resume_fast_forwarded_pieces"] == n_pieces > 0
        assert s["resume_world_mismatch"] == 0

    def test_orphan_rank_dirs_do_not_block_resume(self, env4, env2, rng,
                                                  monkeypatch):
        """Review regression: leftover rank dirs from an older topology
        (a shared PVC reused across launches) must not read as a 'torn
        checkpoint' against a newer run's manifests — the fresh run's
        generation bump outranks them."""
        import shutil
        ldf, rdf = self._frames(rng, n=1200)
        _join_on(env4, ldf, rdf)                      # gen 0 @ world 4
        root = checkpoint.ckpt_dir()
        # simulate a second old process's dir surviving on shared storage
        shutil.copytree(os.path.join(root, "rank0"),
                        os.path.join(root, "rank1"))
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        base = _join_on(env2, ldf, rdf)               # fresh → gen 1 @ 2
        n_pieces = checkpoint.stats()["checkpoint_events"]
        assert n_pieces > 0                           # it did NOT resume
        _resume_mode(monkeypatch)
        out = _join_on(env2, ldf, rdf)
        _frames_bitequal(out, base)
        s = checkpoint.stats()
        # plain fast-forward of the fresh run, orphans ignored
        assert s["resume_fast_forwarded_pieces"] == n_pieces
        assert s["resume_world_mismatch"] == 0

    def test_unrestore_clamps_and_raises(self):
        """Satellite regression: over-unrestoring (a consensus bug)
        clamps the counter at zero and raises typed — a bench read can
        never report a negative fast-forward count."""
        checkpoint._STATS["resume_fast_forwarded_pieces"] = 2
        checkpoint.unrestore(1)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 1
        with pytest.raises(InvalidError):
            checkpoint.unrestore(5)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 0
        with pytest.raises(InvalidError):
            checkpoint.unrestore(-1)
        checkpoint.reset_stats()


# ---------------------------------------------------------------------------
# preemption grace: SIGTERM drains at checkpoint boundaries
# ---------------------------------------------------------------------------

class TestPreemptGrace:
    def test_sigterm_drains_committed_then_resumes(self, env4, rng, grace,
                                                   monkeypatch):
        """The ``term`` injector delivers a REAL SIGTERM mid-run (through
        the installed handler); the piece loop drains at the next
        checkpoint boundary: pending sink chunks settle, the manifest
        commits, and a typed ResumableAbort carries the resume token.
        The resumed run fast-forwards the grace window's commits and is
        bit-equal to the pandas oracle."""
        ldf, rdf, lt, rt = _tables(env4, rng, n=1800)
        recovery.install_faults("ckpt.write::2=term")
        with pytest.raises(ResumableAbort) as ei:
            _run_sink(lt, rt, n_chunks=3)
        assert ei.value.token == os.path.abspath(checkpoint.ckpt_dir())
        assert grace.requested()
        committed = checkpoint.stats()["checkpoint_events"]
        assert committed >= 2
        assert any(e["kind"] == "preempt" and e["action"] == "drain"
                   for e in recovery.recovery_events())
        # the drain left a committed, resumable prefix
        recovery.install_faults("")
        grace.reset()
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        out = _run_sink(lt, rt, n_chunks=3)
        exp = (ldf.merge(rdf, on="k").groupby("k", as_index=False)
               .agg(a_sum=("a", "sum"), b_sum=("b", "sum"))
               .sort_values("k").reset_index(drop=True))
        pd.testing.assert_frame_equal(out, exp, check_dtype=False)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] \
            == committed

    def test_unarmed_checkpoint_means_zero_writes(self, env4, rng, grace,
                                                  monkeypatch, tmp_path):
        """The acceptance contract: with CYLON_TPU_CKPT_DIR unset the
        handler changes NOTHING — the run completes, no file is written,
        no drain fires (SIGTERM flag notwithstanding)."""
        monkeypatch.delenv("CYLON_TPU_CKPT_DIR", raising=False)
        _, _, lt, rt = _tables(env4, rng, n=800)
        import signal
        os.kill(os.getpid(), signal.SIGTERM)   # the real notice
        out = _run_join(lt, rt, n_chunks=3)    # must complete normally
        assert len(out) > 0
        assert grace.requested()
        assert checkpoint.stats() == {
            "checkpoint_events": 0, "bytes_checkpointed": 0,
            "resume_fast_forwarded_pieces": 0, "corrupt_pages": 0,
            "resume_resharded_pieces": 0, "resume_world_mismatch": 0}
        assert not (tmp_path / "ckpt").exists()

    def test_grace_unset_means_no_drain(self, env4, rng):
        """Checkpointing armed but no grace budget declared: the flag
        (set programmatically — without a handler a real SIGTERM would
        just kill the process, which is the point) triggers nothing."""
        preempt.request()
        _, _, lt, rt = _tables(env4, rng, n=800)
        out = _run_join(lt, rt, n_chunks=3)
        assert len(out) > 0
        assert checkpoint.stats()["checkpoint_events"] >= 2

    def test_scheduler_drains_running_tenant(self, env4, rng, grace):
        """Multi-tenant preemption, notice mid-run: the targeted tenant
        drains via typed ResumableAbort at its own checkpoint boundary
        with durable state committed; every other tenant either finished
        BEFORE the notice (a clean preemption leaves them done) or
        drained typed too — no tenant dies mid-piece, none is left
        running or pending."""
        from cylon_tpu.exec.scheduler import QueryScheduler
        ldf, rdf, _, _ = _tables(env4, rng, n=1500)

        def make_fn():
            def fn():
                lt = ct.Table.from_pandas(ldf, env4)
                rt = ct.Table.from_pandas(rdf, env4)
                sink = GroupBySink("k", [("a", "sum")])
                pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=3,
                               sink=sink)
                return sink.finalize()
            return fn

        recovery.install_faults("ckpt.write::2=term@t0")
        sched = QueryScheduler(env4, policy="fair", max_concurrency=2)
        sessions = [sched.submit(f"t{i}", make_fn()) for i in range(3)]
        sched.run()
        assert isinstance(sessions[0].error, ResumableAbort), \
            sessions[0].error
        for s in sessions[1:]:
            assert (s.error is None and s.result is not None) \
                or isinstance(s.error, ResumableAbort), (s.name, s.error)
        assert all(s.state in ("done", "failed") for s in sessions)
        assert sched.stats()["resumable_aborts"] >= 1
        # t0 committed durable state before draining
        assert checkpoint.stats()["checkpoint_events"] >= 1

    def test_scheduler_preempt_before_admission(self, env4, rng, grace):
        """Multi-tenant preemption, notice BEFORE anything ran: no
        session is admitted; every pending tenant fails typed with the
        resume token (nothing committed — a resume recomputes them) and
        the drain is counted."""
        from cylon_tpu.exec.scheduler import QueryScheduler
        ldf, rdf, _, _ = _tables(env4, rng, n=800)

        def fn():
            raise AssertionError("a drained-pending session must not run")

        preempt.request()
        sched = QueryScheduler(env4, policy="fifo")
        sessions = [sched.submit(f"t{i}", fn) for i in range(3)]
        sched.run()
        assert all(isinstance(s.error, ResumableAbort) for s in sessions)
        st = sched.stats()
        assert st["preempt_drained"] == 3
        assert st["resumable_aborts"] == 3
        assert checkpoint.stats()["checkpoint_events"] == 0


# ---------------------------------------------------------------------------
# happy path + FINAL ladder rung
# ---------------------------------------------------------------------------

class TestHappyPathAndFinalRung:
    def test_disabled_means_zero_writes(self, env4, rng, monkeypatch,
                                        tmp_path):
        """With CYLON_TPU_CKPT_DIR unset the checkpoint layer is inert:
        no stage opened, no file written, counters stay zero."""
        monkeypatch.delenv("CYLON_TPU_CKPT_DIR", raising=False)
        _, _, lt, rt = _tables(env4, rng, n=800)
        _run_join(lt, rt)
        assert checkpoint._STAGE_SEQ.get(None, 0) == 0
        assert checkpoint.stats() == {"checkpoint_events": 0,
                                      "bytes_checkpointed": 0,
                                      "resume_fast_forwarded_pieces": 0,
                                      "corrupt_pages": 0,
                                      "resume_resharded_pieces": 0,
                                      "resume_world_mismatch": 0}
        assert not (tmp_path / "ckpt").exists()

    def test_device_oom_abort_becomes_resumable(self, env4, rng):
        """The FINAL rung: an unrecoverable device OOM with checkpoints
        armed raises a typed ResumableAbort carrying the resume token
        (the checkpoint root), original fault on __cause__."""
        ldf, _, _, _ = _tables(env4, rng, n=1000)
        t = ct.Table.from_pandas(ldf, env4)
        from cylon_tpu.relational import groupby_aggregate
        recovery.install_faults("groupby.device_oom::*=device_oom")
        with pytest.raises(ResumableAbort) as ei:
            groupby_aggregate(t, "k", [("a", "sum")])
        assert ei.value.token == os.path.abspath(checkpoint.ckpt_dir())
        assert isinstance(ei.value.__cause__, DeviceOOMError)
        assert os.path.exists(os.path.join(checkpoint.ckpt_dir(),
                                           "RESUME_TOKEN.json"))
        acts = [e["action"] for e in recovery.recovery_events()
                if e["site"] == "groupby"]
        assert acts[-1] == "resumable_abort"

    def test_compiler_crash_takes_final_rung(self, env4):
        """An exhausted compiler-crash ladder (a non-fault exception for
        classify) still takes the FINAL rung when checkpoints are
        armed."""
        def boom():
            raise RuntimeError("tpu_compile_helper subprocess exit "
                               "signal SIGSEGV (11)")

        with pytest.raises(ResumableAbort) as ei:
            recovery.run_with_recovery(boom, False, None, "t", env=env4)
        assert ei.value.token
        assert "CYLON_TPU_RESUME=1" in str(ei.value)

    def test_without_ckpt_faults_stay_typed(self, env4, monkeypatch):
        """Un-armed sessions keep the PR 3 behavior exactly: the typed
        fault raises, no ResumableAbort, no files."""
        monkeypatch.delenv("CYLON_TPU_CKPT_DIR", raising=False)

        def boom():
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

        with pytest.raises(DeviceOOMError):
            recovery.run_with_recovery(boom, False, None, "t", env=env4)


# ---------------------------------------------------------------------------
# trimmed chaos soak (the cross-process kill-and-resume acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_trimmed():
    """scripts/chaos_soak.py with the three pinned schedules: SIGKILL
    mid-range-loop + resume fast-forward (ffwd > 0 asserted by the
    harness), corrupt-on-write and corrupt-on-load — every schedule must
    end bit-equal.  The full ≥20-schedule soak is the standalone CLI."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--seed", "5", "--schedules", "3", "--rows", "1200",
         "--chunks", "3"],
        capture_output=True, text=True, timeout=570, cwd=REPO)
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    assert "killed+resumed(ffwd=1)" in p.stdout, p.stdout[-2000:]


@pytest.mark.slow
def test_chaos_elastic_pinned():
    """scripts/chaos_soak.py --elastic: the pinned elastic-resume
    schedules — checkpoint at world=2, SIGKILL mid-stage-2, resume at
    world=1 (2→1 re-shard, ffwd>0), plain world=2 resume, the 1→2
    after-reshard double hop, corrupt-reshard degradation, and the
    SIGTERM grace drain (typed ResumableAbort exit) — every schedule
    bit-equal to the uninterrupted world=2 baseline."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--elastic", "--rows", "1000", "--chunks", "3"],
        capture_output=True, text=True, timeout=570, cwd=REPO)
    assert p.returncode == 0, p.stdout[-4000:] + p.stderr[-2000:]
    assert "A (2→1 reshard) -> ok" in p.stdout, p.stdout[-3000:]
    assert "C (1→2 after-reshard) -> ok" in p.stdout, p.stdout[-3000:]
    assert "E drain -> ok" in p.stdout, p.stdout[-3000:]
