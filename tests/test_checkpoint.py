"""Durable checkpoint/resume rung (cylon_tpu.exec.checkpoint +
docs/robustness.md "Durable checkpoints & resume"): host-page round
trips, the two-phase manifest commit, resume fast-forward through the
pipelined range loop (sink and sinkless), corruption fallback, the
ladder's FINAL ResumableAbort rung, and the trimmed chaos soak.  The
cross-PROCESS kill-and-resume acceptance runs in scripts/chaos_soak.py
(pinned schedule 0) and in the slow-marked soak test here."""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.exec import GroupBySink, checkpoint, pipelined_join, recovery
from cylon_tpu.status import (CheckpointCorruptError, DeviceOOMError,
                              ResumableAbort)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    """Every test runs with its own checkpoint root, a fresh stage
    sequence, zeroed counters and a disarmed injector."""
    monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path / "ckpt"))
    monkeypatch.delenv("CYLON_TPU_RESUME", raising=False)
    checkpoint.reset_stages()
    checkpoint.reset_stats()
    recovery.install_faults("")
    yield
    checkpoint.reset_stages()
    checkpoint.reset_stats()
    recovery.install_faults("")


def _tables(env, rng, n=2500, card=250):
    ldf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                        "a": rng.integers(0, 50, n).astype(np.int64)})
    rdf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                        "b": rng.integers(0, 50, n).astype(np.int64)})
    return (ldf, rdf, ct.Table.from_pandas(ldf, env),
            ct.Table.from_pandas(rdf, env))


def _frames_bitequal(a: pd.DataFrame, b: pd.DataFrame) -> None:
    assert list(a.columns) == list(b.columns)
    for c in a.columns:
        np.testing.assert_array_equal(a[c].to_numpy(), b[c].to_numpy(), c)


def _run_join(lt, rt, n_chunks=4):
    return (pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=n_chunks)
            .to_pandas().sort_values(["k", "a", "b"])
            .reset_index(drop=True))


def _run_sink(lt, rt, n_chunks=4):
    sink = GroupBySink("k", [("a", "sum"), ("b", "sum")])
    pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=n_chunks,
                   sink=sink)
    return (sink.finalize().to_pandas().sort_values("k")
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# page round trip (Stage.save_piece / load_piece)
# ---------------------------------------------------------------------------

class TestPageRoundTrip:
    def test_bit_exact_all_column_classes(self, env4, rng):
        """Strings (dictionary), nullable ints, NaN-carrying f64 and
        plain int64 all survive the host-page round trip bit-exactly —
        the spill-tier transport persisted."""
        n = 400
        df = pd.DataFrame({
            "k": rng.integers(0, 50, n).astype(np.int64),
            "s": np.asarray([f"v{i % 7}" for i in range(n)], dtype=object),
            "f": np.where(rng.random(n) < 0.1, np.nan, rng.random(n)),
            "ni": pd.array(rng.integers(0, 9, n), dtype="Int64"),
        })
        df.loc[rng.integers(0, n, 20), "ni"] = pd.NA
        t = ct.Table.from_pandas(df, env4)
        stage = checkpoint.open_stage(env4, "unit", "tok")
        stage.save_piece(0, t)
        back = stage.load_piece(0)
        assert back.column_names == t.column_names
        for name in t.column_names:
            a, b = t.column(name), back.column(name)
            np.testing.assert_array_equal(np.asarray(a.data),
                                          np.asarray(b.data), name)
            assert (a.validity is None) == (b.validity is None)
            if a.validity is not None:
                np.testing.assert_array_equal(np.asarray(a.validity),
                                              np.asarray(b.validity))
            assert a.type == b.type
        np.testing.assert_array_equal(t.valid_counts, back.valid_counts)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 1

    def test_manifest_commits_identical_epoch_per_piece(self, env4, rng):
        import json
        _, _, lt, rt = _tables(env4, rng, n=800)
        stage = checkpoint.open_stage(env4, "unit", "tok")
        stage.save_piece(0, lt)
        stage.save_piece(1, rt)
        with open(stage._manifest_path, encoding="utf-8") as f:
            man = json.load(f)
        assert man["epoch"] == 2 and man["plan"] == "tok"
        assert set(man["pieces"]) == {"0", "1"}
        # no stray staged manifest survives a clean commit
        assert not os.path.exists(stage._manifest_path + ".staged")

    def test_hash_mismatch_raises_typed(self, env4, rng):
        _, _, lt, _ = _tables(env4, rng, n=800)
        stage = checkpoint.open_stage(env4, "unit", "tok")
        stage.save_piece(0, lt)
        page = os.path.join(stage.dir, stage.committed[0]["meta"])
        raw = bytearray(open(page, "rb").read())
        raw[0] ^= 0xFF
        with open(page, "wb") as f:
            f.write(bytes(raw))
        with pytest.raises(CheckpointCorruptError):
            stage.load_piece(0)
        assert checkpoint.stats()["corrupt_pages"] == 1


# ---------------------------------------------------------------------------
# resume fast-forward through the pipelined range loop
# ---------------------------------------------------------------------------

class TestResumeFastForward:
    def test_sinkless_resume_bit_equal_no_recompute(self, env4, rng,
                                                    monkeypatch):
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        s1 = checkpoint.stats()
        assert s1["checkpoint_events"] >= 2
        assert s1["bytes_checkpointed"] > 0
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        s2 = checkpoint.stats()
        # every piece fast-forwarded, none recomputed (no new commits)
        assert s2["resume_fast_forwarded_pieces"] == s1["checkpoint_events"]
        assert s2["checkpoint_events"] == 0

    def test_sink_partials_resume_bit_equal(self, env4, rng, monkeypatch):
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_sink(lt, rt)
        exp = (ldf.merge(rdf, on="k").groupby("k", as_index=False)
               .agg(a_sum=("a", "sum"), b_sum=("b", "sum"))
               .sort_values("k").reset_index(drop=True))
        pd.testing.assert_frame_equal(base, exp, check_dtype=False)
        n_committed = checkpoint.stats()["checkpoint_events"]
        assert n_committed >= 2
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_sink(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] \
            == n_committed

    def test_resume_bit_equal_across_overlap_modes(self, env4, rng,
                                                   monkeypatch):
        """Checkpoint state is dispatch-mode agnostic: pieces committed
        under the overlap scheduler resume bit-identically with overlap
        DISABLED (and the plan tokens match — the schedule is not part
        of the plan), so an operator can flip the escape hatch between
        a crash and its resume without losing the checkpoint."""
        from cylon_tpu import config
        ldf, rdf, lt, rt = _tables(env4, rng)
        monkeypatch.setattr(config, "PACKED_OVERLAP", True)
        base = _run_sink(lt, rt)
        n_committed = checkpoint.stats()["checkpoint_events"]
        assert n_committed >= 2
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        monkeypatch.setattr(config, "PACKED_OVERLAP", False)
        resumed = _run_sink(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] \
            == n_committed

    def test_partial_prefix_resume(self, env4, rng, monkeypatch):
        """Only a prefix committed (as after a mid-loop crash): resume
        restores the prefix and recomputes the rest — still bit-equal."""
        import json
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        # drop the last committed piece from the manifest, as if the
        # process died before its commit
        rank_dir = os.path.join(checkpoint.ckpt_dir(),
                                f"rank{0}")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        mpath = os.path.join(stage_dir, "MANIFEST.json")
        man = json.load(open(mpath, encoding="utf-8"))
        full = len(man["pieces"])
        assert full >= 2
        dropped = str(max(int(k) for k in man["pieces"]))
        del man["pieces"][dropped]
        man["epoch"] -= 1
        json.dump(man, open(mpath, "w", encoding="utf-8"))
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        s = checkpoint.stats()
        assert s["resume_fast_forwarded_pieces"] == full - 1
        assert s["checkpoint_events"] == 1   # only the dropped piece re-ran

    def test_corrupt_page_degrades_to_recompute(self, env4, rng,
                                                monkeypatch):
        """A flipped byte in a committed page: resume detects the hash
        mismatch, falls back to recomputing the stage's remaining
        pieces, and the result is STILL bit-equal — corruption never
        produces a wrong answer."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        rank_dir = os.path.join(checkpoint.ckpt_dir(), "rank0")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        page = next(p for p in sorted(os.listdir(stage_dir))
                    if p.startswith("piece_0.p"))
        path = os.path.join(stage_dir, page)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(raw))
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        s = checkpoint.stats()
        assert s["corrupt_pages"] >= 1
        assert s["resume_fast_forwarded_pieces"] == 0

    def test_injected_load_corruption(self, env4, rng, monkeypatch):
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        recovery.install_faults("ckpt.load::1=corrupt")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 0
        assert any(e["site"] == "ckpt.load" and e["action"] == "recompute"
                   for e in recovery.recovery_events())

    def test_plan_token_mismatch_starts_over(self, env4, rng, monkeypatch):
        """A stale checkpoint from a DIFFERENT plan (other chunk count)
        is never spliced in: the stage starts over and recomputes."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        _run_join(lt, rt, n_chunks=4)
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        out = _run_join(lt, rt, n_chunks=3)   # different plan, same stage id
        exp = (ldf.merge(rdf, on="k").sort_values(["k", "a", "b"])
               .reset_index(drop=True))
        pd.testing.assert_frame_equal(out[exp.columns], exp,
                                      check_dtype=False)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 0

    def test_injected_write_fault_records_event(self, env4, rng):
        """A non-corrupt/non-kill fault armed at ckpt.write is recorded
        like every other injection site (the soak's MAX_RECOVERY_EVENTS
        bound counts it) — and the ladder still converges."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        recovery.install_faults("ckpt.write::1=device_oom")

        def attempt(nc=4):
            return _run_join(lt, rt, n_chunks=nc)

        out = recovery.run_with_recovery(attempt, True, attempt, "test",
                                         env=env4)
        exp = (ldf.merge(rdf, on="k").sort_values(["k", "a", "b"])
               .reset_index(drop=True))
        pd.testing.assert_frame_equal(out[exp.columns], exp,
                                      check_dtype=False)
        assert any(e["site"] == "ckpt.write" and e["action"] == "injected"
                   for e in recovery.recovery_events())

    def test_resume_consensus_wire_math(self):
        """Single-controller identity + wire-range validation for the
        min-agree fast-forward vote, and unrestore() backs discarded
        restores out of the counter."""
        assert recovery.ckpt_resume_consensus(None, 0) == 0
        assert recovery.ckpt_resume_consensus(None, 7) == 7
        with pytest.raises(ValueError):
            recovery.ckpt_resume_consensus(None, -1)
        with pytest.raises(ValueError):
            recovery.ckpt_resume_consensus(None, 1 << 20)
        checkpoint._STATS["resume_fast_forwarded_pieces"] = 5
        checkpoint.unrestore(2)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 3
        checkpoint.reset_stats()

    def test_staged_only_manifest_is_ignored(self, env4, rng, monkeypatch):
        """Phase-2 atomicity: a manifest that was STAGED but never
        committed (crash between the write and the consensus rename)
        must not be restored from."""
        ldf, rdf, lt, rt = _tables(env4, rng)
        base = _run_join(lt, rt)
        rank_dir = os.path.join(checkpoint.ckpt_dir(), "rank0")
        stage_dir = os.path.join(rank_dir, sorted(os.listdir(rank_dir))[0])
        mpath = os.path.join(stage_dir, "MANIFEST.json")
        os.replace(mpath, mpath + ".staged")   # un-commit it
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        resumed = _run_join(lt, rt)
        _frames_bitequal(resumed, base)
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] == 0


# ---------------------------------------------------------------------------
# happy path + FINAL ladder rung
# ---------------------------------------------------------------------------

class TestHappyPathAndFinalRung:
    def test_disabled_means_zero_writes(self, env4, rng, monkeypatch,
                                        tmp_path):
        """With CYLON_TPU_CKPT_DIR unset the checkpoint layer is inert:
        no stage opened, no file written, counters stay zero."""
        monkeypatch.delenv("CYLON_TPU_CKPT_DIR", raising=False)
        _, _, lt, rt = _tables(env4, rng, n=800)
        _run_join(lt, rt)
        assert checkpoint._STAGE_SEQ.get(None, 0) == 0
        assert checkpoint.stats() == {"checkpoint_events": 0,
                                      "bytes_checkpointed": 0,
                                      "resume_fast_forwarded_pieces": 0,
                                      "corrupt_pages": 0}
        assert not (tmp_path / "ckpt").exists()

    def test_device_oom_abort_becomes_resumable(self, env4, rng):
        """The FINAL rung: an unrecoverable device OOM with checkpoints
        armed raises a typed ResumableAbort carrying the resume token
        (the checkpoint root), original fault on __cause__."""
        ldf, _, _, _ = _tables(env4, rng, n=1000)
        t = ct.Table.from_pandas(ldf, env4)
        from cylon_tpu.relational import groupby_aggregate
        recovery.install_faults("groupby.device_oom::*=device_oom")
        with pytest.raises(ResumableAbort) as ei:
            groupby_aggregate(t, "k", [("a", "sum")])
        assert ei.value.token == os.path.abspath(checkpoint.ckpt_dir())
        assert isinstance(ei.value.__cause__, DeviceOOMError)
        assert os.path.exists(os.path.join(checkpoint.ckpt_dir(),
                                           "RESUME_TOKEN.json"))
        acts = [e["action"] for e in recovery.recovery_events()
                if e["site"] == "groupby"]
        assert acts[-1] == "resumable_abort"

    def test_compiler_crash_takes_final_rung(self, env4):
        """An exhausted compiler-crash ladder (a non-fault exception for
        classify) still takes the FINAL rung when checkpoints are
        armed."""
        def boom():
            raise RuntimeError("tpu_compile_helper subprocess exit "
                               "signal SIGSEGV (11)")

        with pytest.raises(ResumableAbort) as ei:
            recovery.run_with_recovery(boom, False, None, "t", env=env4)
        assert ei.value.token
        assert "CYLON_TPU_RESUME=1" in str(ei.value)

    def test_without_ckpt_faults_stay_typed(self, env4, monkeypatch):
        """Un-armed sessions keep the PR 3 behavior exactly: the typed
        fault raises, no ResumableAbort, no files."""
        monkeypatch.delenv("CYLON_TPU_CKPT_DIR", raising=False)

        def boom():
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

        with pytest.raises(DeviceOOMError):
            recovery.run_with_recovery(boom, False, None, "t", env=env4)


# ---------------------------------------------------------------------------
# trimmed chaos soak (the cross-process kill-and-resume acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_trimmed():
    """scripts/chaos_soak.py with the three pinned schedules: SIGKILL
    mid-range-loop + resume fast-forward (ffwd > 0 asserted by the
    harness), corrupt-on-write and corrupt-on-load — every schedule must
    end bit-equal.  The full ≥20-schedule soak is the standalone CLI."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--seed", "5", "--schedules", "3", "--rows", "1200",
         "--chunks", "3"],
        capture_output=True, text=True, timeout=570, cwd=REPO)
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    assert "killed+resumed(ffwd=1)" in p.stdout, p.stdout[-2000:]
