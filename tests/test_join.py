"""Join operator tests against the pandas oracle.

Reference analog: cpp/test/join_test.cpp + python test_join.py / test_dist_rl.py
(same ops validated at world sizes 1, 4, 8 — the mpirun -np N dimension).
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import join_tables

from utils import assert_table_matches

HOWS = ["inner", "left", "right", "outer"]


def dfs(rng, nl=97, nr=53, lo=0, hi=30):
    ldf = pd.DataFrame({"k": rng.integers(lo, hi, nl),
                        "a": rng.random(nl),
                        "c": rng.integers(0, 5, nl)})
    rdf = pd.DataFrame({"k": rng.integers(lo, hi, nr),
                        "b": rng.random(nr),
                        "c": rng.integers(0, 5, nr)})
    return ldf, rdf


@pytest.mark.parametrize("envname", ["env1", "env4", "env8"])
@pytest.mark.parametrize("how", HOWS)
def test_join_single_key(request, rng, envname, how):
    env = request.getfixturevalue(envname)
    ldf, rdf = dfs(rng)
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    got = join_tables(lt, rt, "k", "k", how=how)
    exp = ldf.merge(rdf, on="k", how=how, suffixes=("_x", "_y"))
    assert_table_matches(got, exp, sort_by=list(exp.columns))


@pytest.mark.parametrize("how", HOWS)
def test_join_multi_key(env8, rng, how):
    ldf, rdf = dfs(rng)
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    got = join_tables(lt, rt, ["k", "c"], ["k", "c"], how=how)
    exp = ldf.merge(rdf, on=["k", "c"], how=how, suffixes=("_x", "_y"))
    assert_table_matches(got, exp, sort_by=list(exp.columns))


@pytest.mark.parametrize("how", ["inner", "left", "outer"])
def test_join_string_key(env8, rng, how):
    keys = ["ant", "bee", "cat", "dog", "elk", "fox"]
    ldf = pd.DataFrame({"k": rng.choice(keys[:5], 50), "a": rng.random(50)})
    rdf = pd.DataFrame({"k": rng.choice(keys[2:], 30), "b": rng.random(30)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    got = join_tables(lt, rt, "k", "k", how=how)
    exp = ldf.merge(rdf, on="k", how=how)
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_different_key_names(env4, rng):
    ldf = pd.DataFrame({"lk": rng.integers(0, 10, 40), "a": rng.random(40)})
    rdf = pd.DataFrame({"rk": rng.integers(0, 10, 30), "b": rng.random(30)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "lk", "rk", how="inner")
    exp = ldf.merge(rdf, left_on="lk", right_on="rk", how="inner")
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_null_keys_match(env4):
    # pandas merge matches NaN keys with each other; reference comparators
    # likewise treat nulls as equal — verify via string-null keys
    ldf = pd.DataFrame({"k": ["a", None, "b", None], "a": [1, 2, 3, 4]})
    rdf = pd.DataFrame({"k": ["a", None, "c"], "b": [10, 20, 30]})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "k", "k", how="inner")
    exp = ldf.merge(rdf, on="k", how="inner")
    assert_table_matches(got, exp, sort_by=["a", "b"])


def test_join_type_promotion(env4, rng):
    ldf = pd.DataFrame({"k": rng.integers(0, 10, 40).astype(np.int32),
                        "a": rng.random(40)})
    rdf = pd.DataFrame({"k": rng.integers(0, 10, 30).astype(np.int64),
                        "b": rng.random(30)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "k", "k", how="inner")
    exp = ldf.assign(k=ldf.k.astype(np.int64)).merge(rdf, on="k", how="inner")
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_empty_side(env4):
    ldf = pd.DataFrame({"k": np.array([], np.int64), "a": np.array([], np.float64)})
    rdf = pd.DataFrame({"k": np.array([1, 2], np.int64), "b": [1.0, 2.0]})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "k", "k", how="inner")
    assert got.row_count == 0
    got_r = join_tables(lt, rt, "k", "k", how="right")
    assert got_r.row_count == 2


def test_join_heavy_skew(env8, rng):
    # one dominant key (BASELINE skew config analog)
    ldf = pd.DataFrame({"k": np.where(rng.random(200) < 0.8, 7,
                                      rng.integers(0, 50, 200)),
                        "a": rng.random(200)})
    rdf = pd.DataFrame({"k": rng.integers(0, 50, 40), "b": rng.random(40)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    got = join_tables(lt, rt, "k", "k", how="inner")
    exp = ldf.merge(rdf, on="k", how="inner")
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_right_table_key_only(env4):
    """Right side contributes only the coalesced key column (regression:
    carry_right must be a bool and gather_columns must accept empty specs)."""
    import pandas as pd
    ldf = pd.DataFrame({"k": [1, 2, 3, 4], "a": [1., 2., 3., 4.]})
    rdf = pd.DataFrame({"k": [2, 3, 5]})
    for how in ("inner", "left", "outer"):
        j = join_tables(ct.Table.from_pandas(ldf, env4),
                        ct.Table.from_pandas(rdf, env4), "k", "k", how=how)
        exp = ldf.merge(rdf, on="k", how=how)
        assert j.row_count == len(exp), (how, j.row_count, len(exp))


class TestSemiAntiJoin:
    """LEFT SEMI / LEFT ANTI joins (round-5: the NOT-EXISTS operator family
    TPC-H Q16/Q21/Q22 need).  Output = filtered left rows, no expansion."""

    def _oracle(self, ldf, rdf, on, how):
        m = ldf[on].isin(set(rdf[on]))
        return ldf[m] if how == "semi" else ldf[~m]

    @pytest.mark.parametrize("how", ["semi", "anti"])
    def test_matches_pandas_w4(self, env4, rng, how):
        ldf = pd.DataFrame({"k": rng.integers(0, 60, 400).astype(np.int64),
                            "a": rng.random(400)})
        rdf = pd.DataFrame({"k": rng.integers(30, 90, 250).astype(np.int64),
                            "b": rng.random(250)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        out = join_tables(lt, rt, "k", "k", how=how).to_pandas()
        exp = self._oracle(ldf, rdf, "k", how)
        assert sorted(out["k"].tolist()) == sorted(exp["k"].tolist())
        assert np.isclose(out["a"].sum(), exp["a"].sum())

    @pytest.mark.parametrize("how", ["semi", "anti"])
    def test_local_w1(self, env1, rng, how):
        ldf = pd.DataFrame({"k": rng.integers(0, 30, 120).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(15, 45, 80).astype(np.int64)})
        lt = ct.Table.from_pandas(ldf, env1)
        rt = ct.Table.from_pandas(rdf, env1)
        out = join_tables(lt, rt, "k", "k", how=how).to_pandas()
        exp = self._oracle(ldf, rdf, "k", how)
        assert sorted(out["k"].tolist()) == sorted(exp["k"].tolist())

    def test_duplicates_emit_once(self, env4):
        # semi/anti never multiply rows, whatever the right multiplicity
        ldf = pd.DataFrame({"k": np.asarray([1, 1, 2, 3], np.int64)})
        rdf = pd.DataFrame({"k": np.asarray([1] * 50 + [3], np.int64)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        semi = join_tables(lt, rt, "k", "k", how="semi").to_pandas()
        anti = join_tables(lt, rt, "k", "k", how="anti").to_pandas()
        assert sorted(semi["k"].tolist()) == [1, 1, 3]
        assert anti["k"].tolist() == [2]

    def test_null_keys_match_nulls(self, env4):
        # pandas-merge semantics: null keys equal each other (like the
        # other join types here)
        ldf = pd.DataFrame({"k": pd.array([1, None, 2], dtype="Int64")})
        rdf = pd.DataFrame({"k": pd.array([None, 2], dtype="Int64")})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        semi = join_tables(lt, rt, "k", "k", how="semi").to_pandas()
        assert len(semi) == 2   # the null row and the 2 row
        anti = join_tables(lt, rt, "k", "k", how="anti").to_pandas()
        assert anti["k"].tolist() == [1]

    @pytest.mark.parametrize("how", ["semi", "anti"])
    def test_string_keys(self, env4, rng, how):
        lk = np.asarray([f"u{i}" for i in rng.integers(0, 40, 300)], object)
        rk = np.asarray([f"u{i}" for i in rng.integers(20, 60, 200)], object)
        ldf = pd.DataFrame({"k": lk})
        rdf = pd.DataFrame({"k": rk})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        out = join_tables(lt, rt, "k", "k", how=how).to_pandas()
        exp = self._oracle(ldf, rdf, "k", how)
        assert sorted(out["k"].tolist()) == sorted(exp["k"].tolist())

    @pytest.mark.parametrize("how", ["semi", "anti"])
    def test_skewed_probe(self, env8, rng, how, monkeypatch):
        from cylon_tpu import config
        monkeypatch.setattr(config, "SKEW_MIN_SHARE", 0.01)
        n = 4000
        lk = rng.integers(0, 500, n).astype(np.int64)
        lk[rng.random(n) < 0.9] = 7          # 90% one key
        ldf = pd.DataFrame({"k": lk})
        rdf = pd.DataFrame({"k": rng.integers(0, 500, 600).astype(np.int64)})
        lt = ct.Table.from_pandas(ldf, env8)
        rt = ct.Table.from_pandas(rdf, env8)
        out = join_tables(lt, rt, "k", "k", how=how).to_pandas()
        exp = self._oracle(ldf, rdf, "k", how)
        assert sorted(out["k"].tolist()) == sorted(exp["k"].tolist())


class TestOuterSkew:
    """Round-5: full outer joins get the heavy-key split (VERDICT weak #3)
    via the left-join ∪ anti-complement decomposition."""

    def test_outer_90pct_one_key_w8(self, env8, rng, monkeypatch):
        from cylon_tpu import config
        monkeypatch.setattr(config, "SKEW_MIN_SHARE", 0.01)
        n = 3000
        lk = rng.integers(0, 400, n).astype(np.int64)
        lk[rng.random(n) < 0.9] = 11
        ldf = pd.DataFrame({"k": lk, "a": rng.random(n)})
        rdf = pd.DataFrame({"k": rng.integers(200, 600, 800).astype(np.int64),
                            "b": rng.random(800)})
        lt = ct.Table.from_pandas(ldf, env8)
        rt = ct.Table.from_pandas(rdf, env8)
        out = join_tables(lt, rt, "k", "k", how="outer").to_pandas()
        exp = ldf.merge(rdf, on="k", how="outer")
        assert len(out) == len(exp)
        assert sorted(out["k"].tolist()) == sorted(exp["k"].tolist())
        assert np.isclose(out["a"].sum(), exp["a"].sum())
        assert np.isclose(out["b"].sum(), exp["b"].sum())
        assert int(out["b"].isna().sum()) == int(exp["b"].isna().sum())

    def test_outer_skew_with_string_payload(self, env8, rng, monkeypatch):
        from cylon_tpu import config
        monkeypatch.setattr(config, "SKEW_MIN_SHARE", 0.01)
        n = 2000
        lk = rng.integers(0, 200, n).astype(np.int64)
        lk[rng.random(n) < 0.85] = 3
        ldf = pd.DataFrame({"k": lk,
                            "s": [f"L{i%37}" for i in range(n)]})
        rdf = pd.DataFrame({"k": rng.integers(100, 300, 500).astype(np.int64),
                            "t": [f"R{i%23}" for i in range(500)]})
        lt = ct.Table.from_pandas(ldf, env8)
        rt = ct.Table.from_pandas(rdf, env8)
        out = join_tables(lt, rt, "k", "k", how="outer").to_pandas()
        exp = ldf.merge(rdf, on="k", how="outer")
        assert len(out) == len(exp)
        assert sorted(out["k"].tolist()) == sorted(exp["k"].tolist())
        assert (out["t"].dropna().value_counts().sort_index()
                .equals(exp["t"].dropna().value_counts().sort_index()))


class TestBroadcastJoin:
    """Small-side broadcast joins (round 5): the small table replicates,
    the big side never shuffles; reference analog Bcast(Table) + local
    join (net/communicator.hpp:51)."""

    def _mk(self, env, rng, n_big=3000, n_small=40):
        big = pd.DataFrame({"k": rng.integers(0, 50, n_big).astype(np.int64),
                            "a": rng.random(n_big)})
        small = pd.DataFrame({"k": np.arange(25, 25 + n_small,
                                             dtype=np.int64) % 60,
                              "b": rng.random(n_small)})
        return big, small, ct.Table.from_pandas(big, env), \
            ct.Table.from_pandas(small, env)

    @pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
    def test_small_right(self, env8, rng, how, monkeypatch):
        from cylon_tpu import config
        monkeypatch.setattr(config, "BROADCAST_JOIN_ROWS", 1000)
        big, small, bt, st = self._mk(env8, rng)
        out = join_tables(bt, st, "k", "k", how=how)
        if how in ("inner", "left"):
            assert out.grouped_by is None   # big side never co-located
            exp = big.merge(small, on="k", how=how)
            got = out.to_pandas()
            assert len(got) == len(exp)
            assert np.isclose(got["a"].sum(), exp["a"].sum())
            assert sorted(got["k"]) == sorted(exp["k"])
        else:
            m = big["k"].isin(set(small["k"]))
            exp = big[m] if how == "semi" else big[~m]
            assert sorted(out.to_pandas()["k"]) == sorted(exp["k"])

    def test_small_left_right_join(self, env8, rng, monkeypatch):
        from cylon_tpu import config
        monkeypatch.setattr(config, "BROADCAST_JOIN_ROWS", 1000)
        big, small, bt, st = self._mk(env8, rng)
        out = join_tables(st, bt, "k", "k", how="right").to_pandas()
        exp = small.merge(big, on="k", how="right")
        assert len(out) == len(exp)
        assert np.isclose(out["a"].sum(), exp["a"].sum())

    def test_no_shuffle_issued(self, env8, rng, monkeypatch):
        from cylon_tpu import config
        from cylon_tpu.relational import join as jmod
        monkeypatch.setattr(config, "BROADCAST_JOIN_ROWS", 1000)
        calls = []
        orig = jmod.shuffle_table
        monkeypatch.setattr(jmod, "shuffle_table",
                            lambda *a, **k: (calls.append(1) or
                                             orig(*a, **k)))
        big, small, bt, st = self._mk(env8, rng)
        join_tables(bt, st, "k", "k", how="inner").to_pandas()
        assert calls == []   # broadcast replaced both shuffles


class TestJoinTablesMulti:
    """Same-key N-way join: ONE co-partition per table (C17 parity,
    reference join.hpp:29 multi-table overload)."""

    def test_three_way_matches_pandas(self, env4, rng):
        n = 1500
        a = pd.DataFrame({"k": rng.integers(0, 80, n).astype(np.int64),
                          "a": rng.random(n)})
        b = pd.DataFrame({"k": rng.integers(0, 80, n).astype(np.int64),
                          "b": rng.random(n)})
        c = pd.DataFrame({"k": rng.integers(0, 80, 200).astype(np.int64),
                          "c": rng.random(200)})
        from cylon_tpu.relational import join_tables_multi
        out = join_tables_multi(
            [ct.Table.from_pandas(x, env4) for x in (a, b, c)],
            ["k", "k", "k"]).to_pandas()
        exp = a.merge(b, on="k").merge(c, on="k")
        assert len(out) == len(exp)
        for col in ("a", "b", "c"):
            assert np.isclose(out[col].sum(), exp[col].sum())

    def test_one_shuffle_per_table(self, env4, rng, monkeypatch):
        from cylon_tpu.relational import join as jmod
        from cylon_tpu.relational import join_tables_multi
        calls = []
        orig = jmod.shuffle_table
        monkeypatch.setattr(jmod, "shuffle_table",
                            lambda *a, **k: (calls.append(1) or
                                             orig(*a, **k)))
        n = 1200
        ts = [ct.Table.from_pandas(
            pd.DataFrame({"k": rng.integers(0, 60, n).astype(np.int64),
                          f"v{i}": rng.random(n)}), env4)
            for i in range(4)]
        out = join_tables_multi(ts, ["k"] * 4).to_pandas()
        assert len(calls) == 4   # one exchange per table, none repeated
        assert len(out) > 0

    def test_mixed_dtype_keys_promote_before_shuffle(self, env4, rng):
        # int64 vs int32 keys hash differently unpromoted; the N-way path
        # must promote BEFORE its one-shuffle-per-table co-partition
        from cylon_tpu.relational import join_tables_multi
        a = pd.DataFrame({"k": rng.integers(0, 50, 900).astype(np.int64),
                          "a": rng.random(900)})
        b = pd.DataFrame({"k": rng.integers(0, 50, 900).astype(np.int32),
                          "b": rng.random(900)})
        c = pd.DataFrame({"k": rng.integers(0, 50, 300).astype(np.int64),
                          "c": rng.random(300)})
        out = join_tables_multi(
            [ct.Table.from_pandas(x, env4) for x in (a, b, c)],
            ["k", "k", "k"]).to_pandas()
        exp = a.merge(b.assign(k=b["k"].astype(np.int64)), on="k") \
            .merge(c, on="k")
        assert len(out) == len(exp)
        for col in ("a", "b", "c"):
            assert np.isclose(out[col].sum(), exp[col].sum())

    def test_string_keys_multi(self, env4, rng):
        from cylon_tpu.relational import join_tables_multi
        mk = lambda n, lo, hi: pd.DataFrame(
            {"k": np.asarray([f"u{v}" for v in rng.integers(lo, hi, n)],
                             object),
             f"v{lo}": rng.random(n)})
        a, b, c = mk(800, 0, 40), mk(800, 20, 60), mk(200, 0, 60)
        out = join_tables_multi(
            [ct.Table.from_pandas(x, env4) for x in (a, b, c)],
            ["k", "k", "k"]).to_pandas()
        exp = a.merge(b, on="k").merge(c, on="k")
        assert len(out) == len(exp)
