"""Join operator tests against the pandas oracle.

Reference analog: cpp/test/join_test.cpp + python test_join.py / test_dist_rl.py
(same ops validated at world sizes 1, 4, 8 — the mpirun -np N dimension).
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import join_tables

from utils import assert_table_matches

HOWS = ["inner", "left", "right", "outer"]


def dfs(rng, nl=97, nr=53, lo=0, hi=30):
    ldf = pd.DataFrame({"k": rng.integers(lo, hi, nl),
                        "a": rng.random(nl),
                        "c": rng.integers(0, 5, nl)})
    rdf = pd.DataFrame({"k": rng.integers(lo, hi, nr),
                        "b": rng.random(nr),
                        "c": rng.integers(0, 5, nr)})
    return ldf, rdf


@pytest.mark.parametrize("envname", ["env1", "env4", "env8"])
@pytest.mark.parametrize("how", HOWS)
def test_join_single_key(request, rng, envname, how):
    env = request.getfixturevalue(envname)
    ldf, rdf = dfs(rng)
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    got = join_tables(lt, rt, "k", "k", how=how)
    exp = ldf.merge(rdf, on="k", how=how, suffixes=("_x", "_y"))
    assert_table_matches(got, exp, sort_by=list(exp.columns))


@pytest.mark.parametrize("how", HOWS)
def test_join_multi_key(env8, rng, how):
    ldf, rdf = dfs(rng)
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    got = join_tables(lt, rt, ["k", "c"], ["k", "c"], how=how)
    exp = ldf.merge(rdf, on=["k", "c"], how=how, suffixes=("_x", "_y"))
    assert_table_matches(got, exp, sort_by=list(exp.columns))


@pytest.mark.parametrize("how", ["inner", "left", "outer"])
def test_join_string_key(env8, rng, how):
    keys = ["ant", "bee", "cat", "dog", "elk", "fox"]
    ldf = pd.DataFrame({"k": rng.choice(keys[:5], 50), "a": rng.random(50)})
    rdf = pd.DataFrame({"k": rng.choice(keys[2:], 30), "b": rng.random(30)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    got = join_tables(lt, rt, "k", "k", how=how)
    exp = ldf.merge(rdf, on="k", how=how)
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_different_key_names(env4, rng):
    ldf = pd.DataFrame({"lk": rng.integers(0, 10, 40), "a": rng.random(40)})
    rdf = pd.DataFrame({"rk": rng.integers(0, 10, 30), "b": rng.random(30)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "lk", "rk", how="inner")
    exp = ldf.merge(rdf, left_on="lk", right_on="rk", how="inner")
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_null_keys_match(env4):
    # pandas merge matches NaN keys with each other; reference comparators
    # likewise treat nulls as equal — verify via string-null keys
    ldf = pd.DataFrame({"k": ["a", None, "b", None], "a": [1, 2, 3, 4]})
    rdf = pd.DataFrame({"k": ["a", None, "c"], "b": [10, 20, 30]})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "k", "k", how="inner")
    exp = ldf.merge(rdf, on="k", how="inner")
    assert_table_matches(got, exp, sort_by=["a", "b"])


def test_join_type_promotion(env4, rng):
    ldf = pd.DataFrame({"k": rng.integers(0, 10, 40).astype(np.int32),
                        "a": rng.random(40)})
    rdf = pd.DataFrame({"k": rng.integers(0, 10, 30).astype(np.int64),
                        "b": rng.random(30)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "k", "k", how="inner")
    exp = ldf.assign(k=ldf.k.astype(np.int64)).merge(rdf, on="k", how="inner")
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_empty_side(env4):
    ldf = pd.DataFrame({"k": np.array([], np.int64), "a": np.array([], np.float64)})
    rdf = pd.DataFrame({"k": np.array([1, 2], np.int64), "b": [1.0, 2.0]})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    got = join_tables(lt, rt, "k", "k", how="inner")
    assert got.row_count == 0
    got_r = join_tables(lt, rt, "k", "k", how="right")
    assert got_r.row_count == 2


def test_join_heavy_skew(env8, rng):
    # one dominant key (BASELINE skew config analog)
    ldf = pd.DataFrame({"k": np.where(rng.random(200) < 0.8, 7,
                                      rng.integers(0, 50, 200)),
                        "a": rng.random(200)})
    rdf = pd.DataFrame({"k": rng.integers(0, 50, 40), "b": rng.random(40)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    got = join_tables(lt, rt, "k", "k", how="inner")
    exp = ldf.merge(rdf, on="k", how="inner")
    assert_table_matches(got, exp, sort_by=list(exp.columns))


def test_join_right_table_key_only(env4):
    """Right side contributes only the coalesced key column (regression:
    carry_right must be a bool and gather_columns must accept empty specs)."""
    import pandas as pd
    ldf = pd.DataFrame({"k": [1, 2, 3, 4], "a": [1., 2., 3., 4.]})
    rdf = pd.DataFrame({"k": [2, 3, 5]})
    for how in ("inner", "left", "outer"):
        j = join_tables(ct.Table.from_pandas(ldf, env4),
                        ct.Table.from_pandas(rdf, env4), "k", "k", how=how)
        exp = ldf.merge(rdf, on="k", how=how)
        assert j.row_count == len(exp), (how, j.row_count, len(exp))
