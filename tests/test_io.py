"""IO tests (reference python test_io.py, test_parquet.py,
distributed_io.py read/write semantics)."""

import os

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.io import (read_csv, read_csv_dist, read_json, read_parquet,
                          read_parquet_dist, write_csv, write_csv_dist,
                          write_parquet, write_parquet_dist)


@pytest.fixture()
def data(rng):
    return pd.DataFrame({
        "k": rng.integers(0, 50, 100),
        "v": rng.random(100).round(6),
        "s": rng.choice(["aa", "bb", "cc"], 100),
    })


def test_csv_roundtrip(tmp_path, env4, data):
    p = tmp_path / "t.csv"
    data.to_csv(p, index=False)
    t = read_csv(p, env4)
    pd.testing.assert_frame_equal(t.to_pandas(), data, check_dtype=False)
    out = tmp_path / "o.csv"
    write_csv(t, out)
    pd.testing.assert_frame_equal(pd.read_csv(out), data, check_dtype=False)


def test_csv_glob_multifile(tmp_path, env4, data):
    for i in range(3):
        data.iloc[i * 30:(i + 1) * 30].to_csv(tmp_path / f"part{i}.csv",
                                              index=False)
    t = read_csv(str(tmp_path / "part*.csv"), env4)
    assert t.row_count == 90


def test_parquet_roundtrip(tmp_path, env4, data):
    p = tmp_path / "t.parquet"
    data.to_parquet(p, index=False)
    t = read_parquet(p, env4)
    pd.testing.assert_frame_equal(t.to_pandas(), data, check_dtype=False)
    out = tmp_path / "o.parquet"
    write_parquet(t, out)
    pd.testing.assert_frame_equal(pd.read_parquet(out), data,
                                  check_dtype=False)


def test_json_roundtrip(tmp_path, env4, data):
    p = tmp_path / "t.jsonl"
    data.to_json(p, orient="records", lines=True)
    t = read_json(p, env4)
    got = t.to_pandas()
    pd.testing.assert_frame_equal(got, data.reset_index(drop=True),
                                  check_dtype=False, check_exact=False)


def test_read_csv_dist_file_division(tmp_path, env4, data):
    sizes = [40, 25, 20, 15]
    off = 0
    for i, s in enumerate(sizes):
        data.iloc[off:off + s].to_csv(tmp_path / f"f{i}.csv", index=False)
        off += s
    t = read_csv_dist(str(tmp_path / "f*.csv"), env4)
    assert t.row_count == 100
    # rank i got file i (4 files, 4 ranks)
    assert t.valid_counts.tolist() == sizes


def test_read_parquet_dist_balancing(tmp_path, env4, data):
    p = tmp_path / "t.parquet"
    data.to_parquet(p, index=False, row_group_size=10)
    t = read_parquet_dist(str(p), env4)
    assert t.row_count == 100
    # greedy balancing: 10 groups of 10 rows over 4 ranks -> 20..30 each
    assert max(t.valid_counts) <= 30


def test_write_dist(tmp_path, env4, data):
    t = read_csv_dist_or_pandas = ct.Table.from_pandas(data, env4)
    files = write_csv_dist(t, str(tmp_path / "out.csv"))
    assert len(files) == 4
    back = pd.concat([pd.read_csv(f) for f in files], ignore_index=True)
    pd.testing.assert_frame_equal(back, data, check_dtype=False)
    pfiles = write_parquet_dist(t, str(tmp_path / "out.parquet"))
    back2 = pd.concat([pd.read_parquet(f) for f in pfiles],
                      ignore_index=True)
    pd.testing.assert_frame_equal(back2, data, check_dtype=False)
    from cylon_tpu.io import write_json_dist
    jfiles = write_json_dist(t, str(tmp_path / "out.json"))
    assert len(jfiles) == 4
    back3 = pd.concat([pd.read_json(f, orient="records", lines=True)
                       for f in jfiles], ignore_index=True)
    pd.testing.assert_frame_equal(back3, data, check_dtype=False)


def test_dist_writers_stream_per_shard(tmp_path, env8, rng):
    """write_*_dist must pull one shard at a time (no whole-table
    to_pandas): spy on Table.to_pandas and round-trip a table whose
    whole-table materialization is forbidden."""
    import cylon_tpu as ct
    from cylon_tpu.io import io as cio
    n = 16000
    df = pd.DataFrame({"k": np.arange(n, dtype=np.int64),
                       "s": np.asarray(["x", "y", "z"])[
                           rng.integers(0, 3, n)],
                       "v": rng.random(n)})
    t = ct.Table.from_pandas(df, env8)

    def boom(self):
        raise AssertionError("dist writer materialized the whole table")

    orig = ct.Table.to_pandas
    ct.Table.to_pandas = boom
    try:
        files = cio.write_parquet_dist(t, str(tmp_path / "part.parquet"))
        cfiles = cio.write_csv_dist(t, str(tmp_path / "part.csv"))
    finally:
        ct.Table.to_pandas = orig
    assert len(files) == 8 and len(cfiles) == 8
    back = pd.concat([pd.read_parquet(f) for f in files],
                     ignore_index=True)
    pd.testing.assert_frame_equal(
        back.sort_values("k").reset_index(drop=True),
        df.sort_values("k").reset_index(drop=True), check_dtype=False)


# ---------------------------------------------------------------------------
# scan pushdown (docs/robustness.md "Disk tier & scan pushdown"): the
# streaming row-group scan + the pipelined consumer
# ---------------------------------------------------------------------------

def _fact_dim(rng, n=20000, keys=300):
    fact = pd.DataFrame({"k": rng.integers(0, keys, n).astype(np.int64),
                         "v": rng.integers(0, 100, n).astype(np.int64)})
    dim = pd.DataFrame({"k": np.arange(keys, dtype=np.int64),
                        "w": rng.integers(0, 9, keys).astype(np.int64)})
    return fact, dim


def test_scan_parquet_dist_batches_cover_the_file(tmp_path, env4, rng):
    """Iterating the scan yields batch Tables in file/row-group order
    whose concatenation equals the full read — and never more than
    ~batch_rows per batch (row groups are the atomic unit)."""
    from cylon_tpu.io import scan_parquet_dist
    fact, _ = _fact_dim(rng)
    p = str(tmp_path / "fact.parquet")
    fact.to_parquet(p, row_group_size=1500, index=False)
    scan = scan_parquet_dist(p, env4, batch_rows=3000)
    assert scan.total_rows == len(fact)
    assert scan.column_names == ["k", "v"]
    parts = []
    for batch in scan:
        assert batch.row_count <= 3000
        parts.append(batch.to_pandas())
    got = pd.concat(parts, ignore_index=True)
    pd.testing.assert_frame_equal(got, fact, check_dtype=False)


def test_scan_column_projection(tmp_path, env4, rng):
    from cylon_tpu.io import scan_parquet_dist
    fact, _ = _fact_dim(rng, n=4000)
    p = str(tmp_path / "fact.parquet")
    fact.to_parquet(p, row_group_size=1000, index=False)
    scan = scan_parquet_dist(p, env4, batch_rows=2000, columns=["k"])
    assert scan.column_names == ["k"]
    for batch in scan:
        assert batch.column_names == ["k"]
    # the advertised schema follows the REQUESTED order, matching the
    # batches (a file-order answer would transpose a positional
    # consumer's same-dtype columns)
    scan2 = scan_parquet_dist(p, env4, batch_rows=2000,
                              columns=["v", "k"])
    assert scan2.column_names == ["v", "k"]
    for batch in scan2:
        assert batch.column_names == ["v", "k"]


def test_read_parquet_dist_batch_rows_switches_to_scan(tmp_path, env4,
                                                       rng):
    from cylon_tpu.io import ParquetScanSource, read_parquet_dist
    fact, _ = _fact_dim(rng, n=4000)
    p = str(tmp_path / "fact.parquet")
    fact.to_parquet(p, row_group_size=1000, index=False)
    scan = read_parquet_dist(p, env4, batch_rows=2000)
    assert isinstance(scan, ParquetScanSource)
    from cylon_tpu.status import CylonIOError
    with pytest.raises(CylonIOError):
        read_parquet_dist(p, env4, batch_rows=2000, engine="pyarrow")


def test_pipelined_scan_join_never_materializes_full_input(tmp_path,
                                                           env4, rng):
    """The out-of-core input acceptance: scan batches feed the join/
    groupby loop directly, the result equals the pandas oracle, and the
    PEAK ledger stays strictly below the full input's bytes — the scan
    side never enters the ledger at full size."""
    import cylon_tpu as ct
    from cylon_tpu.exec import GroupBySink, memory, pipelined_scan_join
    from cylon_tpu.io import scan_parquet_dist
    fact, dim = _fact_dim(rng)
    p = str(tmp_path / "fact.parquet")
    fact.to_parquet(p, row_group_size=1500, index=False)
    build = ct.Table.from_pandas(dim, env4)
    memory.reset_stats()
    sink = GroupBySink("k", [("v", "sum"), ("w", "sum")])
    pipelined_scan_join(scan_parquet_dist(p, env4, batch_rows=3000),
                        build, "k", "k", how="inner", sink=sink)
    got = sink.finalize().to_pandas().sort_values("k") \
        .reset_index(drop=True)
    exp = (fact.merge(dim, on="k").groupby("k", as_index=False)
           .agg(v_sum=("v", "sum"), w_sum=("w", "sum")))
    pd.testing.assert_frame_equal(got[["k", "v_sum", "w_sum"]], exp,
                                  check_dtype=False)
    full_bytes = sum(fact[c].to_numpy().nbytes for c in fact.columns)
    assert 0 < memory.ledger().peak < full_bytes, \
        (memory.ledger().peak, full_bytes)


def test_pipelined_scan_join_sinkless_matches_pandas(tmp_path, env4, rng):
    import cylon_tpu as ct
    from cylon_tpu.exec import pipelined_scan_join
    from cylon_tpu.io import scan_parquet_dist
    fact, dim = _fact_dim(rng, n=6000, keys=100)
    p = str(tmp_path / "fact.parquet")
    fact.to_parquet(p, row_group_size=1000, index=False)
    build = ct.Table.from_pandas(dim, env4)
    out = pipelined_scan_join(scan_parquet_dist(p, env4, batch_rows=2000),
                              build, "k", "k", how="inner")
    cols = ["k", "v", "w"]
    got = out.to_pandas()[cols].sort_values(cols).reset_index(drop=True)
    exp = fact.merge(dim, on="k")[cols].sort_values(cols) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_pipelined_scan_join_typed_limits(tmp_path, env4, rng):
    """right/outer (cross-batch unmatched-build bookkeeping) and empty
    scans surface typed, never silently wrong."""
    import cylon_tpu as ct
    from cylon_tpu.exec import pipelined_scan_join
    from cylon_tpu.io import scan_parquet_dist
    from cylon_tpu.status import InvalidError
    fact, dim = _fact_dim(rng, n=2000, keys=50)
    p = str(tmp_path / "fact.parquet")
    fact.to_parquet(p, row_group_size=500, index=False)
    build = ct.Table.from_pandas(dim, env4)
    scan = scan_parquet_dist(p, env4, batch_rows=1000)
    with pytest.raises(InvalidError):
        pipelined_scan_join(scan, build, "k", "k", how="outer")
