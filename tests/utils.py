"""Test helpers: pandas-oracle comparison.

Mirrors the reference's correctness oracle (SURVEY.md §4: expected CSVs or
unordered equality between distributed result and single-rank/pandas result):
every operator is validated against pandas on the same data, with
order-insensitive comparison for ops that don't define a global order.
"""

import numpy as np
import pandas as pd


def normalize(df: pd.DataFrame, sort_by=None) -> pd.DataFrame:
    out = df.copy()
    for c in out.columns:
        if out[c].dtype == object:
            # None -> NaN for uniform comparison
            out[c] = out[c].where(pd.notna(out[c]), np.nan)
    if sort_by is None:
        sort_by = list(out.columns)
    out = out.sort_values(sort_by, kind="mergesort").reset_index(drop=True)
    return out


def assert_frames_equal(got: pd.DataFrame, exp: pd.DataFrame, sort_by=None,
                        check_dtype=False, check_like=False):
    assert list(got.columns) == list(exp.columns), \
        f"columns {list(got.columns)} != {list(exp.columns)}"
    g = normalize(got, sort_by)
    e = normalize(exp, sort_by)
    pd.testing.assert_frame_equal(g, e, check_dtype=check_dtype,
                                  check_like=check_like)


def assert_table_matches(table, exp: pd.DataFrame, sort_by=None,
                         ordered=False):
    got = table.to_pandas()
    if ordered:
        assert_frames_equal(got.reset_index(drop=True),
                            exp.reset_index(drop=True),
                            sort_by=list(exp.columns), check_dtype=False)
        # also check exact order
        pd.testing.assert_frame_equal(
            got.reset_index(drop=True), exp.reset_index(drop=True),
            check_dtype=False)
    else:
        assert_frames_equal(got, exp, sort_by=sort_by)
