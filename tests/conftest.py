"""Test rig: force an 8-device virtual CPU mesh.

The moral equivalent of the reference's ``mpirun --oversubscribe`` localhost
testing (SURVEY.md §4.3): multi-chip is simulated by multi-device on one
host.  Must run before any test imports jax-heavy modules.

Note: the interpreter may start with a TPU plugin already registered (axon
sitecustomize imports jax at startup).  ``jax.config.update('jax_platforms')``
still wins as long as no backend has been initialized, so we set it here
rather than relying on env vars.
"""

import os

# read by the CPU client at first backend init
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# trace-safety sentinel (CYLON_TPU_TRACECHECK=1): every test runs under a
# device→host transfer guard — the ONLY sanctioned implicit D2H pulls are
# the cylon_tpu.utils.host funnel's (wrapped in explicit allow scopes) —
# and the retrace sentinel counts XLA compiles per (builder, shape
# signature); budget overruns (RT301/RT302) fail the session at exit.
# Off by default so the plain tier-1 run is byte-identical.
# ---------------------------------------------------------------------------
TRACECHECK = os.environ.get("CYLON_TPU_TRACECHECK") == "1"

# CYLON_TPU_COMPILE_COUNT=1 (set by tests/run_all.py): count XLA
# backend_compile events through the compile-lifecycle facade's
# monitoring listener and print one greppable `# COMPILE_COUNT` line per
# test file at session exit — the suite driver's per-file compile budget
# audit (docs/robustness.md "Compile lifecycle")
COMPILE_COUNT = os.environ.get("CYLON_TPU_COMPILE_COUNT") == "1"

if COMPILE_COUNT:
    from cylon_tpu.exec import compiler as _compiler
    _compiler.install_listener()

if TRACECHECK:
    from cylon_tpu.analysis import runtime as _rt
    _rt.enable()


@pytest.fixture(autouse=TRACECHECK)
def _tracecheck_transfer_guard():
    with jax.transfer_guard_device_to_host("disallow"):
        yield


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')")


def pytest_sessionfinish(session, exitstatus):
    if COMPILE_COUNT:
        from cylon_tpu.exec import compiler as _compiler
        st = _compiler.stats()
        names = sorted({os.path.basename(str(a)).split("::")[0]
                        for a in session.config.args}) or ["?"]
        print(f"\n# COMPILE_COUNT file={','.join(names)} "
              f"n={st['compile_events']} "
              f"seconds={st['compile_seconds']:g} "
              f"live={st['programs_live']}", flush=True)
    if not TRACECHECK:
        return
    from cylon_tpu.analysis import runtime as _rt
    violations = _rt.check_budgets()
    if violations:
        rep = "\n".join(f"  {rule} {msg}" for rule, _b, msg in violations)
        print(f"\n[tracecheck] retrace-sentinel violations:\n{rep}")
        session.exitstatus = 1
    else:
        st = _rt.state()
        n = sum(st.compiles.values())
        print(f"\n[tracecheck] retrace sentinel clean: "
              f"{n} compiling calls across {len(st.builds)} builders")


@pytest.fixture(scope="session")
def env8():
    """8-rank distributed env (one per virtual CPU device)."""
    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig
    return ct.CylonEnv(config=CPUMeshConfig())


@pytest.fixture(scope="session")
def env4():
    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig
    return ct.CylonEnv(config=CPUMeshConfig(world_size=4))


@pytest.fixture(scope="session")
def env1():
    import cylon_tpu as ct
    return ct.CylonEnv(config=ct.LocalConfig())


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
