"""Test rig: force an 8-device virtual CPU mesh.

The moral equivalent of the reference's ``mpirun --oversubscribe`` localhost
testing (SURVEY.md §4.3): multi-chip is simulated by multi-device on one
host.  Must run before any test imports jax-heavy modules.

Note: the interpreter may start with a TPU plugin already registered (axon
sitecustomize imports jax at startup).  ``jax.config.update('jax_platforms')``
still wins as long as no backend has been initialized, so we set it here
rather than relying on env vars.
"""

import os

# read by the CPU client at first backend init
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def env8():
    """8-rank distributed env (one per virtual CPU device)."""
    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig
    return ct.CylonEnv(config=CPUMeshConfig())


@pytest.fixture(scope="session")
def env4():
    import cylon_tpu as ct
    from cylon_tpu.ctx.context import CPUMeshConfig
    return ct.CylonEnv(config=CPUMeshConfig(world_size=4))


@pytest.fixture(scope="session")
def env1():
    import cylon_tpu as ct
    return ct.CylonEnv(config=ct.LocalConfig())


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
