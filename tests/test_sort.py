"""Distributed sort tests (reference cpp/test/dist_sort_test.cpp analog:
numeric types, empty tables, splitter edge cases, multi-key, desc order)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import sort_table


@pytest.mark.parametrize("envname", ["env1", "env4", "env8"])
@pytest.mark.parametrize("dtype", [np.int64, np.int32, np.float64, np.float32,
                                   np.uint32])
def test_sort_numeric(request, rng, envname, dtype):
    env = request.getfixturevalue(envname)
    data = pd.DataFrame({
        "k": rng.integers(0, 1000, 300).astype(dtype)
        if np.issubdtype(dtype, np.integer) else
        (rng.random(300) * 100).astype(dtype),
        "v": np.arange(300),
    })
    t = ct.Table.from_pandas(data, env)
    got = sort_table(t, "k").to_pandas()
    exp = data.sort_values("k", kind="stable").reset_index(drop=True)
    pd.testing.assert_frame_equal(got.reset_index(drop=True), exp,
                                  check_dtype=False)


@pytest.mark.parametrize("ascending", [True, False, [True, False]])
def test_sort_multi_key(env8, rng, ascending):
    data = pd.DataFrame({
        "a": rng.integers(0, 10, 200),
        "b": rng.random(200),
    })
    t = ct.Table.from_pandas(data, env8)
    got = sort_table(t, ["a", "b"], ascending=ascending).to_pandas()
    exp = data.sort_values(["a", "b"], ascending=ascending,
                           kind="stable").reset_index(drop=True)
    pd.testing.assert_frame_equal(got.reset_index(drop=True), exp,
                                  check_dtype=False)


def test_sort_strings(env8, rng):
    words = ["kiwi", "fig", "apple", "mango", "pear", "plum", "lime"]
    data = pd.DataFrame({"s": rng.choice(words, 150), "v": np.arange(150)})
    t = ct.Table.from_pandas(data, env8)
    got = sort_table(t, "s").to_pandas()
    exp = data.sort_values("s", kind="stable").reset_index(drop=True)
    pd.testing.assert_frame_equal(got.reset_index(drop=True), exp,
                                  check_dtype=False)


@pytest.mark.parametrize("nulls_position", ["first", "last"])
def test_sort_nulls(env4, nulls_position):
    data = pd.DataFrame({"s": ["b", None, "a", None, "c", "a"],
                         "v": [1, 2, 3, 4, 5, 6]})
    t = ct.Table.from_pandas(data, env4)
    got = sort_table(t, "s", nulls_position=nulls_position).to_pandas()
    exp = data.sort_values("s", na_position=nulls_position,
                           kind="stable").reset_index(drop=True)
    pd.testing.assert_frame_equal(got.reset_index(drop=True), exp,
                                  check_dtype=False)


def test_sort_nans(env4):
    data = pd.DataFrame({"f": [3.5, np.nan, -1.0, np.nan, 0.0, 99.9, -0.0]})
    t = ct.Table.from_pandas(data, env4)
    got = sort_table(t, "f").to_pandas()
    exp = data.sort_values("f", kind="stable").reset_index(drop=True)
    pd.testing.assert_frame_equal(got.reset_index(drop=True), exp,
                                  check_dtype=False)


def test_sort_empty(env4):
    data = pd.DataFrame({"k": np.array([], np.int64)})
    t = ct.Table.from_pandas(data, env4)
    got = sort_table(t, "k")
    assert got.row_count == 0


def test_sort_all_equal_keys(env8):
    # degenerate splitters: every sample identical
    data = pd.DataFrame({"k": np.full(100, 7), "v": np.arange(100)})
    t = ct.Table.from_pandas(data, env8)
    got = sort_table(t, "k").to_pandas()
    assert got["k"].tolist() == [7] * 100
    assert sorted(got["v"].tolist()) == list(range(100))


def test_sort_skewed(env8, rng):
    vals = np.where(rng.random(400) < 0.7, 5, rng.integers(0, 1000, 400))
    data = pd.DataFrame({"k": vals, "v": np.arange(400)})
    t = ct.Table.from_pandas(data, env8)
    got = sort_table(t, "k").to_pandas()
    assert got["k"].is_monotonic_increasing
    assert sorted(got["v"].tolist()) == list(range(400))


@pytest.mark.parametrize("method", ["initial", "regular"])
def test_sort_strategies_match(env8, rng, method):
    """Both reference sort strategies (DistributedSortRegularSampling
    table.cpp:620 / InitialSampling :692) produce the same globally
    sorted result; regular's quantile-exact splitters must also keep
    shards balanced under a skewed distribution."""
    n = 40_000
    keys = np.minimum(rng.zipf(1.4, n), 500).astype(np.int64)
    df = pd.DataFrame({"k": keys, "v": rng.random(n)})
    t = ct.Table.from_pandas(df, env8)
    out = sort_table(t, "k", method=method)
    got = out.to_pandas()
    assert got["k"].is_monotonic_increasing
    assert sorted(got["v"].tolist()) == sorted(df["v"].tolist())
    if method == "regular":
        top_run = int(pd.Series(keys).value_counts().iloc[0])
        assert int(out.valid_counts.max()) <= max(2 * (n // 8),
                                                  top_run + n // 8)


def test_sort_method_validation(env4, rng):
    t = ct.Table.from_pandas(pd.DataFrame({"k": [3, 1, 2]}), env4)
    from cylon_tpu.status import InvalidError
    with pytest.raises(InvalidError):
        sort_table(t, "k", method="bogus")
