"""Indexing tests (reference cpp/test/indexing_test.cpp, pycylon
test_indexing.py loc/iloc semantics)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.status import CylonIndexError, CylonKeyError


@pytest.fixture()
def df(env4, rng):
    data = pd.DataFrame({
        "id": [10, 20, 30, 40, 50, 60, 70, 80],
        "v": np.arange(8) * 1.5,
        "s": list("abcdefgh"),
    })
    return ct.DataFrame(data, env=env4), data


def test_iloc_scalar_slice(df):
    d, data = df
    assert d.iloc[3].to_pandas()["id"].tolist() == [40]
    assert d.iloc[-1].to_pandas()["id"].tolist() == [80]
    got = d.iloc[2:5].to_pandas()
    pd.testing.assert_frame_equal(got, data.iloc[2:5].reset_index(drop=True),
                                  check_dtype=False)


def test_iloc_list(df):
    d, data = df
    got = d.iloc[[1, 4, 6]].to_pandas()
    pd.testing.assert_frame_equal(
        got, data.iloc[[1, 4, 6]].reset_index(drop=True), check_dtype=False)


def test_iloc_out_of_range(df):
    d, _ = df
    with pytest.raises(CylonIndexError):
        d.iloc[99]


def test_loc_labels(df):
    d, data = df
    di = d.set_index("id")
    got = di.loc[[20, 50]].to_pandas()
    assert got.index.tolist() == [20, 50]
    assert got["s"].tolist() == ["b", "e"]


def test_loc_label_slice_inclusive(df):
    d, data = df
    di = d.set_index("id")
    got = di.loc[30:60].to_pandas()
    assert got.index.tolist() == [30, 40, 50, 60]  # both ends inclusive


def test_loc_string_index(df):
    d, _ = df
    ds = d.set_index("s")
    got = ds.loc[["c", "f"]].to_pandas()
    assert got["id"].tolist() == [30, 60]


def test_loc_missing_label(df):
    d, _ = df
    with pytest.raises(CylonKeyError):
        d.set_index("id").loc[[999]]


def test_loc_column_selection(df):
    d, _ = df
    di = d.set_index("id")
    got = di.loc[[20, 40], "v"].to_pandas()
    assert list(got.columns) == ["v"]
    assert got.index.tolist() == [20, 40]


def test_index_survives_filter_sort(df):
    d, data = df
    di = d.set_index("id")
    f = di[di["v"] > 3.0].sort_values("v", ascending=False)
    got = f.to_pandas()
    exp = data.set_index("id")
    exp = exp[exp["v"] > 3.0].sort_values("v", ascending=False)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_range_loc(df):
    d, data = df
    got = d.loc[2:4].to_pandas()  # inclusive on range index
    pd.testing.assert_frame_equal(got, data.iloc[2:5].reset_index(drop=True),
                                  check_dtype=False)


# -- multi-column index (C24, reference indexer.hpp:76 / index.hpp:36) ------

@pytest.fixture()
def mdf(env4):
    data = pd.DataFrame({
        "a": ["x", "x", "x", "y", "y", "z", "z", "z"],
        "b": [1, 2, 3, 1, 2, 1, 2, 3],
        "v": np.arange(8) * 2.0,
        "w": np.arange(8, dtype=np.int64),
    })
    return ct.DataFrame(data, env=env4), data


def test_multi_set_index_roundtrip(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"])
    assert m.columns == ["v", "w"]
    got = m.to_pandas()
    exp = data.set_index(["a", "b"])
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)
    back = m.reset_index().to_pandas()
    pd.testing.assert_frame_equal(back, data, check_dtype=False)


def test_multi_loc_full_tuple(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"])
    got = m.loc[("y", 2)].to_pandas()
    exp = data[(data.a == "y") & (data.b == 2)].set_index(["a", "b"])
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  exp.reset_index(drop=True),
                                  check_dtype=False)


def test_multi_loc_partial(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"])
    got = m.loc["z"].to_pandas()
    exp = data[data.a == "z"].set_index(["a", "b"])
    # level retention differs from pandas partial loc (which drops the
    # matched level, like the reference's table-out loc keeps all keys);
    # compare data content
    assert got["v"].tolist() == exp["v"].tolist()
    assert got["w"].tolist() == exp["w"].tolist()


def test_multi_loc_list_of_tuples(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"])
    got = m.loc[[("x", 1), ("z", 3)]].to_pandas()
    sel = data[((data.a == "x") & (data.b == 1))
               | ((data.a == "z") & (data.b == 3))]
    assert sorted(got["w"].tolist()) == sorted(sel["w"].tolist())


def test_multi_loc_slice_lexicographic(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"])
    got = m.loc[("x", 2):("z", 1)].to_pandas()
    exp = (data.set_index(["a", "b"]).sort_index()
           .loc[("x", 2):("z", 1)])
    assert sorted(got["w"].tolist()) == sorted(exp["w"].tolist())


def test_multi_loc_missing_raises(mdf):
    d, _ = mdf
    m = d.set_index(["a", "b"])
    with pytest.raises(CylonKeyError):
        m.loc[("q", 9)]
    with pytest.raises(CylonKeyError):
        m.loc[[("x", 1), ("q", 9)]]
    with pytest.raises(CylonKeyError):
        m.loc[("x", 1, 5)]


def test_multi_loc_rows_cols_form(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"])
    got = m.loc[[("y", 1)], "v"].to_pandas()
    assert got.columns.tolist() == ["v"] or got["v"].notna().all()
    sel = data[(data.a == "y") & (data.b == 1)]
    assert got["v"].tolist() == sel["v"].tolist()


def test_multi_index_survives_filter_sort(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"])
    f = m[m["w"] >= 3].sort_values("v", ascending=False)
    got = f.to_pandas()
    exp = (data[data.w >= 3].sort_values("v", ascending=False)
           .set_index(["a", "b"]))
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_multi_set_index_keep_columns(mdf):
    d, data = mdf
    m = d.set_index(["a", "b"], drop=False)
    assert "a" in m.columns and "b" in m.columns
    got = m.to_pandas()
    exp = data.set_index(["a", "b"], drop=False)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)
