"""TS109 fixture: direct ledger admission/eviction calls outside the
serving scheduler (``cylon_tpu/exec/scheduler.py``) and the ledger
module itself (``cylon_tpu/exec/memory.py``).  Admission must be
scheduler-mediated — ``scheduler.admit_allocation`` / ``free_pressure``
/ ``spill_retry`` — so the multi-tenant serving tier's per-session
footprint attribution, admission-wait accounting and cross-tenant
eviction bookkeeping see every decision (docs/serving.md)."""


def pack_without_scheduler(env, memory, nbytes):
    # TS109: an operator admitting its own allocation bypasses the
    # serving tier's footprint attribution
    memory.ensure_headroom(env, nbytes)


def guard_without_scheduler(memory, need):
    # TS109: rank-local eviction shortcut taken behind the scheduler
    memory.try_free(need)
    # TS109: the ladder's spill rung invoked directly
    return memory.spill_for_retry()


def evict_by_hand(ledger, budget):
    # TS109: hand-rolled LRU eviction forks the eviction order away
    # from the consensus'd admission path
    ledger.evict_n(2)
    ledger.evict_until(1 << 20, budget)
