"""Known-bad fixture: raw compilation entry points outside the
compile-lifecycle facade (TS117).  Every compile must ride
``utils.cache.jit`` (deferring to ``exec/compiler.jit``) or
``exec/compiler.aot_compile`` so the bounded compile ledger, the
crash-quarantine intent journal, the watchdog and the persistent-cache
manifest see it."""

from functools import partial

import jax


def raw_jit_call(fn, x):
    # TS117: raw jax.jit call
    return jax.jit(fn)(x)


@partial(jax.jit, static_argnames=("k",))  # TS117: raw partial argument
def raw_jit_decorated(x, k):
    return x * k


def raw_pjit_call(fn):
    from jax.experimental.pjit import pjit

    # TS117: bare pjit is always raw (the facade only re-exports jit)
    return pjit(fn)


def raw_aot_chain(fn, x):
    # TS117: .lower(...).compile() AOT chain bypasses aot_compile
    return fn.lower(x).compile()


def fine_facade(fn, x):
    from cylon_tpu.utils.cache import jit

    # clean: bare `jit` is the sanctioned cache-layer re-export
    return jit(fn)(x)


def fine_regex(pattern, text):
    import re

    # clean: .compile whose receiver is not a .lower(...) call
    return re.compile(pattern).match(text)


def fine_string_case(s):
    # clean: str.lower without a trailing .compile
    return s.lower()
