"""TS103 fixture: jax.jit wrapper without static_argnums for a parameter
that drives Python control flow — tracers crash it, every distinct value
retraces it."""

import jax
import jax.numpy as jnp


def kernel(x, mode):
    if mode == "double":                 # needs mode declared static
        return x * 2
    return x + 1


jitted = jax.jit(kernel)                 # TS103: no static_argnums


def good_kernel(x, mode):
    if mode == "double":
        return x * 2
    return x + 1


# properly declared: not flagged
good = jax.jit(good_kernel, static_argnames=("mode",))
