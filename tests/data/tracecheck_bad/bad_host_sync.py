"""TS101 fixture: host-sync calls inside a traced body — each one is a
device→host round-trip per call (or a trace error under jit)."""

import numpy as np

import jax
import jax.numpy as jnp

from cylon_tpu.utils.host import host_array

shard_map = jax.shard_map


def build(mesh):
    def per_shard(vc, col):
        counts = np.asarray(vc)          # TS101: implicit D2H pull
        top = col.max().item()           # TS101: scalar host pull
        host = host_array(col)           # TS101: the framework pull funnel
        scale = float(jnp.sum(col))      # TS101: concretizing cast
        _ = jax.device_get(vc)           # TS101: explicit D2H inside trace
        return col * scale + counts[0] + top + host[0]

    return jax.jit(shard_map(per_shard, mesh=mesh,
                             in_specs=None, out_specs=None))
