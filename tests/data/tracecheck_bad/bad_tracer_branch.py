"""TS102 fixture: Python control flow on tracer-derived values inside a
shard_map body."""

import jax
import jax.numpy as jnp

shard_map = jax.shard_map


def build(mesh):
    def per_shard(vc, col):
        total = jnp.sum(col)
        if total > 0:                    # TS102: branch on a tracer
            col = col * 2
        while total > 1:                 # TS102: loop on a tracer
            total = total / 2
        return col

    return jax.jit(shard_map(per_shard, mesh=mesh,
                             in_specs=None, out_specs=None))
