"""TS111 fixture: reads of a FOREIGN rank's checkpoint directory —
``rank<r>`` paths constructed off the checkpoint dir — outside
``cylon_tpu/exec/checkpoint.py``.  The elastic re-shard path
(``Stage.load_foreign_pieces``) is the one sanctioned cross-rank
reader: it sha-verifies every page, resolves the manifest GENERATION
(a post-reshard rewrite supersedes stale old-world rank dirs) and
min-votes the adoption over the live mesh.  An ad-hoc read sees none
of that and can splice a stale generation's or a torn write's state
into a resume."""

import json
import os


def peek_peer_manifest(ckpt_dir, r):
    # TS111: foreign rank dir constructed by hand off the ckpt root
    man = os.path.join(ckpt_dir, f"rank{r}", "stage000-pipelined_join",
                       "MANIFEST.json")
    with open(man, encoding="utf-8") as f:
        return json.load(f)


def steal_rank0_page(ckpt_dir):
    # TS111: literal rank segment, same hazard
    path = os.path.join(ckpt_dir, "rank0/stage000-x/piece_0.p0")
    with open(path, "rb") as f:
        return f.read()
