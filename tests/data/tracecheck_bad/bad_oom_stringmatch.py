"""TS105 fixture: except handlers classifying OOM by message text — the
typed fault taxonomy (cylon_tpu.status + cylon_tpu.exec.recovery.classify)
is the sanctioned boundary; ad-hoc string matching forks the recovery
decision away from the rank-coherent consensus ladder."""


def retry_on_oom(op, fallback):
    try:
        return op()
    except Exception as e:  # noqa: BLE001
        if "RESOURCE_EXHAUSTED" in str(e):     # TS105: string-matched OOM
            return fallback()
        raise


def swallow_oom(op):
    try:
        return op()
    except RuntimeError as e:
        msg = str(e)
        if "out of memory" in msg.lower():     # TS105: same hazard, lowercase
            return None
        raise


def nested_retry(op, fb):
    try:
        return op()
    except Exception as e:  # noqa: BLE001
        try:
            return fb()
        except Exception as e2:  # noqa: BLE001
            # ONE finding despite two enclosing handlers
            if "Out of memory" in str(e2):     # TS105
                return None
            raise e from e2
