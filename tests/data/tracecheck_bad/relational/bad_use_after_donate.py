"""TS108 fixture: reads of buffers after they were donated into a
jitted program.  The path puts this under a ``relational/`` directory,
where the rule is in scope."""

import jax


def _builder(mesh, donate=()):
    def go(carry, state):
        return carry + state

    return jax.jit(go, donate_argnums=donate)


def jit_wrapper_then_reads(buf, other):
    fn = jax.jit(lambda x, y: x * y, donate_argnums=(0,))
    out = fn(buf, other)
    return out + buf            # TS108: buf donated two lines up


def builder_kw_then_reads(mesh, carry, state):
    fn = _builder(mesh, donate=(0, 1))
    out = fn(carry, state)
    if carry is not None:       # TS108: carry read after donation
        out = out
    return out, state           # TS108: state read after donation


def immediate_apply_then_reads(mesh, carry, state):
    out = _builder(mesh, donate=(0,))(carry, state)
    return out, carry           # TS108: carry donated on the line above


def conditional_idiom_then_reads(mesh, carry, state, flag):
    fn = _builder(mesh, donate=(0,) if flag else ())
    out = fn(carry, state)
    return out + carry          # TS108: the conditional idiom still counts


def fine_rebind_clears(mesh, carry, state):
    fn = _builder(mesh, donate=(0,))
    carry = fn(carry, state)    # rebinding clears the donated mark
    return carry                # ok: this is the program's output


def fine_del_then_fresh(mesh, carry, state):
    out = _builder(mesh, donate=(0, 1))(carry, state)
    del carry, state            # ok: dropped, never read again
    return out


def fine_unknown_positions(mesh, carry, state, donate):
    fn = _builder(mesh, donate=donate)   # not statically resolvable
    out = fn(carry, state)
    return out, carry           # ok: untracked (under-approximation)
