"""Known-bad fixture: checkpoint artifacts written outside
exec/checkpoint.py (TS107) — a relational operator dumping piece state
straight into CYLON_TPU_CKPT_DIR bypasses the content-hash pages and the
two-phase rank-coherent manifest commit, so a resume could restore torn
or rank-divergent state."""

import os
import pickle

import numpy as np


def sneaky_piece_dump(arr, i):
    ckpt_dir = os.environ["CYLON_TPU_CKPT_DIR"]
    np.save(os.path.join(ckpt_dir, f"piece_{i}.npy"), arr)  # TS107
    with open(os.path.join(ckpt_dir, "manifest.json"), "w") as f:  # TS107
        f.write("{}")


def sneaky_meta_pickle(meta, path_under_ckpt_dir):
    with open(path_under_ckpt_dir, "wb") as fh:  # TS107 (ckpt-named path)
        pickle.dump(meta, fh)  # not flagged itself: args carry no ckpt name


def fine_non_checkpoint_io(arr, scratch_path):
    np.save(scratch_path, arr)  # NOT flagged: not a checkpoint path



def sneaky_restore(i):
    ckpt_dir = os.environ.get("CYLON_TPU_CKPT_DIR", "")
    return np.load(os.path.join(ckpt_dir, f"piece_{i}.npy"))  # TS107
