"""TS118 fixture: integrity-audit decisions outside the exec/integrity
facade — fingerprint primitives called directly from an operator module,
or the typed ``DataIntegrityError`` constructed/raised there.  The
facade's verb wrappers are what guarantee the rank-coherent fingerprint
vote lands BEFORE the raise/proceed decision."""


def my_audit(mesh, table, tgt, cols, integ, DataIntegrityError):
    # flagged: the whole-table fingerprint primitive called directly —
    # skips the consensus vote and the audit-stats accounting
    fp = integ.table_fingerprint(table)
    # flagged: the partition primitive, same hazard
    fp2 = integ.partition_fingerprint(mesh, cols, targets=tgt)
    # flagged: a direct vote out of sequence
    integ.fingerprint_consensus(mesh, fp)
    # flagged: the registered builder invoked outside the facade
    integ._fingerprint_fn(mesh, 4, 2, "prefix")
    if fp != fp2:
        # flagged: a rank-local raise — deserts the other ranks
        # mid-collective instead of voting first
        raise DataIntegrityError("mismatch", site="shuffle.recv")
    return fp


def my_check(ok, DataIntegrityError):
    if not ok:
        # flagged: constructing the typed fault outside the facade
        err = DataIntegrityError("bad", site="topo.exchange")
        return err
    return None


def fine_route(table, outs, per_dest, mesh, tgt, cols, integ):
    # NOT flagged: the sanctioned facade verbs — the vote precedes the
    # raise/proceed decision inside them
    integ.conserve_exchange(None, per_dest, 0, 8)
    if integ.armed():
        integ.verify_exchange(mesh, tgt, cols, outs, per_dest)
        integ.audit_table(table, site="skew.stitch", phase="post_stitch")
    return outs
