"""TS113 fixture: plan-node push/pop outside the obs/plan.py
context-manager facade — operator modules (relational/, exec/, stream/)
must open plan nodes via ``plan.node(...)``/``plan.annotate(...)``."""


def my_operator(table, plan):
    # flagged: raw push leaves the query-scoped stack unbalanced when a
    # typed fault unwinds before the matching pop
    n = plan.push_node("join", {"how": "inner"}, None)
    out = table
    # flagged: the raw inverse, same hazard
    plan.pop_node(n)
    return out


def my_other_operator(push_node):
    # flagged: bare-name call of the stack primitive
    push_node("groupby", {}, None)


def fine_operator(table, plan):
    # NOT flagged: the sanctioned context-manager facade
    with plan.node("sort", by=("k",)) as pn:
        plan.annotate(route="sample_sort")
        return table, pn
