"""TS115 fixture: skew-plan decisions outside the relational/skew.py
plan facade — split-set construction, salt assignment and the
``Code.SkewPlan`` vote must run through detect/finalize_or_none/adopt/
split_exchange so every rank enters ONE voted exchange plan."""

import numpy as np


def my_split(mesh, datas, valids, vc, plan, shf, SkewPlan,
             skew_plan_consensus):
    # flagged: the split-targets primitive called directly — skips the
    # facade's finalize guard and the pre-exchange vote
    tgt = shf.skew_split_targets(mesh, datas, valids, vc, 1, (True,),
                                 (False,), (), plan.src_off, plan.fanout,
                                 plan.start)
    # flagged: ad-hoc plan construction outside the facade
    p = SkewPlan(8, ("k",), [], [], np.zeros(1, np.uint32),
                 np.zeros(1), np.zeros(1, np.int32), np.ones(1, np.int32))
    # flagged: a direct vote out of sequence
    skew_plan_consensus(mesh, 42)
    return tgt, p


def my_rebalance(plan):
    # flagged: post-vote salt mutation — desyncs the voted plan hash
    plan.fanout = plan.fanout * 2
    # flagged: split-set anchor mutation, same hazard
    plan.start = (plan.start + 1) % 8
    return plan


def fine_route(probe, build, env, skewmod):
    # NOT flagged: the sanctioned facade sequence
    plan = skewmod.detect(probe, ["k"], env)
    if plan is not None:
        plan = skewmod.finalize_or_none(plan, probe, ["k"], build, ["k"])
    if plan is not None:
        skewmod.adopt(plan, env)
        return skewmod.split_exchange(probe, ["k"], build, ["k"], plan)
    return None


def fine_reader(plan):
    # NOT flagged: reading plan fields is how the stitch works
    return int(plan.fanout.sum()) + int(plan.start[0])
