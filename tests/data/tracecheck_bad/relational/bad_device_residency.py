"""TS106 fixture: bare device_put/device_get of lane-sized arrays inside
a ``relational/`` module — residency changes of operator state must go
through the exec/memory HBM ledger (register/evict/upload_window) so
budget and spill decisions stay accounted and rank-coherent.  This file
lives under a ``relational/`` directory on purpose: the rule is scoped
to the operator directories (exec/memory itself is exempt)."""

import jax
import numpy as np


def stash_matrix_on_host(mat):
    # TS106: an unaccounted pull bypasses the spill tier's bookkeeping
    # (and the utils.host transfer funnel)
    return jax.device_get(mat)


def restore_matrix(host_mat, sharding):
    # TS106: an unaccounted upload skews every ledger budget decision
    return jax.device_put(np.asarray(host_mat), sharding)
