"""Known-bad fixture for TS110: GroupBySink partial state mutated, and
window-lifetime ledger entry points called, outside cylon_tpu/stream/
(this file stands in for an operator module — streaming state
transitions must ride the sink absorb/snapshot API and the watermark
close lifecycle)."""


def poke_sink(sink, part, reg):
    # direct write of the sink's partial list: a live IncrementalView's
    # read() no longer matches the rows absorbed
    sink._parts = [part]                      # TS110
    sink._parts.append(part)                  # TS110
    sink._adopted = 0                         # TS110
    sink._regs.clear()                        # TS110


def poke_window(memory, arrays, reg):
    # window-lifetime residency managed outside the stream package:
    # the close lifecycle's eviction accounting never sees these
    r = memory.register_window("rogue", arrays)   # TS110
    memory.evict_release(reg)                     # TS110
    return r
