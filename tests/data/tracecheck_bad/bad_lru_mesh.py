"""TS104 fixture: program builder lru_cache'd on a live Mesh — the global
cache pins the mesh (and every executable built for it) for the process
lifetime, and object-identity keys silently recompile for rebuilt
meshes."""

from functools import lru_cache

import jax
from jax.sharding import Mesh

shard_map = jax.shard_map


@lru_cache(maxsize=256)
def _builder_fn(mesh: Mesh, w: int, cap: int):   # TS104
    def per_shard(col):
        return col * w

    return jax.jit(shard_map(per_shard, mesh=mesh,
                             in_specs=None, out_specs=None))


@lru_cache(maxsize=256)
def _spec_fn(spec: tuple):                       # mesh-free: not flagged
    return spec
