"""TS112 fixture: module-level mutable counter tables outside
cylon_tpu/obs/ — each must route through the metrics registry facade
(cylon_tpu.obs.metrics counter/group/namespace)."""

# the classic ad-hoc stats table — flagged
_STATS = {"spill_events": 0, "bytes_spilled": 0}

# other counter-table spellings — flagged
_EVICTION_COUNTERS = {"cold": 0, "hot": 0}
QUERY_METRICS = dict(served=0, failed=0)

# NOT flagged: name does not read as a counter table
_CACHE = {"a": 1}

# NOT flagged: registry-backed view (the sanctioned migration shim) —
# the rule keys on the mutable literal, not the name alone
import sys  # noqa: E402 — stand-in binding, fixtures never import cylon_tpu

_RESUME_STATS = sys.intern("not-a-dict-literal")


def bump():
    # NOT flagged: function-local tables are transient working state,
    # not module-lifetime telemetry
    local_stats = {"n": 0}
    local_stats["n"] += 1
    return local_stats
