"""CX403 fixture: plan vote after its first dependent collective.

The ``Code.SkewPlan`` vote must dominate the split exchange whose shape
it decides; here the vote lands after the dependent collective, so a
rank that faults mid-exchange resumes against an un-voted plan.  Must
fire CX403 and nothing else.
"""


# TS115 suppressed: this fixture exercises the CX403 ordering check in
# isolation — the facade-scoping hazard has its own fixture
# (relational/bad_skew_salt.py).
def vote_after_dependent(mesh, table, plan, split_exchange, skew_plan_consensus):  # tracecheck: off[TS115]
    parts = split_exchange(mesh, table, plan)     # dependent collective
    skew_plan_consensus(mesh, plan.plan_hash())   # CX403: vote too late
    return parts
