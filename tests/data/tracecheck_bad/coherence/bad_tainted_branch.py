"""CX401 fixture: a rank-local branch between two data collectives.

``probe`` is injector state (rank-local); the branch sits after one
``exchange`` and before the next with no consensus vote in between, so
ranks that disagree about ``armed`` diverge mid-sequence.  Must fire
CX401 and nothing else.
"""


def tainted_branch_between(mesh, table, probe, exchange):
    out = exchange(mesh, table)             # first data collective
    kind, armed = probe("fixture.recv_guard")   # rank-local injector state
    if armed:                               # CX401: divergent decision
        kind = "armed"                      # (no collectives in the arm)
    return exchange(mesh, out)              # second data collective
