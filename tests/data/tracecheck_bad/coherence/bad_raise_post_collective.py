"""CX404 fixture: untyped rank-local raise after a collective.

The raise fires from an except handler after a data collective was
entered, without a consensus'd typed status — peer ranks sit in the
next collective while this rank unwinds with a foreign exception.  Must
fire CX404 and nothing else.
"""


def raise_after_collective(mesh, table, exchange, write_page):
    out = exchange(mesh, table)             # data collective entered
    try:
        write_page(out)
    except OSError:                         # rank-local fault...
        raise RuntimeError("page write failed")   # CX404: untyped raise
    return out
