"""CX402 fixture: path-dependent collective sequence.

A branch on injector state issues a different collective on each arm —
ranks that disagree about ``armed`` enter mismatched collectives and
deadlock.  Must fire CX402 and nothing else.
"""


def reordered_on_one_path(mesh, table, probe, exchange, allgather_table):
    kind, armed = probe("fixture.plan")     # rank-local injector state
    if armed:                               # CX402: arms issue different
        table = allgather_table(mesh, table)    # collective sequences
    else:
        table = exchange(mesh, table)
    return table
