"""TS116 fixture: topology decisions outside the cylon_tpu/topo plan
facade — slice-map construction, tier/gateway assignment and the
``Code.TopoPlan`` vote must run through topology/hier_plan/
ensure_adopted/two_hop so every rank routes ONE voted hop plan."""

import numpy as np


def my_tier_map(mesh, counts, TopologyPlan, topo_plan_consensus,
                hop_counts, topo):
    # flagged: ad-hoc plan construction outside the facade — skips the
    # canonical hash and the pre-collective vote
    plan = TopologyPlan(topo, "hierarchical")
    # flagged: the gateway-scheme primitive called directly
    c1, c2 = hop_counts(counts, 2)
    # flagged: a direct vote out of sequence
    topo_plan_consensus(mesh, 42)
    return plan, c1, c2


def my_gateway(dest, topomod):
    # flagged: tier/gateway assignment outside the facade
    return topomod.gateway_of(dest, 0, 4)


def my_rebalance(plan):
    # flagged: post-vote tier-map mutation — desyncs the voted hash and
    # the grouped collectives' membership
    plan.n_slices = 4
    # flagged: route flip after adoption, same hazard
    plan.route = "flat"
    return plan


def fine_route(mesh, env, topomod, exchange_mod, tgt, counts, cols):
    # NOT flagged: the sanctioned facade sequence
    hplan = topomod.hier_plan(mesh)
    if hplan is not None:
        topomod.ensure_adopted(mesh, hplan)
        return exchange_mod.two_hop(mesh, hplan, tgt, counts, cols, 8)
    t = topomod.topology(mesh)
    # NOT flagged: plain field reads and non-plan attribute assigns
    n = t.n_slices + np.int64(0)
    return n
