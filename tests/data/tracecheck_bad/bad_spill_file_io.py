"""Known-bad fixture: spill-file path construction / raw spill page IO
outside exec/memory.py (TS114).  The disk tier's pages are only safe
behind the ledger facade — content-hashed at demote, sha-verified at
promote, written/read under the bounded IO retry."""

import os

import numpy as np


def sneaky_page_dump(arr, spill_dir, owner):
    # TS114 twice: the np.save IO call AND the os.path.join path build
    # both name the spill page
    np.save(os.path.join(spill_dir, owner + ".spill.npy"), arr)


def sneaky_page_read(spill_dir, owner):
    # TS114 twice: np.load + the join
    return np.load(os.path.join(spill_dir, owner + ".spill.npy"))


def sneaky_env_page(owner):
    # TS114 once: the path build off CYLON_TPU_SPILL_DIR; the open()
    # below reads through a neutral name — under-approximated, like the
    # rest of the pass
    path = os.path.join(os.environ["CYLON_TPU_SPILL_DIR"], owner)
    with open(path, "rb") as f:
        return f.read()


def fine_non_spill_io(arr, path):
    # clean: ordinary IO with no spill-path mention
    np.save(path, arr)


def fine_spill_counters(stats):
    # clean: the WORD spill outside the on-disk naming never fires
    return stats["spill_events"] + stats["bytes_spilled"]
