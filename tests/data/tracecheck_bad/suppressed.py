"""Suppression fixture: the same hazards as the bad_* modules, silenced
with `# tracecheck: off[RULE]` — the analyzer must report nothing."""

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

shard_map = jax.shard_map


@lru_cache(maxsize=256)
def _builder_fn(mesh: Mesh, w: int):  # tracecheck: off[TS104]
    def per_shard(vc, col):
        counts = np.asarray(vc)  # tracecheck: off[TS101]
        total = jnp.sum(col)
        if total > 0:  # tracecheck: off[TS102]
            col = col * 2
        return col + counts[0]

    return jax.jit(shard_map(per_shard,  # tracecheck: off[TS117]
                             mesh=mesh, in_specs=None, out_specs=None))
