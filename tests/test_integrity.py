"""End-to-end data-integrity audit tier (cylon_tpu.exec.integrity,
docs/robustness.md "Integrity audit tier"): the always-on conservation
laws over the exchange count sidecar, the armed order-invariant content
fingerprints and their stage-boundary votes, the manifest-fingerprint
resume audit, the ``Code.IntegrityFault`` recompute rung, the
``audit.verify`` stall drill, the armed-only int64 saturation guard,
and the retry_io routing of the obs snapshot/trace writers."""

import errno
import glob
import json
import os

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.exec import checkpoint, integrity, pipelined_join, recovery
from cylon_tpu.obs import metrics
from cylon_tpu.relational import groupby_aggregate, join_tables
from cylon_tpu.relational.setops import set_operation
from cylon_tpu.status import (Code, DataIntegrityError,
                              NumericOverflowError, RankDesyncError)


@pytest.fixture(autouse=True)
def _clean_injector():
    """Every test starts disarmed with empty event/occurrence state."""
    recovery.install_faults("")
    recovery.reset_events()
    yield
    recovery.install_faults("")
    recovery.reset_events()


@pytest.fixture()
def audit_armed():
    """Arm the fingerprint layer for one test (the cached env read is
    re-read on both edges so neighbours stay unarmed)."""
    old = os.environ.get("CYLON_TPU_AUDIT")
    os.environ["CYLON_TPU_AUDIT"] = "1"
    integrity.rearm()
    yield
    if old is None:
        os.environ.pop("CYLON_TPU_AUDIT", None)
    else:
        os.environ["CYLON_TPU_AUDIT"] = old
    integrity.rearm()


def _tables(env, rng, n=1500, card=150):
    ldf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                        "a": rng.integers(0, 50, n).astype(np.int64)})
    rdf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                        "b": rng.integers(0, 50, n).astype(np.int64)})
    return (ldf, rdf, ct.Table.from_pandas(ldf, env),
            ct.Table.from_pandas(rdf, env))


def _sorted(t, cols):
    return t.to_pandas().sort_values(cols).reset_index(drop=True)


# ---------------------------------------------------------------------------
# layer 2 primitive: the order-invariant content fingerprint
# ---------------------------------------------------------------------------

class TestFingerprint:
    def _df(self, rng, n=600):
        df = pd.DataFrame({
            "k": rng.integers(0, 50, n).astype(np.int64),
            "x": rng.random(n),
            "v": rng.integers(0, 9, n).astype("float64")})
        df.loc[df.index % 7 == 0, "v"] = np.nan   # validity lanes too
        return df

    def test_order_and_placement_invariant(self, env8, rng):
        df = self._df(rng)
        fp0 = integrity.table_fingerprint(ct.Table.from_pandas(df, env8))
        shuffled = df.sample(frac=1.0, random_state=3) \
            .reset_index(drop=True)
        fp1 = integrity.table_fingerprint(
            ct.Table.from_pandas(shuffled, env8))
        assert fp0 is not None and fp0 == fp1

    def test_world_invariant(self, env8, env4, rng):
        # the resume-audit property: a piece re-blocked onto a
        # different world fingerprints identically
        df = self._df(rng)
        fp8 = integrity.table_fingerprint(ct.Table.from_pandas(df, env8))
        fp4 = integrity.table_fingerprint(ct.Table.from_pandas(df, env4))
        assert fp8 == fp4

    def test_content_sensitive(self, env8, rng):
        df = self._df(rng)
        fp0 = integrity.table_fingerprint(ct.Table.from_pandas(df, env8))
        bumped = df.copy()
        bumped.loc[1, "k"] += 1
        assert integrity.table_fingerprint(
            ct.Table.from_pandas(bumped, env8)) != fp0
        # a low-mantissa float flip must change it too (nothing is
        # canonicalized or downcast on the audit lanes)
        tiny = df.copy()
        tiny.loc[2, "x"] += 1e-12
        assert integrity.table_fingerprint(
            ct.Table.from_pandas(tiny, env8)) != fp0

    def test_validity_sensitive(self, env8, rng):
        df = self._df(rng)
        fp0 = integrity.table_fingerprint(ct.Table.from_pandas(df, env8))
        nulled = df.copy()
        nulled.loc[3, "v"] = np.nan
        assert not np.isnan(df.loc[3, "v"])   # the flip is real
        assert integrity.table_fingerprint(
            ct.Table.from_pandas(nulled, env8)) != fp0

    def test_world1_deterministic(self, env1):
        # even a local 1-device mesh fingerprints (and twice the same)
        t = ct.Table.from_pydict(
            {"k": np.arange(8, dtype=np.int64)}, env1)
        fp = integrity.table_fingerprint(t)
        assert isinstance(fp, int)
        assert integrity.table_fingerprint(t) == fp


# ---------------------------------------------------------------------------
# layer 1: conservation laws (pure host math, unit-level)
# ---------------------------------------------------------------------------

class TestConservation:
    def _good(self, **kw):
        # mirror what a real exchange does: bump the registry, then audit
        counts = np.array([[1, 2], [3, 4]], np.int64)
        per_dest = counts.sum(axis=0)
        metrics.counter("exchange_rows_total").inc(10)
        metrics.counter("exchange_bytes_total").inc(80)
        integrity.conserve_exchange(counts, per_dest, 10, 8, **kw)

    def test_good_sidecar_passes(self):
        before = integrity.stats()["conservation_checks"]
        self._good()
        assert integrity.stats()["conservation_checks"] == before + 1

    def test_negative_count_raises_typed(self):
        counts = np.array([[1, -2], [3, 4]], np.int64)
        with pytest.raises(DataIntegrityError) as ei:
            integrity.conserve_exchange(counts, counts.sum(axis=0), 6, 8,
                                        site="shuffle.recv")
        assert ei.value.code == Code.IntegrityFault
        assert ei.value.site == "shuffle.recv"
        assert ei.value.phase == "post_exchange"

    def test_delivery_mismatch_raises(self):
        counts = np.array([[1, 2], [3, 4]], np.int64)
        with pytest.raises(DataIntegrityError, match="rows-received"):
            integrity.conserve_exchange(counts, np.array([4, 7]), 10, 8)

    def test_total_mismatch_raises(self):
        counts = np.array([[1, 2], [3, 4]], np.int64)
        with pytest.raises(DataIntegrityError, match="logical row total"):
            integrity.conserve_exchange(counts, counts.sum(axis=0), 11, 8)

    def test_counter_running_ahead_raises_then_resync(self):
        # rows accounted outside the audited exchange path are a drift
        metrics.counter("exchange_rows_total").inc(999)
        try:
            with pytest.raises(DataIntegrityError,
                               match="ran ahead"):
                self._good()
        finally:
            # reset_stats re-seeds the mirror from the live counters so
            # the always-on audit of later tests stays green
            integrity.reset_stats()
        self._good()

    def test_registry_reset_resyncs_not_raises(self):
        metrics.reset("exchange_rows_total")
        metrics.reset("exchange_bytes_total")
        before = integrity.stats()["reconcile_resyncs"]
        self._good()
        assert integrity.stats()["reconcile_resyncs"] == before + 1

    def test_hops_identities(self):
        c = np.array([[1, 2], [3, 4]], np.int64)
        c1 = np.diag(c.sum(axis=1))
        integrity.conserve_hops(c, c1, c)   # exact identities: passes
        with pytest.raises(DataIntegrityError, match="before ICI"):
            integrity.conserve_hops(c, 2 * c1, c)
        with pytest.raises(DataIntegrityError, match="lost on DCN"):
            integrity.conserve_hops(c, c1, np.zeros_like(c))
        bad_gw = np.array([[2, 2], [2, 4]], np.int64)   # col sums ok
        with pytest.raises(DataIntegrityError, match="gateway"):
            integrity.conserve_hops(c, c1, bad_gw)
        with pytest.raises(DataIntegrityError) as ei:
            integrity.conserve_hops(c, -c1, c)
        assert ei.value.site == "topo.exchange"


# ---------------------------------------------------------------------------
# armed stage-boundary audits across operators
# ---------------------------------------------------------------------------

class TestArmedOperators:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_joins_bit_equal_and_audited(self, env8, rng, how,
                                         audit_armed):
        ldf, rdf, lt, rt = _tables(env8, rng)
        s0 = integrity.stats()
        got = _sorted(join_tables(lt, rt, "k", "k", how=how),
                      ["k", "a", "b"])
        s1 = integrity.stats()
        assert s1["fingerprint_checks"] > s0["fingerprint_checks"]
        assert s1["fingerprint_votes"] > s0["fingerprint_votes"]
        assert s1["violations"] == s0["violations"]
        exp = (ldf.merge(rdf, on="k", how=how)
               .sort_values(["k", "a", "b"]).reset_index(drop=True))
        assert len(got) == len(exp)
        np.testing.assert_array_equal(
            got["k"].to_numpy(na_value=-1).astype(np.int64),
            exp["k"].to_numpy(na_value=-1).astype(np.int64))

    def test_set_op_bit_equal_and_audited(self, env8, rng, audit_armed):
        _, _, lt, rt = _tables(env8, rng, n=800)
        la = lt.project(["k"])
        rb = rt.project(["k"])
        s0 = integrity.stats()["fingerprint_checks"]
        got = _sorted(set_operation(la, rb, "union"), ["k"])
        assert integrity.stats()["fingerprint_checks"] > s0
        integrity.rearm()
        os.environ["CYLON_TPU_AUDIT"] = "0"
        try:
            base = _sorted(set_operation(la, rb, "union"), ["k"])
        finally:
            os.environ["CYLON_TPU_AUDIT"] = "1"
            integrity.rearm()
        pd.testing.assert_frame_equal(got, base)

    def test_stream_absorb_audited(self, env4, audit_armed):
        from cylon_tpu.stream import IncrementalView, StreamTable
        rng = np.random.default_rng(5)
        st = StreamTable(env4, key="k", name="t_audit")
        view = IncrementalView(st, "k", [("v", "sum")], env=env4)
        s0 = integrity.stats()["fingerprint_checks"]
        batches = []
        for _ in range(2):
            b = {"k": rng.integers(0, 16, 400).astype(np.int64),
                 "v": rng.integers(0, 9, 400).astype(np.int64)}
            batches.append(b)
            st.append(dict(b))
        # one audit vote per absorbed batch
        assert integrity.stats()["fingerprint_checks"] >= s0 + 2
        got = _sorted(view.read(), ["k"])
        full = ct.Table.from_pydict(
            {c: np.concatenate([b[c] for b in batches])
             for c in ("k", "v")}, env4)
        exp = _sorted(groupby_aggregate(full, "k", [("v", "sum")]), ["k"])
        pd.testing.assert_frame_equal(got, exp, check_exact=True)

    def test_unarmed_zero_fingerprint_work(self, env8, rng):
        _, _, lt, rt = _tables(env8, rng)
        s0 = integrity.stats()
        join_tables(lt, rt, "k", "k", how="inner")
        s1 = integrity.stats()
        assert s1["fingerprint_checks"] == s0["fingerprint_checks"]
        assert s1["fingerprint_votes"] == s0["fingerprint_votes"]
        # the conservation laws stay on — they are free host math
        assert s1["conservation_checks"] > s0["conservation_checks"]


# ---------------------------------------------------------------------------
# layer 3: the IntegrityFault recompute rung
# ---------------------------------------------------------------------------

class TestRecoveryRung:
    def test_one_shot_corruption_recomputed_bit_equal(self, env8, rng,
                                                      audit_armed):
        ldf, rdf, lt, rt = _tables(env8, rng)
        base = _sorted(join_tables(lt, rt, "k", "k", how="inner"),
                       ["k", "a", "b"])
        recovery.reset_events()
        recovery.install_faults("exchange.corrupt=corrupt")
        got = _sorted(join_tables(lt, rt, "k", "k", how="inner"),
                      ["k", "a", "b"])
        pd.testing.assert_frame_equal(got, base)
        evs = [e for e in recovery.recovery_events()
               if e["kind"] == "integrity"]
        assert len(evs) == 1, recovery.recovery_events()
        assert evs[0]["action"].startswith("retry"), evs

    def test_persistent_corruption_aborts_typed(self, env8, rng,
                                                audit_armed):
        _, _, lt, rt = _tables(env8, rng)
        recovery.reset_events()
        recovery.install_faults("exchange.corrupt::*=corrupt")
        with pytest.raises(DataIntegrityError) as ei:
            join_tables(lt, rt, "k", "k", how="inner")
        assert ei.value.code == Code.IntegrityFault
        assert ei.value.site == "shuffle.recv"
        assert ei.value.phase == "post_exchange"
        acts = [e["action"] for e in recovery.recovery_events()
                if e["kind"] == "integrity"]
        # exactly ONE recompute rung, then the typed abort
        assert acts.count("abort") == 1, acts
        assert sum(a.startswith("retry") for a in acts) == 1, acts

    def test_audit_verify_stall_surfaces_typed(self, env8, rng,
                                               audit_armed, monkeypatch):
        _, _, lt, rt = _tables(env8, rng, n=600)
        monkeypatch.setattr(config, "EXCHANGE_WATCHDOG_S", 0.2)
        recovery.install_faults("audit.verify=stall")
        with pytest.raises(RankDesyncError):
            join_tables(lt, rt, "k", "k", how="inner")


# ---------------------------------------------------------------------------
# manifest fingerprints: the resume audit
# ---------------------------------------------------------------------------

class TestManifestAudit:
    @pytest.fixture(autouse=True)
    def _ckpt(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path / "ckpt"))
        monkeypatch.delenv("CYLON_TPU_RESUME", raising=False)
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        yield
        checkpoint.reset_stages()
        checkpoint.reset_stats()

    def test_unit_fp_recorded_and_audited(self, env4, rng, audit_armed):
        _, _, lt, _ = _tables(env4, rng, n=800)
        stage = checkpoint.open_stage(env4, "unit_fp", "tok")
        stage.save_piece(0, lt)
        entry = stage.committed[0]
        assert entry["fp"] is not None
        stage.load_piece(0)   # clean round trip passes the audit
        assert integrity.stats()["manifest_audits"] >= 1
        # pages + shas intact, recorded fingerprint off by one bit:
        # ONLY the content audit can catch this
        entry["fp"] ^= 1
        with pytest.raises(DataIntegrityError, match="refusing to adopt"):
            stage.load_piece(0)

    def test_unarmed_saves_record_none(self, env4, rng):
        _, _, lt, _ = _tables(env4, rng, n=600)
        stage = checkpoint.open_stage(env4, "unit_nofp", "tok")
        stage.save_piece(0, lt)
        assert stage.committed[0]["fp"] is None
        # a None recording never audits, armed or not
        integrity.audit_restored_table(lt, None)

    def test_tampered_manifest_fp_recomputes_never_adopts(
            self, env4, rng, audit_armed, monkeypatch):
        ldf, rdf, lt, rt = _tables(env4, rng, n=1200)
        base = (pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=3)
                .to_pandas().sort_values(["k", "a", "b"])
                .reset_index(drop=True))
        mans = sorted(glob.glob(os.path.join(
            checkpoint.ckpt_dir(), "rank*", "stage*", "MANIFEST.json")))
        assert mans
        with open(mans[0], encoding="utf-8") as f:
            man = json.load(f)
        # tamper the LAST piece: the earlier ones must still
        # fast-forward (a fingerprint miss poisons the piece, not the
        # stage prefix before it)
        piece = sorted(man["pieces"], key=int)[-1]
        assert man["pieces"][piece]["fp"] is not None
        man["pieces"][piece]["fp"] ^= 1   # shas all still valid
        with open(mans[0], "w", encoding="utf-8") as f:
            json.dump(man, f)
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        recovery.reset_events()
        resumed = (pipelined_join(lt, rt, "k", "k", how="inner",
                                  n_chunks=3)
                   .to_pandas().sort_values(["k", "a", "b"])
                   .reset_index(drop=True))
        pd.testing.assert_frame_equal(resumed, base)
        # the tampered piece was recomputed, the rest fast-forwarded
        st = checkpoint.stats()
        assert st["resume_fast_forwarded_pieces"] == 2, st
        assert any(e["site"] == "ckpt.load" and e["action"] == "recompute"
                   for e in recovery.recovery_events())


# ---------------------------------------------------------------------------
# armed int64 saturation guard (groupby finalize + combine)
# ---------------------------------------------------------------------------

class TestSaturationGuard:
    def test_finalize_guard_raises_typed(self, env8, audit_armed):
        t = ct.Table.from_pydict(
            {"k": np.zeros(3, np.int64),
             "v": np.full(3, np.int64(1) << 61)}, env8)
        with pytest.raises(NumericOverflowError) as ei:
            groupby_aggregate(t, "k", [("v", "sum")])
        assert ei.value.site == "groupby.finalize"
        assert ei.value.column == "v_sum"

    def test_unarmed_returns_exact_value(self, env8):
        t = ct.Table.from_pydict(
            {"k": np.zeros(3, np.int64),
             "v": np.full(3, np.int64(1) << 61)}, env8)
        out = groupby_aggregate(t, "k", [("v", "sum")]).to_pandas()
        assert int(out["v_sum"].iloc[0]) == 3 * (1 << 61)

    def test_overflow_at_combine_boundary(self, env8, audit_armed):
        # regression: two partials each BELOW the rail wrap when folded;
        # the disjoint pass-through never reaches the finalize guard
        from cylon_tpu.relational.groupby import combine_sink_partials
        partial = ct.Table.from_pydict(
            {"k": np.arange(2, dtype=np.int64),
             "v_sum": np.full(2, (np.int64(1) << 62) + 7)}, env8)
        with pytest.raises(NumericOverflowError) as ei:
            combine_sink_partials(partial, ["k"], [("v", "sum")],
                                  [("v", "sum")], {"sum": "sum"},
                                  disjoint=True)
        assert ei.value.site == "groupby.combine"

    def test_mean_and_small_sums_unguarded(self, env8, audit_armed):
        t = ct.Table.from_pydict(
            {"k": np.zeros(4, np.int64),
             "v": np.arange(4, dtype=np.int64)}, env8)
        out = groupby_aggregate(t, "k", [("v", "sum"), ("v", "mean")])
        assert int(out.to_pandas()["v_sum"].iloc[0]) == 6


# ---------------------------------------------------------------------------
# obs writers ride retry_io (flaky-then-ok regression)
# ---------------------------------------------------------------------------

class TestObsRetryIO:
    def test_snapshot_flaky_then_ok(self, tmp_path, monkeypatch):
        path = str(tmp_path / "metrics.json")
        monkeypatch.setenv("CYLON_TPU_METRICS_JSON", path)
        monkeypatch.setattr(metrics, "_SNAP", [None, 0.0])
        monkeypatch.setattr("time.sleep", lambda s: None)
        real_replace = os.replace
        calls = {"n": 0}

        def flaky_replace(src, dst):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError(errno.EAGAIN, "scrape sidecar racing")
            return real_replace(src, dst)

        monkeypatch.setattr(os, "replace", flaky_replace)
        assert metrics.maybe_write_snapshot() is True
        monkeypatch.setattr(os, "replace", real_replace)
        assert calls["n"] == 2   # one transient miss, one retry, done
        with open(path, encoding="utf-8") as f:
            assert "metrics" in json.load(f)

    def test_trace_export_flaky_then_ok(self, tmp_path, monkeypatch):
        from cylon_tpu.obs import trace
        path = str(tmp_path / "trace.json")
        monkeypatch.setattr("time.sleep", lambda s: None)
        trace.arm(path)
        try:
            real_replace = os.replace
            calls = {"n": 0}

            def flaky_replace(src, dst):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise OSError(errno.EAGAIN, "transient")
                return real_replace(src, dst)

            monkeypatch.setattr(os, "replace", flaky_replace)
            out = trace.export()
            monkeypatch.setattr(os, "replace", real_replace)
            assert out == path and calls["n"] == 2
            with open(path, encoding="utf-8") as f:
                assert "traceEvents" in json.load(f)
        finally:
            trace.disarm()
