"""Rank-coherent failure recovery (cylon_tpu.exec.recovery +
cylon_tpu.status fault taxonomy): classification, the fault-injection
harness (``CYLON_TPU_FAULTS``), every consensus-ladder branch, and the
exchange watchdog — all exercised on the CPU rig, no real device OOM
needed.  docs/robustness.md."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.exec import recovery
from cylon_tpu.status import (CapacityOverflowError, Code, CylonError,
                              DeviceOOMError, InvalidError,
                              PredictedResourceExhausted, RankDesyncError)


@pytest.fixture(autouse=True)
def _clean_injector():
    """Every test starts disarmed with empty event/occurrence state."""
    recovery.install_faults("")
    recovery.reset_events()
    yield
    recovery.install_faults("")
    recovery.reset_events()


def _tables(env, rng, n=4000):
    ldf = pd.DataFrame({"k": rng.integers(0, 500, n).astype(np.int64),
                        "a": rng.integers(0, 50, n).astype(np.int64)})
    rdf = pd.DataFrame({"k": rng.integers(0, 500, n).astype(np.int64),
                        "b": rng.integers(0, 50, n).astype(np.int64)})
    return (ldf, rdf, ct.Table.from_pandas(ldf, env),
            ct.Table.from_pandas(rdf, env))


# ---------------------------------------------------------------------------
# taxonomy + classification
# ---------------------------------------------------------------------------

class TestTaxonomy:
    def test_codes_and_kinds(self):
        assert PredictedResourceExhausted().code == Code.OutOfMemory
        assert DeviceOOMError().code == Code.OutOfMemory
        assert CapacityOverflowError().code == Code.CapacityError
        assert RankDesyncError().code == Code.ExecutionError
        assert PredictedResourceExhausted.kind == "predicted"
        assert DeviceOOMError.kind == "device_oom"
        assert CapacityOverflowError.kind == "capacity"
        assert RankDesyncError.kind == "desync"

    def test_predicted_is_memoryerror(self):
        # pre-taxonomy compat: foreign callers may catch MemoryError
        assert isinstance(PredictedResourceExhausted(), MemoryError)

    def test_classify_passthrough(self):
        for f in (PredictedResourceExhausted("x"), DeviceOOMError("x"),
                  CapacityOverflowError("x"), RankDesyncError("x")):
            assert recovery.classify(f) is f

    def test_classify_foreign_oom(self):
        e = RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
        f = recovery.classify(e)
        assert isinstance(f, DeviceOOMError) and f.__cause__ is e

    def test_classify_foreign_predicted(self):
        e = MemoryError("RESOURCE_EXHAUSTED (predicted): receive budget")
        f = recovery.classify(e)
        assert isinstance(f, PredictedResourceExhausted)

    def test_classify_non_faults(self):
        assert recovery.classify(ValueError("boom")) is None
        # typed engine errors are not recovery faults
        assert recovery.classify(InvalidError("bad arg")) is None

    def test_is_oom_shim(self):
        from cylon_tpu.relational.common import is_oom
        assert is_oom(RuntimeError("Out of memory while trying"))
        assert is_oom(PredictedResourceExhausted("anything"))
        assert not is_oom(ValueError("fine"))


# ---------------------------------------------------------------------------
# injection harness: grammar, rank/nth selectivity
# ---------------------------------------------------------------------------

class TestInjector:
    def test_grammar_rejects_unknown(self):
        with pytest.raises(ValueError):
            recovery.install_faults("nope.site=predicted")
        with pytest.raises(ValueError):
            recovery.install_faults("shuffle.recv_guard=nope")
        with pytest.raises(ValueError):
            recovery.install_faults("shuffle.recv_guard:0:1:9=predicted")

    def test_nth_selectivity(self):
        recovery.install_faults("groupby.device_oom::2=device_oom")
        assert recovery.injected("groupby.device_oom") is None   # 1st
        assert recovery.injected("groupby.device_oom") == "device_oom"
        assert recovery.injected("groupby.device_oom") is None   # consumed

    def test_every_occurrence(self):
        recovery.install_faults("groupby.device_oom::*=device_oom")
        assert all(recovery.injected("groupby.device_oom") == "device_oom"
                   for _ in range(3))

    def test_rank_selectivity(self):
        # this controller is process 0: a rank-1 spec never fires here
        recovery.install_faults("shuffle.recv_guard:1=predicted")
        assert recovery.injected("shuffle.recv_guard") is None
        recovery.install_faults("shuffle.recv_guard:0=predicted")
        assert recovery.injected("shuffle.recv_guard") == "predicted"

    def test_probe_armed_is_rank_uniform(self):
        """`armed` must depend only on the spec list and the per-site hit
        counter (both identical across ranks), never on whether THIS rank
        fired — a rank-0 spec keeps every rank's guard consensus engaged
        until its occurrence passes, then disengages everywhere."""
        recovery.install_faults("shuffle.recv_guard:1:2=predicted")
        # this controller is rank 0: the spec never fires here, but the
        # site stays armed through occurrence 2 and disarms after
        assert recovery.probe("shuffle.recv_guard") == (None, True)   # hit 1
        assert recovery.probe("shuffle.recv_guard") == (None, True)   # hit 2
        assert recovery.probe("shuffle.recv_guard") == (None, False)  # hit 3
        recovery.install_faults("shuffle.recv_guard::*=predicted")
        assert recovery.probe("shuffle.recv_guard")[1] is True
        assert recovery.probe("shuffle.recv_guard")[1] is True

    def test_unarmed_probe_is_silent(self):
        assert recovery.probe("shuffle.recv_guard") == (None, False)

    def test_grammar_accepts_disk_sites_and_enospc(self):
        recovery.install_faults("disk.write::1=enospc")
        recovery.install_faults("disk.write=corrupt,disk.read=stall")
        with pytest.raises(ValueError):
            recovery.install_faults("shuffle.recv_guard=enospc_typo")


# ---------------------------------------------------------------------------
# bounded IO retry (retry_io): the shared transient-OSError backoff
# ---------------------------------------------------------------------------

class TestRetryIO:
    def test_flaky_then_ok_succeeds(self, monkeypatch):
        """The regression the helper exists for: a single transient
        OSError (an NFS blip) no longer aborts — attempt 2 lands."""
        monkeypatch.setattr("time.sleep", lambda s: None)
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] == 1:
                raise OSError(5, "transient EIO")
            return "landed"

        assert recovery.retry_io(flaky, "ckpt.write") == "landed"
        assert calls[0] == 2

    def test_bounded_and_reraises_last(self, monkeypatch):
        monkeypatch.setattr("time.sleep", lambda s: None)
        calls = [0]

        def always():
            calls[0] += 1
            raise OSError(5, "still down")

        with pytest.raises(OSError):
            recovery.retry_io(always, "ckpt.write", attempts=3)
        assert calls[0] == 3        # bounded: never an unbounded loop

    def test_enospc_is_non_transient(self, monkeypatch):
        """A full disk does not heal on a millisecond backoff: ENOSPC
        re-raises immediately so the caller's typed degrade path owns
        it."""
        import errno
        monkeypatch.setattr("time.sleep", lambda s: None)
        calls = [0]

        def full():
            calls[0] += 1
            raise OSError(errno.ENOSPC, "disk full")

        with pytest.raises(OSError):
            recovery.retry_io(full, "disk.write")
        assert calls[0] == 1

    def test_non_oserror_propagates_untouched(self):
        with pytest.raises(ValueError):
            recovery.retry_io(lambda: (_ for _ in ()).throw(
                ValueError("not io")), "ckpt.write")

    def test_on_retry_callback_and_counter(self, monkeypatch):
        monkeypatch.setattr("time.sleep", lambda s: None)
        from cylon_tpu.obs import metrics
        c0 = metrics.counter("recovery_io_retries").value
        hits = [0]
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise OSError(5, "blip")
            return 1

        assert recovery.retry_io(
            flaky, "disk.write",
            on_retry=lambda: hits.__setitem__(0, hits[0] + 1)) == 1
        assert hits[0] == 2
        assert metrics.counter("recovery_io_retries").value == c0 + 2


class TestDiskCorruptClassification:
    def test_disk_site_corruption_is_a_fault(self):
        from cylon_tpu.status import CheckpointCorruptError
        e = CheckpointCorruptError("spill page bad", site="disk.read")
        assert recovery.classify(e) is e
        # the ladder's recompute rung exists for it
        assert Code.SerializationError in recovery.RETRY_RUNGS

    def test_ckpt_site_corruption_stays_non_fault(self):
        """Checkpoint-site corruption keeps its local restore-degrade
        handling — the ladder must NOT adopt it."""
        from cylon_tpu.status import CheckpointCorruptError
        assert recovery.classify(
            CheckpointCorruptError("page bad", site="ckpt.load")) is None
        assert recovery.classify(
            CheckpointCorruptError("page bad")) is None

    def test_wire_round_trip(self):
        from cylon_tpu.status import CheckpointCorruptError
        e = CheckpointCorruptError("x", site="disk.read")
        wire = recovery._wire_code(e)
        back = recovery._fault_from_wire(wire, "peer corrupt")
        assert isinstance(back, CheckpointCorruptError)
        assert back.site == "disk.read"

    def test_all_four_kinds_constructible(self):
        """Acceptance: every typed fault kind is constructible via
        injection on the CPU rig."""
        recovery.install_faults("join.piece_cap=capacity")
        with pytest.raises(CapacityOverflowError):
            recovery.maybe_inject("join.piece_cap")
        recovery.install_faults("shuffle.recv_guard=predicted")
        with pytest.raises(PredictedResourceExhausted):
            recovery.maybe_inject("shuffle.recv_guard")
        recovery.install_faults("groupby.device_oom=device_oom")
        with pytest.raises(RuntimeError) as ei:  # foreign-shaped on purpose
            recovery.maybe_inject("groupby.device_oom")
        assert isinstance(recovery.classify(ei.value), DeviceOOMError)
        recovery.install_faults("exchange.stall=desync")
        with pytest.raises(RankDesyncError):
            recovery.maybe_inject("exchange.stall")

    def test_ckpt_sites_and_kinds_parse(self):
        """The durable-checkpoint grammar extensions: ckpt.write /
        ckpt.load sites, `corrupt` raises typed, `kill` parses (firing
        it would SIGKILL this process — the chaos-soak harness and
        tests/test_checkpoint.py exercise that in child processes)."""
        from cylon_tpu.status import CheckpointCorruptError
        recovery.install_faults("ckpt.load=corrupt")
        with pytest.raises(CheckpointCorruptError):
            recovery.maybe_inject("ckpt.load")
        recovery.install_faults("ckpt.write:0:2=kill")
        kind, armed = recovery.probe("ckpt.write")
        assert (kind, armed) == (None, True)   # occurrence 1: armed only
        assert recovery.probe("ckpt.write")[0] == "kill"

    def test_elastic_sites_and_kinds_parse(self):
        """The elastic-resume grammar extensions: the ckpt.reshard site
        (corrupt parses as interceptable — exec/checkpoint converts it
        to a typed CheckpointCorruptError — and kill parses; firing it
        would SIGKILL this process, exercised by chaos_soak --elastic)
        and the `term` kind (delivers SIGTERM — the preemption notice;
        tests/test_checkpoint.py fires it under an installed grace
        handler)."""
        recovery.install_faults("ckpt.reshard=corrupt")
        assert recovery.maybe_inject(
            "ckpt.reshard", intercept=("corrupt",)) == "corrupt"
        recovery.install_faults("ckpt.reshard::2=kill")
        kind, armed = recovery.probe("ckpt.reshard")
        assert (kind, armed) == (None, True)
        assert recovery.probe("ckpt.reshard")[0] == "kill"
        recovery.install_faults("ckpt.write::3=term")
        assert recovery.probe("ckpt.write") == (None, True)
        recovery.install_faults("")

    def test_install_faults_fully_resets_state(self):
        """Regression (chaos-soak hygiene): re-installing a schedule
        must clear the per-site occurrence counters AND the recorded
        event log — otherwise iteration N+1's `nth` specs fire shifted
        by iteration N's probe count and its report inherits stale
        events."""
        recovery.install_faults("groupby.device_oom::2=device_oom")
        assert recovery.injected("groupby.device_oom") is None       # hit 1
        assert recovery.injected("groupby.device_oom") == "device_oom"
        # re-install: counters restart — the nth=2 spec must NOT fire at
        # the first post-install occurrence (a stale counter would put
        # the site at hit 3 and the spec would never fire again)
        recovery.install_faults("groupby.device_oom::2=device_oom")
        assert recovery.injected("groupby.device_oom") is None       # hit 1
        assert recovery.injected("groupby.device_oom") == "device_oom"
        # ... and the recorded event log is cleared as well
        recovery.install_faults("groupby.device_oom::1=device_oom")
        with pytest.raises(RuntimeError):
            recovery.maybe_inject("groupby.device_oom")
        assert len(recovery.recovery_events()) == 1
        recovery.install_faults("groupby.device_oom::1=device_oom")
        assert recovery.recovery_events() == []


# ---------------------------------------------------------------------------
# ladder branches (unit level)
# ---------------------------------------------------------------------------

class TestLadder:
    def test_ok_passthrough(self):
        assert recovery.run_with_recovery(
            lambda: 42, True, lambda nc: None, "t") == 42
        assert recovery.recovery_events() == []

    def test_oom_rungs_4_then_16(self):
        seen = []

        def fb(nc):
            seen.append(nc)
            if nc == 4:
                raise RuntimeError("RESOURCE_EXHAUSTED again")
            return "ok"

        def boom():
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

        assert recovery.run_with_recovery(boom, True, fb, "t") == "ok"
        assert seen == [4, 16]
        acts = [e["action"] for e in recovery.recovery_events()]
        assert acts == ["retry_chunks_4", "retry_chunks_16"]

    def test_capacity_single_halving_rung(self):
        seen = []

        def boom():
            raise CapacityOverflowError("cap", site="join.piece_cap")

        assert recovery.run_with_recovery(
            boom, True, lambda nc: seen.append(nc) or "ok", "t") == "ok"
        assert seen == [8]  # exactly one cap-halving step

    def test_exhaustion_raises_typed(self):
        def boom():
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

        def fb(nc):
            raise RuntimeError("RESOURCE_EXHAUSTED still")

        with pytest.raises(DeviceOOMError):
            recovery.run_with_recovery(boom, True, fb, "t")
        acts = [e["action"] for e in recovery.recovery_events()]
        assert acts == ["retry_chunks_4", "retry_chunks_16", "abort"]

    def test_non_fault_propagates_untouched(self):
        def boom():
            raise ValueError("not a fault")

        with pytest.raises(ValueError):
            recovery.run_with_recovery(boom, True, lambda nc: "ok", "t")
        assert recovery.recovery_events() == []

    def test_desync_never_retries(self):
        def boom():
            raise RankDesyncError("peer hung", site="exchange.stall")

        with pytest.raises(RankDesyncError):
            recovery.run_with_recovery(boom, True, lambda nc: "ok", "t")
        assert [e["action"] for e in recovery.recovery_events()] == ["abort"]

    def test_nested_ladder_never_reescalates(self):
        """A fallback re-entering a guarded op gets NO rungs of its own —
        the outer ladder owns the bounded escalation."""
        inner_fallback_calls = []

        def inner():
            def boom():
                raise RuntimeError("RESOURCE_EXHAUSTED inner")
            return recovery.run_with_recovery(
                boom, True, lambda nc: inner_fallback_calls.append(nc),
                "inner")

        def fb(nc):
            if nc == 4:
                inner()  # typed DeviceOOMError escalates the OUTER ladder
            return "ok"

        def boom():
            raise RuntimeError("RESOURCE_EXHAUSTED outer")

        assert recovery.run_with_recovery(boom, True, fb, "outer") == "ok"
        assert inner_fallback_calls == []

    def test_counted_in_timing_stats(self):
        from cylon_tpu.utils import timing

        def boom():
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

        recovery.run_with_recovery(boom, True, lambda nc: "ok", "t")
        snap = timing.snapshot()
        assert any(k.startswith("recovery.t.device_oom.retry")
                   for k in snap), snap


# ---------------------------------------------------------------------------
# ladder branches through the real operators (injection-driven)
# ---------------------------------------------------------------------------

class TestInjectedOperators:
    def test_predicted_guard_retry_join(self, env4, rng):
        """The acceptance scenario, single-controller edition: a predicted
        receive-budget fault at the shuffle guard reroutes the join through
        the streaming pipeline with ONE logged recovery event, and the
        result is identical to the un-injected run."""
        from cylon_tpu.relational import join_tables
        ldf, rdf, lt, rt = _tables(env4, rng)
        recovery.install_faults("shuffle.recv_guard:0:1=predicted")
        j = join_tables(lt, rt, "k", "k", how="inner")
        got = j.to_pandas().sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        exp = ldf.merge(rdf, on="k").sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)
        evs = recovery.recovery_events()
        assert len(evs) == 1, evs
        assert evs[0] == {"site": "join", "kind": "predicted",
                          "action": "retry_chunks_4"}

    def test_device_oom_escalates_to_16(self, env4, rng):
        """4 → 16 chunk escalation: the first fallback rung hits the
        (still-armed) injected fault, the second succeeds."""
        from cylon_tpu.relational import groupby_aggregate
        ldf, _, _, _ = _tables(env4, rng)
        t = ct.Table.from_pandas(ldf, env4)
        recovery.install_faults(
            "groupby.device_oom::1=device_oom,"
            "groupby.device_oom::2=device_oom")
        g = groupby_aggregate(t, "k", [("a", "sum")])
        got = g.to_pandas().sort_values("k").reset_index(drop=True)
        exp = (ldf.groupby("k", as_index=False).agg(a_sum=("a", "sum")))
        exp.columns = got.columns
        pd.testing.assert_frame_equal(got, exp.sort_values("k")
                                      .reset_index(drop=True),
                                      check_dtype=False)
        acts = [e["action"] for e in recovery.recovery_events()]
        assert "retry_chunks_4" in acts and "retry_chunks_16" in acts

    def test_device_oom_exhaustion_typed_raise(self, env4, rng):
        """4 → 16 → typed DeviceOOMError when the fault never clears."""
        from cylon_tpu.relational import groupby_aggregate
        ldf, _, _, _ = _tables(env4, rng, n=1200)
        t = ct.Table.from_pandas(ldf, env4)
        recovery.install_faults("groupby.device_oom::*=device_oom")
        with pytest.raises(DeviceOOMError):
            groupby_aggregate(t, "k", [("a", "sum")])
        acts = [e["action"] for e in recovery.recovery_events()
                if e["site"] == "groupby"]
        assert acts[0] == "retry_chunks_4"
        assert "retry_chunks_16" in acts
        assert acts[-1] == "abort"

    def test_capacity_overflow_escalates_ladder(self, env4, rng):
        """An injected CapacityOverflowError on the first packed-piece
        join (inside the 4-chunk fallback) moves the outer ladder to its
        next rung (halving the piece caps) and still completes
        correctly."""
        from cylon_tpu.relational import join_tables
        ldf, rdf, lt, rt = _tables(env4, rng)
        recovery.install_faults(
            "shuffle.recv_guard:0:1=predicted,join.piece_cap::1=capacity")
        j = join_tables(lt, rt, "k", "k", how="inner")
        got = j.to_pandas().sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        exp = ldf.merge(rdf, on="k").sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)
        acts = [e["action"] for e in recovery.recovery_events()
                if e["site"] == "join"]
        # predicted -> 4-chunk rung (hits capacity fault) -> 16-chunk rung
        assert acts[0] == "retry_chunks_4"
        assert "retry_chunks_16" in acts

    def test_packed_piece_cap_check_is_typed(self, env4, rng):
        from cylon_tpu.relational.piece import PieceSource
        ldf, _, _, _ = _tables(env4, rng, n=800)
        t = ct.Table.from_pandas(ldf, env4)
        src = PieceSource(t, pad=8)
        w = env4.world_size
        with pytest.raises(CapacityOverflowError):
            src.packed(np.zeros(w, np.int64), np.full(w, 64, np.int64),
                       piece_cap=32)


# ---------------------------------------------------------------------------
# overlap scheduler × recovery interplay (ISSUE 6)
# ---------------------------------------------------------------------------

class TestOverlapRobustness:
    """The phase-overlapped piece scheduler (CYLON_TPU_PACKED_OVERLAP)
    must not change WHAT the recovery ladder sees or WHERE typed faults
    surface: deferred phase faults re-raise at the same consume point,
    and the ladder's escalation sequence is identical with overlap on
    or off."""

    def test_piece_future_defers_typed_not_foreign(self):
        from cylon_tpu.exec.pipeline import _PieceFuture

        def typed():
            raise CapacityOverflowError("deferred until consumed")

        fut = _PieceFuture(typed, defer_faults=True)   # held, no raise yet
        with pytest.raises(CapacityOverflowError):
            fut.get()
        # the non-overlapped schedule raises at dispatch
        with pytest.raises(CapacityOverflowError):
            _PieceFuture(typed, defer_faults=False)

        def foreign():
            raise ValueError("not a taxonomy fault")

        # foreign exceptions must NOT be detached from their dispatch
        # context — they raise immediately even when deferring
        with pytest.raises(ValueError):
            _PieceFuture(foreign, defer_faults=True)

    def test_phase_sync_fault_surfaces_typed(self, env4, rng, monkeypatch):
        """A fault injected at the overlap scheduler's designated
        pre-loop sync point (pipe.phase_sync) surfaces as a TYPED fault
        there — not as a raw jax error from an arbitrary later pull."""
        from cylon_tpu import config
        from cylon_tpu.exec import pipelined_join
        ldf, rdf, lt, rt = _tables(env4, rng, n=1500)
        monkeypatch.setattr(config, "PACKED_OVERLAP", True)
        recovery.install_faults("pipe.phase_sync::1=predicted")
        with pytest.raises(PredictedResourceExhausted):
            pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=3)
        assert recovery.recovery_events() == [
            {"site": "pipe.phase_sync", "kind": "predicted",
             "action": "injected"}]
        # with overlap off the designated sync point does not exist
        # (per-phase pulls instead) — the same armed fault never fires
        monkeypatch.setattr(config, "PACKED_OVERLAP", False)
        recovery.install_faults("pipe.phase_sync::1=predicted")
        out = pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=3)
        assert out.row_count == len(ldf.merge(rdf, on="k"))
        assert recovery.recovery_events() == []

    def test_piece_cap_ladder_identical_overlap_on_off(self, env4, rng,
                                                       monkeypatch):
        """Injected CapacityOverflow inside the pipelined fallback: the
        consensus ladder must take the identical escalation sequence and
        produce bit- and order-equal output with overlap on or off."""
        import gc
        from cylon_tpu import config
        from cylon_tpu.relational import join_tables
        ldf, rdf, lt, rt = _tables(env4, rng)
        runs = {}
        for overlap in (True, False):
            # drain leaked spillable registrations from the previous
            # mode's run: a phantom spill rung would (legitimately)
            # change the ladder sequence for reasons unrelated to overlap
            gc.collect()
            monkeypatch.setattr(config, "PACKED_OVERLAP", overlap)
            recovery.install_faults(
                "shuffle.recv_guard:0:1=predicted,"
                "join.piece_cap::1=capacity")
            j = join_tables(lt, rt, "k", "k", how="inner")
            runs[overlap] = (j.to_pandas(), recovery.recovery_events())
            recovery.install_faults("")
        (df_on, ev_on), (df_off, ev_off) = runs[True], runs[False]
        assert ev_on == ev_off
        assert any(e["action"] == "retry_chunks_16" for e in ev_on), ev_on
        pd.testing.assert_frame_equal(df_on, df_off)

    def test_spill_upload_fault_identical_overlap_on_off(self, env4, rng,
                                                         monkeypatch):
        """Budget-forced spilled sources: a device-OOM fault injected at
        the spill.upload re-entry fires inside the piece dispatch — under
        overlap, while dispatching ahead of the consume point — and the
        ladder must classify it and converge to the identical escalation
        sequence and bit-equal result in both dispatch modes."""
        import gc
        from cylon_tpu import config
        from cylon_tpu.exec import pipelined_join
        _ldf, _rdf, lt, rt = _tables(env4, rng)
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 4096)
        runs = {}
        for overlap in (True, False):
            gc.collect()
            monkeypatch.setattr(config, "PACKED_OVERLAP", overlap)
            recovery.install_faults("spill.upload::1=device_oom")

            def attempt(nc):
                return pipelined_join(lt, rt, "k", "k", how="inner",
                                      n_chunks=nc)

            out = recovery.run_with_recovery(
                lambda: attempt(4), True, attempt, "join", env=env4)
            runs[overlap] = (out.to_pandas(), recovery.recovery_events())
            recovery.install_faults("")
        (df_on, ev_on), (df_off, ev_off) = runs[True], runs[False]
        assert ev_on and ev_on == ev_off
        assert ev_on[0]["kind"] == "device_oom", ev_on
        pd.testing.assert_frame_equal(df_on, df_off)

    def test_groupby_oom_ladder_identical_overlap_on_off(self, env4, rng,
                                                         monkeypatch):
        """The chaos-soak workload shape (pipelined join into a
        GroupBySink under run_with_recovery) with an injected device OOM
        at the groupby site: identical ladder events and bit-equal
        finalize with overlap on or off.  The sink keys on a NON-join
        column so the cross-chunk combine (groupby_aggregate — where the
        site is probed) actually runs."""
        import gc
        from cylon_tpu import config
        from cylon_tpu.exec import GroupBySink, pipelined_join
        n = 2000
        ldf = pd.DataFrame({"k": rng.integers(0, 500, n).astype(np.int64),
                            "g": rng.integers(0, 7, n).astype(np.int64),
                            "a": rng.integers(0, 50, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(0, 500, n).astype(np.int64),
                            "b": rng.integers(0, 50, n).astype(np.int64)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        runs = {}
        for overlap in (True, False):
            gc.collect()
            monkeypatch.setattr(config, "PACKED_OVERLAP", overlap)
            recovery.install_faults("groupby.device_oom::1=device_oom")

            def attempt(nc):
                sink = GroupBySink("g", [("a", "sum")])
                pipelined_join(lt, rt, "k", "k", how="inner",
                               n_chunks=nc, sink=sink)
                return sink.finalize()

            out = recovery.run_with_recovery(
                lambda: attempt(4), True, attempt, "soak", env=env4)
            runs[overlap] = (out.to_pandas().sort_values("g")
                             .reset_index(drop=True),
                             recovery.recovery_events())
            recovery.install_faults("")
        (df_on, ev_on), (df_off, ev_off) = runs[True], runs[False]
        assert ev_on and ev_on == ev_off
        pd.testing.assert_frame_equal(df_on, df_off)


# ---------------------------------------------------------------------------
# consensus + watchdog
# ---------------------------------------------------------------------------

class TestConsensusAndWatchdog:
    def test_consensus_single_controller_is_local(self, env4):
        # one process drives the whole mesh: the local code IS the vote
        assert recovery.consensus_code(env4.mesh, Code.OK) == Code.OK
        assert recovery.consensus_code(
            env4.mesh, Code.OutOfMemory) == Code.OutOfMemory
        assert recovery.consensus_code(None, Code.CapacityError) \
            == Code.CapacityError

    def test_consensus_program_is_one_pmax(self, env8):
        """The consensus builder's program: a single unconditional pmax —
        verified the same way the trace-safety gate does."""
        from cylon_tpu.analysis import jaxpr_check, registry
        registry.collect()
        decl = registry.get("cylon_tpu.exec.recovery._consensus_fn")
        assert decl is not None and decl.collectives == {"pmax"}
        assert jaxpr_check.verify_builder(decl, env8.mesh) == []

    def test_guard_consensus_local(self, env4):
        assert recovery.guard_consensus(env4.mesh, True)
        assert not recovery.guard_consensus(env4.mesh, False)

    def test_ckpt_commit_consensus_local(self, env4):
        # single-controller: the local staged epoch IS the agreed epoch
        # (no collective) — multiprocess divergence is exercised by the
        # kill-resume scenario in tests/multihost_driver.py
        assert recovery.ckpt_commit_consensus(env4.mesh, 3) == 3
        assert recovery.ckpt_commit_consensus(None, 0) == 0
        with pytest.raises(ValueError):
            recovery.ckpt_commit_consensus(env4.mesh, 1 << 21)

    def test_watchdog_passthrough_when_off(self):
        assert recovery.exchange_watchdog("exchange.counts",
                                          lambda: 7, timeout_s=0) == 7

    def test_watchdog_completes_within_deadline(self):
        assert recovery.exchange_watchdog("exchange.counts",
                                          lambda: 7, timeout_s=5.0) == 7

    def test_watchdog_propagates_thunk_error(self):
        def boom():
            raise ValueError("inner")

        with pytest.raises(ValueError):
            recovery.exchange_watchdog("exchange.counts", boom,
                                       timeout_s=5.0)

    def test_watchdog_converts_stall_to_desync(self):
        """An injected peer stall becomes a typed RankDesyncError carrying
        the site and the last-known timing phase."""
        from cylon_tpu.utils import timing
        with timing.region("pipe.unit_test_phase"):
            pass
        recovery.install_faults("exchange.stall=stall")
        with pytest.raises(RankDesyncError) as ei:
            recovery.exchange_watchdog("exchange.counts",
                                       lambda: 7, timeout_s=0.2)
        assert ei.value.site == "exchange.counts"
        assert ei.value.phase == "pipe.unit_test_phase"

    def test_watchdog_stall_through_shuffle(self, env4, rng, monkeypatch):
        """End to end: a stalled exchange count pull surfaces as a typed
        RankDesyncError from shuffle_table (no infinite block), and the
        ladder refuses to retry it."""
        from cylon_tpu import config
        from cylon_tpu.relational.repart import shuffle_table
        monkeypatch.setattr(config, "EXCHANGE_WATCHDOG_S", 0.2)
        ldf, _, lt, _ = _tables(env4, rng, n=800)
        recovery.install_faults("exchange.stall=stall")
        with pytest.raises(RankDesyncError):
            shuffle_table(lt, ["k"])


# ---------------------------------------------------------------------------
# taxonomy at the real guard site
# ---------------------------------------------------------------------------

class TestGuardSiteTyped:
    def test_peer_fault_placeholder_is_typed(self):
        """Ranks following a peer's agreed fault must synthesize a TYPED
        taxonomy fault of the SAME class (the wire encoding separates
        predicted from device OOM) — classify() passes it through,
        keeping enclosing ladders and type-dispatching callers (e.g.
        bench_tpch's abort-vs-halve) on the same branch on every rank."""
        from cylon_tpu.exec.recovery import _fault_from_wire, _wire_code
        for local in (PredictedResourceExhausted("x"), DeviceOOMError("x"),
                      CapacityOverflowError("x"), RankDesyncError("x")):
            synth = _fault_from_wire(_wire_code(local), "peer")
            assert type(synth) is type(local), (local, synth)
            assert recovery.classify(synth) is synth
        # predicted sorts BELOW a real device OOM within Code.OutOfMemory:
        # mixed ranks coherently agree on the device_oom interpretation
        assert _wire_code(PredictedResourceExhausted("x")) \
            < _wire_code(DeviceOOMError("x"))
        assert _wire_code(None) == 0

    def test_recv_guard_honors_injected_kind(self, env4, rng):
        """A non-predicted kind injected at the guard site raises THAT
        kind (not the predicted shape), so simulations of real device
        OOMs at the exchange behave like real device OOMs."""
        from cylon_tpu.relational.repart import shuffle_table
        ldf, _, lt, _ = _tables(env4, rng, n=800)
        recovery.install_faults("shuffle.recv_guard::1=capacity")
        with pytest.raises(CapacityOverflowError):
            shuffle_table(lt, ["k"])

    def test_recv_guard_raises_typed(self, env8, rng, monkeypatch):
        from cylon_tpu import config
        from cylon_tpu.relational.repart import shuffle_table
        monkeypatch.setattr(config, "EXCHANGE_RECV_BUDGET_BYTES", 4096)
        monkeypatch.setattr(config, "EXCHANGE_RECV_GUARD_CPU", True)
        n = 4000
        t = ct.Table.from_pandas(
            pd.DataFrame({"k": np.full(n, 7, np.int64),
                          "v": rng.random(n)}), env8)
        with pytest.raises(PredictedResourceExhausted) as ei:
            shuffle_table(t, ["k"])
        assert ei.value.site == "shuffle.recv_guard"
        assert "RESOURCE_EXHAUSTED (predicted)" in str(ei.value)
