"""Multi-slice topology tier (cylon_tpu/topo, docs/topology.md): the
hierarchical two-hop exchange must be bit- and order-equal to the flat
plan for every operator riding the exchange engine on a simulated
two-tier CPU grid, the tier-split comm accounting must reconcile with
the always-on counters, the topology plan must vote before the first
hierarchical collective, and the single-slice/unarmed path must add
zero collectives and zero host syncs."""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.obs import comm, metrics
from cylon_tpu.relational import groupby_aggregate, join_tables, sort_table
from cylon_tpu.relational.repart import repartition, shuffle_table
from cylon_tpu.relational.setops import set_operation
from cylon_tpu.topo import exchange as topo_exchange, model as topo_model


@pytest.fixture
def two_tier(env8, monkeypatch):
    """The 8-rank session env re-declared as 2 slices of 4 (the CPU
    simulation knob); restores the single-slice view on teardown."""
    monkeypatch.setenv("CYLON_TPU_SLICES", "2")
    topo_model._reslice()
    yield env8
    monkeypatch.delenv("CYLON_TPU_SLICES")
    topo_model._reslice()


@pytest.fixture
def flat_route(monkeypatch):
    monkeypatch.setattr(config, "TOPO_SHUFFLE", False)
    yield
    monkeypatch.setattr(config, "TOPO_SHUFFLE", True)


def _tables(env, n=3000, seed=11, mv=300):
    rng = np.random.default_rng(seed)
    ldf = pd.DataFrame({"k": rng.integers(0, mv, n).astype(np.int64),
                        "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, mv, n).astype(np.int64),
                        "b": rng.integers(0, 99, n).astype(np.int64)})
    return (ct.Table.from_pandas(ldf, env), ct.Table.from_pandas(rdf, env),
            ldf, rdf)


def _both_routes(fn):
    """(hierarchical result, flat result) of one thunk — the equality
    harness every operator test runs through."""
    assert config.TOPO_SHUFFLE
    hier = fn()
    prev = config.TOPO_SHUFFLE
    config.TOPO_SHUFFLE = False
    try:
        flat = fn()
    finally:
        config.TOPO_SHUFFLE = prev
    return hier, flat


# ---------------------------------------------------------------------------
# the tier model
# ---------------------------------------------------------------------------

class TestModel:
    def test_env_declaration(self, two_tier):
        t = two_tier.topology
        assert (t.n_slices, t.ranks_per_slice, t.source) == (2, 4, "env")
        assert t.slice_of(0) == 0 and t.slice_of(7) == 1
        assert t.slice_ids().tolist() == [0, 0, 0, 0, 1, 1, 1, 1]
        cross = t.cross_mask()
        assert not cross[0, 3] and cross[0, 4] and cross[7, 1]

    def test_bad_declarations_degrade_to_single(self, env8, monkeypatch):
        # non-dividing, out-of-range and garbage declarations all fall
        # back to single-slice (flat route) — never an error
        for bad in ("3", "16", "1", "0", "nope"):
            monkeypatch.setenv("CYLON_TPU_SLICES", bad)
            topo_model._reslice()
            t = env8.topology
            assert t.n_slices == 1, (bad, t)
            assert topo_model.hier_plan(env8.mesh) is None
        monkeypatch.delenv("CYLON_TPU_SLICES")
        topo_model._reslice()

    def test_gateway_and_plan_identity(self, two_tier):
        # destination (D=1, j=2) buckets on slice 0's local rank 2
        assert topo_model.gateway_of(6, 0, 4) == 2
        assert topo_model.gateway_of(6, 1, 4) == 6
        p1 = topo_model.hier_plan(two_tier.mesh)
        p2 = topo_model.hier_plan(two_tier.mesh)
        assert p1 is p2 and p1.route == "hierarchical"
        # the canonical hash is deterministic across processes/retries
        assert p1.plan_hash() == topo_model.TopologyPlan(
            two_tier.topology, "hierarchical").plan_hash()

    def test_ranks_per_slice_one_routes_flat(self, env8, monkeypatch):
        monkeypatch.setenv("CYLON_TPU_SLICES", "8")
        topo_model._reslice()
        assert env8.topology.n_slices == 8
        # S == W: hop 2 would be the full-axis exchange, hop 1 pure
        # overhead — the plan facade routes flat
        assert topo_model.hier_plan(env8.mesh) is None
        monkeypatch.delenv("CYLON_TPU_SLICES")
        topo_model._reslice()

    def test_slice_major_order(self):
        class D:
            def __init__(self, i, s=None):
                self.id = i
                if s is not None:
                    self.slice_index = s

        interleaved = [D(0, 1), D(1, 0), D(2, 1), D(3, 0)]
        ordered = topo_model.slice_major_order(interleaved)
        assert [d.id for d in ordered] == [1, 3, 0, 2]
        plain = [D(i) for i in range(4)]
        assert topo_model.slice_major_order(plain) == plain

    def test_hop_counts_conservation(self):
        rng = np.random.default_rng(5)
        c = rng.integers(0, 50, (8, 8)).astype(np.int64)
        c1, c2 = topo_exchange.hop_counts(c, 2)
        # hop 1 is slice-local, hop 2 same-local-index only
        sid = np.arange(8) // 4
        assert (c1[sid[:, None] != sid[None, :]] == 0).all()
        loc = np.arange(8) % 4
        assert (c2[loc[:, None] != loc[None, :]] == 0).all()
        # conservation: sources send everything into hop 1, gateways
        # forward exactly what they received, destinations receive the
        # logical column sums
        assert np.array_equal(c1.sum(axis=1), c.sum(axis=1))
        assert np.array_equal(c1.sum(axis=0), c2.sum(axis=1))
        assert np.array_equal(c2.sum(axis=0), c.sum(axis=0))


# ---------------------------------------------------------------------------
# bit/order equality per operator (the tentpole contract)
# ---------------------------------------------------------------------------

class TestEquality:
    def test_shuffle_join_groupby(self, two_tier):
        lt, rt, _, _ = _tables(two_tier)
        sh, sf = _both_routes(
            lambda: shuffle_table(lt, ["k"]).to_pandas())
        pd.testing.assert_frame_equal(sh, sf)   # exact incl. row order
        for how in ("inner", "left", "outer"):
            jh, jf = _both_routes(
                lambda h=how: join_tables(lt, rt, "k", "k",
                                          how=h).to_pandas())
            pd.testing.assert_frame_equal(jh, jf)
        gh, gf = _both_routes(
            lambda: groupby_aggregate(
                join_tables(lt, rt, "k", "k", how="inner"), "k",
                [("a", "sum"), ("b", "sum")]).to_pandas())
        pd.testing.assert_frame_equal(gh, gf)

    def test_sort_repartition_setops(self, two_tier):
        lt, _, ldf, _ = _tables(two_tier, seed=12)
        sh, sf = _both_routes(lambda: sort_table(lt, "k").to_pandas())
        pd.testing.assert_frame_equal(sh, sf)
        rh, rf = _both_routes(
            lambda: repartition(shuffle_table(lt, ["k"])).to_pandas())
        pd.testing.assert_frame_equal(rh, rf)
        rng = np.random.default_rng(13)
        at = ct.Table.from_pandas(
            pd.DataFrame({"k": rng.integers(0, 50, 800).astype(np.int64)}),
            two_tier)
        bt = ct.Table.from_pandas(
            pd.DataFrame({"k": rng.integers(0, 50, 800).astype(np.int64)}),
            two_tier)
        for op in ("intersect", "union", "subtract"):
            oh, of = _both_routes(
                lambda o=op: set_operation(at, bt, o).to_pandas())
            pd.testing.assert_frame_equal(oh, of)

    def test_hot_key_concentration(self, two_tier):
        # an all-to-one distribution drives the multi-round protocol
        # inside the hops; still bit/order-equal
        rng = np.random.default_rng(14)
        df = pd.DataFrame({"k": np.full(60000, 7, np.int64),
                           "a": rng.random(60000)})
        t = ct.Table.from_pandas(df, two_tier)
        n0 = metrics.counter("timing_event_exchange.two_hop").value
        sh, sf = _both_routes(lambda: shuffle_table(t, ["k"]).to_pandas())
        pd.testing.assert_frame_equal(sh, sf)
        assert metrics.counter("timing_event_exchange.two_hop").value > n0

    def test_skew_split_route_under_two_tier(self, two_tier):
        # the adaptive skew-split plan (PR 14) rides the two-hop
        # transport transparently: stitched output still bit/order-equal
        rng = np.random.default_rng(15)
        n = 6000
        hot = np.int64(77)
        sk = rng.integers(0, 600, n).astype(np.int64)
        sk = np.where(rng.random(n) < 0.7, hot, sk)
        bk = rng.integers(0, 600, n).astype(np.int64)
        bk[bk == hot] = hot + 1
        bk[0] = hot
        sl = ct.Table.from_pydict(
            {"k": sk, "a": rng.integers(0, 100, n).astype(np.int64)},
            two_tier)
        sr = ct.Table.from_pydict(
            {"k": bk, "b": rng.integers(0, 100, n).astype(np.int64)},
            two_tier)
        jh, jf = _both_routes(
            lambda: join_tables(sl, sr, "k", "k", how="inner").to_pandas())
        pd.testing.assert_frame_equal(jh, jf)


# ---------------------------------------------------------------------------
# tier accounting + plan vote + unarmed contracts
# ---------------------------------------------------------------------------

class TestAccounting:
    def _armed_shuffle(self, env, lt):
        comm.arm()
        comm.reset()
        r0 = metrics.counter("exchange_rows_total").value
        b0 = metrics.counter("exchange_bytes_total").value
        shuffle_table(lt, ["k"])
        rep = comm.report()
        comm.arm(False)
        comm.reset()
        assert rep["total_rows"] == \
            metrics.counter("exchange_rows_total").value - r0
        assert rep["total_bytes"] == \
            metrics.counter("exchange_bytes_total").value - b0
        return rep

    def test_tier_split_reconciles_and_dcn_messages_quarter(
            self, two_tier):
        lt, _, _, _ = _tables(two_tier, seed=16)
        rep_h = self._armed_shuffle(two_tier, lt)
        prev = config.TOPO_SHUFFLE
        config.TOPO_SHUFFLE = False
        try:
            rep_f = self._armed_shuffle(two_tier, lt)
        finally:
            config.TOPO_SHUFFLE = prev
        for rep in (rep_h, rep_f):
            t = rep["tiers"]
            assert t["n_slices"] == 2
            assert t["ici_rows"] + t["dcn_rows"] == rep["total_rows"]
            assert t["ici_bytes"] + t["dcn_bytes"] == rep["total_bytes"]
            m = np.asarray(t["ici_rows_matrix"]) \
                + np.asarray(t["dcn_rows_matrix"])
            assert np.array_equal(m, np.asarray(rep["rows"]))
        th, tf = rep_h["tiers"], rep_f["tiers"]
        assert th["routes"] == {"two_hop": 1}
        assert tf["routes"] == {"flat": 1}
        # cross-slice PAYLOAD is route-invariant; the MESSAGE count is
        # the two-hop win — exactly 1/R (R = 4) at equal round counts
        assert th["dcn_rows"] == tf["dcn_rows"]
        assert th["dcn_messages"] * 4 == tf["dcn_messages"]
        assert th["dcn_wire_bytes"] <= tf["dcn_wire_bytes"]

    def test_concentrated_counts_cut_dcn_wire_by_ranks_per_slice(
            self, two_tier):
        # a single-source repartition (all rows on rank 0, re-spread
        # evenly) has a one-row count matrix: the flat engine still
        # pads every one of its W−R cross-slice cells per rank to the
        # block, while the two-hop plan's aggregated hop-2 cells stay
        # at W·(S−1) — the DCN WIRE bytes drop by exactly 1/R on this
        # workload class (docs/topology.md "What the two-hop route
        # buys"); payload rows stay route-invariant as always
        rng = np.random.default_rng(19)
        n = 4096
        t = ct.Table.from_pandas(
            pd.DataFrame({"k": rng.integers(0, 999, n).astype(np.int64)}),
            two_tier)
        conc = [n] + [0] * 7
        t0 = repartition(t, rows_per_partition=conc)

        def measure():
            comm.arm()
            comm.reset()
            repartition(t0)
            rep = comm.report()
            comm.arm(False)
            comm.reset()
            return rep["tiers"]

        th, tf = _both_routes(measure)
        assert th["dcn_rows"] == tf["dcn_rows"]
        assert th["dcn_wire_bytes"] * 4 == tf["dcn_wire_bytes"]
        assert th["dcn_messages"] * 4 == tf["dcn_messages"]

    def test_plan_votes_once_per_mesh(self, two_tier):
        lt, _, _, _ = _tables(two_tier, seed=17)
        topo_model._ADOPTED.clear()
        v0 = metrics.counter("topo_plans_voted").value
        shuffle_table(lt, ["k"])
        plan = topo_model.last_plan()
        assert plan is not None and plan.route == "hierarchical"
        assert metrics.counter("topo_plans_voted").value == v0 + 1
        shuffle_table(lt, ["k"])    # same mesh + plan: no re-vote
        assert metrics.counter("topo_plans_voted").value == v0 + 1

    def test_single_slice_armed_is_byte_identical(self, env8):
        # no slice declaration: the ARMED route must take the flat
        # engine verbatim — same results, same exchange counters, no
        # vote, no tier counters (zero extra collectives / host syncs)
        assert env8.topology.n_slices == 1
        assert topo_model.hier_plan(env8.mesh) is None
        lt, rt, _, _ = _tables(env8, seed=18)

        def run():
            r0 = metrics.counter("exchange_rows_total").value
            c0 = metrics.counter("exchange_count").value
            d0 = metrics.counter("exchange_dcn_rows_total").value
            v0 = metrics.counter("topo_plans_voted").value
            out = join_tables(lt, rt, "k", "k", how="inner").to_pandas()
            return (out,
                    metrics.counter("exchange_rows_total").value - r0,
                    metrics.counter("exchange_count").value - c0,
                    metrics.counter("exchange_dcn_rows_total").value - d0,
                    metrics.counter("topo_plans_voted").value - v0)

        (oh, rows_h, cnt_h, dcn_h, vote_h), \
            (of, rows_f, cnt_f, dcn_f, vote_f) = _both_routes(run)
        pd.testing.assert_frame_equal(oh, of)
        assert (rows_h, cnt_h) == (rows_f, cnt_f)
        assert dcn_h == dcn_f == 0
        assert vote_h == vote_f == 0

    def test_recv_guard_sizes_both_tiers(self, two_tier):
        # a remote-slice-concentrated route makes the hop-1 gateway the
        # larger receive tier, and the gateway buffers are still alive
        # while the final buffers fill — the guard bound is the SUM of
        # the tiers (payload + the int32 sidecar lane on hop 1)
        plan = topo_model.hier_plan(two_tier.mesh)
        c = np.zeros((8, 8), np.int64)
        c[0:4, 4] = 1000        # slice 0 → rank (1, 0): gateway (0, 0)
        prep = topo_exchange.prepare(plan, c)
        assert prep.cap1 >= 4000
        rb = 16
        need = topo_exchange.recv_guard_bytes(plan, prep, 4096, rb)
        assert need == prep.cap1 * (rb + 4) + 4096 * rb


# ---------------------------------------------------------------------------
# trimmed chaos soak (the cross-process multislice acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_multislice_pinned():
    """scripts/chaos_soak.py --multislice: the pinned two-tier
    schedules — hierarchical bit-equal to flat with a voted plan and
    ~1/R DCN messages, capacity fault re-adopting the same plan,
    whole-slice SIGKILL resuming via elastic re-shard, and the unarmed
    single-slice zero-extra-collectives leg."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "chaos_soak.py"),
         "--multislice", "--rows", "2000", "--chunks", "3"],
        capture_output=True, text=True, timeout=570, cwd=repo)
    assert p.returncode == 0, p.stdout[-4000:] + p.stderr[-2000:]
    assert "topo hier -> ok" in p.stdout, p.stdout[-3000:]
    assert "slice-kill + elastic resume -> ok" in p.stdout, p.stdout[-3000:]
    assert "unarmed single-slice -> ok" in p.stdout, p.stdout[-3000:]
