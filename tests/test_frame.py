"""DataFrame API tests (reference python test_frame.py — 25 DataFrame cases
— plus the env-dispatch contract from frame.py:2063)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct

from utils import assert_frames_equal


def pdf(rng, n=80):
    return pd.DataFrame({
        "k": rng.integers(0, 12, n),
        "v": rng.random(n),
        "s": rng.choice(["red", "green", "blue"], n),
    })


def test_construct_variants(env4, rng):
    d = {"a": np.arange(10), "b": np.arange(10) * 0.5}
    df1 = ct.DataFrame(d)
    assert df1.shape == (10, 2)
    df2 = ct.DataFrame(pd.DataFrame(d), env=env4)
    assert df2.shape == (10, 2)
    assert df2.env.world_size == 4
    df3 = ct.DataFrame([list(range(5)), list(range(5))])
    assert df3.columns == ["0", "1"]


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_merge_env_dispatch(env8, rng, how):
    ldf, rdf = pdf(rng), pdf(rng, 40)
    # local (no env)
    l_loc, r_loc = ct.DataFrame(ldf), ct.DataFrame(rdf)
    got_local = l_loc.merge(r_loc, on="k", how=how, suffixes=("_x", "_y"))
    assert got_local.env.world_size == 1
    # distributed (env passed at op time, reference contract)
    got_dist = l_loc.merge(r_loc, on="k", how=how, suffixes=("_x", "_y"),
                           env=env8)
    assert got_dist.env.world_size == 8
    exp = ldf.merge(rdf, on="k", how=how, suffixes=("_x", "_y"))
    for got in (got_local, got_dist):
        assert_frames_equal(got.to_pandas(), exp, sort_by=list(exp.columns))


def test_join_suffixes(env4, rng):
    ldf = pd.DataFrame({"k": rng.integers(0, 8, 30), "v": rng.random(30)})
    rdf = pd.DataFrame({"k": rng.integers(0, 8, 20), "v": rng.random(20)})
    l, r = ct.DataFrame(ldf, env=env4), ct.DataFrame(rdf, env=env4)
    got = l.join(r, on="k", how="inner", lsuffix="l", rsuffix="r")
    assert set(got.columns) == {"kl", "kr", "vl", "vr"}
    exp = ldf.merge(rdf, on="k", how="inner", suffixes=("l", "r"))
    g = got.to_pandas()[["kl", "vl", "vr"]].rename(
        columns={"kl": "k"})
    assert_frames_equal(g, exp[["k", "vl", "vr"]], sort_by=["k", "vl"])


def test_sort_values_groupby(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    got = df.groupby("k")[["v"]].sum().sort_values("k").to_pandas()
    exp = data.groupby("k", as_index=False)[["v"]].sum().sort_values(
        "k").reset_index(drop=True)
    pd.testing.assert_frame_equal(got.reset_index(drop=True), exp,
                                  check_dtype=False)


def test_groupby_agg_dict(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    got = df.groupby("k").agg({"v": ["sum", "mean"]}).to_pandas()
    exp = data.groupby("k").agg(v_sum=("v", "sum"), v_mean=("v", "mean")
                                ).reset_index()
    assert_frames_equal(got, exp, sort_by=["k"])


def test_drop_duplicates(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    got = df.drop_duplicates(subset=["k"]).to_pandas()
    exp = data.drop_duplicates(subset=["k"])
    assert_frames_equal(got, exp.reset_index(drop=True), sort_by=["k"])


def test_set_ops_methods(env4, rng):
    a = pd.DataFrame({"x": rng.integers(0, 10, 30)})
    b = pd.DataFrame({"x": rng.integers(5, 15, 30)})
    da, db = ct.DataFrame(a, env=env4), ct.DataFrame(b, env=env4)
    got_u = set(da.union(db).to_pandas()["x"])
    assert got_u == set(a["x"]) | set(b["x"])
    got_i = set(da.intersect(db).to_pandas()["x"])
    assert got_i == set(a["x"]) & set(b["x"])
    got_s = set(da.subtract(db).to_pandas()["x"])
    assert got_s == set(a["x"]) - set(b["x"])


def test_series_arithmetic(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    df["w"] = df["v"] * 2 + 1
    got = df.to_pandas()
    np.testing.assert_allclose(got["w"], data["v"] * 2 + 1)
    df["r"] = df["w"] - df["v"]
    np.testing.assert_allclose(df.to_pandas()["r"], data["v"] + 1)


def test_filter_mask(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    got = df[df["k"] > 5].to_pandas()
    exp = data[data["k"] > 5].reset_index(drop=True)
    assert_frames_equal(got, exp, sort_by=["k", "v"])
    # compound mask
    got2 = df[(df["k"] > 3) & (df["v"] < 0.5)].to_pandas()
    exp2 = data[(data["k"] > 3) & (data["v"] < 0.5)].reset_index(drop=True)
    assert_frames_equal(got2, exp2, sort_by=["k", "v"])


def test_filter_string_compare(env4, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env4)
    got = df[df["s"] == "red"].to_pandas()
    exp = data[data["s"] == "red"].reset_index(drop=True)
    assert_frames_equal(got, exp, sort_by=["k", "v"])
    # absent scalar: ordered compare via insertion point
    got2 = df[df["s"] < "green!"].to_pandas()
    exp2 = data[data["s"] < "green!"].reset_index(drop=True)
    assert_frames_equal(got2, exp2, sort_by=["k", "v"])


def test_series_reductions(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    assert df["k"].sum() == data["k"].sum()
    assert df["k"].min() == data["k"].min()
    assert df["k"].max() == data["k"].max()
    assert df["k"].count() == len(data)
    np.testing.assert_allclose(df["v"].mean(), data["v"].mean())
    assert df["s"].nunique() == data["s"].nunique()
    assert df["s"].min() == data["s"].min()


def test_series_isna_fillna(env4):
    data = pd.DataFrame({"s": ["a", None, "b", None, "c"],
                         "f": [1.0, np.nan, 3.0, 4.0, np.nan]})
    df = ct.DataFrame(data, env=env4)
    assert df["s"].isna().to_numpy().tolist() == [False, True, False, True,
                                                  False]
    assert df["f"].isna().to_numpy().tolist() == [False, True, False, False,
                                                  True]
    filled = df["s"].fillna("zz")
    assert filled.to_numpy().tolist() == ["a", "zz", "b", "zz", "c"]
    ff = df["f"].fillna(0.0)
    np.testing.assert_allclose(ff.to_numpy(), [1.0, 0.0, 3.0, 4.0, 0.0])


def test_head_tail_slice(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    pd.testing.assert_frame_equal(df.head(3).to_pandas(),
                                  data.head(3).reset_index(drop=True),
                                  check_dtype=False)
    pd.testing.assert_frame_equal(df.tail(3).to_pandas(),
                                  data.tail(3).reset_index(drop=True),
                                  check_dtype=False)
    pd.testing.assert_frame_equal(df[10:20].to_pandas(),
                                  data[10:20].reset_index(drop=True),
                                  check_dtype=False)


def test_setitem_host_array(env8, rng):
    data = pdf(rng)
    df = ct.DataFrame(data, env=env8)
    df["z"] = np.arange(len(data))
    got = df.to_pandas()
    assert got["z"].tolist() == list(range(len(data)))
    df["c"] = 7
    assert (df.to_pandas()["c"] == 7).all()


def test_concat_frames(env4, rng):
    a, b = pdf(rng, 30), pdf(rng, 20)
    da, db = ct.DataFrame(a, env=env4), ct.DataFrame(b, env=env4)
    got = ct.concat([da, db])
    assert len(got) == 50
    assert_frames_equal(got.to_pandas(), pd.concat([a, b], ignore_index=True),
                        sort_by=["k", "v"])


def test_equals_method(env4, rng):
    data = pdf(rng)
    d1 = ct.DataFrame(data, env=env4)
    d2 = ct.DataFrame(data.copy(), env=env4)
    assert d1.equals(d2)
    assert d1.equals(ct.DataFrame(data.sample(frac=1.0, random_state=0),
                                  env=env4), ordered=False)


def test_df_reductions(env4, rng):
    data = pd.DataFrame({"a": rng.integers(0, 50, 40),
                         "b": rng.random(40)})
    df = ct.DataFrame(data, env=env4)
    s = df.sum()
    assert s["a"] == data["a"].sum()
    np.testing.assert_allclose(s["b"], data["b"].sum())


def test_merge_algorithm_option(env1):
    import warnings
    import cylon_tpu as ct
    ldf = pd.DataFrame({"k": [1, 2, 3], "a": [1.0, 2.0, 3.0]})
    rdf = pd.DataFrame({"k": [2, 3, 4], "b": [5, 6, 7]})
    lf, rf = ct.DataFrame(ldf, env=env1), ct.DataFrame(rdf, env=env1)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = lf.merge(rf, on="k", algorithm="hash").to_pandas()
    assert any("hash" in str(x.message) for x in w)
    assert len(out) == 2
    with pytest.raises(Exception):
        lf.merge(rf, on="k", algorithm="bogus")
