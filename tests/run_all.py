"""Suite driver: one pytest process per test file, segfault-resilient.

The reference runs its python suite exactly this way — test_all.py shells
out a pytest invocation per file (python/pycylon/test/test_all.py:23-29) —
and here it is load-bearing robustness, not just parity: the XLA:CPU
compiler segfaults nondeterministically in long-lived processes (~1 in
1000 compiles, observed live as faulthandler dumps inside
``backend_compile_and_load`` at random tests on full-suite runs; single
files never accumulate enough compiles to hit it).  Per-file processes
bound the blast radius and a crashed file retries once — a repeated crash
in the SAME file is a real failure and reports as one.

Usage: python tests/run_all.py [pytest args...]
Exit code 0 iff every file passed.
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

#: seconds to back off before re-running a signal-killed file: the
#: crash class this driver exists for (XLA:CPU compiler segfaults)
#: correlates with transient memory pressure, and an immediate rerun
#: inherits it more often than a briefly delayed one
RETRY_BACKOFF_S = 2.0


def run_file(path: str, extra: list[str]) -> int:
    cmd = [sys.executable, "-m", "pytest", path, "-q", *extra]
    # each file's process prints a greppable `# COMPILE_COUNT file=...
    # n=...` line at exit (tests/conftest.py): the per-file compile
    # budget audit that motivated this driver (XLA:CPU segfaults track
    # compile accumulation) becomes a number in the tee'd log
    env = dict(os.environ, CYLON_TPU_COMPILE_COUNT="1")
    for attempt in (1, 2):
        r = subprocess.run(cmd, cwd=os.path.dirname(HERE), env=env)
        if r.returncode in (0, 5):     # 5 = no tests collected
            return 0
        # negative = killed by signal (SIGSEGV -11); retry once
        if r.returncode >= 0 or attempt == 2:
            return r.returncode
        # one-line retry marker: a retried file's dots appear TWICE in
        # the tee'd log, so the tier-1 DOTS accounting needs a greppable
        # record of every retry that fired (and of the crashed first
        # pass's partial dot line) to stay auditable
        print(f"# DOTS_RETRY file={os.path.basename(path)} "
              f"signal={-r.returncode} backoff={RETRY_BACKOFF_S:g}s "
              "(first pass's partial dots above are superseded by the "
              "rerun)", flush=True)
        time.sleep(RETRY_BACKOFF_S)
    return 1


def main() -> int:
    extra = sys.argv[1:]
    files = sorted(glob.glob(os.path.join(HERE, "test_*.py")))
    failed = []
    for f in files:
        print(f"== {os.path.basename(f)}", flush=True)
        if run_file(f, extra) != 0:
            failed.append(os.path.basename(f))
    if failed:
        print(f"FAILED files: {failed}", flush=True)
        return 1
    print("ALL FILES PASSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
