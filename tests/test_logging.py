"""utils/logging.py: set_log_level input validation (satellite of the
trace-safety PR — bool was silently accepted as glog level 1 because
``bool`` is an ``int`` subclass and ``True in {0,1,2,3}``)."""

import logging

import pytest

from cylon_tpu.utils.logging import _GLOG_LEVELS, log, set_log_level


@pytest.fixture(autouse=True)
def _restore_level():
    before = log.level
    yield
    log.setLevel(before)


def test_glog_ints_map():
    for glog, expected in _GLOG_LEVELS.items():
        set_log_level(glog)
        assert log.level == expected


def test_names_and_raw_ints():
    set_log_level("debug")
    assert log.level == logging.DEBUG
    set_log_level("ERROR")
    assert log.level == logging.ERROR
    set_log_level(logging.INFO)
    assert log.level == logging.INFO


@pytest.mark.parametrize("value", [True, False])
def test_bools_rejected(value):
    # True == 1 and False == 0 would silently alias glog WARNING/INFO
    before = log.level
    with pytest.raises(TypeError, match="bool"):
        set_log_level(value)
    assert log.level == before


def test_unknown_name_raises():
    with pytest.raises(AttributeError):
        set_log_level("not_a_level")
