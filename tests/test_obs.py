"""Tests for the unified observability subsystem (cylon_tpu.obs).

Fast tests (tier-1): metrics registry semantics (typed metrics, the
group/namespace migration shims, Prometheus exposition, JSON
snapshots), histogram quantiles bit-consistent with np.percentile (the
serving SLO acceptance), the shared bench_detail collector's key-schema
stability, flight-recorder ring wrap + postmortem content + session
tagging, the obs.export injection site surfacing typed, the
zero-overhead/zero-write unarmed contract, and the utils/timing edge
cases (reset clears the last-region breadcrumb, baton-park netting in
BOTH tables across nesting, sync_region/split_snapshot round-trip).

Slow tests: scripts/bench_smoke.py driven in a subprocess with
``CYLON_TPU_TRACE`` armed, validating the emitted Chrome-trace JSON
schema (pid/tid presence, ts monotonicity, per-piece dispatch spans,
balanced async in-flight pairs).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from cylon_tpu import config, obs
from cylon_tpu.obs import metrics, rank_report, trace
from cylon_tpu.status import (ExecutionError, InvalidError,
                              PredictedResourceExhausted)
from cylon_tpu.utils import timing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Every test starts with the recorder disarmed, a fresh phase
    table, bench-mode flags restored and no armed injector."""
    from cylon_tpu.exec import recovery
    monkeypatch.delenv("CYLON_TPU_TRACE", raising=False)
    monkeypatch.delenv("CYLON_TPU_METRICS_JSON", raising=False)
    monkeypatch.delenv("CYLON_TPU_RANK_REPORT", raising=False)
    prev_bench, prev_async = config.BENCH_TIMINGS, config.TIMING_ASYNC
    trace.disarm()
    timing.reset()
    metrics._rearm_snapshots()
    recovery.install_faults("")
    yield
    trace.disarm()
    timing.reset()
    metrics._rearm_snapshots()
    recovery.install_faults("")
    config.BENCH_TIMINGS, config.TIMING_ASYNC = prev_bench, prev_async


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_roundtrip(self):
        c = metrics.counter("t_reg_c")
        c.inc()
        c.inc(4)
        assert metrics.counter("t_reg_c").value == 5
        g = metrics.gauge("t_reg_g")
        g.set(17)
        assert g.value == 17
        live = metrics.gauge("t_reg_live", fn=lambda: 42)
        assert live.value == 42

    def test_type_conflict_is_typed(self):
        metrics.counter("t_reg_conflict")
        with pytest.raises(InvalidError):
            metrics.gauge("t_reg_conflict")

    def test_group_is_dict_like_and_registry_backed(self):
        st = metrics.group("t_grp", ("a_events", "b_bytes"))
        st["a_events"] += 3
        st["b_bytes"] += 100
        assert dict(st) == {"a_events": 3, "b_bytes": 100}
        # the values live in the registry, not the view
        assert metrics.counter("t_grp_a_events").value == 3
        for k in st:
            st[k] = 0
        assert dict(st) == {"a_events": 0, "b_bytes": 0}

    def test_namespace_dynamic_keys(self):
        ns = metrics.namespace("t_ns")
        ns["x"] = ns.get("x", 0) + 7
        assert ns["x"] == 7 and ns.get("zzz") is None
        assert metrics.counter("t_ns_x").value == 7
        ns.clear()
        assert "x" not in ns
        assert metrics.counter("t_ns_x").value == 0

    def test_reset_prefix(self):
        metrics.counter("t_rst_one").inc(5)
        metrics.counter("other_t_rst").inc(5)
        metrics.reset("t_rst")
        assert metrics.counter("t_rst_one").value == 0
        assert metrics.counter("other_t_rst").value == 5

    def test_exec_stats_shims_are_registry_backed(self):
        from cylon_tpu.exec import checkpoint, memory
        checkpoint.reset_stats()
        memory.reset_stats()
        checkpoint._STATS["checkpoint_events"] += 2
        memory._STATS["spill_events"] += 1
        assert checkpoint.stats()["checkpoint_events"] == 2
        assert metrics.counter("ckpt_checkpoint_events").value == 2
        assert metrics.counter("memory_spill_events").value == 1
        checkpoint.reset_stats()
        memory.reset_stats()
        assert metrics.counter("ckpt_checkpoint_events").value == 0
        assert metrics.counter("memory_spill_events").value == 0


class TestHistogram:
    def test_percentiles_bit_consistent_with_sorted_list(self):
        """The serving-bench acceptance: histogram p50/p99 must equal
        np.percentile over the same observations EXACTLY."""
        h = metrics.histogram("t_hist_exact")
        h.reset()
        rng = np.random.default_rng(3)
        xs = list(rng.gamma(2.0, 0.05, 499))
        for x in xs:
            h.observe(x)
        arr = np.asarray(xs, float)
        for p in (50, 90, 99, 99.9):
            assert h.percentile(p) == float(np.percentile(arr, p)), p

    def test_bucket_counts_and_attainment(self):
        h = metrics.histogram("t_hist_buckets", buckets=(0.1, 1.0, 10.0))
        for x in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(x)
        assert sum(h.bucket_counts) == h.count == 5
        assert h.attainment(1.0) == 3 / 5
        assert h.attainment(0.01) == 0.0
        assert metrics.histogram("t_hist_buckets").value["count"] == 5

    def test_truncated_falls_back_to_buckets(self, monkeypatch):
        monkeypatch.setattr(metrics, "SAMPLE_CAP", 8)
        h = metrics.Histogram("t_hist_trunc")
        for x in np.linspace(0.01, 0.3, 40):
            h.observe(x)
        assert h.truncated
        p = h.percentile(50)
        assert p is not None and 0.0 < p < 1.0

    def test_empty_histogram_percentile_is_nan(self):
        """The edge contract (satellite fix): an EMPTY histogram's
        quantile is NaN — not None, not whatever np does on an empty
        array — so reports carry it through arithmetic and JSON."""
        import math
        h = metrics.Histogram("t_hist_empty")
        assert math.isnan(h.percentile(50))
        assert math.isnan(h.percentile(0)) and math.isnan(h.percentile(100))
        # the live-exposition property must not raise either — and it
        # exports the NaN as None so JSON snapshots stay strict-parseable
        v = h.value
        assert v["count"] == 0 and v["p50"] is None

    def test_fully_truncated_percentile_is_nan(self, monkeypatch):
        """Samples observed but NONE retained (cap exhausted before the
        first observation): bucket interpolation would fabricate a
        quantile from the grid alone — NaN by contract."""
        import math
        monkeypatch.setattr(metrics, "SAMPLE_CAP", 0)
        h = metrics.Histogram("t_hist_fully_trunc")
        for x in (0.5, 1.5, 2.5):
            h.observe(x)
        assert h.truncated and h.count == 3
        assert math.isnan(h.percentile(50))
        assert h.value["p99"] is None
        # attainment still answers from bucket counts
        assert h.attainment(100.0) > 0

    def test_percentile_range_is_typed(self):
        h = metrics.Histogram("t_hist_range")
        h.observe(1.0)
        for bad in (-1, 100.5, 1e9):
            with pytest.raises(InvalidError):
                h.percentile(bad)
        assert h.percentile(0) == h.percentile(100) == 1.0


class TestExposition:
    def test_prometheus_text_format(self):
        metrics.counter("t_prom_c").set(9)
        metrics.gauge("t_prom_g").set(3)
        h = metrics.histogram("t_prom_h", buckets=(1.0, 2.0))
        h.reset()
        h.observe(0.5)
        h.observe(1.5)
        text = metrics.prometheus_text()
        assert "# TYPE cylon_tpu_t_prom_c counter" in text
        assert "cylon_tpu_t_prom_c 9" in text
        assert "cylon_tpu_t_prom_g 3" in text
        assert 'cylon_tpu_t_prom_h_bucket{le="1"} 1' in text
        assert 'cylon_tpu_t_prom_h_bucket{le="2"} 2' in text
        assert 'cylon_tpu_t_prom_h_bucket{le="+Inf"} 2' in text
        assert "cylon_tpu_t_prom_h_count 2" in text
        # name sanitization: dots become underscores
        metrics.counter("t.prom.dotted").inc()
        assert "cylon_tpu_t_prom_dotted 1" in metrics.prometheus_text()

    def test_snapshot_carries_phase_collector(self):
        config.BENCH_TIMINGS = True
        timing.reset()
        with timing.region("t.snapcol"):
            pass
        snap = metrics.snapshot()
        assert "t.snapcol" in snap["phases"]

    def test_json_snapshot_write_and_poll(self, tmp_path, monkeypatch):
        path = str(tmp_path / "metrics.json")
        metrics.write_snapshot(path)
        doc = json.load(open(path, encoding="utf-8"))
        assert "ts" in doc and isinstance(doc["metrics"], dict)
        os.unlink(path)
        # armed poll: first call writes, second call inside the interval
        # does not
        monkeypatch.setenv("CYLON_TPU_METRICS_JSON", path)
        monkeypatch.setenv("CYLON_TPU_METRICS_INTERVAL_S", "3600")
        metrics._rearm_snapshots()
        assert metrics.maybe_write_snapshot() is True
        assert os.path.exists(path)
        os.unlink(path)
        assert metrics.maybe_write_snapshot() is False
        assert not os.path.exists(path)

    def test_unarmed_poll_writes_nothing(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        metrics._rearm_snapshots()
        assert metrics.maybe_write_snapshot() is False
        assert os.listdir(tmp_path) == []


class TestBenchDetail:
    """The dedupe satellite's schema guarantee: the shared collector
    reports EXACTLY the keys each bench script always carried."""

    def test_default_selection_matches_bench_py(self):
        bd = obs.bench_detail()
        assert set(bd) == {
            "recovery_events",
            "spill_events", "bytes_spilled", "peak_ledger_bytes",
            "donated_bytes_reused",
            # the disk-tier pair (round 13): a bench number always says
            # whether it rode the out-of-core rung
            "disk_events", "bytes_to_disk",
            "checkpoint_events", "bytes_checkpointed",
            "resume_fast_forwarded_pieces", "resume_resharded_pieces",
            "resume_world_mismatch",
            # the compile-lifecycle block (round 19): a bench number
            # always says how many executables were live and how much
            # wall-clock went to XLA
            "compile",
            # the integrity block (round 20): a bench number always says
            # whether the audit was armed and whether it saw violations
            "audit"}
        assert isinstance(bd["recovery_events"], list)
        assert set(bd["compile"]) == {
            "programs_live", "cache_hits", "cache_misses",
            "cache_evictions", "compile_seconds"}
        assert set(bd["audit"]) == {
            "conservation_checks", "fingerprint_checks", "violations"}

    def test_q3q5_selection(self):
        bd = obs.bench_detail(spill_keys=("spill_events", "bytes_spilled",
                                          "peak_ledger_bytes"))
        assert set(bd) == {
            "recovery_events", "spill_events", "bytes_spilled",
            "peak_ledger_bytes",
            "checkpoint_events", "bytes_checkpointed",
            "resume_fast_forwarded_pieces", "resume_resharded_pieces",
            "resume_world_mismatch", "compile", "audit"}

    def test_serving_selection(self):
        bd = obs.bench_detail(
            spill_keys=("spill_events", "bytes_spilled", "readmit_events",
                        "cross_session_evictions", "peak_ledger_bytes"),
            ckpt_keys=())
        assert set(bd) == {
            "recovery_events", "spill_events", "bytes_spilled",
            "readmit_events", "cross_session_evictions",
            "peak_ledger_bytes", "compile", "audit"}

    def test_streaming_selection_no_events(self):
        bd = obs.bench_detail(spill_keys=("window_evictions",
                                          "bytes_spilled"),
                              ckpt_keys=(), events=None)
        assert set(bd) == {"window_evictions", "bytes_spilled", "compile",
                           "audit"}

    def test_plan_section_opt_in(self):
        """The profiler satellite: bench_detail(plan=...) adds a "plan"
        section; the default schema (asserted above) stays plan-free."""
        assert "plan" not in obs.bench_detail()
        bd = obs.bench_detail(plan={"mode": "analyze", "roots": []})
        assert bd["plan"] == {"mode": "analyze", "roots": []}

        class _QP:
            def to_dict(self):
                return {"mode": "explain", "roots": [{"op": "join"}]}
        assert obs.bench_detail(plan=_QP())["plan"]["roots"][0]["op"] \
            == "join"

    def test_drain_vs_keep(self):
        from cylon_tpu.exec import recovery
        recovery.reset_events()
        recovery._record("t.site", "predicted", "retry")
        kept = obs.bench_detail(events="keep")["recovery_events"]
        assert len(kept) == 1
        drained = obs.bench_detail()["recovery_events"]
        assert len(drained) == 1
        assert obs.bench_detail()["recovery_events"] == []


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestTraceRecorder:
    def test_regions_and_bumps_land_without_bench_flag(self, tmp_path):
        """Arming the recorder alone makes regions record — the trace
        tier must not require CYLON_TPU_BENCH."""
        assert not config.BENCH_TIMINGS
        path = str(tmp_path / "tr.json")
        trace.arm(path=path, capacity=64)
        with timing.region("t.span"):
            time.sleep(0.001)
        timing.bump("t.instant")
        timing.add_bytes("t.bytes", 128)
        out = trace.export()
        doc = json.load(open(out, encoding="utf-8"))
        by_name = {}
        for e in doc["traceEvents"]:
            by_name.setdefault(e["name"], []).append(e)
        assert by_name["t.span"][0]["ph"] == "X"
        assert by_name["t.span"][0]["dur"] >= 1
        assert by_name["t.instant"][0]["ph"] == "i"
        assert by_name["t.bytes"][0]["args"]["bytes"] == 128
        # ...and the global phase table stayed EMPTY (timings off)
        assert "t.span" not in timing.snapshot()

    def test_ring_wrap_keeps_newest(self):
        rec = trace.arm(capacity=8)
        for i in range(20):
            rec.instant(f"ev{i}")
        evs = rec.events()
        assert len(evs) == 8
        assert [e[3] for e in evs] == [f"ev{i}" for i in range(12, 20)]
        assert rec.dropped == 12

    def test_ts_monotone_and_ids_present(self, tmp_path):
        path = str(tmp_path / "tr.json")
        trace.arm(path=path, capacity=32)
        for i in range(5):
            trace.instant(f"t.mono{i}")
        doc = json.load(open(trace.export(), encoding="utf-8"))
        tss = [e["ts"] for e in doc["traceEvents"] if "ts" in e]
        assert tss == sorted(tss)
        for e in doc["traceEvents"]:
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)

    def test_session_tagged_spans(self, tmp_path):
        path = str(tmp_path / "tr.json")
        trace.arm(path=path, capacity=32)
        with timing.attribution_scope("tenant_x"):
            with timing.region("t.sess"):
                pass
        doc = json.load(open(trace.export(), encoding="utf-8"))
        ev = next(e for e in doc["traceEvents"] if e["name"] == "t.sess")
        assert ev["args"]["session"] == "tenant_x"

    def test_async_pairs(self, tmp_path):
        path = str(tmp_path / "tr.json")
        trace.arm(path=path, capacity=32)
        trace.async_begin("t.piece", 3, piece=3)
        trace.async_end("t.piece", 3)
        doc = json.load(open(trace.export(), encoding="utf-8"))
        pair = [e for e in doc["traceEvents"] if e["name"] == "t.piece"]
        assert [e["ph"] for e in pair] == ["b", "e"]
        assert all(e["id"] == 3 and e["cat"] == "piece" for e in pair)

    def test_postmortem_dump_content(self, tmp_path):
        trace.arm(capacity=16)
        for i in range(20):
            timing.bump(f"t.pm{i}")
        with timing.region("t.last"):
            pass
        out = trace.postmortem("unit test", dir_path=str(tmp_path), n=8)
        doc = json.load(open(out, encoding="utf-8"))
        assert doc["reason"] == "unit test"
        assert doc["pid"] == os.getpid()
        assert len(doc["events"]) == 8
        assert doc["events"][-1]["name"] == "t.last"
        assert doc["dropped_events"] > 0

    def test_flush_for_abort_writes_postmortem(self, tmp_path,
                                               monkeypatch):
        """The drain/final-rung flush drops the breadcrumb next to the
        manifests — superseding the single last_region() string."""
        from cylon_tpu.exec import checkpoint
        ckdir = str(tmp_path / "ckpt")
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", ckdir)
        trace.arm(capacity=16)
        timing.bump("t.pre_abort")
        checkpoint.flush_for_abort("unit")
        doc = json.load(open(os.path.join(ckdir, "TRACE_POSTMORTEM.json"),
                             encoding="utf-8"))
        assert any(e["name"] == "t.pre_abort" for e in doc["events"])
        assert doc["reason"] == "abort flush: unit"

    def test_export_injection_surfaces_typed(self, tmp_path):
        from cylon_tpu.exec import recovery
        trace.arm(path=str(tmp_path / "tr.json"), capacity=16)
        recovery.install_faults("obs.export::1=predicted")
        with pytest.raises(PredictedResourceExhausted):
            trace.export()
        recovery.install_faults("")
        assert trace.export() is not None   # recovers once disarmed

    def test_export_oserror_surfaces_typed(self, tmp_path):
        trace.arm(capacity=16)
        missing = str(tmp_path / "no" / "such" / "dir" / "tr.json")
        with pytest.raises(ExecutionError):
            trace.export(missing)


class TestUnarmedContract:
    """The happy-path acceptance: with nothing armed, zero filesystem
    writes and no recording — the same no-op style the checkpoint
    tier's unarmed assertions use."""

    def test_unarmed_records_and_writes_nothing(self, tmp_path,
                                                monkeypatch):
        monkeypatch.chdir(tmp_path)
        assert not trace.armed() and timing._TRACE[0] is None
        with timing.region("t.off"):
            pass
        timing.bump("t.off_bump")
        trace.instant("t.off_instant")
        trace.complete("t.off_span", time.perf_counter())
        assert trace.export() is None
        assert trace.postmortem("nothing armed") is None
        assert not rank_report.armed()
        assert metrics.maybe_write_snapshot() is False
        assert os.listdir(tmp_path) == []

    def test_autoarm_needs_env(self, monkeypatch):
        monkeypatch.delenv("CYLON_TPU_TRACE", raising=False)
        trace.autoarm()
        assert not trace.armed()
        monkeypatch.setenv("CYLON_TPU_TRACE", "/tmp/t.json")
        trace.autoarm()
        assert trace.armed()
        assert trace.recorder().path == "/tmp/t.json"


# ---------------------------------------------------------------------------
# scheduler integration: baton handoffs on the timeline
# ---------------------------------------------------------------------------

def test_scheduler_baton_events_session_tagged(env4, tmp_path):
    from cylon_tpu.exec.scheduler import QueryScheduler
    trace.arm(path=str(tmp_path / "tr.json"), capacity=256)
    sched = QueryScheduler(env4, policy="fifo")
    sched.submit("tA", lambda: 1)
    sched.submit("tB", lambda: 2)
    sessions = sched.run(raise_errors=True)
    assert [s.result for s in sessions] == [1, 2]
    doc = json.load(open(trace.export(), encoding="utf-8"))
    grants = [e for e in doc["traceEvents"] if e["name"] == "sched.grant"]
    assert {g["args"]["session"] for g in grants} >= {"tA", "tB"}


# ---------------------------------------------------------------------------
# utils/timing edge cases (the satellite fixes)
# ---------------------------------------------------------------------------

class TestTimingEdgeCases:
    def test_reset_clears_last_region(self):
        with timing.region("t.lastreg"):
            pass
        assert timing.last_region() == "t.lastreg"
        timing.reset()
        assert timing.last_region() == ""

    def test_park_time_netted_from_global_table(self):
        """The satellite fix: global phase seconds must not include
        baton-park time inside spanning regions (the scope table
        already netted it)."""
        config.BENCH_TIMINGS = True
        timing.reset()
        with timing.region("t.gpark"):
            time.sleep(0.05)
            timing.exclude_from_scope(0.05)   # the scheduler's call
        s = timing.snapshot()["t.gpark"]["s"]
        assert s < 0.02, s
        timing.reset()
        with timing.region("t.gnopark"):
            time.sleep(0.05)
        assert timing.snapshot()["t.gnopark"]["s"] >= 0.04

    def test_exclusion_nets_across_nesting_in_both_tables(self):
        """A park inside the INNER region must net out of inner AND
        outer, in the scope table and the global table alike."""
        config.BENCH_TIMINGS = True
        timing.reset()
        with timing.attribution_scope("t_nest") as sc:
            with timing.region("t.outer"):
                with timing.region("t.inner"):
                    time.sleep(0.05)
                    timing.exclude_from_scope(0.05)
        snap = sc.snapshot()
        assert snap["t.inner"]["s"] < 0.02, snap
        assert snap["t.outer"]["s"] < 0.02, snap
        gsnap = timing.snapshot()
        assert gsnap["t.inner"]["s"] < 0.02, gsnap
        assert gsnap["t.outer"]["s"] < 0.02, gsnap

    def test_nested_scopes_are_disjoint(self):
        """Inner scope shadows: its regions land in the inner table
        only, and exclusion inside the inner scope does not drain the
        outer scope's unrelated regions."""
        timing.reset()
        with timing.attribution_scope("t_out") as so:
            with timing.region("t.only_outer"):
                time.sleep(0.02)
            with timing.attribution_scope("t_in") as si:
                with timing.region("t.only_inner"):
                    time.sleep(0.02)
                    timing.exclude_from_scope(0.02)
        assert "t.only_inner" not in so.snapshot()
        assert "t.only_outer" not in si.snapshot()
        assert si.snapshot()["t.only_inner"]["s"] < 0.01
        assert so.snapshot()["t.only_outer"]["s"] >= 0.015

    def test_sync_region_split_snapshot_roundtrip(self):
        config.BENCH_TIMINGS = True
        timing.reset()
        with timing.region("t.phase"):
            time.sleep(0.002)
        with timing.sync_region("t.phase"):
            time.sleep(0.002)
        # idempotent suffixing: an already-suffixed name stays single
        with timing.sync_region("t.phase" + timing.BLOCK_SUFFIX):
            pass
        snap = timing.snapshot()
        assert "t.phase" in snap
        assert "t.phase" + timing.BLOCK_SUFFIX in snap
        assert "t.phase" + timing.BLOCK_SUFFIX * 2 not in snap
        dispatch, block = timing.split_snapshot(snap)
        assert "t.phase" in dispatch and "t.phase" in block
        assert block["t.phase"] == snap["t.phase.block"]["s"]
        assert dispatch["t.phase"] == snap["t.phase"]["s"]


# ---------------------------------------------------------------------------
# per-rank report
# ---------------------------------------------------------------------------

class TestRankReport:
    def test_unarmed_by_default_armed_by_env(self, monkeypatch):
        assert not rank_report.armed()
        monkeypatch.setenv("CYLON_TPU_RANK_REPORT", "1")
        assert rank_report.armed()
        monkeypatch.delenv("CYLON_TPU_RANK_REPORT")
        rank_report.arm()
        assert rank_report.armed()
        rank_report.arm(False)
        assert not rank_report.armed()

    def test_single_process_report_shape(self):
        config.BENCH_TIMINGS = True
        timing.reset()
        with timing.region("t.rank_phase"):
            time.sleep(0.01)
        timing.bump("t.rank_bump")     # zero-second phase: skew None
        rep = rank_report.report()
        assert rep["ranks"] == 1
        ent = rep["phases"]["t.rank_phase"]
        assert ent["min_s"] == ent["median_s"] == ent["max_s"]
        assert ent["skew"] == 1.0
        assert rep["phases"]["t.rank_bump"]["skew"] is None


# ---------------------------------------------------------------------------
# slow: the CI schema validation drive (satellite 6)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_smoke_emits_valid_chrome_trace(tmp_path):
    """Drives scripts/bench_smoke.py with CYLON_TPU_TRACE armed and
    validates the emitted Chrome-trace JSON: schema fields, ts
    monotonicity, per-piece dispatch spans, balanced async in-flight
    pairs — the pipelined-join timeline the overlap scheduler's
    acceptance reads in Perfetto."""
    out = str(tmp_path / "smoke_trace.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", CYLON_TPU_TRACE=out)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_smoke.py"),
         "--rows=16384"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert p.returncode == 0, p.stdout + p.stderr
    doc = json.load(open(out, encoding="utf-8"))
    events = doc["traceEvents"]
    assert events, "empty trace"
    tss = []
    for e in events:
        assert e["ph"] in ("X", "i", "b", "e", "M"), e
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if "ts" in e:
            tss.append(e["ts"])
        if e["ph"] == "X":
            assert e["dur"] >= 1
    assert tss == sorted(tss), "ts not monotone"
    names = [e["name"] for e in events]
    # the pipelined phase spans are on the timeline...
    for phase in ("pipe.build_sort", "pipe.piece_join", "pipe.consume"):
        assert phase in names, phase
    # ...with one dispatch span per piece, piece-indexed
    disp = [e for e in events if e["name"] == "pipe.piece_dispatch"]
    assert len(disp) >= 2
    pieces = [e["args"]["piece"] for e in disp]
    assert len(set(pieces)) == len(pieces)
    assert all(isinstance(x, int) for x in pieces)
    # the sink's async in-flight spans pair up per chunk id
    begins = [e["id"] for e in events
              if e["name"] == "sink.chunk_inflight" and e["ph"] == "b"]
    ends = [e["id"] for e in events
            if e["name"] == "sink.chunk_inflight" and e["ph"] == "e"]
    assert begins and sorted(begins) == sorted(ends)
