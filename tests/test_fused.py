"""Fused join→groupby pushdown (relational/fused.py) and deferred join
materialization (core.table.DeferredTable).

Reference analog: the streaming operator DAG (cpp/src/cylon/ops/ — DisJoinOP
composing into downstream ops without materialized intermediates, SURVEY §2
C9).  The fused result must be EXACTLY what materialize-then-groupby
produces; the join must stay unmaterialized when (and only when) every
aggregation reduces to multiplicity algebra over the sorted state.
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.core.table import DeferredTable
from cylon_tpu.relational import groupby_aggregate, join_tables

from utils import assert_table_matches


def _tables(env, rng, n=6000, nkey=700, nulls=False):
    a = rng.integers(0, 100, n).astype(np.int64)
    ldf = pd.DataFrame({"k": rng.integers(0, nkey, n).astype(np.int64),
                        "a": a})
    rdf = pd.DataFrame({"k": rng.integers(0, nkey, n).astype(np.int64),
                        "b": rng.integers(0, 100, n).astype(np.int64)})
    if nulls:
        ldf["a"] = ldf["a"].astype("Int64")
        ldf.loc[::7, "a"] = pd.NA
    return ldf, rdf


def _join(env, ldf, rdf):
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    return join_tables(lt, rt, "k", "k", how="inner")


@pytest.mark.parametrize("world", ["env1", "env4", "env8"])
def test_fused_matches_pandas_all_pushdown_ops(world, request, rng):
    env = request.getfixturevalue(world)
    ldf, rdf = _tables(env, rng)
    j = _join(env, ldf, rdf)
    assert isinstance(j, DeferredTable) and not j.materialized
    g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum"),
                                   ("a", "mean"), ("b", "count"),
                                   ("a", "var"), ("b", "std")])
    assert not j.materialized, "pushdown must not materialize the join"
    ej = ldf.merge(rdf, on="k")
    eg = (ej.groupby("k", as_index=False)
          .agg(a_sum=("a", "sum"), b_sum=("b", "sum"), a_mean=("a", "mean"),
               b_count=("b", "count"), a_var=("a", "var"),
               b_std=("b", "std")))
    assert_table_matches(g, eg)


def test_fused_equals_unfused(env4, rng):
    """The fused answer must equal the materialize-then-groupby answer."""
    ldf, rdf = _tables(env4, rng)
    aggs = [("a", "sum"), ("b", "mean"), ("a", "count")]
    j1 = _join(env4, ldf, rdf)
    fused = groupby_aggregate(j1, "k", aggs)
    assert not j1.materialized
    j2 = _join(env4, ldf, rdf)
    j2.columns  # force materialization -> normal grouped fast path
    assert j2.materialized
    normal = groupby_aggregate(j2, "k", aggs)
    fp = fused.to_pandas().sort_values("k").reset_index(drop=True)
    np_ = normal.to_pandas().sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(fp, np_, check_dtype=False, rtol=1e-12)


def test_null_values_in_aggregated_column(env4, rng):
    ldf, rdf = _tables(env4, rng, nulls=True)
    j = _join(env4, ldf, rdf)
    g = groupby_aggregate(j, "k", [("a", "sum"), ("a", "count"),
                                   ("a", "mean")])
    assert not j.materialized
    ej = ldf.merge(rdf, on="k")
    eg = (ej.groupby("k", as_index=False)
          .agg(a_sum=("a", "sum"), a_count=("a", "count"),
               a_mean=("a", "mean")))
    eg["a_sum"] = eg["a_sum"].astype(np.int64)
    # Float64 extension NA -> float64 NaN (the framework's null-float
    # rendering; the fused and materialize paths agree exactly)
    eg["a_mean"] = eg["a_mean"].astype(np.float64)
    assert_table_matches(g, eg)


def test_non_pushdown_op_materializes_and_matches(env4, rng):
    """min/max are not multiplicity-algebraic: the groupby must fall back
    to the materialize path and still be correct."""
    ldf, rdf = _tables(env4, rng)
    j = _join(env4, ldf, rdf)
    g = groupby_aggregate(j, "k", [("a", "sum"), ("a", "min"),
                                   ("b", "max")])
    assert j.materialized
    ej = ldf.merge(rdf, on="k")
    eg = (ej.groupby("k", as_index=False)
          .agg(a_sum=("a", "sum"), a_min=("a", "min"), b_max=("b", "max")))
    assert_table_matches(g, eg)


def test_groupby_on_non_key_column_materializes(env4, rng):
    ldf, rdf = _tables(env4, rng)
    j = _join(env4, ldf, rdf)
    g = groupby_aggregate(j, "a", [("b", "sum")])
    assert j.materialized
    ej = ldf.merge(rdf, on="k")
    eg = ej.groupby("a", as_index=False).agg(b_sum=("b", "sum"))
    assert_table_matches(g, eg)


def test_agg_on_key_column_itself(env4, rng):
    ldf, rdf = _tables(env4, rng)
    j = _join(env4, ldf, rdf)
    g = groupby_aggregate(j, "k", [("k", "count"), ("a", "sum")])
    assert not j.materialized
    ej = ldf.merge(rdf, on="k")
    eg = (ej.groupby("k", as_index=False)
          .agg(k_count=("k", "count"), a_sum=("a", "sum")))
    assert_table_matches(g, eg)


def test_deferred_schema_queries_do_not_materialize(env4, rng):
    ldf, rdf = _tables(env4, rng)
    j = _join(env4, ldf, rdf)
    assert j.column_names == ["k", "a", "b"]
    assert j.column_count == 3
    assert "a" in j and "zzz" not in j
    assert len(j.schema) == 3
    assert j.row_count == len(ldf.merge(rdf, on="k"))
    assert j.capacity > 0
    assert not j.materialized
    # data access materializes
    _ = j.column("a")
    assert j.materialized


def test_deferred_via_dataframe_api(env4, rng):
    """DataFrame.merge -> .groupby on the join keys rides the fused path
    end-to-end through the public API."""
    ldf, rdf = _tables(env4, rng)
    lf = ct.DataFrame(ldf, env=env4)
    rf = ct.DataFrame(rdf, env=env4)
    m = lf.merge(rf, on="k", env=env4)
    g = (m.groupby("k", env=env4)[["a", "b"]].sum()).to_pandas()
    assert not m._table.materialized, \
        "DataFrame terminal agg must ride the fused path, not materialize"
    ej = ldf.merge(rdf, on="k")
    eg = (ej.groupby("k", as_index=False)
          .agg(a_sum=("a", "sum"), b_sum=("b", "sum")))
    g = g.sort_values("k").reset_index(drop=True)
    eg.columns = g.columns
    pd.testing.assert_frame_equal(g, eg.sort_values("k").reset_index(drop=True),
                                  check_dtype=False)


def test_defer_flag_off_restores_eager_join(env4, rng, monkeypatch):
    from cylon_tpu import config
    monkeypatch.setattr(config, "DEFER_JOIN", False)
    ldf, rdf = _tables(env4, rng)
    j = _join(env4, ldf, rdf)
    assert not isinstance(j, DeferredTable)
    g = groupby_aggregate(j, "k", [("a", "sum")])
    ej = ldf.merge(rdf, on="k")
    assert_table_matches(g, ej.groupby("k", as_index=False)
                         .agg(a_sum=("a", "sum")))


def test_fused_ddof(env4, rng):
    ldf, rdf = _tables(env4, rng)
    j = _join(env4, ldf, rdf)
    g = groupby_aggregate(j, "k", [("a", "var"), ("a", "std")], ddof=0)
    assert not j.materialized
    ej = ldf.merge(rdf, on="k")
    eg = (ej.groupby("k", as_index=False)
          .agg(a_var=("a", lambda x: x.var(ddof=0)),
               a_std=("a", lambda x: x.std(ddof=0))))
    eg.columns = ["k", "a_var", "a_std"]
    assert_table_matches(g, eg)


def test_f64_columns_carry_lite(env4, rng):
    """Carry-LITE: f64 output columns no longer disqualify the join's lane
    carriage — the join defers, laneable columns ride the sort, f64
    columns gather by take index.  A pushdown over an f64 value column is
    gated (not in the sorted lanes) and falls back to materialization."""
    n = 5000
    ldf = pd.DataFrame({"k": rng.integers(0, 600, n).astype(np.int64),
                        "a": rng.integers(0, 50, n).astype(np.int64),
                        "x": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 600, n).astype(np.int64),
                        "b": rng.integers(0, 50, n).astype(np.int64),
                        "y": rng.random(n)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    j = join_tables(lt, rt, "k", "k", how="inner")
    assert isinstance(j, DeferredTable) and not j.materialized
    ej = ldf.merge(rdf, on="k")
    # pushdown over the laneable column only: stays deferred
    g1 = groupby_aggregate(j, "k", [("a", "sum")])
    assert not j.materialized
    e1 = ej.groupby("k", as_index=False).agg(a_sum=("a", "sum"))
    assert_table_matches(g1, e1)
    # f64 value column: gated out of the pushdown, materializes, correct
    g2 = groupby_aggregate(j, "k", [("x", "sum"), ("y", "mean")])
    assert j.materialized
    e2 = ej.groupby("k", as_index=False).agg(x_sum=("x", "sum"),
                                             y_mean=("y", "mean"))
    assert_table_matches(g2, e2)
    # full materialized join equals pandas (f64 columns via carry-lite)
    keycols = ["k", "a", "x", "b", "y"]
    got = j.to_pandas().sort_values(keycols).reset_index(drop=True)
    exp = ej.sort_values(keycols).reset_index(drop=True)
    pd.testing.assert_frame_equal(got[exp.columns], exp, check_dtype=False,
                                  rtol=1e-12)
