"""Skewed-key exchanges must stay memory-bounded (round-1 VERDICT red flag
3): the multi-round exchange caps the per-(src,dst) block near the uniform
stream size, so an all-to-one key distribution runs in R > 1 rounds with
W·block ≈ one shard of extra memory instead of W shards' worth.

Reference analog: partition sampling machinery, table.cpp:620-689."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.parallel import shuffle as shf
from cylon_tpu import config
from cylon_tpu.relational import groupby_aggregate, join_tables, unique_table

from utils import assert_table_matches


def test_block_cap_bounds_send_memory():
    # uniform: single round; skewed: bounded block, multiple rounds
    w = 8
    total = 1_000_000
    cap = shf.exchange_block_cap(total, w)
    assert cap <= config.pow2ceil(2 * total // (w * w))
    max_skewed = int(0.9 * total)
    rounds = -(-max_skewed // cap)
    assert rounds > 1
    # peak send buffer w*block is ~2x one shard, not w shards
    assert w * cap <= 4 * (total // w + cap)


def test_90pct_one_key_join_world8(env8, rng):
    n = 40_000
    keys_l = np.where(rng.random(n) < 0.9, 7, rng.integers(100, 2000, n))
    keys_r = np.where(rng.random(64) < 0.5, 7, rng.integers(100, 2000, 64))
    ldf = pd.DataFrame({"k": keys_l.astype(np.int64), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": keys_r.astype(np.int64), "b": rng.random(64)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    j = join_tables(lt, rt, "k", "k", how="inner")
    exp = ldf.merge(rdf, on="k", how="inner")
    assert j.row_count == len(exp)
    g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])
    eg = exp.groupby("k", as_index=False).agg(a_sum=("a", "sum"),
                                              b_sum=("b", "sum"))
    assert_table_matches(g, eg)


def test_multi_round_exchange_preserves_order(env8, rng):
    """Force R > 1 rounds on a small table by shrinking the block floor, and
    check the order-preserving (src rank, src pos) receive contract."""
    from cylon_tpu.parallel.shuffle import exchange, hash_targets, \
        count_targets
    import cylon_tpu.parallel.shuffle as sh

    n = 4096
    df = pd.DataFrame({"k": np.full(n, 3, np.int64),
                       "v": np.arange(n, dtype=np.int64)})
    t = ct.Table.from_pandas(df, env8)
    tgt = hash_targets(env8.mesh, (t.column("k").data,), (None,),
                       t.valid_counts)
    counts = count_targets(env8.mesh, tgt)
    assert int((counts > 0).sum(axis=1).max()) == 1  # all-to-one

    orig = sh.exchange_block_cap
    sh.exchange_block_cap = lambda total, w: 64   # tiny blocks -> many rounds
    try:
        new_cols, new_valid = exchange(env8.mesh, tgt,
                                       counts, (t.column("v").data,))
    finally:
        sh.exchange_block_cap = orig
    # single destination holds all rows, in (src rank, src pos) order
    d = int(np.argmax(counts.sum(axis=0)))
    cap = new_cols[0].shape[0] // env8.world_size
    vals = np.asarray(new_cols[0])[d * cap: d * cap + n]
    src_caps = t.capacity
    expected = np.concatenate(
        [np.arange(s * src_caps, s * src_caps + int(t.valid_counts[s]))
         for s in range(env8.world_size)]) % (1 << 62)
    # source values were v = global row index in ingest order
    exp_vals = df["v"].to_numpy()
    assert np.array_equal(np.sort(vals), np.sort(exp_vals))
    # order-preserving: strictly increasing within each source segment
    offs = np.cumsum([0] + [int(c) for c in t.valid_counts])
    for s in range(env8.world_size):
        seg = vals[offs[s]:offs[s + 1]]
        assert np.all(np.diff(seg) > 0)


def test_skewed_unique_world8(env8, rng):
    n = 20_000
    keys = np.where(rng.random(n) < 0.95, 1, rng.integers(2, 50, n))
    df = pd.DataFrame({"k": keys.astype(np.int64)})
    t = ct.Table.from_pandas(df, env8)
    u = unique_table(t)
    assert sorted(u.to_pandas()["k"].tolist()) == sorted(set(keys.tolist()))


def test_heavy_key_split_balances_shards(env8, rng):
    """90%-one-key probe side: the skew split must spread the heavy key
    round-robin (balanced shards, ~input-sized peak) and replicate the
    build side's heavy rows, with results identical to pandas."""
    from cylon_tpu.relational import join as rjoin

    n = 40_000
    keys_l = np.where(rng.random(n) < 0.9, 7, rng.integers(100, 2000, n))
    ldf = pd.DataFrame({"k": keys_l.astype(np.int64), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": np.arange(2000, dtype=np.int64),
                        "b": rng.random(2000)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)

    heavy = rjoin._heavy_keys(lt, ["k"], env8)
    assert heavy is not None and len(heavy) >= 1  # hash-space heavy set

    lsh, rsh, split = rjoin._shuffle_for_join(lt, rt, ["k"], ["k"],
                                              "inner", env8)
    assert split
    # probe side balanced: no shard holds more than ~2x the even share
    assert int(lsh.valid_counts.max()) <= 2 * (n // 8) + 1024
    # end-to-end correctness incl. left join (null side)
    for how in ("inner", "left"):
        j = join_tables(lt, rt, "k", "k", how=how)
        assert j.grouped_by is None  # split breaks co-location
        exp = ldf.merge(rdf, on="k", how=how)
        assert j.row_count == len(exp)
        g = groupby_aggregate(j, "k", [("a", "sum")])
        eg = exp.groupby("k", as_index=False).agg(a_sum=("a", "sum"))
        assert_table_matches(g, eg)


def test_heavy_key_split_multi_column(env8, rng):
    """Round-4: heavy-key detection runs on the row HASH of the key
    tuple, so multi-column keys split too (round-3 verdict weak #3)."""
    from cylon_tpu.relational import join as rjoin

    n = 40_000
    hot = rng.random(n) < 0.9
    ldf = pd.DataFrame({
        "k1": np.where(hot, 3, rng.integers(100, 900, n)).astype(np.int64),
        "k2": np.where(hot, 5, rng.integers(0, 9, n)).astype(np.int64),
        "a": rng.random(n)})
    rk = rng.integers(0, 900, 3000)
    rdf = pd.DataFrame({"k1": rk.astype(np.int64),
                        "k2": (rk % 9).astype(np.int64),
                        "b": rng.random(3000)})
    rdf.loc[0, ["k1", "k2"]] = [3, 5]  # ensure the hot tuple matches
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)

    heavy = rjoin._heavy_keys(lt, ["k1", "k2"], env8)
    assert heavy is not None and len(heavy) >= 1

    lsh, _, split = rjoin._shuffle_for_join(
        lt, rt, ["k1", "k2"], ["k1", "k2"], "inner", env8)
    assert split
    assert int(lsh.valid_counts.max()) <= 2 * (n // 8) + 1024
    j = join_tables(lt, rt, ["k1", "k2"], ["k1", "k2"])
    exp = ldf.merge(rdf, on=["k1", "k2"])
    assert j.row_count == len(exp)
    g = groupby_aggregate(j, ["k1", "k2"], [("a", "sum")])
    eg = exp.groupby(["k1", "k2"], as_index=False).agg(a_sum=("a", "sum"))
    assert_table_matches(g, eg)


def test_heavy_key_split_float_keys(env8, rng):
    """Round-4: float keys participate in the skew split (the detection
    hash canonicalizes floats exactly like the routing hash; round-3
    skipped float keys silently)."""
    from cylon_tpu.relational import join as rjoin

    n = 40_000
    keys_l = np.where(rng.random(n) < 0.9, 2.5,
                      rng.integers(100, 2000, n).astype(np.float64))
    ldf = pd.DataFrame({"k": keys_l, "a": rng.random(n)})
    rdf = pd.DataFrame({"k": np.arange(2000).astype(np.float64),
                        "b": rng.random(2000)})
    rdf.loc[0, "k"] = 2.5
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    heavy = rjoin._heavy_keys(lt, ["k"], env8)
    assert heavy is not None
    lsh, _, split = rjoin._shuffle_for_join(lt, rt, ["k"], ["k"],
                                            "inner", env8)
    assert split
    assert int(lsh.valid_counts.max()) <= 2 * (n // 8) + 1024
    j = join_tables(lt, rt, "k", "k")
    exp = ldf.merge(rdf, on="k")
    assert j.row_count == len(exp)


def test_sort_balance_under_skew(env8, rng):
    """Zipf-weighted keys (no single key above the 1/W share): splitter
    samples scale with the world (config.sort_samples) and the post-sort
    shard distribution must stay within 2x the even share (round-3
    verdict weak #4: no balance assertion existed)."""
    from cylon_tpu.relational import sort_table

    n = 64_000
    ranks = rng.zipf(1.3, n).astype(np.int64)  # heavy tail, capped below
    keys = np.minimum(ranks, 200)
    df = pd.DataFrame({"k": keys, "v": rng.random(n)})
    t = ct.Table.from_pandas(df, env8)
    out = sort_table(t, "k")
    got = out.to_pandas()
    assert got["k"].is_monotonic_increasing
    # max run of one key bounds achievable balance: assert against it
    top_run = int(pd.Series(keys).value_counts().iloc[0])
    even = n // 8
    assert int(out.valid_counts.max()) <= max(2 * even, top_run + even)


class TestAdaptiveSkewSplit:
    """ISSUE 14: the adaptive skew-split route (relational/skew.py) —
    heavy-hitter split + duplicate-broadcast behind a voted plan, with
    output BIT- and ORDER-equal to the unsplit hash plan for every join
    type, and the fused join→groupby pushdown combining the heavy keys'
    per-member partials (docs/skew.md)."""

    def _skewed_pair(self, env, rng, n=24_000, frac=0.6, build_hot=1):
        # build side big enough that the broadcast-join route (the right
        # plan for a SMALL build side) does not preempt the skew split
        mv = 2000
        hot = np.int64(700)
        lk = rng.integers(0, mv, n).astype(np.int64)
        lk = np.where(rng.random(n) < frac, hot, lk)
        nb = n // 2
        rk = rng.integers(0, mv, nb).astype(np.int64)
        rk[rk == hot] = hot + 1
        rk[:build_hot] = hot
        lt = ct.Table.from_pydict(
            {"k": lk, "a": rng.integers(0, 1000, n).astype(np.int64)}, env)
        rt = ct.Table.from_pydict(
            {"k": rk, "b": rng.integers(0, 1000, nb).astype(np.int64)},
            env)
        return lt, rt

    def _split_vs_unsplit(self, env, fn, monkeypatch):
        out_split = fn().to_pandas()
        monkeypatch.setattr(config, "SKEW_SPLIT", False)
        out_plain = fn().to_pandas()
        monkeypatch.setattr(config, "SKEW_SPLIT", True)
        # bit- AND order-equal: no sorting before the compare
        pd.testing.assert_frame_equal(out_split, out_plain)
        return out_split

    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_all_hows_bit_and_order_equal(self, env8, rng, monkeypatch,
                                          how):
        from cylon_tpu.relational import skew as skew_facade
        lt, rt = self._skewed_pair(env8, rng, build_hot=3)
        skew_facade.record_plan(None)
        if how == "right":
            # the probe side of a right join is the RIGHT table — put
            # the skewed column there
            fn = lambda: join_tables(rt, lt, "k", "k", how="right")
        else:
            fn = lambda: join_tables(lt, rt, "k", "k", how=how)
        out = self._split_vs_unsplit(env8, fn, monkeypatch)
        plan = skew_facade.last_plan()
        assert plan is not None and len(plan) >= 1, \
            f"{how}: the split route never armed"
        assert int(plan.fanout.max()) >= 2
        assert len(out) > 0

    def test_probe_side_balanced_and_plan_typed(self, env8, rng):
        from cylon_tpu.relational import join as rjoin
        from cylon_tpu.relational.skew import SkewPlan
        n = 24_000
        lt, rt = self._skewed_pair(env8, rng, n=n, frac=0.9)
        lsh, _rsh, split = rjoin._shuffle_for_join(
            lt, rt, ["k"], ["k"], "inner", env8)
        assert isinstance(split, SkewPlan)
        # heavy key spread: no shard holds more than ~2x the even share
        assert int(lsh.valid_counts.max()) <= 2 * (n // 8) + 1024

    def test_fused_groupby_combines_heavy_partials(self, env8, rng,
                                                   monkeypatch):
        """join→groupby-sum on the join keys rides the fused pushdown
        (no join materialization) and the heavy keys' per-member partial
        rows combine onto the home rank — result AND layout equal to the
        unsplit fused plan's."""
        from cylon_tpu import obs
        lt, rt = self._skewed_pair(env8, rng)

        def q():
            j = join_tables(lt, rt, "k", "k", how="inner")
            return groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])

        routes = {}

        def walk(node):
            routes[node["op"]] = node.get("attrs", {})
            for c in node.get("children", ()):
                walk(c)
        qp = obs.explain(q)
        for r in qp.static_dict()["roots"]:
            walk(r)
        assert routes["groupby"].get("route") == "fused_pushdown"
        assert routes["groupby"].get("skew_partials_combined", 0) >= 1
        join_attrs = routes["join"]
        assert join_attrs.get("route") == "skew_split"
        assert join_attrs["skew_plan"]["plan_hash"]
        self._split_vs_unsplit(env8, q, monkeypatch)

    def test_non_additive_aggs_skip_pushdown_and_stitch(self, env8, rng,
                                                        monkeypatch):
        """min/max cannot combine across the split members inside the
        fused kernel — the groupby takes the materialize path, but the
        PRE-stitch table feeds it (stitch elided: aggregation cannot
        observe row order), and the answer still matches the unsplit
        plan's."""
        from cylon_tpu.utils import timing
        lt, rt = self._skewed_pair(env8, rng)
        monkeypatch.setattr(config, "BENCH_TIMINGS", True)
        timing.reset()

        def q():
            j = join_tables(lt, rt, "k", "k", how="inner")
            return groupby_aggregate(j, "k", [("a", "min"), ("a", "max"),
                                              ("b", "sum")])

        got = q().to_pandas().sort_values("k").reset_index(drop=True)
        snap = timing.snapshot()
        assert "skew.stitch_elided" in snap, sorted(snap)
        monkeypatch.setattr(config, "SKEW_SPLIT", False)
        exp = q().to_pandas().sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp)

    def test_plan_vote_is_deterministic(self, env8, rng):
        """The recovery ladder's retry re-detects and re-votes: the
        canonical plan hash must be identical across runs over the same
        inputs (the chaos --skew same-plan contract)."""
        from cylon_tpu.relational import skew as skew_facade
        lt, rt = self._skewed_pair(env8, rng)
        join_tables(lt, rt, "k", "k", how="inner").to_pandas()
        h1 = skew_facade.last_plan().plan_hash()
        join_tables(lt, rt, "k", "k", how="inner").to_pandas()
        h2 = skew_facade.last_plan().plan_hash()
        assert h1 == h2

    def test_null_heavy_key_splits(self, env8, rng, monkeypatch):
        """A heavy NULL key participates in the split exactly like a
        value (the sampled tuple carries validity bits)."""
        n = 24_000
        lk = rng.integers(0, 2000, n).astype(np.float64)
        lk[rng.random(n) < 0.6] = np.nan
        rk = rng.integers(0, 2000, n // 2).astype(np.float64)
        rk[:2] = np.nan
        ldf = pd.DataFrame({"k": lk, "a": rng.random(n)})
        rdf = pd.DataFrame({"k": rk, "b": rng.random(n // 2)})
        lt = ct.Table.from_pandas(ldf, env8)
        rt = ct.Table.from_pandas(rdf, env8)
        from cylon_tpu.relational import skew as skew_facade
        skew_facade.record_plan(None)
        self._split_vs_unsplit(
            env8, lambda: join_tables(lt, rt, "k", "k", how="inner"),
            monkeypatch)
        assert skew_facade.last_plan() is not None

    def test_multicol_and_string_keys_split(self, env8, rng, monkeypatch):
        n = 24_000
        hot = rng.random(n) < 0.7
        ldf = pd.DataFrame({
            "k1": np.where(hot, 3, rng.integers(100, 900, n)
                           ).astype(np.int64),
            "k2": np.where(hot, "x", "y"),
            "a": rng.integers(0, 100, n).astype(np.int64)})
        rk = rng.integers(0, 900, n // 2)
        rdf = pd.DataFrame({"k1": rk.astype(np.int64),
                            "k2": np.where(rk % 2 == 0, "x", "y"),
                            "b": rng.integers(0, 100, n // 2)
                            .astype(np.int64)})
        rdf.loc[0, ["k1", "k2"]] = [3, "x"]
        lt = ct.Table.from_pandas(ldf, env8)
        rt = ct.Table.from_pandas(rdf, env8)
        from cylon_tpu.relational import skew as skew_facade
        skew_facade.record_plan(None)
        self._split_vs_unsplit(
            env8,
            lambda: join_tables(lt, rt, ["k1", "k2"], ["k1", "k2"],
                                how="inner"), monkeypatch)
        assert skew_facade.last_plan() is not None

    def test_wide_heavy_tuple_vs_narrow_build(self, env8, rng,
                                              monkeypatch):
        """A heavy probe key ABOVE int32 against a build side whose
        bounds fit int32: the build-side tuple comparisons must stay on
        the (hi, lo) operand pair — narrowing would truncate the wide
        tuple onto an unrelated narrow build key (phantom build rows in
        the plan, mis-routed duplicate-broadcast).  Regression for
        SkewPlan.operand_statics' per-tuple narrow guard."""
        from cylon_tpu.relational import skew as skew_facade
        n = 24_000
        wide = np.int64((1 << 32) + 5)
        lk = rng.integers(0, 1000, n).astype(np.int64)
        lk = np.where(rng.random(n) < 0.6, wide, lk)
        lt = ct.Table.from_pydict(
            {"k": lk, "a": rng.integers(0, 100, n).astype(np.int64)},
            env8)
        rt = ct.Table.from_pydict(
            {"k": rng.integers(0, 1000, n).astype(np.int64),
             "b": rng.integers(0, 100, n).astype(np.int64)}, env8)
        skew_facade.record_plan(None)
        self._split_vs_unsplit(
            env8, lambda: join_tables(lt, rt, "k", "k", how="left"),
            monkeypatch)
        plan = skew_facade.last_plan()
        assert plan is not None, "wide heavy key never armed the split"
        # the wide key truly has zero build rows — an aliased plan
        # would report the narrow victim key's count here
        assert int(plan.n_build[0]) == 0, plan.summary()

    def test_replication_guard_rejects_heavy_build(self, env8, rng,
                                                   monkeypatch):
        """A key heavy on BOTH sides must NOT split: duplicate-
        broadcasting a huge build group recreates the blow-up.  The
        finalize guard drops it and the join runs the plain hash plan,
        still correct."""
        from cylon_tpu.obs import metrics
        monkeypatch.setattr(config, "SKEW_GUARD_ROWS", 128)
        monkeypatch.setattr(config, "SKEW_GUARD_RATIO", 2.0)
        n = 8000
        lt, rt = self._skewed_pair(env8, rng, n=n, frac=0.7)
        # make the BUILD side heavy on the same key too
        rk = np.asarray(rt.to_pandas()["k"], np.int64)
        rk[: len(rk) // 2] = 700
        rt2 = ct.Table.from_pydict(
            {"k": rk,
             "b": rng.integers(0, 1000, len(rk)).astype(np.int64)}, env8)
        before = metrics.counter("skew_split_joins").value
        out = join_tables(lt, rt2, "k", "k", how="inner").to_pandas()
        assert metrics.counter("skew_split_joins").value == before
        ldf, rdf = lt.to_pandas(), rt2.to_pandas()
        assert len(out) == len(ldf.merge(rdf, on="k"))

    def test_unarmed_at_zero_skew_votes_nothing(self, env8, rng):
        """The zero-extra-collectives contract leg: a uniform key column
        with the route ARMED must not vote, split or touch the consensus
        wire (detection is one pure-local sample + one host pull)."""
        from cylon_tpu.exec import recovery
        from cylon_tpu.obs import metrics
        n = 24_000
        lt = ct.Table.from_pydict(
            {"k": rng.integers(0, n, n).astype(np.int64),
             "a": rng.integers(0, 100, n).astype(np.int64)}, env8)
        rt = ct.Table.from_pydict(
            {"k": rng.integers(0, n, n).astype(np.int64),
             "b": rng.integers(0, 100, n).astype(np.int64)}, env8)
        before = metrics.counter("skew_split_joins").value
        votes = []
        orig = recovery.skew_plan_consensus
        recovery.skew_plan_consensus = \
            lambda mesh, h: votes.append(h) or orig(mesh, h)
        try:
            join_tables(lt, rt, "k", "k", how="inner").to_pandas()
        finally:
            recovery.skew_plan_consensus = orig
        assert metrics.counter("skew_split_joins").value == before
        assert votes == []

    def test_escape_hatch_disables_route(self, env8, rng, monkeypatch):
        from cylon_tpu.obs import metrics
        monkeypatch.setattr(config, "SKEW_SPLIT", False)
        lt, rt = self._skewed_pair(env8, rng, n=8000)
        before = metrics.counter("skew_split_joins").value
        join_tables(lt, rt, "k", "k", how="inner").to_pandas()
        assert metrics.counter("skew_split_joins").value == before

    def test_stitched_layout_is_balanced(self, env8, rng):
        """The stitch lands on the even order-preserving layout: the
        materialized split join's shards are balanced even though the
        unsplit plan would have concentrated the hot key's output."""
        from cylon_tpu.relational.repart import even_partition_counts
        lt, rt = self._skewed_pair(env8, rng, frac=0.9)
        j = join_tables(lt, rt, "k", "k", how="inner")
        j.to_pandas()   # force the stitch
        total = int(j.valid_counts.sum())
        assert np.array_equal(np.asarray(j.valid_counts, np.int64),
                              even_partition_counts(total, 8))


class TestReceiveBudgetGuard:
    """Round-5: the exchange's count sidecar predicts the receive-side
    allocation; past the budget an OOM-shaped error fires BEFORE any
    device allocation so run_with_oom_fallback reroutes to the streaming
    pipeline (VERDICT r4 weak #3's second half)."""

    def test_predicted_blowup_raises_oom_shape(self, env8, rng,
                                               monkeypatch):
        from cylon_tpu import config
        from cylon_tpu.relational.common import is_oom
        from cylon_tpu.relational.repart import shuffle_table
        # tiny budget so a normal-sized skewed shuffle trips it (the
        # guard skips CPU meshes unless forced)
        monkeypatch.setattr(config, "EXCHANGE_RECV_BUDGET_BYTES", 4096)
        monkeypatch.setattr(config, "EXCHANGE_RECV_GUARD_CPU", True)
        n = 4000
        k = np.full(n, 7, np.int64)            # every row -> one shard
        t = ct.Table.from_pandas(
            pd.DataFrame({"k": k, "v": rng.random(n)}), env8)
        with pytest.raises(Exception) as ei:
            shuffle_table(t, ["k"])
        assert is_oom(ei.value)

    def test_skew_split_keeps_receive_under_budget(self, env8, rng,
                                                   monkeypatch):
        """The split (not the guard) is the recovery mechanism: with the
        heavy key spread round-robin, per-dest receives stay balanced and
        a budget that a plain hash shuffle would blow is never hit."""
        from cylon_tpu import config
        monkeypatch.setattr(config, "SKEW_MIN_SHARE", 0.01)
        # generous enough for balanced receives, far below the one-shard
        # concentration a plain hash of the heavy key would produce
        n = 6000
        lk = rng.integers(0, 500, n).astype(np.int64)
        lk[rng.random(n) < 0.9] = 3
        ldf = pd.DataFrame({"k": lk, "a": rng.random(n)})
        rdf = pd.DataFrame({"k": rng.integers(0, 500, 2500)
                            .astype(np.int64), "b": rng.random(2500)})
        lt = ct.Table.from_pandas(ldf, env8)
        rt = ct.Table.from_pandas(rdf, env8)
        # balanced receive ≈ n/8 rows x ~3 u32 lanes; one-shard ≈ 0.9n
        monkeypatch.setattr(config, "EXCHANGE_RECV_BUDGET_BYTES",
                            4 * (n // 8) * 40)
        monkeypatch.setattr(config, "EXCHANGE_RECV_GUARD_CPU", True)
        from cylon_tpu.relational import join_tables
        out = join_tables(lt, rt, "k", "k", how="inner").to_pandas()
        exp = ldf.merge(rdf, on="k")
        assert len(out) == len(exp)
        assert np.isclose(out["a"].sum(), exp["a"].sum())
