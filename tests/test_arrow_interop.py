"""Arrow-native ingest fidelity: from_arrow/to_arrow without pandas,
dtype-exact round trips (VERDICT item 5 / reference table.hpp:61-82)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import cylon_tpu as ct


@pytest.fixture(params=["env1", "env4"])
def env(request):
    return request.getfixturevalue(request.param)


def test_from_arrow_numeric_dtypes(env):
    at = pa.table({
        "i8": pa.array([1, 2, None, 4], type=pa.int8()),
        "i32": pa.array([10, None, 30, 40], type=pa.int32()),
        "i64": pa.array([1 << 40, 2, 3, None], type=pa.int64()),
        "f32": pa.array([1.5, None, 3.5, 4.5], type=pa.float32()),
        "f64": pa.array([0.1, 0.2, None, 0.4], type=pa.float64()),
        "b": pa.array([True, None, False, True]),
    })
    t = ct.Table.from_arrow(at, env)
    # physical dtypes preserved (no object/float64 round trip)
    assert str(t.column("i32").data.dtype) == "int32"
    assert str(t.column("i64").data.dtype) == "int64"
    assert str(t.column("f32").data.dtype) == "float32"
    back = t.to_arrow()
    for name in at.column_names:
        assert back.column(name).null_count == at.column(name).null_count
    # value round trip via pandas (allowing nullable representation diffs)
    pd.testing.assert_frame_equal(back.to_pandas(), at.to_pandas(),
                                  check_dtype=False)


def test_from_arrow_strings_and_dictionary(env):
    at = pa.table({
        "s": pa.array(["foo", None, "bar", "foo", "baz"]),
        "d": pa.array(["x", "y", "x", None, "z"]).dictionary_encode(),
    })
    t = ct.Table.from_arrow(at, env)
    got = t.to_pandas()

    def norm(col):
        return [None if pd.isna(v) else v for v in col]

    assert norm(got["s"]) == ["foo", None, "bar", "foo", "baz"]
    assert norm(got["d"]) == ["x", "y", "x", None, "z"]
    # sorted-dictionary invariant: codes order-isomorphic to strings
    c = t.column("s")
    assert list(c.dictionary) == sorted(c.dictionary)


def test_from_arrow_temporal(env):
    ts = pd.date_range("2021-03-01", periods=4)
    at = pa.table({
        "t": pa.array(ts),
        "date": pa.array([pd.Timestamp("2020-01-01").date()] * 4,
                         type=pa.date32()),
        "dur": pa.array([1_000_000_000, 2, None, 4], type=pa.duration("ns")),
    })
    t = ct.Table.from_arrow(at, env)
    got = t.to_pandas()
    assert (got["t"] == ts).all()
    assert got["date"].iloc[0] == pd.Timestamp("2020-01-01")


def test_from_arrow_bounds_enable_narrow_keys(env):
    at = pa.table({"k": pa.array(np.arange(100), type=pa.int64())})
    t = ct.Table.from_arrow(at, env)
    assert t.column("k").bounds == (0, 99)


def test_arrow_join_roundtrip(env, rng):
    n = 500
    ldf = pd.DataFrame({"k": rng.integers(0, 50, n), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 50, n), "b": rng.random(n)})
    lt = ct.Table.from_arrow(pa.Table.from_pandas(ldf), env)
    rt = ct.Table.from_arrow(pa.Table.from_pandas(rdf), env)
    from cylon_tpu.relational import join_tables
    j = join_tables(lt, rt, "k", "k")
    exp = ldf.merge(rdf, on="k")
    assert j.row_count == len(exp)
