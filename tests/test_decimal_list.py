"""Decimal (scaled-int64) and list-passthrough columns (round-4, VERDICT
item 7).  Reference: the C++ comparators span every Arrow type including
decimal128 and list payloads (arrow_comparator.cpp; join_test.cpp:124 joins
list<float32> columns locally).  Here decimal128(p<=18) is EXACT via
unscaled int64 (TPC-H money semantics) and variable-length lists ride
host-side as passthrough payloads (carried through joins by code gathers,
never usable as keys)."""

import decimal
from decimal import Decimal

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.status import CylonTypeError, InvalidError


def _dec(vals, scale=2):
    q = Decimal(1).scaleb(-scale)
    return np.asarray([Decimal(str(v)).quantize(q) for v in vals],
                      dtype=object)


class TestDecimal:
    def test_pandas_roundtrip_exact(self, env4):
        df = pd.DataFrame({"m": _dec([1.25, -3.10, 0.07, 99999.99]),
                           "k": np.arange(4, dtype=np.int64)})
        t = ct.Table.from_pandas(df, env4)
        back = t.to_pandas()
        assert list(back["m"]) == list(df["m"])  # exact Decimal equality

    def test_arrow_roundtrip(self, env4):
        import pyarrow as pa
        arr = pa.array([Decimal("12.34"), None, Decimal("-0.01")],
                       type=pa.decimal128(10, 2))
        at = pa.table({"m": arr, "k": pa.array([1, 2, 3])})
        t = ct.Table.from_arrow(at, env4)
        out = t.to_arrow()
        assert out.column("m").type == pa.decimal128(10, 2)
        assert out.column("m").to_pylist() == arr.to_pylist()

    def test_join_on_decimal_keys(self, env4, rng):
        lv = rng.integers(0, 40, 300) / 4          # .0 .25 .5 .75 grid
        rv = rng.integers(0, 40, 200) / 4
        ldf = pd.DataFrame({"m": _dec(lv), "a": rng.random(300)})
        rdf = pd.DataFrame({"m": _dec(rv), "b": rng.random(200)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        from cylon_tpu.relational import join_tables
        j = join_tables(lt, rt, "m", "m")
        exp = ldf.merge(rdf, on="m")
        assert j.row_count == len(exp)
        got = j.to_pandas()
        assert sorted(map(float, got["m"])) == sorted(map(float, exp["m"]))

    def test_join_mixed_scales_rescale(self, env4):
        # scale-1 vs scale-2 decimals: 2.5 must match 2.50
        ldf = pd.DataFrame({"m": _dec([2.5, 3.1, 4.0], scale=1),
                            "a": [1, 2, 3]})
        rdf = pd.DataFrame({"m": _dec([2.50, 4.00, 9.99], scale=2),
                            "b": [10, 20, 30]})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        from cylon_tpu.relational import join_tables
        j = join_tables(lt, rt, "m", "m").to_pandas()
        assert sorted(j["b"].tolist()) == [10, 20]

    def test_filter_decimal_literal(self, env4):
        df = pd.DataFrame({"m": _dec([0.05, 0.06, 0.07, 0.08]),
                           "v": [1, 2, 3, 4]})
        d = ct.DataFrame(df, env=env4)
        got = d[d["m"] >= Decimal("0.06")].to_pandas()
        assert got["v"].tolist() == [2, 3, 4]
        got2 = d[d["m"] == Decimal("0.07")].to_pandas()
        assert got2["v"].tolist() == [3]
        with pytest.raises(CylonTypeError):
            d["m"] >= Decimal("0.065")   # finer than the column scale
        with pytest.raises(CylonTypeError):
            d["m"] + 1                   # no decimal arithmetic

    def test_groupby_on_decimal_keys(self, env4, rng):
        df = pd.DataFrame({"m": _dec(rng.integers(0, 8, 500) / 4),
                           "v": rng.integers(0, 50, 500)})
        d = ct.DataFrame(df, env=env4)
        g = d.groupby("m").agg([("v", "sum")]).to_pandas()
        eg = (df.assign(m=df.m.map(float)).groupby("m", as_index=False)
              .agg(v_sum=("v", "sum")))
        got = sorted(zip(map(float, g["m"]), g["v_sum"]))
        exp = sorted(zip(eg["m"], eg["v_sum"]))
        assert got == exp

    def test_sort_by_decimal(self, env4):
        df = pd.DataFrame({"m": _dec([3.5, -1.25, 0.0, 2.75])})
        d = ct.DataFrame(df, env=env4)
        out = d.sort_values("m").to_pandas()
        assert list(map(float, out["m"])) == [-1.25, 0.0, 2.75, 3.5]


class TestListPassthrough:
    def _frames(self, rng, n=200):
        ldf = pd.DataFrame({"k": rng.integers(0, 30, n).astype(np.int64),
                            "payload": [[int(i), int(i) * 2]
                                        for i in range(n)]})
        rdf = pd.DataFrame({"k": np.arange(30, dtype=np.int64),
                            "b": rng.random(30)})
        return ldf, rdf

    def test_roundtrip(self, env4, rng):
        ldf, _ = self._frames(rng)
        t = ct.Table.from_pandas(ldf, env4)
        back = t.to_pandas()
        assert list(back["payload"]) == list(ldf["payload"])

    def test_survives_join_as_payload(self, env4, rng):
        ldf, rdf = self._frames(rng)
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        from cylon_tpu.relational import join_tables
        j = join_tables(lt, rt, "k", "k").to_pandas()
        exp = ldf.merge(rdf, on="k")
        assert len(j) == len(exp)
        # each row's payload must still be the payload ingested with its k
        payload_by_first = {p[0]: k for k, p in
                            zip(ldf["k"], ldf["payload"])}
        for k, p in zip(j["k"], j["payload"]):
            assert payload_by_first[p[0]] == k

    def test_survives_filter_and_concat(self, env4, rng):
        ldf, _ = self._frames(rng)
        d = ct.DataFrame(ldf, env=env4)
        f = d[d["k"] >= 15]
        exp = ldf[ldf.k >= 15]
        got = f.to_pandas()
        assert list(got["payload"]) == list(exp["payload"])
        from cylon_tpu.relational import concat_tables
        both = concat_tables([f._table, f._table]).to_pandas()
        assert len(both) == 2 * len(exp)

    def test_arrow_list_ingest(self, env4):
        import pyarrow as pa
        at = pa.table({"k": pa.array([1, 2, 3]),
                       "ls": pa.array([[1.0, 2.0], [], [3.0]],
                                      type=pa.list_(pa.float64()))})
        t = ct.Table.from_arrow(at, env4)
        back = t.to_pandas()
        assert list(back["ls"]) == [[1.0, 2.0], [], [3.0]]

    def test_list_keys_raise(self, env4, rng):
        ldf, _ = self._frames(rng)
        lt = ct.Table.from_pandas(ldf, env4)
        from cylon_tpu.relational import (groupby_aggregate, join_tables,
                                          set_operation, sort_table,
                                          unique_table)
        with pytest.raises(CylonTypeError):
            join_tables(lt, lt, "payload", "payload")
        with pytest.raises(InvalidError):
            groupby_aggregate(lt, "payload", [("k", "sum")])
        with pytest.raises(InvalidError):
            sort_table(lt, "payload")
        with pytest.raises(InvalidError):
            unique_table(lt)
        with pytest.raises(InvalidError):
            set_operation(lt, lt, "union")
        with pytest.raises(CylonTypeError):
            _ = ct.DataFrame(_table=lt)["payload"] == [1, 2]


class TestReviewRegressions:
    def test_decimal256_takes_float_fallback(self, env4):
        """decimal256 storage is 4 limbs — the int64 buffer view must NOT
        apply (it silently corrupted values); it falls back to float64."""
        import pyarrow as pa
        arr = pa.array([Decimal("1.5"), Decimal("2.5"), Decimal("3.5")],
                       type=pa.decimal256(10, 1))
        t = ct.Table.from_arrow(pa.table({"m": arr}), env4)
        from cylon_tpu.core.dtypes import LogicalType
        assert t.column("m").type == LogicalType.FLOAT64
        assert t.to_pandas()["m"].tolist() == [1.5, 2.5, 3.5]

    def test_rescale_grows_precision(self, env4):
        """Joining (5,0) with (5,3) rescales values by 10^3: the declared
        precision must grow or export crashes (ArrowInvalid)."""
        import pyarrow as pa
        a = pa.table({"m": pa.array([Decimal("99999")],
                                    type=pa.decimal128(5, 0)),
                      "x": pa.array([1])})
        b = pa.table({"m": pa.array([Decimal("99999.000")],
                                    type=pa.decimal128(8, 3)),
                      "y": pa.array([2])})
        ta, tb = ct.Table.from_arrow(a, env4), ct.Table.from_arrow(b, env4)
        from cylon_tpu.relational import join_tables
        j = join_tables(ta, tb, "m", "m")
        assert j.row_count == 1
        out = j.to_arrow()     # must not raise
        assert out.column("m").to_pylist()[0] == Decimal("99999.000")

    def test_leading_pd_na_decimal_ingest(self, env4):
        """A leading pd.NA must not defeat the decimal type probe."""
        df = pd.DataFrame({"m": pd.Series([pd.NA, Decimal("1.5"),
                                           Decimal("2.5")], dtype=object)})
        t = ct.Table.from_pandas(df, env4)
        from cylon_tpu.core.dtypes import LogicalType
        assert t.column("m").type == LogicalType.DECIMAL
        back = t.to_pandas()["m"]
        assert back[0] is None or pd.isna(back[0])
        assert list(back[1:]) == [Decimal("1.5"), Decimal("2.5")]

    def test_multi_loc_missing_after_concat_padding(self, env4):
        """Padding rows (unspecified contents post-concat) must not fake
        a presence hit in multi-index list-label loc."""
        from cylon_tpu.relational import concat_tables
        d1 = ct.DataFrame(pd.DataFrame({"a": [1, 2, 3], "b": [1, 1, 1],
                                        "v": [1., 2., 3.]}), env=env4)
        d2 = ct.DataFrame(pd.DataFrame({"a": [4, 5, 6], "b": [2, 2, 2],
                                        "v": [4., 5., 6.]}), env=env4)
        both = ct.DataFrame(_table=concat_tables([d1._table, d2._table]))
        m = both.set_index(["a", "b"])
        from cylon_tpu.status import CylonKeyError
        with pytest.raises(CylonKeyError):
            m.loc[[(0, 0)]]
