"""Table/Column construction + materialization roundtrips.

Reference analog: cpp/test/create_table_test.cpp, table_op_test.cpp.
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct


def make_df(rng, n=100):
    return pd.DataFrame({
        "i64": rng.integers(-1000, 1000, n),
        "i32": rng.integers(0, 100, n).astype(np.int32),
        "f64": rng.random(n),
        "f32": rng.random(n).astype(np.float32),
        "b": rng.integers(0, 2, n).astype(bool),
        "s": rng.choice(["apple", "banana", "cherry", "date"], n),
    })


@pytest.mark.parametrize("envname", ["env1", "env4", "env8"])
def test_roundtrip(request, rng, envname):
    env = request.getfixturevalue(envname)
    df = make_df(rng)
    t = ct.Table.from_pandas(df, env)
    assert t.row_count == len(df)
    assert t.column_names == list(df.columns)
    back = t.to_pandas()
    pd.testing.assert_frame_equal(back, df, check_dtype=False)


def test_roundtrip_with_nulls(env8):
    df = pd.DataFrame({
        "k": [1, 2, 3, 4, 5, 6, 7, 8, 9, 10],
        "s": ["a", None, "c", None, "e", "f", "g", None, "i", "j"],
    })
    t = ct.Table.from_pandas(df, env8)
    back = t.to_pandas()
    assert back["s"].tolist() == df["s"].tolist()


def test_datetime_roundtrip(env4):
    df = pd.DataFrame({
        "t": pd.to_datetime(["2024-01-01", "2024-06-15", "2025-12-31",
                             "2020-02-29"]),
        "d": pd.to_timedelta([1, 2, 3, 4], unit="d"),
    })
    t = ct.Table.from_pandas(df, env4)
    back = t.to_pandas()
    pd.testing.assert_frame_equal(back, df, check_dtype=False)


def test_project_drop_rename(env4, rng):
    df = make_df(rng, 40)
    t = ct.Table.from_pandas(df, env4)
    assert t.project(["i64", "s"]).column_names == ["i64", "s"]
    assert "i64" not in t.drop(["i64"]).column_names
    assert "x" in t.rename({"i64": "x"}).column_names


def test_uneven_rows(env8):
    # 10 rows over 8 shards: last shards hold fewer
    df = pd.DataFrame({"a": np.arange(10)})
    t = ct.Table.from_pandas(df, env8)
    assert t.row_count == 10
    pd.testing.assert_frame_equal(t.to_pandas(), df, check_dtype=False)


def test_empty_table(env4):
    df = pd.DataFrame({"a": np.array([], np.int64)})
    t = ct.Table.from_pandas(df, env4)
    assert t.row_count == 0
    assert len(t.to_pandas()) == 0


def test_from_pandas_extension_dtypes(env4):
    """pandas StringDtype / nullable Int64 / boolean nulls must ingest as
    real nulls, not stringified '<NA>' (regression: verify-drive finding)."""
    import pandas as pd
    df = pd.DataFrame({
        # "string" (StringDtype) keeps pd.NA; plain "str" is a numpy
        # str_ cast on pandas < 3 and stringifies None to "None" before
        # the frame ever reaches cylon_tpu
        "s": pd.array(["a", None, "b", None], dtype="string"),
        "i": pd.array([1, None, 3, 4], dtype="Int64"),
        "f": pd.array([1.5, 2.5, None, 4.0], dtype="Float64"),
        "b": pd.array([True, None, False, True], dtype="boolean"),
    })
    t = ct.Table.from_pandas(df, env4)
    rt = t.to_pandas()
    assert rt["s"].isna().tolist() == [False, True, False, True]
    assert "<NA>" not in rt["s"].astype(str).tolist()[0]
    assert rt["i"].isna().tolist() == [False, True, False, False]
    assert rt["i"].dropna().tolist() == [1, 3, 4]
    assert rt["f"].isna().tolist() == [False, False, True, False]
    assert rt["b"].isna().tolist() == [False, True, False, False]


def test_exact_capacity_all_live_ops(env8, rng):
    """Rows exactly at per-shard capacity (no padding anywhere): the
    all-live join specialization (no liveness operand, no live gather) and
    the grouped/sorted paths must behave identically to padded shapes
    (VERDICT r1 blind spot: capacity-boundary cases)."""
    import pandas as pd
    from cylon_tpu.relational import (groupby_aggregate, join_tables,
                                      sort_table)
    n = 8 * 256  # 256 rows/shard = a pow2 -> capacity == rows, all live
    ldf = pd.DataFrame({"k": rng.integers(0, 100, n),
                        "a": rng.integers(0, 50, n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 100, n),
                        "b": rng.integers(0, 50, n)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    assert int(lt.valid_counts.sum()) == n
    j = join_tables(lt, rt, "k", "k")
    exp = ldf.merge(rdf, on="k")
    assert j.row_count == len(exp)
    g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])
    ge = (exp.groupby("k", as_index=False)
          .agg(a_sum=("a", "sum"), b_sum=("b", "sum")))
    s = sort_table(g, "k").to_pandas().reset_index(drop=True)
    pd.testing.assert_frame_equal(
        s, ge.sort_values("k").reset_index(drop=True), check_dtype=False)


def test_nested_and_decimal_columns_ingest(env1):
    """Round-4 (VERDICT r03 item 7): decimals ingest as exact scaled-int64,
    lists as host passthrough columns (tests/test_decimal_list.py covers
    the op surface); struct values still raise a clear error, never a
    silent stringify."""
    import decimal
    from cylon_tpu.core.dtypes import LogicalType
    from cylon_tpu.status import CylonTypeError
    t = ct.Table.from_pandas(pd.DataFrame({"x": pd.Series([[1, 2], [3]])}),
                             env1)
    assert t.column("x").type == LogicalType.LIST
    t = ct.Table.from_pandas(
        pd.DataFrame({"x": [decimal.Decimal("1.5")]}), env1)
    assert t.column("x").type == LogicalType.DECIMAL
    with pytest.raises(CylonTypeError, match="struct"):
        ct.Table.from_pandas(pd.DataFrame({"x": [{"a": 1}, {"a": 2}]}),
                             env1)
    # bytes stay supported: utf-8 decode into the string layout
    t = ct.Table.from_pandas(pd.DataFrame({"x": [b"ab", b"cd"]}), env1)
    assert t.to_pandas()["x"].tolist() == ["ab", "cd"]


def test_nested_value_rejected_anywhere_in_column(env1):
    """Mixed str+list columns must still raise (the type probe sees a str
    prefix, so the per-value guard must cover EVERY value)."""
    from cylon_tpu.status import CylonTypeError
    vals = ["s"] * 500 + [[1, 2]] + ["t"] * 10
    with pytest.raises(CylonTypeError, match="struct|nested"):
        ct.Table.from_pandas(pd.DataFrame({"x": pd.Series(vals)}), env1)
