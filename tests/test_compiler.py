"""Compile-lifecycle facade tests (cylon_tpu.exec.compiler, round 19).

Fast tests (tier-1): shape-family canonicalization is bit- AND
order-equal to exact-shape placement for every join how, the fused
join→groupby pushdown and the set ops at the pow2 boundary ±1; the
compiled-program population stays FLAT as same-family tenant shapes
multiply 4× (and grows without families — the escape hatch's contrast);
the bounded compile ledger evicts LRU past ``CYLON_TPU_COMPILE_BUDGET``
with consensus-wire builders pinned; orphaned compile intents are
adopted into the quarantine and surface as typed
``CompileQuarantinedError`` (a capacity fault — the recovery ladder's
re-plan rung); injected stalls surface as typed ``CompileTimeoutError``
via the compile watchdog; a poisoned persistent-manifest entry fails
its content hash at arm time and drops to a clean recompile; and the
unarmed happy path never enters the guarded lifecycle.
"""

import json
from collections import OrderedDict

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.exec import compiler, recovery
from cylon_tpu.frame import DataFrame
from cylon_tpu.relational import join_tables, set_operation
from cylon_tpu.status import (CapacityOverflowError, CompileQuarantinedError,
                              CompileTimeoutError)

HOWS = ["inner", "left", "right", "outer"]
#: the pow2 family boundary the canonicalization tests straddle
B = 256


@pytest.fixture(autouse=True)
def _clean_facade():
    """Leave the facade exactly as tier-1 found it: injector disarmed,
    counters zeroed, persistent-dir state dropped, armed-state cache
    invalidated (recomputed lazily AFTER monkeypatch restores config)."""
    yield
    recovery.install_faults("")
    compiler.reset_stats()
    with compiler._lock:
        compiler._DIR_STATE.update(path=None, quarantine=set(),
                                   manifest={}, adopted=[])
    compiler.rearm()


# ---------------------------------------------------------------------------
# shape families: the canonicalization decision
# ---------------------------------------------------------------------------

class TestFamilyCap:
    def test_pow2_bucketing(self):
        assert compiler.family_cap(0) == 0
        assert compiler.family_cap(1) == config.pow2ceil(1)
        for n in (B - 1, B + 1, 3 * B // 2):
            assert compiler.family_cap(n) == config.pow2ceil(n)
        # an exact family representative maps to itself: zero-copy ingest
        assert compiler.family_cap(B) == B

    def test_escape_hatch(self, monkeypatch):
        monkeypatch.setattr(config, "SHAPE_FAMILIES", False)
        for n in (0, B - 1, B, B + 1):
            assert compiler.family_cap(n) == n

    def test_pure_function_of_row_count(self):
        # rank-uniform by construction: no env, mesh or clock input —
        # repeated calls agree (the no-vote justification)
        assert [compiler.family_cap(n) for n in (7, 300, 4097)] \
            == [compiler.family_cap(n) for n in (7, 300, 4097)]


# ---------------------------------------------------------------------------
# canonicalized vs exact-shape: bit- and order-equality at the boundary
# ---------------------------------------------------------------------------

def _join_dfs(rng, n):
    ldf = pd.DataFrame({"k": rng.integers(0, 40, n).astype(np.int32),
                        "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 40, 53).astype(np.int32),
                        "b": rng.random(53)})
    return ldf, rdf


def _bit_equal(fam: pd.DataFrame, exact: pd.DataFrame):
    # ORDER matters: no sort before comparison — the contract is that
    # padding rides the validity lanes, so row order is identical too
    pd.testing.assert_frame_equal(fam.reset_index(drop=True),
                                  exact.reset_index(drop=True))


class TestCanonicalizationBitEquality:
    @pytest.mark.parametrize("n", [B - 1, B, B + 1])
    @pytest.mark.parametrize("how", HOWS)
    def test_join_hows(self, env1, rng, monkeypatch, n, how):
        ldf, rdf = _join_dfs(rng, n)

        def run():
            lt = ct.Table.from_pandas(ldf, env1)
            rt = ct.Table.from_pandas(rdf, env1)
            return join_tables(lt, rt, "k", "k", how=how).to_pandas()

        fam = run()
        monkeypatch.setattr(config, "SHAPE_FAMILIES", False)
        _bit_equal(fam, run())

    @pytest.mark.parametrize("n", [B - 1, B + 1])
    def test_fused_join_groupby(self, env1, rng, monkeypatch, n):
        ldf = pd.DataFrame({"k": rng.integers(0, 20, n).astype(np.int32),
                            "v": rng.random(n)})
        rdf = pd.DataFrame({"k": np.arange(20, dtype=np.int32),
                            "b": rng.random(20)})

        def run():
            l = DataFrame(ldf, env=env1)
            r = DataFrame(rdf, env=env1)
            j = l.merge(r, on="k", how="inner")   # defers into pushdown
            return j.groupby("k").agg({"v": "sum", "b": "max"}).to_pandas()

        fam = run()
        monkeypatch.setattr(config, "SHAPE_FAMILIES", False)
        _bit_equal(fam, run())

    @pytest.mark.parametrize("n", [B - 1, B + 1])
    @pytest.mark.parametrize("op", ["union", "intersect", "subtract"])
    def test_set_ops(self, env1, rng, monkeypatch, n, op):
        adf = pd.DataFrame({"k": rng.integers(0, 30, n).astype(np.int32),
                            "g": rng.integers(0, 4, n).astype(np.int32)})
        bdf = pd.DataFrame({"k": rng.integers(0, 30, 57).astype(np.int32),
                            "g": rng.integers(0, 4, 57).astype(np.int32)})

        def run():
            ta = ct.Table.from_pandas(adf, env1)
            tb = ct.Table.from_pandas(bdf, env1)
            return set_operation(ta, tb, op).to_pandas()

        fam = run()
        monkeypatch.setattr(config, "SHAPE_FAMILIES", False)
        _bit_equal(fam, run())

    def test_decision_recorded_on_plan(self, env1, rng):
        # the canonicalization decision is auditable: EXPLAIN output
        # carries the family bucket AND the true ingest row count
        from cylon_tpu.obs import plan as obs_plan
        n = B + 1
        df = pd.DataFrame({"k": rng.integers(0, 9, n).astype(np.int32)})

        def ingest():
            with obs_plan.node("ingest"):
                return ct.Table.from_pandas(df, env1)

        qp = obs_plan.explain(ingest)
        attrs = {}
        for root in qp.roots:
            stack = [root]
            while stack:
                node = stack.pop()
                attrs.update(node.attrs)
                stack.extend(getattr(node, "children", ()))
        assert attrs.get("shape_family") == compiler.family_cap(n)
        assert attrs.get("ingest_rows") == n


# ---------------------------------------------------------------------------
# flat compiled-program population across same-family tenants
# ---------------------------------------------------------------------------

class TestFlatProgramCount:
    def test_four_x_tenants_one_program_family(self, env1, rng):
        # four tenants whose plans differ only by near-miss row counts —
        # all bucket onto the 1024 family, so tenants 2..4 add ZERO
        # compiled programs (the multi-tenant compile-cost contract).
        # Unique right keys keep the data-dependent OUTPUT capacity in
        # one bucket too (output rows == left rows <= 1020 -> 1024).
        sizes = [530, 700, 860, 1020]
        misses_after_first = None
        for i, n in enumerate(sizes):
            ldf = pd.DataFrame(
                {"k": rng.integers(0, 40, n).astype(np.int32),
                 "a": rng.random(n)})
            rdf = pd.DataFrame({"k": np.arange(40, dtype=np.int32),
                                "b": rng.random(40)})
            lt = ct.Table.from_pandas(ldf, env1)
            rt = ct.Table.from_pandas(rdf, env1)
            join_tables(lt, rt, "k", "k", how="inner")
            if i == 0:
                misses_after_first = compiler.stats()["cache_misses"]
        assert compiler.stats()["cache_misses"] == misses_after_first

    def test_escape_hatch_recompiles_per_shape(self, env1, rng,
                                               monkeypatch):
        monkeypatch.setattr(config, "SHAPE_FAMILIES", False)
        misses_after_first = None
        for i, n in enumerate([531, 701, 861]):
            ldf, rdf = _join_dfs(rng, n)
            lt = ct.Table.from_pandas(ldf, env1)
            rt = ct.Table.from_pandas(rdf, env1)
            join_tables(lt, rt, "k", "k", how="inner")
            if i == 0:
                misses_after_first = compiler.stats()["cache_misses"]
        # exact-shape placement: every distinct row count is a new
        # program family — the cost the canonicalization removes
        assert compiler.stats()["cache_misses"] > misses_after_first


# ---------------------------------------------------------------------------
# the bounded compile ledger
# ---------------------------------------------------------------------------

class TestCompileLedger:
    def test_budget_evicts_lru(self, monkeypatch):
        monkeypatch.setattr(config, "COMPILE_BUDGET", 4)
        mesh = type("M", (), {})()
        lru: OrderedDict = OrderedDict()
        base = compiler.stats()["cache_evictions"]
        for i in range(7):
            lru[("k", i)] = object()
            compiler.on_insert(mesh, "tests.fake.builder", ("k", i), lru)
        assert list(lru) == [("k", i) for i in range(3, 7)]
        assert compiler.stats()["cache_evictions"] - base == 3

    def test_consensus_wire_builders_pinned(self, monkeypatch):
        monkeypatch.setattr(config, "COMPILE_BUDGET", 2)
        mesh = type("M", (), {})()
        wire: OrderedDict = OrderedDict()
        user: OrderedDict = OrderedDict()
        wire[("w",)] = object()
        compiler.on_insert(mesh, "cylon_tpu.exec.recovery._consensus_fn",
                           ("w",), wire)
        for i in range(4):
            user[i] = object()
            compiler.on_insert(mesh, "tests.user.builder", i, user)
        # the wire survives every budget pass; the user LRU pays
        assert ("w",) in wire
        assert len(user) <= 2

    def test_hit_refreshes_recency(self, monkeypatch):
        monkeypatch.setattr(config, "COMPILE_BUDGET", 2)
        mesh = type("M", (), {})()
        lru: OrderedDict = OrderedDict()
        for i in range(2):
            lru[i] = object()
            compiler.on_insert(mesh, "tests.recency.builder", i, lru)
        compiler.on_hit(mesh, "tests.recency.builder", 0)   # 0 is MRU now
        lru[2] = object()
        compiler.on_insert(mesh, "tests.recency.builder", 2, lru)
        assert 0 in lru and 1 not in lru

    def test_mesh_table_evict_counted(self):
        before = compiler.stats()
        compiler.on_table_evict(0xDEAD, 5)
        after = compiler.stats()
        assert after["mesh_table_evictions"] \
            == before["mesh_table_evictions"] + 1
        assert after["cache_evictions"] == before["cache_evictions"] + 5

    def test_live_gauge_tracks_ledger(self):
        mesh = type("M", (), {})()
        lru: OrderedDict = OrderedDict()
        base = compiler.live_programs()
        lru["x"] = object()
        compiler.on_insert(mesh, "tests.gauge.builder", "x", lru)
        assert compiler.live_programs() == base + 1
        del lru["x"]   # program retired → gauge prunes the dead entry
        assert compiler.live_programs() == base


# ---------------------------------------------------------------------------
# crash quarantine: orphaned compile intents
# ---------------------------------------------------------------------------

class TestQuarantine:
    def test_orphan_intent_adopts_and_raises_typed(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setattr(config, "COMPILE_CACHE_DIR", str(tmp_path))
        compiler.rearm()
        prog = compiler.jit(lambda x: x + 1)
        x = np.zeros((4,), np.int32)
        sig = compiler._sig_hash(prog._facade_label, (x,), {})
        # the predecessor died mid-compile: its intent journal survives
        (tmp_path / "intent.rank0.json").write_text(json.dumps(
            {"builder": prog._facade_label, "sig": sig, "pid": 12345}))
        compiler.rearm()
        with pytest.raises(CompileQuarantinedError) as ei:
            prog(x)
        assert ei.value.signature == sig
        assert sig in compiler.quarantined_signatures()
        assert compiler.stats()["quarantine_adoptions"] == 1
        # adoption consumed the orphan and persisted the quarantine
        assert not (tmp_path / "intent.rank0.json").exists()
        q = json.loads((tmp_path / "quarantine.json").read_text())
        assert sig in q["signatures"]
        # the recovery ladder's re-plan rung: a DIFFERENT shape (what
        # the pad/cap-halving rungs produce) compiles fine
        y = np.zeros((8,), np.int32)
        np.testing.assert_array_equal(np.asarray(prog(y)), y + 1)

    def test_quarantined_error_is_a_capacity_fault(self):
        # the ladder contract: CapacityOverflowError's rung re-plans at
        # a halved cap — a different shape — instead of re-crashing
        e = CompileQuarantinedError("x", site="compile.build",
                                    signature="ab")
        assert isinstance(e, CapacityOverflowError)
        assert e.signature == "ab"

    def test_happy_path_clears_intent(self, tmp_path, monkeypatch):
        monkeypatch.setattr(config, "COMPILE_CACHE_DIR", str(tmp_path))
        compiler.rearm()
        prog = compiler.jit(lambda x: x * 3)
        x = np.arange(4, dtype=np.int32)
        np.testing.assert_array_equal(np.asarray(prog(x)), x * 3)
        # the guarded compile journaled its intent and cleared it
        assert not (tmp_path / "intent.rank0.json").exists()
        man = json.loads((tmp_path / "manifest.json").read_text())
        assert len(man) == 1
        sig, ent = next(iter(man.items()))
        assert ent["sha"] == compiler._entry_sha(sig, ent["builder"])
        assert compiler.expected_warm() == 1


# ---------------------------------------------------------------------------
# compile watchdog
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_stall_surfaces_typed(self, monkeypatch):
        monkeypatch.setattr(config, "COMPILE_TIMEOUT_S", 0.2)
        recovery.install_faults("compile.build=stall")
        compiler.rearm()
        prog = compiler.jit(lambda x: x * 2)
        with pytest.raises(CompileTimeoutError) as ei:
            prog(np.arange(3, dtype=np.int32))
        assert ei.value.site == "compile.build"
        assert compiler.stats()["watchdog_timeouts"] == 1
        # the one-shot spec is consumed: the same compile now finishes
        # under a generous budget
        monkeypatch.setattr(config, "COMPILE_TIMEOUT_S", 60.0)
        compiler.rearm()
        x = np.arange(3, dtype=np.int32)
        np.testing.assert_array_equal(np.asarray(prog(x)), x * 2)

    @pytest.mark.slow
    def test_stall_without_budget_still_types(self):
        # no configured timeout: the injected stall defaults to a 2 s
        # budget so chaos runs always surface typed, never hang
        recovery.install_faults("compile.build=stall")
        compiler.rearm()
        prog = compiler.jit(lambda x: x + 7)
        with pytest.raises(CompileTimeoutError):
            prog(np.arange(5, dtype=np.int32))


# ---------------------------------------------------------------------------
# persistent manifest: poisoned entries drop to a clean miss
# ---------------------------------------------------------------------------

class TestCorruptManifest:
    def test_poisoned_entry_drops_and_recompiles_bit_equal(
            self, tmp_path, monkeypatch):
        monkeypatch.setattr(config, "COMPILE_CACHE_DIR", str(tmp_path))
        recovery.install_faults("compile.build=corrupt")
        compiler.rearm()
        prog = compiler.jit(lambda x: x - 1)
        x = np.arange(5, dtype=np.int32)
        out = np.asarray(prog(x))
        np.testing.assert_array_equal(out, x - 1)
        man = json.loads((tmp_path / "manifest.json").read_text())
        assert next(iter(man.values()))["sha"] == "0" * 16
        # relaunch: arm-time hash validation drops the poisoned entry —
        # a clean miss, never wrong code
        recovery.install_faults("")
        compiler.reset_stats()
        with compiler._lock:
            compiler._DIR_STATE.update(path=None, quarantine=set(),
                                       manifest={}, adopted=[])
        compiler.rearm()
        assert compiler.expected_warm() == 0
        assert compiler.stats()["manifest_drops"] == 1
        # the recompile is bit-equal and re-manifests with a VALID hash
        np.testing.assert_array_equal(np.asarray(prog(x)), out)
        man = json.loads((tmp_path / "manifest.json").read_text())
        sig, ent = next(iter(man.items()))
        assert ent["sha"] == compiler._entry_sha(sig, ent["builder"])


# ---------------------------------------------------------------------------
# the unarmed overhead contract
# ---------------------------------------------------------------------------

class TestUnarmed:
    def test_unarmed_never_enters_lifecycle(self, monkeypatch):
        compiler.rearm()
        assert compiler.cache_dir() == ""
        assert not compiler.armed()

        def boom(*a, **k):
            raise AssertionError("guarded lifecycle entered while unarmed")

        monkeypatch.setattr(compiler, "_lifecycle", boom)
        monkeypatch.setattr(compiler, "_ensure_dir", boom)
        prog = compiler.jit(lambda x: x + 3)
        x = np.arange(4, dtype=np.int32)
        np.testing.assert_array_equal(np.asarray(prog(x)), x + 3)

    def test_pinned_bypasses_even_armed(self, monkeypatch):
        monkeypatch.setattr(config, "COMPILE_TIMEOUT_S", 0.2)
        recovery.install_faults("compile.build=stall")
        compiler.rearm()
        assert compiler.armed()
        prog = compiler.jit(lambda x: x + 9, pinned=True)
        x = np.arange(4, dtype=np.int32)
        # the consensus wire never rides the guarded path: the armed
        # stall spec must not fire through a pinned program
        np.testing.assert_array_equal(np.asarray(prog(x)), x + 9)
