"""2-process jax.distributed SPMD driver (launched by test_multihost.py).

The reference's launch model is `mpirun -np N python app.py` (README.md:
69-73); the TPU-native analog is N processes each calling
``jax.distributed.initialize`` and running the SAME script over the global
mesh.  Each process here: builds an env with ``TPUConfig(distributed=True)``
(4 local CPU devices -> 8-device world), ingests the same host data (each
process materializes only its addressable shards), runs shuffle-backed
join + groupby + sort, validates against pandas, and exercises the real
cross-process barrier.

Usage: multihost_driver.py <process_id> <num_processes> <coordinator>
"""

import os
import sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
coord = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import numpy as np
import pandas as pd

import jax

# run from any cwd / without the package installed
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import cylon_tpu as ct
from cylon_tpu.ctx.context import TPUConfig
from cylon_tpu.relational import groupby_aggregate, join_tables, sort_table

env = ct.CylonEnv(config=TPUConfig(
    distributed=True, coordinator_address=coord,
    process_id=pid, num_processes=nproc))
assert jax.process_count() == nproc, jax.process_count()
assert env.world_size == 4 * nproc, env.world_size
assert env.rank == pid

rng = np.random.default_rng(11)  # same seed in every process: SPMD ingest
n = 5000
ldf = pd.DataFrame({"k": rng.integers(0, 500, n), "a": rng.random(n)})
rdf = pd.DataFrame({"k": rng.integers(0, 500, n), "b": rng.random(n)})
lt = ct.Table.from_pandas(ldf, env)
rt = ct.Table.from_pandas(rdf, env)

env.barrier()

# ---------------------------------------------------------------------------
# Scenario mode: kill-rank-0-and-resume (docs/robustness.md "Durable
# checkpoints & resume").  First launch: the `kill` fault kind SIGKILLs
# rank 0 mid-range-loop (during a piece's ckpt.write, BEFORE its commit
# vote) — rank 1's commit consensus converts the orphaned collective into
# a typed RankDesyncError via the watchdog.  Second launch
# (CYLON_TPU_RESUME=1): both ranks fast-forward past the pieces whose
# two-phase CkptCommit vote completed, recompute the rest, and must end
# bit-equal with the IDENTICAL manifest epoch on every rank.
# ---------------------------------------------------------------------------
# ---------------------------------------------------------------------------
# Scenario mode: elastic_resume (docs/robustness.md "Elastic resume &
# preemption grace").  First launch (2 processes, world=8): a TWO-stage
# pipelined workload — sinkless join feeding a join+sink — checkpoints
# per piece; the `kill` fault SIGKILLs rank 0 at stage 2's first
# checkpoint write, leaving stage 1 COMPLETE on disk across both rank
# dirs.  Second launch (1 process, world=4 — a topology change): the
# resume must detect the world mismatch, merge both old rank dirs'
# shard blocks, re-shard stage 1 onto the 4-device mesh
# (resume_resharded_pieces > 0, ffwd > 0), recompute stage 2, and end
# equal to the pandas oracle.
# ---------------------------------------------------------------------------
if os.environ.get("CYLON_TPU_MH_SCENARIO") == "elastic_resume":
    import hashlib

    from cylon_tpu.exec import GroupBySink, checkpoint, pipelined_join, \
        recovery

    resuming = os.environ.get("CYLON_TPU_RESUME") == "1"
    if not resuming:
        # stage 1 owns writes 1..3 (n_chunks=3); write 4 is stage 2's
        # first piece — killing there leaves stage 1 complete
        recovery.install_faults("ckpt.write:0:4=kill")
    erng = np.random.default_rng(17)   # same seed per process: SPMD ingest
    n_ord, n_li, n_cust = 600, 2400, 16
    orders = ct.Table.from_pydict(
        {"o_orderkey": np.arange(n_ord, dtype=np.int64),
         "o_custkey": erng.integers(0, n_cust, n_ord).astype(np.int64)}, env)
    lineitem = ct.Table.from_pydict(
        {"l_orderkey": erng.integers(0, n_ord, n_li).astype(np.int64),
         "l_quantity": erng.integers(1, 51, n_li).astype(np.int64)}, env)
    customers = ct.Table.from_pydict(
        {"c_custkey": np.arange(n_cust, dtype=np.int64),
         "c_nationkey": erng.integers(0, 5, n_cust).astype(np.int64)}, env)
    jt = pipelined_join(lineitem, orders, "l_orderkey", "o_orderkey",
                        how="inner", n_chunks=3)
    esink = GroupBySink("o_custkey", [("l_quantity", "sum")])
    pipelined_join(jt, customers, "o_custkey", "c_custkey", how="inner",
                   n_chunks=3, sink=esink)
    got = (esink.finalize().to_pandas().sort_values("o_custkey")
           .reset_index(drop=True))
    # pandas oracle (world-invariant: integer sums, unique group keys)
    odf = pd.DataFrame({"o_orderkey": np.arange(n_ord, dtype=np.int64)})
    erng2 = np.random.default_rng(17)
    odf["o_custkey"] = erng2.integers(0, n_cust, n_ord).astype(np.int64)
    ldf2 = pd.DataFrame(
        {"l_orderkey": erng2.integers(0, n_ord, n_li).astype(np.int64),
         "l_quantity": erng2.integers(1, 51, n_li).astype(np.int64)})
    exp = (ldf2.merge(odf, left_on="l_orderkey", right_on="o_orderkey")
           .groupby("o_custkey", as_index=False)
           .agg(l_quantity_sum=("l_quantity", "sum"))
           .sort_values("o_custkey").reset_index(drop=True))
    pd.testing.assert_frame_equal(got[["o_custkey", "l_quantity_sum"]], exp,
                                  check_dtype=False)
    st = checkpoint.stats()
    if resuming:
        assert st["resume_fast_forwarded_pieces"] > 0, st
        assert st["resume_resharded_pieces"] > 0, st
        assert st["resume_world_mismatch"] > 0, st
    sha = hashlib.sha256(got.to_csv(index=False).encode()).hexdigest()
    print(f"ELASTIC_OK pid={pid} world={env.world_size} "
          f"ffwd={st['resume_fast_forwarded_pieces']} "
          f"resharded={st['resume_resharded_pieces']} "
          f"mismatch={st['resume_world_mismatch']} sha={sha[:16]}",
          flush=True)
    sys.exit(0)

if os.environ.get("CYLON_TPU_MH_SCENARIO") == "kill_resume":
    import glob
    import hashlib
    import json
    import zlib

    from jax.experimental import multihost_utils

    from cylon_tpu.exec import checkpoint, pipelined_join, recovery

    resuming = os.environ.get("CYLON_TPU_RESUME") == "1"
    if not resuming:
        recovery.install_faults("ckpt.write:0:2=kill")
    jt = pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=4)
    got = (jt.to_pandas().sort_values(["k", "a", "b"])
           .reset_index(drop=True))
    sha = hashlib.sha256(got.to_csv(index=False).encode()).hexdigest()
    mans = sorted(glob.glob(os.path.join(
        checkpoint.ckpt_dir(), f"rank{pid}", "stage*", "MANIFEST.json")))
    assert mans, "no committed manifest on this rank"
    with open(mans[0], encoding="utf-8") as f:
        epoch = int(json.load(f)["epoch"])
    # every rank must have committed the IDENTICAL epoch and result
    wire = np.asarray([epoch, zlib.crc32(sha.encode())], np.int64)
    gathered = np.asarray(multihost_utils.process_allgather(wire))
    gathered = gathered.reshape(nproc, 2)
    assert len({int(r[0]) for r in gathered}) == 1, gathered
    assert len({int(r[1]) for r in gathered}) == 1, gathered
    ffwd = checkpoint.stats()["resume_fast_forwarded_pieces"]
    if resuming:
        assert ffwd > 0, "resume recomputed every committed piece"
    print(f"KILLRESUME_OK pid={pid} epoch={epoch} ffwd={ffwd}", flush=True)
    sys.exit(0)

j = join_tables(lt, rt, "k", "k", how="inner")
g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "mean")])
s = sort_table(g, "k")

exp = (ldf.merge(rdf, on="k", how="inner")
       .groupby("k", as_index=False)
       .agg(a_sum=("a", "sum"), b_mean=("b", "mean"))
       .sort_values("k").reset_index(drop=True))
got = s.to_pandas().reset_index(drop=True)
pd.testing.assert_frame_equal(got, exp, check_dtype=False, check_exact=False)

# round-5 surface: SEMI/ANTI joins across processes
semi = join_tables(lt, rt, "k", "k", how="semi")
anti = join_tables(lt, rt, "k", "k", how="anti")
m = ldf["k"].isin(set(rdf["k"]))
assert semi.row_count == int(m.sum()), (semi.row_count, int(m.sum()))
assert anti.row_count == int((~m).sum())

# Rank-coherent failure recovery (docs/robustness.md): inject a predicted
# receive-budget fault on RANK 0 ONLY.  The guard consensus must make
# every rank raise (and retry) identically — same streaming-fallback
# branch, no deadlock, exactly one logged recovery event per rank — and
# the recovered join must equal the un-injected run exactly.
from cylon_tpu.exec import recovery

baseline = (join_tables(lt, rt, "k", "k", how="inner").to_pandas()
            .sort_values(["k", "a", "b"]).reset_index(drop=True))
env.barrier()
recovery.install_faults("shuffle.recv_guard:0:1=predicted")
recovery.reset_events()
j_inj = join_tables(lt, rt, "k", "k", how="inner")
got_inj = (j_inj.to_pandas().sort_values(["k", "a", "b"])
           .reset_index(drop=True))
pd.testing.assert_frame_equal(got_inj, baseline, check_dtype=False)
evs = recovery.recovery_events()
assert len(evs) == 1, evs
assert evs[0] == {"site": "join", "kind": "predicted",
                  "action": "retry_chunks_4"}, evs
recovery.install_faults("")
print(f"RECOVERY_OK pid={pid} events={len(evs)}", flush=True)

# Spill tier (docs/robustness.md "Memory ledger & spill tier"): inject
# eviction PRESSURE on RANK 0 ONLY at the ledger's admission site.  The
# spill consensus (Code.SpillRequired over the pmax wire) must make
# every rank run the identical deterministic LRU eviction — same owners,
# same order, no deadlock — and the host-resident source's per-window
# re-uploads must keep the pipelined join bit-equal to the un-injected
# run.  nth=2: the FIRST PieceSource (probe side) must already be
# registered when the pressure fires, so there is something to evict.
import zlib

from jax.experimental import multihost_utils

from cylon_tpu.exec import memory, pipelined_join

pipe_base = (pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=4)
             .to_pandas().sort_values(["k", "a", "b"])
             .reset_index(drop=True))
env.barrier()
recovery.install_faults("spill.evict:0:2=predicted")
recovery.reset_events()
memory.reset_stats()
pipe_inj = (pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=4)
            .to_pandas().sort_values(["k", "a", "b"])
            .reset_index(drop=True))
pd.testing.assert_frame_equal(pipe_inj, pipe_base, check_dtype=False)
seq = memory.eviction_log()
assert len(seq) >= 1, seq
assert memory.stats()["spill_events"] >= 1
# every rank must have evicted the SAME owners in the SAME order
sig = np.int64(zlib.crc32("|".join(seq).encode()))
sigs = np.atleast_1d(multihost_utils.process_allgather(sig))
assert len({int(s) for s in sigs}) == 1, (seq, sigs)
recovery.install_faults("")
print(f"SPILL_OK pid={pid} evictions={seq}", flush=True)

# Per-rank phase skew report (cylon_tpu/obs/rank_report, docs/
# observability.md): each rank times the same pipelined join, then the
# ARMED report allgathers every rank's phase table and reduces to
# min/median/max per phase.  The cross-check: the gathered matrix is the
# same on every rank, so the REPORT must be byte-identical across ranks
# (crc allgather) — and a rank timing a structurally different program
# would have surfaced as the report's typed name-set desync instead.
import json as _json

from cylon_tpu import config as _config, obs

_prev_bench = _config.BENCH_TIMINGS
_config.BENCH_TIMINGS = True
from cylon_tpu.utils import timing as _timing
_timing.reset()
pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=4)
_config.BENCH_TIMINGS = _prev_bench
obs.rank_report.arm()
rep = obs.rank_report.report()
obs.rank_report.arm(False)
assert rep["ranks"] == nproc, rep
assert "pipe.piece_join" in rep["phases"], sorted(rep["phases"])
for ent in rep["phases"].values():
    assert ent["min_s"] <= ent["median_s"] <= ent["max_s"], ent
rep_sig = np.int64(zlib.crc32(_json.dumps(rep, sort_keys=True).encode()))
rep_sigs = np.atleast_1d(multihost_utils.process_allgather(rep_sig))
assert len({int(s) for s in rep_sigs}) == 1, (rep, rep_sigs)
print(f"RANKREPORT_OK pid={pid} phases={len(rep['phases'])}", flush=True)

# Communication matrix (cylon_tpu/obs/comm, docs/observability.md): arm
# the matrix, run one hash shuffle + one join, and cross-check (a) the
# cumulative matrix's grand totals equal the always-on exchange
# counters, (b) the report — which internally allgathers and verifies
# the matrix — is BYTE-IDENTICAL across ranks (each process accumulated
# the same replicated count sidecars, so any divergence is a typed
# RankDesyncError; the crc allgather proves the serialized report
# matches too).
from cylon_tpu.obs import comm as _comm, metrics as _metrics

env.barrier()
_comm.arm()
_comm.reset()
_rows0 = _metrics.counter("exchange_rows_total").value
_bytes0 = _metrics.counter("exchange_bytes_total").value
join_tables(lt, rt, "k", "k", how="inner")
crep = _comm.report()   # allgathers + verifies matrix identity itself
_comm.arm(False)
m_rows = np.asarray(crep["rows"], np.int64)
m_bytes = np.asarray(crep["bytes"], np.int64)
assert crep["world"] == env.world_size, crep["world"]
assert int(m_rows.sum()) == crep["total_rows"] \
    == _metrics.counter("exchange_rows_total").value - _rows0
assert int(m_bytes.sum()) == crep["total_bytes"] \
    == _metrics.counter("exchange_bytes_total").value - _bytes0
assert m_bytes.sum(axis=1).tolist() == crep["row_sums_bytes"]
assert m_bytes.sum(axis=0).tolist() == crep["col_sums_bytes"]
comm_sig = np.int64(zlib.crc32(_json.dumps(crep, sort_keys=True).encode()))
comm_sigs = np.atleast_1d(multihost_utils.process_allgather(comm_sig))
assert len({int(s) for s in comm_sigs}) == 1, (crep, comm_sigs)
_comm.reset()
print(f"COMMMATRIX_OK pid={pid} exchanges={crep['exchanges']} "
      f"rows={crep['total_rows']}", flush=True)

# Streaming window-close determinism (cylon_tpu/stream, docs/
# streaming.md): both processes ingest the same seeded micro-batches
# into a TumblingWindowJoin; the watermark min-vote
# (recovery.watermark_consensus over the pmax wire) must make every
# rank close the IDENTICAL windows at the same step, and the closed
# windows' joined contents must hash identically across ranks
# (allgathered crc over the sorted output bytes).
import hashlib as _hashlib

from cylon_tpu.stream import TumblingWindowJoin

env.barrier()
srng = np.random.default_rng(29)   # same seed per process: SPMD ingest
dims = ct.Table.from_pydict(
    {"k": np.arange(16, dtype=np.int64),
     "dim": np.arange(16, dtype=np.int64) * 3}, env)
wj = TumblingWindowJoin(env, key="k", time_col="t", window=100,
                        build=dims, build_on="k", lateness=10)
for i in range(3):
    wj.append({"k": srng.integers(0, 16, 300).astype(np.int64),
               "t": (i * 100 + srng.integers(0, 100, 300)).astype(np.int64),
               "v": srng.integers(0, 50, 300).astype(np.int64)})
agreed = wj.watermark()
assert wj.windows_closed >= 1, wj.stats()
closed_sig = []
for wid, out in wj.closed:
    h = _hashlib.sha256()
    if out is not None:
        cdf = (out.to_pandas().sort_values(["k", "t", "v"])
               .reset_index(drop=True))
        h.update(cdf.to_csv(index=False).encode())
    closed_sig.append((wid, zlib.crc32(h.hexdigest().encode())))
wire = np.asarray([agreed, len(closed_sig)]
                  + [x for p_ in closed_sig for x in p_], np.int64)
gathered = np.asarray(multihost_utils.process_allgather(wire))
gathered = gathered.reshape(nproc, -1)
for r in range(1, nproc):
    assert np.array_equal(gathered[0], gathered[r]), gathered
print(f"STREAM_OK pid={pid} agreed={agreed} closed={len(closed_sig)}",
      flush=True)

# Adaptive skew-split plan coherence (relational/skew.py, docs/skew.md):
# a Zipf-ish hot key on ~70% of probe rows arms the split route; the
# Code.SkewPlan vote rides the REAL cross-process pmax wire here, and
# every rank must adopt the IDENTICAL plan hash (allgathered crc).  The
# split join's stitched output and its fused groupby must both be
# bit- and order-equal to the unsplit hash plan's (the route's
# equivalence contract, exercised across processes).
from cylon_tpu import config as _cfg
from cylon_tpu.relational import skew as _skew

env.barrier()
skrng = np.random.default_rng(31)   # same seed per process: SPMD ingest
ns = 6000
hot = np.int64(77)
sk = skrng.integers(0, 600, ns).astype(np.int64)
sk = np.where(skrng.random(ns) < 0.7, hot, sk)
sl = ct.Table.from_pydict(
    {"k": sk, "a": skrng.integers(0, 100, ns).astype(np.int64)}, env)
bk = skrng.integers(0, 600, ns).astype(np.int64)
bk[bk == hot] = hot + 1   # hot key exactly once on the build side
bk[0] = hot
sr = ct.Table.from_pydict(
    {"k": bk, "b": skrng.integers(0, 100, ns).astype(np.int64)}, env)
js = join_tables(sl, sr, "k", "k", how="inner")
gs = groupby_aggregate(js, "k", [("a", "sum"), ("b", "sum")])
plan = _skew.last_plan()
assert plan is not None, "skew-split plan did not arm"
plan_sig = np.int64(zlib.crc32(format(plan.plan_hash(), "016x").encode()))
plan_sigs = np.atleast_1d(multihost_utils.process_allgather(plan_sig))
assert len({int(s) for s in plan_sigs}) == 1, (plan.summary(), plan_sigs)
gdf = gs.to_pandas()
jdf = js.to_pandas()    # materializes through the stitch
_cfg.SKEW_SPLIT = False
try:
    ju = join_tables(sl, sr, "k", "k", how="inner")
    judf = ju.to_pandas()
    gudf = groupby_aggregate(ju, "k", [("a", "sum"), ("b", "sum")]) \
        .to_pandas()
finally:
    _cfg.SKEW_SPLIT = True
pd.testing.assert_frame_equal(jdf, judf)
pd.testing.assert_frame_equal(gdf, gudf)
print(f"SKEWPLAN_OK pid={pid} keys={len(plan)} "
      f"fanout={[int(f) for f in plan.fanout]} "
      f"hash={format(plan.plan_hash(), '016x')}", flush=True)

# Multi-slice topology plan coherence (cylon_tpu/topo, docs/
# topology.md): declare a two-slice fabric over the 8-rank world — the
# process boundary IS the simulated DCN tier (4 local devices per
# process, slice-major) — and re-run join + groupby + sort through the
# hierarchical two-hop route.  The Code.TopoPlan vote rides the REAL
# cross-process pmax wire here; every rank must adopt the IDENTICAL
# plan hash (allgathered crc), the two-hop results must be bit- and
# order-equal to the flat route's, and the armed comm report's tier
# split must reconcile (ici + dcn == totals) byte-identically across
# ranks (the report's own allgather covers the tier fields).
from cylon_tpu.topo import model as _topo_model

env.barrier()
os.environ["CYLON_TPU_SLICES"] = "2"
_topo_model._reslice()
tj = join_tables(lt, rt, "k", "k", how="inner")
tg = groupby_aggregate(tj, "k", [("a", "sum"), ("b", "mean")])
ts_ = sort_table(tg, "k")
topo_got = ts_.to_pandas().reset_index(drop=True)
tplan = _topo_model.last_plan()
assert tplan is not None, "two-hop route never voted a topology plan"
assert tplan.route == "hierarchical", tplan.summary()
tp_sig = np.int64(zlib.crc32(format(tplan.plan_hash(), "016x").encode()))
tp_sigs = np.atleast_1d(multihost_utils.process_allgather(tp_sig))
assert len({int(s) for s in tp_sigs}) == 1, (tplan.summary(), tp_sigs)
pd.testing.assert_frame_equal(topo_got, got, check_dtype=False)
_comm.arm()
_comm.reset()
join_tables(lt, rt, "k", "k", how="inner")
trep = _comm.report()   # allgathers + verifies (tier fields included)
_comm.arm(False)
tt = trep["tiers"]
assert tt["ici_rows"] + tt["dcn_rows"] == trep["total_rows"], tt
assert tt["routes"].get("two_hop"), tt
_comm.reset()
del os.environ["CYLON_TPU_SLICES"]
_topo_model._reslice()
print(f"TOPO_OK pid={pid} plan={format(tplan.plan_hash(), '016x')} "
      f"dcn_messages={tt['dcn_messages']}", flush=True)

# Integrity audit tier (cylon_tpu/exec/integrity, docs/robustness.md
# "Integrity audit tier"): arm the fingerprint layer mid-process and
# re-run the join — every post-exchange fingerprint vote rides the REAL
# cross-process consensus wire here.  The armed run must stay bit-equal
# with zero violations, the final table's order-invariant fingerprint
# must be identical across ranks (allgathered crc — order-invariance
# makes it shard-layout-independent), and a corruption injected on RANK
# 0 ONLY must surface rank-coherently: the gathered fingerprint matrix
# is the same everywhere, so EVERY rank raises the typed
# DataIntegrityError and retries identically — no deadlock, exactly one
# integrity recovery event per rank, bit-equal after the recompute.
from cylon_tpu.exec import integrity as _integrity

env.barrier()
os.environ["CYLON_TPU_AUDIT"] = "1"
_integrity.rearm()
_integrity.reset_stats()
aj = join_tables(lt, rt, "k", "k", how="inner")
audit_got = (aj.to_pandas().sort_values(["k", "a", "b"])
             .reset_index(drop=True))
pd.testing.assert_frame_equal(audit_got, baseline, check_dtype=False)
ist = _integrity.stats()
assert ist["fingerprint_checks"] >= 1, ist
assert ist["fingerprint_votes"] >= 1, ist
assert ist["violations"] == 0, ist
afp = _integrity.table_fingerprint(aj)
assert afp is not None
fp_sig = np.int64(zlib.crc32(format(afp, "016x").encode()))
fp_sigs = np.atleast_1d(multihost_utils.process_allgather(fp_sig))
assert len({int(s) for s in fp_sigs}) == 1, fp_sigs

env.barrier()
recovery.reset_events()
recovery.install_faults("exchange.corrupt:0:1=corrupt")
cj = join_tables(lt, rt, "k", "k", how="inner")
cdf = cj.to_pandas().sort_values(["k", "a", "b"]).reset_index(drop=True)
pd.testing.assert_frame_equal(cdf, baseline, check_dtype=False)
ievs = [e for e in recovery.recovery_events() if e["kind"] == "integrity"]
assert len(ievs) == 1, recovery.recovery_events()
recovery.install_faults("")
del os.environ["CYLON_TPU_AUDIT"]
_integrity.rearm()
print(f"AUDIT_OK pid={pid} fp={format(afp, '016x')} "
      f"checks={ist['fingerprint_checks']}", flush=True)

env.barrier()
print(f"MULTIHOST_OK pid={pid} world={env.world_size} rows={j.row_count}",
      flush=True)
