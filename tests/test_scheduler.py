"""Multi-tenant serving tier (exec/scheduler + exec/session): admission
control over the HBM ledger, cooperative interleave at piece-loop
boundaries, pluggable policies, shared plan cache, and per-session
recovery isolation (ISSUE 7 acceptance: per-tenant results bit-equal to
solo runs, admission waits + cross-tenant evictions exercised, no
cross-session recovery contamination)."""

import os
import subprocess
import sys
import time

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.exec import memory, recovery, scheduler
from cylon_tpu.exec.scheduler import QueryScheduler, estimate_footprint
from cylon_tpu.status import InvalidError
from cylon_tpu.utils import timing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    recovery.install_faults("")
    recovery.reset_events()
    recovery.set_session(None, None)
    memory.reset_stats()
    yield
    recovery.install_faults("")
    recovery.reset_events()
    recovery.set_session(None, None)


def _pipe_fn(env, seed, n=1200, chunks=3, label=None):
    """A TPC-H-shaped pipelined join+sink query (the chaos-soak
    workload): piece-loop interleave points + spillable PieceSource
    registrations — the serving tier's reference tenant."""
    from cylon_tpu.exec import GroupBySink, pipelined_join

    def attempt(nc):
        rng = np.random.default_rng(seed)
        n_ord = max(n // 4, 64)
        orders = ct.Table.from_pydict(
            {"o_orderkey": np.arange(n_ord, dtype=np.int64),
             "o_pri": rng.integers(0, 5, n_ord).astype(np.int64)}, env)
        line = ct.Table.from_pydict(
            {"l_orderkey": rng.integers(0, n_ord, n).astype(np.int64),
             "l_qty": rng.integers(1, 51, n).astype(np.int64)}, env)
        sink = GroupBySink("l_orderkey", [("l_qty", "sum")])
        pipelined_join(line, orders, "l_orderkey", "o_orderkey",
                       how="inner", n_chunks=nc, sink=sink)
        return sink.finalize().to_pandas().sort_values("l_orderkey") \
            .reset_index(drop=True)

    if label is None:
        return lambda: attempt(chunks)
    return lambda: recovery.run_with_recovery(
        lambda: attempt(chunks), True, attempt, label, env=env)


class TestPolicies:
    def test_policy_keys(self):
        from cylon_tpu.exec.session import QuerySession
        a = QuerySession("a", lambda: None, 0, priority=1)
        b = QuerySession("b", lambda: None, 1, priority=5)
        c = QuerySession("c", lambda: None, 2, priority=5, weight=2.0)
        assert min([b, a], key=scheduler._fifo_key) is a
        assert min([a, b, c], key=scheduler._priority_key) is b
        # fair: least attributed-seconds-per-weight first; c's double
        # weight halves its effective clock
        a.service_s, b.service_s, c.service_s = 1.0, 3.0, 3.0
        assert min([a, b, c], key=scheduler._fair_key) is a
        a.service_s = 2.0
        assert min([a, b, c], key=scheduler._fair_key) is c

    def test_unknown_policy_and_duplicate_names(self, env1):
        with pytest.raises(InvalidError):
            QueryScheduler(env1, policy="lottery")
        sched = QueryScheduler(env1)
        sched.submit("t0", lambda: 1)
        with pytest.raises(InvalidError):
            sched.submit("t0", lambda: 2)
        with pytest.raises(ValueError):
            sched.submit("bad/name", lambda: 3)

    def test_fair_interleaves_and_timing_tables_disjoint(self, env1):
        """Two interleaved sessions produce DISJOINT per-session phase
        tables (the satellite's regression): each scope holds exactly
        its own thread's regions, even for identically-named regions,
        and without CYLON_TPU_BENCH the global table stays untouched."""
        order = []

        def tenant(name):
            def fn():
                for _ in range(3):
                    with timing.region("q.work"):
                        with timing.region(f"only.{name}"):
                            time.sleep(0.003)
                    order.append(name)
                    scheduler.maybe_yield()
                return name
            return fn

        sched = QueryScheduler(env1, policy="fair")
        a = sched.submit("tA", tenant("tA"))
        b = sched.submit("tB", tenant("tB"))
        sched.run(raise_errors=True)
        # both made progress before either finished (interleaved)
        assert a.slices >= 2 and b.slices >= 2
        assert set(order[:4]) == {"tA", "tB"}
        for s, other in ((a, "tB"), (b, "tA")):
            snap = s.phase_snapshot()
            assert snap["q.work"]["n"] == 3          # own regions only
            assert f"only.{s.name}" in snap
            assert f"only.{other}" not in snap       # no bleed
            assert s.attributed_s() > 0
        assert not config.BENCH_TIMINGS
        assert "q.work" not in timing.snapshot()     # global untouched

    def test_region_spanning_yield_excludes_baton_wait(self, env1):
        """A region that SPANS a yield point (join.shuffle and
        pipe.consume do) must not absorb co-tenants' slice time into
        this tenant's phase table or fair-share clock — the parked
        period is excluded from the enclosing region's attribution."""
        def busy(work_s):
            def fn():
                for _ in range(3):
                    with timing.region("outer.span"):
                        time.sleep(work_s)
                        scheduler.maybe_yield()   # parked mid-region
            return fn

        sched = QueryScheduler(env1, policy="fair")
        a = sched.submit("tA", busy(0.002))
        b = sched.submit("tB", busy(0.03))
        sched.run(raise_errors=True)
        assert a.slices >= 2 and b.slices >= 2     # they did interleave
        # tA's real work is ~6 ms; with baton-wait bleed its region
        # would have absorbed tB's ~90 ms of slices
        assert a.phase_snapshot()["outer.span"]["s"] < 0.05
        assert b.phase_snapshot()["outer.span"]["s"] >= 0.09

    def test_priority_runs_high_first(self, env1):
        done = []

        def mk(name):
            def fn():
                scheduler.maybe_yield()
                done.append(name)
            return fn

        sched = QueryScheduler(env1, policy="priority")
        sched.submit("lo", mk("lo"), priority=0)
        sched.submit("hi", mk("hi"), priority=9)
        sched.run(raise_errors=True)
        assert done == ["hi", "lo"]


class TestAdmission:
    def test_admission_wait_then_release(self, env1):
        """With a budget that fits one declared footprint, the second
        session WAITS at admission (counted + timed) and starts only
        after the first completes — fifo, no overtaking.  Admission
        gates on DECLARED footprints, so the process-global ledger
        balance (other tests' residents) cannot perturb this."""
        events = []

        def mk(name):
            def fn():
                events.append(("start", name))
                scheduler.maybe_yield()
                events.append(("end", name))
            return fn

        sched = QueryScheduler(env1, policy="fifo", budget_bytes=1000)
        a = sched.submit("tA", mk("tA"), footprint_bytes=600)
        b = sched.submit("tB", mk("tB"), footprint_bytes=600)
        sched.run(raise_errors=True)
        assert events == [("start", "tA"), ("end", "tA"),
                          ("start", "tB"), ("end", "tB")]
        assert a.admission_waits == 0
        assert b.admission_waits >= 1
        assert b.admission_wait_s > 0
        assert sched.stats()["admission_waits"] >= 1

    def test_force_admit_when_nothing_runs(self, env1):
        """A footprint larger than the whole budget cannot deadlock the
        queue: with nothing running, admission degrades to serial
        execution (forced admission, counted)."""
        sched = QueryScheduler(env1, budget_bytes=100)
        s = sched.submit("huge", lambda: 42, footprint_bytes=10**9)
        sched.run(raise_errors=True)
        assert s.result == 42
        assert sched.stats()["forced_admissions"] == 1

    def test_force_serial_counted_and_wait_closed(self, env1):
        """Regression (ISSUE 18 satellite): the force-degrade-to-serial
        grant is counted under its own name AND closes the candidate's
        open admission-wait period — it used to leave ``_wait_mark``
        set, so a later ``summary()`` kept accruing phantom wait
        seconds against a session that was already running."""
        from cylon_tpu import obs
        before = obs.counter("sched_admission_force_serial").value
        sched = QueryScheduler(env1, budget_bytes=100)
        s = sched.submit("huge", lambda: 42, footprint_bytes=10**9)
        sched.run(raise_errors=True)
        assert s.result == 42
        assert sched.stats()["admission_force_serial"] == 1
        assert obs.counter("sched_admission_force_serial").value \
            == before + 1
        assert s._wait_mark is None          # the period is CLOSED
        assert s.outcome() == "completed"

    def test_family_history_unblocks_co_fit(self, env1):
        """Satellite (admission estimates from history): two tenants
        declaring 600 B each against a 1000 B budget used to
        serialize; with a recorded ANALYZE peak of 200 B for their
        shape family, admission gates on min(declared, peak * 1.5) =
        300 B and they co-fit — neither waits."""
        events = []

        def mk(name):
            def fn():
                events.append(("start", name))
                scheduler.maybe_yield()
                events.append(("end", name))
            return fn

        scheduler.reset_family_history()
        scheduler.note_family_peak("mixA", 200)
        try:
            sched = QueryScheduler(env1, policy="fifo",
                                   budget_bytes=1000,
                                   history_safety_factor=1.5)
            a = sched.submit("tA", mk("tA"), footprint_bytes=600,
                             shape_family="mixA")
            b = sched.submit("tB", mk("tB"), footprint_bytes=600,
                             shape_family="mixA")
            sched.run(raise_errors=True)
        finally:
            scheduler.reset_family_history()
        # co-fit: neither tenant was ever noted waiting at admission
        # (the identical schedule WITHOUT the family record is
        # test_admission_wait_then_release's serialized case, where tB
        # waits) — the baton order itself stays fifo either way
        assert len(events) == 4
        assert a.admission_waits == 0 and b.admission_waits == 0
        assert sched.stats()["admission_waits"] == 0

    def test_cross_tenant_eviction_under_pressure(self, env1,
                                                  monkeypatch):
        """Tenant B's allocation admission evicts tenant A's cold
        spillable registration first (LRU), counted as a cross-session
        eviction — and A's state comes back bit-exact from host."""
        import jax.numpy as jnp
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 1)
        box = {}

        def tenant_a():
            arr = jnp.arange(1 << 18, dtype=jnp.uint32)   # 1 MiB
            box["host"] = np.asarray(arr)
            box["reg"] = memory.register("tenantA_state", (arr,),
                                         spillable=True)
            scheduler.maybe_yield()     # B runs while A's state is cold
            scheduler.maybe_yield()
            got = memory.readmit(box["reg"])
            np.testing.assert_array_equal(np.asarray(got[0]).ravel(),
                                          box["host"])
            memory.release(box["reg"])

        def tenant_b():
            # a budget below even B's own need: every spillable resident
            # — A's cold registration included, whatever else this
            # process still holds — must evict before B's allocation
            config.HBM_BUDGET_BYTES = (1 << 19) + (1 << 16)
            scheduler.admit_allocation(env1, 1 << 19)

        sched = QueryScheduler(env1, policy="fair")
        sched.submit("tA", tenant_a)
        sched.submit("tB", tenant_b)
        sched.run(raise_errors=True)
        assert memory.stats()["cross_session_evictions"] >= 1
        assert sched.stats()["cross_session_evictions"] >= 1

    def test_estimate_footprint(self, env1):
        t = ct.Table.from_pydict(
            {"a": np.arange(100, dtype=np.int64)}, env1)
        est = estimate_footprint(t, factor=2.0)
        assert est >= 2 * 100 * 8


class TestServing:
    def test_pipelined_sessions_bit_equal_and_isolated(self, env4):
        """Three interleaved pipelined tenants; a predicted-OOM fault is
        injected into tenant tA ONLY (@session grammar).  tA's retry
        ladder runs (events tagged tA), tB/tC stay clean, and every
        tenant's answer is bit-equal to its solo run — the acceptance's
        no-cross-session-recovery-contamination assertion."""
        solo = {s: _pipe_fn(env4, s)() for s in (11, 22, 33)}
        recovery.install_faults("shuffle.recv_guard::1=predicted@tA")
        sched = QueryScheduler(env4, policy="fair")
        a = sched.submit("tA", _pipe_fn(env4, 11, label="tA"))
        b = sched.submit("tB", _pipe_fn(env4, 22))
        c = sched.submit("tC", _pipe_fn(env4, 33))
        sched.run(raise_errors=True)
        for sess, seed in ((a, 11), (b, 22), (c, 33)):
            pd.testing.assert_frame_equal(sess.result, solo[seed])
        assert len(a.recovery_events()) >= 1
        assert all(e["session"] == "tA" for e in a.recovery_events())
        assert b.recovery_events() == []
        assert c.recovery_events() == []
        # the global log saw only tA-tagged events too
        assert all(e.get("session") == "tA"
                   for e in recovery.recovery_events())

    def test_program_cache_shared_across_tenants(self, env4):
        """Tenants running the same plan shapes share compiled programs:
        a second scheduler pass over the identical shape family adds NO
        new cache entries on the mesh."""
        def counts():
            table = getattr(env4.mesh, "_cylon_tpu_program_cache", {})
            return {name: len(lru) for name, lru in table.items()}

        QueryScheduler(env4).submit("warm", _pipe_fn(env4, 44)) \
            .fn()  # direct call warms every program this shape needs
        before = counts()
        sched = QueryScheduler(env4, policy="fair")
        sched.submit("t1", _pipe_fn(env4, 45))
        sched.submit("t2", _pipe_fn(env4, 46))
        sched.run(raise_errors=True)
        assert counts() == before

    def test_scheduler_reusable_across_runs(self, env1):
        """run() is re-enterable: a completed run's abort latch must not
        fail sessions submitted for a later run."""
        sched = QueryScheduler(env1)
        a = sched.submit("a", lambda: 1)
        sched.run(raise_errors=True)
        b = sched.submit("b", lambda: 2)
        sched.run(raise_errors=True)
        assert (a.state, a.result) == ("done", 1)
        assert (b.state, b.result) == ("done", 2)

    def test_scheduler_exclusive(self, env1):
        seen = {}

        def inner():
            with pytest.raises(InvalidError):
                QueryScheduler(env1).run()
            seen["ok"] = True

        sched = QueryScheduler(env1)
        sched.submit("t0", inner)
        sched.run(raise_errors=True)
        assert seen["ok"]

    def test_failed_session_does_not_poison_others(self, env1):
        sched = QueryScheduler(env1, policy="fair")
        bad = sched.submit("bad", lambda: (_ for _ in ()).throw(
            RuntimeError("boom")))
        good = sched.submit("good", lambda: 7)
        sessions = sched.run()
        assert bad.state == "failed" and "boom" in str(bad.error)
        assert good.state == "done" and good.result == 7
        assert len(sessions) == 2


class TestRecoverySessionPlumbing:
    def test_session_fault_targeting(self):
        recovery.install_faults("shuffle.recv_guard::1=predicted@tB")
        recovery.set_session("tA", 0)
        assert recovery.probe("shuffle.recv_guard")[0] is None
        recovery.set_session("tB", 1)
        # nth counts against tB's OWN sequence: this is tB's first probe
        # even though the site was probed before (by tA)
        kind, armed = recovery.probe("shuffle.recv_guard")
        assert kind == "predicted"
        recovery.set_session(None, None)

    def test_session_nth_counts_per_session(self):
        recovery.install_faults("ckpt.write::2=kill@t0")
        recovery.set_session("t1", 1)
        for _ in range(5):        # a co-tenant hammers the site
            assert recovery.probe("ckpt.write")[0] is None
        recovery.set_session("t0", 0)
        assert recovery.probe("ckpt.write")[0] is None   # t0's 1st
        # t0's 2nd — would fire; use a non-kill grammar check instead
        recovery.install_faults("ckpt.write::2=corrupt@t0")
        recovery.set_session("t1", 1)
        for _ in range(3):
            assert recovery.probe("ckpt.write")[0] is None
        recovery.set_session("t0", 0)
        assert recovery.probe("ckpt.write")[0] is None
        assert recovery.probe("ckpt.write")[0] == "corrupt"
        recovery.set_session(None, None)

    def test_grammar_rejects_bad_specs(self):
        with pytest.raises(ValueError):
            recovery.install_faults("ckpt.write::2=nosuch@t0")
        recovery.install_faults("")

    def test_consensus_namespace_identity_single_process(self, env4):
        from cylon_tpu.status import Code
        recovery.set_session("tA", 7)
        assert recovery._session_ns() == 8
        # single-process: local value IS the consensus, namespace or not
        assert recovery.consensus_code(env4.mesh, Code.OK) == Code.OK
        assert recovery.count_consensus(env4.mesh, 3) == 3
        recovery.set_session(None, None)
        assert recovery._session_ns() == 0

    def test_events_tagged_and_filtered(self):
        recovery.set_session("tX", 3)
        recovery._record("shuffle.recv_guard", "predicted", "test")
        recovery.set_session(None, None)
        recovery._record("shuffle.recv_guard", "predicted", "test")
        evs = recovery.recovery_events()
        assert evs[0]["session"] == "tX"
        assert "session" not in evs[1]
        assert recovery.events_for_session("tX") == [evs[0]]

    def test_checkpoint_stage_namespacing(self, env1, monkeypatch,
                                          tmp_path):
        from cylon_tpu.exec import checkpoint
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        checkpoint.reset_stages()
        try:
            recovery.set_session("tA", 0)
            sa0 = checkpoint.open_stage(env1, "pipelined_join", "tok")
            sa1 = checkpoint.open_stage(env1, "pipelined_join", "tok")
            recovery.set_session("tB", 1)
            sb0 = checkpoint.open_stage(env1, "pipelined_join", "tok")
            recovery.set_session(None, None)
            sn0 = checkpoint.open_stage(env1, "pipelined_join", "tok")
            # per-session sequences + session-namespaced labels: the
            # same interleave-independent identity a resumed process
            # derives
            assert sa0.dir.endswith("stage000-tA.pipelined_join")
            assert sa1.dir.endswith("stage001-tA.pipelined_join")
            assert sb0.dir.endswith("stage000-tB.pipelined_join")
            assert sn0.dir.endswith("stage000-pipelined_join")
            assert len({sa0.dir, sa1.dir, sb0.dir, sn0.dir}) == 4
        finally:
            checkpoint.reset_stages()


# ---------------------------------------------------------------------------
# acceptance drivers (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_serving_acceptance():
    """ISSUE 7 acceptance: scripts/bench_serving.py with 4 concurrent
    tenants on the CPU rig — mixed TPC-H workload, every per-tenant
    result bit-equal to its solo run, at least one admission wait and
    one cross-tenant eviction exercised, and per-session recovery event
    logs clean (no cross-session contamination)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from bench_serving import run_serving
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))
    report = run_serving(tenants=4, queries=2, scale=0.004,
                         policy="fair", budget_mb="auto")
    d = report["detail"]
    assert d["bit_equal"], d["failures"]
    assert not d["failures"]
    assert d["scheduler"]["admission_waits"] >= 1
    assert d["spill"]["cross_session_evictions"] >= 1
    assert d["scheduler"]["completed"] == 4
    for name, info in d["tenants"].items():
        # happy-path tenants carry empty per-session recovery logs; any
        # event that does appear must be the tenant's own
        assert all(e.get("session") == name
                   for e in info["recovery_events"])


@pytest.mark.slow
def test_chaos_soak_concurrent_kill_resume():
    """scripts/chaos_soak.py --concurrent 2: mid-query SIGKILL targeted
    at tenant t0, resumed rerun fast-forwards t0's committed pieces,
    and both tenants' answers stay bit-equal to their solo runs."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--concurrent", "2", "--rows", "1200"],
        capture_output=True, text=True, timeout=560, cwd=REPO)
    assert p.returncode == 0, (p.stdout + p.stderr)[-4000:]
    assert '"failures": 0' in p.stdout
