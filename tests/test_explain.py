"""Tests for the query profiler (cylon_tpu.obs.plan / comm / sketch).

Covers: plan-tree static-shape stability across runs, the EXPLAIN
ANALYZE reconciliation invariant (per-node self seconds sum to the
global phase table), the comm-matrix row/col-sum == exchange-counter
identity, Misra-Gries correctness against exact counts, the heavy-hitter
key profiler's 2×-of-ground-truth acceptance, and the unarmed
zero-collective/zero-write/zero-record contract in the checkpoint tier's
assertion style.  The cross-rank byte-identity of the comm matrix lives
in tests/multihost_driver.py.
"""

import json
import os
import sys

import numpy as np
import pytest

import cylon_tpu as ct
from cylon_tpu import config, obs
from cylon_tpu.obs import comm, metrics, plan, sketch
from cylon_tpu.status import InvalidError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    from cylon_tpu.utils import timing
    prev = config.BENCH_TIMINGS
    comm.arm(False)
    comm._rearm()
    comm.reset()
    timing.reset()
    yield
    comm.arm(False)
    comm._rearm()
    comm.reset()
    timing.reset()
    config.BENCH_TIMINGS = prev


def _tables(env, n=4000, hot_frac=0.0, seed=7):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, max(n // 8, 8), n).astype(np.int64)
    if hot_frac > 0.0:
        hot = np.int64(3)
        k = np.where(rng.random(n) < hot_frac, hot, k)
    lt = ct.Table.from_pydict(
        {"k": k, "a": rng.integers(0, 100, n).astype(np.int64)}, env)
    rt = ct.Table.from_pydict(
        {"k": rng.integers(0, max(n // 8, 8), n).astype(np.int64),
         "b": rng.integers(0, 100, n).astype(np.int64)}, env)
    return lt, rt


def _query(lt, rt):
    from cylon_tpu.relational import (groupby_aggregate, join_tables,
                                      sort_table)
    j = join_tables(lt, rt, "k", "k", how="inner")
    g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum")])
    return sort_table(g, "k")


# ---------------------------------------------------------------------------
# plan tree
# ---------------------------------------------------------------------------

class TestPlanTree:
    def test_static_tree_stable_across_runs(self, env4):
        """Same query ⇒ IDENTICAL static tree (ops, attrs, shape)."""
        lt, rt = _tables(env4)
        a = obs.explain(_query, lt, rt).static_dict()
        b = obs.explain(_query, lt, rt).static_dict()
        assert a == b
        # and analyze's static skeleton matches explain's
        c = obs.explain_analyze(_query, lt, rt).static_dict()
        assert a == c

    def test_tree_names_operators_and_routes(self, env4):
        lt, rt = _tables(env4)
        qp = obs.explain(_query, lt, rt)
        ops = {r.op for r in qp.roots}
        assert {"join", "groupby", "sort"} <= ops
        join = next(r for r in qp.roots if r.op == "join")
        assert join.attrs["how"] == "inner"
        assert join.attrs["route"] in ("hash", "broadcast", "skew_split",
                                       "colocated")
        if env4.world_size > 1:
            assert any(c.op == "shuffle" for c in join.children)

    def test_result_passthrough_and_rows(self, env4):
        lt, rt = _tables(env4)
        qp = obs.explain(_query, lt, rt)
        assert qp.result.row_count > 0
        join = next(r for r in qp.roots if r.op == "join")
        assert join.rows_in == lt.row_count + rt.row_count
        # the join DEFERS into the fused groupby pushdown: its node
        # records no rows_out (pulling the deferred counts would break
        # the very deferral being profiled) and the groupby node says so
        g = next(r for r in qp.roots if r.op == "groupby")
        assert g.attrs.get("route") == "fused_pushdown" \
            or g.rows_out == qp.result.row_count
        s = next(r for r in qp.roots if r.op == "sort")
        assert s.rows_out == qp.result.row_count

    def test_pipelined_tree_has_piece_children(self, env4):
        from cylon_tpu.exec import pipelined_join
        lt, rt = _tables(env4, n=6000)
        qp = obs.explain(pipelined_join, lt, rt, "k", "k", how="inner",
                         n_chunks=3)
        root = qp.roots[0]
        assert root.op == "pipelined_join"
        assert root.attrs["route"] == "range_pipeline"
        assert root.attrs["n_ranges"] == 3
        pieces = [c for c in root.children if c.op == "join.piece"]
        assert pieces and all(c.attrs["cap_l"] >= 1 for c in pieces)

    def test_nesting_raises_typed(self, env4):
        lt, rt = _tables(env4, n=256)
        with pytest.raises(InvalidError):
            obs.explain(lambda: obs.explain(_query, lt, rt))

    def test_render_tree_mentions_every_op(self, env4):
        lt, rt = _tables(env4)
        text = obs.explain_analyze(_query, lt, rt).render()
        for op in ("join", "groupby", "sort"):
            assert op in text
        assert "self=" in text and "dispatch" in text


# ---------------------------------------------------------------------------
# analyze: reconciliation + dispatch/block split
# ---------------------------------------------------------------------------

class TestAnalyze:
    def test_totals_reconcile_with_phase_table(self, env4):
        """The acceptance invariant: per-node self seconds sum to the
        global phase table, per region name and in total."""
        lt, rt = _tables(env4)
        qp = obs.explain_analyze(_query, lt, rt)
        rec = qp.reconcile()
        assert rec["phase_s"] > 0
        assert rec["node_s"] <= rec["phase_s"] + 1e-6
        assert abs(rec["unattributed_s"]) \
            <= max(0.05 * rec["phase_s"], 0.02)
        for name, s in rec["per_phase_node_s"].items():
            assert s == pytest.approx(qp.global_phases[name]["s"],
                                      rel=1e-4, abs=2e-3), name

    def test_dispatch_block_split(self, env4):
        lt, rt = _tables(env4)
        qp = obs.explain_analyze(_query, lt, rt)

        def walk(n):
            assert n.seconds is not None
            assert n.dispatch_s is not None and n.block_s is not None
            # phase tables round to 4 decimals; the split sums match
            # to that rounding scale
            assert n.seconds == pytest.approx(
                n.dispatch_s + n.block_s, rel=1e-4, abs=2e-3)
            for c in n.children:
                walk(c)
        for r in qp.roots:
            walk(r)

    def test_caller_flags_restored(self, env4):
        lt, rt = _tables(env4, n=256)
        assert not config.BENCH_TIMINGS
        obs.explain_analyze(_query, lt, rt)
        assert not config.BENCH_TIMINGS

    def test_session_scope_absorbs_node_time(self, env4):
        """A serving-session scope enclosing the profile sees the same
        seconds with profiling on (the absorb-on-pop contract)."""
        from cylon_tpu.utils import timing
        lt, rt = _tables(env4)
        with timing.attribution_scope("tenant") as sc:
            obs.explain_analyze(_query, lt, rt, reset_timings=False)
        assert sc.total_seconds() > 0
        assert "join.shuffle" in sc.snapshot() \
            or "groupby.raw" in sc.snapshot() \
            or "groupby.fused" in sc.snapshot()


# ---------------------------------------------------------------------------
# comm matrix
# ---------------------------------------------------------------------------

class TestCommMatrix:
    def test_row_col_sums_equal_exchange_counters(self, env4):
        lt, rt = _tables(env4)
        comm.arm()
        rows0 = metrics.counter("exchange_rows_total").value
        bytes0 = metrics.counter("exchange_bytes_total").value
        comm.reset()
        _query(lt, rt)
        rep = comm.report()
        drow = metrics.counter("exchange_rows_total").value - rows0
        dbytes = metrics.counter("exchange_bytes_total").value - bytes0
        if env4.world_size == 1:
            assert rep is None and drow == 0
            return
        m_rows = np.asarray(rep["rows"])
        m_bytes = np.asarray(rep["bytes"])
        assert rep["world"] == env4.world_size
        assert rep["exchanges"] >= 3   # two hash shuffles + sort range
        # the identity: matrix grand totals == the always-on counters
        assert int(m_rows.sum()) == rep["total_rows"] == drow
        assert int(m_bytes.sum()) == rep["total_bytes"] == dbytes
        # row/col sums are per-src / per-dst marginals of the same matrix
        assert m_bytes.sum(axis=1).tolist() == rep["row_sums_bytes"]
        assert m_bytes.sum(axis=0).tolist() == rep["col_sums_bytes"]
        # every row routed somewhere: shuffles preserve rows
        assert drow > 0

    def test_single_exchange_marginals(self, env4):
        from cylon_tpu.relational.repart import shuffle_table
        if env4.world_size == 1:
            pytest.skip("no exchange at world 1")
        lt, _ = _tables(env4, n=2000)
        comm.arm()
        comm.reset()
        shuffle_table(lt, ["k"])
        rep = comm.report()
        m = np.asarray(rep["rows"])
        # one hash shuffle moves exactly the table's rows; the row sums
        # are what each source shard held
        assert int(m.sum()) == lt.row_count
        assert m.sum(axis=1).tolist() == [int(x) for x in lt.valid_counts]

    def test_unarmed_profile_never_touches_comm_state(self, env4):
        """Regression (review finding): an UNARMED explain/explain_analyze
        must leave the comm module's cumulative state alone — otherwise a
        later ARMED session's report() serves stale exchanges and its
        totals no longer equal the session's counter deltas."""
        if env4.world_size == 1:
            pytest.skip("no exchange at world 1")
        lt, rt = _tables(env4)
        assert not comm.armed()
        obs.explain(_query, lt, rt)
        obs.explain_analyze(_query, lt, rt)
        assert comm.matrix() is None          # nothing accumulated
        # ...so an armed session's report equals ITS OWN counter deltas
        comm.arm()
        rows0 = metrics.counter("exchange_rows_total").value
        _query(lt, rt)
        rep = comm.report()
        assert rep["total_rows"] \
            == metrics.counter("exchange_rows_total").value - rows0

    def test_profile_keys_opt_out(self, env4):
        """bench.py's comparability knob: profile_keys=False skips the
        sampler's device programs; nodes carry no heavy profile."""
        lt, rt = _tables(env4, n=20000, hot_frac=0.9)
        qp = obs.explain_analyze(_query, lt, rt, profile_keys=False)
        def walk(n):
            assert n.heavy is None
            for c in n.children:
                walk(c)
        for r in qp.roots:
            walk(r)

    def test_plan_attaches_comm_report(self, env4):
        lt, rt = _tables(env4)
        comm.arm()
        qp = obs.explain_analyze(_query, lt, rt)
        if env4.world_size > 1:
            assert qp.comm is not None
            assert qp.to_dict()["comm_matrix"]["total_rows"] > 0


# ---------------------------------------------------------------------------
# Misra-Gries + key profiler
# ---------------------------------------------------------------------------

class TestSketch:
    def test_estimates_vs_exact_counts(self):
        rng = np.random.default_rng(5)
        # zipf-ish known distribution over a small alphabet
        vals = rng.choice(np.arange(50), size=20000,
                          p=np.r_[0.4, 0.2, np.full(48, 0.4 / 48)])
        mg = sketch.MisraGries(k=8)
        mg.update(vals)
        exact = {v: int((vals == v).sum()) for v in np.unique(vals)}
        err = mg.error_bound
        assert err <= len(vals) / 9 + 1e-9
        for v, est in mg.items():
            assert exact[int(v)] - err <= est <= exact[int(v)] + 1e-9
        # every value above the MG threshold is tracked
        tracked = {int(v) for v, _ in mg.items()}
        for v, c in exact.items():
            if c > len(vals) / 9:
                assert int(v) in tracked, (v, c)

    def test_weighted_updates(self):
        mg = sketch.MisraGries(k=4)
        mg.update(np.asarray([1, 2, 3]),
                  np.asarray([100.0, 10.0, 1.0]))
        items = dict(mg.items())
        assert items[1] == 100.0 and items[2] == 10.0
        assert mg.n == pytest.approx(111.0)

    def test_k_validation_typed(self):
        with pytest.raises(InvalidError):
            sketch.MisraGries(k=0)


class TestKeyProfile:
    def test_heavy_hitter_within_2x_of_truth(self, env4):
        """The bench --skew acceptance: a 0.9-hot key column reports
        ≥1 heavy hitter whose estimated share is within 2× of truth."""
        lt, _ = _tables(env4, n=20000, hot_frac=0.9)
        truth = float((np.asarray(
            lt.to_pandas()["k"]) == 3).mean())
        prof = plan.key_profile(lt, "k")
        assert prof is not None and prof["heavy"], prof
        top = prof["heavy"][0]
        assert top["key"] == 3
        assert truth / 2 <= top["share"] <= truth * 2, (top, truth)
        assert prof["max_key_share"] >= truth / 2
        assert prof["est_max_rank_share"] >= prof["max_key_share"]

    def test_uniform_keys_report_no_heavy(self, env4):
        lt, _ = _tables(env4, n=20000)
        prof = plan.key_profile(lt, "k")
        assert prof is not None
        assert prof["max_key_share"] < 0.05

    def test_empty_table_returns_none(self, env4):
        lt = ct.Table.from_pydict(
            {"k": np.zeros(0, np.int64)}, env4)
        assert plan.key_profile(lt, "k") is None

    def test_analyze_attaches_node_profile(self, env4):
        lt, rt = _tables(env4, n=20000, hot_frac=0.9)
        qp = obs.explain_analyze(_query, lt, rt)
        join = next(r for r in qp.roots if r.op == "join")
        assert join.heavy is not None
        assert join.heavy["heavy"][0]["key"] == 3


# ---------------------------------------------------------------------------
# the unarmed contract (PR 10 style: zero writes, zero records)
# ---------------------------------------------------------------------------

class TestUnarmedContract:
    def test_no_profile_means_no_nodes_no_records(self, env4, tmp_path,
                                                  monkeypatch):
        monkeypatch.chdir(tmp_path)
        monkeypatch.delenv("CYLON_TPU_COMM_MATRIX", raising=False)
        assert not plan.active() and not comm.armed()
        # comm.record must never even be CALLED on the unarmed path
        # (the exchange guards on armed()/active()); a call here would
        # raise and fail the query

        def _boom(*a, **k):  # pragma: no cover - the assertion itself
            raise AssertionError("comm.record called while unarmed")
        monkeypatch.setattr(comm, "record", _boom)
        lt, rt = _tables(env4)
        out = _query(lt, rt)
        assert out.row_count > 0
        assert comm.matrix() is None
        assert plan.current() is None
        assert os.listdir(tmp_path) == []

    def test_node_facade_is_noop_without_profile(self):
        with plan.node("join", how="inner") as pn:
            assert not pn
            pn.set(rows_in=5)       # swallowed
            pn.annotate(route="x")  # swallowed
        plan.annotate(route="y")     # no current node: no-op
        assert plan.current() is None

    def test_counters_always_on_but_host_only(self, env4):
        """The exchange totals ride the registry even unarmed — pure
        host arithmetic on the already-pulled sidecar."""
        before = metrics.counter("exchange_rows_total").value
        lt, rt = _tables(env4)
        _query(lt, rt)
        after = metrics.counter("exchange_rows_total").value
        if env4.world_size > 1:
            assert after > before
        else:
            assert after == before


# ---------------------------------------------------------------------------
# histogram edge contract (the obs/metrics satellite) lives in
# tests/test_obs.py; scripts/explain.py CLI round-trip below
# ---------------------------------------------------------------------------

def test_explain_cli_render_and_diff(env4, tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import explain as explain_cli
    finally:
        sys.path.pop(0)
    lt, rt = _tables(env4)
    a = obs.explain_analyze(_query, lt, rt).to_dict()
    b = obs.explain_analyze(_query, lt, rt).to_dict()
    pa = tmp_path / "a.json"
    pa.write_text(json.dumps(a))
    loaded = explain_cli.load_plan(str(pa))
    assert loaded["roots"]
    # bench-JSON wrapping resolves too
    pb = tmp_path / "bench.json"
    pb.write_text(json.dumps({"detail": {"plan": b}}))
    assert explain_cli.load_plan(str(pb))["roots"]
    text = explain_cli.diff_plans(a, b)
    # identical static structure: no structural divergence reported
    assert "structure diverges" not in text
    rendered = explain_cli.render_tree(a)
    assert "join" in rendered
