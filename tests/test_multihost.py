"""Multi-host execution: 2 jax.distributed processes over one logical world
(VERDICT item 6 — makes TPUConfig(distributed=True) and the cross-process
barrier tested code).  The moral analog of the reference's `mpirun -np 2`
suite runs (python/pycylon/test/test_all.py:23-29)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


#: memo for the backend-capability probe: once one world size shows the
#: jaxlib CPU client can't run multiprocess collectives, skip the other
#: parametrizations up front instead of re-spawning doomed process trees
_CPU_MULTIPROCESS_UNSUPPORTED = False


@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_join_groupby_sort(nproc):
    """2- and 4-process worlds (reference test_all.py runs mpirun -n {2,4});
    the 4-process case exercises the multi-controller paths in
    _shard_frames/host pulls beyond W=2."""
    global _CPU_MULTIPROCESS_UNSUPPORTED
    if _CPU_MULTIPROCESS_UNSUPPORTED:
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    driver = os.path.join(os.path.dirname(__file__), "multihost_driver.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, driver, str(i), str(nproc), coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(driver))))
        for i in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=570)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("Multiprocess computations aren't implemented on the CPU backend"
           in out for out in outs):
        # capability gate, not a code failure: this jaxlib's CPU client has
        # no cross-process collective transport (newer jaxlibs use a gloo
        # mesh), so a multi-controller CPU world cannot run here at all
        _CPU_MULTIPROCESS_UNSUPPORTED = True
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_OK pid={i} world={4 * nproc}" in out, out[-2000:]
        # rank-coherent recovery: only rank 0 was injected, yet every
        # process converged on the same retry branch without deadlock
        assert f"RECOVERY_OK pid={i} events=1" in out, out[-2000:]
        # rank-coherent spill: eviction pressure injected on rank 0 only;
        # consensus made every process run the IDENTICAL eviction
        # sequence (the driver cross-checks the sequence hash via
        # allgather and prints it per rank)
        assert f"SPILL_OK pid={i} evictions=" in out, out[-2000:]
