"""Multi-host execution: 2 jax.distributed processes over one logical world
(VERDICT item 6 — makes TPUConfig(distributed=True) and the cross-process
barrier tested code).  The moral analog of the reference's `mpirun -np 2`
suite runs (python/pycylon/test/test_all.py:23-29)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_join_groupby_sort():
    driver = os.path.join(os.path.dirname(__file__), "multihost_driver.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, driver, str(i), "2", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(driver))))
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=570)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_OK pid={i} world=8" in out, out[-2000:]
