"""Multi-host execution: 2 jax.distributed processes over one logical world
(VERDICT item 6 — makes TPUConfig(distributed=True) and the cross-process
barrier tested code).  The moral analog of the reference's `mpirun -np 2`
suite runs (python/pycylon/test/test_all.py:23-29)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


#: memo for the backend-capability probe: once one world size shows the
#: jaxlib CPU client can't run multiprocess collectives, skip the other
#: parametrizations up front instead of re-spawning doomed process trees
_CPU_MULTIPROCESS_UNSUPPORTED = False


def _spawn_drivers(nproc, extra_env, timeout=570):
    driver = os.path.join(os.path.dirname(__file__), "multihost_driver.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra_env)
    procs = [subprocess.Popen(
        [sys.executable, driver, str(i), str(nproc), coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(driver))))
        for i in range(nproc)]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def _cpu_backend_unsupported(outs) -> bool:
    return any("Multiprocess computations aren't implemented on the CPU "
               "backend" in out for out in outs)


def test_kill_rank0_and_resume(tmp_path):
    """Durable checkpoint acceptance, two-process edition: launch 1 kills
    rank 0 mid-range-loop (injected `kill` at ckpt.write — rank 1's
    orphaned commit vote surfaces as a typed desync under the watchdog);
    launch 2 resumes with CYLON_TPU_RESUME=1 and both ranks must
    fast-forward past the committed pieces and converge on the IDENTICAL
    manifest epoch and bit-equal result (asserted in-driver by
    allgather)."""
    global _CPU_MULTIPROCESS_UNSUPPORTED
    if _CPU_MULTIPROCESS_UNSUPPORTED:
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    base_env = {"CYLON_TPU_MH_SCENARIO": "kill_resume",
                "CYLON_TPU_CKPT_DIR": str(tmp_path),
                "CYLON_TPU_WATCHDOG_S": "30"}
    procs, outs = _spawn_drivers(2, base_env)
    if _cpu_backend_unsupported(outs):
        _CPU_MULTIPROCESS_UNSUPPORTED = True
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    # rank 0 must have died by SIGKILL mid-loop; rank 1 must NOT have
    # silently completed (its commit partner vanished)
    assert procs[0].returncode == -9, (procs[0].returncode, outs[0][-2000:])
    assert "KILLRESUME_OK pid=1" not in outs[1], outs[1][-2000:]
    procs2, outs2 = _spawn_drivers(2, {**base_env, "CYLON_TPU_RESUME": "1"})
    for i, (p, out) in enumerate(zip(procs2, outs2)):
        assert p.returncode == 0, f"resume proc {i} failed:\n{out[-4000:]}"
        assert f"KILLRESUME_OK pid={i}" in out, out[-2000:]
    # both ranks printed the same epoch (also asserted in-driver via
    # allgather) and fast-forwarded at least one committed piece
    import re
    stats = [re.search(r"KILLRESUME_OK pid=\d+ epoch=(\d+) ffwd=(\d+)", o)
             for o in outs2]
    assert all(stats), outs2
    assert stats[0].group(1) == stats[1].group(1), outs2
    assert all(int(m.group(2)) > 0 for m in stats), outs2


def test_elastic_resume_world_change(tmp_path):
    """Elastic resume acceptance, cross-process edition (docs/
    robustness.md "Elastic resume & preemption grace"): a 2-process
    world=8 session checkpoints a two-stage workload and is SIGKILLed at
    stage 2's first write (stage 1 complete across BOTH rank dirs); a
    SINGLE-process world=4 relaunch must detect the topology change,
    merge the two rank dirs' shard blocks, re-shard stage 1 onto the
    4-device mesh (ffwd > 0, resharded > 0), recompute stage 2 and match
    the pandas oracle."""
    global _CPU_MULTIPROCESS_UNSUPPORTED
    if _CPU_MULTIPROCESS_UNSUPPORTED:
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    base_env = {"CYLON_TPU_MH_SCENARIO": "elastic_resume",
                "CYLON_TPU_CKPT_DIR": str(tmp_path),
                "CYLON_TPU_WATCHDOG_S": "30"}
    procs, outs = _spawn_drivers(2, base_env)
    if _cpu_backend_unsupported(outs):
        _CPU_MULTIPROCESS_UNSUPPORTED = True
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    # rank 0 died by SIGKILL mid-stage-2; rank 1 must not have completed
    assert procs[0].returncode == -9, (procs[0].returncode, outs[0][-2000:])
    assert "ELASTIC_OK pid=1" not in outs[1], outs[1][-2000:]
    # the relaunch is ONE process (4 local devices): world 8 -> 4
    procs2, outs2 = _spawn_drivers(1, {**base_env, "CYLON_TPU_RESUME": "1"})
    assert procs2[0].returncode == 0, outs2[0][-4000:]
    import re
    m = re.search(r"ELASTIC_OK pid=0 world=4 ffwd=(\d+) resharded=(\d+) "
                  r"mismatch=(\d+)", outs2[0])
    assert m, outs2[0][-2000:]
    assert int(m.group(1)) > 0 and int(m.group(2)) > 0, outs2[0][-1000:]


@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_join_groupby_sort(nproc):
    """2- and 4-process worlds (reference test_all.py runs mpirun -n {2,4});
    the 4-process case exercises the multi-controller paths in
    _shard_frames/host pulls beyond W=2."""
    global _CPU_MULTIPROCESS_UNSUPPORTED
    if _CPU_MULTIPROCESS_UNSUPPORTED:
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    driver = os.path.join(os.path.dirname(__file__), "multihost_driver.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, driver, str(i), str(nproc), coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(driver))))
        for i in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=570)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("Multiprocess computations aren't implemented on the CPU backend"
           in out for out in outs):
        # capability gate, not a code failure: this jaxlib's CPU client has
        # no cross-process collective transport (newer jaxlibs use a gloo
        # mesh), so a multi-controller CPU world cannot run here at all
        _CPU_MULTIPROCESS_UNSUPPORTED = True
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_OK pid={i} world={4 * nproc}" in out, out[-2000:]
        # rank-coherent recovery: only rank 0 was injected, yet every
        # process converged on the same retry branch without deadlock
        assert f"RECOVERY_OK pid={i} events=1" in out, out[-2000:]
        # rank-coherent spill: eviction pressure injected on rank 0 only;
        # consensus made every process run the IDENTICAL eviction
        # sequence (the driver cross-checks the sequence hash via
        # allgather and prints it per rank)
        assert f"SPILL_OK pid={i} evictions=" in out, out[-2000:]
        # rank-coherent skew plan: the Code.SkewPlan vote rode the real
        # cross-process wire and every rank adopted the IDENTICAL plan
        # hash (the driver allgathers the hash crc and bit-checks the
        # stitched + fused outputs against the unsplit plan)
        assert f"SKEWPLAN_OK pid={i} keys=" in out, out[-2000:]
        # the two-hop topology leg: identical voted plan hash on every
        # rank + bit/order-equal to the flat route (asserted in-driver)
        assert f"TOPO_OK pid={i} plan=" in out, out[-2000:]
        # the integrity-audit leg: armed fingerprints voted over the
        # real cross-process wire (identical order-invariant fp on
        # every rank, allgather-checked in-driver), and a corruption
        # injected on rank 0 only made EVERY rank raise typed and
        # retry identically — one integrity event per rank, bit-equal
        assert f"AUDIT_OK pid={i} fp=" in out, out[-2000:]
