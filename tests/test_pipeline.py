"""Pipelined chunked execution (C9 analog, exec/pipeline.py): chunked
streaming join must equal the monolithic operator, chunk decomposition must
re-cover the table, and per-chunk capacities must stay bounded (the memory
property that lets oversized joins run at all)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.exec import chunk_table, pipelined_join
from cylon_tpu.relational import (concat_tables, groupby_aggregate,
                                  join_tables)

from utils import assert_table_matches


@pytest.fixture(params=["env1", "env4", "env8"])
def env(request):
    return request.getfixturevalue(request.param)


def test_chunks_recover_table(env, rng):
    df = pd.DataFrame({"k": rng.integers(0, 40, 333),
                       "s": rng.choice(["a", "b", "c"], 333),
                       "v": rng.random(333)})
    df.loc[df.index % 11 == 0, "v"] = None
    t = ct.Table.from_pandas(df, env)
    chunks = chunk_table(t, 4)
    assert sum(c.row_count for c in chunks) == t.row_count
    back = concat_tables(chunks)
    # per-shard chunk order re-covers each shard's prefix => global rows
    # are a permutation; compare as multisets
    assert_table_matches(back, df)


@pytest.mark.parametrize("how", ["inner", "left"])
@pytest.mark.parametrize("n_chunks", [2, 5])
def test_pipelined_join_matches_monolithic(env, rng, how, n_chunks):
    n = 4000
    ldf = pd.DataFrame({"k": rng.integers(0, 300, n), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 300, n // 2),
                        "b": rng.random(n // 2)})
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    out = pipelined_join(lt, rt, "k", "k", how=how, n_chunks=n_chunks)
    exp = ldf.merge(rdf, on="k", how=how)
    assert out.row_count == len(exp)
    assert_table_matches(out, exp)


def test_chunked_capacity_bounded(env8, rng):
    """Each chunk's join materializes at ~1/C of the monolithic output
    capacity — the memory bound that lets oversized joins run."""
    n = 8000
    ldf = pd.DataFrame({"k": rng.integers(0, 50, n), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 50, n // 4),
                        "b": rng.random(n // 4)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    mono = join_tables(lt, rt, "k", "k")
    chunks = chunk_table(lt, 8)
    assert max(c.capacity for c in chunks) <= -(-lt.capacity // 8)
    out = pipelined_join(lt, rt, "k", "k", n_chunks=8)
    assert out.row_count == mono.row_count


def test_pipelined_groupby_sink_combines(env4, rng):
    """Streaming aggregation: per-chunk groupby sink + one partial combine
    equals the monolithic join+groupby (the out-of-HBM recipe that
    scripts/bench_pipelined.py runs at 96M rows/chip)."""
    n = 4000
    ldf = pd.DataFrame({"k": rng.integers(0, 300, n),
                        "a": rng.integers(0, 50, n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 300, n // 2),
                        "b": rng.integers(0, 50, n // 2)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    parts = pipelined_join(
        lt, rt, "k", "k", n_chunks=3,
        sink=lambda c: groupby_aggregate(c, "k", [("a", "sum"),
                                                  ("b", "sum")]))
    partial = concat_tables(parts)
    got = groupby_aggregate(partial, "k", [("a_sum", "sum"),
                                           ("b_sum", "sum")])
    exp = (ldf.merge(rdf, on="k").groupby("k", as_index=False)
           .agg(a_sum_sum=("a", "sum"), b_sum_sum=("b", "sum")))
    assert_table_matches(got, exp)
