"""Pipelined chunked execution (C9 analog, exec/pipeline.py): chunked
streaming join must equal the monolithic operator, chunk decomposition must
re-cover the table, and per-chunk capacities must stay bounded (the memory
property that lets oversized joins run at all)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.exec import chunk_table, pipelined_join
from cylon_tpu.relational import (concat_tables, groupby_aggregate,
                                  join_tables)

from utils import assert_table_matches


@pytest.fixture(params=["env1", "env4", "env8"])
def env(request):
    return request.getfixturevalue(request.param)


def test_chunks_recover_table(env, rng):
    df = pd.DataFrame({"k": rng.integers(0, 40, 333),
                       "s": rng.choice(["a", "b", "c"], 333),
                       "v": rng.random(333)})
    df.loc[df.index % 11 == 0, "v"] = None
    t = ct.Table.from_pandas(df, env)
    chunks = chunk_table(t, 4)
    assert sum(c.row_count for c in chunks) == t.row_count
    back = concat_tables(chunks)
    # per-shard chunk order re-covers each shard's prefix => global rows
    # are a permutation; compare as multisets
    assert_table_matches(back, df)


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
@pytest.mark.parametrize("n_chunks", [2, 5])
def test_pipelined_join_matches_monolithic(env, rng, how, n_chunks):
    n = 4000
    ldf = pd.DataFrame({"k": rng.integers(0, 300, n), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(100, 400, n // 2),
                        "b": rng.random(n // 2)})
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    out = pipelined_join(lt, rt, "k", "k", how=how, n_chunks=n_chunks)
    exp = ldf.merge(rdf, on="k", how=how)
    assert out.row_count == len(exp)
    assert_table_matches(out, exp)


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_pipelined_join_null_and_string_keys(env4, rng, how):
    """Range partitioning must keep null-key and dictionary-coded string
    groups intact (splitter operands include the null flags, so a null
    run snaps to one range like any other key group)."""
    n = 1500
    ldf = pd.DataFrame({"k": rng.choice(["ant", "bee", "cow", "dog", "elk"],
                                        n).astype(object),
                        "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.choice(["bee", "cow", "dog", "fox"],
                                        n // 2).astype(object),
                        "b": rng.random(n // 2)})
    ldf.loc[ldf.index % 7 == 0, "k"] = None
    rdf.loc[rdf.index % 5 == 0, "k"] = None
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    out = pipelined_join(lt, rt, "k", "k", how=how, n_chunks=3)
    exp = ldf.merge(rdf, on="k", how=how)
    assert out.row_count == len(exp)
    assert_table_matches(out, exp)


def test_pipelined_join_exact_capacity_max_key(env1, rng):
    """Regression (round-4 review): when a shard's valid count EQUALS its
    capacity there is no padding row to serve as the +inf splitter
    sentinel; the boundary gather must not fall back to the last live key
    or probe rows holding the shard's max key silently lose matches.
    Single-key build at an exact pow2 row count is the worst case (every
    candidate position lands inside the one run)."""
    n = 4096  # == pow2 capacity at world 1
    ldf = pd.DataFrame({"k": np.full(n, 7, np.int64), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": np.full(n, 7, np.int64), "b": rng.random(n)})
    lt = ct.Table.from_pandas(ldf, env1)
    rt = ct.Table.from_pandas(rdf, env1)
    assert rt.capacity == rt.row_count  # the no-padding premise
    out = pipelined_join(lt, rt, "k", "k", n_chunks=4)
    assert out.row_count == n * n


@pytest.mark.parametrize("how", ["inner", "outer"])
def test_pipelined_join_multi_key(env4, rng, how):
    n = 2000
    ldf = pd.DataFrame({"k1": rng.integers(0, 30, n),
                        "k2": rng.integers(0, 9, n),
                        "a": rng.random(n)})
    rdf = pd.DataFrame({"k1": rng.integers(0, 30, n // 2),
                        "k2": rng.integers(0, 9, n // 2),
                        "b": rng.random(n // 2)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    out = pipelined_join(lt, rt, ["k1", "k2"], ["k1", "k2"], how=how,
                         n_chunks=4)
    exp = ldf.merge(rdf, on=["k1", "k2"], how=how)
    assert out.row_count == len(exp)
    assert_table_matches(out, exp)


def test_chunked_capacity_bounded(env8, rng):
    """Each chunk's join materializes at ~1/C of the monolithic output
    capacity — the memory bound that lets oversized joins run."""
    n = 8000
    ldf = pd.DataFrame({"k": rng.integers(0, 50, n), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 50, n // 4),
                        "b": rng.random(n // 4)})
    lt = ct.Table.from_pandas(ldf, env8)
    rt = ct.Table.from_pandas(rdf, env8)
    mono = join_tables(lt, rt, "k", "k")
    chunks = chunk_table(lt, 8)
    assert max(c.capacity for c in chunks) <= -(-lt.capacity // 8)
    out = pipelined_join(lt, rt, "k", "k", n_chunks=8)
    assert out.row_count == mono.row_count


def test_pipelined_groupby_sink_combines(env4, rng):
    """Streaming aggregation: per-chunk groupby sink + one partial combine
    equals the monolithic join+groupby (the out-of-HBM recipe that
    scripts/bench_pipelined.py runs at 96M rows/chip)."""
    n = 4000
    ldf = pd.DataFrame({"k": rng.integers(0, 300, n),
                        "a": rng.integers(0, 50, n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 300, n // 2),
                        "b": rng.integers(0, 50, n // 2)})
    lt = ct.Table.from_pandas(ldf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    parts = pipelined_join(
        lt, rt, "k", "k", n_chunks=3,
        sink=lambda c: groupby_aggregate(c, "k", [("a", "sum"),
                                                  ("b", "sum")]))
    partial = concat_tables(parts)
    got = groupby_aggregate(partial, "k", [("a_sum", "sum"),
                                           ("b_sum", "sum")])
    exp = (ldf.merge(rdf, on="k").groupby("k", as_index=False)
           .agg(a_sum_sum=("a", "sum"), b_sum_sum=("b", "sum")))
    assert_table_matches(got, exp)


class TestGroupBySink:
    def test_sink_matches_monolithic(self, env4, rng):
        import cylon_tpu as ct
        from cylon_tpu.exec import GroupBySink, pipelined_join
        from cylon_tpu.relational import groupby_aggregate, join_tables
        n = 8000
        ldf = pd.DataFrame({"k": rng.integers(0, 900, n).astype(np.int64),
                            "a": rng.integers(0, 50, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(0, 900, n).astype(np.int64),
                            "b": rng.integers(0, 50, n).astype(np.int64)})
        lt, rt = ct.Table.from_pandas(ldf, env4), ct.Table.from_pandas(rdf, env4)
        aggs = [("a", "sum"), ("b", "mean"), ("a", "min"), ("b", "max"),
                ("a", "count"), ("b", "var"), ("a", "std")]
        sink = GroupBySink("k", aggs)
        pipelined_join(lt, rt, "k", "k", n_chunks=5, sink=sink)
        got = sink.finalize().to_pandas().sort_values("k").reset_index(drop=True)
        mono = groupby_aggregate(join_tables(lt, rt, "k", "k"), "k", aggs)
        exp = mono.to_pandas().sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)

    def test_sink_var_overlapping_chunks(self, env4, rng):
        """var/std must combine across chunks that SHARE keys (the sumsq
        partial path, no key-disjoint shortcut): feed overlapping chunks
        by hand."""
        from cylon_tpu.exec import GroupBySink
        import cylon_tpu as ct
        df = pd.DataFrame({"k": rng.integers(0, 40, 3000).astype(np.int64),
                           "v": rng.random(3000)})
        sink = GroupBySink("k", [("v", "var"), ("v", "std"), ("v", "mean")])
        for lo, hi in ((0, 1000), (1000, 2600), (2600, 3000)):
            sink(ct.Table.from_pandas(df.iloc[lo:hi], env4))
        got = sink.finalize().to_pandas().sort_values("k") \
            .reset_index(drop=True)
        exp = (df.groupby("k", as_index=False)
               .agg(v_var=("v", "var"), v_std=("v", "std"),
                    v_mean=("v", "mean")))
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-9)

    def test_sink_rejects_nonstreaming_op(self):
        from cylon_tpu.exec import GroupBySink
        from cylon_tpu.status import InvalidError
        with pytest.raises(InvalidError):
            GroupBySink("k", [("a", "nunique")])


class TestOOMFallback:
    def _data(self, env, rng, n=6000):
        import cylon_tpu as ct
        ldf = pd.DataFrame({"k": rng.integers(0, 700, n).astype(np.int64),
                            "a": rng.integers(0, 50, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(0, 700, n).astype(np.int64),
                            "b": rng.integers(0, 50, n).astype(np.int64)})
        return (ldf, rdf, ct.Table.from_pandas(ldf, env),
                ct.Table.from_pandas(rdf, env))

    def test_join_oom_falls_back_to_pipeline(self, env4, rng, monkeypatch):
        from cylon_tpu.relational import join as rj
        ldf, rdf, lt, rt = self._data(env4, rng)
        calls = {"n": 0}
        orig = rj._join_tables_impl

        def flaky(*a, **k):
            # OOM on the top-level attempt; chunk joins (assume_colocated)
            # succeed
            if not k.get("assume_colocated") and len(a) < 8:
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
            return orig(*a, **k)

        monkeypatch.setattr(rj, "_join_tables_impl", flaky)
        j = rj.join_tables(lt, rt, "k", "k", how="inner")
        got = j.to_pandas().sort_values(["k", "a", "b"]).reset_index(drop=True)
        exp = ldf.merge(rdf, on="k").sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)

    def test_groupby_oom_falls_back_to_chunked(self, env4, rng, monkeypatch):
        import cylon_tpu as ct
        from cylon_tpu.relational import groupby as rg
        ldf, rdf, lt, rt = self._data(env4, rng)
        t = ct.Table.from_pandas(ldf, env4)
        calls = {"n": 0}
        orig = rg._groupby_aggregate_impl

        def flaky(table, by, aggs, ddof=1):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
            return orig(table, by, aggs, ddof)

        monkeypatch.setattr(rg, "_groupby_aggregate_impl", flaky)
        g = rg.groupby_aggregate(t, "k", [("a", "sum"), ("a", "mean")])
        got = g.to_pandas().sort_values("k").reset_index(drop=True)
        exp = (ldf.groupby("k", as_index=False)
               .agg(a_sum=("a", "sum"), a_mean=("a", "mean")))
        exp.columns = got.columns
        pd.testing.assert_frame_equal(got, exp.sort_values("k")
                                      .reset_index(drop=True),
                                      check_dtype=False, rtol=1e-12)
        assert calls["n"] > 1

    def test_groupby_var_oom_falls_back(self, env4, rng, monkeypatch):
        """var/std now stream through the sumsq partial — the OOM fallback
        covers them (round-3 verdict gap: can_fallback was False)."""
        import cylon_tpu as ct
        from cylon_tpu.relational import groupby as rg
        ldf, _, _, _ = self._data(env4, rng)
        t = ct.Table.from_pandas(ldf, env4)
        calls = {"n": 0}
        orig = rg._groupby_aggregate_impl

        def flaky(table, by, aggs, ddof=1):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
            return orig(table, by, aggs, ddof)

        monkeypatch.setattr(rg, "_groupby_aggregate_impl", flaky)
        g = rg.groupby_aggregate(t, "k", [("a", "var"), ("a", "std")])
        got = g.to_pandas().sort_values("k").reset_index(drop=True)
        exp = (ldf.groupby("k", as_index=False)
               .agg(a_var=("a", "var"), a_std=("a", "std")))
        exp.columns = got.columns
        pd.testing.assert_frame_equal(got, exp.sort_values("k")
                                      .reset_index(drop=True),
                                      check_dtype=False, rtol=1e-9)
        assert calls["n"] > 1


class TestPipelinedSetOps:
    @pytest.mark.parametrize("op", ["union", "intersect", "subtract"])
    @pytest.mark.parametrize("world", ["env1", "env4"])
    def test_matches_monolithic(self, op, world, request, rng):
        import cylon_tpu as ct
        from cylon_tpu.exec import pipelined_set_op
        from cylon_tpu.relational import set_operation
        env = request.getfixturevalue(world)
        adf = pd.DataFrame({"k": rng.integers(0, 120, 3000).astype(np.int64),
                            "v": rng.integers(0, 4, 3000).astype(np.int64)})
        bdf = pd.DataFrame({"k": rng.integers(0, 120, 900).astype(np.int64),
                            "v": rng.integers(0, 4, 900).astype(np.int64)})
        at, bt = ct.Table.from_pandas(adf, env), ct.Table.from_pandas(bdf, env)
        got = pipelined_set_op(at, bt, op, n_chunks=3).to_pandas()
        exp = set_operation(at, bt, op).to_pandas()
        key = ["k", "v"]
        got = got.sort_values(key).reset_index(drop=True)
        exp = exp.sort_values(key).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_setop_oom_falls_back(self, env4, rng, monkeypatch):
        import cylon_tpu as ct
        from cylon_tpu.relational import setops as rs
        adf = pd.DataFrame({"k": rng.integers(0, 80, 2000).astype(np.int64)})
        bdf = pd.DataFrame({"k": rng.integers(0, 80, 500).astype(np.int64)})
        at, bt = ct.Table.from_pandas(adf, env4), ct.Table.from_pandas(bdf, env4)
        calls = {"n": 0}
        orig = rs._set_operation_impl

        def flaky(a, b, op, assume_colocated=False):
            calls["n"] += 1
            if calls["n"] == 1 and not assume_colocated:
                raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
            return orig(a, b, op, assume_colocated)

        # pipelined_set_op resolves _set_operation_impl at call time from
        # the setops module, so this single patch covers both paths
        monkeypatch.setattr(rs, "_set_operation_impl", flaky)
        got = rs.set_operation(at, bt, "subtract").to_pandas()
        A, B = adf.drop_duplicates(), bdf.drop_duplicates()
        exp = A.merge(B, on="k", how="left", indicator=True)
        exp = exp[exp._merge == "left_only"][["k"]]
        assert sorted(got["k"].tolist()) == sorted(exp["k"].tolist())
        assert calls["n"] > 1


class TestPackedPieces:
    """The packed-piece join entry (relational/piece.py + join.py packed
    programs): window slice + lane unpack fused into the join program.
    Contract: EXACTLY equal — same rows, same order, same bits — to the
    seed's materialize-then-join path."""

    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_packed_equals_materialized_exactly(self, env4, rng, how):
        n = 3000
        ldf = pd.DataFrame({
            "k": rng.integers(0, 200, n).astype(np.int64),
            "a": rng.random(n),                              # f64 side col
            "c": rng.integers(0, 9, n).astype(np.int32),
            "s": rng.choice(["x", "y", "z"], n).astype(object)})
        rdf = pd.DataFrame({"k": rng.integers(50, 260, n // 2).astype(np.int64),
                            "b": rng.random(n // 2)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        prev = config.PACKED_PIECES
        try:
            config.PACKED_PIECES = True
            got = pipelined_join(lt, rt, "k", "k", how=how,
                                 n_chunks=4).to_pandas()
            config.PACKED_PIECES = False
            ref = pipelined_join(lt, rt, "k", "k", how=how,
                                 n_chunks=4).to_pandas()
        finally:
            config.PACKED_PIECES = prev
        # exact: both paths must produce identical rows in identical order
        pd.testing.assert_frame_equal(got, ref, check_exact=True)
        exp = ldf.merge(rdf, on="k", how=how)
        assert len(got) == len(exp)

    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_donation_and_pallas_probe_bit_equal(self, env4, rng, how):
        """Buffer donation (CYLON_TPU_DONATE), the overlap scheduler
        (CYLON_TPU_PACKED_OVERLAP) and the Pallas probe kernel
        (CYLON_TPU_PALLAS_PROBE, interpreter mode on CPU) must each be
        EXACTLY equal — same rows, same order, same bits — to the plain
        per-phase-sync, no-donation dispatch."""
        from cylon_tpu.ops import pallas_probe
        n = 4096  # per-shard capacity 1024: Pallas tile-aligned
        ldf = pd.DataFrame({
            "k": rng.integers(0, 300, n).astype(np.int64),
            "a": rng.random(n),                              # f64 side col
            "s": rng.choice(["x", "y", "z"], n).astype(object)})
        rdf = pd.DataFrame({"k": rng.integers(100, 400, n).astype(np.int64),
                            "b": rng.random(n)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        prev = (config.PACKED_OVERLAP, config.DONATE_BUFFERS,
                config.PALLAS_PROBE)
        probed = []
        orig_supported = pallas_probe.supported

        def spy(cap, n_split, kinds):
            ok = orig_supported(cap, n_split, kinds)
            probed.append(ok)
            return ok

        try:
            config.PACKED_OVERLAP = False
            config.DONATE_BUFFERS = False
            config.PALLAS_PROBE = False
            ref = pipelined_join(lt, rt, "k", "k", how=how,
                                 n_chunks=3).to_pandas()
            config.PACKED_OVERLAP = True
            config.DONATE_BUFFERS = True
            got = pipelined_join(lt, rt, "k", "k", how=how,
                                 n_chunks=3).to_pandas()
            pd.testing.assert_frame_equal(got, ref, check_exact=True)
            config.PALLAS_PROBE = True
            pallas_probe.supported = spy
            got = pipelined_join(lt, rt, "k", "k", how=how,
                                 n_chunks=3).to_pandas()
            pd.testing.assert_frame_equal(got, ref, check_exact=True)
        finally:
            pallas_probe.supported = orig_supported
            (config.PACKED_OVERLAP, config.DONATE_BUFFERS,
             config.PALLAS_PROBE) = prev
        # the eligibility gate must have actually routed the probe
        # through the kernel — a silent fallback would make the pallas
        # leg of this test vacuous
        assert probed == [True]
        exp = ldf.merge(rdf, on="k", how=how)
        assert len(got) == len(exp)

    def test_pallas_probe_kernel_wide_operand_bit_equal(self, rng):
        """Kernel-level bit-equality over the operand shapes the narrow
        single-lane join test can't reach: a MULTI-operand key whose lo
        lane is uint32 (the wide-int64 (hi int32, lo uint32) pack pair —
        ops/pack) with values straddling the 0x80000000 rebase boundary
        and hi-lane ties forcing the lexicographic eq-chain."""
        import jax.numpy as jnp
        from cylon_tpu.ops import pack, pallas_probe
        cap, nsplit = 2048, 13
        hi = rng.integers(-3, 3, cap).astype(np.int32)   # heavy ties
        lo = rng.integers(0, 2**32, cap, dtype=np.uint64).astype(np.uint32)
        lo[:64] = np.uint32(0x80000000)                  # rebase boundary
        lo[64:128] = np.uint32(0x7FFFFFFF)
        live = np.ones(cap, np.int32)
        sel = rng.integers(0, cap, nsplit)
        kinds = ("i", "i", "i")
        assert pallas_probe.supported(cap, nsplit, kinds)
        ops = (jnp.asarray(live), jnp.asarray(hi), jnp.asarray(lo))
        sops = (jnp.asarray(live[sel]), jnp.asarray(hi[sel]),
                jnp.asarray(lo[sel]))
        ge = pack.rows_ge_splitters(pack.KeyOps(ops=ops, kinds=kinds), sops)
        ref = jnp.sum(ge, axis=1, dtype=jnp.int32)
        got = pallas_probe.count_ge_splitters(ops, sops)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_pallas_probe_wide_int64_keys_bit_equal(self, env4, rng):
        """End-to-end: wide int64 keys (bounds past int32, negatives
        included) pack as TWO value operands per key — the Pallas probe
        must engage (eligibility spy) and stay bit-equal to the XLA
        matrix path through the full pipelined join."""
        from cylon_tpu.ops import pallas_probe
        n = 4096
        pool = rng.integers(-2**62, 2**62, 300, dtype=np.int64)
        ldf = pd.DataFrame({"k": rng.choice(pool, n),
                            "a": rng.integers(0, 50, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.choice(pool, n // 2),
                            "b": rng.random(n // 2)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        prev = config.PALLAS_PROBE
        probed = []
        orig_supported = pallas_probe.supported

        def spy(cap, n_split, kinds):
            ok = orig_supported(cap, n_split, kinds)
            probed.append(ok)
            return ok

        try:
            config.PALLAS_PROBE = False
            ref = pipelined_join(lt, rt, "k", "k", how="inner",
                                 n_chunks=3).to_pandas()
            config.PALLAS_PROBE = True
            pallas_probe.supported = spy
            got = pipelined_join(lt, rt, "k", "k", how="inner",
                                 n_chunks=3).to_pandas()
        finally:
            pallas_probe.supported = orig_supported
            config.PALLAS_PROBE = prev
        assert probed == [True]
        pd.testing.assert_frame_equal(got, ref, check_exact=True)
        assert len(got) == len(ldf.merge(rdf, on="k", how="inner"))

    def test_overlap_one_host_sync_per_piece(self, env4, rng):
        """Acceptance: under the overlap scheduler the range loop costs
        at most ONE sanctioned host pull per piece (the transfer funnel's
        ledger is the counter), and disabling overlap restores the
        per-phase pulls (strictly more) — the escape hatch contract."""
        from cylon_tpu.analysis import runtime
        n = 4096
        lt = ct.Table.from_pydict(
            {"k": rng.integers(0, 2000, n).astype(np.int64),
             "a": rng.integers(0, 50, n).astype(np.int64)}, env4)
        rt = ct.Table.from_pydict(
            {"k": rng.integers(0, 2000, n).astype(np.int64),
             "b": rng.integers(0, 50, n).astype(np.int64)}, env4)

        def pulls(nc, overlap):
            prev = config.PACKED_OVERLAP
            config.PACKED_OVERLAP = overlap
            try:
                with runtime.transfer_scope() as ledger:
                    pipelined_join(lt, rt, "k", "k", how="inner",
                                   n_chunks=nc)
                return sum(ledger.values())
            finally:
                config.PACKED_OVERLAP = prev

        p3, p6 = pulls(3, True), pulls(6, True)
        # dense uniform keys: every range qualifies, pieces == n_chunks.
        # marginal host syncs per extra piece <= 1
        assert p6 - p3 <= 3, (p3, p6)
        # the one batched pre-loop sync beats the per-phase pulls
        assert p3 < pulls(3, False)

    def test_packed_join_defers_with_lazy_counts(self, env4, rng):
        """A packed inner join with allow_defer hands back a DeferredTable
        whose output counts stay ON DEVICE until someone asks — the piece
        loop enqueues the next piece's programs before this one's host
        sync.  Materialization must still be exact."""
        from cylon_tpu.core.table import DeferredTable
        from cylon_tpu.relational.piece import PieceSource
        from cylon_tpu.relational.join import join_tables as jt
        from cylon_tpu.relational.sort import local_sort_table
        n = 2000
        ldf = pd.DataFrame({"k": rng.integers(0, 150, n).astype(np.int64),
                            "a": rng.integers(0, 50, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(0, 150, n).astype(np.int64),
                            "b": rng.integers(0, 50, n).astype(np.int64)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        from cylon_tpu.relational.repart import shuffle_table
        lw = shuffle_table(lt, ["k"])
        rw = shuffle_table(rt, ["k"])
        ls = local_sort_table(lw, ["k"])
        rs = local_sort_table(rw, ["k"])
        src_l = PieceSource(ls, 0)
        src_r = PieceSource(rs, 0)
        w = env4.world_size
        zl = np.zeros(w, np.int64)
        pl = src_l.packed(zl, np.asarray(ls.valid_counts), ls.capacity)
        pr = src_r.packed(zl, np.asarray(rs.valid_counts), rs.capacity)
        out = jt(pl, pr, ["k"], ["k"], how="inner", allow_defer=True)
        assert isinstance(out, DeferredTable) and not out.materialized
        # counts pull on demand; materialization equals the reference join
        ref = jt(lw, rw, ["k"], ["k"], how="inner", assume_colocated=True,
                 allow_defer=False)
        assert out.row_count == ref.row_count
        got = out.to_pandas().sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        exp = ref.to_pandas().sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_exact=True)


class TestRangeBoundsSentinel:
    """_range_bounds_fn's +inf sentinel edge: a build shard whose live
    prefix is exactly at capacity (n == cap) has NO padding row to serve
    as the boundary sentinel — the explicit sentinel slot must make
    boundary operands read +infinity, or probe rows holding the shard's
    max key silently lose matches (round-4 regression, now for all four
    join types)."""

    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_exact_capacity_all_hows(self, env1, rng, how):
        n = 4096  # == pow2 capacity at world 1
        bdf = pd.DataFrame({"k": np.full(n, 7, np.int64),
                            "b": rng.random(n)})
        # probe: the build's max key (must hit all n rows) + a key beyond
        # it (must route to the last range, not vanish past the end)
        pdf = pd.DataFrame({"k": np.where(np.arange(96) % 2 == 0, 7, 9)
                            .astype(np.int64),
                            "a": rng.random(96)})
        lt = ct.Table.from_pandas(pdf, env1)
        rt = ct.Table.from_pandas(bdf, env1)
        assert rt.capacity == rt.row_count  # the no-padding premise
        out = pipelined_join(lt, rt, "k", "k", how=how, n_chunks=4)
        exp = pdf.merge(bdf, on="k", how=how)
        assert out.row_count == len(exp)
        assert_table_matches(out, exp)

    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_no_qualifying_range_fallback(self, env1, how):
        """With a 2-row build, range 0 snaps empty and all probe keys
        (below the build's min) route there — for inner no range
        qualifies at all (the outs == [] fallback); every how must keep
        the uniform output schema and exact pandas semantics."""
        bdf = pd.DataFrame({"k": np.array([10, 20], np.int64),
                            "b": [1.0, 2.0]})
        pdf = pd.DataFrame({"k": np.array([1, 2, 3], np.int64),
                            "a": [0.1, 0.2, 0.3]})
        lt = ct.Table.from_pandas(pdf, env1)
        rt = ct.Table.from_pandas(bdf, env1)
        out = pipelined_join(lt, rt, "k", "k", how=how, n_chunks=4)
        exp = pdf.merge(bdf, on="k", how=how)
        assert out.row_count == len(exp)
        assert list(out.column_names) == ["k", "a", "b"]
        if len(exp):
            assert_table_matches(out, exp)


class TestGroupBySinkHows:
    """pipelined_join(..., sink=GroupBySink) must match the monolithic
    join→groupby for every streaming join type, not just inner — and both
    with the key-disjoint fast path (sink keyed on the join keys) and
    without it (sink keyed on a payload column, cross-chunk combine)."""

    def _data(self, env, rng, n=3000):
        ldf = pd.DataFrame({"k": rng.integers(0, 250, n).astype(np.int64),
                            "g": rng.integers(0, 7, n).astype(np.int64),
                            "a": rng.integers(0, 50, n).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(100, 350, n // 2)
                            .astype(np.int64),
                            "b": rng.integers(0, 50, n // 2)
                            .astype(np.int64)})
        return (ldf, rdf, ct.Table.from_pandas(ldf, env),
                ct.Table.from_pandas(rdf, env))

    @pytest.mark.parametrize("how", ["left", "right", "outer"])
    def test_sink_matches_monolithic(self, env4, rng, how):
        from cylon_tpu.exec import GroupBySink
        _ldf, _rdf, lt, rt = self._data(env4, rng)
        aggs = [("a", "sum"), ("b", "mean"), ("b", "count")]
        sink = GroupBySink("k", aggs)
        pipelined_join(lt, rt, "k", "k", how=how, n_chunks=4, sink=sink)
        assert sink._disjoint  # keyed on the join keys: fast path taken
        got = sink.finalize().to_pandas().sort_values("k") \
            .reset_index(drop=True)
        mono = groupby_aggregate(
            join_tables(lt, rt, "k", "k", how=how), "k", aggs)
        exp = mono.to_pandas().sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                      rtol=1e-9)

    @pytest.mark.parametrize("how", ["inner", "outer"])
    def test_sink_non_key_by_combines_across_chunks(self, env4, rng, how):
        """by != join keys: groups SPAN chunks, so the cross-chunk combine
        (no disjoint shortcut) must run and still match the monolith."""
        from cylon_tpu.exec import GroupBySink
        _ldf, _rdf, lt, rt = self._data(env4, rng)
        aggs = [("a", "sum"), ("b", "mean")]
        sink = GroupBySink("g", aggs)
        pipelined_join(lt, rt, "k", "k", how=how, n_chunks=4, sink=sink)
        assert not sink._disjoint
        got = sink.finalize().to_pandas().sort_values("g") \
            .reset_index(drop=True)
        mono = groupby_aggregate(
            join_tables(lt, rt, "k", "k", how=how), "g", aggs)
        exp = mono.to_pandas().sort_values("g").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                      rtol=1e-9)


class TestLazyChunks:
    def test_sequence_protocol(self, env4, rng):
        df = pd.DataFrame({"k": rng.integers(0, 40, 500),
                           "v": rng.random(500)})
        t = ct.Table.from_pandas(df, env4)
        chunks = chunk_table(t, 4)
        assert len(chunks) == 4
        assert chunks[-1].row_count == chunks[3].row_count
        assert [c.row_count for c in chunks[1:3]] == \
            [chunks[1].row_count, chunks[2].row_count]
        with pytest.raises(IndexError):
            chunks[4]
        # re-indexing re-dispatches the same slice (pure function of i)
        assert chunks[0].row_count == chunks[0].row_count
        assert sum(c.row_count for c in chunks) == t.row_count


def test_async_timing_mode_records_dispatch_only(env1, rng):
    """CYLON_TPU_TIMING=async: maybe_block is a no-op and regions record
    dispatch-only markers — the pipelined phases still appear in the
    snapshot, without the per-phase device syncs."""
    from cylon_tpu.utils import timing
    prev_bench, prev_async = config.BENCH_TIMINGS, config.TIMING_ASYNC
    df = pd.DataFrame({"k": rng.integers(0, 60, 800).astype(np.int64),
                       "a": rng.integers(0, 9, 800).astype(np.int64)})
    t = ct.Table.from_pandas(df, env1)
    try:
        config.BENCH_TIMINGS = True
        config.TIMING_ASYNC = True
        timing.reset()
        out = pipelined_join(t, t, "k", "k", n_chunks=3)
        snap = timing.snapshot()
    finally:
        config.BENCH_TIMINGS = prev_bench
        config.TIMING_ASYNC = prev_async
        timing.reset()
    assert out.row_count == len(df.merge(df, on="k"))
    assert "pipe.piece_join" in snap and snap["pipe.piece_join"]["n"] >= 1
    assert "pipe.build_sort" in snap


@pytest.mark.slow
class TestBenchSmoke:
    def test_smoke_dispatch_path(self, env4):
        """scripts/bench_smoke.py: the bench driver's pipelined sink path
        at a tiny shape — phase markers recorded, streamed result equals
        the monolith exactly (dispatch-path regressions surface here
        instead of in a TPU bench round)."""
        import os
        import sys
        scripts = os.path.join(os.path.dirname(__file__), "..", "scripts")
        sys.path.insert(0, scripts)
        try:
            from bench_smoke import EXPECTED_PHASES, run_smoke
        finally:
            # remove by value: importing bench_smoke itself prepends the
            # repo root to sys.path, so pop(0) would strip the wrong entry
            sys.path.remove(scripts)
        snap = run_smoke(env=env4, rows=16384, n_chunks=4)
        assert all(p in snap for p in EXPECTED_PHASES)

    def test_smoke_all_dispatch_rungs(self, env4):
        """The same tiny-shape path with ALL ISSUE-6 dispatch rungs
        pinned on — overlap scheduler + buffer donation + Pallas probe
        (interpreter mode on CPU): the three flag paths stay covered by
        tier-1, run_smoke itself asserts the phase_sync marker and that
        the Pallas eligibility gate engaged (no silent fallback)."""
        import os
        import sys
        scripts = os.path.join(os.path.dirname(__file__), "..", "scripts")
        sys.path.insert(0, scripts)
        try:
            from bench_smoke import run_smoke
        finally:
            sys.path.remove(scripts)
        snap = run_smoke(env=env4, rows=16384, n_chunks=4,
                         overlap=True, donate=True, pallas=True)
        assert "pipe.phase_sync.block" in snap
