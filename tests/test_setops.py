"""Set ops / unique / equals tests (reference cpp/test/set_op_test.cpp,
equal_test.cpp, python test_dist_rl.py analogs)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import equals, set_operation, unique_table

from utils import assert_frames_equal


def two(rng, na=60, nb=40, hi=25):
    a = pd.DataFrame({"k": rng.integers(0, hi, na),
                      "g": rng.integers(0, 3, na)})
    b = pd.DataFrame({"k": rng.integers(0, hi, nb),
                      "g": rng.integers(0, 3, nb)})
    return a, b


def pd_union(a, b):
    return pd.concat([a, b]).drop_duplicates().reset_index(drop=True)


def pd_intersect(a, b):
    ad = a.drop_duplicates()
    return ad.merge(b.drop_duplicates(), on=list(a.columns))


def pd_subtract(a, b):
    ad = a.drop_duplicates()
    m = ad.merge(b.drop_duplicates(), on=list(a.columns), how="left",
                 indicator=True)
    return m[m["_merge"] == "left_only"].drop(columns="_merge")


@pytest.mark.parametrize("envname", ["env1", "env4", "env8"])
@pytest.mark.parametrize("op,oracle", [("union", pd_union),
                                       ("intersect", pd_intersect),
                                       ("subtract", pd_subtract)])
def test_set_ops(request, rng, envname, op, oracle):
    env = request.getfixturevalue(envname)
    a, b = two(rng)
    ta = ct.Table.from_pandas(a, env)
    tb = ct.Table.from_pandas(b, env)
    got = set_operation(ta, tb, op).to_pandas()
    exp = oracle(a, b)
    assert_frames_equal(got, exp.reset_index(drop=True), sort_by=["k", "g"])


def test_set_ops_strings(env8, rng):
    a = pd.DataFrame({"s": rng.choice(["a", "b", "c", "d"], 40)})
    b = pd.DataFrame({"s": rng.choice(["c", "d", "e"], 30)})
    ta = ct.Table.from_pandas(a, env8)
    tb = ct.Table.from_pandas(b, env8)
    got = set_operation(ta, tb, "intersect").to_pandas()
    exp = pd_intersect(a, b)
    assert_frames_equal(got, exp.reset_index(drop=True), sort_by=["s"])


@pytest.mark.parametrize("envname", ["env1", "env8"])
@pytest.mark.parametrize("keep", ["first", "last"])
def test_unique(request, rng, envname, keep):
    env = request.getfixturevalue(envname)
    df = pd.DataFrame({"k": rng.integers(0, 10, 80), "v": np.arange(80)})
    t = ct.Table.from_pandas(df, env)
    got = unique_table(t, subset=["k"], keep=keep).to_pandas()
    exp = df.drop_duplicates(subset=["k"], keep=keep)
    assert_frames_equal(got, exp.reset_index(drop=True), sort_by=["k"])


def test_unique_all_columns(env8, rng):
    df = pd.DataFrame({"k": rng.integers(0, 5, 60),
                       "g": rng.integers(0, 2, 60)})
    t = ct.Table.from_pandas(df, env8)
    got = unique_table(t).to_pandas()
    exp = df.drop_duplicates()
    assert_frames_equal(got, exp.reset_index(drop=True), sort_by=["k", "g"])


@pytest.mark.parametrize("envname", ["env1", "env4", "env8"])
def test_equals(request, rng, envname):
    env = request.getfixturevalue(envname)
    df = pd.DataFrame({"k": rng.integers(0, 10, 50), "v": rng.random(50)})
    t1 = ct.Table.from_pandas(df, env)
    t2 = ct.Table.from_pandas(df.copy(), env)
    assert equals(t1, t2)
    df3 = df.copy()
    df3.loc[17, "v"] = -1.0
    t3 = ct.Table.from_pandas(df3, env)
    assert not equals(t1, t3)


def test_equals_unordered(env4, rng):
    df = pd.DataFrame({"k": rng.integers(0, 10, 50), "v": rng.random(50)})
    shuffled = df.sample(frac=1.0, random_state=1).reset_index(drop=True)
    t1 = ct.Table.from_pandas(df, env4)
    t2 = ct.Table.from_pandas(shuffled, env4)
    assert not equals(t1, t2, ordered=True)
    assert equals(t1, t2, ordered=False)


def test_equals_nan(env4):
    df = pd.DataFrame({"f": [1.0, np.nan, 3.0, np.nan]})
    t1 = ct.Table.from_pandas(df, env4)
    t2 = ct.Table.from_pandas(df.copy(), env4)
    assert equals(t1, t2)


def test_setop_mixed_nullability(env4, rng):
    """One side nullable, other not: operand structures must still align
    (need_null_flags union) — regression for the round-2 packing change."""
    import pandas as pd
    a = pd.DataFrame({"x": [1.0, None, 3.0, 4.0]})
    b = pd.DataFrame({"x": [3.0, 4.0, 5.0]})          # no nulls
    ta = ct.Table.from_pandas(a, env4)
    tb = ct.Table.from_pandas(b, env4)
    got = set_operation(ta, tb, "intersect").to_pandas()
    assert sorted(got["x"].tolist()) == [3.0, 4.0]
    got2 = set_operation(ta, tb, "subtract").to_pandas()
    vals = got2["x"].tolist()
    assert len(vals) == 2 and 1.0 in vals  # {1.0, null}
