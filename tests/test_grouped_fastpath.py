"""The grouped-input groupby fast path (join/sort output carries
``grouped_by``: boundary-flag group ids, no shuffle, no rank sort) must give
identical results to the general path — checked against the pandas oracle."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import groupby_aggregate, join_tables, sort_table

from utils import assert_table_matches


@pytest.fixture(params=["env1", "env4"])
def env(request):
    return request.getfixturevalue(request.param)


def test_join_then_groupby_matches_oracle(env, rng):
    n = 200
    ldf = pd.DataFrame({"k": rng.integers(0, 20, n), "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 20, n // 2),
                        "b": rng.random(n // 2)})
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    j = join_tables(lt, rt, "k", "k", how="inner")
    assert j.grouped_by == ("k",)
    g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "mean"),
                                   ("a", "count")])
    exp = (ldf.merge(rdf, on="k", how="inner")
           .groupby("k", as_index=False)
           .agg(a_sum=("a", "sum"), b_mean=("b", "mean"),
                a_count=("a", "count")))
    assert_table_matches(g, exp)


def test_sort_then_groupby_matches_oracle(env, rng):
    n = 300
    df = pd.DataFrame({"k": rng.integers(0, 12, n).astype(float),
                       "v": rng.standard_normal(n)})
    # sprinkle nulls into the key to hit the null-aware boundary compare
    df.loc[df.index % 17 == 0, "k"] = None
    t = ct.Table.from_pandas(df, env)
    s = sort_table(t, "k")
    assert s.grouped_by == ("k",)
    g = groupby_aggregate(s, "k", [("v", "sum"), ("v", "max")])
    exp = (df.groupby("k", as_index=False, dropna=False)
           .agg(v_sum=("v", "sum"), v_max=("v", "max")))
    assert_table_matches(g, exp)


def test_grouped_flag_cleared_by_other_ops(env):
    df = pd.DataFrame({"k": [1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0]})
    t = ct.Table.from_pandas(df, env)
    s = sort_table(t, "k")
    assert s.grouped_by == ("k",)
    # projection rebuilds a Table -> metadata conservatively dropped
    assert s.project(["k"]).grouped_by is None
    # groupby on different keys ignores the metadata
    g = groupby_aggregate(s, "v", [("k", "count")])
    assert g.row_count == 4


def test_float_keys_grouped_path_nan_and_negzero(env):
    df = pd.DataFrame({"k": [0.0, -0.0, 1.5, np.nan, np.nan, 1.5],
                       "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]})
    t = ct.Table.from_pandas(df, env)
    s = sort_table(t, "k")
    g = groupby_aggregate(s, "k", [("v", "sum")])
    exp = df.groupby("k", as_index=False, dropna=False).agg(
        v_sum=("v", "sum"))
    assert_table_matches(g, exp)


def test_narrow_key_join_matches_wide(env, rng):
    """int64 keys within int32 range pack to one sort operand — results must
    match a join on keys forced outside the narrow range."""
    n = 100
    base = rng.integers(0, 50, n)
    ldf = pd.DataFrame({"k": base, "a": rng.random(n)})
    rdf = pd.DataFrame({"k": rng.integers(0, 50, n), "b": rng.random(n)})
    lt = ct.Table.from_pandas(ldf, env)
    rt = ct.Table.from_pandas(rdf, env)
    j = join_tables(lt, rt, "k", "k", how="outer")
    exp = ldf.merge(rdf, on="k", how="outer")
    assert_table_matches(j, exp)
    # same data shifted beyond int32 -> wide (hi, lo) packing path
    big = np.int64(1) << 40
    ldf2 = ldf.assign(k=ldf.k + big)
    rdf2 = rdf.assign(k=rdf.k + big)
    j2 = join_tables(ct.Table.from_pandas(ldf2, env),
                     ct.Table.from_pandas(rdf2, env), "k", "k", how="outer")
    assert_table_matches(j2, ldf2.merge(rdf2, on="k", how="outer"))


def test_grouped_uint64_wide_keys_and_values(env4, rng):
    """uint64 keys/values beyond int32 range through the grouped fast path
    (regression: the u32 lane split must mask with the source dtype, and
    wide values must keep 2-lane sum prefixes)."""
    n = 256
    base = np.uint64(1) << np.uint64(33)
    kdf = pd.DataFrame({"k": (rng.integers(0, 6, n).astype(np.uint64) + base),
                        "a": rng.integers(0, 1 << 40, n).astype(np.uint64)})
    rdf = pd.DataFrame({"k": (rng.integers(0, 6, n // 2).astype(np.uint64)
                              + base),
                        "b": rng.integers(0, 100, n // 2).astype(np.uint64)})
    lt = ct.Table.from_pandas(kdf, env4)
    rt = ct.Table.from_pandas(rdf, env4)
    j = join_tables(lt, rt, "k", "k", how="inner")
    g = groupby_aggregate(j, "k", [("a", "sum"), ("b", "sum"),
                                   ("a", "count")])
    exp = (kdf.merge(rdf, on="k", how="inner")
           .groupby("k", as_index=False)
           .agg(a_sum=("a", "sum"), b_sum=("b", "sum"),
                a_count=("a", "count")))
    assert_table_matches(g, exp)
