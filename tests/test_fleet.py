"""Fleet survival under live traffic (ISSUE 18): preemptive
drain/requeue under ``priority``/``fair``, typed admission deadlines,
requeue-capacity overflow, per-tenant outcome accounting, and the
elastic mesh resize controller (exec/fleet) — acceptance: a preempted
tenant's answer stays BIT-EQUAL to its solo run, co-tenants' recovery
logs stay clean, and the unarmed happy path adds zero checkpoint
machinery."""

import os
import subprocess
import sys
import time

import pytest

from cylon_tpu.exec import checkpoint, fleet, memory, recovery, scheduler
from cylon_tpu.exec.scheduler import QueryScheduler
from cylon_tpu.exec.session import QuerySession
from cylon_tpu.status import (AdmissionTimeoutError, InvalidError,
                              RequeueOverflowError, ResumableAbort)
from test_scheduler import _pipe_fn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    recovery.install_faults("")
    recovery.reset_events()
    recovery.set_session(None, None)
    memory.reset_stats()
    checkpoint.reset_stats()
    checkpoint.reset_stages()
    scheduler.reset_family_history()
    yield
    recovery.install_faults("")
    recovery.reset_events()
    recovery.set_session(None, None)
    checkpoint.reset_stats()
    checkpoint.reset_stages()
    scheduler.reset_family_history()


class TestPreemption:
    def test_preempt_requeue_resume_bit_equal(self, env4, monkeypatch,
                                              tmp_path):
        """The tentpole's acceptance schedule: tB (priority 5) arrives
        while tA runs and preempts it at its next checkpoint boundary;
        tA requeues, fast-forwards its committed pieces on re-grant,
        gets preempted AGAIN by tB2 (after committing new pieces — the
        no-progress guard demands that), and still finishes bit-equal
        to its solo run.  tC shares the box untouched: its recovery
        event log stays empty (no cross-session contamination)."""
        solo_a = _pipe_fn(env4, 11, n=1800, chunks=6)()
        solo_b = _pipe_fn(env4, 22, n=900, chunks=2)()
        solo_c = _pipe_fn(env4, 33, n=1800, chunks=6)()
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        checkpoint.reset_stages()
        checkpoint.reset_stats()

        sched = QueryScheduler(env4, policy="priority",
                               max_concurrency=1)
        runs = {"n": 0}
        fn_a = _pipe_fn(env4, 11, n=1800, chunks=6)

        def tenant_a():
            # each replay submits the NEXT high-priority arrival — two
            # preemptions of tA, deterministically placed at its first
            # boundary after each (re)grant
            runs["n"] += 1
            if runs["n"] == 1:
                sched.submit("tB", _pipe_fn(env4, 22, n=900, chunks=2),
                             priority=5)
            elif runs["n"] == 2:
                sched.submit("tB2", _pipe_fn(env4, 22, n=900, chunks=2),
                             priority=5)
            return fn_a()

        a = sched.submit("tA", tenant_a)
        c = sched.submit("tC", _pipe_fn(env4, 33, n=1800, chunks=6))
        sched.run()

        b = next(s for s in sched.sessions if s.name == "tB")
        b2 = next(s for s in sched.sessions if s.name == "tB2")
        assert a.state == "done" and a.error is None, a.error
        assert a.preemptions == 2 and a.requeues == 2
        assert runs["n"] == 3                      # two replays
        # requeued replays FAST-FORWARD committed pieces, not recompute
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] > 0
        assert a.result.equals(solo_a), "tA diverged from its solo run"
        assert b.result.equals(solo_b) and b2.result.equals(solo_b)
        assert c.result.equals(solo_c)
        assert c.recovery_events() == []
        assert a.outcome() == "preempted_requeued"
        st = sched.stats()
        assert st["preemptions"] == 2 and st["requeues"] == 2
        assert st["outcomes"] == {"preempted_requeued": 1,
                                  "completed": 3}

    def test_no_progress_guard_and_budget(self, env1):
        """A tenant that committed nothing since its last preemption is
        temporarily unpreemptable (storm guard), and an exhausted
        preemption budget excludes it permanently."""
        sched = QueryScheduler(env1, policy="priority")
        cand = QuerySession("hi", lambda: None, 5, priority=9)
        v = QuerySession("lo", lambda: None, 0, priority=0)
        assert sched._pick_victim(cand, [v]) is v
        # preempted once, no new pieces since: guarded
        v.preemptions, v.pieces_committed, v._progress_mark = 1, 3, 3
        assert sched._pick_victim(cand, [v]) is None
        v.pieces_committed = 4                     # made progress
        assert sched._pick_victim(cand, [v]) is v
        v.preemptions = v.preempt_budget           # budget exhausted
        assert sched._pick_victim(cand, [v]) is None
        # a draining session is never re-picked
        v.preemptions, v._drain_mode = 0, "preempt"
        assert sched._pick_victim(cand, [v]) is None
        # an equal-ranked candidate never preempts (strict outrank)
        v2 = QuerySession("peer", lambda: None, 1, priority=9)
        assert sched._pick_victim(cand, [v2]) is None

    def test_requeue_overflow_typed(self, env4, monkeypatch, tmp_path):
        """With requeue capacity 0, a completed preempt drain cannot be
        requeued: the tenant fails TYPED (RequeueOverflowError) with the
        original resumable abort — resume token included — chained as
        __cause__, never silently dropped."""
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        checkpoint.reset_stages()
        sched = QueryScheduler(env4, policy="priority",
                               max_concurrency=1, requeue_capacity=0)
        runs = {"n": 0}
        fn_a = _pipe_fn(env4, 11, n=1800, chunks=6)

        def tenant_a():
            runs["n"] += 1
            if runs["n"] == 1:
                sched.submit("tB", _pipe_fn(env4, 22, n=900, chunks=2),
                             priority=5)
            return fn_a()

        a = sched.submit("tA", tenant_a)
        sched.run()
        b = next(s for s in sched.sessions if s.name == "tB")
        assert b.state == "done" and b.error is None
        assert a.state == "failed"
        assert isinstance(a.error, RequeueOverflowError)
        assert isinstance(a.error.__cause__, ResumableAbort)
        assert a.outcome() == "failed_typed"
        assert sched.stats()["requeue_overflows"] == 1

    def test_unarmed_happy_path_adds_nothing(self, env4):
        """No priorities, no resize controller, checkpointing unarmed:
        the serving loop must carry ZERO preemption machinery — no
        checkpoint events, no filesystem writes, no recovery events, no
        votes beyond the baseline admission path (the PR 10/11 unarmed
        contract, extended to the fleet tier)."""
        assert not checkpoint.enabled()
        checkpoint.reset_stats()
        recovery.reset_events()
        sched = QueryScheduler(env4, policy="fair")
        sched.submit("t0", _pipe_fn(env4, 11))
        sched.submit("t1", _pipe_fn(env4, 22))
        sched.run(raise_errors=True)
        assert all(v == 0 for v in checkpoint.stats().values()), \
            checkpoint.stats()
        assert recovery.recovery_events() == []
        st = sched.stats()
        assert st["preemptions"] == 0 and st["requeues"] == 0
        assert st["fleet_drains"] == 0 and st["resize_target"] is None
        assert st["admission_timeouts"] == 0
        assert st["outcomes"] == {"completed": 2}


class TestAdmissionDeadline:
    def test_admission_timeout_typed(self, env1):
        """A pending session whose admission wait exceeds the deadline
        fails TYPED — AdmissionTimeoutError carrying the session name
        and waited seconds — with its wait period closed; the running
        tenant is untouched."""
        def holder():
            for _ in range(12):
                time.sleep(0.02)
                scheduler.maybe_yield()
            return "done"

        sched = QueryScheduler(env1, policy="fifo", budget_bytes=1000,
                               admission_timeout_s=0.05)
        a = sched.submit("tA", holder, footprint_bytes=600)
        b = sched.submit("tB", lambda: 1, footprint_bytes=600)
        sched.run()
        assert a.state == "done" and a.result == "done"
        assert b.state == "failed"
        assert isinstance(b.error, AdmissionTimeoutError)
        assert b.error.kind == "admission_timeout"
        assert b.error.session == "tB" and b.error.waited_s > 0.05
        assert b._wait_mark is None and b.admission_wait_s > 0
        assert b.outcome() == "failed_typed"
        st = sched.stats()
        assert st["admission_timeouts"] == 1
        assert st["outcomes"] == {"completed": 1, "failed_typed": 1}

    def test_admission_timeout_env_knob(self, env1, monkeypatch):
        """CYLON_TPU_ADMISSION_TIMEOUT_S arms the same deadline without
        a constructor change (the chaos/deploy surface)."""
        monkeypatch.setenv("CYLON_TPU_ADMISSION_TIMEOUT_S", "0.04")
        sched = QueryScheduler(env1)
        assert sched._admission_timeout() == pytest.approx(0.04)
        monkeypatch.setenv("CYLON_TPU_ADMISSION_TIMEOUT_S", "bogus")
        assert sched._admission_timeout() is None
        monkeypatch.setenv("CYLON_TPU_ADMISSION_TIMEOUT_S", "0")
        assert sched._admission_timeout() is None


class TestResizeController:
    def test_rejects_bad_target(self, env1):
        with pytest.raises(InvalidError):
            fleet.ResizeController(env1, target_world=0)

    def test_gated_on_checkpoint(self, env1):
        """Without durable checkpointing there is nothing to resume
        from: the controller must never engage (a drain now would lose
        work — the one thing this tier must never do)."""
        assert not checkpoint.enabled()
        ctrl = fleet.ResizeController(env1, target_world=2,
                                      queue_depth_high=0,
                                      min_committed_pieces=0)
        sched = QueryScheduler(env1, fleet=ctrl)
        assert ctrl.maybe_resize(sched) is False
        assert not ctrl.engaged and not sched._fleet_drain

    def test_pressure_triggers_and_breadcrumb(self, env1, monkeypatch,
                                              tmp_path):
        """Queue-depth pressure + durable progress engage the all-or-
        nothing fleet drain: every running tenant is flagged, the
        resize target latches, and the FLEET_RESIZE.json breadcrumb
        lands in the checkpoint root for the relauncher."""
        import json
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        ctrl = fleet.ResizeController(env1, target_world=2,
                                      queue_depth_high=1,
                                      min_committed_pieces=1)
        sched = QueryScheduler(env1, fleet=ctrl)
        run = sched.submit("hot", lambda: None)
        run.state, run.pieces_committed = "running", 3
        queued = sched.submit("cold", lambda: None)     # depth 1
        assert ctrl.should_resize(sched)
        assert ctrl.maybe_resize(sched) is True
        assert ctrl.engaged and sched._fleet_drain
        assert sched.resize_target == 2
        assert run._drain_mode == "fleet"
        assert queued._drain_mode is None               # pending: not flagged
        crumb = json.load(open(tmp_path / "FLEET_RESIZE.json"))
        assert crumb["target_world"] == 2
        assert crumb["from_world"] == env1.world_size
        assert crumb["queue_depth"] == 1
        assert sched.stats()["fleet_drains"] == 1
        # idempotent: an engaged controller never re-votes
        assert ctrl.maybe_resize(sched) is False

    def test_min_committed_guard(self, env1, monkeypatch, tmp_path):
        """Resizing a fleet that has committed nothing durable is just
        a restart — the controller waits for real progress."""
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        ctrl = fleet.ResizeController(env1, target_world=2,
                                      queue_depth_high=0,
                                      min_committed_pieces=5)
        sched = QueryScheduler(env1, fleet=ctrl)
        assert not ctrl.should_resize(sched)
        assert ctrl.maybe_resize(sched) is False

    def test_fleet_drain_resume_bit_equal(self, env4, monkeypatch,
                                          tmp_path):
        """End-to-end elastic drain in-process: the controller engages
        mid-traffic, every tenant exits resumable (ZERO failed-typed),
        and a resumed scheduler pass finishes all of them bit-equal
        with fast-forwarded pieces.  (The cross-world 4->2 relaunch leg
        runs in scripts/chaos_soak.py --fleet.)"""
        solos = {s: _pipe_fn(env4, s, n=1800, chunks=6)()
                 for s in (11, 22, 33)}
        monkeypatch.setenv("CYLON_TPU_CKPT_DIR", str(tmp_path))
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        ctrl = fleet.ResizeController(env4, target_world=2,
                                      queue_depth_high=2)
        sched = QueryScheduler(env4, policy="fifo", max_concurrency=1,
                               fleet=ctrl)
        for i, s in enumerate((11, 22, 33)):
            sched.submit(f"t{i}", _pipe_fn(env4, s, n=1800, chunks=6))
        sched.run()
        assert sched.resize_target == 2
        st = sched.stats()
        assert st["outcomes"].get("failed_typed", 0) == 0
        assert all(s.outcome() in ("completed", "drained_resumable")
                   for s in sched.sessions)
        assert os.path.exists(tmp_path / "FLEET_RESIZE.json")

        # "relaunch" stand-in: resume in the same process
        monkeypatch.setenv("CYLON_TPU_RESUME", "1")
        checkpoint.reset_stages()
        checkpoint.reset_stats()
        sched2 = QueryScheduler(env4, policy="fifo", max_concurrency=1)
        for i, s in enumerate((11, 22, 33)):
            sched2.submit(f"t{i}", _pipe_fn(env4, s, n=1800, chunks=6))
        sched2.run(raise_errors=True)
        for i, s in enumerate((11, 22, 33)):
            assert sched2.sessions[i].result.equals(solos[s]), \
                f"t{i} diverged after the fleet drain resume"
        assert checkpoint.stats()["resume_fast_forwarded_pieces"] > 0


class TestFamilyHistory:
    def test_note_and_observe_peak(self):
        scheduler.reset_family_history()
        assert scheduler.observed_peak("mixA") is None
        scheduler.note_family_peak("mixA", 200)
        scheduler.note_family_peak("mixA", 150)     # max-update
        assert scheduler.observed_peak("mixA") == 200
        scheduler.note_family_peak("mixA", 500)
        assert scheduler.observed_peak("mixA") == 500


# ---------------------------------------------------------------------------
# acceptance drivers (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_fleet():
    """scripts/chaos_soak.py --fleet: the four pinned fleet schedules —
    preempt/requeue bit-equal, SIGKILL inside the preempt drain +
    resume, elastic 4->2 resize relaunch with zero failed tenants, and
    the typed admission deadline."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--fleet", "--rows", "2400", "--chunks", "4"],
        capture_output=True, text=True, timeout=560, cwd=REPO)
    assert p.returncode == 0, (p.stdout + p.stderr)[-4000:]
    assert '"failures": 0' in p.stdout


@pytest.mark.slow
def test_bench_serving_preemptive_64(tmp_path):
    """ISSUE 18 acceptance: the 64-tenant preemptive serving round
    (SERVING_r02 shape) — 8 high-priority arrivals against a running
    fleet, per-tenant p99 SLO attainment from the histogram registry,
    the per-tenant outcome table, and every answer bit-equal."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from bench_serving import run_serving
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))
    report = run_serving(tenants=64, queries=2, scale=0.004,
                         policy="priority", budget_mb="auto",
                         slo_ms=2000, preempt_tenants=8,
                         ckpt_dir=str(tmp_path))
    d = report["detail"]
    assert d["bit_equal"], d["failures"]
    assert not d["failures"]
    st = d["scheduler"]
    assert st["preemptions"] >= 1 and st["requeues"] >= 1
    assert st["outcomes"].get("failed_typed", 0) == 0
    assert sum(st["outcomes"].values()) == 64
    for name, info in d["tenants"].items():
        assert info["outcome"] in ("completed", "preempted_requeued")
        assert 0.0 <= info["slo_attainment"] <= 1.0
