"""Property-based cross-world fuzz: generator-driven sweep of
dtype x nulls x skew x world x operator against the pandas oracle.

The example-based suite pins known shapes; the bugs that survived past
rounds lived in INTERACTIONS (fused string-agg under defer, skewed
exchange x fallback).  This sweep draws structured-random configs from a
fixed seed (deterministic in CI) and checks every drawn (tables, op)
against pandas.  Time-boxed: small row counts in a few pow2 buckets so
compiled programs are shared across draws.

Reference analog: the randomized table generators the C++ tests lean on
(util/arrow_rand.hpp + test_utils.hpp random csv-pair runners).
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import (groupby_aggregate, join_tables,
                                  sort_table, unique_table)
from cylon_tpu.relational.setops import set_operation

SEED = 20260731
N_DRAWS = 28

KEY_DTYPES = ["int64", "int32", "float64", "str"]
VAL_DTYPES = ["int64", "float64", "float32"]


def _gen_col(rng, n, dtype, nulls: float, skew: float, card: int):
    if dtype == "str":
        vals = np.asarray([f"s{v:05d}" for v in rng.integers(0, card, n)],
                          dtype=object)
    elif dtype.startswith("float"):
        vals = rng.integers(0, card, n).astype(dtype)
    else:
        vals = rng.integers(0, card, n).astype(dtype)
    if skew > 0:
        hot = vals[0]
        m = rng.random(n) < skew
        vals = vals.copy()
        vals[m] = hot
    if nulls > 0:
        vals = pd.array(vals).astype(object)
        mask = rng.random(n) < nulls
        vals = np.asarray(vals, dtype=object)
        vals[mask] = None
        return pd.Series(vals).astype(
            "object" if dtype == "str" else f"{dtype.capitalize()}"
            if dtype.startswith("int") else dtype)
    return pd.Series(vals)


def _draw(rng):
    """One random scenario (sizes in pow2-friendly buckets for program
    reuse across draws)."""
    return {
        "n_l": int(rng.choice([96, 256, 700])),
        "n_r": int(rng.choice([96, 256, 700])),
        "key": str(rng.choice(KEY_DTYPES)),
        "val": str(rng.choice(VAL_DTYPES)),
        "nulls": float(rng.choice([0.0, 0.0, 0.1])),
        "skew": float(rng.choice([0.0, 0.0, 0.7])),
        "card": int(rng.choice([8, 40, 400])),
        "op": str(rng.choice(["join_inner", "join_left", "join_right",
                              "join_outer", "join_semi", "join_anti",
                              "groupby", "sort", "unique", "union",
                              "subtract"])),
    }


def _tables(rng, cfg, env):
    lk = _gen_col(rng, cfg["n_l"], cfg["key"], cfg["nulls"], cfg["skew"],
                  cfg["card"])
    lv = _gen_col(rng, cfg["n_l"], cfg["val"], 0.0, 0.0, 1000)
    rk = _gen_col(rng, cfg["n_r"], cfg["key"], 0.0, 0.0, cfg["card"])
    rv = _gen_col(rng, cfg["n_r"], cfg["val"], 0.0, 0.0, 1000)
    ldf = pd.DataFrame({"k": lk, "a": lv})
    rdf = pd.DataFrame({"k": rk, "b": rv})
    return ldf, rdf, ct.Table.from_pandas(ldf, env), \
        ct.Table.from_pandas(rdf, env)


def _sorted_vals(df, cols):
    return sorted(map(tuple, df[cols].astype(str).to_numpy()))


def _check(cfg, env):
    rng = np.random.default_rng(cfg.pop("_seed"))
    ldf, rdf, lt, rt = _tables(rng, cfg, env)
    op = cfg["op"]
    if op.startswith("join_"):
        how = op.split("_")[1]
        got = join_tables(lt, rt, "k", "k", how=how).to_pandas()
        if how in ("semi", "anti"):
            rset = set(rdf["k"].dropna()) | (
                {None} if rdf["k"].isna().any() else set())
            m = ldf["k"].map(lambda v: (v in rset) or
                             (pd.isna(v) and None in rset))
            exp = ldf[m] if how == "semi" else ldf[~m]
            assert len(got) == len(exp), cfg
            assert _sorted_vals(got, ["k"]) == _sorted_vals(exp, ["k"]), cfg
        else:
            exp = ldf.merge(rdf, on="k", how=how)
            assert len(got) == len(exp), cfg
            assert np.isclose(got["a"].sum(), exp["a"].sum(),
                              equal_nan=True), cfg
            assert np.isclose(got["b"].sum(), exp["b"].sum(),
                              equal_nan=True), cfg
    elif op == "groupby":
        got = groupby_aggregate(lt, ["k"], [("a", "sum"), ("a", "count"),
                                            ("a", "max")]).to_pandas()
        exp = (ldf.groupby("k", dropna=False, as_index=False)
               .agg(a_sum=("a", "sum"), a_count=("a", "count"),
                    a_max=("a", "max")))
        assert len(got) == len(exp), cfg
        assert np.isclose(got["a_sum"].sum(), exp["a_sum"].sum()), cfg
        assert got["a_count"].sum() == exp["a_count"].sum(), cfg
    elif op == "sort":
        got = sort_table(lt, "k").to_pandas()
        exp = ldf.sort_values("k", na_position="last") \
            .reset_index(drop=True)
        assert got["k"].astype(str).tolist() == \
            exp["k"].astype(str).tolist(), cfg
    elif op == "unique":
        got = unique_table(lt, ["k"]).to_pandas()
        assert len(got) == ldf["k"].nunique(dropna=False), cfg
    elif op == "union":
        got = set_operation(lt, _align(rt, env), "union").to_pandas()
        exp = pd.concat([ldf, _align_df(rdf)]).drop_duplicates()
        assert len(got) == len(exp), cfg
    elif op == "subtract":
        got = set_operation(lt, _align(rt, env), "subtract").to_pandas()
        exp = ldf.drop_duplicates().merge(
            _align_df(rdf).drop_duplicates(), how="left", indicator=True,
            on=list(ldf.columns))
        exp = exp[exp["_merge"] == "left_only"]
        assert len(got) == len(exp), cfg


def _align_df(rdf):
    out = rdf.rename(columns={"b": "a"})
    return out[["k", "a"]]


def _align(rt, env):
    from cylon_tpu.frame import DataFrame
    df = DataFrame(_table=rt)
    df = df.rename({"b": "a"})
    return df[["k", "a"]]._table


def _run_sweep(env):
    rng = np.random.default_rng(SEED)
    failures = []
    for i in range(N_DRAWS):
        cfg = _draw(rng)
        cfg["_seed"] = SEED + 1000 + i
        # float keys with nulls: NaN-vs-None oracle semantics differ in
        # pandas merge; keep the sweep on the well-defined space
        if cfg["key"].startswith("float") and cfg["nulls"] > 0:
            cfg["nulls"] = 0.0
        if cfg["key"] == "str" and cfg["op"] == "sort":
            cfg["nulls"] = 0.0   # exercised in test_hashed_strings
        try:
            _check(dict(cfg), env)
        except AssertionError as e:
            failures.append((i, cfg, str(e)[:200]))
    assert not failures, failures


def test_fuzz_world4(env4):
    _run_sweep(env4)


def test_fuzz_world8(env8):
    _run_sweep(env8)


def test_fuzz_world1(env1):
    _run_sweep(env1)
