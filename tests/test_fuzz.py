"""Property-based cross-world fuzz: generator-driven sweep of
dtype x nulls x skew x world x operator against the pandas oracle.

The example-based suite pins known shapes; the bugs that survived past
rounds lived in INTERACTIONS (fused string-agg under defer, skewed
exchange x fallback).  This sweep draws structured-random configs from a
fixed seed (deterministic in CI) and checks every drawn (tables, op)
against pandas.  Time-boxed: small row counts in a few pow2 buckets so
compiled programs are shared across draws.

Reference analog: the randomized table generators the C++ tests lean on
(util/arrow_rand.hpp + test_utils.hpp random csv-pair runners).
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.relational import (groupby_aggregate, join_tables,
                                  sort_table, unique_table)
from cylon_tpu.relational.setops import set_operation

SEED = 20260731
N_DRAWS = 28

KEY_DTYPES = ["int64", "int32", "float64", "str"]
VAL_DTYPES = ["int64", "float64", "float32"]


def _gen_col(rng, n, dtype, nulls: float, skew: float, card: int):
    if dtype == "str":
        vals = np.asarray([f"s{v:05d}" for v in rng.integers(0, card, n)],
                          dtype=object)
    elif dtype.startswith("float"):
        vals = rng.integers(0, card, n).astype(dtype)
    else:
        vals = rng.integers(0, card, n).astype(dtype)
    if skew > 0:
        hot = vals[0]
        m = rng.random(n) < skew
        vals = vals.copy()
        vals[m] = hot
    if nulls > 0:
        vals = pd.array(vals).astype(object)
        mask = rng.random(n) < nulls
        vals = np.asarray(vals, dtype=object)
        vals[mask] = None
        return pd.Series(vals).astype(
            "object" if dtype == "str" else f"{dtype.capitalize()}"
            if dtype.startswith("int") else dtype)
    return pd.Series(vals)


def _draw(rng):
    """One random scenario (sizes in pow2-friendly buckets for program
    reuse across draws)."""
    return {
        "n_l": int(rng.choice([96, 256, 700])),
        "n_r": int(rng.choice([96, 256, 700])),
        "key": str(rng.choice(KEY_DTYPES)),
        "val": str(rng.choice(VAL_DTYPES)),
        "nulls": float(rng.choice([0.0, 0.0, 0.1])),
        "skew": float(rng.choice([0.0, 0.0, 0.7])),
        "card": int(rng.choice([8, 40, 400])),
        "op": str(rng.choice(["join_inner", "join_left", "join_right",
                              "join_outer", "join_semi", "join_anti",
                              "groupby", "sort", "unique", "union",
                              "subtract"])),
    }


def _tables(rng, cfg, env):
    lk = _gen_col(rng, cfg["n_l"], cfg["key"], cfg["nulls"], cfg["skew"],
                  cfg["card"])
    lv = _gen_col(rng, cfg["n_l"], cfg["val"], 0.0, 0.0, 1000)
    rk = _gen_col(rng, cfg["n_r"], cfg["key"], 0.0, 0.0, cfg["card"])
    rv = _gen_col(rng, cfg["n_r"], cfg["val"], 0.0, 0.0, 1000)
    ldf = pd.DataFrame({"k": lk, "a": lv})
    rdf = pd.DataFrame({"k": rk, "b": rv})
    return ldf, rdf, ct.Table.from_pandas(ldf, env), \
        ct.Table.from_pandas(rdf, env)


def _sorted_vals(df, cols):
    return sorted(map(tuple, df[cols].astype(str).to_numpy()))


def _check(cfg, env):
    rng = np.random.default_rng(cfg.pop("_seed"))
    ldf, rdf, lt, rt = _tables(rng, cfg, env)
    op = cfg["op"]
    if op.startswith("join_"):
        how = op.split("_")[1]
        got = join_tables(lt, rt, "k", "k", how=how).to_pandas()
        if how in ("semi", "anti"):
            rset = set(rdf["k"].dropna()) | (
                {None} if rdf["k"].isna().any() else set())
            m = ldf["k"].map(lambda v: (v in rset) or
                             (pd.isna(v) and None in rset))
            exp = ldf[m] if how == "semi" else ldf[~m]
            assert len(got) == len(exp), cfg
            assert _sorted_vals(got, ["k"]) == _sorted_vals(exp, ["k"]), cfg
        else:
            exp = ldf.merge(rdf, on="k", how=how)
            assert len(got) == len(exp), cfg
            assert np.isclose(got["a"].sum(), exp["a"].sum(),
                              equal_nan=True), cfg
            assert np.isclose(got["b"].sum(), exp["b"].sum(),
                              equal_nan=True), cfg
    elif op == "groupby":
        got = groupby_aggregate(lt, ["k"], [("a", "sum"), ("a", "count"),
                                            ("a", "max")]).to_pandas()
        exp = (ldf.groupby("k", dropna=False, as_index=False)
               .agg(a_sum=("a", "sum"), a_count=("a", "count"),
                    a_max=("a", "max")))
        assert len(got) == len(exp), cfg
        assert np.isclose(got["a_sum"].sum(), exp["a_sum"].sum()), cfg
        assert got["a_count"].sum() == exp["a_count"].sum(), cfg
    elif op == "sort":
        got = sort_table(lt, "k").to_pandas()
        exp = ldf.sort_values("k", na_position="last") \
            .reset_index(drop=True)
        assert got["k"].astype(str).tolist() == \
            exp["k"].astype(str).tolist(), cfg
    elif op == "unique":
        got = unique_table(lt, ["k"]).to_pandas()
        assert len(got) == ldf["k"].nunique(dropna=False), cfg
    elif op == "union":
        got = set_operation(lt, _align(rt, env), "union").to_pandas()
        exp = pd.concat([ldf, _align_df(rdf)]).drop_duplicates()
        assert len(got) == len(exp), cfg
    elif op == "subtract":
        got = set_operation(lt, _align(rt, env), "subtract").to_pandas()
        exp = ldf.drop_duplicates().merge(
            _align_df(rdf).drop_duplicates(), how="left", indicator=True,
            on=list(ldf.columns))
        exp = exp[exp["_merge"] == "left_only"]
        assert len(got) == len(exp), cfg


def _align_df(rdf):
    out = rdf.rename(columns={"b": "a"})
    return out[["k", "a"]]


def _align(rt, env):
    from cylon_tpu.frame import DataFrame
    df = DataFrame(_table=rt)
    df = df.rename({"b": "a"})
    return df[["k", "a"]]._table


def _run_sweep(env):
    rng = np.random.default_rng(SEED)
    failures = []
    for i in range(N_DRAWS):
        cfg = _draw(rng)
        cfg["_seed"] = SEED + 1000 + i
        # float keys with nulls: NaN-vs-None oracle semantics differ in
        # pandas merge; keep the sweep on the well-defined space
        if cfg["key"].startswith("float") and cfg["nulls"] > 0:
            cfg["nulls"] = 0.0
        if cfg["key"] == "str" and cfg["op"] == "sort":
            cfg["nulls"] = 0.0   # exercised in test_hashed_strings
        try:
            _check(dict(cfg), env)
        except AssertionError as e:
            failures.append((i, cfg, str(e)[:200]))
    assert not failures, failures


def test_fuzz_world4(env4):
    _run_sweep(env4)


def test_fuzz_world8(env8):
    _run_sweep(env8)


def test_fuzz_world1(env1):
    _run_sweep(env1)


# ---------------------------------------------------------------------------
# regime-boundary tier (VERDICT item 7): draws PINNED to the seams the
# uniform sweep above rarely lands on — pow2 piece-bucket straddles, 0.9
# skew under a lowered receive budget, the broadcast-join cutover, the
# multi-round exchange, and a draw that forces the pipelined OOM
# fallback — each asserting on timing counters / recovery events that
# the claimed path ACTUALLY executed (a draw that silently took the
# happy path proves nothing).
# ---------------------------------------------------------------------------

def _counter(name: str) -> int:
    from cylon_tpu.utils import timing
    return timing.snapshot().get(name, {}).get("n", 0)


def _skew_tables(env, rng, n, skew, card=500):
    lk = rng.integers(0, card, n).astype(np.int64)
    hot = np.int64(card // 2)
    lk = np.where(rng.random(n) < skew, hot, lk)
    ldf = pd.DataFrame({"k": lk, "a": rng.integers(0, 50, n)
                        .astype(np.int64)})
    rdf = pd.DataFrame({"k": rng.integers(0, card, n).astype(np.int64),
                        "b": rng.integers(0, 50, n).astype(np.int64)})
    return ldf, rdf, ct.Table.from_pandas(ldf, env), \
        ct.Table.from_pandas(rdf, env)


class TestRegimeBoundaries:
    @pytest.fixture(autouse=True)
    def _disarm(self):
        from cylon_tpu.exec import recovery
        recovery.install_faults("")
        yield
        recovery.install_faults("")

    def test_pow2_piece_bucket_straddle(self, env4):
        """Piece sizes one row either side of pow2 caps: the
        range-bounds/piece-cap machinery must stay exact where
        pow2ceil's bucket flips."""
        from cylon_tpu.exec import pipelined_join
        rng = np.random.default_rng(77)
        for n_l, n_r in ((255, 257), (256, 256), (1023, 1025), (1024, 513)):
            ldf = pd.DataFrame(
                {"k": rng.integers(0, 64, n_l).astype(np.int64),
                 "a": rng.integers(0, 50, n_l).astype(np.int64)})
            rdf = pd.DataFrame(
                {"k": rng.integers(0, 64, n_r).astype(np.int64),
                 "b": rng.integers(0, 50, n_r).astype(np.int64)})
            lt = ct.Table.from_pandas(ldf, env4)
            rt = ct.Table.from_pandas(rdf, env4)
            got = pipelined_join(lt, rt, "k", "k", how="inner",
                                 n_chunks=3).to_pandas()
            exp = ldf.merge(rdf, on="k")
            assert len(got) == len(exp), (n_l, n_r)
            assert got["a"].sum() == exp["a"].sum(), (n_l, n_r)
            assert got["b"].sum() == exp["b"].sum(), (n_l, n_r)

    def test_skew_forces_pipelined_fallback(self, env4, rng):
        """Skew-0.9 draw + a one-shot predicted receive-guard fault: the
        consensus ladder must reroute through the pipelined fallback
        (recovery counter proves it ran) and the recovered result equals
        pandas exactly."""
        from cylon_tpu.exec import recovery
        ldf, rdf, lt, rt = _skew_tables(env4, rng, 4000, skew=0.9)
        before = _counter("recovery.join.predicted.retry_chunks_4")
        recovery.install_faults("shuffle.recv_guard:0:1=predicted")
        recovery.reset_events()
        got = (join_tables(lt, rt, "k", "k", how="inner").to_pandas()
               .sort_values(["k", "a", "b"]).reset_index(drop=True))
        exp = (ldf.merge(rdf, on="k").sort_values(["k", "a", "b"])
               .reset_index(drop=True))
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)
        acts = [e["action"] for e in recovery.recovery_events()
                if e["site"] == "join"]
        assert acts == ["retry_chunks_4"], acts
        # the timing counter pins the fallback path, not just the event
        assert _counter("recovery.join.predicted.retry_chunks_4") \
            == before + 1

    def test_receive_guard_fires_under_lowered_budget(self, env8, rng,
                                                      monkeypatch):
        """Skew 0.9 with EXCHANGE_RECV_BUDGET lowered below the hot
        shard's receive: the guard must fire TYPED and pre-collective,
        and because the streaming fallback shuffles the same rows, every
        rung re-faults — the event trail proves guard + both fallback
        rungs executed before the bounded abort."""
        from cylon_tpu import config
        from cylon_tpu.exec import recovery
        from cylon_tpu.status import PredictedResourceExhausted
        monkeypatch.setattr(config, "EXCHANGE_RECV_BUDGET_BYTES", 4096)
        monkeypatch.setattr(config, "EXCHANGE_RECV_GUARD_CPU", True)
        _, _, lt, rt = _skew_tables(env8, rng, 4000, skew=0.9)
        recovery.reset_events()
        with pytest.raises(PredictedResourceExhausted) as ei:
            join_tables(lt, rt, "k", "k", how="inner")
        assert ei.value.site == "shuffle.recv_guard"
        acts = [e["action"] for e in recovery.recovery_events()
                if e["site"] == "join"]
        assert acts == ["retry_chunks_4", "retry_chunks_16", "abort"], acts

    def test_broadcast_join_cutover_engages(self, env4, rng):
        """A build side under BROADCAST_JOIN_ROWS with a 4x probe: the
        broadcast-hash-join path must actually engage (counter) and
        stay exact."""
        n_l, n_r = 2000, 96
        ldf = pd.DataFrame({"k": rng.integers(0, 80, n_l).astype(np.int64),
                            "a": rng.integers(0, 50, n_l).astype(np.int64)})
        rdf = pd.DataFrame({"k": rng.integers(0, 80, n_r).astype(np.int64),
                            "b": rng.integers(0, 50, n_r).astype(np.int64)})
        lt = ct.Table.from_pandas(ldf, env4)
        rt = ct.Table.from_pandas(rdf, env4)
        before = _counter("join.broadcast")
        got = join_tables(lt, rt, "k", "k", how="inner").to_pandas()
        assert _counter("join.broadcast") == before + 1
        exp = ldf.merge(rdf, on="k")
        assert len(got) == len(exp)
        assert got["a"].sum() == exp["a"].sum()

    def test_multiround_exchange_engages(self, env4, rng):
        """Full-skew draw big enough that one (src,dst) stream exceeds
        the exchange block cap: the multi-round protocol must engage
        (counter) while the shuffle stays lossless."""
        from cylon_tpu.relational.repart import shuffle_table
        n = 40_000
        df = pd.DataFrame({"k": np.full(n, 7, np.int64),
                           "v": rng.integers(0, 1000, n).astype(np.int64)})
        t = ct.Table.from_pandas(df, env4)
        before = _counter("exchange.multiround")
        out = shuffle_table(t, ["k"])
        assert _counter("exchange.multiround") > before
        assert out.row_count == n
        got = out.to_pandas()
        assert got["v"].sum() == df["v"].sum()

    @pytest.mark.slow
    def test_heavy_skew_recovery_draw(self, env8, rng):
        """The heavy draw (slow tier): 20k rows at skew 0.9 across 8
        shards with an injected mid-exchange fault — multi-round-scale
        traffic through the full ladder, still exact."""
        from cylon_tpu.exec import recovery
        ldf, rdf, lt, rt = _skew_tables(env8, rng, 20_000, skew=0.9,
                                        card=2000)
        recovery.install_faults("shuffle.recv_guard:0:1=predicted")
        recovery.reset_events()
        got = join_tables(lt, rt, "k", "k", how="inner").to_pandas()
        exp = ldf.merge(rdf, on="k")
        assert len(got) == len(exp)
        assert got["a"].sum() == exp["a"].sum()
        assert got["b"].sum() == exp["b"].sum()
        assert any(e["action"] == "retry_chunks_4"
                   for e in recovery.recovery_events())
