"""Tests for the trace-safety analyzer (cylon_tpu.analysis).

Fast tests (tier-1): every AST rule fires on its known-bad fixture, the
suppression escape works, the whole cylon_tpu/ package lints clean (the
CI gate's green-start guarantee), the jaxpr pass verifies the four
required op families (join, sort, groupby, shuffle) and catches seeded
violations, and the runtime sentinel counts retraces/transfers.

Slow tests: the jaxpr pass over EVERY registered builder and the CLI
subprocess round-trip.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cylon_tpu.analysis import ast_lint, coherence, rules
from cylon_tpu.analysis.registry import BuilderDecl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BAD = os.path.join(REPO, "tests", "data", "tracecheck_bad")
PKG = os.path.join(REPO, "cylon_tpu")


def _rules_in(path):
    return {f.rule for f in ast_lint.lint_file(os.path.join(BAD, path))}


# ---------------------------------------------------------------------------
# AST pass: each rule fires on its fixture
# ---------------------------------------------------------------------------

def test_ts101_host_sync_fixture():
    found = ast_lint.lint_file(os.path.join(BAD, "bad_host_sync.py"))
    ts101 = [f for f in found if f.rule == "TS101"]
    # np.asarray, .item(), host_array, float(), jax.device_get
    assert len(ts101) >= 5
    assert all(f.line > 0 for f in ts101)


def test_ts102_tracer_branch_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_tracer_branch.py")) if f.rule == "TS102"]
    assert len(found) == 2  # the if and the while


def test_ts103_jit_static_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_jit_static.py")) if f.rule == "TS103"]
    # flags the bare jax.jit(kernel), not the static_argnames one
    assert len(found) == 1
    assert "mode" in found[0].message


def test_ts104_lru_mesh_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_lru_mesh.py")) if f.rule == "TS104"]
    assert len(found) == 1
    assert "_builder_fn" in found[0].message


def test_ts105_oom_stringmatch_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_oom_stringmatch.py")) if f.rule == "TS105"]
    # one finding PER STRING MATCH — the nested-handler case must not
    # double-report its single match through both enclosing handlers
    assert len(found) == 3
    assert len({(f.line,) for f in found}) == 3
    assert all("recovery" in f.message for f in found)


def test_ts105_sanctioned_in_recovery_module():
    # the identical pattern inside exec/recovery.py is the sanctioned
    # classification boundary and must NOT be flagged
    src = ("def f(op):\n"
           "    try:\n"
           "        return op()\n"
           "    except Exception as e:\n"
           "        if 'RESOURCE_EXHAUSTED' in str(e):\n"
           "            return None\n"
           "        raise\n")
    assert ast_lint.lint_source("cylon_tpu/exec/recovery.py", src) == []
    assert any(f.rule == "TS105"
               for f in ast_lint.lint_source("cylon_tpu/other.py", src))


def test_ts106_device_residency_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "relational", "bad_device_residency.py"))
        if f.rule == "TS106"]
    # one device_get + one device_put, both flagged
    assert len(found) == 2
    assert all("exec.memory" in f.message for f in found)


def test_ts106_scoped_to_operator_dirs():
    # the identical calls OUTSIDE relational/ or parallel/ are fine —
    # exec/memory.py (the ledger itself) and core/table.py (_put, the
    # documented upload boundary) must not be flagged
    src = "import jax\n\ndef f(x, s):\n    return jax.device_put(x, s)\n"
    assert ast_lint.lint_source("cylon_tpu/exec/memory.py", src) == []
    assert ast_lint.lint_source("cylon_tpu/core/table.py", src) == []
    assert any(f.rule == "TS106" for f in ast_lint.lint_source(
        "cylon_tpu/relational/other.py", src))
    assert any(f.rule == "TS106" for f in ast_lint.lint_source(
        "cylon_tpu/parallel/other.py", src))


def test_ts107_ckpt_artifact_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "relational", "bad_ckpt_write.py"))
        if f.rule == "TS107"]
    # np.save, two opens of ckpt-named paths, np.load — the non-ckpt
    # np.save stays clean
    assert len(found) == 4
    assert all("exec/checkpoint.py" in f.message for f in found)
    # pickle.dump's args carry no ckpt name — not flagged itself (the
    # enclosing open of the ckpt-named path is); nor is the non-ckpt
    # np.save in fine_non_checkpoint_io
    assert not any(f.line == 22 for f in found)
    assert not any(f.line == 26 for f in found)


def test_ts107_scoped_to_pipeline_and_relational():
    # the identical write inside exec/checkpoint.py (the sanctioned
    # module) or any other exec/ module is NOT flagged; relational/ and
    # exec/pipeline.py are
    src = ("import os\nimport numpy as np\n\n"
           "def f(arr):\n"
           "    ckpt_dir = os.environ['CYLON_TPU_CKPT_DIR']\n"
           "    np.save(os.path.join(ckpt_dir, 'p.npy'), arr)\n")
    assert ast_lint.lint_source("cylon_tpu/exec/checkpoint.py", src) == []
    assert ast_lint.lint_source("cylon_tpu/exec/memory.py", src) == []
    assert any(f.rule == "TS107" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", src))
    assert any(f.rule == "TS107" for f in ast_lint.lint_source(
        "cylon_tpu/relational/other.py", src))
    # non-checkpoint IO in those modules stays clean
    clean = ("import numpy as np\n\ndef f(arr, path):\n"
             "    np.save(path, arr)\n")
    assert ast_lint.lint_source("cylon_tpu/exec/pipeline.py", clean) == []


def test_ts108_use_after_donate_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "relational", "bad_use_after_donate.py"))
        if f.rule == "TS108"]
    # jit-wrapper read, builder-kw carry + state, immediate-apply read,
    # conditional-idiom read — the rebind/del/unknown-positions cases
    # stay clean
    assert len(found) == 5
    assert all("donate" in f.message for f in found)


def test_ts108_scoped_and_cleared():
    src = ("import jax\n\n"
           "def f(buf):\n"
           "    fn = jax.jit(lambda x: x, donate_argnums=(0,))\n"
           "    out = fn(buf)\n"
           "    return out + buf\n")
    # in scope under relational/ and exec/, out of scope elsewhere
    assert any(f.rule == "TS108" for f in ast_lint.lint_source(
        "cylon_tpu/relational/other.py", src))
    assert any(f.rule == "TS108" for f in ast_lint.lint_source(
        "cylon_tpu/exec/other.py", src))
    assert not any(f.rule == "TS108" for f in ast_lint.lint_source(
        "cylon_tpu/ops/other.py", src))
    # rebinding the donated name clears the mark
    clean = ("import jax\n\n"
             "def f(buf):\n"
             "    fn = jax.jit(lambda x: x, donate_argnums=(0,))\n"
             "    buf = fn(buf)\n"
             "    return buf\n")
    def _ts108(src):
        # the raw-jit spelling here also fires TS117 by design — this
        # test scopes the donate tracking only
        return [f for f in ast_lint.lint_source(
            "cylon_tpu/relational/other.py", src) if f.rule == "TS108"]

    assert _ts108(clean) == []
    # a non-static donate keyword is not tracked (under-approximation)
    unknown = ("import jax\n\n"
               "def f(buf, d):\n"
               "    fn = jax.jit(lambda x: x, donate_argnums=d)\n"
               "    out = fn(buf)\n"
               "    return out + buf\n")
    assert _ts108(unknown) == []
    # metadata-only reads (shape/dtype/... — _STATIC_ATTRS) of a donated
    # name are safe: jax keeps the aval on a deleted Array
    meta = ("import jax\n\n"
            "def f(buf):\n"
            "    fn = jax.jit(lambda x: x, donate_argnums=(0,))\n"
            "    out = fn(buf)\n"
            "    return out.reshape(buf.shape[0]), buf.dtype\n")
    assert _ts108(meta) == []
    # a compound statement rebinding the donated name (for-loop target)
    # shadows the buffer BEFORE its body reads it — no finding
    loop = ("import jax\n\n"
            "def f(buf, items):\n"
            "    fn = jax.jit(lambda x: x, donate_argnums=(0,))\n"
            "    out = fn(buf)\n"
            "    for buf in items:\n"
            "        out = out + buf\n"
            "    return out\n")
    assert _ts108(loop) == []
    # rebinding the CALLABLE to a non-donating program drops its stale
    # donate positions — the new program's args must not flag
    redef = ("import jax\n\n"
             "def f(buf):\n"
             "    fn = jax.jit(lambda x: x, donate_argnums=(0,))\n"
             "    fn = jax.jit(lambda x: x)\n"
             "    out = fn(buf)\n"
             "    return out + buf\n")
    assert _ts108(redef) == []


def test_ts112_stats_dict_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_stats_dict.py")) if f.rule == "TS112"]
    # _STATS literal, _EVICTION_COUNTERS literal, QUERY_METRICS dict()
    # call — the non-counter name, the non-dict value and the
    # function-local table stay clean
    assert len(found) == 3, found
    assert all("cylon_tpu.obs" in f.message for f in found)


def test_ts112_obs_package_exempt_and_shims_clean():
    src = "_STATS = {'spill_events': 0}\n"
    # the obs package is the defining module — exempt by construction,
    # including under an absolute checkout path
    assert not any(f.rule == "TS112" for f in ast_lint.lint_source(
        "cylon_tpu/obs/metrics.py", src))
    assert not any(f.rule == "TS112" for f in ast_lint.lint_source(
        "/home/ci/repo/cylon_tpu/obs/metrics.py", src))
    # ...but a workspace directory that merely happens to be called
    # "obs" must NOT disable the rule (qualified-pair scoping)
    assert any(f.rule == "TS112" for f in ast_lint.lint_source(
        "/home/ci/obs/repo/cylon_tpu/exec/memory.py", src))
    assert any(f.rule == "TS112" for f in ast_lint.lint_source(
        "cylon_tpu/exec/memory.py", src))
    assert any(f.rule == "TS112" for f in ast_lint.lint_source(
        "cylon_tpu/utils/timing.py", src))
    # the registry-backed migration shim (metrics.group) is sanctioned:
    # the rule keys on the mutable literal, not the name
    shim = ("from ..obs import metrics as _metrics\n"
            "_STATS = _metrics.group('memory', ('spill_events',))\n")
    assert not any(f.rule == "TS112" for f in ast_lint.lint_source(
        "cylon_tpu/exec/memory.py", shim))


def test_suppression_silences_everything():
    assert ast_lint.lint_file(os.path.join(BAD, "suppressed.py")) == []


def test_findings_carry_file_and_line():
    found = ast_lint.lint_file(os.path.join(BAD, "bad_tracer_branch.py"))
    assert found and all(
        f.path.endswith("bad_tracer_branch.py") and f.line > 0
        for f in found)
    assert all(f.rule in rules.RULES for f in found)


# ---------------------------------------------------------------------------
# the gate starts green: the whole package lints clean
# ---------------------------------------------------------------------------

def test_ts109_direct_admission_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_direct_admission.py")) if f.rule == "TS109"]
    # ensure_headroom, try_free, spill_for_retry, evict_n, evict_until
    assert len(found) == 5
    assert all("scheduler-mediated" in f.message for f in found)


def test_ts109_sanctioned_modules_exempt():
    src = ("def admit(env, memory, n):\n"
           "    memory.ensure_headroom(env, n)\n"
           "    memory.try_free(n)\n")
    # the serving scheduler and the ledger itself are the two sanctioned
    # callers; anywhere else in the package fires
    assert not any(f.rule == "TS109" for f in ast_lint.lint_source(
        "cylon_tpu/exec/scheduler.py", src))
    assert not any(f.rule == "TS109" for f in ast_lint.lint_source(
        "cylon_tpu/exec/memory.py", src))
    assert any(f.rule == "TS109" for f in ast_lint.lint_source(
        "cylon_tpu/relational/piece.py", src))
    assert any(f.rule == "TS109" for f in ast_lint.lint_source(
        "cylon_tpu/tpch.py", src))


def test_ts110_stream_state_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_stream_mutation.py")) if f.rule == "TS110"]
    # _parts assign, _parts.append, _adopted assign, _regs.clear,
    # register_window, evict_release
    assert len(found) == 6, found
    assert any("absorb/snapshot" in f.message for f in found)
    assert any("window-lifetime" in f.message for f in found)


def test_ts110_sanctioned_modules_exempt():
    src = ("def poke(sink, memory, reg, part):\n"
           "    sink._parts.append(part)\n"
           "    memory.evict_release(reg)\n")
    # the stream package and the defining modules are sanctioned;
    # anywhere else in the package fires
    assert not any(f.rule == "TS110" for f in ast_lint.lint_source(
        "cylon_tpu/stream/view.py", src))
    assert not any(f.rule == "TS110" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", src))
    assert not any(f.rule == "TS110" for f in ast_lint.lint_source(
        "cylon_tpu/exec/memory.py", src))
    assert any(f.rule == "TS110" for f in ast_lint.lint_source(
        "cylon_tpu/relational/groupby.py", src))
    assert any(f.rule == "TS110" for f in ast_lint.lint_source(
        "cylon_tpu/exec/scheduler.py", src))


def test_ts111_foreign_rank_read_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_foreign_rank_read.py")) if f.rule == "TS111"]
    # the f-string rank{r} join and the literal rank0/ segment
    assert len(found) == 2, found
    assert all("load_foreign_pieces" in f.message for f in found)


def test_ts111_scoping_and_negatives():
    src = ("import os\n"
           "def peek(ckpt_dir, r):\n"
           "    return os.path.join(ckpt_dir, f'rank{r}', 'MANIFEST.json')\n")
    # the checkpoint module is the one sanctioned cross-rank reader
    assert not any(f.rule == "TS111" for f in ast_lint.lint_source(
        "cylon_tpu/exec/checkpoint.py", src))
    assert any(f.rule == "TS111" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", src))
    assert any(f.rule == "TS111" for f in ast_lint.lint_source(
        "cylon_tpu/stream/view.py", src))
    # rank literals with no checkpoint-path mention stay clean (an
    # exchange peer table is not a checkpoint read) …
    clean = ("import os\n"
             "def peer(base, r):\n"
             "    return os.path.join(base, f'rank{r}')\n")
    assert not any(f.rule == "TS111" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", clean))
    # … and ckpt paths without a rank<r> segment are TS107's business
    no_rank = ("import os\n"
               "def tokenfile(ckpt_dir):\n"
               "    return os.path.join(ckpt_dir, 'RESUME_TOKEN.json')\n")
    assert not any(f.rule == "TS111" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", no_rank))
    # prefix words containing 'rank' are not rank dirs
    ranked = ("import os\n"
              "def f(ckpt_dir):\n"
              "    return os.path.join(ckpt_dir, 'ranked_results')\n")
    assert not any(f.rule == "TS111" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", ranked))


def test_package_lints_clean():
    found = ast_lint.lint_paths([PKG])
    assert found == [], "\n".join(map(str, found))


def test_ts113_plan_stack_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "relational", "bad_plan_push.py"))
        if f.rule == "TS113"]
    # push_node, pop_node, bare-name push_node — the context-manager
    # facade call stays clean
    assert len(found) == 3, found
    assert all("obs.plan" in f.message for f in found)


def test_ts113_scoping():
    src = "def f(plan, n):\n    plan.push_node('join', {}, None)\n"
    # scoped to the operator directories...
    assert any(f.rule == "TS113" for f in ast_lint.lint_source(
        "cylon_tpu/relational/join.py", src))
    assert any(f.rule == "TS113" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", src))
    assert any(f.rule == "TS113" for f in ast_lint.lint_source(
        "cylon_tpu/stream/table.py", src))
    # ...not the rest of the package, and the defining module is exempt
    assert not any(f.rule == "TS113" for f in ast_lint.lint_source(
        "cylon_tpu/obs/plan.py", src))
    assert not any(f.rule == "TS113" for f in ast_lint.lint_source(
        "cylon_tpu/parallel/shuffle.py", src))
    # the facade itself never flags
    ok = "def f(plan):\n    with plan.node('join'):\n        pass\n"
    assert not any(f.rule == "TS113" for f in ast_lint.lint_source(
        "cylon_tpu/relational/join.py", ok))


def test_ts114_spill_file_io_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_spill_file_io.py")) if f.rule == "TS114"]
    # save+join, load+join, env-var join — the neutral-name open, the
    # non-spill np.save and the counter reads stay clean
    assert len(found) == 5, found
    assert all("exec/memory.py" in f.message for f in found)


def test_ts114_scoping_and_negatives():
    src = ("import os\nimport numpy as np\n\n"
           "def demote(spill_dir, owner, arr):\n"
           "    np.save(os.path.join(spill_dir, owner + '.spill.npy'), "
           "arr)\n")
    # the ledger module is the one sanctioned spill-page IO site
    assert not any(f.rule == "TS114" for f in ast_lint.lint_source(
        "cylon_tpu/exec/memory.py", src))
    assert any(f.rule == "TS114" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", src))
    assert any(f.rule == "TS114" for f in ast_lint.lint_source(
        "cylon_tpu/relational/piece.py", src))
    # the WORD spill outside the on-disk naming never fires: counters,
    # the consensus verb, ordinary residency flags
    clean = ("def f(memory, stats, mesh, recovery):\n"
             "    n = stats['spill_events']\n"
             "    recovery.spill_consensus(mesh, True)\n"
             "    return n\n")
    assert not any(f.rule == "TS114" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", clean))
    # ordinary np.save of a non-spill path stays clean
    io_clean = ("import numpy as np\n\ndef f(arr, path):\n"
                "    np.save(path, arr)\n")
    assert not any(f.rule == "TS114" for f in ast_lint.lint_source(
        "cylon_tpu/exec/pipeline.py", io_clean))


def test_ts115_skew_plan_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "relational", "bad_skew_salt.py"))
        if f.rule == "TS115"]
    # split targets, SkewPlan ctor, direct vote, fanout + start salt
    # mutations — the facade sequence and plain field reads stay clean
    assert len(found) == 5, found
    assert all("relational/skew.py" in f.message for f in found)


def test_ts115_scoping():
    call = ("def f(mesh, shf):\n"
            "    return shf.skew_split_targets(mesh)\n")
    salt = "def f(plan):\n    plan.chunk = plan.chunk * 2\n"
    # fires anywhere outside the facade — operator AND transport dirs
    for src in (call, salt):
        assert any(f.rule == "TS115" for f in ast_lint.lint_source(
            "cylon_tpu/relational/join.py", src))
        assert any(f.rule == "TS115" for f in ast_lint.lint_source(
            "cylon_tpu/exec/pipeline.py", src))
    # the defining facade is exempt by construction
    for src in (call, salt):
        assert not any(f.rule == "TS115" for f in ast_lint.lint_source(
            "cylon_tpu/relational/skew.py", src))
    # reads of plan fields and non-plan attribute assigns stay clean
    clean = ("def f(plan, span):\n"
             "    n = plan.fanout.sum()\n"
             "    span.start = 3\n"
             "    return n\n")
    assert not any(f.rule == "TS115" for f in ast_lint.lint_source(
        "cylon_tpu/relational/join.py", clean))


def test_ts116_topo_plan_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_topo_plan.py")) if f.rule == "TS116"]
    # TopologyPlan ctor, hop_counts, direct vote, gateway_of, n_slices +
    # route mutations — the facade sequence and plain reads stay clean
    assert len(found) == 6, found
    assert all("cylon_tpu/topo" in f.message for f in found)


def test_ts116_scoping():
    call = ("def f(mesh, topomod):\n"
            "    return topomod.topo_plan_consensus(mesh, 42)\n")
    tier = "def f(plan):\n    plan.route = 'flat'\n"
    # fires anywhere outside the facade — operator AND transport dirs
    for src in (call, tier):
        assert any(f.rule == "TS116" for f in ast_lint.lint_source(
            "cylon_tpu/parallel/shuffle.py", src))
        assert any(f.rule == "TS116" for f in ast_lint.lint_source(
            "cylon_tpu/exec/pipeline.py", src))
    # the defining package is exempt by construction (qualified pair:
    # a workspace dir merely named "topo" is NOT exempt)
    for src in (call, tier):
        assert not any(f.rule == "TS116" for f in ast_lint.lint_source(
            "cylon_tpu/topo/model.py", src))
        assert any(f.rule == "TS116" for f in ast_lint.lint_source(
            "topo/something.py", src))
    # facade-entry calls, plain field reads and non-plan attribute
    # assigns stay clean
    clean = ("def f(mesh, topomod, span):\n"
             "    hp = topomod.hier_plan(mesh)\n"
             "    topomod.ensure_adopted(mesh, hp)\n"
             "    n = hp.n_slices\n"
             "    span.route = 'x'\n"
             "    return n\n")
    assert not any(f.rule == "TS116" for f in ast_lint.lint_source(
        "cylon_tpu/parallel/shuffle.py", clean))


def test_ts117_raw_jit_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "bad_raw_jit.py")) if f.rule == "TS117"]
    # jax.jit call, partial(jax.jit, ...) decorator argument, bare pjit
    # call, .lower().compile() chain — the facade re-export, re.compile
    # and str.lower stay clean
    assert len(found) == 4, found
    assert all("compile-lifecycle facade" in f.message for f in found)


def test_ts117_scoping():
    raw = ("import jax\n\ndef f(fn, x):\n"
           "    return jax.jit(fn)(x)\n")
    aot = "def f(fn, x):\n    return fn.lower(x).compile()\n"
    # fires anywhere outside the two facade modules
    for src in (raw, aot):
        assert any(f.rule == "TS117" for f in ast_lint.lint_source(
            "cylon_tpu/relational/join.py", src))
        assert any(f.rule == "TS117" for f in ast_lint.lint_source(
            "cylon_tpu/exec/pipeline.py", src))
    # the cache-layer re-export and the lifecycle facade are exempt by
    # construction (they ARE the sanctioned compile sites)
    for src in (raw, aot):
        assert not any(f.rule == "TS117" for f in ast_lint.lint_source(
            "cylon_tpu/utils/cache.py", src))
        assert not any(f.rule == "TS117" for f in ast_lint.lint_source(
            "cylon_tpu/exec/compiler.py", src))
    # the facade spelling and non-AOT .compile receivers stay clean
    clean = ("from cylon_tpu.utils.cache import jit\nimport re\n\n"
             "def f(fn, x, pat):\n"
             "    prog = jit(fn, static_argnames=())\n"
             "    return prog(x), re.compile(pat)\n")
    assert not any(f.rule == "TS117" for f in ast_lint.lint_source(
        "cylon_tpu/relational/join.py", clean))


def test_ts118_integrity_fixture():
    found = [f for f in ast_lint.lint_file(
        os.path.join(BAD, "relational", "bad_integrity.py"))
        if f.rule == "TS118"]
    # table/partition fingerprint primitives, direct vote, raw builder,
    # rank-local raise + constructor — the facade verbs stay clean
    assert len(found) == 6, found
    assert all("exec/integrity" in f.message for f in found)


def test_ts118_scoping():
    prim = ("def f(integ, table):\n"
            "    return integ.table_fingerprint(table)\n")
    raised = ("def f(DataIntegrityError):\n"
              "    raise DataIntegrityError('x', site='s')\n")
    # fires in the operator/transport/topo dirs the audit tier covers
    for src in (prim, raised):
        assert any(f.rule == "TS118" for f in ast_lint.lint_source(
            "cylon_tpu/relational/join.py", src))
        assert any(f.rule == "TS118" for f in ast_lint.lint_source(
            "cylon_tpu/parallel/shuffle.py", src))
        assert any(f.rule == "TS118" for f in ast_lint.lint_source(
            "cylon_tpu/topo/exchange.py", src))
    # the defining facade and the rest of exec/ are exempt (the
    # checkpoint/pipeline callers route through the facade's verbs and
    # the facade itself must hash/raise)
    for src in (prim, raised):
        assert not any(f.rule == "TS118" for f in ast_lint.lint_source(
            "cylon_tpu/exec/integrity.py", src))
        assert not any(f.rule == "TS118" for f in ast_lint.lint_source(
            "cylon_tpu/exec/checkpoint.py", src))
    # the sanctioned facade verbs stay clean where the rule applies
    clean = ("def f(integ, mesh, tgt, cols, outs, per_dest, table):\n"
             "    integ.conserve_exchange(None, per_dest, 0, 8)\n"
             "    if integ.armed():\n"
             "        integ.verify_exchange(mesh, tgt, cols, outs, "
             "per_dest)\n"
             "        integ.audit_table(table, site='s', phase='p')\n")
    assert not any(f.rule == "TS118" for f in ast_lint.lint_source(
        "cylon_tpu/relational/join.py", clean))


def test_fixture_package_is_dirty():
    found = ast_lint.lint_paths([BAD])
    assert {f.rule for f in found} >= {"TS101", "TS102", "TS103", "TS104",
                                       "TS105", "TS106", "TS107", "TS108",
                                       "TS109", "TS110", "TS111", "TS112",
                                       "TS113", "TS114", "TS115", "TS116",
                                       "TS117", "TS118"}


# ---------------------------------------------------------------------------
# coherence pass (CX4xx): fixtures, call graph, taint, vote dominance
# ---------------------------------------------------------------------------

COH = os.path.join(BAD, "coherence")


def _cx_rules(name):
    rep = coherence.analyze_paths([os.path.join(COH, name)])
    return [f.rule for f in rep.findings]


def test_cx_fixtures_fire_exactly_their_rule():
    assert _cx_rules("bad_tainted_branch.py") == ["CX401"]
    assert _cx_rules("bad_path_dependent.py") == ["CX402"]
    assert _cx_rules("bad_vote_after_collective.py") == ["CX403"]
    assert _cx_rules("bad_raise_post_collective.py") == ["CX404"]


def test_cx_fixture_package_fires_all_four():
    rep = coherence.analyze_paths([COH])
    assert sorted(f.rule for f in rep.findings) == [
        "CX401", "CX402", "CX403", "CX404"]


def test_callgraph_propagates_collective_entry():
    files = {
        "cylon_tpu/fake/a.py":
            "def leafop(mesh, t):\n"
            "    return exchange(mesh, t)\n",
        "cylon_tpu/fake/b.py":
            "def mid(mesh, t):\n"
            "    return leafop(mesh, t)\n\n\n"
            "def top(mesh, t):\n"
            "    return mid(mesh, t)\n\n\n"
            "def voter(mesh, x):\n"
            "    return consensus_code(mesh, x)\n\n\n"
            "def pure(x):\n"
            "    return x + 1\n",
    }
    an = coherence.Analyzer(files)
    info = {f.qualname: f for f in an.functions}
    assert info["leafop"].enters_data          # facade seed
    assert info["mid"].enters_data             # direct call edge
    assert info["top"].enters_data             # transitive, via fixpoint
    assert info["voter"].enters_consensus and not info["voter"].enters_data
    assert not info["pure"].enters_data
    assert not info["pure"].enters_consensus


def test_registry_harvest_seeds_data_builders():
    src = (
        "def _make(mesh):\n"
        "    def _sortish_fn(t):\n"
        "        return t\n"
        "    declare_builder(f\"{__name__}._sortish_fn\", _sortish_fn,\n"
        "                    collectives={\"all_to_all\"})\n"
        "    return _sortish_fn\n")
    an = coherence.Analyzer({"cylon_tpu/fake/reg.py": src})
    assert "_sortish_fn" in an.data_builders
    assert an.classify("_sortish_fn") == "data"


def test_taint_flows_through_assignment_and_returns():
    src = (
        "def my_rank():\n"
        "    return jax.process_index()\n\n\n"
        "def step(mesh, t):\n"
        "    t = exchange(mesh, t)\n"
        "    r = my_rank()\n"               # returns-taint across the call
        "    k = r + 1\n"                   # taint through assignment
        "    if k > 0:\n"
        "        t = t[:1]\n"
        "    return exchange(mesh, t)\n")
    rep = coherence.analyze_source("cylon_tpu/fake/taint.py", src)
    assert [(f.rule, f.line) for f in rep.findings] == [("CX401", 9)]


def test_consensus_vote_sanitizes_branch():
    src = (
        "def step(mesh, t):\n"
        "    t = exchange(mesh, t)\n"
        "    r = jax.process_index()\n"
        "    voted = consensus_code(mesh, r)\n"   # sanitizer: all ranks agree
        "    if voted:\n"
        "        t = t[:1]\n"
        "    return exchange(mesh, t)\n")
    rep = coherence.analyze_source("cylon_tpu/fake/voted.py", src)
    assert rep.findings == []


def test_vote_before_loop_dominates():
    src = (
        "def adopt_plan(mesh, t, plan):\n"
        "    skew_plan_consensus(mesh, plan)\n"
        "    for _ in range(2):\n"
        "        t = split_exchange(mesh, t, plan)\n"
        "    return t\n")
    rep = coherence.analyze_source("cylon_tpu/fake/skew.py", src)
    assert rep.findings == []
    assert rep.vote_summary["skew"] == ["cylon_tpu/fake/skew.py:2"]


def test_vote_moved_after_collective_fires():
    # the same function with the vote after its dependent collective —
    # the dominance proof must break
    src = (
        "def adopt_plan(mesh, t, plan):\n"
        "    t = split_exchange(mesh, t, plan)\n"
        "    skew_plan_consensus(mesh, plan)\n"
        "    return t\n")
    rep = coherence.analyze_source("cylon_tpu/fake/skew.py", src)
    assert [f.rule for f in rep.findings] == ["CX403"]
    assert rep.vote_summary["skew"] == []


def test_vote_on_one_path_only_fires():
    src = (
        "def adopt_plan(mesh, t, plan, cheap):\n"
        "    if cheap:\n"
        "        skew_plan_consensus(mesh, plan)\n"
        "    return split_exchange(mesh, t, plan)\n")
    rep = coherence.analyze_source("cylon_tpu/fake/skew.py", src)
    assert [f.rule for f in rep.findings] == ["CX403"]


def test_vote_in_branch_test_dominates_body():
    # the drain idiom: the vote is the branch condition itself
    src = (
        "def maybe_abort(mesh, env):\n"
        "    if drain_requested(env):\n"
        "        drain_abort('preempt')\n")
    rep = coherence.analyze_source("cylon_tpu/fake/drain.py", src)
    assert rep.findings == []
    assert rep.vote_summary["drain"] == ["cylon_tpu/fake/drain.py:2"]


def test_cx_suppression_honored():
    src = (
        "def tainted(mesh, table, probe, exchange):\n"
        "    out = exchange(mesh, table)\n"
        "    kind, armed = probe('guard')\n"
        "    if armed:  # tracecheck: off[CX401] — fixture for the test\n"
        "        kind = 'armed'\n"
        "    return exchange(mesh, out)\n")
    rep = coherence.analyze_source("cylon_tpu/fake/sup.py", src)
    assert rep.findings == []
    assert [f.rule for f in rep.raw] == ["CX401"]


def test_package_coherence_clean_and_votes_dominate():
    rep = coherence.analyze_paths([PKG])
    assert [str(f) for f in rep.findings] == []
    # the four plan votes are each proven to dominate at >=1 real site
    for kind in ("skew", "topo", "ckpt", "drain"):
        assert rep.vote_summary.get(kind), kind


# ---------------------------------------------------------------------------
# jaxpr pass: required op families verify clean; seeded hazards are caught
# ---------------------------------------------------------------------------

def test_jaxpr_pass_required_builders(env8):
    from cylon_tpu.analysis import jaxpr_check, registry
    decls = registry.collect()
    by_tag = {t for d in decls for t in d.tags}
    assert {"join", "sort", "groupby", "shuffle"} <= by_tag
    required = [d for d in decls
                if set(d.tags) & {"join", "sort", "groupby", "shuffle"}]
    findings = []
    for decl in required:
        findings.extend(jaxpr_check.verify_builder(decl, env8.mesh))
    assert findings == [], "\n".join(map(str, findings))


def test_jaxpr_pass_catches_conditional_collective(env8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from cylon_tpu.analysis import jaxpr_check
    from cylon_tpu.ctx.context import ROW_AXIS

    def per_shard(flag, col):
        # the deadlock class: collective participation depends on data
        return jax.lax.cond(flag[0] > 0,
                            lambda c: jax.lax.psum(c, ROW_AXIS),
                            lambda c: c, col)

    fn = jax.jit(jax.shard_map(per_shard, mesh=env8.mesh,
                               in_specs=(P(), P(ROW_AXIS)),
                               out_specs=P(ROW_AXIS)))
    S = jax.ShapeDtypeStruct
    decl = BuilderDecl(
        builder="fixture.conditional_psum",
        trace=lambda mesh: jax.make_jaxpr(fn)(
            S((1,), np.int32), S((8 * 1024,), np.float64)),
        collectives=frozenset({"psum"}))
    found = jaxpr_check.verify_builder(decl, env8.mesh)
    assert any(f.rule == "JX201" for f in found), found


def test_jaxpr_pass_catches_widening(env8):
    import jax
    from jax.sharding import PartitionSpec as P
    from cylon_tpu.analysis import jaxpr_check
    from cylon_tpu.ctx.context import ROW_AXIS
    import jax.numpy as jnp

    def per_shard(col):
        # the hazard: a stray promotion doubles a row-scale array's bytes
        return jnp.cumsum(col.astype(jnp.int64))

    fn = jax.jit(jax.shard_map(per_shard, mesh=env8.mesh,
                               in_specs=(P(ROW_AXIS),),
                               out_specs=P(ROW_AXIS)))
    S = jax.ShapeDtypeStruct
    decl = BuilderDecl(
        builder="fixture.widening_cumsum",
        trace=lambda mesh: jax.make_jaxpr(fn)(S((8 * 1024,), np.int32)))
    found = jaxpr_check.verify_builder(decl, env8.mesh)
    assert any(f.rule == "JX203" for f in found), found


def test_jaxpr_pass_catches_undeclared_collective(env8):
    import jax
    from jax.sharding import PartitionSpec as P
    from cylon_tpu.analysis import jaxpr_check
    from cylon_tpu.ctx.context import ROW_AXIS

    def per_shard(col):
        return jax.lax.psum(col, ROW_AXIS)

    fn = jax.jit(jax.shard_map(per_shard, mesh=env8.mesh,
                               in_specs=(P(ROW_AXIS),),
                               out_specs=P()))
    S = jax.ShapeDtypeStruct
    decl = BuilderDecl(
        builder="fixture.undeclared_psum",
        trace=lambda mesh: jax.make_jaxpr(fn)(S((8 * 1024,), np.float64)),
        collectives=frozenset())  # declaration says pure-local
    found = jaxpr_check.verify_builder(decl, env8.mesh)
    assert any(f.rule == "JX205" for f in found), found


# ---------------------------------------------------------------------------
# runtime sentinel
# ---------------------------------------------------------------------------

def test_retrace_sentinel_attributes_compiles(env8):
    import jax.numpy as jnp
    from cylon_tpu.analysis import runtime
    from cylon_tpu.parallel import shuffle
    st = runtime.enable()
    runtime.reset()
    tgt = jnp.zeros(8 * 64, jnp.int32)
    shuffle._count_fn(env8.mesh, 8)(tgt)
    shuffle._count_fn(env8.mesh, 8)(tgt)  # cached program, cached compile
    key = "cylon_tpu.parallel.shuffle._count_fn"
    compiling = {tag[0] for tag in st.compiles}
    # at most one compiling call for the signature; second call is a hit
    assert all(n == 1 for n in st.compiles.values()), dict(st.compiles)
    if compiling:  # program may be compile-cached from an earlier test
        assert compiling == {key}
    assert runtime.check_budgets() == []
    runtime.reset()


def test_retrace_budget_violations_detected():
    from cylon_tpu.analysis import runtime
    st = runtime.enable()
    runtime.reset()
    st.compiles[("some.builder", ((8,),))] = 3        # same-signature retrace
    st.builds["other.builder"] = 99                   # program explosion
    found = runtime.check_budgets(budgets={"other.builder": 4})
    assert {r for r, _b, _m in found} == {"RT301", "RT302"}
    runtime.reset()


def test_transfer_ledger_counts_funnel_pulls(env8):
    import jax.numpy as jnp
    from cylon_tpu.analysis import runtime
    from cylon_tpu.utils.host import host_array
    with runtime.transfer_scope() as ledger:
        host_array(jnp.arange(8))
        host_array(np.arange(8))  # already host: no pull recorded
    assert ledger["host_array"] == 1


# ---------------------------------------------------------------------------
# slow: full registry + CLI round-trip
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_jaxpr_pass_all_registered_builders(env8):
    from cylon_tpu.analysis import jaxpr_check, registry
    decls = registry.collect()
    assert len(decls) >= 12
    findings = jaxpr_check.verify_all(env8.mesh, decls)
    assert findings == [], "\n".join(map(str, findings))


@pytest.mark.slow
def test_cli_strict_green_on_repo_red_on_fixtures():
    script = os.path.join(REPO, "scripts", "check_trace_safety.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run([sys.executable, script, "--strict"],
                        capture_output=True, text=True, env=env, cwd=REPO)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run([sys.executable, script, BAD],
                         capture_output=True, text=True, env=env, cwd=REPO)
    assert bad.returncode == 1
    assert "TS102" in bad.stdout and ":" in bad.stdout.splitlines()[0]


@pytest.mark.slow
def test_cli_json_schema_and_suppressed_flag(tmp_path):
    script = os.path.join(REPO, "scripts", "check_trace_safety.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = tmp_path / "findings.json"
    r = subprocess.run([sys.executable, script, "--json", str(out), COH],
                       capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 1
    payload = json.loads(out.read_text())
    assert payload["version"] == 1
    assert set(payload["counts"]) >= {"CX401", "CX402", "CX403", "CX404"}
    for f in payload["findings"]:
        assert set(f) == {"rule", "file", "line", "message", "suppressed"}
    by_rule = {}
    for f in payload["findings"]:
        by_rule.setdefault(f["rule"], []).append(f)
    # the CX403 fixture's def-line TS115 suppression is reported, flagged
    assert all(f["suppressed"] for f in by_rule["TS115"])
    for cx in ("CX401", "CX402", "CX403", "CX404"):
        assert [f["suppressed"] for f in by_rule[cx]] == [False]


@pytest.mark.slow
def test_cli_suppression_audit_and_stale_failure(tmp_path):
    script = os.path.join(REPO, "scripts", "check_trace_safety.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    dead = tmp_path / "dead.py"
    dead.write_text("def f(x):  # tracecheck: off[TS101]\n    return x\n")
    audit = subprocess.run(
        [sys.executable, script, "--audit-suppressions", str(dead)],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert audit.returncode == 0
    assert "TS101" in audit.stdout
    fail = subprocess.run(
        [sys.executable, script, "--fail-stale-suppressions", str(dead)],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert fail.returncode == 1
    clean = subprocess.run(
        [sys.executable, script, "--audit-suppressions", PKG],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert clean.returncode == 0
    assert "clean" in clean.stdout + clean.stderr


@pytest.mark.slow
def test_cli_gate_cache_warm_and_bypass():
    script = os.path.join(REPO, "scripts", "check_trace_safety.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    first = subprocess.run([sys.executable, script, COH],
                           capture_output=True, text=True, env=env, cwd=REPO)
    warm = subprocess.run([sys.executable, script, COH],
                          capture_output=True, text=True, env=env, cwd=REPO)
    assert warm.returncode == first.returncode == 1
    assert "coherence pass: cached" in warm.stderr
    assert "(4 cached)" in warm.stderr
    # identical findings from the cached path
    assert warm.stdout == first.stdout
    cold = subprocess.run([sys.executable, script, "--no-cache", COH],
                          capture_output=True, text=True, env=env, cwd=REPO)
    assert "(0 cached)" in cold.stderr
    assert "coherence pass: ran" in cold.stderr
    assert cold.stdout == first.stdout
