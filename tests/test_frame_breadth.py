"""Frame breadth added for reference parity (frame.py:187-2421): index
drop semantics + propagation, dropna/fillna/isna/notna, frame arithmetic,
applymap/iterrows, Row/Scalar."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.status import CylonKeyError

from utils import assert_frames_equal


@pytest.fixture(params=["env1", "env4"])
def env(request):
    return request.getfixturevalue(request.param)


@pytest.fixture
def data(rng):
    df = pd.DataFrame({"id": np.arange(20),
                       "v": rng.standard_normal(20),
                       "w": rng.integers(0, 5, 20).astype(float)})
    df.loc[df.index % 4 == 0, "v"] = np.nan
    return df


def test_set_index_drop_semantics(env, data):
    d = ct.DataFrame(data, env=env)
    di = d.set_index("id")            # pandas default: drop=True
    assert "id" not in di.columns
    with pytest.raises(CylonKeyError):
        di["id"]
    pd.testing.assert_frame_equal(di.to_pandas(), data.set_index("id"),
                                  check_dtype=False)
    dk = d.set_index("id", drop=False)
    assert "id" in dk.columns
    pd.testing.assert_frame_equal(dk.to_pandas(),
                                  data.set_index("id", drop=False),
                                  check_dtype=False)
    # reset_index restores the column either way
    assert "id" in di.reset_index().columns


def test_index_survives_sort_filter_head(env, data):
    d = ct.DataFrame(data, env=env).set_index("id")
    s = d.sort_values("v", env=env)
    assert s._index == "id"
    exp = data.set_index("id").sort_values("v")
    pd.testing.assert_frame_equal(s.to_pandas(), exp, check_dtype=False)
    f = d[d["w"] > 1.0]
    exp = data.set_index("id")
    exp = exp[exp.w > 1.0]
    pd.testing.assert_frame_equal(f.to_pandas(), exp, check_dtype=False)


def test_merge_ignores_dropped_index(env, data):
    d = ct.DataFrame(data, env=env).set_index("id")
    other = ct.DataFrame(pd.DataFrame({"w": [0.0, 1.0, 2.0],
                                       "z": [9, 8, 7]}), env=env)
    j = d.merge(other, on="w", env=env)
    exp = data.drop(columns="id").merge(pd.DataFrame(
        {"w": [0.0, 1.0, 2.0], "z": [9, 8, 7]}), on="w")
    assert_frames_equal(j.to_pandas().sort_values(["w", "v", "z"]).reset_index(drop=True),
                        exp.sort_values(["w", "v", "z"]).reset_index(drop=True))


def test_isna_notna_dropna_fillna(env, data):
    df = data.copy()
    d = ct.DataFrame(df, env=env)
    pd.testing.assert_frame_equal(d.isna().to_pandas(), df.isna(),
                                  check_dtype=False)
    pd.testing.assert_frame_equal(d.notna().to_pandas(), df.notna(),
                                  check_dtype=False)
    pd.testing.assert_frame_equal(d.dropna().to_pandas().reset_index(drop=True),
                                  df.dropna().reset_index(drop=True),
                                  check_dtype=False)
    pd.testing.assert_frame_equal(
        d.fillna(0.5).to_pandas(), df.fillna(0.5), check_dtype=False)
    # subset + how=all
    pd.testing.assert_frame_equal(
        d.dropna(subset=["v"], how="all").to_pandas().reset_index(drop=True),
        df.dropna(subset=["v"], how="all").reset_index(drop=True),
        check_dtype=False)


def test_frame_arithmetic(env, data):
    df = data.fillna(1.0)
    d = ct.DataFrame(df, env=env)
    pd.testing.assert_frame_equal((d * 2).to_pandas(), df * 2,
                                  check_dtype=False)
    pd.testing.assert_frame_equal((d + 1).to_pandas(), df + 1,
                                  check_dtype=False)
    pd.testing.assert_frame_equal((-d).to_pandas(), -df, check_dtype=False)
    pd.testing.assert_frame_equal((d - d).to_pandas(), df - df,
                                  check_dtype=False)
    pd.testing.assert_frame_equal(d.abs().to_pandas(), df.abs(),
                                  check_dtype=False)


def test_applymap_iterrows_row_scalar(env, data):
    df = data.fillna(0.0)
    d = ct.DataFrame(df, env=env)
    am = d.applymap(lambda x: x * 2)
    pd.testing.assert_frame_equal(am.to_pandas(), df.map(lambda x: x * 2),
                                  check_dtype=False)
    rows = list(d.iterrows())
    assert len(rows) == len(df)
    # Row / Scalar (reference row.hpp / scalar.hpp)
    r = d.row(3)
    assert r["id"] == df.iloc[3]["id"]
    sc = r.scalar("v")
    assert sc == df.iloc[3]["v"] and not sc.is_null
    assert list(r.to_dict()) == list(df.columns)


def test_index_drop_false_survives_loc_iloc_arith(env, data):
    """Regressions from review: drop=False index must survive loc/iloc and
    elementwise ops; drop=True index must survive isna/arithmetic; fillna
    must skip type-incompatible string columns instead of failing."""
    dk = ct.DataFrame(data, env=env).set_index("id", drop=False)
    assert "id" in dk.loc[[2, 3]].columns
    assert "id" in dk.iloc[0:2].columns
    d = ct.DataFrame(data, env=env).set_index("id")
    assert d.isna()._index == "id"
    assert (d * 2)._index == "id"
    assert d.shape == (20, 2) and "id" not in d.dtypes and "id" not in d
    # applymap keeps index labels untouched
    am = d.fillna(0.0).applymap(lambda x: x * 2)
    exp = data.set_index("id").fillna(0.0).map(lambda x: x * 2)
    pd.testing.assert_frame_equal(am.to_pandas(), exp, check_dtype=False)
    # string + numeric fill: string column unchanged, float filled
    sdf = pd.DataFrame({"s": ["a", None, "b"], "v": [1.0, np.nan, 3.0]})
    sd = ct.DataFrame(sdf, env=env).fillna(0.0)
    got = sd.to_pandas()
    assert got["v"].tolist() == [1.0, 0.0, 3.0]
    assert pd.isna(got["s"][1])  # string column left as-is
    # row() hides a dropped index column
    r = d.row(0)
    assert "id" not in r.to_dict()


def test_prefix_suffix_aliases_where_pydict(env1):
    import pandas as pd
    df = pd.DataFrame({"a": [1, 2, 3, 4], "b": [1.0, None, 3.0, 4.0]})
    f = ct.DataFrame(df, env=env1)
    assert f.add_prefix("x_").columns == ["x_a", "x_b"]
    assert f.add_suffix("_y").columns == ["a_y", "b_y"]
    # isnull/notnull aliases
    assert f.isnull().to_pandas()["b"].tolist() == [False, True, False, False]
    assert f.notnull().to_pandas()["a"].all()
    # where with a bool Series: masked slots null (pandas parity)
    cond = f["a"] > 2
    w = f.where(cond).to_pandas()
    exp = df.where(df["a"] > 2)
    assert w["b"].isna().tolist() == exp["b"].isna().tolist()
    # where with other: masked slots filled
    w2 = f.where(cond, 0).to_pandas()
    assert w2["a"].tolist() == [0, 0, 3, 4]
    # to_pydict round trip
    pd2 = f.to_pydict()
    assert pd2["a"] == [1, 2, 3, 4]
    # show/to_string smoke
    assert "a" in f.to_string()
    f.show(2)
