"""Driver-entry regression tests.

Round-1 lesson (VERDICT.md): the driver's multichip dryrun must be exercised
by the suite itself, and it must never touch any backend other than cpu —
the round-1 dryrun died because ingestion staged arrays on the default
(accelerator) backend before distributing.  The subprocess test reproduces
the driver environment (host-device-count flag only, no JAX_PLATFORMS pin)
and asserts the cpu client is the ONLY initialized backend.
"""

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_8():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__
        __graft_entry__.dryrun_multichip(8)
    finally:
        sys.path.remove(REPO)


def test_entry_jit_compiles_and_runs():
    import jax
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__
        fn, args = __graft_entry__.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)
    finally:
        sys.path.remove(REPO)


def test_dryrun_touches_only_cpu_backend():
    """Run the dryrun in a clean subprocess (driver-style env: device-count
    flag, NO platform pin) and assert no non-cpu backend got initialized."""
    code = """
import jax, sys
sys.path.insert(0, {repo!r})
import __graft_entry__
__graft_entry__.dryrun_multichip(8)
try:
    from jax._src import xla_bridge
    backends = set(xla_bridge._backends)
except Exception:
    backends = set()  # private probe gone in this jax version: skip assert
assert backends <= {{"cpu"}}, f"non-cpu backends initialized: {{backends}}"
print("OK")
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    r = subprocess.run([sys.executable, "-c", code.format(repo=REPO)],
                       capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
