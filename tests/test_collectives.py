"""Table/column collective surface (reference net/communicator.hpp:31-69,
pycylon net/comm_ops.pyx:34-126): AllGather / Gather / Bcast on tables,
AllReduce on columns."""

import numpy as np
import pandas as pd

import cylon_tpu as ct


def _df(rng, n):
    return pd.DataFrame({"k": rng.integers(0, 50, n).astype(np.int64),
                         "v": rng.random(n),
                         "s": rng.choice(["a", "bb", "c"], n)})


def test_allgather_table(env4, rng):
    df = _df(rng, 23)
    t = ct.Table.from_pandas(df, env4)
    g = env4.allgather(t)
    # every shard holds the full row set, in global (rank, pos) order
    assert np.array_equal(g.valid_counts, np.full(4, 23))
    got = g.to_pandas()
    exp = pd.concat([df] * 4, ignore_index=True)
    # shard s's prefix must equal df in order
    cap = g.capacity
    for s in range(4):
        shard = got.iloc[s * 23:(s + 1) * 23].reset_index(drop=True)
        pd.testing.assert_frame_equal(shard, df.reset_index(drop=True),
                                      check_dtype=False)


def test_gather_table(env4, rng):
    df = _df(rng, 31)
    t = ct.Table.from_pandas(df, env4)
    g = env4.gather(t, root=2)
    assert g.valid_counts.tolist() == [0, 0, 31, 0]
    pd.testing.assert_frame_equal(g.to_pandas(), df.reset_index(drop=True),
                                  check_dtype=False)


def test_bcast_table(env4, rng):
    df = _df(rng, 17)
    t = ct.Table.from_pandas(df, env4)
    g = env4.gather(t, root=1)
    b = env4.bcast(g, root=1)
    assert np.array_equal(b.valid_counts, np.full(4, 17))
    got = b.to_pandas()
    for s in range(4):
        shard = got.iloc[s * 17:(s + 1) * 17].reset_index(drop=True)
        pd.testing.assert_frame_equal(shard, df.reset_index(drop=True),
                                      check_dtype=False)


def test_allreduce_column(env4):
    # 4 shards x capacity rows; elementwise reduce across shards
    n = 8  # rows per shard after ingest of 32
    df = pd.DataFrame({"x": np.arange(32, dtype=np.int64)})
    t = ct.Table.from_pandas(df, env4)
    cap = t.capacity
    col = t.column("x")
    red = env4.allreduce(col, "sum")
    host = np.asarray(col.data).reshape(4, cap)
    assert np.array_equal(red, host.sum(axis=0))
    assert np.array_equal(env4.allreduce(col, "max"), host.max(axis=0))
    assert np.array_equal(env4.allreduce(col, "min"), host.min(axis=0))


def test_collectives_world1(env1, rng):
    df = _df(rng, 9)
    t = ct.Table.from_pandas(df, env1)
    assert env1.allgather(t) is t
    pd.testing.assert_frame_equal(env1.gather(t, 0).to_pandas(),
                                  df.reset_index(drop=True),
                                  check_dtype=False)
    assert env1.bcast(t, 0) is t
