"""High-cardinality string keys: the hashed-codes path (core.column.
HashedStrings + cylon_tpu.native.strhash).

Reference analog: non-fixed-width keys flatten to binary and hash
(util/flatten_array.cpp + util/murmur3.cpp).  Here: device codes are
stable 64-bit value hashes (no n-entry dictionary is ever built), raw
values stay host-side, equality ops are exact (up to 64-bit collisions),
ordered ops raise.
Round 5: ordered SORTS on hashed strings now work via value-stable
byte-lane expansion (relational/sort._expand_hashed_string_keys);
min/max/range-compares still raise.
"""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config, native
from cylon_tpu.core.column import Column, HashedStrings
from cylon_tpu.relational import (groupby_aggregate, join_tables,
                                  sort_table, unique_table)

from utils import assert_table_matches


@pytest.fixture
def hashed_mode(monkeypatch):
    """Force the hashed-codes crossover for small test tables."""
    monkeypatch.setattr(config, "STRING_HASH_MIN_ROWS", 100)
    monkeypatch.setattr(config, "STRING_HASH_RATIO", 0.2)


def _keys(rng, n, card=2000):
    return np.asarray([f"user_{i:08d}" for i in
                       rng.integers(0, card, n)], dtype=object)


class TestNativeHash:
    def test_native_builds_and_is_stable(self):
        vals = np.asarray(["a", "bb", "", "ccc", "a"], dtype=object)
        h1, h2 = native.hash_strings(vals), native.hash_strings(vals)
        assert h1.dtype == np.uint64
        np.testing.assert_array_equal(h1, h2)
        assert h1[0] == h1[4] and h1[0] != h1[1]
        # g++ is present in this image: the native path must actually load
        assert native.native_available()

    def test_collision_free_at_200k(self):
        vals = np.asarray([f"v{i}" for i in range(200_000)], dtype=object)
        h = native.hash_strings(vals)
        assert len(np.unique(h)) == len(vals)


class TestEncodeCrossover:
    def test_high_cardinality_skips_dictionary(self, hashed_mode):
        vals = np.asarray([f"k{i}" for i in range(5000)], dtype=object)
        c = Column.from_numpy(vals)
        assert isinstance(c.dictionary, HashedStrings)
        assert c.data.dtype == np.int64
        np.testing.assert_array_equal(c.to_numpy(5000), vals)

    def test_low_cardinality_keeps_dictionary(self, hashed_mode):
        vals = np.asarray(["a", "b", "c"] * 2000, dtype=object)
        c = Column.from_numpy(vals)
        assert not isinstance(c.dictionary, HashedStrings)

    def test_default_thresholds_keep_small_tables_dictionary(self):
        vals = np.asarray([f"k{i}" for i in range(5000)], dtype=object)
        c = Column.from_numpy(vals)
        assert not isinstance(c.dictionary, HashedStrings)


class TestRelationalOps:
    @pytest.mark.parametrize("world", ["env1", "env4"])
    def test_join_on_hashed_keys(self, world, request, rng, hashed_mode):
        env = request.getfixturevalue(world)
        n = 4000
        ldf = pd.DataFrame({"k": _keys(rng, n), "a": rng.integers(0, 99, n)})
        rdf = pd.DataFrame({"k": _keys(rng, n), "b": rng.integers(0, 99, n)})
        lt, rt = ct.Table.from_pandas(ldf, env), ct.Table.from_pandas(rdf, env)
        assert isinstance(lt.column("k").dictionary, HashedStrings)
        j = join_tables(lt, rt, "k", "k", how="inner")
        exp = ldf.merge(rdf, on="k")
        got = j.to_pandas().sort_values(["k", "a", "b"]).reset_index(drop=True)
        exp = exp.sort_values(["k", "a", "b"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)

    def test_join_hashed_vs_dictionary_side(self, env4, rng, hashed_mode,
                                            monkeypatch):
        """One side hashed, the other dictionary-encoded: unification
        re-codes the dictionary side into hash space."""
        n = 4000
        ldf = pd.DataFrame({"k": _keys(rng, n), "a": rng.integers(0, 9, n)})
        lt = ct.Table.from_pandas(ldf, env4)
        assert isinstance(lt.column("k").dictionary, HashedStrings)
        monkeypatch.setattr(config, "STRING_HASH_MIN_ROWS", 10**12)
        rdf = pd.DataFrame({"k": _keys(rng, 500, card=300),
                            "b": rng.integers(0, 9, 500)})
        rt = ct.Table.from_pandas(rdf, env4)
        assert not isinstance(rt.column("k").dictionary, HashedStrings)
        j = join_tables(lt, rt, "k", "k", how="inner")
        exp = ldf.merge(rdf, on="k")
        assert j.row_count == len(exp)
        got = j.to_pandas().sort_values(["k", "a", "b"]).reset_index(drop=True)
        exp = exp.sort_values(["k", "a", "b"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)

    def test_groupby_on_hashed_keys(self, env4, rng, hashed_mode):
        n = 4000
        df = pd.DataFrame({"k": _keys(rng, n), "v": rng.random(n)})
        t = ct.Table.from_pandas(df, env4)
        g = groupby_aggregate(t, "k", [("v", "sum"), ("v", "count"),
                                       ("k", "nunique")])
        eg = (df.groupby("k", as_index=False)
              .agg(v_sum=("v", "sum"), v_count=("v", "count"),
                   k_nunique=("k", "nunique")))
        assert_table_matches(g, eg)

    def test_unique_and_filter(self, env4, rng, hashed_mode):
        n = 3000
        df = pd.DataFrame({"k": _keys(rng, n, card=500),
                           "v": np.arange(n, dtype=np.int64)})
        t = ct.Table.from_pandas(df, env4)
        u = unique_table(t, ["k"])
        assert u.row_count == df["k"].nunique()
        f = ct.DataFrame(df, env=env4)
        target = str(df["k"].iloc[0])
        got = f[f["k"] == target].to_pandas()
        exp = df[df["k"] == target]
        assert len(got) == len(exp)
        got_ne = f[f["k"] != target].to_pandas()
        assert len(got_ne) == len(df) - len(exp)


class TestOrderedOpsRaise:
    def test_range_compare_raises(self, env1, rng, hashed_mode):
        df = pd.DataFrame({"k": _keys(rng, 2000)})
        f = ct.DataFrame(df, env=env1)
        with pytest.raises(Exception, match="hashed|ordered"):
            f[f["k"] < "user_5"]

    def test_min_max_agg_raises(self, env1, rng, hashed_mode):
        df = pd.DataFrame({"g": np.zeros(2000, np.int64),
                           "k": _keys(rng, 2000)})
        t = ct.Table.from_pandas(df, env1)
        with pytest.raises(Exception, match="hashed"):
            groupby_aggregate(t, "g", [("k", "min")])


class TestMaterialization:
    def test_to_pandas_round_trip_with_nulls(self, env4, rng, hashed_mode):
        vals = _keys(rng, 3000).astype(object)
        vals[::11] = None
        df = pd.DataFrame({"k": vals, "v": np.arange(3000)})
        t = ct.Table.from_pandas(df, env4)
        assert isinstance(t.column("k").dictionary, HashedStrings)
        back = t.to_pandas()
        assert back["k"].isna().sum() == pd.isna(vals).sum()
        ok = ~pd.isna(vals)
        np.testing.assert_array_equal(back["k"].to_numpy()[ok],
                                      vals[ok])

    def test_fillna_on_hashed(self, env1, rng, hashed_mode):
        vals = _keys(rng, 2000).astype(object)
        vals[::7] = None
        df = pd.DataFrame({"k": vals})
        f = ct.DataFrame(df, env=env1)
        out = f["k"].fillna("MISSING").to_pandas()
        exp = pd.Series(vals, name="k").fillna("MISSING")
        np.testing.assert_array_equal(np.asarray(out), exp.to_numpy())


class TestReviewRegressions:
    def test_series_min_max_raise(self, env1, rng, hashed_mode):
        df = pd.DataFrame({"k": _keys(rng, 2000)})
        f = ct.DataFrame(df, env=env1)
        with pytest.raises(Exception, match="hashed"):
            f["k"].min()
        with pytest.raises(Exception, match="hashed"):
            f["k"].max()
        assert f["k"].count() == 2000  # count still fine

    def test_series_vs_series_ordered_raises_eq_works(self, env1, rng,
                                                      hashed_mode):
        df = pd.DataFrame({"a": _keys(rng, 2000), "b": _keys(rng, 2000)})
        f = ct.DataFrame(df, env=env1)
        with pytest.raises(Exception, match="hashed|ordered"):
            _ = f["a"] < f["b"]
        eq = f[f["a"] == f["b"]].to_pandas()
        assert len(eq) == (df["a"] == df["b"]).sum()

    def test_crossover_requires_x64(self, rng, monkeypatch):
        monkeypatch.setattr(config, "STRING_HASH_MIN_ROWS", 100)
        monkeypatch.setattr(config, "STRING_HASH_RATIO", 0.2)
        monkeypatch.setattr(config, "X64_ENABLED", False)
        c = Column.from_numpy(_keys(rng, 5000))
        assert not isinstance(c.dictionary, HashedStrings)

    def test_loc_on_hashed_index(self, env1, rng, hashed_mode):
        df = pd.DataFrame({"k": np.asarray([f"id_{i}" for i in range(2000)],
                                           dtype=object),
                           "v": np.arange(2000, dtype=np.int64)})
        f = ct.DataFrame(df, env=env1).set_index("k")
        assert isinstance(f._table.column("k").dictionary, HashedStrings)
        out = f.loc[["id_7", "id_42"]].to_pandas()
        assert sorted(out["v"].tolist()) == [7, 42]


class TestStringSort:
    """Lexical sort on hashed (high-cardinality) string keys — VERDICT r4
    missing #1.  Reference: arrow_kernels.hpp:53 IndexSortKernel over
    StringArray; distributed via MapToSortPartitions."""

    def _check(self, df, env, by="k", ascending=True, npos="last"):
        t = ct.Table.from_pandas(df, env)
        assert isinstance(t.column("k").dictionary, HashedStrings)
        out = sort_table(t, by, ascending=ascending, nulls_position=npos)
        got = out.to_pandas()
        exp = df.sort_values(by, ascending=ascending,
                             na_position=npos).reset_index(drop=True)
        assert got["k"].tolist() == exp["k"].tolist()
        if "v" in df:
            # ties (equal keys) may order differently; compare key-wise sums
            assert got.groupby("k", dropna=False)["v"].sum().sort_index() \
                .tolist() == exp.groupby("k", dropna=False)["v"].sum() \
                .sort_index().tolist()

    def test_sort_matches_pandas_w1(self, env1, rng, hashed_mode):
        df = pd.DataFrame({"k": _keys(rng, 3000, card=100000),
                           "v": np.arange(3000)})
        self._check(df, env1)

    def test_sort_matches_pandas_w4(self, env4, rng, hashed_mode):
        df = pd.DataFrame({"k": _keys(rng, 4000, card=100000),
                           "v": np.arange(4000)})
        self._check(df, env4)

    def test_sort_matches_pandas_w8(self, env8, rng, hashed_mode):
        df = pd.DataFrame({"k": _keys(rng, 6000, card=100000),
                           "v": np.arange(6000)})
        self._check(df, env8)

    def test_descending(self, env4, rng, hashed_mode):
        df = pd.DataFrame({"k": _keys(rng, 2000, card=50000)})
        self._check(df, env4, ascending=False)

    def test_nulls_first_and_last(self, env4, rng, hashed_mode):
        k = _keys(rng, 2000, card=50000)
        k[rng.random(2000) < 0.05] = None
        df = pd.DataFrame({"k": k, "v": np.arange(2000)})
        t = ct.Table.from_pandas(df, env4)
        for npos in ("last", "first"):
            got = sort_table(t, "k", nulls_position=npos).to_pandas()
            exp = df.sort_values("k", na_position=npos) \
                .reset_index(drop=True)
            assert got["k"].tolist() == exp["k"].tolist()

    def test_mixed_string_and_numeric_keys(self, env4, rng, hashed_mode):
        df = pd.DataFrame({"k": _keys(rng, 2500, card=1000),
                           "v": rng.integers(0, 50, 2500)})
        t = ct.Table.from_pandas(df, env4)
        assert isinstance(t.column("k").dictionary, HashedStrings)
        got = sort_table(t, ["k", "v"]).to_pandas()
        exp = df.sort_values(["k", "v"]).reset_index(drop=True)
        assert got["k"].tolist() == exp["k"].tolist()
        assert got["v"].tolist() == exp["v"].tolist()

    def test_variable_length_prefix_order(self, env4, hashed_mode):
        # short strings sort before their extensions; multi-lane depths
        vals = ["b", "ba", "b0", "a" * 9, "a" * 9 + "z", "a" * 8, "aa",
                "", "zz", "z"]
        k = np.asarray([vals[i % len(vals)] + f"_{i}" for i in range(1500)],
                       dtype=object)
        df = pd.DataFrame({"k": k})
        self._check(df, env4)

    def test_deep_common_prefix_rank_fallback(self, env4, hashed_mode):
        # >64 shared prefix bytes: lanes cannot separate; exact dense-rank
        # fallback (single-process)
        pre = "p" * 80
        k = np.asarray([f"{pre}{i:06d}" for i in
                        np.random.default_rng(0).permutation(1500)],
                       dtype=object)
        df = pd.DataFrame({"k": k})
        self._check(df, env4)

    def test_grouped_by_contract(self, env4, rng, hashed_mode):
        # groupby after string sort must take the no-shuffle fast path and
        # still be correct (lane equality == value equality)
        df = pd.DataFrame({"k": _keys(rng, 3000, card=500),
                           "v": rng.random(3000)})
        t = ct.Table.from_pandas(df, env4)
        out = sort_table(t, "k")
        assert out.grouped_by == ("k",)
        got = groupby_aggregate(out, ["k"], [("v", "sum")]).to_pandas()
        exp = df.groupby("k", as_index=False)["v"].sum()
        got = got.sort_values("k").reset_index(drop=True)
        exp = exp.sort_values("k").reset_index(drop=True)
        assert got["k"].tolist() == exp["k"].tolist()
        np.testing.assert_allclose(got["v_sum"], exp["v"])


class TestOrderLanesNative:
    def test_prefix_lanes_order(self):
        vals = np.asarray(["", "a", "ab", "abc", "abcd", "abcde", "b",
                           "aa" * 10], dtype=object)
        L = 3
        lanes = native.prefix_lanes(vals, L)
        assert lanes.shape == (len(vals), L)
        key = [tuple(r) for r in lanes]
        order = sorted(range(len(vals)), key=lambda i: key[i])
        exp = sorted(range(len(vals)), key=lambda i: vals[i])
        assert order == exp

    def test_max_adjacent_lcp(self):
        assert native.max_adjacent_lcp(
            np.asarray(["ab", "abc", "abd", "b"], dtype=object)) == 2
        assert native.max_adjacent_lcp(
            np.asarray(["x"], dtype=object)) == 0
        assert native.max_adjacent_lcp(
            np.asarray(["q", "q"], dtype=object)) == 0

    def test_trailing_nul_bytes(self, env4, hashed_mode):
        # 'ab' vs 'ab\0': zero-padded lanes are identical — the length
        # lane must break the tie in bytewise order
        base = [f"v{i}" for i in range(300)]
        vals = []
        for b in base:
            vals += [b, b + "\0", b + "\0\0"]
        k = np.asarray(vals, dtype=object)
        df = pd.DataFrame({"k": k})
        t = ct.Table.from_pandas(df, env4)
        assert isinstance(t.column("k").dictionary, HashedStrings)
        got = sort_table(t, "k").to_pandas()
        exp = df.sort_values("k").reset_index(drop=True)
        assert got["k"].tolist() == exp["k"].tolist()
