"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.frame import DataFrame
from cylon_tpu.status import CylonKeyError, InvalidError


def _df(data, env):
    return DataFrame(pd.DataFrame(data), env=env)


class TestNullMaskFilter:
    """frame.py bool-mask filter must treat null predicate rows as False."""

    def test_null_rows_excluded(self, env1):
        df = _df({"s": ["a", None, "b"], "v": [1, 2, 3]}, env1)
        out = df[df["s"] < "b"].to_pandas()
        assert out["v"].tolist() == [1]

    def test_null_rows_excluded_dist(self, env4):
        df = _df({"s": ["a", None, "b", "c", None, "a", "b", "c"],
                  "v": list(range(8))}, env4)
        out = df[df["s"] < "b"].to_pandas()
        assert sorted(out["v"].tolist()) == [0, 5]


class TestNaNSkippingAggs:
    """groupby + Series reductions skip float NaN like pandas skipna=True."""

    def test_groupby_sum_skips_nan(self, env1):
        pdf = pd.DataFrame({"k": [0, 0, 1, 1], "x": [1.0, np.nan, 2.0, 3.0]})
        df = _df(pdf, env1)
        got = (df.groupby("k").sum().to_pandas()
               .sort_values("k").reset_index(drop=True))
        exp = pdf.groupby("k", as_index=False)["x"].sum()
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_groupby_mean_min_count_skip_nan(self, env4):
        rng = np.random.default_rng(0)
        x = rng.random(64)
        x[::5] = np.nan
        pdf = pd.DataFrame({"k": rng.integers(0, 4, 64), "x": x})
        df = _df(pdf, env4)
        got = (df.groupby("k").agg({"x": ["mean", "min", "count"]})
               .to_pandas().sort_values("k").reset_index(drop=True))
        exp = (pdf.groupby("k", as_index=False)
               .agg(x_mean=("x", "mean"), x_min=("x", "min"),
                    x_count=("x", "count")))
        pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                      check_exact=False)

    def test_series_sum_skips_nan(self, env1):
        df = _df({"x": [1.0, np.nan, 2.0]}, env1)
        assert df["x"].sum() == pytest.approx(3.0)
        assert df["x"].count() == 2
        assert df["x"].mean() == pytest.approx(1.5)
        assert df["x"].min() == pytest.approx(1.0)


class TestIlocLocSemantics:
    def test_iloc_list_order_preserved(self, env1):
        df = _df({"v": [10, 11, 12, 13, 14]}, env1)
        assert df.iloc[[3, 1]].to_pandas()["v"].tolist() == [13, 11]

    def test_iloc_list_duplicates(self, env4):
        df = _df({"v": list(range(16))}, env4)
        assert df.iloc[[5, 5, 2]].to_pandas()["v"].tolist() == [5, 5, 2]

    def test_loc_partially_missing_label_raises(self, env1):
        df = _df({"k": [1, 2, 3], "v": [10, 20, 30]}, env1).set_index("k")
        with pytest.raises(CylonKeyError):
            df.loc[[1, 99]]

    def test_loc_string_missing_label_raises(self, env1):
        df = _df({"k": ["a", "b"], "v": [1, 2]}, env1).set_index("k")
        with pytest.raises(CylonKeyError):
            df.loc[["a", "zz"]]


class TestInt64Precision:
    def test_sum_beyond_2_53(self, env1):
        big = (1 << 53) + 1
        df = _df({"x": np.asarray([big, 2], np.int64)}, env1)
        assert df["x"].sum() == big + 2  # float64 round-trip would lose the +1
        assert df["x"].max() == big


class TestSetitemLayoutCheck:
    def test_misaligned_series_rejected(self, env4):
        # same per-shard capacity (8), different valid_counts -> must reject
        a = _df({"v": list(range(24))}, env4)          # (6, 6, 6, 6) cap 8
        b = _df({"w": list(range(24))}, env4)
        from cylon_tpu.relational import repartition
        t = repartition(b.table, (8, 8, 4, 4))          # cap 8 too
        misaligned = DataFrame.from_table(t)
        assert t.capacity == a.table.capacity
        with pytest.raises(InvalidError):
            a["w"] = misaligned["w"]


class TestReviewFindings:
    """Round-2 inline code-review findings."""

    def test_iloc_preserves_nullable_int_dtype(self, env1):
        # nullable int column (e.g. from an outer join) must survive iloc
        l = _df({"k": [1, 2], "a": [10, 20]}, env1)
        r = _df({"k": [2, 3], "b": [5, 6]}, env1)
        m = l.merge(r, on="k", how="outer").sort_values("k")
        out = m.iloc[[2, 0]]
        assert out.dtypes["a"] != "str"
        pdm = m.to_pandas().reset_index(drop=True)
        got = out.to_pandas().reset_index(drop=True)
        exp = pdm.iloc[[2, 0]].reset_index(drop=True)
        import pandas as pd
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_loc_slice_null_index_excluded(self, env1):
        df = _df({"k": ["a", None, "b"], "v": [1, 2, 3]}, env1).set_index("k")
        out = df.loc[:"z"].to_pandas()
        assert sorted(out["v"].tolist()) == [1, 3]  # null label filters False

    def test_min_of_all_nan_is_nan(self, env1):
        df = _df({"x": [np.nan, np.nan]}, env1)
        assert np.isnan(df["x"].min())
        assert np.isnan(df["x"].max())


class TestRound2Advice:
    """Round-2 advisor findings (ADVICE.md r2)."""

    def test_bounded_cache_refresh_keeps_other_entries(self):
        from cylon_tpu.relational.common import BoundedCache
        c = BoundedCache(maxlen=2)
        c.put("a", 1)
        c.put("b", 2)
        c.put("a", 3)  # refresh at capacity must NOT evict "b"
        assert c.get("b") == 2 and c.get("a") == 3 and len(c) == 2

    def test_empty_agg_spec_raises(self, env1):
        df = _df({"k": [1, 1, 2], "v": [1.0, 2.0, 3.0]}, env1)
        with pytest.raises(InvalidError):
            df.groupby("k").agg([])
        with pytest.raises(InvalidError):
            df.groupby("k").agg({})

    def test_env_serial_monotonic(self, env1):
        assert isinstance(env1.serial, int)
        e2 = ct.CylonEnv()  # LocalConfig: no mesh cost
        assert e2.serial > env1.serial


class TestRound3Advice:
    """Round-3 advisor findings (ADVICE.md r3)."""

    def test_fused_pushdown_rejects_string_agg(self, env1):
        # sum over a STRING column of a deferred inner join must raise the
        # same InvalidError the materialized path does — never silently
        # aggregate dictionary codes
        l = _df({"k": [1, 1, 2, 2], "s": ["x", "y", "z", "w"]}, env1)
        r = _df({"k": [1, 2, 2, 3], "b": [1, 2, 3, 4]}, env1)
        j = l.merge(r, on="k", how="inner")
        with pytest.raises(InvalidError):
            j.groupby("k").agg({"s": "sum"})

    def test_fused_pushdown_missing_column_keyerror(self, env1):
        # a nonexistent agg column on a deferred join must raise the same
        # CylonKeyError the materialized path does, not a raw ValueError
        l = _df({"k": [1, 2], "a": [1, 2]}, env1)
        r = _df({"k": [1, 2], "b": [3, 4]}, env1)
        j = l.merge(r, on="k", how="inner")
        with pytest.raises(CylonKeyError):
            j.groupby("k").agg({"nonexistent": "sum"})

    def test_fused_pushdown_allows_string_count(self, env1):
        l = _df({"k": [1, 1, 2, 2], "s": ["x", None, "z", "w"]}, env1)
        r = _df({"k": [1, 2, 2, 3], "b": [1, 2, 3, 4]}, env1)
        got = (l.merge(r, on="k", how="inner").groupby("k")
               .agg({"s": "count"}).to_pandas()
               .sort_values("k").reset_index(drop=True))
        exp = (l.to_pandas().merge(r.to_pandas(), on="k")
               .groupby("k", as_index=False).agg(s_count=("s", "count")))
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_compiler_crash_matches_non_tunnel_messages(self):
        # directly-attached TPU VMs surface compile crashes WITHOUT the
        # axon tunnel's "remote_compile" marker — the ladder must engage
        from cylon_tpu.relational.groupby import _is_compiler_crash
        assert _is_compiler_crash(
            RuntimeError("tpu_compile_helper exited with status 139"))
        assert _is_compiler_crash(
            RuntimeError("Compilation failure: SIGSEGV in subprocess"))
        assert _is_compiler_crash(RuntimeError(
            "remote_compile failed: tpu_compile_helper SIGSEGV"))
        assert not _is_compiler_crash(RuntimeError("shape mismatch"))

    def test_deferred_materialize_does_not_resort(self, env1, monkeypatch):
        # materializing a deferred join must NOT re-run phase 1 (the sort);
        # the carry rebuilds from the held slim state via scans
        from cylon_tpu.relational import join as join_mod
        calls = []
        orig = join_mod._count_fn

        def counting(*a, **k):
            calls.append(k.get("slim", False)
                         or (len(a) > 6 and a[6]))
            return orig(*a, **k)

        monkeypatch.setattr(join_mod, "_count_fn", counting)
        l = _df({"k": [1, 2, 2, 3], "a": [1, 2, 3, 4]}, env1)
        r = _df({"k": [2, 2, 3, 5], "b": [5, 6, 7, 8]}, env1)
        j = l.merge(r, on="k", how="inner")
        from cylon_tpu.core.table import DeferredTable
        assert isinstance(j.table, DeferredTable)
        got = (j.to_pandas().sort_values(["k", "a", "b"])
               .reset_index(drop=True))
        # exactly ONE phase-1 dispatch, and it was the slim one
        assert calls == [True]
        exp = (l.to_pandas().merge(r.to_pandas(), on="k")
               .sort_values(["k", "a", "b"]).reset_index(drop=True))
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_fused_first_sight_mispredict_redetects(self, env1, monkeypatch):
        # first-sight fused dispatch at a tiny segment space must detect
        # the mispredict via n_groups and re-dispatch at the true bucket
        from cylon_tpu.relational import groupby as gb_mod
        monkeypatch.setattr(gb_mod, "_FIRST_SEG_CAP", 2)
        n = 64
        ks = np.arange(n, dtype=np.int64) % 16     # 16 groups > 2
        l = _df({"k": ks, "a": np.arange(n, dtype=np.int64)}, env1)
        r = _df({"k": ks, "b": np.arange(n, dtype=np.int64)}, env1)
        got = (l.merge(r, on="k", how="inner").groupby("k")
               .agg({"a": "sum"}).to_pandas()
               .sort_values("k").reset_index(drop=True))
        exp = (l.to_pandas().merge(r.to_pandas(), on="k")
               .groupby("k", as_index=False).agg(a_sum=("a", "sum")))
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)


class TestConcatDecimalScales:
    """Round-4 advisor (high): concat of >=3 decimal tables with mixed
    scales must rescale EVERY block to the common scale — the old pairwise
    promotion left middle blocks at a stale scale under the final (largest)
    dictionary, silently corrupting values."""

    def test_three_way_mixed_scales(self, env1):
        import decimal
        from cylon_tpu.frame import concat
        mk = lambda vals, sc: _df(
            {"m": np.asarray([decimal.Decimal(v).quantize(
                decimal.Decimal(1).scaleb(-sc)) for v in vals], object)},
            env1)
        a = mk(["1.5"], 1)
        b = mk(["2.5"], 1)     # the middle block the pairwise loop missed
        c = mk(["3.1234"], 4)
        out = concat([a, b, c]).to_pandas()
        assert sorted(map(float, out["m"])) == [1.5, 2.5, 3.1234]

    def test_three_way_mixed_scales_dist(self, env4):
        import decimal
        from cylon_tpu.frame import concat
        mk = lambda vals, sc: _df(
            {"m": np.asarray([decimal.Decimal(str(v)).quantize(
                decimal.Decimal(1).scaleb(-sc)) for v in vals], object)},
            env4)
        a = mk([1.5, 7.5, 0.5, 2.5], 1)
        b = mk([2.5, 8.5, 1.5, 3.5], 1)
        c = mk([3.1234, 4.5678, 0.0001, 9.9999], 4)
        out = concat([a, b, c]).to_pandas()
        exp = sorted([1.5, 7.5, 0.5, 2.5, 2.5, 8.5, 1.5, 3.5,
                      3.1234, 4.5678, 0.0001, 9.9999])
        assert sorted(map(float, out["m"])) == exp

    def test_concat_mixed_numeric_middle(self, env1):
        # same stale-middle pattern for plain numerics: [i64, i64, f64]
        from cylon_tpu.frame import concat
        a = _df({"x": np.asarray([1, 2], np.int64)}, env1)
        b = _df({"x": np.asarray([3, 4], np.int64)}, env1)
        c = _df({"x": np.asarray([0.5], np.float64)}, env1)
        out = concat([a, b, c]).to_pandas()
        assert sorted(out["x"].tolist()) == [0.5, 1.0, 2.0, 3.0, 4.0]


class TestDecimalPrecisionVsScale:
    """Round-4 advisor (medium): ingested tight precision can undercut the
    scale ([0.01, 0.02] -> precision 1, scale 2); to_arrow must still emit
    a valid decimal128."""

    def test_to_arrow_small_fractions(self, env1):
        import decimal
        df = _df({"m": np.asarray([decimal.Decimal("0.01"),
                                   decimal.Decimal("0.02")], object)}, env1)
        at = df.table.to_arrow()
        assert at.column("m").to_pylist() == [decimal.Decimal("0.01"),
                                              decimal.Decimal("0.02")]

    def test_parquet_roundtrip_small_fractions(self, env1, tmp_path):
        import decimal
        df = _df({"m": np.asarray([decimal.Decimal("0.01"),
                                   decimal.Decimal("0.02")], object)}, env1)
        p = str(tmp_path / "d.parquet")
        df.to_parquet(p)
        back = pd.read_parquet(p)
        assert sorted(map(float, back["m"])) == [0.01, 0.02]


class TestLocalSortGroupedBy:
    """Round-4 advisor (low): a per-shard sort alone must NOT claim
    grouped_by (it gates groupby's no-shuffle fast path, which also needs
    cross-shard co-location)."""

    def test_local_sort_does_not_set_grouped_by(self, env4, rng):
        from cylon_tpu.relational.sort import local_sort_table
        t = ct.Table.from_pandas(
            pd.DataFrame({"k": rng.integers(0, 4, 64),
                          "x": rng.random(64)}), env4)
        out = local_sort_table(t, ["k"])
        assert out.grouped_by is None

    def test_groupby_after_local_sort_still_correct(self, env4, rng):
        # the bug scenario: non-colocated but per-shard-sorted table must
        # still take the shuffling groupby path and produce global groups
        from cylon_tpu.relational.sort import local_sort_table
        from cylon_tpu.relational import groupby_aggregate
        pdf = pd.DataFrame({"k": rng.integers(0, 4, 64).astype(np.int64),
                            "x": rng.random(64)})
        t = ct.Table.from_pandas(pdf, env4)
        out = groupby_aggregate(local_sort_table(t, ["k"]), ["k"],
                                [("x", "sum")]).to_pandas()
        exp = pdf.groupby("k", as_index=False).agg(x_sum=("x", "sum"))
        got = out.sort_values("k").reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_allclose(got["x_sum"], exp["x_sum"])


class TestMixedDecimalIngest:
    """Round-4 advisor (low): a column mixing Decimal with other types must
    raise the framework's CylonTypeError, not a raw decimal error."""

    def test_decimal_then_str(self, env1):
        import decimal
        from cylon_tpu.status import CylonTypeError
        with pytest.raises(CylonTypeError):
            _df({"m": np.asarray([decimal.Decimal("1.5"), "oops"], object)},
                env1)

    def test_decimal_then_list(self, env1):
        import decimal
        from cylon_tpu.status import CylonTypeError
        with pytest.raises(CylonTypeError):
            _df({"m": np.asarray([decimal.Decimal("1.5"), [1, 2]], object)},
                env1)

    def test_nonfinite_decimal(self, env1):
        import decimal
        from cylon_tpu.status import CylonTypeError
        # Decimal('NaN') is a null under pd.isna -> ingests as None
        df = _df({"m": np.asarray([decimal.Decimal("1.5"),
                                   decimal.Decimal("NaN")], object)}, env1)
        assert df.to_pandas()["m"].tolist() == [decimal.Decimal("1.5"), None]
        # Decimal('Infinity') is NOT null: framework error, not raw TypeError
        with pytest.raises(CylonTypeError):
            _df({"m": np.asarray([decimal.Decimal("1.5"),
                                  decimal.Decimal("Infinity")], object)},
                env1)


class TestMultiJoinKeyTracking:
    """Round-5 advisor: join_tables_multi's accumulated key names must
    survive suffix renaming.  The seed's fallback silently switched to the
    RIGHT table's key names when a collision renamed the left keys —
    null-valued for unmatched rows in a `how='left'` chain, fabricating
    null-key matches against any null-keyed row downstream."""

    def _frames(self):
        # t2 carries a NON-key payload column named "k" (collides with
        # t1's key -> k_x/k_y suffixes); t3 holds a null-keyed row.  The
        # buggy right-key fallback joins step 2 on "j" — null for the
        # unmatched "9" row — and nulls compare equal in this engine's
        # joins (reference comparator semantics), so it FABRICATES the
        # z=999 match (verified live against the seed logic); the fix
        # joins on the renamed left key "k_x" instead.
        t1 = pd.DataFrame({"k": ["1", "2", "3", "9"],
                           "x": [10, 20, 30, 90]})
        t2 = pd.DataFrame({"j": ["1", "2", "3"],
                           "k": ["a", "b", "c"],
                           "y": [7, 8, 9]})
        t3 = pd.DataFrame({"m": ["1", None], "z": [111, 999]})
        return t1, t2, t3

    def _expected(self, t1, t2, t3):
        p12 = t1.merge(t2, left_on="k", right_on="j", how="left",
                       suffixes=("_x", "_y"))
        return p12.merge(t3, left_on="k_x", right_on="m", how="left")

    @pytest.mark.parametrize("world", ["env1", "env4"])
    def test_colliding_left_chain_keeps_left_keys(self, world, request):
        from cylon_tpu.relational import join_tables_multi
        env = request.getfixturevalue(world)
        t1, t2, t3 = self._frames()
        out = join_tables_multi(
            [ct.Table.from_pandas(t1, env), ct.Table.from_pandas(t2, env),
             ct.Table.from_pandas(t3, env)],
            ons=["k", "j", "m"], how="left")
        exp = self._expected(t1, t2, t3)
        got = out.to_pandas()
        assert sorted(got.columns) == sorted(exp.columns)
        got = got.sort_values(["k_x", "x"]).reset_index(drop=True)
        exp = exp.sort_values(["k_x", "x"]).reset_index(drop=True)
        # the unmatched-left row must NOT pick up t3's null-keyed payload
        row9 = got[got["k_x"] == "9"]
        assert row9["z"].isna().all(), row9
        for c in exp.columns:
            assert (got[c].fillna("<null>").tolist()
                    == exp[c].fillna("<null>").tolist()), c
