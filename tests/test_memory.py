"""HBM budget ledger + host spill tier (cylon_tpu.exec.memory): ledger
invariants, bit-exact spill round-trips, the ladder's spill rung (and its
handoff to chunk escalation), budget-driven spilling through the
pipelined join, and the spill-site watchdog/injection surface.
docs/robustness.md "Memory ledger & spill tier"."""

import gc
import os

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import config
from cylon_tpu.exec import memory, recovery
from cylon_tpu.status import RankDesyncError


@pytest.fixture(autouse=True)
def _clean():
    """Disarmed injector, zeroed stats, and no leaked registrations on
    either side of every test (leftover spillable state would give other
    tests' ladders a phantom spill rung)."""
    recovery.install_faults("")
    recovery.reset_events()
    memory.reset_stats()
    yield
    recovery.install_faults("")
    recovery.reset_events()
    gc.collect()
    memory.reset_stats()


def _tables(env, rng, n=4000):
    """Same shapes/bounds as tests/test_recovery.py's tables on purpose:
    every join/pipeline program this file triggers shares the compiled
    family with that suite (and across the tests here), keeping the
    tier-1 wall-clock cost of this file low."""
    ldf = pd.DataFrame({"k": rng.integers(0, 500, n).astype(np.int64),
                        "a": rng.integers(0, 50, n).astype(np.int64)})
    rdf = pd.DataFrame({"k": rng.integers(0, 500, n).astype(np.int64),
                        "b": rng.integers(0, 50, n).astype(np.int64)})
    return (ldf, rdf, ct.Table.from_pandas(ldf, env),
            ct.Table.from_pandas(rdf, env))


def _mixed_lane_table(env, rng, n=400):
    """Every lane class in one table: wide int64 (2 lanes), narrow int64
    (1 lane via bounds), int32, f32 (bitcast lane), bool, dictionary
    string codes, a NULLABLE int64 (validity lane) and an f64 SIDE array
    carrying a NaN (bit-exactness must survive it)."""
    f64 = rng.random(n)
    f64[0] = np.nan
    df = pd.DataFrame({
        "i64w": (rng.integers(0, 2**40, n)).astype(np.int64),
        "i64n": rng.integers(0, 100, n).astype(np.int64),
        "i32": rng.integers(0, 100, n).astype(np.int32),
        "f32": rng.random(n).astype(np.float32),
        "f64": f64,
        "b": rng.random(n) < 0.5,
        "s": pd.Series(rng.choice(["aa", "bb", "cc"], n)),
        "ni": pd.array(np.where(rng.random(n) < 0.1, None,
                                rng.integers(0, 50, n)), dtype="Int64"),
    })
    return ct.Table.from_pandas(df, env)


def _host_bytes(table):
    """{name: (data bytes, validity array|None)} of the live rows — the
    bit-exact comparison surface."""
    out = {}
    for name, (data, valid) in table.host_columns().items():
        out[name] = (np.asarray(data).tobytes(),
                     None if valid is None else np.asarray(valid, bool))
    return out


# ---------------------------------------------------------------------------
# ledger invariants
# ---------------------------------------------------------------------------

class TestLedger:
    def test_register_release_drains(self):
        led = memory.ledger()
        base = led.balance()
        reg = memory.register("t", (np.zeros(128, np.int64),))
        assert led.balance() == base + 1024
        memory.release(reg)
        assert led.balance() == base
        memory.release(reg)  # idempotent: never drives the balance negative
        assert led.balance() == base

    def test_table_release_drains_to_zero(self, env4, rng):
        led = memory.ledger()
        base = led.balance()
        t = ct.Table.from_pandas(
            pd.DataFrame({"k": rng.integers(0, 9, 256)}), env4)
        reg = memory.register_table("tbl", t)
        assert reg is not None and led.balance() > base
        del t
        gc.collect()   # the weakref.finalize anchor drains the entry
        assert led.balance() == base

    def test_budget_env_override(self, monkeypatch):
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 12345)
        assert memory.budget_bytes() == 12345
        assert memory.over_budget(12346)

    def test_property_random_sequences(self, env4, rng):
        """Random register/evict/readmit/touch/release sequences keep
        ``balance == sum of live un-spilled registrations`` and the
        balance non-negative throughout."""
        led = memory.ledger()
        base = led.balance()
        live = []

        def expected():
            return base + sum(r.nbytes for r in live if not r.spilled)

        for step in range(60):
            op = rng.integers(0, 5)
            if op == 0 or not live:
                arr = np.zeros(int(rng.integers(8, 512)), np.float64)
                live.append(memory.register(
                    "prop", (arr,), spillable=bool(rng.integers(0, 2))))
            else:
                reg = live[int(rng.integers(0, len(live)))]
                if op == 1:
                    memory.evict(reg)
                elif op == 2 and reg.spilled:
                    memory.readmit(reg)
                elif op == 3:
                    memory.touch(reg)
                else:
                    memory.release(reg)
                    live.remove(reg)
            assert led.balance() == expected(), step
            assert led.balance() >= 0
        for reg in live:
            memory.release(reg)
        assert led.balance() == base

    def test_lru_eviction_order_is_deterministic(self):
        regs = [memory.register(f"lru", (np.zeros(64, np.int64),),
                                spillable=True) for _ in range(3)]
        memory.touch(regs[0])   # oldest untouched entry is regs[1]
        evicted = memory.ledger().evict_until(1, budget=1)
        assert evicted[0] == regs[1].owner
        for r in regs:
            memory.release(r)


# ---------------------------------------------------------------------------
# spill round-trips
# ---------------------------------------------------------------------------

class TestSpillRoundTrip:
    def test_bit_exact_all_lane_dtypes(self, env4, rng):
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng)
        w = env4.world_size
        lens = t.valid_counts
        src = PieceSource(t, pad=8)
        cap = config.pow2ceil(int(lens.max()))
        starts = np.zeros(w, np.int64)
        ref = _host_bytes(src.packed(starts, lens, cap).to_table())
        freed = memory.evict(src._reg)
        assert freed > 0 and src.spilled and src.arrs is None
        got = _host_bytes(src.packed(starts, lens, cap).to_table())
        assert set(got) == set(ref)
        for name in ref:
            assert got[name][0] == ref[name][0], f"{name} data bytes differ"
            rv, gv = ref[name][1], got[name][1]
            assert (rv is None) == (gv is None)
            if rv is not None:
                assert np.array_equal(rv, gv), f"{name} validity differs"
        st = memory.stats()
        assert st["spill_events"] == 1 and st["bytes_spilled"] == freed
        assert st["bytes_readmitted"] > 0

    def test_whole_registration_readmit_bit_exact(self, env4, rng):
        from cylon_tpu.relational.piece import PieceSource
        from cylon_tpu.utils.host import host_arrays
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        before = [np.asarray(a).tobytes() for a in host_arrays(
            list(src.arrs))]
        memory.evict(src._reg)
        arrs = memory.readmit(src._reg)
        assert src.arrs is not None and not src.spilled
        after = [np.asarray(a).tobytes() for a in host_arrays(list(arrs))]
        assert before == after


# ---------------------------------------------------------------------------
# budget-driven spilling through the pipelined join (the acceptance run)
# ---------------------------------------------------------------------------

class TestBudgetSpill:
    def test_pipelined_join_spills_and_stays_bit_equal(self, env4, rng,
                                                       monkeypatch):
        """CYLON_TPU_HBM_BUDGET below the resident working set: the
        pipelined join completes via the spill tier at the SAME chunk
        count — no recompute escalation, spill_events > 0, result
        bit-equal (and order-equal) to the unconstrained run."""
        from cylon_tpu.exec import pipelined_join
        _ldf, _rdf, lt, rt = _tables(env4, rng)
        base = pipelined_join(lt, rt, "k", "k", how="inner",
                              n_chunks=4).to_pandas()
        gc.collect()
        memory.reset_stats()
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 4096)
        out = pipelined_join(lt, rt, "k", "k", how="inner",
                             n_chunks=4).to_pandas()
        st = memory.stats()
        assert st["spill_events"] > 0, st
        assert memory.eviction_log(), "no eviction sequence recorded"
        assert recovery.recovery_events() == []  # NO ladder escalation
        pd.testing.assert_frame_equal(out, base)  # bit- and order-equal

    def test_spill_disabled_escape_hatch(self, env4, rng, monkeypatch):
        """CYLON_TPU_SPILL=0: the ledger keeps accounting but NOTHING
        evicts — neither under real budget pressure nor under injected
        pressure, and the ladder's spill rung reports nothing to free."""
        from cylon_tpu.relational.piece import PieceSource
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 4096)
        monkeypatch.setattr(config, "SPILL_ENABLED", False)
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        assert memory.balance() > 0          # accounting still live
        recovery.install_faults("spill.evict:0:1=predicted")
        memory.ensure_headroom(env4, 1 << 20)   # over budget + pressure
        assert not src.spilled
        assert memory.stats()["spill_events"] == 0
        assert memory.spill_for_retry() == 0    # ladder rung disabled too
        del src


# ---------------------------------------------------------------------------
# the ladder's spill rung + handoff to chunk escalation
# ---------------------------------------------------------------------------

class TestSpillRung:
    def test_predicted_fault_takes_spill_rung_first(self, env4, rng):
        """A predicted receive-budget fault with spillable resident
        state: the ladder frees bytes and retries at the SAME chunk
        count — one spill_retry event, no chunk escalation, identical
        result."""
        from cylon_tpu.relational import join_tables
        from cylon_tpu.relational.piece import PieceSource
        ldf, rdf, lt, rt = _tables(env4, rng)
        aux = ct.Table.from_pandas(ldf, env4)
        src = PieceSource(aux, pad=8)
        recovery.install_faults("shuffle.recv_guard:0:1=predicted")
        j = join_tables(lt, rt, "k", "k", how="inner")
        got = j.to_pandas().sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        exp = ldf.merge(rdf, on="k").sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)
        assert [e["action"] for e in recovery.recovery_events()] \
            == ["spill_retry"]
        assert src.spilled
        del src, aux

    def test_spill_insufficient_hands_off_to_chunks(self, env4, rng):
        """Spill-insufficient → chunk-escalation handoff: the guard
        re-faults after the spill rung (nth=2 injection), so the ladder
        falls through to the 4-chunk streaming rung and completes."""
        from cylon_tpu.relational import join_tables
        from cylon_tpu.relational.piece import PieceSource
        ldf, rdf, lt, rt = _tables(env4, rng)
        src = PieceSource(ct.Table.from_pandas(ldf, env4), pad=8)
        recovery.install_faults("shuffle.recv_guard:0:1=predicted,"
                                "shuffle.recv_guard:0:2=predicted")
        j = join_tables(lt, rt, "k", "k", how="inner")
        got = j.to_pandas().sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        exp = ldf.merge(rdf, on="k").sort_values(["k", "a", "b"]) \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns], exp,
                                      check_dtype=False)
        acts = [e["action"] for e in recovery.recovery_events()
                if e["site"] == "join"]
        assert acts[:2] == ["spill_retry", "retry_chunks_4"], acts
        del src

    def test_no_spillable_state_goes_straight_to_chunks(self, env4, rng):
        """Without spillable registrations the rung is skipped — the
        pre-existing behavior (retry_chunks_4) is unchanged."""
        from cylon_tpu.relational import join_tables
        ldf, rdf, lt, rt = _tables(env4, rng)
        gc.collect()   # no leftover spillable sources from other tests
        recovery.install_faults("shuffle.recv_guard:0:1=predicted")
        join_tables(lt, rt, "k", "k", how="inner")
        assert [e["action"] for e in recovery.recovery_events()] \
            == ["retry_chunks_4"]


# ---------------------------------------------------------------------------
# spill-site injection + watchdog
# ---------------------------------------------------------------------------

class TestSpillInjection:
    def test_grammar_accepts_spill_sites_and_kind(self):
        recovery.install_faults("spill.evict=predicted")
        recovery.install_faults("spill.upload=spill_stall")
        recovery.install_faults("spill.evict:0:2=spill_stall")
        with pytest.raises(ValueError):
            recovery.install_faults("spill.nope=predicted")

    def test_upload_stall_surfaces_typed_desync(self, env4, rng):
        """A hung host→device re-upload surfaces as RankDesyncError with
        site=spill.upload (exchange watchdog reuse), not a silent
        stall."""
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        memory.evict(src._reg)
        recovery.install_faults("spill.upload=spill_stall")
        w = env4.world_size
        with pytest.raises(RankDesyncError) as ei:
            src.packed(np.zeros(w, np.int64), t.valid_counts, 64)
        assert ei.value.site == "spill.upload"
        del src

    def test_evict_pressure_injection_evicts_lru(self, env4, rng):
        """kind=predicted at spill.evict simulates memory pressure: the
        admission path evicts exactly the LRU spillable owner."""
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        owner = src._reg.owner
        recovery.install_faults("spill.evict:0:1=predicted")
        memory.ensure_headroom(env4, 0)
        assert src.spilled
        assert memory.eviction_log() == [owner]
        del src

    def test_evict_exception_kinds_raise_typed(self, env4):
        from cylon_tpu.status import DeviceOOMError
        recovery.install_faults("spill.evict=device_oom")
        with pytest.raises(Exception) as ei:
            memory.ensure_headroom(env4, 0)
        assert isinstance(recovery.classify(ei.value), DeviceOOMError)


# ---------------------------------------------------------------------------
# disk tier: host pages demote to spill files (docs/robustness.md
# "Disk tier & scan pushdown")
# ---------------------------------------------------------------------------

@pytest.fixture()
def disk(tmp_path, monkeypatch):
    """Arm the disk tier with a tiny host budget and a private spill
    root; yields the root path."""
    root = str(tmp_path / "spill")
    monkeypatch.setattr(config, "HOST_BUDGET_BYTES", 4096)
    monkeypatch.setattr(config, "SPILL_DIR", root)
    return root


class TestDiskTier:
    def test_demote_window_read_bit_exact(self, env4, rng, disk):
        """device → host → DISK → windowed mmap read: bit-equal to the
        resident path across every lane class, with the page files on
        disk while demoted and the traffic counted."""
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng)
        w = env4.world_size
        lens = t.valid_counts
        src = PieceSource(t, pad=8)
        cap = config.pow2ceil(int(lens.max()))
        starts = np.zeros(w, np.int64)
        ref = _host_bytes(src.packed(starts, lens, cap).to_table())
        memory.evict(src._reg)
        assert memory.demote(src._reg) > 0
        assert src._reg.on_disk and src._reg.host is None
        assert memory.demotion_log() == [src._reg.owner]
        import glob as _glob
        pages = _glob.glob(os.path.join(disk, "rank*", "*.spill.npy"))
        assert pages, "no spill page files written"
        got = _host_bytes(src.packed(starts, lens, cap).to_table())
        for name in ref:
            assert got[name][0] == ref[name][0], f"{name} data differs"
        st = memory.stats()
        assert st["disk_events"] >= 2            # demote + window read
        assert st["bytes_to_disk"] > 0 and st["bytes_from_disk"] > 0
        assert st["disk_pages_demoted"] == len(pages)
        del src

    def test_full_readmit_from_disk_bit_exact(self, env4, rng, disk):
        """disk → host → device whole-registration promotion is
        bit-exact and deletes the spill pages."""
        from cylon_tpu.relational.piece import PieceSource
        from cylon_tpu.utils.host import host_arrays
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        before = [np.asarray(a).tobytes()
                  for a in host_arrays(list(src.arrs))]
        memory.evict(src._reg)
        memory.demote(src._reg)
        arrs = memory.readmit(src._reg)
        assert not src.spilled and not src._reg.on_disk
        after = [np.asarray(a).tobytes() for a in host_arrays(list(arrs))]
        assert before == after
        import glob as _glob
        assert not _glob.glob(os.path.join(disk, "rank*", "*.spill.npy"))
        del src

    def test_host_budget_drives_demotion_through_pipelined_join(
            self, env4, rng, disk, monkeypatch):
        """Both budgets below the working set: the pipelined join rides
        the FULL residency ladder (device → host → disk → mmap windows)
        and stays bit- and order-equal with no ladder escalation."""
        from cylon_tpu.exec import pipelined_join
        monkeypatch.setattr(config, "HOST_BUDGET_BYTES", 0)
        _ldf, _rdf, lt, rt = _tables(env4, rng)
        base = pipelined_join(lt, rt, "k", "k", how="inner",
                              n_chunks=4).to_pandas()
        gc.collect()
        memory.reset_stats()
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 4096)
        monkeypatch.setattr(config, "HOST_BUDGET_BYTES", 4096)
        out = pipelined_join(lt, rt, "k", "k", how="inner",
                             n_chunks=4).to_pandas()
        st = memory.stats()
        assert st["disk_events"] > 0 and st["bytes_to_disk"] > 0, st
        assert memory.demotion_log(), "no demotion sequence recorded"
        assert recovery.recovery_events() == []  # NO ladder escalation
        pd.testing.assert_frame_equal(out, base)

    def test_enospc_demotion_degrades_in_memory(self, env4, rng, disk):
        """ENOSPC mid-demote: the page STAYS host-resident, a typed
        recovery event records the degrade, nothing crashes."""
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        memory.evict(src._reg)
        recovery.install_faults("disk.write::1=enospc")
        assert memory.demote(src._reg) == 0
        assert src._reg.host is not None and not src._reg.on_disk
        assert memory.stats()["disk_write_degrades"] == 1
        assert [(e["site"], e["kind"], e["action"])
                for e in recovery.recovery_events()] \
            == [("disk.write", "enospc", "degrade_in_memory")]
        del src

    def test_corrupt_promote_is_typed_and_retires_owner(self, env4, rng,
                                                        disk):
        """A page corrupted after hashing (injected at disk.write) fails
        the on-touch verification: typed CheckpointCorruptError at site
        disk.read, the poisoned owner released — never a wrong answer."""
        from cylon_tpu.relational.piece import PieceSource
        from cylon_tpu.status import CheckpointCorruptError
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        w = env4.world_size
        memory.evict(src._reg)
        recovery.install_faults("disk.write::1=corrupt")
        assert memory.demote(src._reg) > 0
        with pytest.raises(CheckpointCorruptError) as ei:
            src.packed(np.zeros(w, np.int64), t.valid_counts, 64)
        assert ei.value.site == "disk.read"
        assert not src._reg.live        # poisoned owner retired
        assert memory.stats()["disk_corrupt_degrades"] == 1
        del src

    def test_corrupt_promote_recomputes_through_ladder(self, env4, rng,
                                                       disk, monkeypatch):
        """End to end: corrupt-on-promote inside a guarded pipelined
        join+sink workload degrades to ONE recompute rung — bit-equal,
        bounded, never a wrong answer."""
        from cylon_tpu.exec import GroupBySink, pipelined_join
        ldf, rdf, lt, rt = _tables(env4, rng)

        def attempt(nc):
            sink = GroupBySink("k", [("a", "sum"), ("b", "sum")])
            pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=nc,
                           sink=sink)
            return sink.finalize()

        base = attempt(4).to_pandas().sort_values("k") \
            .reset_index(drop=True)
        gc.collect()
        memory.reset_stats()
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 4096)
        recovery.install_faults("disk.read::1=corrupt")
        out = recovery.run_with_recovery(lambda: attempt(4), True, attempt,
                                         "oocore", env=env4)
        got = out.to_pandas().sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, base)
        acts = [(e["site"], e["action"])
                for e in recovery.recovery_events()]
        assert ("disk.read", "recompute_owner") in acts
        assert ("oocore", "retry_chunks_4") in acts
        assert memory.stats()["disk_corrupt_degrades"] == 1

    def test_torn_page_surfaces_typed_not_crash(self, env4, rng, disk):
        """A genuinely TRUNCATED page (crash mid-write, external tamper)
        raises ValueError inside np.load, not OSError — it must still
        surface as the typed CheckpointCorruptError → recompute path,
        never an unhandled crash (review finding, round 13)."""
        import glob as _glob
        from cylon_tpu.relational.piece import PieceSource
        from cylon_tpu.status import CheckpointCorruptError
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        w = env4.world_size
        memory.evict(src._reg)
        assert memory.demote(src._reg) > 0
        page = sorted(_glob.glob(
            os.path.join(disk, "rank*", "*.spill.npy")))[0]
        with open(page, "r+b") as f:       # truncate mid-data
            f.truncate(os.path.getsize(page) // 2)
        with pytest.raises(CheckpointCorruptError) as ei:
            src.packed(np.zeros(w, np.int64), t.valid_counts, 64)
        assert ei.value.site == "disk.read"
        assert memory.stats()["disk_corrupt_degrades"] == 1
        del src

    def test_disk_stalls_surface_typed_desync(self, env4, rng, disk):
        """A hung page write or verify read surfaces via the exchange
        watchdog as RankDesyncError at the disk site, never a silent
        block."""
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        memory.evict(src._reg)
        recovery.install_faults("disk.write::1=stall")
        with pytest.raises(RankDesyncError) as ei:
            memory.demote(src._reg)
        assert ei.value.site == "disk.write"
        recovery.install_faults("")     # disarm; the page is still host
        assert memory.demote(src._reg) > 0
        recovery.install_faults("disk.read::1=stall")
        w = env4.world_size
        with pytest.raises(RankDesyncError) as ei:
            src.packed(np.zeros(w, np.int64), t.valid_counts, 64)
        assert ei.value.site == "disk.read"
        del src

    def test_transient_oserror_retries_then_succeeds(self, env4, rng,
                                                     disk, monkeypatch):
        """The bounded IO retry saves a flaky-then-ok page write: the
        demotion succeeds on attempt 2 and the retry is counted."""
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        memory.evict(src._reg)
        real_save = np.save
        fails = [1]

        def flaky_save(path, arr, **kw):
            if fails[0]:
                fails[0] -= 1
                raise OSError(5, "transient EIO blip")
            return real_save(path, arr, **kw)

        monkeypatch.setattr(np, "save", flaky_save)
        assert memory.demote(src._reg) > 0
        assert memory.stats()["disk_retries"] == 1
        del src

    def test_unarmed_disk_tier_writes_nothing(self, env4, rng, tmp_path,
                                              monkeypatch):
        """The standing contract: with no host budget, a spill-heavy run
        never creates a spill file or directory — zero filesystem
        writes, zero disk counters."""
        from cylon_tpu.exec import pipelined_join
        root = str(tmp_path / "never")
        monkeypatch.setattr(config, "SPILL_DIR", root)
        monkeypatch.setattr(config, "HBM_BUDGET_BYTES", 4096)
        monkeypatch.setattr(config, "HOST_BUDGET_BYTES", 0)
        _ldf, _rdf, lt, rt = _tables(env4, rng)
        pipelined_join(lt, rt, "k", "k", how="inner", n_chunks=4)
        st = memory.stats()
        assert st["spill_events"] > 0          # the host tier DID engage
        assert st["disk_events"] == 0 and st["bytes_to_disk"] == 0
        assert not os.path.exists(root)

    def test_predecessor_orphans_purged_on_first_use(self, env4, rng,
                                                     disk):
        """A crashed predecessor's leftover pages in a FIXED spill dir
        are purged on this process's first use of it — a shared spill
        volume cannot fill up run over run (review finding, round 13)."""
        import jax
        d = os.path.join(disk, f"rank{jax.process_index()}")
        os.makedirs(d, exist_ok=True)
        orphan = os.path.join(d, "dead_owner.a0.s0.spill.npy")
        np.save(orphan, np.zeros(8))
        memory._PURGED_DIRS.discard(d)   # fresh-process semantics
        from cylon_tpu.relational.piece import PieceSource
        t = _mixed_lane_table(env4, rng, n=256)
        src = PieceSource(t, pad=8)
        memory.evict(src._reg)
        assert memory.demote(src._reg) > 0
        assert not os.path.exists(orphan)
        del src

    def test_demotion_lru_order_is_deterministic(self, disk):
        regs = [memory.register("dlru", (np.zeros(64, np.int64),),
                                spillable=True) for _ in range(3)]
        for r in regs:
            memory.evict(r)
        memory.touch(regs[0])   # oldest untouched host page is regs[1]
        led = memory.ledger()
        assert led.demote_count_for(1) >= 1
        assert led.demote_n(1) == [regs[1].owner]
        for r in regs:
            memory.release(r)
