"""Series: a named device-resident column with elementwise compute.

TPU-native equivalent of PyCylon's ``Series`` (python/pycylon/pycylon/
series.py) and the dual arrow/numpy "compute engine" behind DataFrame math
and filters (python/pycylon/pycylon/data/compute.pyx:212-218).  The reference
dispatches per-op to pyarrow.compute or numpy on host memory; here every op
is a ``jax.numpy`` expression over the (possibly mesh-sharded) column array —
XLA fuses chains of elementwise ops into single kernels, and padding rows
simply compute garbage that the valid-prefix convention ignores.

Null semantics: validity propagates through arithmetic/comparison as AND
(null op x -> null), matching Arrow/pandas nullable behavior.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import config
from .utils.cache import jit, program_cache
from .core.column import Column
from .core.dtypes import LogicalType, from_numpy_dtype, physical_np_dtype
from .core.table import Table
from .status import CylonTypeError, InvalidError

shard_map = jax.shard_map


def _binop_validity(a: Column, b) -> Any:
    va = a.validity
    vb = b.validity if isinstance(b, Column) else None
    if va is None:
        return vb
    if vb is None:
        return va
    return va & vb


class Series:
    """A column bound to a table's row layout (env + per-shard valid counts).

    Arithmetic/comparison with scalars or layout-matched Series; boolean
    Series feed ``DataFrame.__getitem__`` filters.
    """

    __slots__ = ("name", "_col", "_env", "_valid")

    def __init__(self, name: str, col: Column, env, valid_counts: np.ndarray):
        self.name = name
        self._col = col
        self._env = env
        self._valid = valid_counts

    # -- basics ------------------------------------------------------------
    @property
    def column(self) -> Column:
        return self._col

    @property
    def dtype(self) -> LogicalType:
        return self._col.type

    @property
    def env(self):
        return self._env

    @property
    def valid_counts(self) -> np.ndarray:
        return self._valid

    def __len__(self) -> int:
        return int(self._valid.sum())

    # reference series.py properties: id/data/shape
    @property
    def id(self) -> str:
        return self.name

    @property
    def data(self) -> np.ndarray:
        """Materialized values (valid prefixes compacted across shards,
        string codes decoded) — NOT the raw padded device buffer, which
        holds per-shard padding garbage (use .column.data for that)."""
        return self.to_numpy()

    @property
    def shape(self) -> tuple:
        return (len(self),)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Series({self.name!r}, {self.dtype.value}, len={len(self)})"

    def to_numpy(self) -> np.ndarray:
        w = self._valid.shape[0]
        cap = len(self._col) // max(w, 1)
        host = np.asarray(self._col.data)
        valid = (np.asarray(self._col.validity)
                 if self._col.validity is not None else None)
        parts = [slice(i * cap, i * cap + int(self._valid[i]))
                 for i in range(w)]
        data = np.concatenate([host[s] for s in parts]) if parts else host[:0]
        vcat = (np.concatenate([valid[s] for s in parts])
                if valid is not None else None)
        return Column(data, self._col.type, vcat,
                      self._col.dictionary).to_numpy(len(data))

    def to_pandas(self):
        import pandas as pd
        return pd.Series(self.to_numpy(), name=self.name)

    # -- elementwise machinery --------------------------------------------
    def _wrap(self, data, validity, lt: LogicalType | None = None,
              dictionary=None, name: str | None = None) -> "Series":
        lt = lt or from_numpy_dtype(np.dtype(data.dtype))
        return Series(name or self.name, Column(data, lt, validity, dictionary),
                      self._env, self._valid)

    def _other_operand(self, other):
        """-> (device array or scalar, validity or None)."""
        if isinstance(other, Series):
            if other._col.data.shape != self._col.data.shape:
                raise InvalidError("series layouts differ; align first")
            if (other._col.type == LogicalType.STRING) != (
                    self._col.type == LogicalType.STRING):
                raise CylonTypeError("cannot mix string and numeric series")
            if other._col.type == LogicalType.STRING:
                from .relational.common import unify_dictionaries
                a, b = unify_dictionaries(self._col, other._col)
                return (a, b.data), _binop_validity(a, b)
            return (self._col, other._col.data), _binop_validity(
                self._col, other._col)
        # scalar
        if isinstance(other, str):
            raise CylonTypeError("string scalar only valid in comparisons")
        return (self._col, other), self._col.validity

    def _arith(self, other, fn, name: str) -> "Series":
        if self._col.type == LogicalType.STRING:
            raise CylonTypeError(f"{name} not supported for string series")
        if self._col.type == LogicalType.LIST:
            raise CylonTypeError(f"{name} not supported for list series")
        if self._col.type == LogicalType.DECIMAL:
            raise CylonTypeError(
                f"{name} on decimal series is not supported (scale-exact "
                "arithmetic is not implemented); cast to float64 first")
        (col, rhs), validity = self._other_operand(other)
        out = fn(col.data, rhs)
        return self._wrap(out, validity)

    def _compare(self, other, fn) -> "Series":
        if self._col.type == LogicalType.LIST or (
                isinstance(other, Series)
                and other._col.type == LogicalType.LIST):
            raise CylonTypeError(
                "comparisons on list passthrough series are not supported")
        if self._col.type == LogicalType.DECIMAL:
            import decimal
            sc = self._col.dictionary
            if isinstance(other, Series) \
                    and other._col.type == LogicalType.DECIMAL:
                from .relational.common import rescale_decimal_pair
                a, b = rescale_decimal_pair(self._col, other._col)
                return self._wrap(fn(a.data, b.data),
                                  _binop_validity(a, b), LogicalType.BOOL)
            if isinstance(other, (int, decimal.Decimal)):
                q = decimal.Decimal(other).scaleb(sc.scale)
                if q != int(q):
                    raise CylonTypeError(
                        f"literal {other!r} has more fractional digits "
                        f"than the column scale {sc.scale}")
                return self._wrap(fn(self._col.data, int(q)),
                                  self._col.validity, LogicalType.BOOL)
            raise CylonTypeError(
                "decimal compares need a Decimal/int literal or another "
                "decimal series (float literals are lossy)")
        if isinstance(other, str):
            if self._col.type != LogicalType.STRING:
                raise CylonTypeError("string scalar vs numeric series")
            from .core.column import HashedStrings
            if isinstance(self._col.dictionary, HashedStrings):
                # hashed codes have no lexical order: equality only
                if fn not in (jnp.equal, jnp.not_equal):
                    raise CylonTypeError(
                        "ordered compare on a high-cardinality hashed "
                        "string column is not supported (== and != work)")
                h = int(self._col.dictionary.hash_values([other])[0])
                out = fn(self._col.data, jnp.int64(h))
                return self._wrap(out, self._col.validity, LogicalType.BOOL)
            # dictionary is sorted, so codes are order-isomorphic to values;
            # absent scalars compare via their insertion point - 0.5 (all
            # comparisons then resolve exactly in float space)
            d = self._col.dictionary
            pos = int(np.searchsorted(d, other))
            present = pos < len(d) and d[pos] == other
            rhs = float(pos) if present else pos - 0.5
            out = fn(self._col.data.astype(jnp.float64), rhs)
            return self._wrap(out, self._col.validity, LogicalType.BOOL)
        (col, rhs), validity = self._other_operand(other)
        if fn not in (jnp.equal, jnp.not_equal):
            # series-vs-series ordered compare: hashed string codes carry
            # no lexical order (codes would compare by hash — silently
            # wrong, never allowed)
            from .core.column import HashedStrings
            for c in (col, getattr(other, "_col", None)):
                if c is not None and isinstance(
                        getattr(c, "dictionary", None), HashedStrings):
                    raise CylonTypeError(
                        "ordered compare on a high-cardinality hashed "
                        "string column is not supported (== and != work)")
        out = fn(col.data, rhs)
        return self._wrap(out, validity, LogicalType.BOOL)

    # arithmetic
    def __add__(self, o):
        return self._arith(o, jnp.add, "+")

    def __radd__(self, o):
        return self._arith(o, jnp.add, "+")

    def __sub__(self, o):
        return self._arith(o, jnp.subtract, "-")

    def __rsub__(self, o):
        return self._arith(o, lambda a, b: jnp.subtract(b, a), "-")

    def __mul__(self, o):
        return self._arith(o, jnp.multiply, "*")

    def __rmul__(self, o):
        return self._arith(o, jnp.multiply, "*")

    def __truediv__(self, o):
        return self._arith(o, jnp.true_divide, "/")

    def __rtruediv__(self, o):
        return self._arith(o, lambda a, b: jnp.true_divide(b, a), "/")

    def __floordiv__(self, o):
        return self._arith(o, jnp.floor_divide, "//")

    def __mod__(self, o):
        return self._arith(o, jnp.mod, "%")

    def __pow__(self, o):
        return self._arith(o, jnp.power, "**")

    def __neg__(self):
        return self._arith(0, lambda a, _: jnp.negative(a), "neg")

    def __abs__(self):
        return self._arith(0, lambda a, _: jnp.abs(a), "abs")

    # comparisons
    def __eq__(self, o):  # type: ignore[override]
        return self._compare(o, jnp.equal)

    def __ne__(self, o):  # type: ignore[override]
        return self._compare(o, jnp.not_equal)

    def __lt__(self, o):
        return self._compare(o, jnp.less)

    def __le__(self, o):
        return self._compare(o, jnp.less_equal)

    def __gt__(self, o):
        return self._compare(o, jnp.greater)

    def __ge__(self, o):
        return self._compare(o, jnp.greater_equal)

    __hash__ = None  # type: ignore[assignment]

    # logical
    def _logical(self, other, fn) -> "Series":
        if self._col.type != LogicalType.BOOL:
            raise CylonTypeError("logical op on non-bool series")
        (col, rhs), validity = self._other_operand(other)
        return self._wrap(fn(col.data, rhs), validity, LogicalType.BOOL)

    def __and__(self, o):
        return self._logical(o, jnp.logical_and)

    def __or__(self, o):
        return self._logical(o, jnp.logical_or)

    def __xor__(self, o):
        return self._logical(o, jnp.logical_xor)

    def __invert__(self):
        if self._col.type != LogicalType.BOOL:
            raise CylonTypeError("~ on non-bool series")
        return self._wrap(jnp.logical_not(self._col.data), self._col.validity,
                          LogicalType.BOOL)

    # -- null handling -----------------------------------------------------
    def isna(self) -> "Series":
        if self._col.validity is None:
            if self._col.type in (LogicalType.FLOAT32, LogicalType.FLOAT64):
                return self._wrap(jnp.isnan(self._col.data), None,
                                  LogicalType.BOOL)
            # zeros_like preserves the source's device/sharding (never the
            # default backend, unlike a bare jnp.zeros)
            return self._wrap(jnp.zeros_like(self._col.data, dtype=bool),
                              None, LogicalType.BOOL)
        out = jnp.logical_not(self._col.validity)
        if self._col.type in (LogicalType.FLOAT32, LogicalType.FLOAT64):
            out = out | jnp.isnan(self._col.data)
        return self._wrap(out, None, LogicalType.BOOL)

    def notna(self) -> "Series":
        return ~self.isna()

    def where(self, cond: "Series", other=None) -> "Series":
        """Rows where ``cond`` holds keep their value; the rest become
        ``other`` (default: null) — pandas ``Series.where`` (null conds
        never select, like every filter-on-bool site)."""
        if not isinstance(cond, Series):
            raise CylonTypeError("where condition must be a Series")
        if cond._col.type != LogicalType.BOOL:
            raise CylonTypeError("where condition must be boolean")
        from .relational.common import valid_flag
        keep = valid_flag(cond._col)
        if other is None:
            v = keep if self._col.validity is None \
                else (self._col.validity & keep)
            return self._wrap(self._col.data, v)
        return self._fill_where(jnp.logical_not(keep), value=other)

    def fillna(self, value) -> "Series":
        # mask covers every invalid slot -> the result is fully valid
        return self._fill_where(self.isna()._col.data, value,
                                all_valid=True)

    def _fill_where(self, mask, value, all_valid: bool = False) -> "Series":
        """Replace positions where ``mask`` (bool data array) holds with
        ``value``; the filled positions become valid.  Backs ``fillna``
        (mask = isna, all_valid=True since every null gets filled) and
        ``DataFrame.where`` (mask = ~cond)."""
        if self._col.type == LogicalType.STRING:
            if not isinstance(value, str):
                raise CylonTypeError("fill on string series needs str")
            from .core.column import HashedStrings
            d = self._col.dictionary
            if isinstance(d, HashedStrings):
                code = int(d.hash_values([value])[0])
                newd = d.merged_with(HashedStrings(
                    np.asarray([code]).astype(np.int64).view(np.uint64),
                    np.asarray([value], dtype=object)))
                data = jnp.where(mask, jnp.int64(code), self._col.data)
                v2 = None if (all_valid or self._col.validity is None) \
                    else (self._col.validity | mask)
                return self._wrap(data, v2, LogicalType.STRING, newd)
            pos = int(np.searchsorted(d, value))
            if not (pos < len(d) and d[pos] == value):
                newd = np.insert(d, pos, value)
                remap = np.searchsorted(newd, d).astype(np.int32)
                codes = jnp.take(remap,
                                 jnp.clip(self._col.data, 0, len(d) - 1))
                col = Column(codes, LogicalType.STRING, self._col.validity,
                             newd)
            else:
                col = self._col
            code = int(np.searchsorted(col.dictionary, value))
            data = jnp.where(mask, jnp.int32(code), col.data)
            v = None if (all_valid or col.validity is None) \
                else (col.validity | mask)
            return self._wrap(data, v, LogicalType.STRING, col.dictionary)
        data = jnp.where(mask, np.asarray(value, self._col.data.dtype),
                         self._col.data)
        v = None if (all_valid or self._col.validity is None) \
            else (self._col.validity | mask)
        return self._wrap(data, v, self._col.type)

    def astype(self, dtype) -> "Series":
        lt = from_numpy_dtype(np.dtype(dtype)) if not isinstance(
            dtype, LogicalType) else dtype
        return Series(self.name, self._col.cast(lt), self._env, self._valid)

    # -- reductions --------------------------------------------------------
    def _reduce(self, kind: str):
        from .relational.common import live_mask, REP, ROW
        col, valid, lt = self._col, self._valid, self._col.type
        if lt == LogicalType.STRING and kind not in ("count", "min", "max"):
            raise CylonTypeError(f"{kind} on string series")
        from .core.column import HashedStrings
        if (lt == LogicalType.STRING and kind in ("min", "max")
                and isinstance(col.dictionary, HashedStrings)):
            raise CylonTypeError(
                f"{kind} on a high-cardinality hashed string series: "
                "hashed codes carry no lexical order")
        mesh = self._env.mesh
        cap = len(col) // max(valid.shape[0], 1)
        out, cnt = _reduce_fn(mesh, kind, max(cap, 1))(
            np.asarray(valid, np.int32), col.data,
            col.validity if col.validity is not None
            else np.ones(len(col), bool))
        # partials keep the accumulator dtype (int64 stays int64 — no float64
        # round-trip that would lose precision past 2^53)
        parts = np.asarray(out)
        cnts = np.asarray(cnt)
        if kind == "sum":
            if lt not in (LogicalType.FLOAT32, LogicalType.FLOAT64):
                return int(parts.sum())
            return float(parts.sum())
        if kind == "count":
            return int(parts.sum())
        live = cnts > 0
        if not live.any():
            # pandas: min/max of empty / all-NaN numeric series is nan
            return None if lt == LogicalType.STRING else float("nan")
        v = parts[live].min() if kind == "min" else parts[live].max()
        if lt == LogicalType.STRING:
            from .core.column import HashedStrings
            if isinstance(self._col.dictionary, HashedStrings):
                return str(self._col.dictionary.take(
                    np.asarray([int(v)], np.int64))[0])
            return str(self._col.dictionary[int(v)])
        if lt in (LogicalType.FLOAT32, LogicalType.FLOAT64):
            return float(v)
        return int(v)

    def sum(self):
        return self._reduce("sum")

    def count(self) -> int:
        return self._reduce("count")

    def min(self):
        return self._reduce("min")

    def max(self):
        return self._reduce("max")

    def mean(self):
        c = self.count()
        return self.sum() / c if c else float("nan")

    def nunique(self) -> int:
        import pandas as pd
        from .relational import unique_table
        t = Table({self.name: self._col}, self._env, self._valid)
        vals = unique_table(t, [self.name]).to_pandas()[self.name]
        return int(pd.notna(vals).sum())  # pandas semantics: drop nulls

    def unique(self) -> np.ndarray:
        from .relational import unique_table
        t = Table({self.name: self._col}, self._env, self._valid)
        return unique_table(t, [self.name]).to_pandas()[self.name].to_numpy()


@program_cache()
def _reduce_fn(mesh: Mesh, kind: str, cap: int):
    from .relational.common import REP, ROW, live_mask

    def per_shard(vc, data, validity):
        mask = live_mask(vc, cap) & validity
        if data.dtype.kind == "f":
            mask = mask & ~jnp.isnan(data)  # pandas skipna=True
        if kind == "sum":
            out = jnp.sum(jnp.where(mask, data, 0))
            cnt = jnp.sum(mask)
        elif kind == "count":
            out = jnp.sum(mask)
            cnt = out
        elif kind == "min":
            big = jnp.iinfo(data.dtype).max if data.dtype.kind in "iu" \
                else jnp.inf
            out = jnp.min(jnp.where(mask, data, big))
            cnt = jnp.sum(mask)
        elif kind == "max":
            small = jnp.iinfo(data.dtype).min if data.dtype.kind in "iu" \
                else -jnp.inf
            out = jnp.max(jnp.where(mask, data, small))
            cnt = jnp.sum(mask)
        else:
            raise ValueError(kind)
        # dtype-preserving partials: int64 sums stay exact past 2^53
        return out.reshape(1), cnt.astype(jnp.int64).reshape(1)

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(REP, ROW, ROW),
                             out_specs=(ROW, ROW)))
