"""Execution context: ``CylonEnv`` + communicator configs.

TPU-native replacement for the reference's context + communicator bootstrap
(reference: ctx/cylon_context.hpp:30 ``CylonContext::Init/InitDistributed``,
net/comm_config.hpp, net/mpi/mpi_communicator.hpp:26 ``MPIConfig``).

Design shift (SURVEY.md §7): the reference is multi-process SPMD bootstrapped
by MPI/UCX/Gloo; the TPU build is **single-controller SPMD** — one Python
process drives an N-device ``jax.sharding.Mesh`` and the mesh *is* the world.
``rank`` becomes a device index, the hand-rolled channel/AllToAll engine
(net/ops/all_to_all.hpp:78) becomes XLA collectives inside ``shard_map``, and
MPI_Init becomes ``jax.distributed.initialize`` (multi-host, optional).

Config classes keep the reference's naming so user code reads the same:
``CylonEnv(config=TPUConfig())`` ~ ``CylonEnv(config=MPIConfig())``.
"""

from __future__ import annotations

import itertools
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..status import InvalidError

ROW_AXIS = "cyl_rows"  # the mesh axis tables are row-sharded over


def _distributed_initialized() -> bool:
    """jax < 0.5 compatibility: ``jax.distributed.is_initialized`` landed
    after 0.4.x; fall back to probing the distributed client state."""
    try:
        return jax.distributed.is_initialized()
    except AttributeError:
        from jax._src import distributed
        return getattr(distributed.global_state, "client", None) is not None


class CommConfig:
    """Base communicator config (reference: net/comm_config.hpp)."""

    comm_type = "local"

    def resolve_devices(self) -> list[Any]:
        raise NotImplementedError


class LocalConfig(CommConfig):
    """Serial context: world size 1, no collectives (reference Init())."""

    comm_type = "local"

    def resolve_devices(self):
        return [jax.devices()[0]]


class TPUConfig(CommConfig):
    """Bind ranks to accelerator chips via a 1-D device mesh.

    ``world_size=None`` uses every visible device.  ``devices`` may pin an
    explicit device list.  ``distributed=True`` calls
    ``jax.distributed.initialize`` first (multi-host DCN bootstrap — the
    moral slot of the reference's Redis/MPI OOB, §2 C15).
    """

    comm_type = "tpu"

    def __init__(self, world_size: int | None = None, devices: Sequence[Any] | None = None,
                 distributed: bool = False, coordinator_address: str | None = None,
                 process_id: int | None = None, num_processes: int | None = None):
        self.world_size = world_size
        self.devices = list(devices) if devices is not None else None
        self.distributed = distributed
        self.coordinator_address = coordinator_address
        self.process_id = process_id
        self.num_processes = num_processes

    def resolve_devices(self):
        if self.distributed and not _distributed_initialized():
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.process_id,
            )
        devs = self.devices if self.devices is not None else list(jax.devices())
        if self.world_size is not None:
            if self.world_size > len(devs):
                raise InvalidError(
                    f"world_size {self.world_size} > visible devices {len(devs)}")
            devs = devs[: self.world_size]
        # slice-major rank numbering (cylon_tpu/topo, docs/topology.md):
        # on a multi-slice fleet the mesh axis orders devices by
        # (slice_index, position) so rank // ranks_per_slice == slice —
        # the layout premise of the two-hop exchange's order-preservation
        # proof and of repart's global index math.  Single-slice fleets
        # and CPU grids come back untouched.
        from ..topo.model import slice_major_order
        return slice_major_order(devs)


class CPUMeshConfig(TPUConfig):
    """Host-CPU simulated grid (tests): the analog of the reference's
    ``mpirun --oversubscribe`` localhost testing (SURVEY.md §4.3).  Requires
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``."""

    comm_type = "cpu-mesh"

    def resolve_devices(self):
        if self.devices is not None:
            devs = list(self.devices)
        else:
            # jax.devices("cpu") initializes ONLY the cpu client — never call
            # plain jax.devices() here, it would initialize the default
            # (accelerator) backend just to filter it out again.
            devs = list(jax.devices("cpu"))
        if self.world_size is not None:
            if self.world_size > len(devs):
                raise InvalidError(
                    f"world_size {self.world_size} > visible CPU devices "
                    f"{len(devs)} — set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count={self.world_size}")
            devs = devs[: self.world_size]
        return devs


_seq = itertools.count()


class CylonEnv:
    """The world handle (reference: python/pycylon frame.py:90 ``CylonEnv``,
    C++ ``CylonContext``).  Holds the device mesh, rank/world bookkeeping, a
    string config map, and the per-collective sequence counter."""

    #: monotonically assigned per-env id — prediction caches key on this
    #: instead of id(mesh) (CPython reuses ids after GC, which would let a
    #: new env inherit a dead env's capacity predictions)
    _next_serial = 0

    def __init__(self, config: CommConfig | None = None, verbose: bool = False):
        self.config = config or LocalConfig()
        self.verbose = verbose
        devs = self.config.resolve_devices()
        self._devices = devs
        self._mesh = Mesh(np.asarray(devs, dtype=object), (ROW_AXIS,))
        # settle the compiler-crash signature classification while the
        # backend is known-good (one probe compile, cached per process) —
        # the operator compile ladders dispatch on it (exec/recovery)
        from ..exec.recovery import prime_compiler_probe
        prime_compiler_probe()
        # spot/preemptible semantics: arm the SIGTERM grace drain when
        # CYLON_TPU_PREEMPT_GRACE_S declares a budget (exec/preempt —
        # one env read and no handler otherwise)
        from ..exec.preempt import install as _install_preempt
        _install_preempt()
        self._conf: dict[str, str] = {}
        self._finalized = False
        self.serial = CylonEnv._next_serial
        CylonEnv._next_serial += 1

    # -- reference CylonContext surface ------------------------------------
    @property
    def world_size(self) -> int:
        return len(self._devices)

    @property
    def rank(self) -> int:
        # Single-controller: the controller addresses all ranks; expose the
        # process index for multi-host parity with GetRank().
        return jax.process_index()

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def devices(self):
        return list(self._devices)

    @property
    def is_distributed(self) -> bool:
        return self.world_size > 1

    @property
    def topology(self):
        """The mesh's tier model (cylon_tpu/topo — slice count, ranks
        per slice, discovery source; docs/topology.md).  Single-slice
        on fleets without slice attributes and without a
        ``CYLON_TPU_SLICES`` declaration."""
        from ..topo import model as _topo_model
        return _topo_model.topology(self._mesh)

    def sharding(self, spec: P | None = None) -> NamedSharding:
        """NamedSharding over this env's mesh; default = row-sharded."""
        return NamedSharding(self._mesh, P(ROW_AXIS) if spec is None else spec)

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self._mesh, P())

    def get_next_sequence(self) -> int:
        """Monotone op id (reference cylon_context.hpp:135 edge-id allocator;
        here only used for tracing tags — XLA orders collectives for us)."""
        return next(_seq)

    def add_config(self, key: str, value: str) -> None:
        self._conf[key] = value

    def get_config(self, key: str, default: str = "") -> str:
        return self._conf.get(key, default)

    # -- collective surface (reference net/communicator.hpp:31-69) ---------
    def allgather(self, table):
        """AllGather(Table): every shard receives every row."""
        from ..parallel.collectives import allgather_table
        return allgather_table(table)

    def gather(self, table, root: int = 0):
        """Gather(Table, root): all rows onto shard ``root``."""
        from ..parallel.collectives import gather_table
        return gather_table(table, root)

    def bcast(self, table, root: int = 0):
        """Bcast(Table): replicate shard ``root``'s rows to every shard."""
        from ..parallel.collectives import bcast_table
        return bcast_table(table, root)

    def allreduce(self, column_or_array, op: str = "sum", valid_counts=None):
        """AllReduce(Column, op): elementwise across shards -> host array.
        Pass the owning table's ``valid_counts`` to mask capacity padding."""
        from ..parallel.collectives import allreduce
        return allreduce(column_or_array, op, valid_counts)

    def barrier(self) -> None:
        """Synchronization barrier (reference Barrier()).

        Multi-process (``jax.distributed``): a REAL cross-process barrier —
        every process blocks until all reach it (the reference's
        MPI_Barrier).  Single-process: drains queued work on every device
        of the env."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                f"cylon_env_barrier_{next(_seq)}")
            return
        for d in self._devices:
            jax.block_until_ready(jax.device_put(np.zeros((), np.int32), d))

    def finalize(self) -> None:
        self._finalized = True

    def __repr__(self) -> str:  # pragma: no cover
        return (f"CylonEnv(world={self.world_size}, comm={self.config.comm_type}, "
                f"devices={[str(d) for d in self._devices]})")
