"""Durable checkpoint/resume — the recovery ladder's persistence rung.

PRs 3–4 made the pipeline survive *in-process* faults: the consensus
retry ladder re-plans at degraded configurations and the HBM ledger
spills resident state to host RAM.  What neither can cure is a fault
that poisons the PROCESS — a real XLA ``RESOURCE_EXHAUSTED`` on an
HBM-poisoning rig, a libtpu compiler crash that exhausted its pad
ladder — where the only honest remedy is a fresh process, and before
this module that meant recomputing every completed piece from zero.
Following the lineage/checkpoint recovery tradition of the
MapReduce/Spark line (PAPERS.md), this module adds the missing
*durability* rung:

1. **Per-rank checkpoint directories** (``CYLON_TPU_CKPT_DIR``): each
   pipelined stage (one ``pipelined_join`` invocation — deterministic
   stage ids replay identically in a fresh process) owns
   ``<dir>/rank<r>/stage<k>-<label>/``.  Completed-piece state — the
   range loop's per-piece outputs, or the GroupBySink's per-piece
   partial aggregates — is serialized through the SAME host-page
   transport the PR 4 spill tier uses (``utils.host.host_shard_blocks``
   out, :func:`cylon_tpu.exec.memory.put_blocks` back in), so a
   restored piece is byte-identical to the resident array it was
   pulled from and multi-controller checkpoints stay collective-free
   (each process writes/reads only its addressable shards).  Every
   page carries a content hash (sha256); the piece meta sidecar is
   hashed into the manifest entry.

2. **Two-phase rank-coherent manifest commit**: after a piece's pages
   land, the updated manifest is STAGED (atomic rank-local write), then
   every rank votes :class:`~cylon_tpu.status.Code.CkptCommit` with its
   staged epoch over the PR 3 pmax wire
   (:func:`cylon_tpu.exec.recovery.ckpt_commit_consensus`) and only
   then renames staged → ``MANIFEST.json`` — so a manifest is committed
   on every rank at the IDENTICAL epoch or on none, and a crash between
   stage and commit leaves only staged files, which resume ignores.

3. **Resume** (``CYLON_TPU_RESUME=1``): a fresh process replaying the
   same workload reaches each stage with the same plan token (a hash of
   the stage's static plan — operator, key names, chunk count, piece
   capacities, per-range row counts); committed pieces whose token
   matches are loaded bit-identically and the range loop fast-forwards
   past them (``resume_fast_forwarded_pieces`` in the bench detail).  A
   corrupt or hash-mismatched page raises a typed
   :class:`~cylon_tpu.status.CheckpointCorruptError` and the stage
   falls back to recomputing its remaining pieces — corruption degrades
   resume to recompute, never to a wrong answer.

4. **The FINAL ladder rung** (:mod:`cylon_tpu.exec.recovery`): an
   unrecoverable ``DeviceOOMError`` or exhausted compiler-crash ladder
   flushes the session (:func:`flush_for_abort`) and raises a typed
   :class:`~cylon_tpu.status.ResumableAbort` carrying the resume token
   instead of a bare abort.

Happy path contract: with ``CYLON_TPU_CKPT_DIR`` unset this module's
entry points are a couple of env reads — ZERO filesystem writes, zero
extra collectives, no measurable cost on the pipelined hot path.  In a
single-controller session even an armed checkpoint adds no collectives
(the commit consensus short-circuits locally).

Fault injection (``scripts/chaos_soak.py``, docs/robustness.md): sites
``ckpt.write``/``ckpt.load``; kind ``corrupt`` flips page bytes after
hashing (write) or simulates a failed hash check (load); ``kill``
SIGKILLs the process mid-write — the chaos-soak harness's hard-crash
primitive.

Lint rule TS107: this module is the ONE sanctioned place that writes
checkpoint artifacts — a direct ``open``/``np.save``/pickle of
``CYLON_TPU_CKPT_DIR`` paths in ``relational/`` or ``exec/pipeline.py``
bypasses the hash/manifest protocol and is a finding.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle

import numpy as np

from ..status import CheckpointCorruptError
from ..utils import timing


# ---------------------------------------------------------------------------
# switches (read dynamically: tests and the chaos harness flip env vars)
# ---------------------------------------------------------------------------

def ckpt_dir() -> str | None:
    """The checkpoint root (``CYLON_TPU_CKPT_DIR``), or None = disabled."""
    return os.environ.get("CYLON_TPU_CKPT_DIR") or None


def enabled() -> bool:
    return ckpt_dir() is not None


def resume_requested() -> bool:
    """``CYLON_TPU_RESUME=1``: committed pieces of matching stages are
    restored instead of recomputed."""
    return os.environ.get("CYLON_TPU_RESUME") == "1"


# ---------------------------------------------------------------------------
# stats (bench JSON detail, alongside recovery_events / spill counters)
# ---------------------------------------------------------------------------

_STATS = {"checkpoint_events": 0, "bytes_checkpointed": 0,
          "resume_fast_forwarded_pieces": 0, "corrupt_pages": 0}


def stats() -> dict:
    """Checkpoint counters for the bench JSON detail:
    ``checkpoint_events`` (committed piece checkpoints),
    ``bytes_checkpointed`` (page bytes written),
    ``resume_fast_forwarded_pieces`` (pieces restored instead of
    recomputed) and ``corrupt_pages`` (hash-mismatch fallbacks)."""
    return dict(_STATS)


def reset_stats() -> None:
    for k in _STATS:
        _STATS[k] = 0


def unrestore(k: int) -> None:
    """Back out ``k`` discarded restores from the fast-forward counter:
    a multiprocess resume adopts the MINIMUM restorable prefix across
    ranks (:func:`cylon_tpu.exec.recovery.ckpt_resume_consensus`), so
    pieces a rank restored beyond the agreed prefix are recomputed and
    must not count as fast-forwarded."""
    _STATS["resume_fast_forwarded_pieces"] -= int(k)


# ---------------------------------------------------------------------------
# stage identity
# ---------------------------------------------------------------------------

#: per-(serving-session) stage sequences, key None = outside a
#: scheduler: checkpoint-enabled stages replay in the same PER-SESSION
#: order in a fresh process (each session's workload is deterministic,
#: and the serving scheduler re-creates sessions under the same names),
#: so (session, counter) IS the cross-process stage identity even when
#: concurrent sessions interleave their stage openings in a different
#: order — the plan token guards against the workload having actually
#: changed
_STAGE_SEQ: dict = {}

#: stage directories opened this process (for the resume-token file)
_OPEN_DIRS: list[str] = []


def reset_stages() -> None:
    """Restart the stage sequences (tests replaying a workload in-process
    to exercise the resume path without a fresh interpreter)."""
    _STAGE_SEQ.clear()
    _OPEN_DIRS.clear()


def plan_token(*parts) -> str:
    """Deterministic token over a stage's static plan (pass plain python
    ints/strs/tuples): resume restores a committed piece only when the
    fresh process derived the IDENTICAL plan — a changed workload, chunk
    count or world size silently starts the stage over instead of
    splicing foreign state in."""
    return hashlib.sha1(repr(parts).encode()).hexdigest()[:16]


def _rank() -> int:
    import jax
    return jax.process_index()


# ---------------------------------------------------------------------------
# page serialization — the spill tier's host-page transport, persisted
# ---------------------------------------------------------------------------

def _sha(raw: bytes) -> str:
    return hashlib.sha256(raw).hexdigest()


def _page_bytes(blocks: list) -> bytes:
    """One array's per-shard host blocks → one page (npz).  Remote
    shards' entries are None (another process owns them) and are simply
    absent — each rank's page holds exactly its addressable shards."""
    buf = io.BytesIO()
    arrs = {f"b{k}": b for k, b in enumerate(blocks) if b is not None}
    np.savez(buf, w=np.asarray(len(blocks), np.int64), **arrs)
    return buf.getvalue()


def _page_blocks(raw: bytes) -> list:
    with np.load(io.BytesIO(raw)) as z:
        blocks: list = [None] * int(z["w"])
        for key in z.files:
            if key != "w":
                blocks[int(key[1:])] = z[key]
    return blocks


class Stage:
    """One pipelined stage's durable checkpoint state: piece pages +
    hashed meta sidecars under the per-rank stage directory, committed
    under the two-phase manifest.  Obtain via :func:`open_stage`."""

    def __init__(self, env, label: str, token: str, seq: int):
        self.env = env
        self.label = label
        self.token = token
        self.dir = os.path.join(ckpt_dir(), f"rank{_rank()}",
                                f"stage{seq:03d}-{label}")
        os.makedirs(self.dir, exist_ok=True)
        self.epoch = 0
        self.committed: dict[int, dict] = {}
        self.resuming = False
        if resume_requested():
            man = self._read_manifest()
            if man is not None and man.get("plan") == token:
                self.committed = {int(k): v
                                  for k, v in man.get("pieces", {}).items()}
                self.epoch = int(man.get("epoch", 0))
                self.resuming = bool(self.committed)
            elif man is not None:
                from ..utils.logging import log
                log.warning(
                    "checkpoint stage %s: plan token mismatch (manifest %s, "
                    "workload %s) — stale checkpoint ignored, stage starts "
                    "over", self.dir, man.get("plan"), token)

    # -- manifest ----------------------------------------------------------
    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "MANIFEST.json")

    def _read_manifest(self) -> dict | None:
        try:
            with open(self._manifest_path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _commit(self) -> None:
        """Two-phase manifest commit: stage (atomic rank-local write +
        fsync), consensus (every rank votes Code.CkptCommit with its
        staged epoch over the pmax wire), then rename staged →
        MANIFEST.json.  Single-controller sessions skip the collective
        entirely."""
        from . import recovery
        self.epoch += 1
        man = {"plan": self.token, "label": self.label, "epoch": self.epoch,
               "world": int(self.env.world_size),
               "pieces": {str(k): v for k, v in self.committed.items()}}
        staged = self._manifest_path + ".staged"
        with open(staged, "w", encoding="utf-8") as f:
            json.dump(man, f)
            f.flush()
            os.fsync(f.fileno())
        recovery.ckpt_commit_consensus(getattr(self.env, "mesh", None),
                                       self.epoch)
        os.replace(staged, self._manifest_path)

    def has_piece(self, i: int) -> bool:
        return int(i) in self.committed

    # -- save --------------------------------------------------------------
    def save_piece(self, i: int, table) -> None:
        """Checkpoint one completed piece's Table: per-array host pages
        (spill-tier transport) + hashed meta sidecar, committed under
        the two-phase manifest.  The piece is durable only after
        :meth:`_commit` returns — a kill mid-write leaves staged files
        that resume ignores."""
        from . import recovery
        corrupt = recovery.maybe_inject(
            "ckpt.write", intercept=("corrupt",)) == "corrupt"
        i = int(i)
        with timing.region("ckpt.write"):
            nbytes, meta_sha, meta_file = self._write_pages(i, table,
                                                            corrupt)
            self.committed[i] = {"meta": meta_file, "sha": meta_sha,
                                 "nbytes": nbytes}
            self._commit()
        _STATS["checkpoint_events"] += 1
        _STATS["bytes_checkpointed"] += nbytes
        timing.add_bytes("ckpt.write", nbytes)
        timing.bump("ckpt.piece_committed")

    def _write_pages(self, i: int, table, corrupt: bool):
        from ..utils.host import host_shard_blocks
        w = int(self.env.world_size)
        cols, flats = [], []
        for name, c in table.columns.items():
            cols.append({"name": name, "type": c.type,
                         "dictionary": c.dictionary, "bounds": c.bounds,
                         "has_validity": c.validity is not None})
            flats.append(c.data)
            if c.validity is not None:
                flats.append(c.validity)
        pages, total = [], 0
        for j, arr in enumerate(flats):
            raw = _page_bytes(host_shard_blocks(arr, w))
            fname = f"piece_{i}.p{j}"
            # each page carries a content hash computed over the GOOD
            # bytes; an injected corruption flips a byte AFTER hashing so
            # the resume path's verification catches it (the acceptance
            # path for CheckpointCorruptError)
            pages.append({"file": fname, "sha": _sha(raw), "nbytes": len(raw)})
            if corrupt and j == 0:
                raw = bytes([raw[0] ^ 0xFF]) + raw[1:]
            self._atomic_write(fname, raw)
            total += len(raw)
        meta = pickle.dumps({
            "cols": cols,
            "valid_counts": np.asarray(table.valid_counts, np.int64),
            "grouped_by": table.grouped_by,
            "pages": pages,
        })
        meta_file = f"piece_{i}.meta"
        self._atomic_write(meta_file, meta)
        return total + len(meta), _sha(meta), meta_file

    def _atomic_write(self, fname: str, raw: bytes) -> None:
        path = os.path.join(self.dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)

    # -- load (resume fast-forward) ----------------------------------------
    def load_piece(self, i: int):
        """Restore one committed piece bit-identically: verify the meta
        sidecar against the manifest hash, every page against its meta
        hash, and re-enter the device through the spill tier's sanctioned
        upload boundary (:func:`cylon_tpu.exec.memory.put_blocks`).  Any
        mismatch (or an injected ``corrupt``) raises a typed
        :class:`CheckpointCorruptError` — the caller recomputes the
        stage's remaining pieces."""
        from . import memory, recovery
        from ..core.column import Column
        from ..core.table import Table
        if recovery.maybe_inject("ckpt.load", intercept=("corrupt",)):
            _STATS["corrupt_pages"] += 1
            raise CheckpointCorruptError(
                "injected checkpoint corruption on load", site="ckpt.load")
        entry = self.committed[int(i)]
        with timing.region("ckpt.load"):
            meta_raw = self._read_verified(entry["meta"], entry["sha"])
            meta = pickle.loads(meta_raw)
            sharding = self.env.sharding()
            flats = []
            for page in meta["pages"]:
                raw = self._read_verified(page["file"], page["sha"])
                flats.append(memory.put_blocks(_page_blocks(raw), sharding))
        flats = iter(flats)
        cols = {}
        for cm in meta["cols"]:
            data = next(flats)
            validity = next(flats) if cm["has_validity"] else None
            cols[cm["name"]] = Column(data, cm["type"], validity,
                                      cm["dictionary"], bounds=cm["bounds"])
        out = Table(cols, self.env, meta["valid_counts"])
        out.grouped_by = meta["grouped_by"]
        _STATS["resume_fast_forwarded_pieces"] += 1
        timing.bump("ckpt.piece_restored")
        return out

    def _read_verified(self, fname: str, want_sha: str) -> bytes:
        path = os.path.join(self.dir, fname)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            _STATS["corrupt_pages"] += 1
            raise CheckpointCorruptError(
                f"checkpoint page {path} unreadable: {e}",
                site="ckpt.load") from e
        if _sha(raw) != want_sha:
            _STATS["corrupt_pages"] += 1
            raise CheckpointCorruptError(
                f"checkpoint page {path} failed its content-hash check "
                "(torn write or on-disk corruption)", site="ckpt.load")
        return raw


def open_stage(env, label: str, token: str) -> Stage:
    """The next pipelined stage's checkpoint handle (advances the
    deterministic PER-SESSION stage sequence; under the serving
    scheduler the stage directory is additionally namespaced by the
    session name, so concurrent tenants' checkpoints never collide and a
    resumed process matches each tenant's stages regardless of how the
    original interleave ordered them).  Call only when :func:`enabled`."""
    from . import recovery
    sid = recovery.current_session()
    seq = _STAGE_SEQ.get(sid, 0)
    _STAGE_SEQ[sid] = seq + 1
    if sid is not None:
        label = f"{sid}.{label}"
    stage = Stage(env, label, token, seq)
    _OPEN_DIRS.append(stage.dir)
    return stage


def corrupt_fallback(stage: Stage, piece: int, err: Exception) -> None:
    """Log + count a corruption-triggered recompute fallback (the range
    loop calls this, then recomputes the stage's remaining pieces)."""
    from . import recovery
    from ..utils.logging import log
    recovery._record("ckpt.load", "corrupt", "recompute")
    log.warning("checkpoint stage %s piece %d failed verification (%s); "
                "recomputing this stage's remaining pieces instead of "
                "restoring", stage.label, piece, err)


def flush_for_abort(label: str) -> str:
    """The FINAL ladder rung's flush: committed state is already durable
    (every piece commits at its own stage boundary), so this records the
    resume token — a ``RESUME_TOKEN.json`` breadcrumb naming the stages
    this process committed — and returns the token (the checkpoint
    root's absolute path)."""
    root = ckpt_dir()
    token = os.path.abspath(root)
    try:
        os.makedirs(root, exist_ok=True)
        path = os.path.join(root, "RESUME_TOKEN.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"label": label, "pid": os.getpid(),
                       "stages": list(_OPEN_DIRS),
                       "resume": "rerun with CYLON_TPU_RESUME=1"}, f)
    except OSError:
        pass  # the committed manifests are the durable state; the
        # breadcrumb is best-effort
    return token
